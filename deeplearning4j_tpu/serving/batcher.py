"""ParallelInference: dynamic micro-batching dispatcher for serving.

TPU-native re-expression of the reference's ``ParallelInference``
(reference: ``deeplearning4j-parallel-wrapper .../parallelism/
ParallelInference.java``† per SURVEY.md §2.6; reference mount was empty,
citation upstream-relative, unverified). The reference replicates the
model per GPU and round-robins an observable queue; on TPU one compiled
program serves the whole slice, so the contract that survives is the
queueing semantics:

- ``InferenceMode.SEQUENTIAL`` — requests run one at a time (a lock),
  no coalescing; the reference's low-latency/low-traffic mode.
- ``InferenceMode.BATCHED`` — a bounded request queue plus a dispatcher
  thread that coalesces concurrent requests up to ``max_batch_size``
  rows or ``max_wait_ms`` of linger into ONE
  ``serving.engine.InferenceEngine`` call (padded to a compiled bucket),
  then scatters the rows back and resolves per-request futures.

Divergences from the reference (recorded in PARITY.md): futures instead
of observables, bucket padding instead of per-batch-size queues, and a
mesh option — the coalesced batch is placed over the ``'data'`` axis via
``NamedSharding``, so serving throughput scales with the slice.

Observability: per-request p50/p99 latency, queue depth, coalesced batch
sizes, and the engine's bucket-hit/compile counters, via :meth:`stats`
(pumped into the ui/stats storage by ``ui.stats.ServingStatsListener``).

Graceful degradation (ISSUE 5 tentpole, layer 4): per-request deadlines
(an expired request fails fast with ``DeadlineExceeded`` BEFORE dispatch
— its device slot goes to a request that can still meet its SLO), a
queue-depth load-shedding threshold (``QueueFull`` rejection in the
caller's thread instead of unbounded linger), ONE retry on transient
executor errors, and a health state machine —
``HEALTHY``/``DEGRADED``/``SHEDDING`` — surfaced through :meth:`health`,
:meth:`stats`, ``ui.ServingStatsListener`` and ``JsonModelServer``'s
``GET /healthz``. Every degradation path is counted (shed /
deadline_expired / retries — zero silent fallbacks) and injectable via
``runtime/faults.py`` (``serving.dispatch``, ``serving.slow``).
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
import weakref
from collections import deque
from concurrent.futures import Future, TimeoutError as _FutTimeout
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import sampling as _smp
from ..runtime import faults as _faults
from ..runtime import telemetry as _tel
from ..runtime.faults import DeadlineExceeded, QueueFull, ShutdownError
from .engine import InferenceEngine, next_bucket

# per-front counters/reservoirs live in the process-wide MetricsRegistry
# (ISSUE 6), labeled by a monotonically assigned instance id; the
# attribute names pre-registry callers used (pi.requests, pi.shed, ...)
# survive as properties, and stats() is a view with optional windowing
_M_REQUESTS = _tel.counter("serving.requests", "requests submitted")
_M_BATCHES = _tel.counter("serving.batches", "coalesced engine dispatches")
_M_FAILURES = _tel.counter("serving.failures", "failed requests")
_M_SHED = _tel.counter("serving.shed", "load-shed (QueueFull) rejections")
_M_DEADLINE = _tel.counter("serving.deadline_expired",
                           "requests expired before dispatch")
_M_RETRIES = _tel.counter("serving.retries", "transient dispatch retries")
_H_LATENCY = _tel.histogram(
    "serving.request_latency_s",
    "submit->resolve latency per request (timestamped reservoir: "
    "stats(window=...) reads only the recent samples)")
_H_ROWS = _tel.histogram("serving.batch_rows",
                         "rows per coalesced engine call")
_H_QUEUE = _tel.histogram("serving.phase.queue_s",
                          "enqueue->dequeue wait per dispatched request")
_H_COALESCE = _tel.histogram("serving.phase.coalesce_s",
                             "first-dequeue->dispatch linger per batch")
# continuous-batching decode (ISSUE 8): how many of the warmed slots hold
# an in-flight generation right now, per front
_G_SLOTS = _tel.gauge("serving.slots_active",
                      "occupied decode slots in the continuous batcher")
_M_TOKENS = _tel.counter("serving.tokens_generated",
                         "tokens emitted by the continuous batcher")
# speculative decoding (ISSUE 12): draft-propose / target-verify loop
_M_PROPOSED = _tel.counter("serving.speculative.proposed",
                           "draft tokens proposed per active slot")
_M_ACCEPTED = _tel.counter("serving.speculative.accepted",
                           "draft tokens the target verify accepted")
_H_ACCEPT = _tel.histogram(
    "serving.speculative.accept_rate",
    "accepted/k per verify window per active slot — THE draft-quality "
    "signal (emitted tokens per target step = accepted + 1)")
# generative latency decomposition (ISSUE 13): time-to-first-token
# (submit -> first emitted token, queue+prefill included) and
# time-per-output-token (steady-state inter-token interval), per request
_H_TTFT = _tel.histogram(
    "serving.ttft_s",
    "time to first token per generative request (submit -> first emit)")
_H_TPOT = _tel.histogram(
    "serving.tpot_s",
    "time per output token per generative request "
    "((resolve - first emit) / (tokens - 1))")
# host-free decode horizons (ISSUE 19): decode_step_s decomposes into a
# device fraction (the blocking readback of an in-flight multi-token
# horizon) and a host fraction (emission, deadline checks, trace
# stitching) — with double-buffering the host fraction overlaps the
# NEXT in-flight horizon instead of stalling the device
_H_DECODE_DEV = _tel.histogram(
    "serving.phase.decode_device_s",
    "per-dispatch device wait: the one blocking readback of an "
    "in-flight decode horizon (host-loop decode: the dispatch+sync)")
_H_DECODE_HOST = _tel.histogram(
    "serving.phase.decode_host_s",
    "per-dispatch host-side share of the decode phase (sampling/"
    "emission/featurization/trace stitching); overlapped with the "
    "next in-flight horizon when double-buffering engages")
_H_HORIZON = _tel.histogram(
    "serving.decode.horizon",
    "tokens per decode dispatch (the adaptive horizon k)")
_M_DISPATCH = _tel.counter(
    "serving.decode.dispatch",
    "decode dispatch decisions by kind (decision= on_device / "
    "host_loop / speculative) — host-loop fallbacks for custom "
    "sample_fn/token_to_features are counted, never silent")
_G_TPS = _tel.gauge(
    "serving.tokens_per_s",
    "windowed generative throughput over the batcher health window — "
    "lets SLO burn-rate alarms gate on throughput, not just TPOT")
_pi_ids = itertools.count()


class InferenceMode:
    SEQUENTIAL = "sequential"
    BATCHED = "batched"


class HealthState:
    HEALTHY = "HEALTHY"
    DEGRADED = "DEGRADED"
    SHEDDING = "SHEDDING"


class _Request:
    __slots__ = ("x", "length", "future", "t_enqueue", "t_dequeue",
                 "deadline", "trace")

    def __init__(self, x, length, deadline=None, trace=None):
        self.x = x
        self.length = length          # true seq length (seq models)
        self.future: Future = Future()
        self.t_enqueue = time.perf_counter()
        self.t_dequeue = None         # stamped by the dispatcher's get()
        self.deadline = deadline      # absolute perf_counter time or None
        # explicit trace context (ISSUE 13): contextvars die at the queue
        # boundary, so the trace rides the request object itself — which
        # is also what keeps a carried-over coalesce request on its
        # ORIGINAL trace
        self.trace = trace if trace is not None else _tel.NULL_TRACE
        self.future.trace_id = self.trace.trace_id

    def expired(self, now=None) -> bool:
        return self.deadline is not None and \
            (now if now is not None else time.perf_counter()) > self.deadline


class ParallelInference:
    """Thread-safe inference front over a model's forward pass.

    Usage::

        pi = ParallelInference(net, mode=InferenceMode.BATCHED,
                               max_batch_size=32, max_wait_ms=5)
        y = pi.output(x)          # blocking, callable from many threads
        f = pi.submit(x)          # non-blocking -> concurrent Future
        pi.stats()                # p50/p99 latency, queue depth, buckets
        pi.shutdown()

    ``batch_limit`` is accepted as a deprecated alias of
    ``max_batch_size`` (pre-engine API).
    """

    def __init__(self, model, mode: str = InferenceMode.BATCHED,
                 max_batch_size: int = 32, max_wait_ms: float = 5.0,
                 queue_limit: int = 256, mesh=None,
                 engine: Optional[InferenceEngine] = None,
                 warmup: bool = False,
                 batch_limit: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 shed_queue_depth: Optional[int] = None,
                 retry_transient: bool = True,
                 health_window_s: float = 5.0,
                 degraded_p99_ms: Optional[float] = None,
                 quantize: Optional[str] = None,
                 slo: Optional[_tel.SLO] = None,
                 pool_label: str = "default"):
        if mode not in (InferenceMode.SEQUENTIAL, InferenceMode.BATCHED):
            raise ValueError(f"unknown inference mode {mode!r}")
        self._pool_label = str(pool_label)
        if batch_limit is not None:  # deprecated alias
            max_batch_size = batch_limit
        self.model = model
        self.mode = mode
        self.max_batch_size = int(max_batch_size)
        self.max_wait = max_wait_ms / 1e3
        # graceful degradation knobs (ISSUE 5): default deadline applied to
        # every request unless submit() overrides; load shedding kicks in
        # at shed_queue_depth queued requests (None = never shed — the
        # queue_limit bound still blocks); one retry on transient executor
        # errors; health window for the DEGRADED/SHEDDING decay.
        self.deadline_ms = deadline_ms
        self.shed_queue_depth = None if shed_queue_depth is None \
            else int(shed_queue_depth)
        self.retry_transient = bool(retry_transient)
        self.health_window = float(health_window_s)
        # ISSUE 6 satellite: health reacts to RECENT latency — p99 over
        # the health window above this threshold reports DEGRADED even
        # with no hard failures (None = latency never degrades health)
        self.degraded_p99_ms = degraded_p99_ms
        # ISSUE 13: a windowed SLO objective (target p99 / error-rate with
        # multi-window burn-rate alarms); every resolved request records
        # into it, and a firing alarm reports DEGRADED through health()
        self.slo = slo
        if engine is not None and quantize is not None:
            # a silently-dropped quantize kwarg would serve f32 while
            # the deploy config believes it is int8 — fail loudly
            raise ValueError("pass quantize= on the engine you build "
                             "(InferenceEngine(model, quantize=...)), "
                             "not alongside engine=")
        if engine is None:
            # default: share the model's engine, so net.output() and the
            # batcher hit the same warmed bucket cache; a mesh or a
            # quantize request needs its own engine (its executables are
            # compiled over different params avals/shardings)
            engine = (InferenceEngine(model, mesh=mesh, quantize=quantize,
                                      pool_label=self._pool_label)
                      if mesh is not None or quantize is not None
                      else model.inference_engine())
        self.engine = engine
        self._seq = any(engine._seq_input or ())
        if warmup:
            # cover every bucket a coalesced batch can land on: the
            # dispatcher caps totals at max_batch_size, which pads up to
            # next_bucket(max_batch_size)
            from .engine import default_buckets
            engine.warmup(default_buckets(
                next_bucket(self.max_batch_size, engine.min_bucket),
                minimum=engine.min_bucket))
        self._q: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._dispatch_lock = threading.Lock()  # SEQUENTIAL execution
        self._shutdown = threading.Event()
        self._worker: Optional[threading.Thread] = None
        # -- observability: registry cells labeled by instance (ISSUE 6);
        # latency/batch-rows are timestamped reservoirs so stats(window=)
        # can report percentiles over only the recent samples; a finalizer
        # drops the cells when this front is collected (bounded registry) --
        self._id = str(next(_pi_ids))
        weakref.finalize(self, _tel.registry.discard_cells, pi=self._id)
        # explicit pi=/pool= kwargs at every .labeled() site — the
        # staticcheck label rules read them from the AST (a **splat
        # would be invisible to metric-label-blending / pool-scoped)
        _pi, _pool = self._id, self._pool_label
        self._m_requests = _M_REQUESTS.labeled(pi=_pi, pool=_pool)
        self._m_batches = _M_BATCHES.labeled(pi=_pi, pool=_pool)
        self._m_failures = _M_FAILURES.labeled(pi=_pi, pool=_pool)
        self._m_shed = _M_SHED.labeled(pi=_pi, pool=_pool)
        self._m_deadline = _M_DEADLINE.labeled(pi=_pi, pool=_pool)
        self._m_retries = _M_RETRIES.labeled(pi=_pi, pool=_pool)
        self._h_latency = _H_LATENCY.labeled(pi=_pi, pool=_pool)
        self._h_rows = _H_ROWS.labeled(pi=_pi, pool=_pool)
        self._h_queue = _H_QUEUE.labeled(pi=_pi, pool=_pool)
        self._h_coalesce = _H_COALESCE.labeled(pi=_pi, pool=_pool)
        # degradation events: the recent-event window behind health()
        self._events = deque(maxlen=1024)      # (t, kind) kind in
        #                                        {shed, failure, retry,
        #                                         deadline}
        if mode == InferenceMode.BATCHED:
            self._worker = threading.Thread(
                target=self._dispatcher, daemon=True,
                name="ParallelInference-dispatcher")
            self._worker.start()

    # ---- public ------------------------------------------------------------
    def submit(self, x, deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one request; resolves to the unpadded output rows.
        Requests larger than ``max_batch_size`` are split into capped
        chunks (each lands on a warmed bucket) and rejoined.

        ``deadline_ms`` (default: the constructor's ``deadline_ms``): if
        the request is still queued when its deadline passes, it fails
        fast with :class:`DeadlineExceeded` — never dispatched, so device
        time goes to requests that can still meet their SLO. When the
        queue is at ``shed_queue_depth``, this raises :class:`QueueFull`
        in the caller's thread immediately (load shedding)."""
        if self._shutdown.is_set():
            raise ShutdownError("ParallelInference is shut down")
        x = self._validate(np.asarray(x))
        dl = self.deadline_ms if deadline_ms is None else deadline_ms
        deadline = None if dl is None else time.perf_counter() + dl / 1e3
        self._m_requests.inc()
        # explicit trace context (ISSUE 13): born here, carried through
        # the queue on the request; every terminal path — resolve, shed,
        # deadline, shutdown, failure — finishes it with a status
        trace = _tel.start_request_trace("serving.request", pi=self._id,
                                         mode=str(self.mode))
        if self.mode == InferenceMode.SEQUENTIAL:
            req = self._make_request(x, deadline, trace)
            phases: List = []
            try:
                if req.expired():
                    raise DeadlineExceeded(
                        "request deadline expired before dispatch")
                # dispatch lock only — stats() must not block behind a
                # device call
                with self._dispatch_lock:
                    if req.expired():
                        raise DeadlineExceeded(
                            "request deadline expired before dispatch")
                    t_d = time.perf_counter()
                    trace.phase("queue", t_d - req.t_enqueue)
                    with _tel.span("serving.dispatch",
                                   labels={"pi": self._id,
                                           "pool": self._pool_label,
                                           "mode": str(self.mode)},
                                   rows=int(x.shape[0]),
                                   links=[trace.trace_id]):
                        with _tel.sink_phases(
                                lambda n, d: phases.append((n, d))):
                            out = self._call_engine(x)
                self._m_batches.inc()
                self._h_rows.observe(x.shape[0])
                req.future.set_result(
                    [np.asarray(o) for o in out] if isinstance(out, list)
                    else np.asarray(out))
                done_t = time.perf_counter()
                for name, d in phases:
                    trace.phase(name, d)
                trace.phase("resolve", max(
                    0.0, done_t - t_d - sum(d for _, d in phases)))
                trace.finish("ok", rows=int(x.shape[0]))
                self._record_slo(done_t - req.t_enqueue, True)
            except DeadlineExceeded as e:
                self._m_deadline.inc()
                self._note("deadline")
                req.future.set_exception(e)
                trace.finish("error", f"{type(e).__name__}: {e}")
                self._record_slo(time.perf_counter() - req.t_enqueue, False)
            except Exception as e:
                self._m_failures.inc()
                self._note("failure")
                req.future.set_exception(e)
                trace.finish("error", f"{type(e).__name__}: {e}")
                self._record_slo(time.perf_counter() - req.t_enqueue, False)
                _tel.flight.auto_dump("serving.dispatch")
            finally:
                self._record_latency(req)
            return req.future
        if self.shed_queue_depth is not None and \
                self._q.qsize() >= self.shed_queue_depth:
            # LOAD SHEDDING: reject in the caller's thread, before the
            # queue — a fast, counted failure instead of unbounded linger.
            # Checked BEFORE chunking so oversized requests (the heaviest
            # traffic) cannot evade the overload protection.
            self._m_shed.inc()
            self._note("shed")
            trace.finish("error", "QueueFull: shed at queue depth "
                         f"{self._q.qsize()}")
            self._record_slo(0.0, False)
            raise QueueFull(
                f"serving queue depth {self._q.qsize()} at/above shedding "
                f"threshold {self.shed_queue_depth}")
        if x.shape[0] > self.max_batch_size:
            return self._submit_chunked(x, deadline, trace)
        return self._enqueue(self._make_request(x, deadline, trace))

    def _make_request(self, x, deadline=None, trace=None) -> _Request:
        return _Request(x, x.shape[1] if self._seq and x.ndim >= 2 else None,
                        deadline, trace)

    def _record_slo(self, latency_s: float, ok: bool) -> None:
        if self.slo is not None:
            self.slo.record(latency_s, ok)

    def _enqueue(self, req: _Request) -> Future:
        self._q.put(req)
        # a shutdown() racing this put may already have drained the queue
        # and joined the dispatcher — fail the future here rather than
        # strand a submit() caller forever
        if self._shutdown.is_set() and not req.future.done():
            req.future.set_exception(ShutdownError(
                "ParallelInference shut down before the request was served"))
            req.trace.finish("error", "ShutdownError: shut down before "
                             "the request was served")
        return req.future

    def _submit_chunked(self, x, deadline=None, trace=None) -> Future:
        """Split an oversized request into <= max_batch_size chunks (each
        pads onto a warmed bucket — no compile under traffic) and resolve
        one parent future with the rejoined rows. Each chunk gets its own
        child trace (``parent=`` the submitting request's trace id); the
        parent trace finishes when the rejoined future resolves."""
        m = self.max_batch_size
        trace = trace if trace is not None else _tel.NULL_TRACE
        subs = []
        for i in range(0, x.shape[0], m):
            sub_tr = _tel.NULL_TRACE if trace.trace_id is None else \
                _tel.start_request_trace("serving.request", pi=self._id,
                                         mode=str(self.mode),
                                         parent=trace.trace_id)
            subs.append(self._make_request(x[i:i + m], deadline, sub_tr))
        parent: Future = Future()
        parent.trace_id = trace.trace_id
        state = {"left": len(subs)}
        plock = threading.Lock()

        def on_done(f: Future):
            with plock:
                if parent.done():
                    return
                err = f.exception()
                if err is not None:
                    parent.set_exception(err)
                    trace.finish("error", f"{type(err).__name__}: {err}")
                    return
                state["left"] -= 1
                if state["left"]:
                    return
                results = [s.future.result() for s in subs]
                if isinstance(results[0], list):  # multi-output graph
                    parent.set_result([
                        np.concatenate([r[k] for r in results])
                        for k in range(len(results[0]))])
                else:
                    parent.set_result(np.concatenate(results))
                # one covering phase so the parent timeline keeps the
                # phases-sum-to-latency contract (the per-phase detail
                # lives in the linked child traces); NULL_TRACE has no
                # clock — skip when telemetry is off
                if trace.trace_id is not None:
                    trace.phase("chunked",
                                time.perf_counter() - trace.t_start,
                                chunks=len(subs))
                trace.finish("ok", chunks=len(subs),
                             children=[s.trace.trace_id for s in subs])

        for s in subs:
            s.future.add_done_callback(on_done)
        for s in subs:
            self._enqueue(s)
        return parent

    def output(self, x, deadline_ms: Optional[float] = None) -> np.ndarray:
        """Blocking convenience over :meth:`submit`; re-checks shutdown so
        a racing ``shutdown()`` cannot strand the caller."""
        return self._wait(self.submit(x, deadline_ms=deadline_ms))

    def _wait(self, fut: Future):
        """Block on one submitted future, re-checking shutdown (shared by
        :meth:`output` and ``JsonModelServer``, which needs the future —
        and its ``trace_id`` — rather than just the rows)."""
        while True:
            try:
                return fut.result(timeout=0.2)
            except _FutTimeout:
                if self._shutdown.is_set() and not fut.done():
                    raise ShutdownError(
                        "ParallelInference shut down before the request "
                        "was served") from None

    def queue_depth(self) -> int:
        return self._q.qsize()

    def _note(self, kind: str):
        """Record a degradation event for the health window (deque append
        is atomic under the GIL; readers snapshot)."""
        self._events.append((time.perf_counter(), kind))

    def note_shed(self):
        """Count an EXTERNAL load-shed against this front's health state
        machine (ISSUE 20: the fleet's per-model quota rejects before
        submit() — the rejection must still flip health to SHEDDING
        exactly as a queue-depth shed would)."""
        self._m_shed.inc()
        self._note("shed")

    def health(self) -> str:
        """The serving health state machine:

        - ``SHEDDING`` — the queue is at/above the shedding threshold, or
          a request was shed within the health window (clients should
          back off / be rerouted).
        - ``DEGRADED`` — recent failures, transient-error retries, or
          deadline expiries — or, with ``degraded_p99_ms`` set, a recent
          (health-window) latency p99 above the threshold — but requests
          are being accepted.
        - ``HEALTHY`` — none of the above.

        All inputs are *recent*: the event deque and the latency
        reservoir are both read over ``health_window_s``, so a latency
        spike an hour ago cannot pin the state (ISSUE 6 satellite —
        the pre-registry percentiles were lifetime-of-process)."""
        # ISSUE 13: evaluate the SLO FIRST, unconditionally — alarm() is
        # what exports the burn-rate gauges and counts transitions, and
        # an incident (shedding/degraded below) is exactly when those
        # must keep moving
        slo_alarm = self.slo.alarm() if self.slo is not None else None
        now = time.perf_counter()
        recent = {k for t, k in list(self._events)
                  if now - t <= self.health_window}
        if "shed" in recent or (
                self.shed_queue_depth is not None
                and self._q.qsize() >= self.shed_queue_depth):
            return HealthState.SHEDDING
        if recent & {"failure", "retry", "deadline"}:
            return HealthState.DEGRADED
        if self.degraded_p99_ms is not None:
            p99 = self._h_latency.percentile(99, window=self.health_window)
            if p99 is not None and p99 * 1e3 > self.degraded_p99_ms:
                return HealthState.DEGRADED
        # a burning SLO (sustained multi-window budget burn) degrades
        # health even when no individual request failed hard
        if slo_alarm is not None:
            return HealthState.DEGRADED
        return HealthState.HEALTHY

    # legacy counter attributes — views over the registry cells
    @property
    def requests(self) -> int:
        return int(self._m_requests.value())

    @property
    def batches(self) -> int:
        return int(self._m_batches.value())

    @property
    def failures(self) -> int:
        return int(self._m_failures.value())

    @property
    def shed(self) -> int:
        return int(self._m_shed.value())

    @property
    def deadline_expired(self) -> int:
        return int(self._m_deadline.value())

    @property
    def retries(self) -> int:
        return int(self._m_retries.value())

    def stats(self, window: Optional[float] = None) -> dict:
        """Serving health snapshot: request latency percentiles (ms),
        queue depth, coalesced batch sizes, the degradation counters +
        health state, and the engine's bucket-hit / compile counters.

        ``window`` (seconds): restrict the latency/batch-size
        percentiles to samples observed in the last N seconds, so a
        DEGRADED/SHEDDING operator view reacts to *recent* behaviour
        instead of the process lifetime (the counters stay lifetime —
        they are monotonic by contract)."""
        health = self.health()
        lat = self._h_latency.hist_snapshot(window=window)
        rows = self._h_rows.hist_snapshot(window=window)
        out = {
            "mode": self.mode,
            "health": health,
            "requests": self.requests,
            "batches": self.batches,
            "failures": self.failures,
            "shed": self.shed,
            "deadline_expired": self.deadline_expired,
            "retries": self.retries,
            "queue_depth": self._q.qsize(),
            "window_s": window,
            "latency_ms_p50": None if lat["p50"] is None
            else lat["p50"] * 1e3,
            "latency_ms_p99": None if lat["p99"] is None
            else lat["p99"] * 1e3,
            "batch_rows_mean": rows["mean"],
            "batch_rows_max": None if rows["max"] is None
            else int(rows["max"]),
        }
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        out["engine"] = self.engine.stats()
        return out

    def shutdown(self):
        """Stop the dispatcher and FAIL every queued/in-flight future with
        :class:`ShutdownError` — an unresolved future strands its caller
        forever, which is worse than a clean error."""
        self._shutdown.set()
        if self._worker:
            self._worker.join(timeout=5)
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if not req.future.done():
                req.future.set_exception(ShutdownError(
                    "ParallelInference shut down before the request "
                    "was served"))
            req.trace.finish("error", "ShutdownError: shut down before "
                             "the request was served")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # ---- internals ---------------------------------------------------------
    def _validate(self, x: np.ndarray) -> np.ndarray:
        in_shape = getattr(self.model.conf, "input_shape", None)
        if in_shape is not None:
            if x.ndim == len(in_shape):
                x = x[None]  # single-example convenience
            ok = x.ndim == len(in_shape) + 1 and (
                self._seq  # [B,T,F]: T is ragged, F must match
                and x.shape[2:] == tuple(in_shape[1:])
                or not self._seq and tuple(x.shape[1:]) == tuple(in_shape))
            if not ok:
                # reject HERE, in the offending caller's thread — a bad
                # shape inside a coalesced batch would fail everyone
                raise ValueError(
                    f"input shape {tuple(x.shape[1:])} does not match "
                    f"model input {tuple(in_shape)}")
        return x

    def _record_latency(self, req: _Request):
        self._h_latency.observe(time.perf_counter() - req.t_enqueue)

    def _expire(self, req: _Request, now=None) -> bool:
        """Deadline fail-fast: an expired request never reaches the device
        — its future fails with DeadlineExceeded and the slot goes to a
        request that can still make its SLO."""
        if not req.expired(now):
            return False
        self._m_deadline.inc()
        self._note("deadline")
        if not req.future.done():
            req.future.set_exception(DeadlineExceeded(
                "request deadline expired before dispatch"))
        req.trace.finish("error", "DeadlineExceeded: request deadline "
                         "expired before dispatch")
        self._record_slo(time.perf_counter() - req.t_enqueue, False)
        self._record_latency(req)
        return True

    def _call_engine(self, x, lengths=None):
        """The engine dispatch with the transient-retry contract: ONE
        retry on a transient executor failure (counted; second failure
        propagates). Fault sites: ``serving.slow`` (injected latency —
        the overload scenario) and ``serving.dispatch`` (injected
        executor error — the retry scenario)."""
        attempt = 0
        while True:
            try:
                if _faults.enabled():
                    _faults.trip("serving.slow")
                    _faults.trip("serving.dispatch")
                return self.engine.output(x, lengths=lengths) \
                    if lengths is not None else self.engine.output(x)
            except Exception as e:
                if attempt == 0 and self.retry_transient and \
                        _faults.is_transient(e):
                    attempt = 1
                    self._m_retries.inc()
                    self._note("retry")
                    continue
                raise

    def _dispatcher(self):
        pending: Optional[_Request] = None  # carry-over, never overshoot
        while not self._shutdown.is_set():
            if pending is not None:
                first, pending = pending, None
            else:
                try:
                    first = self._q.get(timeout=0.05)
                except queue.Empty:
                    continue
                first.t_dequeue = time.perf_counter()
            if self._expire(first):
                continue
            batch: List[_Request] = [first]
            total = first.x.shape[0]
            t_first = time.perf_counter()
            deadline = t_first + self.max_wait
            while total < self.max_batch_size:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    r = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                r.t_dequeue = time.perf_counter()
                if self._expire(r):
                    continue
                if total + r.x.shape[0] > self.max_batch_size:
                    # would overshoot the cap (and the warmed bucket set):
                    # lead the NEXT batch with it instead
                    pending = r
                    break
                batch.append(r)
                total += r.x.shape[0]
            if _tel.enabled():
                # request-lifecycle phases: time queued (per request,
                # enqueue->its own dequeue — the coalesce linger belongs
                # to coalesce_s, not here) and the linger this batch paid
                now = time.perf_counter()
                self._h_queue.observe_many(
                    [r.t_dequeue - r.t_enqueue for r in batch])
                self._h_coalesce.observe(now - t_first)
            self._run(batch, total)
        if pending is not None:  # don't strand a carried request
            pending.future.set_exception(ShutdownError(
                "ParallelInference shut down before the request was served"))
            pending.trace.finish("error", "ShutdownError: shut down "
                                 "before the request was served")
        # queued-request drain happens in shutdown() (this thread exits first)

    def _run(self, batch: List[_Request], total: int):
        # per-request timeline stitching (ISSUE 13): queue = enqueue ->
        # own dequeue, coalesce = own dequeue -> dispatch start; the
        # engine-internal pad/execute/unpad phases arrive through the
        # phase sink and are SHARED batch wall-time; resolve absorbs the
        # remaining dispatch wall (concat, fault hooks, scatter) so the
        # per-request phase durations sum to the measured latency
        t_d = time.perf_counter()
        tel = _tel.enabled()
        phases: List = []
        for r in batch:
            r.trace.phase("queue", r.t_dequeue - r.t_enqueue)
            r.trace.phase("coalesce", t_d - r.t_dequeue)
        try:
            # the coalesced span LINKS every member request's trace — the
            # fan-in edge a queue-crossing contextvar could never record
            with _tel.span("serving.dispatch",
                           labels={"pi": self._id,
                                   "pool": self._pool_label,
                                   "mode": str(self.mode)},
                           rows=int(total), requests=len(batch),
                           links=[r.trace.trace_id for r in batch
                                  if r.trace.trace_id is not None]):
                if tel:
                    with _tel.sink_phases(
                            lambda n, d: phases.append((n, d))):
                        out = self._run_engine(batch)
                else:
                    out = self._run_engine(batch)
            outs = out if isinstance(out, list) else [out]
            i = 0
            done_t = time.perf_counter()
            shared = sum(d for _, d in phases)
            for r in batch:
                n = r.x.shape[0]
                rows = [o[i:i + n] for o in outs]
                if self._seq and r.length is not None:
                    rows = [o[:, :r.length] if o.ndim >= 3 else o
                            for o in rows]
                i += n
                if not r.future.done():  # a shutdown race may have failed it
                    r.future.set_result(rows if len(rows) > 1 else rows[0])
                for name, d in phases:
                    r.trace.phase(name, d, shared=True)
                r.trace.phase("resolve", max(0.0, done_t - t_d - shared))
                r.trace.finish("ok", rows=int(n), batch_rows=int(total))
                self._record_slo(done_t - r.t_enqueue, True)
            self._m_batches.inc()
            self._h_rows.observe(total)
            self._h_latency.observe_many(
                [done_t - r.t_enqueue for r in batch])
        except Exception as e:  # propagate to every waiter
            done_t = time.perf_counter()
            self._m_failures.inc(len(batch))
            self._h_latency.observe_many(
                [done_t - r.t_enqueue for r in batch])
            self._note("failure")
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
                r.trace.finish("error", f"{type(e).__name__}: {e}")
                self._record_slo(done_t - r.t_enqueue, False)
            # black box: the failed batch's span chains + the preceding
            # compile/fault events are already in the ring — dump AFTER
            # the traces finish so the dump contains them
            _tel.flight.auto_dump("serving.dispatch")

    def _run_engine(self, batch: List[_Request]):
        """Coalesce one batch's arrays and dispatch the engine call."""
        if self._seq:
            # ragged T: end-pad every request to the coalesced max;
            # the engine masks the pad steps out exactly
            t_max = max(r.x.shape[1] for r in batch)
            xs, lengths = [], []
            for r in batch:
                t = r.x.shape[1]
                x = r.x if t == t_max else np.concatenate(
                    [r.x, np.zeros((r.x.shape[0], t_max - t)
                                   + r.x.shape[2:], r.x.dtype)], axis=1)
                xs.append(x)
                lengths.extend([t] * r.x.shape[0])
            x = np.concatenate(xs, axis=0)
            return self._call_engine(x, lengths=np.asarray(lengths))
        x = np.concatenate([r.x for r in batch], axis=0)
        return self._call_engine(x)


# ===========================================================================
# Continuous batching for autoregressive decode (ISSUE 8 tentpole, layer 3)
# ===========================================================================

class GenerationHandle:
    """Per-request view of an in-flight generation: a ``Future`` resolving
    to ``{"tokens": [ids], "logits": last-step logits}``, plus a streaming
    iterator (:meth:`tokens`) that yields token ids as each decode
    iteration lands — the per-token partial results ``JsonModelServer``'s
    ``/generate`` endpoint streams out."""

    def __init__(self):
        self.future: Future = Future()
        self._stream: "queue.Queue" = queue.Queue()

    def _emit(self, index: int, token: int):
        self._stream.put((index, int(token)))

    def _finish(self, err: Optional[BaseException] = None):
        self._stream.put(None)
        if err is not None and not self.future.done():
            self.future.set_exception(err)

    def tokens(self, timeout: Optional[float] = None):
        """Yield generated token ids in order as they are produced."""
        while True:
            item = self._stream.get(timeout=timeout)
            if item is None:
                # surface a terminal failure to the streaming consumer too
                err = self.future.exception() if self.future.done() else None
                if err is not None:
                    raise err
                return
            yield item[1]

    def result(self, timeout: Optional[float] = None) -> dict:
        return self.future.result(timeout=timeout)


class _GenRequest:
    __slots__ = ("x", "plen", "max_new", "eos_id", "handle", "t_enqueue",
                 "deadline", "t_admitted", "tokens", "emitted", "trace",
                 "t_first_token", "t_anchor", "shipment")

    def __init__(self, x, plen, max_new, eos_id, deadline, trace=None,
                 shipment=None):
        self.x = x                    # [T, F] prompt features (host)
        self.plen = int(plen)
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.handle = GenerationHandle()
        self.t_enqueue = time.perf_counter()
        self.deadline = deadline      # absolute admission deadline or None
        self.t_admitted = None
        self.tokens: List[int] = []
        self.emitted = 0
        # ISSUE 18: a migrated-KV handoff (serving.disagg.KVShipment) —
        # admission ADOPTS its pages instead of prefilling. The deadline
        # above was RE-ARMED at submit_prefilled time (r13 semantics
        # extended: a slow handoff never expires paid-for prefill work);
        # t_enqueue is back-dated by the shipment's origin elapsed so
        # latency/TTFT span the whole request across pools.
        self.shipment = shipment
        # explicit trace context through the queue (ISSUE 13); t_anchor
        # is the end of the last timeline phase, so per-iteration decode
        # phases tile the admitted lifetime exactly (timeline sums to the
        # measured latency)
        self.trace = trace if trace is not None else _tel.NULL_TRACE
        self.handle.trace_id = self.trace.trace_id
        self.t_first_token = None
        self.t_anchor = None

    def expired(self, now=None) -> bool:
        return self.deadline is not None and \
            (now if now is not None else time.perf_counter()) > self.deadline


class _Horizon:
    """One in-flight multi-token decode dispatch (ISSUE 19): the
    engine's non-blocking ``HorizonResult`` plus the host bookkeeping
    needed to consume it — which slots were live at dispatch and how
    much token budget each has left AFTER this horizon lands (the
    chain gate: only slots with budget remaining may ride the next
    chained dispatch)."""

    __slots__ = ("res", "live", "k", "budget_after")

    def __init__(self, res, live, k, budget_after):
        self.res = res
        self.live = list(live)
        self.k = int(k)
        self.budget_after = dict(budget_after)


class ContinuousBatcher:
    """Token-boundary continuous batching over a
    :class:`~..serving.engine.GenerativeEngine` slot set.

    Requests JOIN the in-flight decode batch when a slot frees up (their
    prompt prefills between decode iterations — ``prefill_per_iter``
    bounds the admission stall decode pays per iteration) and LEAVE the
    moment they finish (``max_new_tokens`` reached or ``eos_id``
    sampled), without perturbing the other slots' state: slot rows are
    independent by construction, which the join/leave parity test
    asserts bit-exactly. The pre-ISSUE-8 dispatcher could only coalesce
    once and let a decode batch drain to one request; here the batch
    refills every token boundary.

    **Deadline semantics (decided + documented, ISSUE 8 satellite):**
    ``deadline_ms`` bounds ENQUEUE -> ADMISSION — a request still queued
    when it expires fails fast with ``DeadlineExceeded`` and never
    prefills. At admission the clock RESTARTS: an admitted multi-token
    generation is never killed mid-flight by the admission deadline
    (deadline = per-request-admission, not per-token — the
    ``ParallelInference`` one-shot front keeps its whole-request
    enqueue->dispatch deadline; both are regression-tested).

    ``shed_queue_depth`` sheds in the caller's thread with ``QueueFull``
    exactly like the one-shot front. The ``serving.decode`` fault site
    makes the decode-iteration failure path deterministic in tier-1.
    """

    def __init__(self, model, slots: int = 4, max_cache_len: int = 256,
                 min_cache_len: int = 16,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 max_new_tokens: int = 32,
                 queue_limit: int = 256,
                 deadline_ms: Optional[float] = None,
                 shed_queue_depth: Optional[int] = None,
                 prefill_per_iter: int = 1,
                 eos_id: Optional[int] = None,
                 token_to_features=None,
                 sample_fn=None,
                 engine: Optional["GenerativeEngine"] = None,
                 warmup: bool = True,
                 quantize: Optional[str] = None,
                 kv_cache: Optional[str] = None,
                 paged: bool = False,
                 page_size: int = 16,
                 pages: Optional[int] = None,
                 prefix_cache: bool = True,
                 draft_model=None,
                 speculate_k: int = 4,
                 slo: Optional[_tel.SLO] = None,
                 pool_label: str = "default",
                 migrate_buckets: Sequence[int] = (),
                 max_horizon: Optional[int] = None,
                 sampling: Optional["_smp.SamplingSpec"] = None,
                 seed: int = 0):
        from .engine import GenerativeEngine, PagedGenerativeEngine
        self.model = model
        # ISSUE 18: pool role of this front (prefill / decode /
        # colocated) — every serving.* cell carries pool= beside pi=
        self._pool_label = str(pool_label)
        self._migrate_buckets = tuple(int(n) for n in migrate_buckets)
        # ISSUE 9: quantize="int8" (weights) / kv_cache="int8" (per-row
        # quantized KV buckets — half the cache HBM per slot) flow to the
        # engine; with an explicit engine= the caller configures it there
        # (passing both would silently serve the engine's config)
        if engine is not None and (quantize is not None
                                   or kv_cache is not None
                                   or paged or pages is not None):
            raise ValueError("pass quantize=/kv_cache=/paged config on "
                             "the engine you build (GenerativeEngine / "
                             "PagedGenerativeEngine), not alongside "
                             "engine=")
        self.max_cache_len = next_bucket(max_cache_len)
        self.min_cache_len = next_bucket(min_cache_len)
        if engine is None:
            if paged:
                # ISSUE 12: fixed-size HBM pages + host page tables; the
                # default pool can hold every slot at its FULL bucket (no
                # pressure) — capacity-constrained deployments size
                # ``pages`` down and lean on sharing/eviction
                psz = next_bucket(page_size)
                mp = max(1, self.max_cache_len // psz)
                n_pages = int(pages) if pages is not None \
                    else 1 + int(slots) * mp
                engine = PagedGenerativeEngine(
                    model, slots=slots, pages=n_pages, page_size=psz,
                    max_cache_len=self.max_cache_len, quantize=quantize,
                    kv_cache=kv_cache, pool_label=self._pool_label)
            else:
                engine = GenerativeEngine(model, slots=slots,
                                          quantize=quantize,
                                          kv_cache=kv_cache,
                                          pool_label=self._pool_label)
        self.engine = engine
        self.paged = isinstance(engine, PagedGenerativeEngine)
        if self.paged and self.max_cache_len > engine.max_cache_len:
            # an explicitly built engine caps the page table; admitting
            # prompts the table cannot hold would overflow map_pages and
            # leak the allocated pages — reject the config loudly
            raise ValueError(
                f"batcher max_cache_len {self.max_cache_len} exceeds the "
                f"paged engine's max_cache_len {engine.max_cache_len}; "
                "size the engine (or the batcher bound) to match")
        self.prefix_cache = bool(prefix_cache) and self.paged
        self.slots = self.engine.slots
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_ms = deadline_ms
        self.shed_queue_depth = None if shed_queue_depth is None \
            else int(shed_queue_depth)
        self.prefill_per_iter = max(1, int(prefill_per_iter))
        self.eos_id = eos_id
        self._f = self.engine._feature_dim()
        # ISSUE 19: a custom sample_fn / token_to_features cannot run
        # inside the compiled horizon — those callers keep the per-token
        # host loop, counted as decision="host_loop" dispatches (never a
        # silent degradation)
        self._custom_host_loop = (sample_fn is not None
                                  or token_to_features is not None)
        if sampling is not None and sample_fn is not None:
            raise ValueError("sampling= configures the ON-DEVICE "
                             "sampler; a host sample_fn bypasses it — "
                             "pass one of the two")
        self._sampling = sampling if sampling is not None else _smp.GREEDY
        if self._sampling.stochastic and draft_model is not None:
            raise ValueError("speculative decoding verifies GREEDY "
                             "tokens; stochastic on-device sampling "
                             "cannot be teacher-forced")
        if max_horizon is None:
            env = os.environ.get("DL4J_TPU_DECODE_HORIZON")
            max_horizon = int(env) if env else 8
        self.max_horizon = max(1, int(max_horizon))
        # horizon ramp ladder: powers of two up to max_horizon (plus
        # max_horizon itself) — paces how fast the adaptive scheduler
        # grows k in steady state. Purely a scheduling schedule: k is a
        # RUNTIME scalar of the ONE warmed kmax=max_horizon program per
        # cache bucket, so any budget-capped k <= max_horizon dispatches
        # without a post-warmup compile
        ladder, h = [], 1
        while h < self.max_horizon:
            ladder.append(h)
            h <<= 1
        ladder.append(self.max_horizon)
        self._ladder = tuple(ladder)
        self.token_to_features = token_to_features or self._one_hot
        self.sample_fn = sample_fn or (lambda logits: int(np.argmax(logits)))
        # speculative decoding (ISSUE 12): a small draft engine proposes
        # k tokens; the target verifies all k in ONE bucketed Tq=k step
        self.speculate_k = int(speculate_k)
        self.draft = None
        if draft_model is not None:
            if not self.paged:
                raise ValueError("speculative decoding rides the paged "
                                 "engine's verify executable; pass "
                                 "paged=True (or a PagedGenerativeEngine)")
            if sample_fn is not None:
                raise ValueError("speculative decoding verifies GREEDY "
                                 "tokens; a custom sample_fn cannot be "
                                 "teacher-forced — drop one of the two")
            if self.speculate_k < 2:
                raise ValueError("speculate_k must be >= 2 (k=1 is plain "
                                 "decode)")
            self.draft = GenerativeEngine(draft_model, slots=self.slots,
                                          pool_label=self._pool_label)
            if self.draft._feature_dim() != self._f:
                raise ValueError(
                    f"draft model feature dim {self.draft._feature_dim()} "
                    f"!= target {self._f}: the draft must share the "
                    "token feature space")
        if warmup:
            cb, b = [], self.min_cache_len
            while b <= self.max_cache_len:
                cb.append(b)
                b <<= 1
            pb = list(prompt_buckets) if prompt_buckets else cb
            # ONE kmax=max_horizon program per cache bucket serves every
            # runtime k the scheduler picks (k is a scalar argument of
            # the compiled loop); host-loop/speculative fronts skip it
            horizons = () if (self._custom_host_loop
                              or self.draft is not None) \
                else (self.max_horizon,)
            if self.paged:
                self.engine.warmup(
                    cb, pb, speculate=(self.speculate_k,)
                    if self.draft is not None else (),
                    migrate_buckets=self._migrate_buckets,
                    horizons=horizons, sampling=self._sampling)
            else:
                self.engine.warmup(cb, pb, horizons=horizons,
                                   sampling=self._sampling)
            if self.draft is not None:
                self.draft.warmup(cb, pb)
        # live decode state + host mirrors (worker-thread-only)
        self._state = self.engine.new_state(self.min_cache_len)
        self._slot_req: List[Optional[_GenRequest]] = [None] * self.slots
        self._lengths = np.zeros((self.slots,), np.int64)
        self._x_t = np.zeros((self.slots, 1, self._f), np.float32)
        if self.draft is not None:
            self._dstate = self.draft.new_state(self.min_cache_len)
            self._dlengths = np.zeros((self.slots,), np.int64)
        # ISSUE 19 runtime state: the in-flight horizon (double-
        # buffering holds at most ONE), the adaptive-horizon streak, the
        # threaded PRNG key (device-carried across chained dispatches),
        # and a token-timestamp ring for the windowed throughput gauge
        self._inflight: Optional[_Horizon] = None
        self._h_streak = 0
        self._key = np.asarray(jax.random.PRNGKey(int(seed)), np.uint32)
        self._token_times: deque = deque(maxlen=4096)
        self._q: "queue.Queue[_GenRequest]" = queue.Queue(maxsize=queue_limit)
        self._shutdown = threading.Event()
        # observability: same registry families as the one-shot front,
        # its own pi= instance id, plus the slot-occupancy gauge
        self._id = str(next(_pi_ids))
        weakref.finalize(self, _tel.registry.discard_cells, pi=self._id)
        _pi, _pool = self._id, self._pool_label
        self._m_requests = _M_REQUESTS.labeled(pi=_pi, pool=_pool)
        self._m_failures = _M_FAILURES.labeled(pi=_pi, pool=_pool)
        self._m_shed = _M_SHED.labeled(pi=_pi, pool=_pool)
        self._m_deadline = _M_DEADLINE.labeled(pi=_pi, pool=_pool)
        self._m_retries = _M_RETRIES.labeled(pi=_pi, pool=_pool)
        self._m_tokens = _M_TOKENS.labeled(pi=_pi, pool=_pool)
        self._h_latency = _H_LATENCY.labeled(pi=_pi, pool=_pool)
        # ISSUE 13 satellite: per-request TTFT/TPOT as first-class
        # registry reservoirs (previously TPOT existed only as a bench
        # artifact number) — stats()/GET /stats report their p50/p99
        self._h_ttft = _H_TTFT.labeled(pi=_pi, pool=_pool)
        self._h_tpot = _H_TPOT.labeled(pi=_pi, pool=_pool)
        self.slo = slo
        self._g_slots = _G_SLOTS.labeled(pi=_pi, pool=_pool)
        self._g_slots.set(0)
        self._m_proposed = _M_PROPOSED.labeled(pi=_pi, pool=_pool)
        self._m_accepted = _M_ACCEPTED.labeled(pi=_pi, pool=_pool)
        self._h_accept = _H_ACCEPT.labeled(pi=_pi, pool=_pool)
        # ISSUE 19: device/host decode split, horizon histogram,
        # dispatch-decision mix, windowed throughput
        self._h_dec_dev = _H_DECODE_DEV.labeled(pi=_pi, pool=_pool)
        self._h_dec_host = _H_DECODE_HOST.labeled(pi=_pi, pool=_pool)
        self._h_horizon = _H_HORIZON.labeled(pi=_pi, pool=_pool)
        self._m_disp_dev = _M_DISPATCH.labeled(pi=_pi, pool=_pool,
                                               decision="on_device")
        self._m_disp_host = _M_DISPATCH.labeled(pi=_pi, pool=_pool,
                                                decision="host_loop")
        self._m_disp_spec = _M_DISPATCH.labeled(pi=_pi, pool=_pool,
                                                decision="speculative")
        self._g_tps = _G_TPS.labeled(pi=_pi, pool=_pool)
        self._g_tps.set(0.0)
        # r10 degradation state machine, same recent-event window as the
        # one-shot front
        self.health_window = 5.0
        self._events = deque(maxlen=1024)
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="ContinuousBatcher-decode")
        self._worker.start()

    def _note(self, kind: str):
        self._events.append((time.perf_counter(), kind))

    def note_shed(self):
        """Count an EXTERNAL load-shed against this front's health state
        machine (ISSUE 20 fleet quota — see ParallelInference.note_shed)."""
        self._m_shed.inc()
        self._note("shed")

    def health(self) -> str:
        """HEALTHY / DEGRADED / SHEDDING over the recent event window —
        the r10 serving state machine applied to the generative front."""
        # SLO first, unconditionally: alarm() exports the burn gauges
        # and counts transitions; they must keep moving during incidents
        slo_alarm = self.slo.alarm() if self.slo is not None else None
        now = time.perf_counter()
        recent = {k for t, k in list(self._events)
                  if now - t <= self.health_window}
        if "shed" in recent or (
                self.shed_queue_depth is not None
                and self._q.qsize() >= self.shed_queue_depth):
            return HealthState.SHEDDING
        if recent & {"failure", "retry", "deadline"}:
            return HealthState.DEGRADED
        if slo_alarm is not None:
            return HealthState.DEGRADED
        return HealthState.HEALTHY

    def _record_slo(self, latency_s: float, ok: bool) -> None:
        if self.slo is not None:
            self.slo.record(latency_s, ok)

    # ---- public ------------------------------------------------------------
    def _one_hot(self, token: int) -> np.ndarray:
        f = np.zeros((self._f,), np.float32)
        f[int(token) % self._f] = 1.0
        return f

    def submit(self, prompt=None, tokens=None, plen: Optional[int] = None,
               max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               eos_id: Optional[int] = None) -> GenerationHandle:
        """Enqueue one generation. ``prompt``: [T, F] feature array (or
        ``tokens``: a list of ids run through ``token_to_features``).
        Returns a :class:`GenerationHandle` immediately; tokens stream as
        they decode."""
        if self._shutdown.is_set():
            raise ShutdownError("ContinuousBatcher is shut down")
        if tokens is not None:
            prompt = np.stack([self.token_to_features(t) for t in tokens])
        prompt = np.asarray(prompt, np.float32)
        if prompt.ndim == 3 and prompt.shape[0] == 1:
            prompt = prompt[0]
        if prompt.ndim != 2 or prompt.shape[1] != self._f:
            raise ValueError(f"prompt must be [T, {self._f}] features; got "
                             f"{prompt.shape}")
        plen = int(plen) if plen is not None else prompt.shape[0]
        max_new = int(max_new_tokens) if max_new_tokens is not None \
            else self.max_new_tokens
        # speculative verify windows cache up to k-1 rejected rows past
        # the live sequence — reserve that slack at admission so the
        # host-side overflow guard can never trip mid-generation
        slack = self.speculate_k if self.draft is not None else 0
        if next_bucket(plen + max_new + slack) > self.max_cache_len:
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens ({max_new})"
                + (f" + speculative slack ({slack})" if slack else "")
                + f" exceeds max_cache_len {self.max_cache_len}")
        trace = _tel.start_request_trace("serving.generate", pi=self._id,
                                         pool=self._pool_label,
                                         plen=plen, max_new=max_new)
        if self.shed_queue_depth is not None and \
                self._q.qsize() >= self.shed_queue_depth:
            self._m_shed.inc()
            self._note("shed")
            trace.finish("error", "QueueFull: shed at queue depth "
                         f"{self._q.qsize()}")
            self._record_slo(0.0, False)
            raise QueueFull(
                f"generation queue depth {self._q.qsize()} at/above "
                f"shedding threshold {self.shed_queue_depth}")
        dl = self.deadline_ms if deadline_ms is None else deadline_ms
        deadline = None if dl is None else time.perf_counter() + dl / 1e3
        req = _GenRequest(prompt, plen, max_new,
                          self.eos_id if eos_id is None else eos_id,
                          deadline, trace)
        self._m_requests.inc()
        self._q.put(req)
        if self._shutdown.is_set() and not req.handle.future.done():
            req.handle.future.set_exception(ShutdownError(
                "ContinuousBatcher shut down before the request was served"))
            req.handle._finish()
            req.trace.finish("error", "ShutdownError: shut down before "
                             "the request was served")
        return req.handle

    def generate(self, prompt=None, tokens=None, **kw) -> dict:
        """Blocking convenience over :meth:`submit`."""
        return self.submit(prompt=prompt, tokens=tokens, **kw).result()

    def submit_prefilled(self, shipment,
                         max_new_tokens: Optional[int] = None,
                         deadline_ms: Optional[float] = None,
                         eos_id: Optional[int] = None) -> GenerationHandle:
        """Enqueue a generation whose prompt was prefilled in ANOTHER
        pool (ISSUE 18 disaggregated serving): the request joins the
        decode queue carrying a :class:`~.disagg.KVShipment`; admission
        ADOPTS its migrated pages into this engine's pool instead of
        prefilling, and the first token comes from the shipped prefill
        logits.

        **Deadline semantics (the r13 contract extended):**
        ``deadline_ms`` RE-ARMS here — it bounds decode-pool enqueue ->
        admission from THIS call, never from the origin submit, so a
        slow handoff can never expire prefill work the other pool
        already paid for (and an admitted generation is still never
        killed mid-flight). Latency/TTFT still span the WHOLE request:
        ``t_enqueue`` is back-dated by the shipment's origin-side
        elapsed time."""
        if self._shutdown.is_set():
            raise ShutdownError("ContinuousBatcher is shut down")
        if not self.paged:
            raise ValueError("submit_prefilled needs a paged engine — KV "
                             "pages migrate; contiguous buckets do not")
        if self.draft is not None:
            raise ValueError("speculative decoding cannot adopt a "
                             "migrated prompt: the draft engine has no "
                             "KV for it (route speculative traffic to a "
                             "colocated replica)")
        shipment.validate_for(self.engine)
        plen = int(shipment.plen)
        max_new = int(max_new_tokens) if max_new_tokens is not None \
            else self.max_new_tokens
        if next_bucket(plen + max_new) > self.max_cache_len:
            raise ValueError(
                f"prompt ({plen}) + max_new_tokens ({max_new}) exceeds "
                f"max_cache_len {self.max_cache_len}")
        trace = _tel.start_request_trace(
            "serving.generate", trace_id=shipment.trace_id,
            pi=self._id, pool=self._pool_label, plen=plen,
            max_new=max_new, migrated=True)
        if self.shed_queue_depth is not None and \
                self._q.qsize() >= self.shed_queue_depth:
            self._m_shed.inc()
            self._note("shed")
            trace.finish("error", "QueueFull: shed at queue depth "
                         f"{self._q.qsize()}")
            self._record_slo(0.0, False)
            raise QueueFull(
                f"generation queue depth {self._q.qsize()} at/above "
                f"shedding threshold {self.shed_queue_depth}")
        dl = self.deadline_ms if deadline_ms is None else deadline_ms
        # RE-ARMED: absolute deadline from NOW (decode-pool submit), not
        # from the back-dated origin enqueue below
        deadline = None if dl is None else time.perf_counter() + dl / 1e3
        x = shipment.x if shipment.x is not None \
            else np.zeros((plen, self._f), np.float32)
        req = _GenRequest(x, plen, max_new,
                          self.eos_id if eos_id is None else eos_id,
                          deadline, trace, shipment=shipment)
        req.t_enqueue = time.perf_counter() - float(shipment.elapsed_s)
        self._m_requests.inc()
        self._q.put(req)
        if self._shutdown.is_set() and not req.handle.future.done():
            req.handle.future.set_exception(ShutdownError(
                "ContinuousBatcher shut down before the request was served"))
            req.handle._finish()
            req.trace.finish("error", "ShutdownError: shut down before "
                             "the request was served")
        return req.handle

    def active_slots(self) -> int:
        return sum(1 for r in self._slot_req if r is not None)

    def queue_depth(self) -> int:
        return self._q.qsize()

    def stats(self) -> dict:
        ttft = self._h_ttft.hist_snapshot()
        tpot = self._h_tpot.hist_snapshot()
        # windowed throughput (ISSUE 19 satellite): tokens emitted in
        # the trailing health window — the gauge SLO burn-rate alarms
        # can gate on (TPOT percentiles alone miss idle-front decay)
        now = time.perf_counter()
        w = self.health_window
        tps = sum(1 for t in list(self._token_times)
                  if now - t <= w) / w
        self._g_tps.set(tps)
        out = {
            "slots": self.slots,
            "pool": self._pool_label,
            "health": self.health(),
            "slots_active": int(self._g_slots.value()),
            "queue_depth": self._q.qsize(),
            "requests": int(self._m_requests.value()),
            "tokens_generated": int(self._m_tokens.value()),
            "failures": int(self._m_failures.value()),
            "shed": int(self._m_shed.value()),
            "deadline_expired": int(self._m_deadline.value()),
            "retries": int(self._m_retries.value()),
            "cache_len": self._state.cache_len,
            # ISSUE 13 satellite: per-request TTFT/TPOT percentiles (ms)
            # — previously TPOT was a bench-artifact-only number
            "ttft_ms_p50": None if ttft["p50"] is None
            else ttft["p50"] * 1e3,
            "ttft_ms_p99": None if ttft["p99"] is None
            else ttft["p99"] * 1e3,
            "tpot_ms_p50": None if tpot["p50"] is None
            else tpot["p50"] * 1e3,
            "tpot_ms_p99": None if tpot["p99"] is None
            else tpot["p99"] * 1e3,
            "tokens_per_s": tps,
            "max_horizon": self.max_horizon,
            "dispatch_decisions": {
                "on_device": int(self._m_disp_dev.value()),
                "host_loop": int(self._m_disp_host.value()),
                "speculative": int(self._m_disp_spec.value()),
            },
            "engine": self.engine.stats(),
        }
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        if self.paged:
            # page-pool occupancy/free + prefix hit counters, per engine
            # (labeled engine= in the registry; surfaced here for
            # GET /stats and ServingStatsListener — ISSUE 12 satellite)
            out["page_pool"] = self.engine.pool.stats()
        if self.draft is not None:
            prop = int(self._m_proposed.value())
            acc = int(self._m_accepted.value())
            out["speculative"] = {
                "k": self.speculate_k,
                "proposed": prop,
                "accepted": acc,
                "accept_rate": (acc / prop) if prop else None,
            }
        return out

    def shutdown(self):
        self._shutdown.set()
        if self._worker:
            self._worker.join(timeout=10)
        err = ShutdownError(
            "ContinuousBatcher shut down before the request was served")
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if not req.handle.future.done():
                req.handle.future.set_exception(err)
            req.handle._stream.put(None)
            req.trace.finish("error", f"ShutdownError: {err}")
        for i, req in enumerate(self._slot_req):
            if req is not None and not req.handle.future.done():
                req.handle.future.set_exception(err)
                req.handle._stream.put(None)
            if req is not None:
                req.trace.finish("error", f"ShutdownError: {err}")
            self._slot_req[i] = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()

    # ---- worker internals (single thread owns _state and the mirrors) -----
    def _free_slot(self) -> Optional[int]:
        for i, r in enumerate(self._slot_req):
            if r is None:
                return i
        return None

    def _loop(self):
        while not self._shutdown.is_set():
            try:
                if self._inflight is not None:
                    # double-buffering (ISSUE 19): a horizon is in
                    # flight — chain its successor, then land it (one
                    # readback) and run the host-side emission work
                    self._consume_horizon()
                    continue
                admitted = self._admit()
                if any(r is not None for r in self._slot_req):
                    if self.draft is not None or self._custom_host_loop:
                        self._decode_iter()
                    else:
                        self._dispatch_horizon()
                elif not admitted:
                    time.sleep(0.002)  # idle: no queue, no active slots
            except Exception as e:
                # LAST-RESORT guard: a user-supplied sample_fn /
                # token_to_features or an unexpected engine error must
                # not kill the decode thread and strand every future
                self._fail_active(e)

    def _fail_active(self, e: BaseException):
        """Fail every in-flight request with ``e``, rebuild the decode
        state from scratch (the decode executable DONATES the cache
        buffers, so after a failed dispatch they may be consumed — with
        every slot freed, fresh zeros are the correct state), and keep
        the worker alive for subsequent traffic."""
        self._inflight = None
        self._h_streak = 0
        live = [i for i, r in enumerate(self._slot_req) if r is not None]
        self._m_failures.inc(max(1, len(live)))
        self._note("failure")
        for i in live:
            req = self._slot_req[i]
            if not req.handle.future.done():
                req.handle.future.set_exception(e)
            req.handle._stream.put(None)
            req.trace.finish("error", f"{type(e).__name__}: {e}",
                             tokens=req.emitted)
            self._record_slo(time.perf_counter() - req.t_enqueue, False)
            self._slot_req[i] = None
        # black box (ISSUE 13): decode-thread failure is the generative
        # front's "unhandled engine failure" — dump after the in-flight
        # traces resolve so their span chains are in the ring
        _tel.flight.auto_dump("serving.decode")
        self._lengths[:] = 0
        self._x_t[:] = 0.0
        if self.paged:
            # reclaim every mapped page AND forget registered prefixes:
            # the pool device buffers were donated into the failed
            # dispatch, so a later prefix hit would map zeroed pages
            for s in range(self.slots):
                self.engine.pool.release(
                    self.engine.release_slot(self._state, s))
            self.engine.pool.clear_prefixes()
        self._state = self.engine.new_state(self.min_cache_len)
        if self.draft is not None:
            self._dstate = self.draft.new_state(self.min_cache_len)
            self._dlengths[:] = 0
        self._g_slots.set(self.active_slots())

    def _admit(self) -> int:
        """Prefill up to ``prefill_per_iter`` queued requests into free
        slots — the admission work interleaved between decode iterations
        so joins happen at token boundaries."""
        n = 0
        while n < self.prefill_per_iter:
            slot = self._free_slot()
            if slot is None:
                break
            try:
                req = self._q.get(timeout=0.02 if n == 0 and
                                  not any(r is not None
                                          for r in self._slot_req) else 0.0)
            except queue.Empty:
                break
            # ISSUE 8 satellite (decided semantics): the admission
            # deadline is checked HERE, against the enqueue-time clock; a
            # request that makes it into a slot restarts its clock — the
            # generation itself is never expired mid-flight
            if req.expired():
                self._m_deadline.inc()
                self._note("deadline")
                req.handle.future.set_exception(DeadlineExceeded(
                    "generation request expired before admission"))
                req.handle._stream.put(None)
                req.trace.finish("error", "DeadlineExceeded: generation "
                                 "request expired before admission")
                self._record_slo(time.perf_counter() - req.t_enqueue,
                                 False)
                continue
            try:
                self._prefill(req, slot)
                n += 1
            except Exception as e:
                self._m_failures.inc()
                self._note("failure")
                if not req.handle.future.done():
                    req.handle.future.set_exception(e)
                req.handle._stream.put(None)
                req.trace.finish("error", f"{type(e).__name__}: {e}")
                self._record_slo(time.perf_counter() - req.t_enqueue,
                                 False)
                # a mid-admission failure (page-pool exhaustion, a
                # raising sample_fn in _emit_token, ...) must not leave
                # a zombie slot decoding a dead request — or leak the
                # pages already mapped into its table row
                if self._slot_req[slot] is req:
                    self._slot_req[slot] = None
                self._reset_slot(slot)
        self._g_slots.set(self.active_slots())
        return n

    def _prefill(self, req: _GenRequest, slot: int):
        need_c = next_bucket(max(req.plen + 1, next_bucket(req.x.shape[0])))
        if need_c > self._state.cache_len:
            self._state = self.engine.grow(self._state, need_c)
        req.t_admitted = time.perf_counter()
        if req.shipment is not None:
            # ISSUE 18 handoff: everything since the ORIGIN submit that
            # the shipped phases don't already cover (serialization, the
            # channel, the decode queue wait) is the handoff phase — so
            # the stitched cross-pool timeline still tiles the measured
            # latency exactly
            req.trace.phase("handoff", max(
                0.0, (req.t_admitted - req.t_enqueue)
                - float(req.shipment.phase_total_s)))
            logits = self._adopt_admit(req, slot)
            now = time.perf_counter()
            req.trace.phase("adopt", now - req.t_admitted, slot=slot)
        else:
            # timeline (ISSUE 13): queue = enqueue -> admission; the
            # deadline clock restarts here (decided r13 semantics), the
            # trace keeps the whole submit->resolve wall
            req.trace.phase("queue", req.t_admitted - req.t_enqueue)
            if self.paged:
                logits = self._paged_admit(req, slot)
            else:
                self._state, logits = self.engine.prefill(
                    self._state, req.x, req.plen, slot)
            if self.draft is not None:
                # the draft's (small, contiguous) caches always prefill
                # — they are private per slot, never shared
                if need_c > self._dstate.cache_len:
                    self._dstate = self.draft.grow(self._dstate, need_c)
                self._dstate, _ = self.draft.prefill(
                    self._dstate, req.x, req.plen, slot)
                self._dlengths[slot] = req.plen
            now = time.perf_counter()
            req.trace.phase("prefill", now - req.t_admitted, slot=slot)
        req.t_anchor = now
        self._slot_req[slot] = req
        self._lengths[slot] = req.plen
        self._emit_token(slot, logits)

    def _adopt_admit(self, req: _GenRequest, slot: int) -> np.ndarray:
        """Admission for a migrated request (ISSUE 18): a prefix-registry
        hit on the shipped key maps ALREADY-adopted pages (the fleet-wide
        hit — an identical prompt migrated here before, or was prefilled
        locally); a miss adopts fresh pages, scatters the shipped payload
        blocks in bucketed device calls, and registers the prefix so the
        NEXT identical prompt on this pool hits without re-migrating —
        the shared system prompt is prefilled once per POOL."""
        sh = req.shipment
        eng = self.engine
        if self.prefix_cache and sh.prefix_key is not None:
            hit = eng.pool.lookup_prefix(sh.prefix_key)
            if hit is not None:
                eng.map_pages(self._state, slot, hit.pages)
                self._state.lengths[slot] = req.plen
                return hit.logits.copy()
        pages = eng.pool.adopt(len(sh.pages))
        try:
            self._state = eng.import_pages(self._state, pages, sh.payload)
            eng.map_pages(self._state, slot, pages)
            self._state.lengths[slot] = req.plen
        except BaseException:
            # same once-only reclaim as _paged_admit: clear the row
            # before releasing so _reset_slot cannot double-release
            self._state.page_table[slot, :] = 0
            eng.pool.release(pages)
            raise
        # materialize ONCE (the host-sync-in-hot-path staticcheck rule
        # flagged the former asarray(...).copy() double-copy here); the
        # registry keeps the materialized array, so only the registered
        # path pays a defensive copy for the caller-visible buffer
        logits = np.asarray(sh.logits)
        if self.prefix_cache and sh.prefix_key is not None:
            eng.pool.register_prefix(sh.prefix_key, pages, req.plen,
                                     logits)
            return logits.copy()
        return logits

    def _paged_admit(self, req: _GenRequest, slot: int) -> np.ndarray:
        """Paged admission with prefix sharing (ISSUE 12): hash the full
        prompt; a registry hit maps the SAME physical pages into this
        slot (refcounted — the prompt was prefilled ONCE, fleet-wide)
        and reuses the recorded logits; a miss allocates pages, prefills,
        and registers. A shared page forks only on first write
        (copy-on-write in ``prepare_write``).

        The key is the FULL prompt, not a per-page token chunk: the
        stack's prefix-LM semantics make the prompt attend
        bidirectionally over itself, so deep-layer k/v for a shared
        token prefix DIFFER under different suffixes — only identical
        prompts may share pages (divergence recorded in PARITY.md)."""
        P = self.engine.page_size
        n_pages = -(-req.plen // P)
        key = None
        if self.prefix_cache:
            # shared with the ISSUE 18 router: both sides must agree on
            # the key for repeat prompts to hit migrated pages
            from .kv_pool import prompt_key
            key = prompt_key(req.x, req.plen)
            hit = self.engine.pool.lookup_prefix(key)
            if hit is not None:
                self.engine.map_pages(self._state, slot, hit.pages)
                self._state.lengths[slot] = req.plen
                return hit.logits.copy()
        pages = self.engine.pool.alloc(n_pages)
        try:
            self.engine.map_pages(self._state, slot, pages)
            self._state, logits = self.engine.prefill(
                self._state, req.x, req.plen, slot)
        except BaseException:
            # reclaim the WHOLE allocation exactly once: clear the table
            # row first so the caller's _reset_slot sweep cannot release
            # the mapped subset a second time (a double release would
            # put duplicate ids on the free list)
            self._state.page_table[slot, :] = 0
            self.engine.pool.release(pages)
            raise
        if key is not None:
            self.engine.pool.register_prefix(key, pages, req.plen, logits)
            # the registry keeps `logits`; hand the caller its own copy
            # so a user mutating the result dict cannot corrupt the
            # recorded prefix logits future hits replay
            return logits.copy()
        return logits

    def _reset_slot(self, slot: int):
        """Reclaim one slot's host mirrors and (paged) its pages/draft
        length — shared between normal leave, admission failure, and the
        fail-active sweep."""
        self._lengths[slot] = 0
        self._x_t[slot] = 0.0
        if self.paged:
            self.engine.pool.release(
                self.engine.release_slot(self._state, slot))
        if self.draft is not None:
            self._dlengths[slot] = 0

    def _emit_token(self, slot: int, logits: np.ndarray):
        """Sample, stream, and either finish the slot's request or queue
        the token as the slot's next decode input."""
        tok = self.sample_fn(logits)
        self._emit_known(slot, tok, logits)

    def _emit_known(self, slot: int, tok: int, logits: np.ndarray) -> bool:
        """Emit one decided token (sampled, or a verified/corrected
        speculative token). Returns True when the request finished and
        the slot was reclaimed."""
        req = self._slot_req[slot]
        tok = int(tok)
        req.tokens.append(tok)
        req.emitted += 1
        self._m_tokens.inc()
        req.handle._emit(req.emitted - 1, tok)
        now = time.perf_counter()
        self._token_times.append(now)
        if req.t_first_token is None:
            # first-class TTFT (ISSUE 13): submit -> first emitted token,
            # queue wait and prefill included — the user-visible stall
            req.t_first_token = now
            self._h_ttft.observe(now - req.t_enqueue)
        done = req.emitted >= req.max_new or \
            (req.eos_id is not None and tok == req.eos_id)
        if done:
            # submit->resolve, the family's documented unit (the one-shot
            # front observes at resolution too — dashboards can compare)
            latency = now - req.t_enqueue
            self._h_latency.observe(latency)
            ttft = req.t_first_token - req.t_enqueue
            tpot = None
            if req.emitted > 1:
                tpot = (now - req.t_first_token) / (req.emitted - 1)
                self._h_tpot.observe(tpot)
            self._record_slo(latency, True)
            if not req.handle.future.done():
                req.handle.future.set_result(
                    {"tokens": list(req.tokens), "logits": logits})
            req.handle._stream.put(None)
            req.trace.finish("ok", tokens=req.emitted, ttft_s=ttft,
                             tpot_s=tpot)
            self._slot_req[slot] = None
            self._reset_slot(slot)
        else:
            self._x_t[slot, 0] = self.token_to_features(tok)
        return done

    def _trip_decode_fault(self):
        """Deterministic fault site for the decode dispatch path with
        the documented ONE-transient-retry semantics. Only covers
        PRE-dispatch failures: once a dispatch lands, the donated
        buffers are consumed and re-dispatch is impossible — executor
        failures route to _fail_active's fresh-state recovery."""
        attempt = 0
        while _faults.enabled():
            try:
                _faults.trip("serving.decode")
                break
            except Exception as e:
                if attempt == 0 and _faults.is_transient(e):
                    attempt = 1
                    self._m_retries.inc()
                    self._note("retry")
                    continue
                raise

    # ---- host-free decode horizons (ISSUE 19) ------------------------------
    def _pick_horizon(self, live) -> int:
        """Adaptive horizon: k=1 while the admission queue is non-empty
        (joins/leaves stay at token boundaries), doubling up the ramp
        ladder toward max_horizon in steady state, always capped by the
        smallest remaining token budget over the live slots — no slot
        can ever decode past its max_new inside a horizon, so the host
        and device length mirrors never diverge. The cap is EXACT (k is
        a runtime scalar of the warmed kmax=max_horizon program, so an
        off-ladder k never compiles)."""
        if self.max_horizon <= 1 or not self._q.empty():
            self._h_streak = 0
            k = 1
        else:
            k = self._ladder[min(self._h_streak, len(self._ladder) - 1)]
            self._h_streak += 1
        budget = min(self._slot_req[s].max_new - self._slot_req[s].emitted
                     for s in live)
        return max(1, min(k, budget))

    def _eos_vec(self, live) -> np.ndarray:
        """Per-slot EOS ids for on-device EOS detection (-1 = none)."""
        eos = np.full((self.slots,), -1, np.int32)
        for s in live:
            e = self._slot_req[s].eos_id
            if e is not None:
                eos[s] = int(e)
        return eos

    def _dispatch_horizon(self):
        """Dispatch ONE multi-token decode horizon without blocking on
        its result (ISSUE 19 tentpole): sampling, featurization, EOS
        freezing, and length advance all run on-device inside a single
        compiled loop; the host reads tokens back once per horizon
        in _consume_horizon, overlapped with the NEXT chained dispatch
        when the chain gate allows."""
        active = np.array([1 if r is not None else 0
                           for r in self._slot_req], np.int32)
        live = [i for i in range(self.slots) if active[i]]
        k = self._pick_horizon(live)
        need = int(self._lengths[live].max()) + k
        if need > self._state.cache_len:
            self._state = self.engine.grow(self._state, need)
        eos = self._eos_vec(live)
        try:
            self._trip_decode_fault()
            if self.paged:
                # copy-on-write over the WHOLE horizon: every position
                # the k steps will write must land on exclusively-owned
                # pages BEFORE dispatch (one refcount snapshot)
                snap = self.engine.pool.ref_snapshot()
                pairs = []
                for s in live:
                    pairs += self.engine.prepare_write(
                        self._state, s, k, ref_snapshot=snap)
                if pairs:
                    self._state = self.engine.fork(self._state, pairs)
                self._state, res = self.engine.pdecode_multi(
                    self._state, self._x_t, active, k, eos_ids=eos,
                    sampling=self._sampling, key=self._key)
            else:
                self._state, res = self.engine.decode_multi(
                    self._state, self._x_t, active, k, eos_ids=eos,
                    sampling=self._sampling, key=self._key)
        except Exception as e:
            self._fail_active(e)
            return
        self._key = res.chain.key
        self._m_disp_dev.inc()
        self._h_horizon.observe(float(k))
        self._inflight = _Horizon(res, live, k, {
            s: self._slot_req[s].max_new - self._slot_req[s].emitted - k
            for s in live})

    def _maybe_chain(self, h: "_Horizon"):
        """Double-buffering: dispatch horizon i+1 from horizon i's
        device-carried chain (x_t/active/lengths/key never touch the
        host) BEFORE consuming horizon i, so emission and trace work
        overlap device compute. Chaining yields to admission (a queued
        request that could take a free slot) and to contiguous growth
        (a host-side cache gather would block on the in-flight
        horizon)."""
        if self.max_horizon <= 1:
            return
        if not self._q.empty() and self._free_slot() is not None:
            return
        # a slot that hit EOS in an EARLIER horizon was reset during
        # that consume (req gone, device side already frozen) — its
        # dispatch-time budget is stale, so require a live request too
        cont = [s for s in h.live if h.budget_after.get(s, 0) > 0
                and self._slot_req[s] is not None]
        if not cont:
            return
        budget = min(h.budget_after[s] for s in cont)
        k2 = self._ladder[min(self._h_streak, len(self._ladder) - 1)]
        k2 = max(1, min(k2, budget))
        # lengths after horizon i land at AT MOST mirror + h.k (EOS
        # freezes advance less — writes past a frozen slot's length are
        # gated off, so sizing for the maximum is safe)
        assumed_max = max(int(self._lengths[s]) + h.k for s in cont)
        need = assumed_max + k2
        if not self.paged and need > self._state.cache_len:
            return  # contiguous growth host-gathers: consume first
        cap = np.zeros((self.slots,), np.int32)
        cap[cont] = 1
        eos = self._eos_vec(cont)
        self._trip_decode_fault()
        if self.paged:
            if need > self._state.cache_len:
                self._state = self.engine.grow(self._state, need)
            # CoW for the chained horizon is planned against ASSUMED
            # post-horizon lengths (mirror + h.k); restore the mirror
            # right after — _consume_horizon advances it by the ACTUAL
            # emitted counts
            saved = self._state.lengths.copy()
            try:
                for s in cont:
                    self._state.lengths[s] = saved[s] + h.k
                snap = self.engine.pool.ref_snapshot()
                pairs = []
                for s in cont:
                    pairs += self.engine.prepare_write(
                        self._state, s, k2, ref_snapshot=snap)
            finally:
                self._state.lengths[:] = saved
            if pairs:
                self._state = self.engine.fork(self._state, pairs)
            self._state, res = self.engine.pdecode_multi(
                self._state, None, None, k2, eos_ids=eos,
                active_cap=cap, sampling=self._sampling,
                chain=h.res.chain)
        else:
            self._state, res = self.engine.decode_multi(
                self._state, None, None, k2, eos_ids=eos,
                active_cap=cap, sampling=self._sampling,
                chain=h.res.chain)
        self._key = res.chain.key
        self._h_streak += 1
        self._m_disp_dev.inc()
        self._h_horizon.observe(float(k2))
        self._inflight = _Horizon(
            res, cont, k2, {s: h.budget_after[s] - k2 for s in cont})

    def _consume_horizon(self):
        """Land the in-flight horizon: chain the successor FIRST (the
        device keeps working), then ONE blocking readback, then the
        host-side per-token work — emission, featurization-free trace
        stitching, slot reclaim — exactly the work the device no
        longer waits on."""
        h = self._inflight
        self._inflight = None
        self._maybe_chain(h)
        t0 = time.perf_counter()
        toks, logits, emitted = h.res.fetch()
        t_fetch = time.perf_counter()
        self._h_dec_dev.observe(t_fetch - t0)
        for s in h.live:
            req = self._slot_req[s]
            if req is None:
                continue  # freed by a failure path while in flight
            m = int(emitted[:, s].sum())
            if m <= 0:
                continue
            # per-TOKEN decode phases tiling the horizon wall exactly:
            # the stitched timeline keeps its one-phase-per-token shape
            # and its sums-to-latency contract under any horizon k
            now = time.perf_counter()
            dt = (now - req.t_anchor) / m
            req.t_anchor = now
            for j in range(m):
                req.trace.phase("decode", dt, horizon=h.k)
                self._lengths[s] += 1
                if self.paged:
                    self._state.lengths[s] += 1
                if self._emit_known(s, int(toks[j, s]), logits[j, s]):
                    break
        self._g_slots.set(self.active_slots())
        self._h_dec_host.observe(time.perf_counter() - t_fetch)

    def _decode_iter(self):
        active = np.array([1 if r is not None else 0
                           for r in self._slot_req], np.int32)
        live = [i for i in range(self.slots) if active[i]]
        # cache insert lands at position lengths: grow before any active
        # slot would write past the bucket
        if int(self._lengths[live].max()) >= self._state.cache_len:
            self._state = self.engine.grow(
                self._state, self._state.cache_len + 1)
        try:
            self._trip_decode_fault()
            if self.draft is not None:
                self._m_disp_spec.inc()
                self._speculative_iter(active, live)
                self._g_slots.set(self.active_slots())
                return
            self._m_disp_host.inc()
            if self.paged:
                # copy-on-write: every active slot's write position must
                # land on an exclusively-owned page BEFORE dispatch.
                # ONE locked refcount snapshot per round (ISSUE 17
                # satellite) — not one pool-lock round-trip per page.
                snap = self.engine.pool.ref_snapshot()
                pairs = []
                for s in live:
                    pairs += self.engine.prepare_write(
                        self._state, s, 1, ref_snapshot=snap)
                if pairs:
                    self._state = self.engine.fork(self._state, pairs)
            t_d0 = time.perf_counter()
            state, logits = self.engine.decode(
                self._state, self._x_t, active)
            t_d1 = time.perf_counter()
            self._h_dec_dev.observe(t_d1 - t_d0)
        except Exception as e:
            self._fail_active(e)
            return
        self._state = state
        self._lengths[live] += 1
        for i in live:
            # per-iteration timeline phase BEFORE the emit (emit may
            # finish the request): anchor -> now tiles the request's
            # admitted lifetime with no gaps, so the stitched phases sum
            # to the measured latency
            req = self._slot_req[i]
            now = time.perf_counter()
            req.trace.phase("decode", now - req.t_anchor)
            req.t_anchor = now
            self._emit_token(i, logits[i])
        self._g_slots.set(self.active_slots())
        self._h_horizon.observe(1.0)
        self._h_dec_host.observe(time.perf_counter() - t_d1)

    def _speculative_iter(self, active, live):
        """Draft-propose / target-verify (ISSUE 12): the draft engine
        decodes k cheap single-token steps; the target verifies all k in
        ONE bucketed Tq=k step through the fused multi-query path.
        Greedy teacher-forcing makes the emitted stream equal the
        target's own greedy decode: accepted draft tokens matched the
        target argmax given exactly the accepted prefix, and the first
        mismatch emits the target's correction. Accept/reject rollback
        is a host-side lengths truncation — the rejected rows' pages
        stay mapped and are simply overwritten by the next window.
        Raises on dispatch failure (the caller routes to _fail_active)."""
        from .engine import DecodeState
        k = self.speculate_k
        S = self.slots
        need = int(self._lengths[live].max()) + k
        if need > self._state.cache_len:
            self._state = self.engine.grow(self._state, need)
        if need > self._dstate.cache_len:
            self._dstate = self.draft.grow(self._dstate, need)
        # 1) draft proposes k tokens (its lengths mirror is host-owned so
        # the post-verify rollback can truncate it)
        dstate = DecodeState(self._dstate.caches,
                             jnp.asarray(self._dlengths.astype(np.int32)),
                             self._dstate.cache_len)
        props = np.zeros((S, k), np.int64)
        x_d = self._x_t.copy()
        for j in range(k):
            dstate, dlg = self.draft.decode(dstate, x_d, active)
            for s in live:
                t = int(np.argmax(dlg[s]))
                props[s, j] = t
                x_d[s, 0] = self.token_to_features(t)
        self._dstate = dstate
        self._m_proposed.inc(k * len(live))
        # 2) target verifies the window [pending, d_1 .. d_{k-1}]
        x_seq = np.zeros((S, k, self._f), np.float32)
        x_seq[:, 0] = self._x_t[:, 0]
        for s in live:
            for i in range(1, k):
                x_seq[s, i] = self.token_to_features(int(props[s, i - 1]))
        snap = self.engine.pool.ref_snapshot()
        pairs = []
        for s in live:
            pairs += self.engine.prepare_write(
                self._state, s, k, ref_snapshot=snap)
        if pairs:
            self._state = self.engine.fork(self._state, pairs)
        self._state, vlg = self.engine.verify(self._state, x_seq, active)
        # 3) accept while draft == target argmax; first mismatch emits
        # the target's correction; rollback = lengths truncation
        for s in live:
            g = np.argmax(vlg[s], axis=-1)
            accepted = 0
            emitted = []
            for i in range(k):
                emitted.append(int(g[i]))
                if int(props[s, i]) != int(g[i]):
                    break
                accepted += 1
            self._m_accepted.inc(accepted)
            self._h_accept.observe(accepted / k)
            # ISSUE 13 satellite: accept/reject shows up in the stitched
            # timeline — one speculative window phase per verify step
            req = self._slot_req[s]
            now = time.perf_counter()
            req.trace.phase("decode", now - req.t_anchor,
                            speculative=True, proposed=k,
                            accepted=accepted)
            req.t_anchor = now
            l0 = int(self._lengths[s])
            done = False
            for j, tok in enumerate(emitted):
                done = self._emit_known(s, tok, vlg[s, j])
                if done:
                    break
            if not done:
                new_l = l0 + len(emitted)
                self._lengths[s] = new_l
                self._state.lengths[s] = new_l
                self._dlengths[s] = new_l
