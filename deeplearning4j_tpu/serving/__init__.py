"""Inference serving (SURVEY.md §2.5/§2.6: ParallelInference +
JsonModelServer, re-expressed for TPU as a bucketed AOT engine plus a
dynamic micro-batching dispatcher)."""

from ..runtime.faults import (DeadlineExceeded, QueueFull,  # noqa: F401
                              ShutdownError)
from .engine import InferenceEngine, default_buckets, next_bucket  # noqa: F401
from .batcher import HealthState, InferenceMode, ParallelInference  # noqa: F401
from .server import JsonModelServer  # noqa: F401
