"""Inference serving (SURVEY.md §2.5/§2.6: ParallelInference +
JsonModelServer, re-expressed for TPU as a bucketed AOT engine plus a
dynamic micro-batching dispatcher; ISSUE 8 adds the generative decode
hot path — KV-cache prefill/decode executables and token-boundary
continuous batching with streaming; ISSUE 12 adds the paged KV pool —
fixed-size HBM pages + host page tables, copy-on-write prefix sharing,
and draft/verify speculative decoding; ISSUE 18 disaggregates the
generative path — prefill and decode pools joined by KV-page migration,
with a router owning admission; ISSUE 20 adds the model fleet — a
versioned registry behind one front with checkpoint-watch hot-swap,
SLO-gated canarying and automatic rollback)."""

from ..runtime.faults import (DeadlineExceeded, QueueFull,  # noqa: F401
                              ShutdownError)
from .engine import (DecodeState, GenerativeEngine,  # noqa: F401
                     InferenceEngine, PagedDecodeState,
                     PagedGenerativeEngine, default_buckets, next_bucket)
from .kv_pool import (PagedKVPool, PoolExhausted,  # noqa: F401
                      prompt_key)
from .batcher import (ContinuousBatcher, GenerationHandle,  # noqa: F401
                      HealthState, InferenceMode, ParallelInference)
from .disagg import (DisaggRouter, KVShipment,  # noqa: F401
                     PrefillReplica, RouterHandle)
from .fleet import (CanaryGate, CheckpointWatcher,  # noqa: F401
                    FleetError, ModelRegistry, ModelVersion)
from .server import JsonModelServer  # noqa: F401
