"""Inference serving (SURVEY.md §2.5/§2.6: ParallelInference +
JsonModelServer)."""

from .inference import InferenceMode, ParallelInference  # noqa: F401
from .server import JsonModelServer  # noqa: F401
