"""JsonModelServer: minimal HTTP JSON inference endpoint.

TPU-native equivalent of the reference's serving module (reference:
``deeplearning4j-remote .../JsonModelServer.java``† per SURVEY.md §2.5;
reference mount was empty, citation upstream-relative, unverified).

Same contract: POST JSON → model → JSON. Fronted by ParallelInference so
concurrent requests batch onto the device. stdlib ``http.server`` only —
this is the reference's "minimal inference server", not a production
gateway, and says so.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..runtime.faults import DeadlineExceeded, QueueFull, ShutdownError
from .batcher import HealthState, InferenceMode, ParallelInference


class JsonModelServer:
    """POST /predict {"data": [...]} -> {"output": [...]};
    GET /health -> {"status": "ok"} (liveness);
    GET /healthz -> the serving health state machine (readiness):
    200 {"status": "HEALTHY"|"DEGRADED", ...} or 503 when SHEDDING —
    load balancers route away while the queue drains. Degradation errors
    map to real status codes: QueueFull -> 429, DeadlineExceeded -> 504,
    ShutdownError -> 503 (a generic bad request stays 400);
    GET /metrics -> the process-wide MetricsRegistry in Prometheus text
    exposition (ISSUE 6): serving counters/latency summaries, engine
    bucket/compile counters, flash-attention dispatch, resilience
    telemetry, retrace-tracker events — one scrape endpoint for the lot.

    Fleet mode (ISSUE 20): pass ``fleet=ModelRegistry(...)`` instead of a
    model and ONE server front-ends N models x N versions. Requests route
    by the ``X-Model`` header (optional when the fleet serves exactly one
    model) and optional ``X-Model-Version`` pin; unknown names/versions
    are 404s. ``/healthz`` becomes per-model: the top-level status code
    is worst-of the LIVE versions only (a SHEDDING canary cannot 503 the
    whole front while its incumbent is HEALTHY), with the per-model —
    and per-canary — breakdown in the body. The registry's lifecycle
    (hot-swap watch loops, canary evaluation) belongs to the caller;
    ``stop()`` does not shut the fleet down."""

    def __init__(self, model=None, port: int = 0, host: str = "127.0.0.1",
                 mode: str = InferenceMode.BATCHED,
                 pre_processor=None, generate=None, fleet=None,
                 **inference_kwargs):
        if (model is None) == (fleet is None):
            raise ValueError("pass exactly one of model= or fleet=")
        self.fleet = fleet
        self.inference = None if fleet is not None else ParallelInference(
            model, mode=mode, **inference_kwargs)
        # ISSUE 8: generative serving front. ``generate`` is a kwargs dict
        # for ContinuousBatcher (slots/max_cache_len/...); when set, POST
        # /generate streams per-token partial results (NDJSON lines, one
        # per decode iteration) or returns the full token list
        self.generator = None
        if generate is not None and fleet is None:
            from .batcher import ContinuousBatcher
            self.generator = ContinuousBatcher(model, **dict(generate))
        self.pre_processor = pre_processor
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.host = host
        self.port = port

    def start(self) -> int:
        """Start serving in a background thread; returns the bound port."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._send(200, {"status": "ok"})
                elif self.path == "/healthz":
                    if server.fleet is not None:
                        # ISSUE 20 bugfix: per-model readiness. The top-
                        # level code aggregates worst-of the LIVE versions
                        # only — a SHEDDING canary must not 503 the whole
                        # front while its incumbent is HEALTHY; its health
                        # rides in the per-model breakdown instead
                        body = server.fleet.healthz()
                        self._send(503 if body["status"] ==
                                   HealthState.SHEDDING else 200, body)
                        return
                    pi = server.inference
                    h = pi.health()
                    body = {"status": h,
                            "queue_depth": pi.queue_depth(),
                            "shed": pi.shed,
                            "deadline_expired": pi.deadline_expired,
                            "retries": pi.retries,
                            "failures": pi.failures}
                    if server.generator is not None:
                        # disaggregated topologies (ISSUE 18): the pool
                        # role rides readiness so a router/load balancer
                        # can tell a prefill replica from a decode pool
                        # without a second round-trip to /stats
                        body["pool"] = server.generator._pool_label
                    self._send(503 if h == HealthState.SHEDDING else 200,
                               body)
                elif self.path == "/stats":
                    # serving observability: request latency percentiles,
                    # queue depth, bucket hits / compiles; with a
                    # generative front, the page-pool occupancy / prefix
                    # hits / speculative accept-rate ride along (ISSUE 12)
                    if server.fleet is not None:
                        self._send(200, server.fleet.stats())
                        return
                    st = dict(server.inference.stats())
                    if server.generator is not None:
                        st["generator"] = server.generator.stats()
                    self._send(200, st)
                elif self.path == "/metrics":
                    # Prometheus text exposition of the whole registry
                    from ..runtime import telemetry as _telemetry
                    body = _telemetry.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4; "
                                     "charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.startswith("/trace/"):
                    # per-request stitched timeline (ISSUE 13): /predict
                    # and /generate return a trace_id; this resolves it
                    from ..runtime import telemetry as _telemetry
                    tl = _telemetry.get_trace(
                        self.path[len("/trace/"):])
                    if tl is None:
                        self._send(404, {"error": "unknown or evicted "
                                         "trace id"})
                    else:
                        self._send(200, tl)
                elif self.path == "/traces":
                    from ..runtime import telemetry as _telemetry
                    self._send(200,
                               {"traces": _telemetry.recent_traces()})
                else:
                    self._send(404, {"error": "unknown path"})

            def _fleet_target(self):
                """Resolve (name, version) from the routing headers.
                ``X-Model`` may be omitted when the fleet serves exactly
                one model; ``X-Model-Version`` pins a version."""
                from .fleet import FleetError
                name = self.headers.get("X-Model")
                if name is None:
                    name = server.fleet.single_model_name()
                ver = self.headers.get("X-Model-Version")
                if ver is not None:
                    try:
                        ver = int(ver)
                    except ValueError:
                        raise FleetError(
                            f"X-Model-Version must be an integer; got "
                            f"{ver!r}")
                return name, ver

            def do_POST(self):
                if self.path == "/generate":
                    self._generate()
                    return
                if self.path != "/predict":
                    self._send(404, {"error": "unknown path"})
                    return
                from .fleet import FleetError
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    x = np.asarray(req["data"], dtype=np.float32)
                    if server.pre_processor is not None:
                        from ..data.dataset import DataSet
                        ds = DataSet(x, None)
                        server.pre_processor.transform(ds)
                        x = ds.features
                    if server.fleet is not None:
                        name, ver = self._fleet_target()
                        fut = server.fleet.submit(name, x, version=ver)
                        out = server.fleet.wait(fut)
                    else:
                        fut = server.inference.submit(x)
                        out = server.inference._wait(fut)
                    payload = {"output":
                               [np.asarray(o).tolist() for o in out]
                               if isinstance(out, list)
                               else np.asarray(out).tolist()}
                    # stitched-timeline handle (ISSUE 13): resolve it at
                    # GET /trace/<id> (absent when telemetry is off)
                    if getattr(fut, "trace_id", None) is not None:
                        payload["trace_id"] = fut.trace_id
                    if server.fleet is not None:
                        # which version actually served the request (the
                        # canary split means the caller cannot know)
                        payload["version"] = fut.fleet_version
                    self._send(200, payload)
                except FleetError as e:
                    self._send(404, {"error": f"{type(e).__name__}: {e}"})
                except QueueFull as e:
                    self._send(429, {"error": f"{type(e).__name__}: {e}"})
                except DeadlineExceeded as e:
                    self._send(504, {"error": f"{type(e).__name__}: {e}"})
                except ShutdownError as e:
                    self._send(503, {"error": f"{type(e).__name__}: {e}"})
                except Exception as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})

            def _generate(self):
                """POST /generate {"prompt": [[...]] | "tokens": [ids],
                "max_new_tokens": n, "stream": bool} — continuous-batching
                autoregressive decode. ``stream=true`` writes one NDJSON
                line per generated token as each decode iteration lands
                (partial results at token boundaries), then a final
                ``{"done": true, "tokens": [...]}`` line; non-streaming
                returns one JSON body."""
                from .fleet import FleetError
                if server.generator is None and server.fleet is None:
                    self._send(404, {"error": "server was built without "
                                     "generate= support"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    kw = {}
                    if req.get("max_new_tokens") is not None:
                        kw["max_new_tokens"] = int(req["max_new_tokens"])
                    if req.get("deadline_ms") is not None:
                        kw["deadline_ms"] = float(req["deadline_ms"])
                    if "tokens" in req:
                        kw["tokens"] = [int(t) for t in req["tokens"]]
                    else:
                        kw["prompt"] = np.asarray(req["prompt"],
                                                  np.float32)
                    if server.fleet is not None:
                        name, ver = self._fleet_target()
                        handle = server.fleet.submit_generate(
                            name, version=ver, **kw)
                    else:
                        handle = server.generator.submit(**kw)
                    if not req.get("stream"):
                        res = handle.result()
                        payload = {"tokens": res["tokens"]}
                        if getattr(handle, "trace_id", None) is not None:
                            payload["trace_id"] = handle.trace_id
                        self._send(200, payload)
                        return
                    # stream NDJSON per token; HTTP/1.0 close-delimited
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.end_headers()
                    try:  # headers are out: failures become an error LINE
                        i = 0
                        for tok in handle.tokens():
                            self.wfile.write(json.dumps(
                                {"index": i, "token": int(tok)}
                            ).encode() + b"\n")
                            self.wfile.flush()
                            i += 1
                        res = handle.result()
                        final = {"done": True, "tokens": res["tokens"]}
                        if getattr(handle, "trace_id", None) is not None:
                            final["trace_id"] = handle.trace_id
                        self.wfile.write(json.dumps(final).encode()
                                         + b"\n")
                    except Exception as e:
                        self.wfile.write(json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}
                        ).encode() + b"\n")
                except FleetError as e:
                    self._send(404, {"error": f"{type(e).__name__}: {e}"})
                except QueueFull as e:
                    self._send(429, {"error": f"{type(e).__name__}: {e}"})
                except DeadlineExceeded as e:
                    self._send(504, {"error": f"{type(e).__name__}: {e}"})
                except ShutdownError as e:
                    self._send(503, {"error": f"{type(e).__name__}: {e}"})
                except Exception as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        # a fleet's lifecycle (watch loops, canaries) belongs to whoever
        # built the registry — the HTTP front never tears it down
        if self.inference is not None:
            self.inference.shutdown()
        if self.generator is not None:
            self.generator.shutdown()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
