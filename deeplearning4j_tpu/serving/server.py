"""JsonModelServer: minimal HTTP JSON inference endpoint.

TPU-native equivalent of the reference's serving module (reference:
``deeplearning4j-remote .../JsonModelServer.java``† per SURVEY.md §2.5;
reference mount was empty, citation upstream-relative, unverified).

Same contract: POST JSON → model → JSON. Fronted by ParallelInference so
concurrent requests batch onto the device. stdlib ``http.server`` only —
this is the reference's "minimal inference server", not a production
gateway, and says so.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..runtime.faults import DeadlineExceeded, QueueFull, ShutdownError
from .batcher import HealthState, InferenceMode, ParallelInference


class JsonModelServer:
    """POST /predict {"data": [...]} -> {"output": [...]};
    GET /health -> {"status": "ok"} (liveness);
    GET /healthz -> the serving health state machine (readiness):
    200 {"status": "HEALTHY"|"DEGRADED", ...} or 503 when SHEDDING —
    load balancers route away while the queue drains. Degradation errors
    map to real status codes: QueueFull -> 429, DeadlineExceeded -> 504,
    ShutdownError -> 503 (a generic bad request stays 400);
    GET /metrics -> the process-wide MetricsRegistry in Prometheus text
    exposition (ISSUE 6): serving counters/latency summaries, engine
    bucket/compile counters, flash-attention dispatch, resilience
    telemetry, retrace-tracker events — one scrape endpoint for the lot."""

    def __init__(self, model, port: int = 0, host: str = "127.0.0.1",
                 mode: str = InferenceMode.BATCHED,
                 pre_processor=None, generate=None, **inference_kwargs):
        self.inference = ParallelInference(model, mode=mode,
                                           **inference_kwargs)
        # ISSUE 8: generative serving front. ``generate`` is a kwargs dict
        # for ContinuousBatcher (slots/max_cache_len/...); when set, POST
        # /generate streams per-token partial results (NDJSON lines, one
        # per decode iteration) or returns the full token list
        self.generator = None
        if generate is not None:
            from .batcher import ContinuousBatcher
            self.generator = ContinuousBatcher(model, **dict(generate))
        self.pre_processor = pre_processor
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.host = host
        self.port = port

    def start(self) -> int:
        """Start serving in a background thread; returns the bound port."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._send(200, {"status": "ok"})
                elif self.path == "/healthz":
                    pi = server.inference
                    h = pi.health()
                    body = {"status": h,
                            "queue_depth": pi.queue_depth(),
                            "shed": pi.shed,
                            "deadline_expired": pi.deadline_expired,
                            "retries": pi.retries,
                            "failures": pi.failures}
                    if server.generator is not None:
                        # disaggregated topologies (ISSUE 18): the pool
                        # role rides readiness so a router/load balancer
                        # can tell a prefill replica from a decode pool
                        # without a second round-trip to /stats
                        body["pool"] = server.generator._pool_label
                    self._send(503 if h == HealthState.SHEDDING else 200,
                               body)
                elif self.path == "/stats":
                    # serving observability: request latency percentiles,
                    # queue depth, bucket hits / compiles; with a
                    # generative front, the page-pool occupancy / prefix
                    # hits / speculative accept-rate ride along (ISSUE 12)
                    st = dict(server.inference.stats())
                    if server.generator is not None:
                        st["generator"] = server.generator.stats()
                    self._send(200, st)
                elif self.path == "/metrics":
                    # Prometheus text exposition of the whole registry
                    from ..runtime import telemetry as _telemetry
                    body = _telemetry.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4; "
                                     "charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif self.path.startswith("/trace/"):
                    # per-request stitched timeline (ISSUE 13): /predict
                    # and /generate return a trace_id; this resolves it
                    from ..runtime import telemetry as _telemetry
                    tl = _telemetry.get_trace(
                        self.path[len("/trace/"):])
                    if tl is None:
                        self._send(404, {"error": "unknown or evicted "
                                         "trace id"})
                    else:
                        self._send(200, tl)
                elif self.path == "/traces":
                    from ..runtime import telemetry as _telemetry
                    self._send(200,
                               {"traces": _telemetry.recent_traces()})
                else:
                    self._send(404, {"error": "unknown path"})

            def do_POST(self):
                if self.path == "/generate":
                    self._generate()
                    return
                if self.path != "/predict":
                    self._send(404, {"error": "unknown path"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    x = np.asarray(req["data"], dtype=np.float32)
                    if server.pre_processor is not None:
                        from ..data.dataset import DataSet
                        ds = DataSet(x, None)
                        server.pre_processor.transform(ds)
                        x = ds.features
                    fut = server.inference.submit(x)
                    out = server.inference._wait(fut)
                    payload = {"output":
                               [np.asarray(o).tolist() for o in out]
                               if isinstance(out, list)
                               else np.asarray(out).tolist()}
                    # stitched-timeline handle (ISSUE 13): resolve it at
                    # GET /trace/<id> (absent when telemetry is off)
                    if getattr(fut, "trace_id", None) is not None:
                        payload["trace_id"] = fut.trace_id
                    self._send(200, payload)
                except QueueFull as e:
                    self._send(429, {"error": f"{type(e).__name__}: {e}"})
                except DeadlineExceeded as e:
                    self._send(504, {"error": f"{type(e).__name__}: {e}"})
                except ShutdownError as e:
                    self._send(503, {"error": f"{type(e).__name__}: {e}"})
                except Exception as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})

            def _generate(self):
                """POST /generate {"prompt": [[...]] | "tokens": [ids],
                "max_new_tokens": n, "stream": bool} — continuous-batching
                autoregressive decode. ``stream=true`` writes one NDJSON
                line per generated token as each decode iteration lands
                (partial results at token boundaries), then a final
                ``{"done": true, "tokens": [...]}`` line; non-streaming
                returns one JSON body."""
                if server.generator is None:
                    self._send(404, {"error": "server was built without "
                                     "generate= support"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    kw = {}
                    if req.get("max_new_tokens") is not None:
                        kw["max_new_tokens"] = int(req["max_new_tokens"])
                    if req.get("deadline_ms") is not None:
                        kw["deadline_ms"] = float(req["deadline_ms"])
                    if "tokens" in req:
                        handle = server.generator.submit(
                            tokens=[int(t) for t in req["tokens"]], **kw)
                    else:
                        handle = server.generator.submit(
                            prompt=np.asarray(req["prompt"], np.float32),
                            **kw)
                    if not req.get("stream"):
                        res = handle.result()
                        payload = {"tokens": res["tokens"]}
                        if getattr(handle, "trace_id", None) is not None:
                            payload["trace_id"] = handle.trace_id
                        self._send(200, payload)
                        return
                    # stream NDJSON per token; HTTP/1.0 close-delimited
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.end_headers()
                    try:  # headers are out: failures become an error LINE
                        i = 0
                        for tok in handle.tokens():
                            self.wfile.write(json.dumps(
                                {"index": i, "token": int(tok)}
                            ).encode() + b"\n")
                            self.wfile.flush()
                            i += 1
                        res = handle.result()
                        final = {"done": True, "tokens": res["tokens"]}
                        if getattr(handle, "trace_id", None) is not None:
                            final["trace_id"] = handle.trace_id
                        self.wfile.write(json.dumps(final).encode()
                                         + b"\n")
                    except Exception as e:
                        self.wfile.write(json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}
                        ).encode() + b"\n")
                except QueueFull as e:
                    self._send(429, {"error": f"{type(e).__name__}: {e}"})
                except DeadlineExceeded as e:
                    self._send(504, {"error": f"{type(e).__name__}: {e}"})
                except ShutdownError as e:
                    self._send(503, {"error": f"{type(e).__name__}: {e}"})
                except Exception as e:
                    self._send(400, {"error": f"{type(e).__name__}: {e}"})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        self.inference.shutdown()
        if self.generator is not None:
            self.generator.shutdown()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
