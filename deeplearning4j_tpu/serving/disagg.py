"""Disaggregated generative serving (ISSUE 18 tentpole): a PREFILL pool
and a DECODE pool connected by KV-page migration, with a router that owns
admission.

The r17 per-iteration timelines show the interference this removes:
prefill (compute-bound, bursty, long) and decode (memory-bound, steady,
short) share chips in a colocated batcher, so one long prefill stalls
every decode iteration admitted behind it — TPOT p99 degrades exactly
when prefill load ramps. Here each phase runs on the resource it is
bound on (the TensorFlow dynamic-placement thesis, PAPERS.md
1605.08695), and the placement decision reads MEASURED attribution
fractions, not guesses:

- :class:`PrefillReplica` runs ``PagedGenerativeEngine`` prefill ONLY
  and ships the resulting KV pages as a :class:`KVShipment` — payload
  blocks of ``[page_size, H, d]`` token rows per layer (plus the d=1
  int8 scale rows when ``kv_cache="int8"``), a host-side page-table
  handoff, the prefill logits, and the prefix-registry key.
- The decode pool's ``ContinuousBatcher.submit_prefilled`` ADOPTS the
  shipment: its ``kv_pool`` allocator hands out fresh table slots
  (``adopt`` — refcounted exactly like local pages), the payload
  scatters in bucketed device calls, and the prefix registers under the
  SHIPPED key — so a fleet-wide system prompt is prefilled once per
  POOL, not per process, and the second identical prompt on a DIFFERENT
  replica reuses the migrated pages.
- :class:`DisaggRouter` owns admission: prefill requests route to
  compute-rich replicas and decode residency to HBM-rich ones, using
  each replica's cached ``attribution_report`` fractions plus live
  pages-free/queue-depth telemetry. One ``ref_snapshot()`` per ROUTING
  ROUND (the r21 pattern) supplies every candidate's pages-free count —
  the router never takes a pool lock per candidate request.
- Deadline semantics (the r13 contract extended): the router's
  ``deadline_ms`` bounds submit -> PREFILL admission; at the decode pool
  the clock RE-ARMS (``submit_prefilled``), so a slow handoff can never
  expire prefill work the other pool already paid for.
- One request, ONE timeline: the decode pool continues the prefill
  pool's trace id, so ``stitch_event_logs`` + ``merge_trace_records``
  yield a single timeline whose phases (queue, prefill, export, handoff,
  adopt, decode xN) sum to the measured latency across the process
  boundary.

Serialization is pickle-free: a JSON header + raw ``tobytes()`` buffers
(:meth:`KVShipment.to_bytes` / :meth:`KVShipment.from_bytes`), framed
for a stream socket by :func:`write_msg` / :func:`read_msg` — the same
loopback process channels ``parallel/multihost_sim.py`` exercises.
"""

from __future__ import annotations

import json
import struct
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np

from ..runtime import telemetry as _tel
from ..runtime.faults import DeadlineExceeded, QueueFull, ShutdownError
from .batcher import HealthState, _pi_ids
from .engine import PagedGenerativeEngine, next_bucket
from .kv_pool import prompt_key

_M_MIGRATIONS = _tel.counter(
    "serving.disagg.migrations",
    "KV shipments adopted across the prefill->decode pool boundary")
_M_ROUTED_PREFILL = _tel.counter(
    "serving.disagg.routed_prefill",
    "router admissions that paid a prefill-pool prefill")
_M_ROUTED_HIT = _tel.counter(
    "serving.disagg.routed_prefix_hit",
    "router admissions served from a decode pool's resident prefix "
    "(no prefill, no migration)")
_H_ROUTE = _tel.histogram(
    "serving.phase.route_s",
    "router admission decision time per request (snapshot + scoring)")


# --------------------------------------------------------------------- wire

def write_msg(sock, data: bytes) -> None:
    """Length-prefixed frame on a stream socket (the shipment channel)."""
    sock.sendall(struct.pack("<Q", len(data)) + data)


def read_msg(sock) -> bytes:
    """Read one :func:`write_msg` frame; raises ConnectionError on EOF
    mid-frame (a torn shipment must fail loudly, never truncate)."""
    buf = b""
    while len(buf) < 8:
        chunk = sock.recv(8 - len(buf))
        if not chunk:
            raise ConnectionError("channel closed reading frame header")
        buf += chunk
    (n,) = struct.unpack("<Q", buf)
    parts: List[bytes] = []
    got = 0
    while got < n:
        chunk = sock.recv(min(1 << 20, n - got))
        if not chunk:
            raise ConnectionError(
                f"channel closed mid-frame ({got}/{n} bytes)")
        parts.append(chunk)
        got += len(chunk)
    return b"".join(parts)


class KVShipment:
    """One migrated prompt's KV pages + handoff metadata (ISSUE 18).

    ``payload`` mirrors the engine's ``paged_cache_spec`` tree
    (``{layer: {name: [n_pages*page_size, H, d] rows}}``) in the PAGE
    ORDER of ``pages``; int8 pools carry their per-row f32 scale leaves
    in the same tree. ``elapsed_s`` is the origin-side wall from the
    ORIGIN submit to shipment construction and back-dates the decode
    pool's ``t_enqueue``; ``phase_total_s`` is the sum of trace phases
    the origin emitted, so the decode pool's ``handoff`` phase can tile
    the un-phased remainder exactly."""

    __slots__ = ("page_size", "plen", "pages", "payload", "logits",
                 "prefix_key", "x", "kv_quant", "trace_id", "elapsed_s",
                 "phase_total_s")

    def __init__(self, page_size: int, plen: int, pages: Sequence[int],
                 payload, logits, prefix_key: Optional[str] = None,
                 x=None, kv_quant: bool = False,
                 trace_id: Optional[str] = None,
                 elapsed_s: float = 0.0, phase_total_s: float = 0.0):
        self.page_size = int(page_size)
        self.plen = int(plen)
        self.pages = [int(p) for p in pages]
        self.payload = payload
        self.logits = np.asarray(logits)
        self.prefix_key = prefix_key
        self.x = None if x is None else np.asarray(x, np.float32)
        self.kv_quant = bool(kv_quant)
        self.trace_id = trace_id
        self.elapsed_s = float(elapsed_s)
        self.phase_total_s = float(phase_total_s)

    # ------------------------------------------------------------ validation
    def validate_for(self, engine: PagedGenerativeEngine) -> None:
        """Loud structural rejection BEFORE the request queues (ISSUE 18
        satellite): page-size, quantization-mode, layer-tree, and
        head-count/dtype mismatches between pools raise here, not deep
        inside a device scatter."""
        if self.page_size != int(engine.page_size):
            raise ValueError(
                f"page-size mismatch: shipment pages are "
                f"{self.page_size} tokens, receiving pool uses "
                f"{engine.page_size}")
        if self.kv_quant != bool(engine._kv_quant):
            raise ValueError(
                "kv_cache quantization modes disagree across pools: "
                f"shipment int8={self.kv_quant}, receiving engine "
                f"int8={bool(engine._kv_quant)}")
        spec = engine._pool_spec()
        spec_leaves, spec_def = jax.tree.flatten(spec)
        pay_leaves, pay_def = jax.tree.flatten(self.payload)
        if pay_def != spec_def:
            raise ValueError(
                "migrated payload layer tree does not match the "
                f"receiving pool's cache layout: {pay_def} vs {spec_def}")
        rows = len(self.pages) * self.page_size
        for sl, pl in zip(spec_leaves, pay_leaves):
            pl = np.asarray(pl)
            want = (rows,) + tuple(sl.shape[1:])
            if tuple(pl.shape) != want:
                raise ValueError(
                    f"migrated payload block {tuple(pl.shape)} != {want} "
                    "(head-count/head-dim mismatch between pools)")
            if np.dtype(pl.dtype) != np.dtype(sl.dtype):
                raise ValueError(
                    f"migrated payload dtype {pl.dtype} != pool dtype "
                    f"{sl.dtype}")
        if -(-self.plen // self.page_size) != len(self.pages):
            raise ValueError(
                f"shipment carries {len(self.pages)} pages for plen "
                f"{self.plen} (page_size {self.page_size})")

    # --------------------------------------------------------- serialization
    def _leaf_iter(self):
        # layer keys stay exactly as the pool spec spells them (string
        # layer indices) — coercing them would change the tree_def and
        # fail validate_for on a byte-identical payload
        for layer in sorted(self.payload, key=str):
            for name in sorted(self.payload[layer]):
                yield layer, name, np.asarray(self.payload[layer][name])

    def to_bytes(self) -> bytes:
        """Pickle-free wire form: one JSON header + concatenated raw
        ``tobytes()`` buffers (logits, optional prompt features, then
        every payload leaf in sorted (layer, name) order)."""
        leaves = []
        bufs = [np.ascontiguousarray(self.logits).tobytes()]
        if self.x is not None:
            bufs.append(np.ascontiguousarray(self.x).tobytes())
        for layer, name, arr in self._leaf_iter():
            leaves.append({"layer": layer, "name": name,
                           "shape": list(arr.shape),
                           "dtype": np.dtype(arr.dtype).name})
            bufs.append(np.ascontiguousarray(arr).tobytes())
        header = {
            "v": 1,
            "page_size": self.page_size,
            "plen": self.plen,
            "pages": self.pages,
            "kv_quant": self.kv_quant,
            "prefix_key": self.prefix_key,
            "trace_id": self.trace_id,
            "elapsed_s": self.elapsed_s,
            "phase_total_s": self.phase_total_s,
            "logits": {"shape": list(self.logits.shape),
                       "dtype": np.dtype(self.logits.dtype).name},
            "x": None if self.x is None else
                 {"shape": list(self.x.shape), "dtype": "float32"},
            "leaves": leaves,
        }
        hj = json.dumps(header).encode("utf-8")
        return struct.pack("<Q", len(hj)) + hj + b"".join(bufs)

    @classmethod
    def from_bytes(cls, data: bytes) -> "KVShipment":
        (hn,) = struct.unpack("<Q", data[:8])
        header = json.loads(data[8:8 + hn].decode("utf-8"))
        if header.get("v") != 1:
            raise ValueError(f"unknown KVShipment wire version "
                             f"{header.get('v')!r}")
        off = 8 + hn

        def take(shape, dtype):
            nonlocal off
            n = int(np.prod(shape or [1])) * np.dtype(dtype).itemsize
            arr = np.frombuffer(data[off:off + n], dtype=dtype) \
                .reshape(shape).copy()
            off += n
            return arr

        logits = take(header["logits"]["shape"], header["logits"]["dtype"])
        x = None
        if header["x"] is not None:
            x = take(header["x"]["shape"], header["x"]["dtype"])
        payload: Dict[str, Dict[str, np.ndarray]] = {}
        for leaf in header["leaves"]:
            payload.setdefault(leaf["layer"], {})[leaf["name"]] = \
                take(leaf["shape"], leaf["dtype"])
        return cls(header["page_size"], header["plen"], header["pages"],
                   payload, logits, prefix_key=header["prefix_key"],
                   x=x, kv_quant=header["kv_quant"],
                   trace_id=header["trace_id"],
                   elapsed_s=header["elapsed_s"],
                   phase_total_s=header["phase_total_s"])


class PrefillReplica:
    """A compute-pool replica: runs ``PagedGenerativeEngine`` prefill
    ONLY and ships the resulting pages (ISSUE 18). Its own pool's prefix
    registry makes repeat prompts free on THIS side too — a registered
    prompt exports its resident pages without re-prefilling.

    ``prompt_buckets`` drive both the prefill executables and the
    migration (page-count) buckets, so a warmed replica ships at zero
    post-warmup compiles."""

    def __init__(self, model, pages: int = 64, page_size: int = 16,
                 max_cache_len: int = 256,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 quantize: Optional[str] = None,
                 kv_cache: Optional[str] = None,
                 prefix_cache: bool = True,
                 ship_features: bool = False,
                 pool_label: str = "prefill"):
        self.engine = PagedGenerativeEngine(
            model, slots=1, pages=pages, page_size=page_size,
            max_cache_len=max_cache_len, quantize=quantize,
            kv_cache=kv_cache, pool_label=pool_label)
        self.pool_label = str(pool_label)
        self.prefix_cache = bool(prefix_cache)
        self.ship_features = bool(ship_features)
        P = self.engine.page_size
        pb = sorted({next_bucket(int(t)) for t in
                     (prompt_buckets or [max_cache_len])})
        self.engine.warmup(
            [max_cache_len], pb,
            migrate_buckets=sorted({-(-t // P) for t in pb}))
        self._state = self.engine.new_state(max_cache_len)
        self._lock = threading.Lock()
        self._inflight = 0
        self._events: deque = deque(maxlen=1024)

    # same r10 recent-event window as the serving fronts, per POOL
    def note(self, kind: str) -> None:
        self._events.append((time.perf_counter(), kind))

    def health(self, window_s: float = 5.0) -> str:
        now = time.perf_counter()
        recent = {k for t, k in list(self._events) if now - t <= window_s}
        if "shed" in recent:
            return HealthState.SHEDDING
        if recent & {"failure", "retry", "deadline"}:
            return HealthState.DEGRADED
        return HealthState.HEALTHY

    def queue_depth(self) -> int:
        return self._inflight

    def prefill(self, prompt, plen: Optional[int] = None,
                t_origin: Optional[float] = None) -> KVShipment:
        """Prefill one prompt (or hit this replica's own registry) and
        export its pages as a :class:`KVShipment`. Synchronous — the
        router serializes prefills per replica; ``t_origin`` is the
        origin submit's ``perf_counter`` so the shipment's elapsed time
        (and the stitched timeline's ``queue`` phase) spans any router
        queue wait."""
        prompt = np.asarray(prompt, np.float32)
        if prompt.ndim == 3 and prompt.shape[0] == 1:
            prompt = prompt[0]
        plen = int(plen) if plen is not None else int(prompt.shape[0])
        eng = self.engine
        P = eng.page_size
        n_pages = -(-plen // P)
        t0 = time.perf_counter()
        origin = t_origin if t_origin is not None else t0
        trace = _tel.start_request_trace(
            "serving.generate", pool=self.pool_label, plen=plen,
            migrated=True)
        phases: List[float] = []

        def phase(name, dur, **attrs):
            trace.phase(name, dur, **attrs)
            phases.append(float(dur))

        phase("queue", t0 - origin)
        key = prompt_key(prompt, plen) if self.prefix_cache else None
        self._inflight += 1
        try:
            with self._lock:
                hit = eng.pool.lookup_prefix(key) \
                    if key is not None else None
                if hit is not None:
                    pages = list(hit.pages)
                    logits = hit.logits.copy()
                else:
                    t1 = time.perf_counter()
                    pages = eng.pool.alloc(n_pages)
                    try:
                        eng.map_pages(self._state, 0, pages)
                        self._state, logits = eng.prefill(
                            self._state, prompt, plen, 0)
                    except BaseException:
                        self._state.page_table[0, :] = 0
                        eng.pool.release(pages)
                        self.note("failure")
                        raise
                    if key is not None:
                        eng.pool.register_prefix(key, pages, plen, logits)
                    phase("prefill", time.perf_counter() - t1)
                t2 = time.perf_counter()
                payload = eng.export_pages(self._state, pages)
                phase("export", time.perf_counter() - t2,
                      pages=len(pages))
                if hit is not None:
                    # lookup_prefix bumped a stream ref for us
                    eng.pool.release(pages)
                else:
                    # the registry's own ref keeps the pages resident;
                    # release the stream ref + clear the slot row (or
                    # drop an unregistered prompt's pages entirely)
                    eng.release_slot(self._state, 0)
                    eng.pool.release(pages)
        finally:
            self._inflight -= 1
        now = time.perf_counter()
        ship = KVShipment(
            P, plen, pages, payload, logits, prefix_key=key,
            x=prompt if self.ship_features else None,
            kv_quant=bool(eng._kv_quant), trace_id=trace.trace_id,
            elapsed_s=now - origin, phase_total_s=sum(phases))
        # the prefill pool's half of the ONE timeline ends at handoff;
        # the decode pool continues under the same trace id
        trace.finish("handoff", pages=len(pages))
        return ship

    def stats(self) -> dict:
        return {"pool": self.pool_label, "health": self.health(),
                "inflight": self._inflight,
                "engine": self.engine.stats()}


class RouterHandle:
    """The router's answer to :class:`GenerationHandle`: resolves to the
    decode-pool handle once routing lands; ``result()``/``tokens()``
    delegate. Routing failures (shed, deadline, structural rejection)
    surface through :meth:`result` exactly like batcher failures."""

    def __init__(self):
        from concurrent.futures import Future
        self._inner: "Future" = Future()
        self.trace_id: Optional[str] = None

    def _bind(self, handle) -> None:
        self.trace_id = handle.trace_id
        self._inner.set_result(handle)

    def _fail(self, err: BaseException) -> None:
        if not self._inner.done():
            self._inner.set_exception(err)

    def result(self, timeout: Optional[float] = None) -> dict:
        return self._inner.result(timeout=timeout).result(timeout=timeout)

    def tokens(self, timeout: Optional[float] = None):
        handle = self._inner.result(timeout=timeout)
        return handle.tokens(timeout=timeout)


class _RouteRequest:
    __slots__ = ("prompt", "plen", "max_new", "deadline_ms", "eos_id",
                 "handle", "t_enqueue", "deadline")

    def __init__(self, prompt, plen, max_new, deadline_ms, eos_id):
        self.prompt = prompt
        self.plen = int(plen)
        self.max_new = max_new
        self.deadline_ms = deadline_ms
        self.eos_id = eos_id
        self.handle = RouterHandle()
        self.t_enqueue = time.perf_counter()
        # the ROUTER deadline bounds submit -> prefill admission; the
        # decode pool re-arms its own clock at submit_prefilled (r13
        # semantics extended — see ContinuousBatcher.submit_prefilled)
        self.deadline = None if deadline_ms is None \
            else self.t_enqueue + deadline_ms / 1e3


class DisaggRouter:
    """Admission owner for a disaggregated serving topology (ISSUE 18):
    N prefill replicas (compute pool) + M decode replicas
    (``ContinuousBatcher`` fronts over HBM-rich pools).

    Routing, per request:

    1. Probe every decode replica's prefix registry (non-mutating
       ``peek_prefix``) — a resident prompt routes straight to that
       replica's ordinary ``submit`` (its admission maps the resident
       pages; no prefill, no migration).
    2. Otherwise prefill on the most COMPUTE-RICH prefill replica —
       ranked by cached ``attribution_report`` compute-fraction headroom
       (lower measured compute fraction = more headroom), queue depth
       breaking ties — then adopt on the most HBM-RICH decode replica:
       pages-free (read from this round's ``ref_snapshot``, see below)
       minus a queue-depth penalty, SHEDDING replicas excluded.

    One ``ref_snapshot()`` per ROUTING ROUND (ISSUE 18 satellite, the
    r21 pattern): the admission loop drains a batch of queued requests
    per round and takes ONE refcount snapshot per decode pool for the
    whole batch — scoring candidates never takes a pool lock per
    request. A stale snapshot can at worst mis-rank a replica by a few
    pages; it can never corrupt admission (the batcher re-checks
    capacity under its own lock).

    Health is per-POOL (the r10/r17 state machine extended): ``health()``
    reports prefill-pool, decode-pool, and router states; the pool SLOs
    (burn-rate alarms) ride the member fronts' existing machinery."""

    def __init__(self, prefills: Sequence[PrefillReplica],
                 decodes: Sequence, max_new_tokens: int = 32,
                 deadline_ms: Optional[float] = None,
                 shed_queue_depth: Optional[int] = None,
                 queue_limit: int = 256,
                 round_limit: int = 8,
                 health_window_s: float = 5.0):
        import queue as _queue
        if not prefills or not decodes:
            raise ValueError("DisaggRouter needs >= 1 prefill and >= 1 "
                             "decode replica")
        self.prefills = list(prefills)
        self.decodes = list(decodes)
        for cb in self.decodes:
            if not getattr(cb, "paged", False):
                raise ValueError("decode replicas must serve paged "
                                 "engines (KV pages migrate)")
        self.max_new_tokens = int(max_new_tokens)
        self.deadline_ms = deadline_ms
        self.shed_queue_depth = None if shed_queue_depth is None \
            else int(shed_queue_depth)
        self.round_limit = max(1, int(round_limit))
        self.health_window = float(health_window_s)
        self._q: "_queue.Queue[_RouteRequest]" = \
            _queue.Queue(maxsize=queue_limit)
        self._shutdown = threading.Event()
        self._events: deque = deque(maxlen=1024)
        self._reports: Dict[tuple, Optional[dict]] = {}
        self._id = str(next(_pi_ids))
        weakref.finalize(self, _tel.registry.discard_cells, pi=self._id)
        _pi = self._id
        self._m_migrations = _M_MIGRATIONS.labeled(pi=_pi, pool="router")
        self._m_routed_prefill = _M_ROUTED_PREFILL.labeled(
            pi=_pi, pool="router")
        self._m_routed_hit = _M_ROUTED_HIT.labeled(pi=_pi, pool="router")
        self._h_route = _H_ROUTE.labeled(pi=_pi, pool="router")
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name="DisaggRouter-admission")
        self._worker.start()

    # ------------------------------------------------------------- admission
    def submit(self, prompt=None, tokens=None, plen: Optional[int] = None,
               max_new_tokens: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               eos_id: Optional[int] = None) -> RouterHandle:
        """Enqueue one generation into the topology. Sheds in the
        caller's thread (``QueueFull``) above ``shed_queue_depth``, like
        the member fronts."""
        if self._shutdown.is_set():
            raise ShutdownError("DisaggRouter is shut down")
        if tokens is not None:
            t2f = self.decodes[0].token_to_features
            prompt = np.stack([t2f(t) for t in tokens])
        prompt = np.asarray(prompt, np.float32)
        if prompt.ndim == 3 and prompt.shape[0] == 1:
            prompt = prompt[0]
        plen = int(plen) if plen is not None else int(prompt.shape[0])
        if self.shed_queue_depth is not None and \
                self._q.qsize() >= self.shed_queue_depth:
            self._note("shed")
            raise QueueFull(
                f"router queue depth {self._q.qsize()} at/above shedding "
                f"threshold {self.shed_queue_depth}")
        dl = self.deadline_ms if deadline_ms is None else deadline_ms
        req = _RouteRequest(prompt, plen,
                            max_new_tokens if max_new_tokens is not None
                            else self.max_new_tokens, dl, eos_id)
        self._q.put(req)
        return req.handle

    def generate(self, prompt=None, tokens=None, **kw) -> dict:
        return self.submit(prompt=prompt, tokens=tokens, **kw).result()

    def _note(self, kind: str) -> None:
        self._events.append((time.perf_counter(), kind))

    def _loop(self):
        import queue as _queue
        while not self._shutdown.is_set():
            try:
                first = self._q.get(timeout=0.05)
            except _queue.Empty:
                continue
            batch = [first]
            while len(batch) < self.round_limit:
                try:
                    batch.append(self._q.get_nowait())
                except _queue.Empty:
                    break
            # ONE snapshot per routing round (r21 pattern): refcounts ->
            # pages-free for every decode candidate, no per-request lock
            snaps = [cb.engine.pool.ref_snapshot() for cb in self.decodes]
            free = [int(np.count_nonzero(s[1:] == 0)) for s in snaps]
            for req in batch:
                try:
                    self._route_one(req, free)
                except BaseException as e:
                    self._note("failure")
                    req.handle._fail(e)

    def _route_one(self, req: _RouteRequest, pages_free: List[int]):
        t0 = time.perf_counter()
        # router deadline: bounds submit -> prefill admission only
        if req.deadline is not None and t0 > req.deadline:
            self._note("deadline")
            req.handle._fail(DeadlineExceeded(
                "request expired in the router queue before prefill "
                "admission"))
            return
        key = prompt_key(req.prompt, req.plen)
        # 1) resident prompt? route to its decode replica, no migration
        for i, cb in enumerate(self.decodes):
            if cb.prefix_cache and cb.engine.pool.peek_prefix(key):
                self._m_routed_hit.inc()
                self._h_route.observe(time.perf_counter() - t0)
                req.handle._bind(cb.submit(
                    prompt=req.prompt, plen=req.plen,
                    max_new_tokens=req.max_new,
                    deadline_ms=req.deadline_ms, eos_id=req.eos_id))
                return
        # 2) prefill on the compute-rich replica, adopt on the HBM-rich
        pre = self.prefills[self._pick_prefill()]
        self._h_route.observe(time.perf_counter() - t0)
        ship = pre.prefill(req.prompt, plen=req.plen,
                           t_origin=req.t_enqueue)
        self._m_routed_prefill.inc()
        di = self._pick_decode(pages_free, len(ship.pages))
        cb = self.decodes[di]
        pages_free[di] -= len(ship.pages)   # keep the round's view honest
        self._m_migrations.inc()
        # deadline RE-ARMS at the decode pool (r13 extended): the full
        # original budget guards decode-queue wait, never the handoff
        req.handle._bind(cb.submit_prefilled(
            ship, max_new_tokens=req.max_new,
            deadline_ms=req.deadline_ms, eos_id=req.eos_id))

    # --------------------------------------------------------------- scoring
    def _report_fractions(self, idx, engine, cache_len: int):
        """Cached attribution fractions per replica engine (the ISSUE 13
        machinery as a routing signal). None when the program cannot be
        attributed (no cost model, no measurement) — scoring then falls
        back to queue depth / pages-free alone."""
        if idx not in self._reports:
            try:
                rep = engine.attribution_report(cache_len)
                self._reports[idx] = rep.get("fractions") \
                    if rep.get("cost_available") else None
            except Exception:
                self._reports[idx] = None
        return self._reports[idx]

    def _pick_prefill(self) -> int:
        best, best_score = 0, None
        for i, pre in enumerate(self.prefills):
            if pre.health(self.health_window) == HealthState.SHEDDING:
                continue
            fr = self._report_fractions(
                ("p", i), pre.engine, pre.engine.max_cache_len)
            headroom = 1.0 - float(fr["compute"]) if fr else 0.5
            score = headroom - 0.25 * pre.queue_depth()
            if best_score is None or score > best_score:
                best, best_score = i, score
        return best

    def _pick_decode(self, pages_free: List[int], need: int) -> int:
        best, best_score = 0, None
        for i, cb in enumerate(self.decodes):
            if cb.health() == HealthState.SHEDDING:
                continue
            fr = self._report_fractions(
                ("d", i), cb.engine, cb.max_cache_len)
            # HBM-rich: free pages normalized by pool size, discounted
            # by measured memory-boundedness and queue depth
            total = max(1, cb.engine.pages - 1)
            score = pages_free[i] / total \
                - 0.1 * (float(fr["memory"]) if fr else 0.5) \
                - 0.05 * cb.queue_depth()
            if pages_free[i] < need:
                score -= 1.0    # would force eviction on arrival
            if best_score is None or score > best_score:
                best, best_score = i, score
        return best

    # ---------------------------------------------------------------- health
    def health(self) -> dict:
        """Per-POOL health (r10/r17 extended): worst member state per
        pool plus the router's own shed/deadline window."""
        def worst(states):
            order = [HealthState.HEALTHY, HealthState.DEGRADED,
                     HealthState.SHEDDING]
            return max(states, key=order.index) if states else \
                HealthState.HEALTHY
        now = time.perf_counter()
        recent = {k for t, k in list(self._events)
                  if now - t <= self.health_window}
        if "shed" in recent or (
                self.shed_queue_depth is not None
                and self._q.qsize() >= self.shed_queue_depth):
            router = HealthState.SHEDDING
        elif recent & {"failure", "deadline"}:
            router = HealthState.DEGRADED
        else:
            router = HealthState.HEALTHY
        return {
            "router": router,
            "prefill": worst([p.health(self.health_window)
                              for p in self.prefills]),
            "decode": worst([cb.health() for cb in self.decodes]),
        }

    def stats(self) -> dict:
        return {
            "health": self.health(),
            "queue_depth": self._q.qsize(),
            "migrations": int(self._m_migrations.value()),
            "routed_prefill": int(self._m_routed_prefill.value()),
            "routed_prefix_hit": int(self._m_routed_hit.value()),
            "prefill": [p.stats() for p in self.prefills],
            "decode": [cb.stats() for cb in self.decodes],
        }

    def shutdown(self):
        self._shutdown.set()
        if self._worker:
            self._worker.join(timeout=10)
        err = ShutdownError("DisaggRouter shut down before the request "
                            "was routed")
        import queue as _queue
        while True:
            try:
                req = self._q.get_nowait()
            except _queue.Empty:
                break
            req.handle._fail(err)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
