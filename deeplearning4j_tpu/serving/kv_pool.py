"""Paged KV-cache pool: host-side page allocator + copy-on-write prefix
registry (ISSUE 12 tentpole, layer 1).

The r13/r14 generative stack held one contiguous power-of-two KV bucket
per slot, so HBM — not compute — was the concurrency ceiling, and a
fleet-wide system prompt was prefilled and cached once per stream. This
module is the bookkeeping half of the fix: the device holds ONE pool of
fixed-size pages per layer ([n_pages * page_size, H, d] token rows —
``nn.model.paged_cache_spec``); everything that decides WHICH page holds
WHAT lives here, in plain host Python:

- **allocator**: a free list over page ids with per-page reference
  counts. Page 0 is reserved as the zero page (unallocated page-table
  entries point there; write-gated scatters are no-ops against it).
- **prefix registry**: admitted prompts register their pages under a
  content key (the full prompt's digest — see the prefix-LM caveat in
  the engine/PARITY notes); an identical later prompt maps the SAME
  physical pages into its slot (refcounted) and reuses the recorded
  prefill logits, so the fleet-wide system prompt is prefilled once.
- **copy-on-write**: a shared page (refcount > 1 — other streams or the
  registry still reference it) is never written; the engine forks it
  (device page copy) on first write and the table entry swings to the
  private copy. This module only answers ``shared(page)`` and counts the
  fork.
- **eviction under pressure**: when the free list runs dry, registry
  entries are dropped LRU-first (their pages return to the pool once no
  live stream references them) — the serving system degrades (prefix
  hit rate drops, counted) instead of dying; only a pool where every
  page is pinned by a LIVE stream raises :class:`PoolExhausted`. The
  ``serving.page_pool`` fault site makes the failure path deterministic
  in tier-1.

Thread-safety: one decode worker owns admission/release; ``stats()`` may
be read from any thread — all state mutates under one lock.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..runtime import faults as _faults
from ..runtime import telemetry as _tel

_G_TOTAL = _tel.gauge("serving.page_pool.pages_total",
                      "allocatable pages in the paged KV pool")
_G_FREE = _tel.gauge("serving.page_pool.pages_free",
                     "pages on the free list right now")
_M_PREFIX_HITS = _tel.counter(
    "serving.page_pool.prefix_hits",
    "admissions that mapped a registered prompt's pages (prefilled once)")
_M_PREFIX_MISSES = _tel.counter(
    "serving.page_pool.prefix_misses",
    "admissions that prefilled and registered fresh pages")
_M_EVICTIONS = _tel.counter(
    "serving.page_pool.evictions",
    "prefix-registry entries dropped under allocation pressure")
_M_FORKS = _tel.counter(
    "serving.page_pool.forks",
    "copy-on-write page forks (first write to a shared page)")
_M_ADOPTIONS = _tel.counter(
    "serving.page_pool.adoptions",
    "pages adopted from a migrating prefill pool (ISSUE 18 handoff)")


def prompt_key(x, plen: int) -> str:
    """Content key of one FULL prompt (the prefix-registry admission
    key): length + f32 feature bytes through blake2b. Full-prompt only —
    the stack's prefix-LM prompts attend bidirectionally over
    themselves, so per-chunk sharing would blend suffix-dependent k/v
    (see the engine/PARITY notes). Shared by the batcher's paged
    admission and the ISSUE 18 disaggregated router, which must agree on
    the key to route repeat prompts to their migrated pages."""
    h = hashlib.blake2b(digest_size=16)
    h.update(np.int64(int(plen)).tobytes())
    h.update(np.ascontiguousarray(np.asarray(x)[:int(plen)],
                                  dtype=np.float32).tobytes())
    return h.hexdigest()


class PoolExhausted(RuntimeError):
    """Every page is pinned by a live stream: admission must shed. The
    batcher maps this to the same counted-failure path as QueueFull —
    degradation, never corruption."""


class _PrefixEntry:
    __slots__ = ("pages", "plen", "logits")

    def __init__(self, pages: List[int], plen: int, logits: np.ndarray):
        self.pages = list(pages)
        self.plen = int(plen)
        self.logits = np.asarray(logits).copy()


class PagedKVPool:
    """Host bookkeeping for one engine's device page pool.

    ``n_pages`` counts ALL physical pages including the reserved zero
    page, matching the device pool built from
    ``model.paged_cache_spec(n_pages, page_size)`` — so ``n_pages - 1``
    pages are allocatable.
    """

    def __init__(self, n_pages: int, page_size: int,
                 engine_id: str = "0", pool_label: str = "default"):
        if n_pages < 2:
            raise ValueError("paged pool needs >= 2 pages (page 0 is the "
                             "reserved zero page)")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.pool_label = str(pool_label)
        self._lock = threading.RLock()
        self._free: List[int] = list(range(self.n_pages - 1, 0, -1))
        self._ref = np.zeros(self.n_pages, np.int64)
        self._prefix: "OrderedDict[str, _PrefixEntry]" = OrderedDict()
        self.pages_peak = 0
        # pool= beside engine= (ISSUE 18): a disaggregated process pair
        # scrapes both roles into one dashboard — unlabeled cells would
        # blend the prefill pool's churn with decode-pool residency
        eid, pool = engine_id, self.pool_label
        self._g_total = _G_TOTAL.labeled(engine=eid, pool=pool)
        self._g_free = _G_FREE.labeled(engine=eid, pool=pool)
        self._m_hits = _M_PREFIX_HITS.labeled(engine=eid, pool=pool)
        self._m_misses = _M_PREFIX_MISSES.labeled(engine=eid, pool=pool)
        self._m_evict = _M_EVICTIONS.labeled(engine=eid, pool=pool)
        self._m_forks = _M_FORKS.labeled(engine=eid, pool=pool)
        self._m_adopt = _M_ADOPTIONS.labeled(engine=eid, pool=pool)
        self._g_total.set(self.n_pages - 1)
        self._g_free.set(len(self._free))

    # ----------------------------------------------------------- allocator
    def _note_free(self):
        self._g_free.set(len(self._free))
        in_use = (self.n_pages - 1) - len(self._free)
        if in_use > self.pages_peak:
            self.pages_peak = in_use

    def alloc(self, n: int = 1) -> List[int]:
        """``n`` fresh pages (refcount 1 each). Under pressure, evicts
        prefix-registry entries LRU-first; raises :class:`PoolExhausted`
        only when live streams pin everything. All-or-nothing: a failed
        alloc consumes no pages. Fault site ``serving.page_pool``."""
        if _faults.enabled():
            _faults.trip("serving.page_pool")
        with self._lock:
            while len(self._free) < n and self._evict_one():
                pass
            if len(self._free) < n:
                raise PoolExhausted(
                    f"paged KV pool exhausted: need {n} pages, "
                    f"{len(self._free)} free of {self.n_pages - 1} "
                    "(every page pinned by live streams)")
            out = [self._free.pop() for _ in range(n)]
            for p in out:
                self._ref[p] = 1
            self._note_free()
            return out

    def retain(self, pages: Sequence[int]) -> None:
        with self._lock:
            for p in pages:
                if p:
                    self._ref[p] += 1

    def _unref_locked(self, pages: Sequence[int]) -> None:
        """Drop one reference per page (caller holds the lock); a page
        at refcount 0 returns to the free list."""
        for p in pages:
            if not p:
                continue
            self._ref[p] -= 1
            if self._ref[p] <= 0:
                self._ref[p] = 0
                self._free.append(int(p))
        self._note_free()

    def release(self, pages: Sequence[int]) -> None:
        """Drop one reference per page; a page at refcount 0 returns to
        the free list (registered prefix pages stay alive through the
        registry's own reference)."""
        with self._lock:
            self._unref_locked(pages)

    def shared(self, page: int) -> bool:
        """True when writing this page would be visible to another
        reference (another stream or the prefix registry) — the
        copy-on-write trigger."""
        with self._lock:
            return bool(page) and self._ref[page] > 1

    def ref_snapshot(self) -> "np.ndarray":
        """One locked copy of the refcount table (ISSUE 17 satellite):
        the batcher takes this ONCE per admission round and probes
        shared-ness against it instead of calling :meth:`shared` (one
        lock round-trip) per candidate page. Safe for CoW because only
        the calling decode worker can raise a refcount — a stale entry
        can at worst trigger a spurious fork, never lose one."""
        with self._lock:
            return self._ref.copy()

    def note_fork(self, n: int = 1) -> None:
        if n:
            self._m_forks.inc(n)

    def adopt(self, n: int = 1) -> List[int]:
        """Fresh table slots for MIGRATED pages (ISSUE 18): allocation-
        wise identical to :meth:`alloc` (refcount 1 per page — the
        adopting stream's reference; the caller re-registers a migrated
        prefix for the registry's own ref), counted separately so pool
        telemetry splits locally prefilled pages from adopted ones."""
        out = self.alloc(n)
        self._m_adopt.inc(len(out))
        return out

    # ------------------------------------------------------ prefix registry
    def lookup_prefix(self, key: str) -> Optional[_PrefixEntry]:
        """Map a registered prompt: bumps every page's refcount for the
        new stream, refreshes LRU recency, and counts the hit. Returns
        None (counted miss) when the key is unknown."""
        with self._lock:
            e = self._prefix.get(key)
            if e is None:
                self._m_misses.inc()
                return None
            self._prefix.move_to_end(key)
            for p in e.pages:
                self._ref[p] += 1
            self._m_hits.inc()
            return e

    def peek_prefix(self, key: str) -> bool:
        """Non-mutating registry probe (ISSUE 18 router): True when the
        key is registered HERE. Bumps no refcount/LRU and counts no
        hit/miss — the router probes every decode replica per candidate
        prompt, and a counted miss per probe would poison the hit-rate
        signal the pool exports."""
        with self._lock:
            return key in self._prefix

    def register_prefix(self, key: str, pages: Sequence[int], plen: int,
                        logits) -> None:
        """Record a freshly prefilled prompt's pages + logits. The
        registry holds its OWN reference on each page, so the prefix
        outlives the stream that paid the prefill."""
        with self._lock:
            if key in self._prefix:
                return
            e = _PrefixEntry(list(pages), plen, logits)
            for p in e.pages:
                self._ref[p] += 1
            self._prefix[key] = e

    def _evict_one(self) -> bool:
        """Drop the least-recently-used registry entry (caller holds the
        lock). Returns False when the registry is empty."""
        if not self._prefix:
            return False
        _key, e = self._prefix.popitem(last=False)
        self._m_evict.inc()
        self._unref_locked(e.pages)
        return True

    def clear_prefixes(self) -> None:
        """Forget every registered prefix (decode-state rebuild after a
        failed dispatch: the device pool is re-zeroed, so registered
        pages no longer hold their contents)."""
        with self._lock:
            while self._prefix:
                _key, e = self._prefix.popitem(last=False)
                self._unref_locked(e.pages)

    # ----------------------------------------------------------------- view
    def pages_free(self) -> int:
        with self._lock:
            return len(self._free)

    def pages_in_use(self) -> int:
        with self._lock:
            return (self.n_pages - 1) - len(self._free)

    def stats(self) -> Dict:
        with self._lock:
            return {
                "page_size": self.page_size,
                "pages_total": self.n_pages - 1,
                "pages_free": len(self._free),
                "pages_in_use": (self.n_pages - 1) - len(self._free),
                "pages_peak": self.pages_peak,
                "prefix_entries": len(self._prefix),
                "prefix_hits": int(self._m_hits.value()),
                "prefix_misses": int(self._m_misses.value()),
                "evictions": int(self._m_evict.value()),
                "forks": int(self._m_forks.value()),
                "adoptions": int(self._m_adopt.value()),
            }
