"""ParallelInference: concurrent request batching over a jitted apply.

TPU-native equivalent of the reference's inference front (reference:
``deeplearning4j-parallel-wrapper .../parallelism/ParallelInference.java``
— INPLACE/SEQUENTIAL/BATCHED modes with per-device model replicas† per
SURVEY.md §2.6; reference mount was empty, citation upstream-relative,
unverified).

The reference replicates the model across GPUs and round-robins requests;
on TPU one compiled program serves everything, so the useful part of the
contract is the BATCHED mode: many threads call ``output()`` with small
inputs, a collector thread coalesces them (up to ``batch_limit`` or
``max_wait_ms``) into ONE padded device batch — turning request traffic
into MXU-sized work. Pad-to-bucket keeps the number of compiled shapes
bounded (powers of two), the XLA analog of the reference's per-batch-size
queues.
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional

import numpy as np


class InferenceMode:
    SEQUENTIAL = "sequential"
    BATCHED = "batched"


class _Request:
    __slots__ = ("x", "event", "result", "error")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error = None


class ParallelInference:
    """Thread-safe inference front over a model's ``output``.

    Usage::

        pi = ParallelInference(net, mode=InferenceMode.BATCHED,
                               batch_limit=32, max_wait_ms=5)
        y = pi.output(x)         # callable from many threads
        pi.shutdown()
    """

    def __init__(self, model, mode: str = InferenceMode.BATCHED,
                 batch_limit: int = 32, max_wait_ms: float = 5.0,
                 queue_limit: int = 256):
        if mode not in (InferenceMode.SEQUENTIAL, InferenceMode.BATCHED):
            raise ValueError(f"unknown inference mode {mode!r}")
        self.model = model
        self.mode = mode
        self.batch_limit = int(batch_limit)
        self.max_wait = max_wait_ms / 1e3
        self._q: "queue.Queue[_Request]" = queue.Queue(maxsize=queue_limit)
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        self._worker: Optional[threading.Thread] = None
        if mode == InferenceMode.BATCHED:
            self._worker = threading.Thread(target=self._collector,
                                            daemon=True)
            self._worker.start()

    # ---- public -------------------------------------------------------------
    def output(self, x) -> np.ndarray:
        if self._shutdown.is_set():
            raise RuntimeError("ParallelInference is shut down")
        x = np.asarray(x)
        in_shape = getattr(self.model.conf, "input_shape", None)
        if in_shape is not None:
            if x.ndim == len(in_shape):
                x = x[None]  # single example convenience
            if tuple(x.shape[1:]) != tuple(in_shape):
                # reject HERE, in the offending caller's thread — a bad
                # shape inside a coalesced batch would fail everyone
                # sharing the np.concatenate
                raise ValueError(
                    f"input shape {tuple(x.shape[1:])} does not match model "
                    f"input {tuple(in_shape)}")
        if self.mode == InferenceMode.SEQUENTIAL:
            with self._lock:
                return np.asarray(self.model.output(x))
        req = _Request(x)
        self._q.put(req)
        # re-checking wait: shutdown() can win the race between the check
        # above and the put — the queue drain would then miss this request
        # and a bare wait() would deadlock its caller
        while not req.event.wait(timeout=0.2):
            if self._shutdown.is_set():
                raise RuntimeError(
                    "ParallelInference shut down before the request was "
                    "served")
        if req.error is not None:
            raise req.error
        return req.result

    def shutdown(self):
        self._shutdown.set()
        if self._worker:
            self._worker.join(timeout=5)
        # fail any request still queued — leaving them un-signaled would
        # deadlock their callers on event.wait()
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            req.error = RuntimeError("ParallelInference shut down before "
                                     "the request was served")
            req.event.set()

    # ---- collector ----------------------------------------------------------
    def _collector(self):
        while not self._shutdown.is_set():
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            batch: List[_Request] = [first]
            total = first.x.shape[0]
            deadline = _now() + self.max_wait
            while total < self.batch_limit:
                remaining = deadline - _now()
                if remaining <= 0:
                    break
                try:
                    r = self._q.get(timeout=remaining)
                    batch.append(r)
                    total += r.x.shape[0]
                except queue.Empty:
                    break
            self._run(batch, total)

    def _run(self, batch: List[_Request], total: int):
        try:
            x = np.concatenate([r.x for r in batch], axis=0)
            padded = _next_bucket(total)
            if padded != total:  # bounded compiled-shape count
                pad = np.zeros((padded - total,) + x.shape[1:], x.dtype)
                x = np.concatenate([x, pad], axis=0)
            with self._lock:
                out = np.asarray(self.model.output(x))
            i = 0
            for r in batch:
                n = r.x.shape[0]
                r.result = out[i:i + n]
                i += n
                r.event.set()
        except Exception as e:  # propagate to every waiter
            for r in batch:
                r.error = e
                r.event.set()


def _now() -> float:
    import time
    return time.perf_counter()


def _next_bucket(n: int) -> int:
    b = 1
    while b < n:
        b <<= 1
    return b
