"""Back-compat shim: the serving front moved to ``serving.batcher``
(``ParallelInference`` futures dispatcher) + ``serving.engine``
(``InferenceEngine`` bucketed AOT cache). Import from those — or the
``deeplearning4j_tpu.serving`` package — directly."""

from .batcher import InferenceMode, ParallelInference  # noqa: F401
from .engine import next_bucket as _next_bucket  # noqa: F401  (old name)
