"""Sync deep Q-learning (reference ``rl4j-core .../learning/sync/qlearning/
discrete/QLearningDiscreteDense.java``†: DQN over a dense network with
target network, experience replay, double Q-learning, epsilon-greedy).

TPU-first shape: the whole TD update — online forward on obs AND next_obs,
target forward, double-DQN action selection, TD targets, MSE on the taken
actions, gradients and the fused updater sweep — is ONE jitted XLA program
(``_build_update``); the host loop only steps the MDP and fills the
replay buffer. The reference interleaves per-op nd4j calls for the same
math (§3.1 hot-loop contrast).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..nn import updaters as _upd
from .mdp import MDP
from .policy import DQNPolicy, EpsGreedy
from .replay import ExpReplay, Transition


@dataclass
class QLearningConfiguration:
    """Reference ``QLearning.QLConfiguration``† fields that matter here."""
    seed: int = 123
    max_step: int = 5000
    batch_size: int = 32
    target_dqn_update_freq: int = 100
    update_start: int = 64          # replay warmup before learning
    gamma: float = 0.99
    eps_init: float = 1.0
    eps_min: float = 0.05
    eps_decay_steps: int = 1000
    exp_replay_size: int = 10000
    double_dqn: bool = True


class QLearningDiscreteDense:
    """DQN trainer over a MultiLayerNetwork Q-function."""

    def __init__(self, mdp: MDP, network,
                 conf: Optional[QLearningConfiguration] = None):
        self.mdp = mdp
        self.net = network
        self.conf = conf or QLearningConfiguration()
        self.replay = ExpReplay(self.conf.exp_replay_size,
                                self.conf.batch_size, self.conf.seed)
        self.policy = DQNPolicy(network)
        self.explorer = EpsGreedy(self.policy, mdp.n_actions,
                                  self.conf.eps_init, self.conf.eps_min,
                                  self.conf.eps_decay_steps, self.conf.seed)
        self._target_params = jax.tree.map(jnp.copy, network.params)
        self._update = None
        self.step_count = 0       # environment steps
        self.update_count = 0     # gradient updates (drives Adam/schedules)
        self.episode_returns = []
        # set by play(): the shared mdp/history were driven off-policy, so
        # the next train_step must start a fresh episode instead of pairing
        # observations from two unrelated trajectories in the replay buffer
        self._pending_reset = False

    # ------------------------------------------------------------ training
    def _build_update(self):
        net = self.net
        updater = net.conf.updater
        gamma = self.conf.gamma
        double = self.conf.double_dqn

        def q_of(params, x):
            out, _, _ = net._forward(params, x, net.state, train=False,
                                     rng=None)
            return out  # [B, n_actions]

        def update(params, opt_state, target_params, obs, actions, rewards,
                   next_obs, dones, step):
            def loss_fn(p):
                q = q_of(p, obs)
                q_taken = jnp.take_along_axis(
                    q, actions[:, None].astype(jnp.int32), axis=1)[:, 0]
                q_next_t = q_of(target_params, next_obs)
                if double:
                    # double DQN: online net picks, target net evaluates
                    a_star = jnp.argmax(q_of(p, next_obs), axis=1)
                    q_next = jnp.take_along_axis(
                        q_next_t, a_star[:, None], axis=1)[:, 0]
                else:
                    q_next = jnp.max(q_next_t, axis=1)
                td_target = rewards + gamma * (1.0 - dones) * \
                    jax.lax.stop_gradient(q_next)
                return jnp.mean((q_taken - td_target) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            # leaf-wise (apply_fused measured a large regression in the
            # engines' hot steps — see ComputationGraph._build_train_step)
            new_params, new_opt = _upd.apply_leafwise(
                updater, grads, opt_state, params, step)
            return new_params, new_opt, loss

        return jax.jit(update, donate_argnums=(0, 1))

    # observation hooks: the conv/pixel subclass stacks frame history here
    def _observe_reset(self, frame):
        return frame

    def _observe_step(self, frame):
        return frame

    def train_step(self) -> Optional[float]:
        """One environment step (+ one learn step once warm). Returns the
        TD loss when a learn step ran."""
        mdp = self.mdp
        if mdp.is_done() or self.step_count == 0 or self._pending_reset:
            self._obs = self._observe_reset(mdp.reset())
            self._ep_ret = 0.0
            self._pending_reset = False
        obs = self._obs
        action = self.explorer.next_action(obs)
        next_frame, reward, done = mdp.step(action)
        next_obs = self._observe_step(next_frame)
        self.replay.store(Transition(obs, action, reward, next_obs, done))
        self._obs = next_obs
        self._ep_ret += reward
        if done:
            self.episode_returns.append(self._ep_ret)
        self.step_count += 1

        loss = None
        if len(self.replay) >= max(self.conf.update_start,
                                   self.conf.batch_size):
            if self._update is None:
                self._update = self._build_update()
            o, a, r, no, d = self.replay.sample()
            # updater step = UPDATE count (not env steps): Adam bias
            # correction and lr schedules key off optimizer steps, same as
            # MultiLayerNetwork.fit's self.iteration
            self.net.params, self.net.updater_state, loss = self._update(
                self.net.params, self.net.updater_state,
                self._target_params, jnp.asarray(o), jnp.asarray(a),
                jnp.asarray(r), jnp.asarray(no), jnp.asarray(d),
                jnp.asarray(self.update_count, jnp.int32))
            self.update_count += 1
            self.net.iteration = self.update_count  # later fit() continues
            if self.step_count % self.conf.target_dqn_update_freq == 0:
                self._target_params = jax.tree.map(jnp.copy, self.net.params)
        return None if loss is None else float(loss)

    def train(self, max_steps: Optional[int] = None) -> "QLearningDiscreteDense":
        """Run the training loop (reference ``Learning.train()``)."""
        for _ in range(max_steps or self.conf.max_step):
            self.train_step()
        return self

    def get_policy(self) -> DQNPolicy:
        return self.policy


class HistoryProcessor:
    """Rolling frame stack (reference ``rl4j-core .../learning/
    HistoryProcessor.java``†: the Atari-style last-N-frames observation).
    ``reset(frame)`` fills the stack with the first frame; ``add(frame)``
    rolls it. Stacked output is [history, H, W] float32 — the channel axis
    a NCHW conv Q-net consumes."""

    def __init__(self, history_length: int = 4):
        self.n = int(history_length)
        self._frames = None

    def reset(self, frame) -> np.ndarray:
        f = np.asarray(frame, np.float32)
        self._frames = [f] * self.n
        return self.get()

    def add(self, frame) -> np.ndarray:
        self._frames = self._frames[1:] + [np.asarray(frame, np.float32)]
        return self.get()

    def get(self) -> np.ndarray:
        return np.stack(self._frames, axis=0)


class QLearningDiscreteConv(QLearningDiscreteDense):
    """DQN over a convolutional Q-net on stacked pixel frames (reference
    ``rl4j-core .../qlearning/discrete/QLearningDiscreteConv.java``†: the
    flagship pixel-DQN entry point — HistoryProcessor frame stack feeding
    a conv net through the same sync double-DQN machinery).

    The MDP must emit 2-D frames [H, W]; observations seen by the replay
    buffer, policy, and the jitted TD update are the stacked
    [history, H, W] arrays. Everything else — replay, target network,
    double-DQN TD update as one XLA program — is inherited unchanged."""

    def __init__(self, mdp: MDP, network,
                 conf: Optional[QLearningConfiguration] = None,
                 history_length: int = 4):
        super().__init__(mdp, network, conf)
        self.history = HistoryProcessor(history_length)

    def _observe_reset(self, frame):
        return self.history.reset(frame)

    def _observe_step(self, frame):
        return self.history.add(frame)

    def play(self, max_steps: int = 1000) -> float:
        """Greedy rollout with the frame stack applied (DQNPolicy.play
        sees raw frames; the conv Q-net needs stacked observations).
        Drives the shared mdp/history, so the trainer is flagged to start
        a fresh episode on the next train_step."""
        obs = self.history.reset(self.mdp.reset())
        total = 0.0
        for _ in range(max_steps):
            a = self.policy.next_action(obs)
            frame, r, done = self.mdp.step(a)
            obs = self.history.add(frame)
            total += r
            if done:
                break
        self._pending_reset = True
        return total
