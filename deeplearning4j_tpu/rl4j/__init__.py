"""rl4j-equivalent reinforcement learning: MDP contract, experience replay,
double-DQN trainer, policies.

TPU-native equivalent of the reference's rl4j module (reference: ``rl4j/``†
per SURVEY.md §2.5 — presence varies by snapshot and upstream deprecated
it; reference mount was empty, citations upstream-relative, unverified).
Scope mirrors rl4j's discrete-action core: ``MDP`` (gym-style contract),
``ExpReplay``, ``QLearningDiscreteDense`` (DQN with target network, double
Q-learning, epsilon-greedy annealing), ``QLearningDiscreteConv`` +
``HistoryProcessor`` (the pixel path: frame stacking into a conv Q-net,
solved on ``PixelGridworldMDP`` in-suite — ALE/gym emulators are absent in
this environment, recorded), ``DQNPolicy``/``EpsGreedy``. The async family
(A3C/AsyncNStep) is out of scope (recorded; upstream deprecated it).
"""

from .mdp import MDP, PixelGridworldMDP, SimpleToyMDP  # noqa: F401
from .replay import ExpReplay, Transition  # noqa: F401
from .qlearning import (HistoryProcessor,  # noqa: F401
                        QLearningConfiguration,
                        QLearningDiscreteConv,
                        QLearningDiscreteDense)
from .policy import DQNPolicy, EpsGreedy  # noqa: F401
