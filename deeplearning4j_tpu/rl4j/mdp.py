"""MDP contract (reference ``rl4j-api .../mdp/MDP.java``†: gym-style
reset/step/isDone over typed observation/action spaces)."""

from __future__ import annotations

from typing import Tuple

import numpy as np


class MDP:
    """Discrete-action MDP. Subclass and implement reset/step."""

    #: observation vector length
    obs_size: int = 0
    #: number of discrete actions
    n_actions: int = 0

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool]:
        """-> (next_observation, reward, done)"""
        raise NotImplementedError

    def is_done(self) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        pass


class SimpleToyMDP(MDP):
    """1-D corridor with a goal: the canonical rl4j toy (reference
    ``rl4j-core .../mdp/toy/SimpleToy.java``† — a deterministic chain whose
    optimal return is known in closed form, used for trainer convergence
    tests). State i in [0, length); action 1 moves right (+reward at the
    end), action 0 moves left (small negative step reward). Optimal policy:
    always right; optimal return from 0 = (length - 2) * -0.1 + 10 (the
    final step into the goal earns the +10, not the step penalty).
    """

    def __init__(self, length: int = 8, max_steps: int = 50):
        self.length = int(length)
        self.max_steps = int(max_steps)
        self.obs_size = self.length
        self.n_actions = 2
        self._pos = 0
        self._t = 0
        self._done = False

    def _obs(self) -> np.ndarray:
        v = np.zeros((self.obs_size,), np.float32)
        v[self._pos] = 1.0
        return v

    def reset(self) -> np.ndarray:
        self._pos = 0
        self._t = 0
        self._done = False
        return self._obs()

    def step(self, action: int):
        if self._done:
            raise RuntimeError("step() after done; call reset()")
        self._t += 1
        if action == 1:
            self._pos += 1
        else:
            self._pos = max(0, self._pos - 1)
        if self._pos >= self.length - 1:
            self._done = True
            return self._obs(), 10.0, True
        if self._t >= self.max_steps:
            self._done = True
        return self._obs(), -0.1, self._done

    def is_done(self) -> bool:
        return self._done


class PixelGridworldMDP(MDP):
    """Pixel-observation gridworld for the conv-DQN path (the in-suite
    stand-in for the reference's ALE/gym pixel environments, which need
    native emulators this environment lacks — reference
    ``rl4j-gym``/``rl4j-ale``† per SURVEY.md §2.5).

    The agent walks a ``size``x``size`` grid from (0,0) to the goal at
    (size-1, size-1). Observations are raw frames [size, size] float32:
    goal pixel = 0.5, agent pixel = 1.0 (overwrites the goal pixel when
    standing on it). Actions: 0=right, 1=down, 2=left, 3=up. Reward +10
    at the goal, -0.1 per step; episode truncates at ``max_steps``.
    Optimal return = 10 - 0.1 * (2*(size-1) - 1).
    """

    def __init__(self, size: int = 4, max_steps: int = 40):
        self.size = int(size)
        self.max_steps = int(max_steps)
        self.obs_size = self.size * self.size
        self.n_actions = 4
        self._pos = (0, 0)
        self._t = 0
        self._done = False

    @property
    def optimal_return(self) -> float:
        return 10.0 - 0.1 * (2 * (self.size - 1) - 1)

    def _frame(self) -> np.ndarray:
        f = np.zeros((self.size, self.size), np.float32)
        g = self.size - 1
        f[g, g] = 0.5
        r, c = self._pos
        f[r, c] = 1.0
        return f

    def reset(self) -> np.ndarray:
        self._pos = (0, 0)
        self._t = 0
        self._done = False
        return self._frame()

    def step(self, action: int):
        if self._done:
            raise RuntimeError("step() after done; call reset()")
        self._t += 1
        r, c = self._pos
        dr, dc = [(0, 1), (1, 0), (0, -1), (-1, 0)][int(action)]
        r = min(self.size - 1, max(0, r + dr))
        c = min(self.size - 1, max(0, c + dc))
        self._pos = (r, c)
        if self._pos == (self.size - 1, self.size - 1):
            self._done = True
            return self._frame(), 10.0, True
        if self._t >= self.max_steps:
            self._done = True
        return self._frame(), -0.1, self._done

    def is_done(self) -> bool:
        return self._done
