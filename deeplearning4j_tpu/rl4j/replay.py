"""Experience replay (reference ``rl4j-core .../learning/sync/ExpReplay.java``†:
bounded uniform-sampling transition store)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

import numpy as np


@dataclass
class Transition:
    obs: np.ndarray
    action: int
    reward: float
    next_obs: np.ndarray
    done: bool


class ExpReplay:
    """Ring-buffer replay store with uniform batch sampling."""

    def __init__(self, max_size: int = 10000, batch_size: int = 32,
                 seed: int = 123):
        self.max_size = int(max_size)
        self.batch_size = int(batch_size)
        self._buf: List[Transition] = []
        self._pos = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return len(self._buf)

    def store(self, t: Transition) -> None:
        if len(self._buf) < self.max_size:
            self._buf.append(t)
        else:
            self._buf[self._pos] = t
        self._pos = (self._pos + 1) % self.max_size

    def sample(self, batch_size: int | None = None):
        """-> (obs [B,D], actions [B], rewards [B], next_obs [B,D],
        dones [B]) as stacked numpy arrays."""
        bs = batch_size or self.batch_size
        idx = self._rng.integers(0, len(self._buf), bs)
        ts = [self._buf[i] for i in idx]
        return (np.stack([t.obs for t in ts]).astype(np.float32),
                np.asarray([t.action for t in ts], np.int32),
                np.asarray([t.reward for t in ts], np.float32),
                np.stack([t.next_obs for t in ts]).astype(np.float32),
                np.asarray([t.done for t in ts], np.float32))
