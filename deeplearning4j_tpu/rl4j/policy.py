"""Policies (reference ``rl4j-core .../policy/{DQNPolicy,EpsGreedy}.java``†)."""

from __future__ import annotations

import numpy as np


class DQNPolicy:
    """Greedy policy over a Q-network (any model exposing ``output``)."""

    def __init__(self, network):
        self.network = network

    def next_action(self, obs: np.ndarray) -> int:
        q = np.asarray(self.network.output(obs[None, :]))
        return int(np.argmax(q[0]))

    def play(self, mdp, max_steps: int = 1000) -> float:
        """Roll one greedy episode; returns the undiscounted return."""
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done = mdp.step(self.next_action(obs))
            total += r
            if done:
                break
        return total


class EpsGreedy:
    """Annealed epsilon-greedy exploration wrapper (reference EpsGreedy†:
    linear anneal from eps_init to eps_min over eps_decay_steps)."""

    def __init__(self, policy: DQNPolicy, n_actions: int,
                 eps_init: float = 1.0, eps_min: float = 0.05,
                 eps_decay_steps: int = 1000, seed: int = 7):
        self.policy = policy
        self.n_actions = int(n_actions)
        self.eps_init = float(eps_init)
        self.eps_min = float(eps_min)
        self.eps_decay_steps = int(eps_decay_steps)
        self._step = 0
        self._rng = np.random.default_rng(seed)

    @property
    def epsilon(self) -> float:
        frac = min(1.0, self._step / max(1, self.eps_decay_steps))
        return self.eps_init + frac * (self.eps_min - self.eps_init)

    def next_action(self, obs: np.ndarray) -> int:
        eps = self.epsilon
        self._step += 1
        if self._rng.random() < eps:
            return int(self._rng.integers(0, self.n_actions))
        return self.policy.next_action(obs)
