"""Gradient-bucket collective overlap for the ZeRO-1 sharded update.

``ParallelWrapper(shard_update=True)`` lets GSPMD place the gradient
reduce-scatter wherever the partitioner likes along the grad -> clip ->
sentinel -> updater chain — in practice at the updater boundary, AFTER the
global grad-norm joins (clip + divergence sentinel each reduce over the
WHOLE gradient tree), i.e. after every gradient of every layer exists.
Nothing can overlap with a collective that is not issued until the backward
pass is completely done. The TensorFlow system design (PAPERS.md,
1605.08695) names the fix: issue communication as its inputs become ready
and let the scheduler run it under the remaining compute.

This module restructures the step's dataflow to make that legal:

- **Bucketing** (:func:`make_buckets`): parameter leaves are grouped into
  size-capped buckets in REVERSE layer order — backward produces the LAST
  layer's gradients first, so the first bucket's collective can be issued
  while earlier layers' backward compute is still in flight. Size capping
  keeps each chunk big enough to amortize collective launch overhead and
  small enough to pipeline (the DDP/DeepSpeed bucketing recipe).
- **Early scatter** (:func:`overlap_transform`): each bucket's gradient
  leaves are pinned to the ZeRO-1 update sharding with
  ``with_sharding_constraint`` at gradient-production time — GSPMD then
  emits the reduce-scatter THERE, before the global-norm joins (which it
  rewrites to reduce over the shards), instead of at the updater boundary.
- **Issue-order chaining**: consecutive buckets are threaded through
  ``lax.optimization_barrier`` so bucket *i*'s scatter is scheduled before
  bucket *i+1*'s — collectives drain the ICI link in gradient-availability
  order instead of racing, while compute (never passed through a barrier)
  flows freely around them. The XLA latency-hiding scheduler
  (``environment.engine_compiler_options``) does the actual overlap.

Everything here is scheduling structure: sharding constraints and barriers
are value-identity, so ``overlap_grads=True`` is bit-equivalent to the
unoverlapped path (tested, including ``accum_steps`` and tensor-parallel
``model_axis`` composition).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import numpy as np

from ..runtime import telemetry as _tel

#: default bucket size cap — the DDP sweet spot neighborhood; override per
#: wrapper with ``overlap_bucket_mb=``
DEFAULT_BUCKET_MB = 4.0

#: gradient buckets baked into a wrapper's compiled step, labeled
#: ``model=<id>`` (the wrapper's model's telemetry label — same
#: anti-blending rule as the engine/pi/model cells, cleaned by the same
#: weakref finalizer); 0 = that wrapper's current step runs overlap-free.
#: Written by ``ParallelWrapper._build``, not here — the transform itself
#: is a pure function.
BUCKETS_GAUGE = _tel.gauge(
    "parallel.overlap.buckets",
    "gradient buckets in a ParallelWrapper's compiled step, by model= "
    "label (0 = that wrapper's step runs overlap-free)")


def _flatten_paths(tree) -> List[Tuple[Tuple[str, ...], object]]:
    """[(path, leaf)] with the same stringified path names the wrapper's
    sharding trees use, in the pytree's own (layer/topo) order."""
    from jax.tree_util import tree_flatten_with_path
    flat, _ = tree_flatten_with_path(tree)
    return [(tuple(str(getattr(k, "key", k)) for k in path), leaf)
            for path, leaf in flat]


def make_buckets(params, bucket_bytes: int) -> List[List[Tuple[str, ...]]]:
    """Partition the parameter-leaf paths into size-capped buckets in
    reverse top-level (layer/vertex) order. Every leaf lands in exactly one
    bucket; a leaf bigger than the cap gets its own bucket."""
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    flat = _flatten_paths(params)
    # group by top-level key, preserving the dict's construction order
    # (layer index for MultiLayerNetwork, topo order for ComputationGraph)
    groups: Dict[str, List] = {}
    for path, leaf in flat:
        groups.setdefault(path[0] if path else "", []).append((path, leaf))
    buckets: List[List[Tuple[str, ...]]] = []
    cur: List[Tuple[str, ...]] = []
    cur_bytes = 0
    for key in reversed(list(groups)):
        for path, leaf in groups[key]:
            nbytes = int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
            if cur and cur_bytes + nbytes > bucket_bytes:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(path)
            cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


def overlap_transform(buckets: List[List[Tuple[str, ...]]],
                      shardings) -> "callable":
    """The ``grad_transform`` the engines apply right after gradient
    production (BEFORE clip/sentinel): per bucket, pin every leaf to its
    ZeRO-1 update sharding (forcing the reduce-scatter at grad time), and
    chain consecutive buckets through ``optimization_barrier`` so the
    collectives issue in bucket order. Values pass through untouched."""
    shard_by_path = dict(_flatten_paths(shardings))

    def transform(grads):
        flat = dict(_flatten_paths(grads))
        prev: List[Tuple[str, ...]] = []
        for bucket in buckets:
            vals = [flat[p] for p in bucket]
            if prev:
                sealed = jax.lax.optimization_barrier(
                    tuple(flat[p] for p in prev) + tuple(vals))
                for p, v in zip(prev, sealed[:len(prev)]):
                    flat[p] = v
                vals = list(sealed[len(prev):])
            for p, v in zip(bucket, vals):
                sh = shard_by_path.get(p)
                flat[p] = v if sh is None else \
                    jax.lax.with_sharding_constraint(v, sh)
            prev = bucket
        # rebuild the tree in the original structure
        from jax.tree_util import tree_flatten_with_path, tree_unflatten
        paths_leaves, treedef = tree_flatten_with_path(grads)
        keys = [tuple(str(getattr(k, "key", k)) for k in path)
                for path, _ in paths_leaves]
        return tree_unflatten(treedef, [flat[k] for k in keys])

    return transform
