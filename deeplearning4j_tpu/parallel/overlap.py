"""Gradient-bucket collective overlap for the ZeRO-1 sharded update.

``ParallelWrapper(shard_update=True)`` lets GSPMD place the gradient
reduce-scatter wherever the partitioner likes along the grad -> clip ->
sentinel -> updater chain — in practice at the updater boundary, AFTER the
global grad-norm joins (clip + divergence sentinel each reduce over the
WHOLE gradient tree), i.e. after every gradient of every layer exists.
Nothing can overlap with a collective that is not issued until the backward
pass is completely done. The TensorFlow system design (PAPERS.md,
1605.08695) names the fix: issue communication as its inputs become ready
and let the scheduler run it under the remaining compute.

This module restructures the step's dataflow to make that legal:

- **Bucketing** (:func:`make_buckets`): parameter leaves are grouped into
  size-capped buckets in REVERSE layer order — backward produces the LAST
  layer's gradients first, so the first bucket's collective can be issued
  while earlier layers' backward compute is still in flight. Size capping
  keeps each chunk big enough to amortize collective launch overhead and
  small enough to pipeline (the DDP/DeepSpeed bucketing recipe).
- **Early scatter** (:func:`overlap_transform`): each bucket's gradient
  leaves are pinned to the ZeRO-1 update sharding with
  ``with_sharding_constraint`` at gradient-production time — GSPMD then
  emits the reduce-scatter THERE, before the global-norm joins (which it
  rewrites to reduce over the shards), instead of at the updater boundary.
- **Issue-order chaining**: consecutive buckets are threaded through
  ``lax.optimization_barrier`` so bucket *i*'s scatter is scheduled before
  bucket *i+1*'s — collectives drain the ICI link in gradient-availability
  order instead of racing, while compute (never passed through a barrier)
  flows freely around them. The XLA latency-hiding scheduler
  (``environment.engine_compiler_options``) does the actual overlap.

**Multi-host hierarchy** (ISSUE 10): on a pod, the data axis spans two
very different interconnects — ICI within a host, DCN between hosts, an
order of magnitude slower. :class:`HostHierarchy` re-views the pod mesh's
host-major data axis as ``('dcn', 'ici')`` and the transform pins each
bucket in two stages: first to the **intra-host** scatter layout (shard
over ``ici``, replicated over ``dcn`` — GSPMD emits the fast within-host
reduce-scatter plus the cross-host combine of the already-1/local-sized
shards), then to the final ZeRO-1 layout over the full data axis (a local
slice — no further traffic). The DCN hop therefore moves ``1/local``
of the gradient bytes and is issued per-bucket as its gradients appear,
instead of one monolithic end-of-backward collective.
:func:`split_dcn_chains` additionally puts the DCN-heaviest buckets
(leaves whose update could not be sharded — their gradient needs a full
all-reduce, 2x the reduce-scatter's DCN bytes) on their own independent
barrier chain, so the slowest hops issue at the earliest point their
gradients exist and overlap with the remaining backward compute —
without ever gating the light buckets' reduce-scatters behind a heavy
bucket produced late in the backward pass.

Numerics contract of the hierarchy: the bucket ORDERING is value-identity,
but the two-stage pin changes the reduction *decomposition* (within-host
reduce, then cross-host combine, instead of one flat reduce-scatter) — a
different summation tree, so results match the flat schedule to float
rounding (~1 ulp per reduction level), not bit-for-bit. That is the same
trade every real hierarchical collective makes. Any FIXED configuration
remains fully deterministic (same program, same reduction tree every
step), which is what checkpoints/resume bit-equality relies on — and is
tested.

Everything here is scheduling structure: sharding constraints and barriers
are value-identity, so ``overlap_grads=True`` is bit-equivalent to the
unoverlapped path (tested, including ``accum_steps`` and tensor-parallel
``model_axis`` composition).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..runtime import telemetry as _tel

#: default bucket size cap — the DDP sweet spot neighborhood; override per
#: wrapper with ``overlap_bucket_mb=``
DEFAULT_BUCKET_MB = 4.0

#: gradient buckets baked into a wrapper's compiled step, labeled
#: ``model=<id>`` (the wrapper's model's telemetry label — same
#: anti-blending rule as the engine/pi/model cells, cleaned by the same
#: weakref finalizer); 0 = that wrapper's current step runs overlap-free.
#: Written by ``ParallelWrapper._build``, not here — the transform itself
#: is a pure function.
BUCKETS_GAUGE = _tel.gauge(
    "parallel.overlap.buckets",
    "gradient buckets in a ParallelWrapper's compiled step, by model= "
    "label (0 = that wrapper's step runs overlap-free)")


def _flatten_paths(tree) -> List[Tuple[Tuple[str, ...], object]]:
    """[(path, leaf)] with the same stringified path names the wrapper's
    sharding trees use, in the pytree's own (layer/topo) order."""
    from jax.tree_util import tree_flatten_with_path
    flat, _ = tree_flatten_with_path(tree)
    return [(tuple(str(getattr(k, "key", k)) for k in path), leaf)
            for path, leaf in flat]


def make_buckets(params, bucket_bytes: int) -> List[List[Tuple[str, ...]]]:
    """Partition the parameter-leaf paths into size-capped buckets in
    reverse top-level (layer/vertex) order. Every leaf lands in exactly one
    bucket; a leaf bigger than the cap gets its own bucket."""
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be positive, got {bucket_bytes}")
    flat = _flatten_paths(params)
    # group by top-level key, preserving the dict's construction order
    # (layer index for MultiLayerNetwork, topo order for ComputationGraph)
    groups: Dict[str, List] = {}
    for path, leaf in flat:
        groups.setdefault(path[0] if path else "", []).append((path, leaf))
    buckets: List[List[Tuple[str, ...]]] = []
    cur: List[Tuple[str, ...]] = []
    cur_bytes = 0
    for key in reversed(list(groups)):
        for path, leaf in groups[key]:
            nbytes = int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
            if cur and cur_bytes + nbytes > bucket_bytes:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(path)
            cur_bytes += nbytes
    if cur:
        buckets.append(cur)
    return buckets


class HostHierarchy:
    """``('dcn', 'ici'[, <model axis>])`` view of a pod mesh whose data
    axis is host-major (``launcher.pod_mesh``): ``dcn`` indexes hosts,
    ``ici`` the within-host extent of the data axis. Built once per
    compiled step; :meth:`split` maps a final ZeRO-1 update sharding to
    its (intra-host, full) two-stage pin targets."""

    def __init__(self, mesh: Mesh, hosts: int):
        devs = mesh.devices
        data = devs.shape[0]
        if hosts < 2 or data % hosts:
            raise ValueError(f"data axis {data} does not split over "
                             f"{hosts} hosts")
        self.hosts = int(hosts)
        self.local = data // hosts
        shape = (hosts, self.local) + devs.shape[1:]
        names = ("dcn", "ici") + tuple(mesh.axis_names[1:])
        self.mesh = Mesh(devs.reshape(shape), names)

    def _map_spec(self, spec: P, data_to):
        out = []
        for ax in spec:
            if ax == "data":
                out.append(data_to)
            else:
                out.append(ax)
        return P(*out)

    def split(self, sharding: NamedSharding):
        """(intra, full) pins for one leaf. ``intra`` shards the leaf's
        ZeRO dimension over ``ici`` only (replicated over ``dcn`` — the
        within-host reduce-scatter happens here); ``full`` shards it over
        ``('dcn', 'ici')`` == the original data axis (a no-traffic local
        slice after ``intra``). Leaves whose update was never sharded
        (no ``'data'`` in the spec) return ``(None, None)`` — they take
        the plain single-stage pin."""
        spec = sharding.spec
        if "data" not in tuple(spec):
            return None, None
        return (NamedSharding(self.mesh, self._map_spec(spec, "ici")),
                NamedSharding(self.mesh,
                              self._map_spec(spec, ("dcn", "ici"))))


def host_hierarchy(mesh: Mesh, dcn_hosts: Optional[int] = None
                   ) -> Optional[HostHierarchy]:
    """The mesh's host hierarchy, or None when it has none (single host,
    or a data axis too small to split). ``dcn_hosts`` overrides the
    process-membership detection — the single-process simulation knob
    (virtual hosts over virtual CPU devices) and the escape hatch for
    exotic topologies. Auto-detection VALIDATES host-majorness: a mesh
    whose data-axis blocks interleave processes is not DCN-aware (use
    ``launcher.pod_mesh``) and pinning an 'intra-host' sharding over it
    would put the fast stage on the slow wire."""
    devs = mesh.devices
    data = devs.shape[0]
    if dcn_hosts is None:
        procs = [getattr(d, "process_index", 0) for d in devs.flat]
        hosts = len(set(procs))
        if hosts <= 1 or data % hosts:
            return None
        # host-major check: every contiguous data-axis block must belong
        # to exactly one process
        per = data // hosts
        row_major = devs.reshape(data, -1)
        for b in range(hosts):
            block = {getattr(d, "process_index", 0)
                     for d in row_major[b * per:(b + 1) * per].flat}
            if len(block) != 1:
                raise ValueError(
                    "mesh data axis is not host-major (block %d spans "
                    "processes %s); build the mesh with launcher.pod_mesh "
                    "so intra-host collectives stay on ICI" % (b,
                                                               sorted(block)))
        return HostHierarchy(mesh, hosts)
    if dcn_hosts <= 1:
        return None
    return HostHierarchy(mesh, dcn_hosts)


def split_dcn_chains(buckets: List[List[Tuple[str, ...]]],
                     shardings) -> List[List[List[Tuple[str, ...]]]]:
    """Split the (reverse-layer-ordered) buckets into INDEPENDENT barrier
    chains: DCN-heavy buckets — any leaf whose update sharding has no
    ``'data'`` axis, i.e. its gradient needs a full all-reduce (2x a
    reduce-scatter's DCN bytes) — in one chain, the rest in another,
    each preserving production order. Two chains rather than a reordered
    single chain on purpose: a barrier chain orders collective ISSUE, so
    hoisting a heavy bucket to the front of ONE chain would gate every
    light bucket's reduce-scatter behind the heavy bucket's data
    dependency — if that heavy leaf lives in an input-side layer its
    gradient is produced LAST, and the whole pipeline would serialize to
    end-of-backward. Separate chains let each class issue as early as
    its own gradients exist: the slow DCN all-reduces start at first
    opportunity without ever blocking the light reduce-scatters."""
    shard_by_path = dict(_flatten_paths(shardings))

    def heavy(bucket) -> bool:
        for p in bucket:
            sh = shard_by_path.get(p)
            if sh is None or "data" not in tuple(sh.spec):
                return True
        return False

    chains = [[b for b in buckets if heavy(b)],
              [b for b in buckets if not heavy(b)]]
    return [c for c in chains if c]


def overlap_transform(buckets: List[List[Tuple[str, ...]]],
                      shardings,
                      hierarchy: Optional[HostHierarchy] = None,
                      chains: Optional[List[List[List[Tuple[str, ...]]]]]
                      = None) -> "callable":
    """The ``grad_transform`` the engines apply right after gradient
    production (BEFORE clip/sentinel): per bucket, pin every leaf to its
    ZeRO-1 update sharding (forcing the reduce-scatter at grad time), and
    chain consecutive buckets through ``optimization_barrier`` so the
    collectives issue in bucket order. With a ``hierarchy`` (multi-host
    pod mesh) each sharded leaf is pinned in two stages — intra-host
    (``ici``) scatter first, then the full data-axis layout — so the
    cross-host DCN hop carries 1/local-sized shards (see module doc).
    ``chains`` (from :func:`split_dcn_chains`) partitions the buckets
    into INDEPENDENT barrier chains — issue order is constrained within
    a chain, never across chains. Default: one chain of all buckets.
    Values pass through untouched."""
    if chains is None:
        chains = [buckets]
    shard_by_path = dict(_flatten_paths(shardings))

    def pin(v, sh):
        if sh is None:
            return v
        if hierarchy is not None:
            intra, full = hierarchy.split(sh)
            if intra is not None:
                # stage 1: within-host reduce-scatter (+ cross-host
                # combine of the scattered shards); stage 2: local slice
                # to the final ZeRO-1 layout. Value-identity both times.
                v = jax.lax.with_sharding_constraint(v, intra)
                return jax.lax.with_sharding_constraint(v, full)
        return jax.lax.with_sharding_constraint(v, sh)

    def transform(grads):
        flat = dict(_flatten_paths(grads))
        for chain in chains:
            prev: List[Tuple[str, ...]] = []
            for bucket in chain:
                vals = [flat[p] for p in bucket]
                if prev:
                    sealed = jax.lax.optimization_barrier(
                        tuple(flat[p] for p in prev) + tuple(vals))
                    for p, v in zip(prev, sealed[:len(prev)]):
                        flat[p] = v
                    vals = list(sealed[len(prev):])
                for p, v in zip(bucket, vals):
                    flat[p] = pin(v, shard_by_path.get(p))
                prev = bucket
        # rebuild the tree in the original structure
        from jax.tree_util import tree_flatten_with_path, tree_unflatten
        paths_leaves, treedef = tree_flatten_with_path(grads)
        keys = [tuple(str(getattr(k, "key", k)) for k in path)
                for path, _ in paths_leaves]
        return tree_unflatten(treedef, [flat[k] for k in keys])

    return transform
