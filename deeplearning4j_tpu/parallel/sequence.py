"""Sequence/context parallelism: ring attention over a device mesh.

BEYOND-PARITY long-context support (SURVEY.md §2.7 records the reference's
only long-sequence mechanism as truncated BPTT; §5 marks ring/blockwise
attention "explicitly stretch"). The build brief makes long context
first-class, so this module provides the TPU-native mechanism: the sequence
axis is sharded across the mesh, each device holds its Q shard plus a
rotating K/V block, and blocks circulate over ICI via ``lax.ppermute``
while an online-softmax accumulator (the flash-attention recurrence)
combines partial results — attention over sequences ~mesh_size× longer
than one device's HBM could hold, with compute/communication overlap left
to XLA's latency hiding.

Layout: [B, H, T, D] with T sharded on the ``sp`` mesh axis. Causal masking
uses global position offsets carried alongside each rotating block.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.math import precision_for


def _block_attention(q, k, v, m, l, o, q_pos, k_pos, causal, key_mask):
    """One flash-accumulation step against a single K/V block.

    q [B,H,Tq,D]; k,v [B,H,Tb,D]; m,l [B,H,Tq]; o [B,H,Tq,D];
    q_pos [Tq], k_pos [Tb] global positions; key_mask [B,Tb] keep-mask.
    """
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   precision=precision_for(q, k)) * scale
    # -inf (not finfo.min): the isfinite guards below detect fully-masked
    # rows only if masked scores are genuinely non-finite
    neg = jnp.asarray(-jnp.inf, s.dtype)
    if causal:
        allow = q_pos[:, None] >= k_pos[None, :]          # [Tq, Tb]
        s = jnp.where(allow[None, None], s, neg)
    if key_mask is not None:
        s = jnp.where(key_mask[:, None, None, :] > 0, s, neg)
    blk_max = jnp.max(s, axis=-1)                          # [B,H,Tq]
    m_new = jnp.maximum(m, blk_max)
    # fully-masked rows keep m = -inf; exp(neg - neg) would NaN, so clamp
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    correction = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    l_new = l * correction + jnp.sum(p, axis=-1)
    o_new = o * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v, precision=precision_for(p, v))
    return m_new, l_new, o_new


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp",
                   causal: bool = False, key_mask=None):
    """Exact attention with K/V rotating around the mesh ring.

    Args: q/k/v [B, H, T, D] GLOBAL arrays with T sharded over ``axis``
    (replicated inputs are resharded); optional ``key_mask`` [B, T]
    keep-mask sharded the same way. Returns [B, H, T, D] sharded like q.

    Each of the ``p`` ring steps attends q's local shard against one K/V
    block, then ppermutes the block (and its global offset) to the next
    device — per-device peak memory O(T/p), total traffic (p-1)/p of K+V
    over ICI, and the result is EXACT (online softmax), not an
    approximation.
    """
    n = mesh.shape[axis]
    t_total = q.shape[2]
    if t_total % n:
        raise ValueError(f"sequence length {t_total} not divisible by "
                         f"mesh axis {axis}={n}")

    spec_qkv = P(None, None, axis, None)
    spec_mask = P(None, axis)

    def local_fn(q_l, k_l, v_l, mask_l):
        idx = jax.lax.axis_index(axis)
        t_loc = q_l.shape[2]
        q_pos = idx * t_loc + jnp.arange(t_loc)
        B, H, Tq, D = q_l.shape
        m = jnp.full((B, H, Tq), -jnp.inf, q_l.dtype)
        l = jnp.zeros((B, H, Tq), q_l.dtype)
        o = jnp.zeros_like(q_l)

        def body(i, carry):
            m, l, o, k_blk, v_blk, blk_idx, mask_blk = carry
            k_pos = blk_idx * t_loc + jnp.arange(t_loc)
            m, l, o = _block_attention(q_l, k_blk, v_blk, m, l, o,
                                       q_pos, k_pos, causal, mask_blk)
            perm = [(j, (j + 1) % n) for j in range(n)]
            k_blk = jax.lax.ppermute(k_blk, axis, perm)
            v_blk = jax.lax.ppermute(v_blk, axis, perm)
            blk_idx = jax.lax.ppermute(blk_idx, axis, perm)
            if mask_blk is not None:
                mask_blk = jax.lax.ppermute(mask_blk, axis, perm)
            return m, l, o, k_blk, v_blk, blk_idx, mask_blk

        carry = (m, l, o, k_l, v_l, idx, mask_l)
        for i in range(n):  # unrolled: n is a small static mesh dim
            carry = body(i, carry)
        m, l, o = carry[0], carry[1], carry[2]
        return o / jnp.maximum(l, 1e-30)[..., None]

    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # older jax spelling
        from jax.experimental.shard_map import shard_map  # type: ignore

    if key_mask is None:
        fn = shard_map(lambda a, b, c: local_fn(a, b, c, None), mesh=mesh,
                       in_specs=(spec_qkv, spec_qkv, spec_qkv),
                       out_specs=spec_qkv)
        return fn(q, k, v)
    fn = shard_map(local_fn, mesh=mesh,
                   in_specs=(spec_qkv, spec_qkv, spec_qkv, spec_mask),
                   out_specs=spec_qkv)
    return fn(q, k, v, key_mask)


def sequence_sharded(x, mesh: Mesh, axis: str = "sp", time_axis: int = 2):
    """Place an array with its time dimension sharded over the mesh axis."""
    spec = [None] * x.ndim
    spec[time_axis] = axis
    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


def make_sp_mesh(devices=None, axis: str = "sp") -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (axis,))
