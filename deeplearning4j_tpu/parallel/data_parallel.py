"""Data-parallel training over a device mesh.

TPU-native equivalent of DL4J's ``ParallelWrapper`` + Spark
``SharedTrainingMaster`` + ``VoidParameterServer`` stack (reference:
``deeplearning4j-parallel-wrapper .../parallelism/ParallelWrapper.java``†,
``dl4j-spark-parameterserver``†, ``nd4j .../parameterserver/distributed/v2``†
per SURVEY.md §2.6/§2.8/§3.4; reference mount was empty, citations
upstream-relative, unverified).

The entire reference stack (trainer threads, threshold-encoded gradient
gossip over Aeron UDP, mesh organizer) collapses into GSPMD: the batch is
sharded over the mesh's ``data`` axis, parameters are replicated, and XLA
inserts the gradient AllReduce over ICI inside the ONE compiled step
(SURVEY.md §3.4 "TPU translation"). The *contract* kept from the reference:
same-step synchronized replicas, deterministic update application,
listener-visible aggregated stats.

Multi-host: the same compiled program runs on every host via
``jax.distributed.initialize`` (see ``parallel/launcher.py``); this module is
oblivious to host count — the mesh spans whatever ``jax.devices()`` reports.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data.dataset import DataSetIterator
from ..nn.model import MultiLayerNetwork, _as_iterator


def make_mesh(devices: Optional[Sequence] = None, axis: str = "data") -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (axis,))


class ParallelWrapper:
    """Data-parallel fit() over a mesh (name kept for reference parity).

    Usage mirrors DL4J::

        pw = ParallelWrapper(net)            # mesh over all devices
        pw.fit(iterator, epochs=2)

    Batches whose size is not divisible by the mesh size are padded to the
    next multiple and the padded examples are masked out of the loss (DL4J's
    prefetch splitter silently constrained batch%workers; pad-and-mask keeps
    every example contributing exactly once). Caveat recorded: in train mode
    BatchNorm batch statistics see the zero-padded rows of the tail batch —
    a bounded, tail-only artifact; the loss and gradients exclude them.
    """

    def __init__(self, model: MultiLayerNetwork, mesh: Optional[Mesh] = None):
        self.model = model
        self.mesh = mesh or make_mesh()
        self._step = None

    def _build(self):
        base = self.model._build_train_step()  # already jit; re-wrap with shardings
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P("data"))

        # Same pure step; GSPMD partitions the batch dim and inserts the
        # gradient AllReduce. Donation mirrors the single-chip path.
        def step_fn(params, opt_state, bn_state, step, key, x, y, fm, lm):
            return base(params, opt_state, bn_state, step, key, x, y, fm, lm)

        def shard_args(params, opt_state, bn_state, step, key, x, y, fm, lm):
            put = lambda t, s: jax.device_put(t, s)
            params = jax.tree.map(lambda a: put(a, repl), params)
            opt_state = jax.tree.map(lambda a: put(a, repl), opt_state)
            bn_state = jax.tree.map(lambda a: put(a, repl), bn_state)
            x = put(x, data)
            y = put(y, data)
            fm = None if fm is None else put(fm, data)
            lm = None if lm is None else put(lm, data)
            return params, opt_state, bn_state, step, key, x, y, fm, lm

        return step_fn, shard_args

    def fit(self, data, epochs: int = 1) -> MultiLayerNetwork:
        m = self.model
        if not m.params:
            m.init()
        if self._step is None:
            self._step = self._build()
        step_fn, shard_args = self._step
        n = self.mesh.devices.size
        it: DataSetIterator = _as_iterator(data)
        for _ in range(epochs):
            for ds in it:
                x = np.asarray(ds.features)
                y = np.asarray(ds.labels)
                fm = None if ds.features_mask is None else np.asarray(ds.features_mask)
                lm = None if ds.labels_mask is None else np.asarray(ds.labels_mask)
                rem = x.shape[0] % n
                if rem:
                    x, y, fm, lm = _pad_and_mask(x, y, fm, lm, n - rem)
                m._key, sub = jax.random.split(m._key)
                args = shard_args(
                    m.params, m.updater_state, m.state,
                    jnp.asarray(m.iteration, jnp.int32), sub,
                    jnp.asarray(x), jnp.asarray(y),
                    None if fm is None else jnp.asarray(fm),
                    None if lm is None else jnp.asarray(lm))
                m.params, m.updater_state, m.state, loss = step_fn(*args)
                m._score = loss
                m.iteration += 1
                for cb in m._listeners:
                    cb.iteration_done(m, m.iteration, m.epoch)
            m.epoch += 1
            for cb in m._listeners:
                cb.on_epoch_end(m)
        return m


def _pad_and_mask(x, y, fm, lm, pad):
    """Zero-pad `pad` examples onto the batch and mask them out of the loss.

    The label mask is the loss-weighting channel (losses average over the
    unmasked count, see ops/losses._per_example), so padded rows contribute
    zero loss and zero gradient.
    """
    def zpad(a):
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths)

    x, y = zpad(x), zpad(y)
    if fm is not None:
        fm = zpad(fm)  # padded rows have all-zero feature mask
    if lm is not None:
        lm = zpad(lm)  # padded rows masked (zeros)
    elif fm is None:
        # no masks anywhere: synthesize one matching the per-example loss
        # shape (labels' leading dims — [B] dense, [B,T] per-timestep)
        lm = np.ones(y.shape[:-1] or (y.shape[0],), dtype=np.float32)
        lm[-pad:] = 0.0
    # else (fm set, lm absent): the network-propagated out_mask derived from
    # the zero-padded feature mask already excludes padded rows AND masked
    # timesteps of real sequences — synthesizing lm here would override it
    return x, y, fm, lm
