"""Data-parallel training over a device mesh.

TPU-native equivalent of DL4J's ``ParallelWrapper`` + Spark
``SharedTrainingMaster`` + ``VoidParameterServer`` stack (reference:
``deeplearning4j-parallel-wrapper .../parallelism/ParallelWrapper.java``†,
``dl4j-spark-parameterserver``†, ``nd4j .../parameterserver/distributed/v2``†
per SURVEY.md §2.6/§2.8/§3.4; reference mount was empty, citations
upstream-relative, unverified).

The entire reference stack (trainer threads, threshold-encoded gradient
gossip over Aeron UDP, mesh organizer) collapses into GSPMD: the batch is
sharded over the mesh's ``data`` axis, parameters are replicated, and XLA
inserts the gradient AllReduce over ICI inside the ONE compiled step
(SURVEY.md §3.4 "TPU translation"). The *contract* kept from the reference:
same-step synchronized replicas, deterministic update application,
listener-visible aggregated stats.

Multi-host: the same compiled program runs on every host via
``jax.distributed.initialize`` (see ``parallel/launcher.py``); this module is
oblivious to host count — the mesh spans whatever ``jax.devices()`` reports.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..data.dataset import DataSetIterator
from ..nn.model import MultiLayerNetwork, _as_iterator


def make_mesh(devices: Optional[Sequence] = None, axis: str = "data") -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (axis,))


class ParallelWrapper:
    """Data-parallel fit() over a mesh (name kept for reference parity).

    Usage mirrors DL4J::

        pw = ParallelWrapper(net)            # mesh over all devices
        pw.fit(iterator, epochs=2)

    Batches are split evenly across the mesh's data axis; the global batch
    size must be divisible by the mesh size (DL4J's prefetch splitter had the
    same constraint per-workersize).
    """

    def __init__(self, model: MultiLayerNetwork, mesh: Optional[Mesh] = None):
        self.model = model
        self.mesh = mesh or make_mesh()
        self._step = None

    def _build(self):
        base = self.model._build_train_step()  # already jit; re-wrap with shardings
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P("data"))

        # Same pure step; GSPMD partitions the batch dim and inserts the
        # gradient AllReduce. Donation mirrors the single-chip path.
        def step_fn(params, opt_state, bn_state, step, key, x, y, fm, lm):
            return base(params, opt_state, bn_state, step, key, x, y, fm, lm)

        def shard_args(params, opt_state, bn_state, step, key, x, y, fm, lm):
            put = lambda t, s: jax.device_put(t, s)
            params = jax.tree.map(lambda a: put(a, repl), params)
            opt_state = jax.tree.map(lambda a: put(a, repl), opt_state)
            bn_state = jax.tree.map(lambda a: put(a, repl), bn_state)
            x = put(x, data)
            y = put(y, data)
            fm = None if fm is None else put(fm, data)
            lm = None if lm is None else put(lm, data)
            return params, opt_state, bn_state, step, key, x, y, fm, lm

        return step_fn, shard_args

    def fit(self, data, epochs: int = 1) -> MultiLayerNetwork:
        m = self.model
        if not m.params:
            m.init()
        if self._step is None:
            self._step = self._build()
        step_fn, shard_args = self._step
        n = self.mesh.devices.size
        it: DataSetIterator = _as_iterator(data)
        for _ in range(epochs):
            for ds in it:
                if ds.num_examples() % n:
                    continue  # drop ragged tail (keeps shapes static)
                m._key, sub = jax.random.split(m._key)
                args = shard_args(
                    m.params, m.updater_state, m.state,
                    jnp.asarray(m.iteration, jnp.int32), sub,
                    jnp.asarray(ds.features), jnp.asarray(ds.labels),
                    None if ds.features_mask is None else jnp.asarray(ds.features_mask),
                    None if ds.labels_mask is None else jnp.asarray(ds.labels_mask))
                m.params, m.updater_state, m.state, loss = step_fn(*args)
                m._score = loss
                m.iteration += 1
                for cb in m._listeners:
                    cb.iteration_done(m, m.iteration, m.epoch)
            m.epoch += 1
            for cb in m._listeners:
                cb.on_epoch_end(m)
        return m
