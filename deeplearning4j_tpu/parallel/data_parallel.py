"""Data-parallel training over a device mesh.

TPU-native equivalent of DL4J's ``ParallelWrapper`` + Spark
``SharedTrainingMaster`` + ``VoidParameterServer`` stack (reference:
``deeplearning4j-parallel-wrapper .../parallelism/ParallelWrapper.java``†,
``dl4j-spark-parameterserver``†, ``nd4j .../parameterserver/distributed/v2``†
per SURVEY.md §2.6/§2.8/§3.4; reference mount was empty, citations
upstream-relative, unverified).

The entire reference stack (trainer threads, threshold-encoded gradient
gossip over Aeron UDP, mesh organizer) collapses into GSPMD: the batch is
sharded over the mesh's ``data`` axis, parameters are replicated, and XLA
inserts the gradient AllReduce over ICI inside the ONE compiled step
(SURVEY.md §3.4 "TPU translation"). The *contract* kept from the reference:
same-step synchronized replicas, deterministic update application,
listener-visible aggregated stats.

Multi-host: the same compiled program runs on every host via
``jax.distributed.initialize`` (see ``parallel/launcher.py``); this module is
oblivious to host count — the mesh spans whatever ``jax.devices()`` reports.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import environment as _envmod
from ..data.dataset import DataSetIterator, MultiDataSet
from ..nn.model import MultiLayerNetwork, _as_iterator


def make_mesh(devices: Optional[Sequence] = None, axis: str = "data") -> Mesh:
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (axis,))


def make_dp_tp_mesh(data: int, model: int,
                    devices: Optional[Sequence] = None) -> Mesh:
    """2-axis ``('data', 'model')`` mesh for combined data + tensor
    parallelism. Model-axis neighbors should be ICI-adjacent (the default
    device order is), since the per-layer collectives ride that axis."""
    devs = list(devices) if devices is not None else jax.devices()
    if data * model != len(devs):
        raise ValueError(f"data*model = {data * model} != "
                         f"{len(devs)} devices")
    return Mesh(np.array(devs).reshape(data, model), ("data", "model"))


class ParallelWrapper:
    """Data-parallel fit() over a mesh (name kept for reference parity).

    Usage mirrors DL4J::

        pw = ParallelWrapper(net)            # mesh over all devices
        pw.fit(iterator, epochs=2)

    Batches whose size is not divisible by the mesh size are padded to the
    next multiple and the padded examples are masked out of the loss (DL4J's
    prefetch splitter silently constrained batch%workers; pad-and-mask keeps
    every example contributing exactly once). A pad feature mask is
    synthesized alongside the loss mask, so train-mode BatchNorm computes
    mask-aware batch moments — padded rows perturb neither the loss nor
    the running statistics (the round-2 recorded artifact, now fixed;
    equivalence to the unpadded single-chip step is tested).

    ``shard_update=True`` (ZeRO-1, "Automatic Cross-Replica Sharding of
    Weight Update in Data-Parallel Training", Xu et al. 2020, PAPERS.md):
    the updater-state pytree and the weight-update computation are sharded
    over the ``data`` mesh axis instead of replicated — each parameter leaf
    gets its largest divisible dimension partitioned (composed with any
    tensor-parallel ``model_axis`` sharding), ``out_shardings`` pin the
    updated params back to their replicated/TP layout, and GSPMD emits the
    reduce-scatter → 1/N-shard update → all-gather pipeline inside the one
    compiled step (the TVM/GSPMD posture: sharding is a compiler
    annotation, not hand-written collectives). Update FLOPs and updater
    memory (Adam m/v ≈ 2x params) then scale with the per-device share,
    not the model. Numerically equivalent to the replicated path — every
    updater is elementwise (``nn.updaters.apply_leaf`` contract), so the
    shard of the update equals the update of the shard; non-elementwise
    updaters are rejected. Checkpoints gather on save and reshard lazily
    on restore (``parallel/checkpoint.py``), so round-trips across
    ``shard_update`` settings and topologies are exact.

    ``accum_steps=k``: gradient micro-accumulation — each global batch is
    split into k microbatches scanned on device (``nn/microbatch.py``),
    with ONE updater application (and, under ``shard_update``, one
    reduce-scatter/all-gather) per k microbatches, amortizing the update
    collectives exactly as the paper prescribes. Pad granularity becomes
    ``devices * accum_steps`` so microbatches stay equal-sized; microbatch
    losses/gradients combine as a mean WEIGHTED by unmasked label count,
    so a ragged tail whose padding lands unevenly across microbatches
    (even entire all-pad microbatches) still reproduces the unpadded step
    exactly (tested).

    ``overlap_grads=True`` (requires ``shard_update=True``): gradient
    leaves are bucketed by size in reverse layer order and each bucket is
    pinned to the ZeRO-1 update sharding at gradient-production time
    (``parallel/overlap.py``) — the reduce-scatter of early (deep-layer)
    buckets is issued while backward compute of earlier layers is still in
    flight, instead of all collectives waiting behind the clip/sentinel
    global-norm joins at the updater boundary. Pure scheduling structure
    (sharding constraints + ordering barriers): bit-equivalent to the
    unoverlapped path, composes with ``accum_steps`` and ``model_axis``
    (tested). ``overlap_bucket_mb`` caps the per-bucket payload (default
    4 MiB — the DDP bucketing sweet-spot neighborhood).
    """

    def __init__(self, model, mesh: Optional[Mesh] = None,
                 model_axis: Optional[str] = None,
                 shard_update: bool = False, accum_steps: int = 1,
                 overlap_grads: bool = False,
                 overlap_bucket_mb: float = None,
                 dcn_hosts: Optional[int] = None):
        # model: MultiLayerNetwork or ComputationGraph (duck-typed: both
        # expose params/updater_state/state/_build_train_step with the same
        # pytree layout; only the batch-argument arity differs)
        #
        # model_axis: name of a mesh axis to TENSOR-PARALLEL the dense
        # family over (make_dp_tp_mesh): dense/output kernels [in, out]
        # shard over their out column, biases follow, everything else
        # (conv/BN/recurrent) replicates. GSPMD inserts the per-layer
        # collectives; updater state follows parameter sharding. This goes
        # BEYOND the reference (DL4J's parallelism is data-parallel only) —
        # the TPU-first extension SURVEY.md §3.4's translation invites.
        self.model = model
        self.mesh = mesh or make_mesh()
        self.model_axis = model_axis
        if model_axis is not None and model_axis not in self.mesh.axis_names:
            raise ValueError(f"model_axis {model_axis!r} not in mesh axes "
                             f"{self.mesh.axis_names}")
        self.shard_update = bool(shard_update)
        if accum_steps < 1:
            raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
        self.accum_steps = int(accum_steps)
        if self.shard_update:
            if "data" not in self.mesh.axis_names:
                raise ValueError("shard_update needs a 'data' mesh axis to "
                                 f"shard over; mesh has {self.mesh.axis_names}")
            if model_axis == "data":
                raise ValueError("model_axis cannot be the 'data' axis the "
                                 "sharded update partitions over")
            upd = getattr(model.conf, "updater", None)
            if upd is not None and not getattr(upd, "elementwise", True):
                # the ZeRO-1 shard-equivalence contract (updaters.apply_leaf)
                # only holds for elementwise updaters: a per-tensor norm
                # computed over a 1/N shard is not the global norm
                raise ValueError(
                    f"shard_update requires an elementwise updater; "
                    f"{type(upd).__name__} is not")
        from . import overlap as _overlap
        if overlap_grads and not self.shard_update:
            # the collectives the overlap chunks/pipelines ARE the ZeRO-1
            # reduce-scatter/all-gather; the replicated path's one grad
            # all-reduce has no per-bucket shard layout to pin
            raise ValueError("overlap_grads=True requires shard_update=True "
                             "(it pipelines the ZeRO-1 collectives)")
        self.overlap_grads = bool(overlap_grads)
        self.overlap_bucket_bytes = int(
            (overlap_bucket_mb or _overlap.DEFAULT_BUCKET_MB) * (1 << 20))
        # dcn_hosts: DCN-group count along the data axis for the
        # hierarchical gradient collectives (ISSUE 10). None = auto-detect
        # from device process membership (a real pod mesh built by
        # launcher.pod_mesh); an explicit int simulates the hierarchy on a
        # single process's virtual devices (tests / bench) or overrides
        # detection on exotic topologies.
        self.dcn_hosts = dcn_hosts
        self._pending_step_cause = None
        self._step = None
        self._dense_key_cache = None
        from ..nn.graph import ComputationGraph
        self._is_graph = isinstance(model, ComputationGraph)

    def set_overlap(self, on: bool, bucket_mb: Optional[float] = None
                    ) -> "ParallelWrapper":
        """Toggle the gradient-collective overlap (``parallel/overlap.py``)
        in place. The bucketing/sharding pins are baked into the compiled
        step, so a change drops the cached step and the rebuild is
        attributed ``cause="overlap"`` in the retrace tracker."""
        on = bool(on)
        if on and not self.shard_update:
            raise ValueError("overlap_grads=True requires shard_update=True")
        changed = on != self.overlap_grads
        if bucket_mb is not None:
            nb = int(float(bucket_mb) * (1 << 20))
            if nb != self.overlap_bucket_bytes:
                self.overlap_bucket_bytes = nb
                # the bucket size is only baked into OVERLAP steps — a
                # change while overlap stays off must not retrace the
                # (bucket-free) program
                changed = changed or on
        self.overlap_grads = on
        if changed and self._step is not None:
            self._step = None
            self._pending_step_cause = "overlap"
        return self

    def set_accum_steps(self, k: int) -> "ParallelWrapper":
        """Change the gradient micro-accumulation factor in place (the
        ISSUE 14 schedule-tuner apply seam). The microbatch split is
        baked into the compiled step, so a change drops the cached step;
        the rebuild is attributed ``cause="config_change"`` (or whatever
        the tuner arms). Note accum_steps changes the summation ORDER of
        the gradient (weighted-mean recombination, ``nn/microbatch.py``):
        equal to accum_steps=1 to float tolerance, not bit-for-bit."""
        k = int(k)
        if k < 1:
            raise ValueError(f"accum_steps must be >= 1, got {k}")
        if k != self.accum_steps:
            self.accum_steps = k
            if self._step is not None:
                self._step = None
                self._pending_step_cause = \
                    self._pending_step_cause or "config_change"
        return self

    def tune_schedule(self, batch_size: int, apply: bool = True,
                      force: bool = False, **kwargs) -> dict:
        """Joint schedule search over THIS wrapper's sharded train step
        (ISSUE 14, ``runtime/schedule.py``): workspace-mode x accum_steps
        x GLOBAL batch size x ``overlap_bucket_mb`` (when the ZeRO-1
        overlap is on), oracle-pruned via AOT ``memory_analysis`` of the
        GSPMD program, attribution-seeded, timed as real sharded steps.
        ``apply=True`` routes the winner through the existing seams —
        ``model.set_workspace_mode`` / :meth:`set_overlap` /
        :meth:`set_accum_steps` — one attributed retrace each, zero
        steady-state compiles after. Batch size is a recommendation in
        the returned entry (the iterator owns the real batch)."""
        from ..runtime import schedule as _sched
        return _sched.tune_schedule(self, batch_size, apply=apply,
                                    force=force, **kwargs)

    def _dense_keys(self) -> set:
        """Top-level param keys (layer index / vertex name) whose layer is
        in the dense family — the only layers TP shards. Matching on the
        leaf name 'W' alone would also catch embedding tables and LSTM/GRU
        input kernels, whose per-step collectives hurt the TP path.
        Shared with the serving placement layer (ISSUE 17)."""
        from . import placement as _pl
        return _pl.dense_tp_keys(self.model)

    def _param_spec(self, path: tuple, arr) -> P:
        """PartitionSpec for one parameter leaf under tensor parallelism —
        the training contract: dense family only (``attn_heads=None``;
        serving extends the same derivation with the attention family
        through ``ParamsPlacement``)."""
        from . import placement as _pl
        if self.model_axis is None:
            return P()
        if self._dense_key_cache is None:
            self._dense_key_cache = self._dense_keys()
        return _pl.tp_param_spec(
            tuple(str(p) for p in path), arr, self.model_axis,
            int(self.mesh.shape[self.model_axis]), self._dense_key_cache)

    def _update_spec(self, path: tuple, arr) -> P:
        """PartitionSpec for one UPDATER-STATE leaf under the sharded weight
        update (ZeRO-1): on top of the parameter's own spec (replicated, or
        the TP spec when ``model_axis`` is set), the largest still-free
        dimension divisible by the data-axis size is partitioned over
        ``'data'`` — e.g. a dense kernel [in, out] with out >= in becomes
        ``P(None, 'data')`` plain, or ``P('data', 'model')`` under tensor
        parallelism (out taken by 'model', so 'data' lands on the in dim).
        Leaves with no divisible free dimension stay on the base spec
        (replicated update for that leaf — correct, just not sharded)."""
        base = self._param_spec(path, arr)
        n = self.mesh.shape["data"]
        ndim = getattr(arr, "ndim", 0)
        if n <= 1 or ndim == 0:
            return base
        taken = {i for i, ax in enumerate(base) if ax is not None}
        free = [d for d in range(ndim) if d not in taken]
        for d in sorted(free, key=lambda d: -arr.shape[d]):
            if arr.shape[d] % n == 0:
                spec = list(base) + [None] * (ndim - len(base))
                spec[d] = "data"
                return P(*spec)
        return base

    def _shardings(self, params, spec_fn):
        """NamedSharding tree matching the params pytree."""
        from jax.tree_util import tree_map_with_path

        def leaf(path, a):
            names = tuple(str(getattr(k, "key", k)) for k in path)
            return NamedSharding(self.mesh, spec_fn(names, a))
        return tree_map_with_path(leaf, params)

    def _param_shardings(self, params):
        return self._shardings(params, self._param_spec)

    def _update_shardings(self, params):
        return self._shardings(params, self._update_spec)

    def _sharding_trees(self):
        """(repl, data, params, updater-state-slot, opt_state, bn_state,
        params-structure) sharding trees for the step's carried arguments —
        the ONE place the opt-state placement rule lives, shared by
        ``_build`` (out_shardings / per-step placement) and
        ``memory_report`` (sharded avals for AOT lowering)."""
        from jax.tree_util import tree_structure
        repl = NamedSharding(self.mesh, P())
        data = NamedSharding(self.mesh, P("data"))
        p_sh = self._param_shardings(self.model.params)
        upd_sh = self._update_shardings(self.model.params) \
            if self.shard_update else p_sh
        p_struct = tree_structure(self.model.params)
        opt = self.model.updater_state
        if isinstance(opt, dict):
            opt_sh = {k: (upd_sh if tree_structure(sub) == p_struct
                          else jax.tree.map(lambda a: repl, sub))
                      for k, sub in opt.items()}
        else:
            opt_sh = jax.tree.map(lambda a: repl, opt)
        bn_sh = jax.tree.map(lambda a: repl, self.model.state)
        return repl, data, p_sh, upd_sh, opt_sh, bn_sh, p_struct

    def _build(self):
        mesh = self.mesh
        repl = NamedSharding(mesh, P())
        data = NamedSharding(mesh, P("data"))

        # Same pure step; GSPMD partitions the batch dim and inserts the
        # gradient AllReduce. Donation mirrors the single-chip path.
        # out_shardings pin the UPDATED params/state to the input layout:
        # without the pin, GSPMD is free to pick different output shardings
        # for the updated tree than the inputs carried (observed r4 with
        # the then-fused updater's concat/slice chain), which would force a
        # host reshard every step — the pin keeps the TP layout stable
        # regardless of how the update arithmetic is expressed.
        #
        # shard_update=True: the OPT-STATE in/out shardings carry the
        # P('data')-partitioned specs instead of the param specs, while the
        # updated params stay pinned to their replicated/TP layout. GSPMD
        # then materializes the ZeRO-1 pipeline inside this one program:
        # the gradient arrives reduce-SCATTERED into the update's shard
        # layout, the m/v/delta arithmetic runs on each device's 1/N
        # share, and the params pin forces the all-gather of the fresh
        # weights — no hand-written collectives anywhere.
        # overlap_grads (ISSUE 7): bucket the gradient leaves (reverse
        # layer order, size-capped) and pin each bucket to the ZeRO-1
        # update sharding AT GRAD TIME — GSPMD then emits per-bucket
        # reduce-scatters before the clip/sentinel global-norm joins, where
        # the latency-hiding scheduler can run them under the remaining
        # backward compute. Value-identity: bit-equivalent to overlap off.
        grad_transform = None
        from . import overlap as _overlap
        from ..runtime import telemetry as _tel
        n_buckets = 0
        if self.overlap_grads:
            buckets = _overlap.make_buckets(self.model.params,
                                            self.overlap_bucket_bytes)
            upd_shardings = self._update_shardings(self.model.params)
            # multi-host (ISSUE 10): two-stage intra-host/DCN pins per
            # bucket, DCN-heavy buckets on their own issue chain so the
            # slow hops start as early as their grads exist without
            # gating the light reduce-scatters; on a single host
            # hierarchy is None and this is the flat r12 path
            hierarchy = _overlap.host_hierarchy(self.mesh, self.dcn_hosts)
            chains = _overlap.split_dcn_chains(buckets, upd_shardings) \
                if hierarchy is not None else None
            grad_transform = _overlap.overlap_transform(
                buckets, upd_shardings, hierarchy=hierarchy, chains=chains)
            n_buckets = len(buckets)
        # per-model labeled cell (anti-blending rule; 0 = overlap off for
        # THIS wrapper's current step) — the model's telemetry_label
        # finalizer discards it with the rest of the model= cells. On a
        # pod the cell additionally carries host=<process_index> so a
        # pod-wide scrape/merge keeps hosts apart (ISSUE 10 satellite).
        _overlap.BUCKETS_GAUGE.labeled(
            model=getattr(self.model, "telemetry_label",
                          type(self.model).__name__),
            **_tel.host_labels()).set(n_buckets)
        pure = self.model._build_train_step(
            self.accum_steps, grad_transform=grad_transform).__wrapped__
        from jax.tree_util import tree_structure
        from ..runtime import sentinel as _sent
        _, _, p_sh, upd_sh, opt_sh, bn_sh, p_struct = self._sharding_trees()
        # sentinel counters (divergence sentinel, runtime/sentinel.py) ride
        # along replicated — GSPMD reduces the finite-check across shards
        # inside the step, so every device agrees on skip-vs-apply
        sent_sh = {n: repl for n in _sent.COUNTERS}
        step_fn = jax.jit(
            pure, donate_argnums=(0, 1, 2),
            out_shardings=(p_sh, opt_sh, bn_sh, sent_sh, repl),
            compiler_options=_envmod.engine_compiler_options())

        multi_host = jax.process_count() > 1

        # FULL-VALUE placement (params / opt state / BN state / sentinel —
        # every host holds the entire logical value): the shared placement
        # layer's put (ISSUE 17); see placement.put_full for the
        # full-value vs host-shard contract (the (6,16)->(6,32) Adam-slot
        # incident lives in its docstring now).
        from .placement import put_full as put

        def shard_batch(t):
            """Batch-sharded placement for one array, a tuple of arrays
            (multi-input/-output graphs), or None (absent mask).
            Multi-host semantics differ from :func:`put`: the host-local
            batch (HostShardedIterator) IS this host's contiguous SHARD of
            the global batch, so ``make_array_from_process_local_data``
            reassembles the global array in host order."""
            if t is None:
                return None
            if isinstance(t, tuple):
                return tuple(shard_batch(a) for a in t)
            if isinstance(t, jax.Array) and t.sharding == data:
                return t
            if multi_host:
                return jax.make_array_from_process_local_data(
                    data, np.asarray(t))
            return jax.device_put(t, data)

        def shard_args(params, opt_state, bn_state, sentinel, step, key,
                       x, y, fm, lm):
            # params/opt structure and model_axis are fixed after init, so
            # the build-time sharding trees apply every step (after the
            # first step every put() is a pass-through anyway)
            params = jax.tree.map(put, params, p_sh)
            # updater state slots ("m"/"v"/"h"...) mirror the params tree —
            # place them on the update sharding (== the param sharding when
            # shard_update is off) so sharded state stays sharded, and a
            # replicated restore (checkpoint) re-shards lazily here
            opt_state = {
                k: (jax.tree.map(put, sub, upd_sh)
                    if tree_structure(sub) == p_struct
                    else jax.tree.map(lambda a: put(a, repl), sub))
                for k, sub in opt_state.items()
            } if isinstance(opt_state, dict) else jax.tree.map(
                lambda a: put(a, repl), opt_state)
            bn_state = jax.tree.map(lambda a: put(a, repl), bn_state)
            return (params, opt_state, bn_state,
                    put(step, repl), put(key, repl),
                    shard_batch(x), shard_batch(y),
                    shard_batch(fm), shard_batch(lm),
                    jax.tree.map(lambda a: put(a, repl), sentinel))

        return step_fn, shard_args

    def _lower_step(self, batch_size: int, seq_len=None, step_fn=None,
                    cause="probe"):
        """AOT lower+compile of a sharded train step at the GLOBAL
        ``batch_size`` (nothing executes). ``step_fn=None`` uses (and
        caches) THIS wrapper's step; an explicit ``step_fn`` (the
        schedule tuner's candidate builds) is lowered without touching
        the wrapper's cache. The compile is reported to the retrace
        tracker as ``cause`` (``None`` = the caller already attributed
        it, e.g. the tuner's ``schedule_tune``)."""
        from ..nn import memory as _memory
        from ..runtime import sentinel as _sent
        from ..runtime import telemetry as _tel
        m = self.model
        if cause is not None:
            _tel.record_compile("parallel.step", cause,
                                model=type(m).__name__, batch=batch_size)
        if not m.params:
            m.init()
        if step_fn is None:
            if self._step is None:
                self._step = self._build()
            step_fn, _ = self._step
        repl, data, p_sh, _, opt_sh, bn_sh, _ = self._sharding_trees()

        def sds(aval, sh):
            return jax.ShapeDtypeStruct(aval.shape, aval.dtype, sharding=sh)

        x, y = _memory._batch_avals(m, batch_size, seq_len)
        x = jax.tree.map(lambda a: sds(a, data), x)
        y = jax.tree.map(lambda a: sds(a, data), y)
        fm = (None,) * len(x) if isinstance(x, tuple) else None
        lm = (None,) * len(y) if isinstance(y, tuple) else None
        return step_fn.lower(
            jax.tree.map(sds, jax.eval_shape(lambda: m.params), p_sh),
            jax.tree.map(sds, jax.eval_shape(lambda: m.updater_state),
                         opt_sh),
            jax.tree.map(sds, jax.eval_shape(lambda: m.state), bn_sh),
            jax.ShapeDtypeStruct((), jnp.int32, sharding=repl),
            sds(jax.eval_shape(lambda: jax.random.PRNGKey(0)), repl),
            x, y, fm, lm,
            jax.tree.map(lambda a: sds(a, repl),
                         _sent.counter_avals())).compile()

    def memory_report(self, batch_size: int, seq_len=None) -> dict:
        """Compiled-HBM accounting of THIS wrapper's sharded train step
        (GSPMD program — the per-device memory_analysis view) at the
        GLOBAL ``batch_size``, via AOT lower+compile (nothing executes).
        Same fields as ``model.memory_report`` (``nn/memory.py``); the
        conf's ``workspace_mode`` remat policy and ``shard_update``/
        ``accum_steps`` are all baked into the measured program."""
        from ..nn import memory as _memory
        m = self.model
        report = {
            "workspace_mode": str(getattr(m.conf, "workspace_mode", "none")),
            "batch_size": int(batch_size),
            "accum_steps": self.accum_steps,
            "shard_update": self.shard_update,
            "devices": int(self.mesh.devices.size),
            "temp_bytes": None, "argument_bytes": None, "output_bytes": None,
            "alias_bytes": None, "generated_code_bytes": None,
            "peak_bytes": None,
            "device": _memory.device_memory_stats(),
        }
        cm = _memory.compiled_memory(self._lower_step(batch_size, seq_len))
        if cm:
            report.update(cm)
        return report

    def _host_share(self, batch_args, batch_size: int):
        """Slice synthetic FULL-GLOBAL-size batch arrays down to THIS
        host's share before ``shard_args``: the multi-host contract of
        ``shard_batch`` is local-value-IS-the-shard
        (``make_array_from_process_local_data``), so feeding every host
        the full global batch would silently reassemble — and measure —
        a ``batch_size x process_count`` program while the cost model
        and cache key describe ``batch_size`` (the attribution/tuner
        measurement paths). Identity on a single process."""
        n = jax.process_count()
        if n <= 1:
            return batch_args
        if batch_size % n:
            raise ValueError(
                f"global batch {batch_size} does not divide over "
                f"{n} hosts — pass a host-divisible batch_size")
        share = batch_size // n
        return jax.tree.map(lambda a: a[:share], batch_args)

    def _schedule_key_suffix(self) -> dict:
        """The wrapper-schedule fields every cached attribution report
        must be keyed on (ISSUE 14 satellite bugfix): a report measured
        with overlap ON describes a differently-scheduled program than
        one with overlap OFF, and the tuner seeding from the cache must
        never read across that boundary."""
        from . import placement as _pl
        return {"su": int(self.shard_update),
                "ov": int(self.overlap_grads),
                "mb": self.overlap_bucket_bytes / (1 << 20),
                "mesh": _pl.mesh_key(self.mesh)}

    def attribution_report(self, batch_size: int, steps: int = 3,
                           seq_len=None, peaks=None,
                           measured_s=None) -> dict:
        """MFU attribution of THIS wrapper's sharded step at the GLOBAL
        ``batch_size`` (``runtime/attribution.py``): AOT
        ``cost_analysis`` + a synced self-measurement of ``steps`` real
        sharded executions on zero batches (or a caller-supplied
        ``measured_s``). The report key carries the full schedule —
        workspace_mode, accum_steps, shard_update, overlap on/off and
        bucket size, mesh shape — so the ISSUE 14 tuner can seed from
        cached fractions without ever reading a differently-scheduled
        program's numbers."""
        import time as _time

        from ..runtime import attribution as _attr
        from ..runtime import telemetry as _tel
        m = self.model
        if not m.params:
            m.init()
        if self._step is None:
            self._step = self._build()
        step_fn, shard_args = self._step
        # _lower_step records the probe compile itself (parallel.step/
        # probe) — attributing here too would double-count the event
        compiled = self._lower_step(batch_size, seq_len)
        if measured_s is None:
            durs = []
            for i in range(max(1, int(steps)) + 1):
                (params, opt, state, stepi, key, xs, ys, fm, lm,
                 sent) = _attr._train_step_args(
                    m, batch_size, self.accum_steps, seq_len, i)
                xs, ys = self._host_share((xs, ys), batch_size)
                args = shard_args(params, opt, state, sent, stepi, key,
                                  xs, ys, fm, lm)
                t0 = _time.perf_counter()
                out = step_fn(*args)
                jax.block_until_ready(out)
                durs.append(_time.perf_counter() - t0)
            measured_s = min(durs[1:]) if len(durs) > 1 else durs[0]
        key = _attr.train_step_key(m, batch_size, self.accum_steps,
                                   seq_len,
                                   schedule=self._schedule_key_suffix())
        rep = _attr.attribute_compiled(compiled, measured_s, peaks=peaks,
                                       key=key)
        rep.update({"kind": "parallel_step",
                    "batch_size": int(batch_size),
                    "accum_steps": self.accum_steps,
                    "shard_update": self.shard_update,
                    "overlap": self.overlap_grads,
                    "overlap_bucket_mb":
                        self.overlap_bucket_bytes / (1 << 20),
                    "devices": int(self.mesh.devices.size),
                    "workspace_mode":
                        str(getattr(m.conf, "workspace_mode", "none"))})
        return rep

    def on_host_loss(self) -> None:
        """Post-``launcher.reinitialize()`` repair (ISSUE 10): the old
        mesh's device objects belong to the torn-down backend client, so
        rebuild the mesh over the FRESH ``jax.devices()`` with the same
        shape/axes (host-major grouping preserved via ``pod_mesh``'s
        rule), and drop every compiled program that baked the dead
        devices in — the wrapper step and the model's own caches. The
        rebuild is attributed ``cause="host_loss"`` in the retrace
        tracker. Model STATE is not touched here: arrays from the old
        client are dead, and ``run_resilient_fit`` restores them from the
        checkpoint right after."""
        from . import launcher as _launcher
        shape = self.mesh.devices.shape
        if self.mesh.axis_names not in (("data",), ("data", "model")):
            raise RuntimeError(
                f"on_host_loss cannot rebuild a mesh with axes "
                f"{self.mesh.axis_names}; rebuild it yourself and assign "
                "wrapper.mesh before resuming")
        model_ax = shape[1] if len(shape) > 1 else 1
        rebuilt = _launcher.pod_mesh(model=model_ax)
        if rebuilt.devices.shape != shape:
            raise RuntimeError(
                f"post-host-loss topology changed: mesh was {shape}, "
                f"fresh devices give {rebuilt.devices.shape}; restore onto "
                "the new topology explicitly (TrainingCheckpointer restore "
                "is topology-independent)")
        self.mesh = rebuilt
        self._step = None
        self._pending_step_cause = "host_loss"
        if hasattr(self.model, "_invalidate_compiled"):
            self.model._invalidate_compiled(cause="host_loss")

    def serving_engine(self, **kwargs):
        """A ``serving.engine.InferenceEngine`` over THIS wrapper's mesh:
        train data-parallel, then serve the same slice — coalesced request
        batches shard over the ``'data'`` axis (bucket floor rises to the
        mesh size so every device holds equal rows). Keyword args pass
        through (e.g. ``min_bucket=``)."""
        from ..serving.engine import InferenceEngine
        if "data" not in self.mesh.axis_names:
            raise ValueError("serving_engine needs a 'data' mesh axis; "
                             f"mesh has {self.mesh.axis_names}")
        kwargs.setdefault("model_axis", self.model_axis or "model")
        return InferenceEngine(self.model, mesh=self.mesh, **kwargs)

    def fit(self, data, epochs: int = 1, resilience=None):
        if resilience is not None:
            from .resilience import run_resilient_fit
            return run_resilient_fit(self, data, epochs=epochs,
                                     policy=resilience)
        from ..runtime import faults as _faults
        m = self.model
        if not m.params:
            m.init()
        if self._step is None:
            self._step = self._build()
            from ..runtime import telemetry as _tel
            cause = self._pending_step_cause or (
                m._consume_retrace_cause()
                if hasattr(m, "_consume_retrace_cause") else "first_build")
            self._pending_step_cause = None
            _tel.record_compile("parallel.step", cause,
                                shard_update=self.shard_update,
                                overlap=self.overlap_grads)
        step_fn, shard_args = self._step
        # step-phase tracing (shared CompiledCacheMixin scaffold, ISSUE 6):
        # pod fits get the same train.phase.data_wait_s/step_s cells as the
        # engine fit loops — labeled model= AND host= (ISSUE 10), so a
        # pod-wide scrape shows every host's step-time distribution apart
        h_wait, h_step = m._phase_clocks()
        for _ in range(epochs):
            for batch, tel in m._timed_batches(self._batches(data), h_wait):
                x, y, fm, lm = batch
                if _faults.enabled():
                    _faults.trip("train.step")  # crash/preemption site
                    # whole-host-loss site (ISSUE 10): deterministic
                    # injections fire on every process at the same step
                    # (SPMD), raising HostLoss — run_resilient_fit routes
                    # it through launcher.reinitialize() + restore
                    _faults.trip("parallel.host_loss")
                    # float check FIRST: all-int inputs must not consume
                    # the injection's fire budget without poisoning anything
                    if any(np.issubdtype(np.asarray(a).dtype, np.floating)
                           for a in jax.tree.leaves(x)) and \
                            _faults.trip("train.nonfinite") is not None:
                        x = jax.tree.map(
                            lambda a: np.full_like(a, np.nan)
                            if np.issubdtype(np.asarray(a).dtype, np.floating)
                            else a, x)  # sentinel site
                m._key, sub = jax.random.split(m._key)
                args = shard_args(
                    m.params, m.updater_state, m.state, m._ensure_sentinel(),
                    jnp.asarray(m.iteration, jnp.int32), sub, x, y, fm, lm)
                with m._timed_dispatch(tel, h_step):
                    m.params, m.updater_state, m.state, m._sentinel, loss = \
                        step_fn(*args)
                m._score = loss
                m.iteration += 1
                for cb in m._listeners:
                    cb.iteration_done(m, m.iteration, m.epoch)
            m.epoch += 1
            for cb in m._listeners:
                cb.on_epoch_end(m)
        return m

    def _pad_granularity(self) -> int:
        """Rows the per-host batch must divide into: this host's extent of
        the DATA axis (batches shard over 'data' only — the model axis
        replicates them, so padding to ``devices.size`` on a 2-D mesh
        over-padded) times ``accum_steps`` for the microbatch split."""
        data_size = self.mesh.shape.get("data", self.mesh.devices.size)
        return max(1, data_size // jax.process_count()) * self.accum_steps

    def _passthrough_batch(self, t, n: int):
        """Pre-placed device batches (AsyncDataSetIterator
        ``device_prefetch`` with a multi-host/global sharding) bypass the
        host-side pad path — a non-addressable global array can neither be
        np.asarray'd nor padded here. Their batch dim must already divide
        the GLOBAL data extent."""
        arrs = t if isinstance(t, tuple) else (t,)
        g = n * jax.process_count()
        for a in arrs:
            if a is not None and a.shape[0] % g:
                raise ValueError(
                    f"pre-placed device batch of {a.shape[0]} rows does not "
                    f"divide the global data extent {g}; size (or pre-pad) "
                    "device-prefetched batches to a multiple — host-side "
                    "pad-and-mask only applies to numpy batches")
        return t

    def _batches(self, data):
        """Yield (x, y, fm, lm) step arguments — arrays for the sequential
        engine, tuples-of-arrays for the graph engine — ragged tails padded
        to the data-axis extent and masked. Multi-host: batches are
        HOST-LOCAL shards (see launcher.HostShardedIterator), so the pad
        granularity is the per-host share of the data axis, keeping every
        host's shard equal-sized. With ``accum_steps=k`` the granularity
        multiplies by ``k`` so the microbatch split stays equal-sized.
        Already-global jax.Arrays (multi-host device prefetch) pass
        through untouched."""
        n = self._pad_granularity()

        def is_device_batch(a):
            first = a[0] if isinstance(a, tuple) else a
            return isinstance(first, jax.Array) and \
                not first.is_fully_addressable

        if self._is_graph:
            from ..nn.graph import _as_multi_iterator
            for mds in _as_multi_iterator(data):
                if any(is_device_batch(a) for a in mds.features
                       if a is not None):
                    yield (self._passthrough_batch(tuple(mds.features), n),
                           self._passthrough_batch(tuple(mds.labels), n),
                           tuple(mds.features_masks), tuple(mds.labels_masks))
                    continue
                fs = [np.asarray(a) for a in mds.features]
                ls = [np.asarray(a) for a in mds.labels]
                fms = [None if a is None else np.asarray(a)
                       for a in mds.features_masks]
                lms = [None if a is None else np.asarray(a)
                       for a in mds.labels_masks]
                rem = fs[0].shape[0] % n
                if rem:
                    fs, ls, fms, lms = _pad_and_mask_multi(
                        fs, ls, fms, lms, n - rem)
                yield (tuple(fs), tuple(ls), tuple(fms), tuple(lms))
        else:
            it: DataSetIterator = _as_iterator(data)
            for ds in it:
                if is_device_batch(ds.features):
                    yield (self._passthrough_batch(ds.features, n),
                           self._passthrough_batch(ds.labels, n),
                           ds.features_mask, ds.labels_mask)
                    continue
                x = np.asarray(ds.features)
                y = np.asarray(ds.labels)
                fm = None if ds.features_mask is None else np.asarray(ds.features_mask)
                lm = None if ds.labels_mask is None else np.asarray(ds.labels_mask)
                rem = x.shape[0] % n
                if rem:
                    x, y, fm, lm = _pad_and_mask(x, y, fm, lm, n - rem)
                yield (x, y, fm, lm)


def _synth_pad_feature_mask(x, pad):
    """Pad feature mask so mask-aware layers (train-mode BatchNorm moments)
    exclude the padded rows: per-timestep [B,T] for sequence inputs,
    per-example [B] otherwise. ``x`` is already zero-padded by ``pad``."""
    fm = np.ones(x.shape[:2] if x.ndim == 3 else (x.shape[0],), np.float32)
    if pad:  # fm[-0:] would zero the ENTIRE mask
        fm[-pad:] = 0.0
    return fm


def _pad_and_mask(x, y, fm, lm, pad):
    """Zero-pad `pad` examples onto the batch and mask them out of the loss.

    The label mask is the loss-weighting channel (losses average over the
    unmasked count, see ops/losses._per_example), so padded rows contribute
    zero loss and zero gradient.
    """
    def zpad(a):
        widths = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, widths)

    x, y = zpad(x), zpad(y)
    if fm is not None:
        fm = zpad(fm)  # padded rows have all-zero feature mask
    else:
        fm = _synth_pad_feature_mask(x, pad)
    if lm is not None:
        lm = zpad(lm)  # padded rows masked (zeros)
    else:
        # synthesize a per-example pad mask; the loss INTERSECTS it with any
        # network-propagated mask (ops/losses.combine_masks), so real
        # sequences' masked timesteps stay excluded too
        lm = np.ones((y.shape[0],), dtype=np.float32)
        lm[-pad:] = 0.0
    return x, y, fm, lm


def _pad_and_mask_multi(fs, ls, fms, lms, pad):
    """Multi-input/-output variant of :func:`_pad_and_mask` for the graph
    engine: every feature/label array is zero-padded; label masks are padded
    or (when no mask exists anywhere) synthesized per output slot."""
    def zpad(a):
        return np.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))

    fs = [zpad(a) for a in fs]
    ls = [zpad(a) for a in ls]
    fms = [zpad(m) if m is not None else _synth_pad_feature_mask(x, pad)
           for x, m in zip(fs, fms)]
    out_lms = []
    for y, m in zip(ls, lms):
        if m is not None:
            out_lms.append(zpad(m))
        else:
            # per-example pad mask; intersected with any propagated mask by
            # the loss (ops/losses.combine_masks)
            lm = np.ones((y.shape[0],), dtype=np.float32)
            lm[-pad:] = 0.0
            out_lms.append(lm)
    return fs, ls, fms, out_lms
