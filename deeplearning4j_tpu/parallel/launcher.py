"""Multi-host control plane: jax.distributed launcher + per-host data sharding.

TPU-native equivalent of the reference's multi-node orchestration layer
(reference: ``dl4j-spark-parameterserver .../SharedTrainingMaster.java``,
``nd4j .../parameterserver/distributed/v2/**`` — MeshOrganizer tree, Aeron
UDP transport, heartbeats — per SURVEY.md §2.8/§3.4; reference mount was
empty, citations upstream-relative, unverified).

The entire transport/mesh/codec stack collapses into the JAX control plane
(SURVEY.md §2.8 "TPU-native equivalent"): ``jax.distributed.initialize``
brings up the coordination service (the MeshOrganizer/heartbeat analog —
PJRT's distributed runtime does membership, barriers and health checks), and
the hot gradient path is XLA AllReduce over ICI/DCN emitted by GSPMD — no
parameter server, no gradient gossip. What this module keeps from the
reference's contract: every host runs the same program on the same step,
updates are deterministic, and each host reads its own shard of the data
(Spark's per-executor RDD partitions → :class:`HostShardedIterator`).

Typical pod usage (same script on every host)::

    from deeplearning4j_tpu.parallel import launcher
    launcher.initialize()                      # env-driven on TPU pods
    mesh = launcher.global_mesh()              # all devices, all hosts
    it = launcher.HostShardedIterator(base_iterator)
    ParallelWrapper(net, mesh).fit(it, epochs=...)
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np

from ..data.dataset import DataSet, DataSetIterator

_initialized = False


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None) -> None:
    """Bring up the multi-host JAX runtime (idempotent).

    On TPU pods all arguments are auto-detected from the metadata/env by
    ``jax.distributed.initialize``; pass them explicitly for CPU/GPU
    clusters or simulated multi-host tests. Single-process callers may call
    this unconditionally: with no coordinator configured anywhere it is a
    no-op, so the same training script runs 1-host and N-host unchanged.
    """
    global _initialized
    if _initialized:
        return
    import jax

    if (coordinator_address is None and num_processes is None
            and "JAX_COORDINATOR_ADDRESS" not in os.environ
            and "COORDINATOR_ADDRESS" not in os.environ
            and not _on_tpu_pod()):
        return  # single-process: nothing to initialize
    from jax._src import xla_bridge as _xb
    if _xb.backends_are_initialized():
        # a backend client predates us (e.g. an eager sitecustomize import);
        # distributed init must come first, so tear the client down. Any
        # jax.Array created before this point is invalidated — call
        # initialize() at program start, before building models.
        _xb._clear_backends()
        jax.clear_caches()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)
    _initialized = True


def _on_tpu_pod() -> bool:
    """True when TPU pod env vars indicate a MULTI-host slice (single-host
    TPU VMs also set TPU_WORKER_HOSTNAMES — with one entry)."""
    if "MEGASCALE_COORDINATOR_ADDRESS" in os.environ:
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()]) > 1


def shutdown() -> None:
    global _initialized
    if _initialized:
        import jax
        jax.distributed.shutdown()
        _initialized = False


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def is_multi_host() -> bool:
    return process_count() > 1


def global_mesh(axis: str = "data", devices: Optional[Sequence] = None):
    """Mesh over ALL devices of ALL hosts (the pod-wide data axis)."""
    from .data_parallel import make_mesh

    return make_mesh(devices, axis)


def make_global_array(local_data, mesh, spec):
    """Assemble a global jax.Array from this host's shard of the data.

    ``spec=P('data')`` treats ``local_data`` as this host's contiguous slice
    of the global batch (global batch = per-host batch x process_count);
    ``spec=P()`` treats it as a fully-replicated value (must be identical on
    every host). Single-host this degrades to a plain device_put.
    """
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    arr = np.asarray(local_data)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(sharding, arr)


class HostShardedIterator(DataSetIterator):
    """Each host sees its contiguous 1/N slice of every global batch.

    The multi-host analog of Spark's per-executor partitions: the base
    iterator is assumed identical on every host (same seed → same shuffle
    permutation, guaranteed by NumpyDataSetIterator's (seed, epoch) perms),
    and host ``p`` takes rows ``[p*k, (p+1)*k)`` of each batch. Combined with
    :func:`make_global_array` / ParallelWrapper, the slices re-assemble into
    the global batch in host order. The restorable cursor delegates to the
    base, so checkpoint/resume works unchanged.
    """

    def __init__(self, base: DataSetIterator,
                 process_id: Optional[int] = None,
                 num_processes: Optional[int] = None):
        self._base = base
        self._pid = process_index() if process_id is None else process_id
        self._n = process_count() if num_processes is None else num_processes

    def batch_size(self) -> int:
        return max(1, self._base.batch_size() // self._n)

    def reset(self):
        self._base.reset()

    def state(self) -> dict:
        return self._base.state()

    def set_state(self, state: dict):
        self._base.set_state(state)

    def _slice(self, a, lo, hi):
        return None if a is None else a[lo:hi]

    def __iter__(self):
        for ds in self._base:
            b = ds.num_examples()
            # pad the global batch to a per-host-equal size; the extra rows
            # land on the tail hosts and are masked out of the loss
            k = (b + self._n - 1) // self._n
            ragged = k * self._n != b
            lo, hi = min(self._pid * k, b), min((self._pid + 1) * k, b)
            feats = ds.features[lo:hi]
            labels = self._slice(ds.labels, lo, hi)
            fm = self._slice(ds.features_mask, lo, hi)
            lm = self._slice(ds.labels_mask, lo, hi)
            short = k - feats.shape[0]
            if short:
                def zpad(a):
                    if a is None:
                        return None
                    return np.pad(a, [(0, short)] + [(0, 0)] * (a.ndim - 1))
                feats, labels, fm, lm = (zpad(feats), zpad(labels),
                                         zpad(fm), zpad(lm))
            if ragged and lm is None:
                # EVERY host must synthesize the mask, not just the short
                # ones: hosts are SPMD — if some passed lm=None and others an
                # array, the per-host programs (and their collectives) would
                # diverge and the step would hang at the first AllReduce
                lm = np.ones((k,), dtype=np.float32)
                if short:
                    lm[-short:] = 0.0
            yield self._pp(DataSet(feats, labels, fm, lm))
