"""Multi-host control plane: jax.distributed launcher + per-host data sharding.

TPU-native equivalent of the reference's multi-node orchestration layer
(reference: ``dl4j-spark-parameterserver .../SharedTrainingMaster.java``,
``nd4j .../parameterserver/distributed/v2/**`` — MeshOrganizer tree, Aeron
UDP transport, heartbeats — per SURVEY.md §2.8/§3.4; reference mount was
empty, citations upstream-relative, unverified).

The entire transport/mesh/codec stack collapses into the JAX control plane
(SURVEY.md §2.8 "TPU-native equivalent"): ``jax.distributed.initialize``
brings up the coordination service (the MeshOrganizer/heartbeat analog —
PJRT's distributed runtime does membership, barriers and health checks), and
the hot gradient path is XLA AllReduce over ICI/DCN emitted by GSPMD — no
parameter server, no gradient gossip. What this module keeps from the
reference's contract: every host runs the same program on the same step,
updates are deterministic, and each host reads its own shard of the data
(Spark's per-executor RDD partitions → :class:`HostShardedIterator`).

Typical pod usage (same script on every host)::

    from deeplearning4j_tpu.parallel import launcher
    launcher.initialize()                      # env-driven on TPU pods
    mesh = launcher.pod_mesh(model=4)          # DCN-aware data x model
    it = launcher.HostShardedIterator(base_iterator)
    ParallelWrapper(net, mesh, model_axis="model",
                    shard_update=True, overlap_grads=True).fit(it, ...)
"""

from __future__ import annotations

import inspect
import logging
import os
import socket
import time
from typing import Optional, Sequence

import numpy as np

from ..data.dataset import DataSet, DataSetIterator
from ..runtime import telemetry as _tel

log = logging.getLogger("deeplearning4j_tpu")

_initialized = False
_init_kwargs: Optional[dict] = None

#: bounded coordinator-connect budget (seconds) — an unreachable
#: coordinator must be a clear, *transient-classified* error, never a hang
#: (ISSUE 10 satellite); override per deploy with this env var
TIMEOUT_ENV = "DL4J_TPU_COORDINATOR_TIMEOUT_S"
DEFAULT_TIMEOUT_S = 60.0


def _coordinator_timeout() -> float:
    try:
        return float(os.environ.get(TIMEOUT_ENV, DEFAULT_TIMEOUT_S))
    except ValueError:
        return DEFAULT_TIMEOUT_S


def _check_coordinator_reachable(address: str, timeout: float) -> None:
    """Bounded TCP pre-check of the coordinator address for NON-zero
    processes (process 0 *hosts* the coordinator — it has nothing to
    connect to before ``jax.distributed.initialize`` binds it). Raises
    ``ConnectionError`` — transient in the fault taxonomy
    (``runtime.faults.is_transient``), so a supervisor/retry loop treats a
    not-yet-up or dead coordinator as retryable instead of fatal."""
    host, _, port = address.rpartition(":")
    try:
        port_no = int(port)
    except ValueError:
        # a malformed address must still surface as the documented
        # transient ConnectionError (supervisor retry contract), not a
        # bare int() ValueError
        raise ConnectionError(
            f"JAX coordinator address {address!r} has no usable port "
            "(expected host:port)")
    deadline = time.monotonic() + timeout
    last: Optional[BaseException] = None
    while time.monotonic() < deadline:
        try:
            with socket.create_connection(
                    (host or "127.0.0.1", port_no),
                    timeout=min(2.0, max(0.1, deadline - time.monotonic()))):
                return
        except OSError as e:
            last = e
            time.sleep(min(0.25, max(0.0, deadline - time.monotonic())))
    raise ConnectionError(
        f"JAX coordinator at {address!r} unreachable after {timeout:.1f}s "
        f"(last error: {last}); is process 0 up, and is the address "
        f"routable from this host?")


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               local_device_ids: Optional[Sequence[int]] = None,
               timeout: Optional[float] = None) -> None:
    """Bring up the multi-host JAX runtime (idempotent).

    On TPU pods all arguments are auto-detected from the metadata/env by
    ``jax.distributed.initialize``; pass them explicitly for CPU/GPU
    clusters or simulated multi-host tests. Single-process callers may call
    this unconditionally: with no coordinator configured anywhere it is a
    no-op, so the same training script runs 1-host and N-host unchanged.

    Hardening (ISSUE 10): a configured-but-unreachable coordinator raises
    a clear ``ConnectionError`` within ``timeout`` seconds (default
    ``DL4J_TPU_COORDINATOR_TIMEOUT_S`` or 60) instead of hanging — the
    error is *transient* in the fault taxonomy so supervisors retry it.
    On CPU platforms the ``gloo`` cross-process collective implementation
    is selected automatically (without it jax 0.4.x silently builds a
    single-process client and ``process_count()`` stays 1 — the simulated
    pod the tests and bench use would quietly not be a pod).
    """
    global _initialized, _init_kwargs
    if _initialized:
        return
    import jax

    env_addr = os.environ.get("JAX_COORDINATOR_ADDRESS") \
        or os.environ.get("COORDINATOR_ADDRESS")
    if (coordinator_address is None and num_processes is None
            and env_addr is None and not _on_tpu_pod()):
        return  # single-process: nothing to initialize
    timeout = _coordinator_timeout() if timeout is None else float(timeout)
    addr = coordinator_address or env_addr
    env_pid = os.environ.get("JAX_PROCESS_ID") or os.environ.get("PROCESS_ID")
    pid = process_id if process_id is not None else (
        int(env_pid) if env_pid and env_pid.isdigit() else None)
    if addr and pid not in (None, 0):
        # process 0 hosts the coordinator service itself; everyone else
        # gets the bounded pre-check so a dead coordinator is an error,
        # not a silent initialization hang
        _check_coordinator_reachable(addr, timeout)
    # multi-process CPU collectives need gloo (jax 0.4.x): without it the
    # CPU client silently comes up single-process. Set UNCONDITIONALLY —
    # the flag only affects the CPU backend (TPU pods ignore it), and
    # gating on an explicit platform pin would leave the silent failure
    # in place for CPU clusters running on jax's default platform. No
    # jax.devices()/default_backend() probe here: those would instantiate
    # the very backend client distributed init must precede.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # flag absent on this jax version
        pass
    from jax._src import xla_bridge as _xb
    if _xb.backends_are_initialized():
        # a backend client predates us (e.g. an eager sitecustomize import);
        # distributed init must come first, so tear the client down. Any
        # jax.Array created before this point is invalidated — call
        # initialize() at program start, before building models.
        _clear_backends()
    kw = dict(coordinator_address=coordinator_address,
              num_processes=num_processes,
              process_id=process_id,
              local_device_ids=local_device_ids)
    sig = inspect.signature(jax.distributed.initialize).parameters
    if "initialization_timeout" in sig:
        kw["initialization_timeout"] = max(1, int(timeout))
    jax.distributed.initialize(**kw)
    _initialized = True
    _init_kwargs = kw
    if num_processes is not None and jax.process_count() != num_processes:
        # the pod "formed" but the backend client is not distributed
        # (e.g. a collectives-implementation gap on this backend): without
        # this check the job trains WRONG silently — host-sharded
        # iterators stop sharding, pod meshes collapse to one host
        raise RuntimeError(
            f"distributed init completed but jax.process_count() == "
            f"{jax.process_count()}, expected {num_processes}: the "
            "backend client did not attach to the coordination service "
            "(on CPU this usually means no cross-process collectives "
            "implementation is available)")
    _tel.set_host(jax.process_index(), jax.process_count())


def _clear_backends() -> None:
    """Tear down every live backend client AND the lru-cached process
    topology views. ``xla_bridge.process_count``/``process_index`` are
    ``@lru_cache``'d — if anything touched them before ``jax.distributed``
    came up (importing this package is enough: telemetry probes a device),
    the cached single-process answer SURVIVES ``_clear_backends`` and the
    whole pod trains while believing ``process_count() == 1`` (host-sharded
    iterators stop sharding, pod meshes collapse — observed, not
    hypothetical). Clearing the caches with the clients keeps the topology
    view and the backend in lockstep."""
    import jax
    from jax._src import xla_bridge as _xb
    _xb._clear_backends()
    jax.clear_caches()
    for fn in (getattr(_xb, "process_count", None),
               getattr(_xb, "process_index", None),
               getattr(_xb, "process_indices", None)):
        if fn is not None and hasattr(fn, "cache_clear"):
            fn.cache_clear()


def _on_tpu_pod() -> bool:
    """True when TPU pod env vars indicate a MULTI-host slice (single-host
    TPU VMs also set TPU_WORKER_HOSTNAMES — with one entry)."""
    if "MEGASCALE_COORDINATOR_ADDRESS" in os.environ:
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h.strip()]) > 1


def shutdown() -> None:
    global _initialized
    if _initialized:
        import jax
        jax.distributed.shutdown()
        _initialized = False
        _tel.set_host(0, 1)


def reinitialize() -> bool:
    """Whole-host-loss recovery hook (fault site ``parallel.host_loss``):
    tear the distributed runtime down and bring it back up with the same
    arguments — every surviving process runs this at the same recovery
    point (SPMD: the injected/real loss surfaces on all of them), the
    backend client is rebuilt, and the coordination barrier inside
    ``jax.distributed.initialize`` re-forms the pod. All live jax.Arrays
    die with the old client, so the caller (``run_resilient_fit``) MUST
    restore model state from a checkpoint afterwards. Returns True when a
    distributed runtime was actually cycled (False = single-process no-op:
    arrays stay live, restore alone suffices)."""
    global _initialized
    if not _initialized or _init_kwargs is None:
        return False
    import jax
    try:
        jax.distributed.shutdown()
    except Exception as e:  # a dead partner can fail the clean shutdown
        log.warning("reinitialize: shutdown failed (%s: %s); proceeding "
                    "to re-init", type(e).__name__, e)
    _initialized = False
    _clear_backends()
    jax.distributed.initialize(**_init_kwargs)
    _initialized = True
    _tel.set_host(jax.process_index(), jax.process_count())
    log.warning("reinitialize: pod re-formed (process %d/%d)",
                jax.process_index(), jax.process_count())
    return True


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def is_multi_host() -> bool:
    return process_count() > 1


def global_mesh(axis: str = "data", devices: Optional[Sequence] = None):
    """Mesh over ALL devices of ALL hosts (the pod-wide data axis)."""
    from .data_parallel import make_mesh

    return make_mesh(devices, axis)


def _group_by_host(devices, hosts: Optional[int] = None):
    """``[[host0 devices...], [host1 devices...], ...]`` in process order,
    each inner list in local (ICI-adjacent) order. ``hosts=`` overrides
    the process grouping with equal contiguous blocks — the single-process
    simulation knob (virtual hosts on one process's virtual devices)."""
    if hosts is not None and hosts >= 1:
        if len(devices) % hosts:
            raise ValueError(f"{len(devices)} devices do not split into "
                             f"{hosts} equal virtual hosts")
        per = len(devices) // hosts
        return [list(devices[h * per:(h + 1) * per]) for h in range(hosts)]
    by_host: dict = {}
    for d in devices:
        by_host.setdefault(getattr(d, "process_index", 0), []).append(d)
    return [by_host[p] for p in sorted(by_host)]


def pod_mesh(model: int = 1, devices: Optional[Sequence] = None,
             hosts: Optional[int] = None, model_span: str = "host"):
    """2-D DCN-aware ``('data', 'model')`` multi-host mesh (ISSUE 10).

    Placement rule: the **model** (tensor-parallel) axis is laid over
    consecutive devices *within one host* — those are ICI-adjacent, and
    the per-layer TP collectives that ride the model axis every
    microsecond must never cross the slow DCN hop — while the **data**
    axis runs host-major across the pod (host h occupies the contiguous
    block ``[h*local, (h+1)*local)`` of the data axis). XLA's collective
    decomposition then splits the data-axis gradient collectives into an
    intra-host ICI stage and a cross-host DCN stage (the mesh ordering is
    what makes that decomposition legal — a data axis that interleaved
    hosts would force every hop onto DCN); ``parallel/overlap.py`` makes
    the same hierarchy explicit per gradient bucket.

    ``model`` must divide every host's local device count (a model axis
    spilling across hosts would put layer collectives on DCN — rejected,
    not silently accepted). ``model=1`` returns a 1-axis ``('data',)``
    mesh. ``hosts=`` carves one process's devices into that many virtual
    hosts (simulation/testing; on a real pod leave it None — process
    membership decides). Works unchanged through ``ParallelWrapper``:
    batch shards over ``'data'``, ``model_axis="model"`` composes, and
    ``shard_update``/``overlap_grads`` ride the data axis.

    ``model_span="pod"`` (ISSUE 17) lifts the one-host restriction: the
    model axis is laid host-major over the whole pod, so a model whose
    shards cannot fit one host's HBM still serves as a SINGLE sharded
    replica. The per-layer TP collectives then ride DCN — the documented
    tradeoff for pod serving, where "exists at all" beats "ICI-fast" and
    decode steps are latency-tolerant relative to a training step.
    ``model`` must divide the total device count; requires
    ``model_span`` in ``("host", "pod")``.
    """
    import jax
    from jax.sharding import Mesh

    if model_span not in ("host", "pod"):
        raise ValueError(
            f"model_span={model_span!r} not in ('host', 'pod')")
    devs = list(devices) if devices is not None else jax.devices()
    groups = _group_by_host(devs, hosts)
    locals_ = {len(g) for g in groups}
    if len(locals_) != 1:
        raise ValueError(
            f"ragged pod: per-host device counts differ "
            f"({sorted(len(g) for g in groups)}); a mesh needs equal hosts")
    local = locals_.pop()
    if model_span == "pod":
        total = len(groups) * local
        if model < 1 or total % model:
            raise ValueError(
                f"model={model} must divide the pod device count {total} "
                "when model_span='pod'")
        flat = [d for g in groups for d in g]
        data = total // model
        arr = np.empty((data, model), dtype=object)
        for row in range(data):
            arr[row, :] = flat[row * model:(row + 1) * model]
        if model == 1:
            return Mesh(arr[:, 0], ("data",))
        return Mesh(arr, ("data", "model"))
    if model < 1 or local % model:
        raise ValueError(
            f"model={model} must divide the per-host device count {local}: "
            "the model axis must stay inside one host (ICI-adjacent) — "
            "tensor-parallel collectives on the DCN hop would dominate the "
            "step (serve a too-big-for-one-host model with "
            "model_span='pod')")
    data = len(groups) * (local // model)
    arr = np.empty((data, model), dtype=object)
    row = 0
    for g in groups:
        for i in range(local // model):
            arr[row, :] = g[i * model:(i + 1) * model]
            row += 1
    if model == 1:
        return Mesh(arr[:, 0], ("data",))
    return Mesh(arr, ("data", "model"))


def make_global_array(local_data, mesh, spec):
    """Assemble a global jax.Array from this host's shard of the data.

    ``spec=P('data')`` treats ``local_data`` as this host's contiguous slice
    of the global batch (global batch = per-host batch x process_count);
    ``spec=P()`` treats it as a fully-replicated value (must be identical on
    every host). Single-host this degrades to a plain device_put.
    """
    import jax
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    arr = np.asarray(local_data)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    return jax.make_array_from_process_local_data(sharding, arr)


class HostShardedIterator(DataSetIterator):
    """Each host sees its contiguous 1/N slice of every global batch.

    The multi-host analog of Spark's per-executor partitions: the base
    iterator is assumed identical on every host (same seed → same shuffle
    permutation, guaranteed by NumpyDataSetIterator's (seed, epoch) perms),
    and host ``p`` takes rows ``[p*k, (p+1)*k)`` of each batch. Combined with
    :func:`make_global_array` / ParallelWrapper, the slices re-assemble into
    the global batch in host order. The restorable cursor delegates to the
    base, so checkpoint/resume works unchanged.
    """

    def __init__(self, base: DataSetIterator,
                 process_id: Optional[int] = None,
                 num_processes: Optional[int] = None):
        self._base = base
        self._pid = process_index() if process_id is None else process_id
        self._n = process_count() if num_processes is None else num_processes

    def batch_size(self) -> int:
        return max(1, self._base.batch_size() // self._n)

    def reset(self):
        self._base.reset()

    def state(self) -> dict:
        return self._base.state()

    def set_state(self, state: dict):
        self._base.set_state(state)

    def _slice(self, a, lo, hi):
        return None if a is None else a[lo:hi]

    def __iter__(self):
        from .data_parallel import _synth_pad_feature_mask
        for ds in self._base:
            b = ds.num_examples()
            # pad the global batch to a per-host-equal size; the extra rows
            # land on the tail hosts and are masked out of the loss
            k = (b + self._n - 1) // self._n
            ragged = k * self._n != b
            lo, hi = min(self._pid * k, b), min((self._pid + 1) * k, b)
            feats = ds.features[lo:hi]
            labels = self._slice(ds.labels, lo, hi)
            fm = self._slice(ds.features_mask, lo, hi)
            lm = self._slice(ds.labels_mask, lo, hi)
            short = k - feats.shape[0]
            if short:
                def zpad(a):
                    if a is None:
                        return None
                    return np.pad(a, [(0, short)] + [(0, 0)] * (a.ndim - 1))
                feats, labels, fm, lm = (zpad(feats), zpad(labels),
                                         zpad(fm), zpad(lm))
            if ragged:
                # EVERY host must synthesize the masks, not just the short
                # ones: hosts are SPMD — if some passed None and others an
                # array, the per-host programs (and their collectives) would
                # diverge and the step would hang at the first AllReduce.
                if lm is None:
                    # zero LOSS weight on the zero-padded rows: losses
                    # average over the unmasked count (the r6 weighted-
                    # microbatch rule, ops/losses._per_example), so the
                    # global multi-host step divides by the REAL example
                    # count and stays bit-comparable to single-host
                    lm = np.ones((k,), dtype=np.float32)
                    if short:
                        lm[-short:] = 0.0
                if fm is None:
                    # pad FEATURE mask too (same rule as the wrapper's
                    # _pad_and_mask): mask-aware layers — train-mode
                    # BatchNorm batch moments — must exclude the padded
                    # rows, or multi-host running stats drift from the
                    # single-host run even though the loss matches
                    fm = _synth_pad_feature_mask(feats, short)
            yield self._pp(DataSet(feats, labels, fm, lm))
