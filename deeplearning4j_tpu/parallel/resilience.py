"""Resilience policy + auto-resume driver (ISSUE 5 tentpole, layers 1+3).

The host-side half of the fault-tolerance story (the device-side half is
``runtime/sentinel.py``, fused into every engine's compiled train step):

- :class:`ResiliencePolicy` — the knob bundle ``fit(..., resilience=policy)``
  takes on both nn engines and the ``ParallelWrapper``.
- :func:`run_resilient_fit` — wraps the epoch loop in a bounded
  retry-with-backoff. Transient runtime failures (device loss /
  preemption-shaped ``XlaRuntimeError`` / iterator I/O errors / injected
  crashes) restore model + updater + iterator state from the policy's
  crash-safe :class:`~.checkpoint.TrainingCheckpointer` and continue;
  divergence escalations (K consecutive sentinel-skipped steps, detected
  host-side at ``check_every`` cadence) roll back to the last GOOD
  checkpoint with an optional learning-rate backoff. Because the
  checkpoint captures params, updater state, BN state, the rng key, the
  iteration counter AND the data-iterator cursor, a resumed run is
  step-count-exact and bit-equivalent to an uninterrupted one on CPU
  (tested in tests/test_resilience.py).

This is the TensorFlow OSDI-2016 recovery contract (user-level
checkpointing + automatic re-execution on failure) expressed over our
engines; DL4J's closest analog is Spark-driver fault tolerance, which has
no single-process equivalent — divergence recorded in PARITY.md.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Optional

import jax.numpy as jnp

from ..runtime import faults as _faults
from ..runtime import sentinel  # noqa: F401  (re-export: policy API surface)
from ..runtime.faults import DivergenceError

log = logging.getLogger("deeplearning4j_tpu")


@dataclasses.dataclass
class ResiliencePolicy:
    """What to tolerate, and how hard to try.

    - ``max_consecutive_bad_steps``: escalate to rollback after K
      consecutive sentinel-skipped (non-finite) steps. 0 disables the
      escalation (the sentinel still skips and counts).
    - ``lr_backoff``: learning-rate multiplier applied at each divergence
      rollback (1.0 = off). Mutates the live updater config and retraces
      the step — a recovery-path cost, never a steady-state one.
    - ``max_restarts``: total restore-and-continue budget (crashes and
      divergence rollbacks combined); exceeding it re-raises.
    - ``backoff_seconds``: base of the exponential retry backoff
      (``backoff * 2**(restart-1)`` before each resume; 0 = immediate).
    - ``checkpointer``: a ``TrainingCheckpointer`` or a directory path
      (one is created with ``max_to_keep=3``); required — rollback needs
      somewhere to roll back to.
    - ``checkpoint_every_iterations``: mid-epoch checkpoint cadence (on
      top of the always-on epoch-boundary checkpoint). None = epoch
      boundaries only.
    - ``check_every``: how often (iterations) the driver reads the
      bad-step counter. The read is one step LAGGED (it syncs the
      previous iteration's counter while the current step is in flight),
      so even the default 1 does not stall the dispatch pipeline;
      escalation lands one iteration after the crossing, with a final
      synced check at each epoch boundary.
    """

    max_consecutive_bad_steps: int = 5
    lr_backoff: float = 1.0
    max_restarts: int = 3
    backoff_seconds: float = 0.0
    checkpointer: Any = None
    checkpoint_every_iterations: Optional[int] = None
    check_every: int = 1

    def resolve_checkpointer(self):
        from .checkpoint import TrainingCheckpointer
        if self.checkpointer is None:
            raise ValueError(
                "ResiliencePolicy needs a checkpointer (TrainingCheckpointer "
                "or directory path): auto-resume and divergence rollback "
                "restore from it")
        if isinstance(self.checkpointer, str):
            self.checkpointer = TrainingCheckpointer(self.checkpointer,
                                                     max_to_keep=3)
        return self.checkpointer


class _ResilienceListener:
    """Fit-loop hook: mid-epoch/epoch-boundary checkpoints + the
    divergence check. The check reads the PREVIOUS iteration's counter
    snapshot (sentinel arrays are immutable per-step values), so the
    host blocks only on an already-dispatched step — the in-flight step
    keeps pipelining and the zero-host-sync property of the fused
    sentinel survives the default ``check_every=1``. Escalation
    therefore lands one iteration after the threshold crossing; the
    epoch boundary does a final synced check so a streak ending exactly
    at the last step cannot escape. Checked BEFORE the checkpoint
    cadence so a diverging run never checkpoints its way past K."""

    def __init__(self, policy: ResiliencePolicy, ckpt, model, iterator):
        self.policy = policy
        self.ckpt = ckpt
        self.model = model
        self.iterator = iterator
        self._lagged = None  # previous step's bad_consec device scalar

    def _escalate(self, bad, iteration):
        if bad >= self.policy.max_consecutive_bad_steps:
            raise DivergenceError(
                f"{bad} consecutive non-finite steps by iteration "
                f"{iteration} (threshold "
                f"{self.policy.max_consecutive_bad_steps})")

    def iteration_done(self, model, iteration, epoch):
        p = self.policy
        if p.max_consecutive_bad_steps:
            prev, self._lagged = self._lagged, (
                self.model._sentinel["bad_consec"]
                if self.model._sentinel else None)
            if prev is not None and iteration % p.check_every == 0:
                self._escalate(int(prev), iteration)
        if p.checkpoint_every_iterations and \
                iteration % p.checkpoint_every_iterations == 0:
            from ..runtime import telemetry as _tel
            t0 = time.perf_counter()
            self.ckpt.save(self.model, iterator=self.iterator)
            # the step-loop-visible checkpoint cost (the enqueue side of
            # an async save; durable latency is checkpoint.save_latency_s)
            _h = _tel.histogram("train.phase.checkpoint_s")
            lbl = getattr(self.model, "telemetry_label", None)
            host = _tel.host_labels()  # pod anti-blending (ISSUE 10)
            if lbl is not None:
                _h.observe(time.perf_counter() - t0, model=lbl, **host)
            else:
                _h.observe(time.perf_counter() - t0, **host)

    def on_epoch_end(self, model):
        if self.policy.max_consecutive_bad_steps:
            self._escalate(
                self.model.resilience_counters()["bad_consec"],
                self.model.iteration)
        self.ckpt.save(self.model, iterator=self.iterator)


def _scale_learning_rate(model, factor: float) -> Optional[float]:
    """Divergence LR backoff: scale the live updater's scalar learning
    rate and invalidate the compiled step (the LR is baked into the
    trace). Schedule-valued learning rates are left alone (scaling a
    schedule object is not well-defined) — returns the new LR or None."""
    upd = getattr(model.conf, "updater", None)
    lr = getattr(upd, "learning_rate", None)
    if upd is None or not isinstance(lr, (int, float)):
        log.warning("lr_backoff skipped: updater has no scalar learning "
                    "rate (schedule or solver path)")
        return None
    upd.learning_rate = float(lr) * factor
    model._invalidate_compiled(cause="lr_backoff")
    return upd.learning_rate


def run_resilient_fit(fit_target, data, labels=None, epochs: int = 1,
                      policy: Optional[ResiliencePolicy] = None):
    """The auto-resume epoch-loop wrapper behind ``fit(...,
    resilience=policy)``. ``fit_target`` is a MultiLayerNetwork /
    ComputationGraph, or a ParallelWrapper (whose inner model carries the
    state). Every recovery action is counted (faults telemetry: no silent
    fallbacks) and bounded by ``policy.max_restarts``."""
    policy = policy or ResiliencePolicy()
    ckpt = policy.resolve_checkpointer()
    model = getattr(fit_target, "model", fit_target)  # wrapper -> engine

    # normalize the data to ONE stateful iterator whose cursor the
    # checkpointer captures; the engines accept it directly
    from ..nn.graph import ComputationGraph, _as_multi_iterator
    from ..nn.model import _as_iterator
    if isinstance(model, ComputationGraph):
        it = _as_multi_iterator(data, labels)
    else:
        it = _as_iterator(data, labels)

    if not model.params and not model.state:
        model.init()
    target_epoch = model.epoch + int(epochs)
    latest = ckpt.latest_step()
    if latest is None:
        # a base to roll back to even if the FIRST step diverges/crashes
        ckpt.save(model, iterator=it, wait=True)
    elif model.iteration == 0:
        # JOB-RESTART CONTINUATION: the directory holds a previous run's
        # checkpoints and this model is fresh — restoring stale state on
        # the first transient failure would silently discard this run, so
        # resume the previous run NOW instead (the preempted-job restart
        # semantics auto-resume exists for). A fresh run needs a fresh
        # checkpoint directory.
        step = ckpt.restore(model, iterator=it)
        log.warning(
            "resilient fit: checkpoint directory %s already holds a run — "
            "resumed it at step %s (epoch %d, iteration %d); use a fresh "
            "directory to start over", ckpt.directory, step, model.epoch,
            model.iteration)
    elif int(model.iteration) not in set(ckpt._mngr.all_steps()):
        # mid-lineage entry (model trained/restored outside the driver):
        # checkpoint the CURRENT state so rollback never leaves this run
        ckpt.save(model, iterator=it, wait=True)

    listener = _ResilienceListener(policy, ckpt, model, it)
    model.add_listener(listener)
    restarts = 0
    try:
        while model.epoch < target_epoch:
            try:
                fit_target.fit(it, epochs=1)
            except DivergenceError as e:
                restarts += 1
                if restarts > policy.max_restarts:
                    raise
                log.warning("divergence escalation (%s); rolling back to "
                            "last good checkpoint (restart %d/%d)",
                            e, restarts, policy.max_restarts)
                step = ckpt.restore(model, iterator=it)
                listener._lagged = None  # pre-rollback snapshot is stale
                if policy.lr_backoff != 1.0:
                    new_lr = _scale_learning_rate(model, policy.lr_backoff)
                    if new_lr is not None:
                        log.warning("learning rate backed off to %g", new_lr)
                        if fit_target is not model:
                            fit_target._step = None  # wrapper's own trace
                # a restored bad_consec must not instantly re-escalate
                model._sentinel = dict(model._ensure_sentinel(),
                                       bad_consec=jnp.zeros((), jnp.int32))
                _faults.telemetry_bump("divergence_rollbacks")
                _sleep(policy, restarts)
                log.warning("rolled back to checkpoint step %s", step)
            except Exception as e:
                if not _faults.is_transient(e):
                    raise
                restarts += 1
                if restarts > policy.max_restarts:
                    raise
                log.warning("transient failure (%s: %s); restoring and "
                            "resuming (restart %d/%d)", type(e).__name__, e,
                            restarts, policy.max_restarts)
                if isinstance(e, _faults.HostLoss):
                    # whole-host loss (ISSUE 10): the pod's control plane
                    # is gone, not just this step — rebuild it BEFORE the
                    # restore. reinitialize() cycles jax.distributed (a
                    # barrier: every surviving process re-joins here) and
                    # invalidates all live arrays; on_host_loss() re-derives
                    # the wrapper's mesh over the fresh devices and drops
                    # the compiled step. Single-process runs skip the cycle
                    # (False) — restore alone suffices. The checkpoint
                    # restore right below then rebuilds model state, so the
                    # resumed run is bit-equal to an uninterrupted one.
                    from . import launcher as _launcher
                    ckpt.quiesce()  # drain saves BEFORE the client dies
                    cycled = _launcher.reinitialize()
                    if cycled:
                        # orbax captured the old coordination client's
                        # barrier fn at manager construction — rebuild it
                        ckpt.reopen()
                        if hasattr(fit_target, "on_host_loss"):
                            fit_target.on_host_loss()
                    _faults.telemetry_bump("host_loss_recoveries")
                step = ckpt.restore(model, iterator=it)
                listener._lagged = None  # pre-crash snapshot is stale
                _faults.telemetry_bump("auto_resumes")
                _sleep(policy, restarts)
                log.warning("resumed from checkpoint step %s", step)
    finally:
        if listener in model._listeners:
            model._listeners.remove(listener)
        ckpt.wait_until_finished()
    return fit_target


def _sleep(policy: ResiliencePolicy, restart: int):
    if policy.backoff_seconds:
        time.sleep(policy.backoff_seconds * (2 ** (restart - 1)))
