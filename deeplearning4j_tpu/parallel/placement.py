"""Mesh-aware params/KV placement layer (ISSUE 17 tentpole, layer 1).

Training already knows how to lay a params tree over a pod mesh
(``ParallelWrapper``'s GSPMD specs, r7/r15); the serving engines each
re-derived a private slice of that machinery — identity-cached
``device_put`` walks, a placement fingerprint keyed into the bucket
cache, and the quantized-params source. This module is the shared
substrate both sides ride:

- **TP spec derivation** (:func:`tp_param_spec`): the dense-family rule
  extracted from ``ParallelWrapper._param_spec`` (``W [in, out]`` shards
  its out-dim over the model axis, ``b [out]`` follows), extended for
  serving with the attention family — ``Wq/Wk/Wv [f, H*hs]`` column-
  shard so each device owns whole heads (no cross-shard reduction in the
  projection), ``Wo [H*hs, out]`` row-shards (one psum per layer),
  biases follow their sharded dim. Attention params shard only when the
  layer's head count divides the model-axis size; everything else
  replicates — replication is always correct, sharding is the
  optimization.
- **QuantizedTensor awareness**: a pytree-registered int8 leaf places as
  one unit — ``q`` gets the weight spec; the f32 ``scale [channels]``
  (always the out-channel axis, the r14 cast rule keeps it f32) shards
  with the model axis exactly when the weight spec put the model axis on
  the quantized axis, else replicates.
- **KV head sharding** (:func:`cache_sharding_tree`): contiguous decode
  caches ``[S, H, C, d]`` and paged pool payloads ``[n_pages*P, H, d]``
  split their head axis ``H/k`` per device. The page-row axis must NOT
  shard over data — the host-side int32 page table indexes arbitrary
  rows, so every device needs every row of its head slice. int8 KV
  scale leaves (``[.., H, .., 1]``) carry the same head axis and shard
  identically.
- **The multi-host put contract** (:func:`put_full`): host full values
  become global arrays via ``jax.make_array_from_callback`` (every host
  holds the full value and donates the shards it owns — the same
  contract as ``ParallelWrapper._build``'s ``put``, where confusing
  full-value with host-shard placement once doubled an Adam slot).
- **Identity-cached placement + fingerprint**
  (:class:`ParamsPlacement`): the engines' per-placement compiled-key
  machinery, extracted — place once per params identity, fingerprint the
  leaf shardings so AOT executables are keyed to the placement they were
  lowered for.

``QuantizedParamsMixin`` (the serving engines' quantized-params source,
previously private to ``serving/engine.py``) lives here too so the
placement walk and the quantize walk stay one layer.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, Optional, Set, Tuple

import numpy as np

import jax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P
from jax.tree_util import tree_map_with_path

from ..ops import quantize as _q
from ..runtime import faults as _faults
from ..runtime import telemetry as _tel

log = logging.getLogger("deeplearning4j_tpu")

# int8 post-training quantization (ISSUE 9) telemetry, declared beside
# the quantized-params source below; every cell binds engine= (the
# per-instance anti-blending rule) and dies with its engine through
# :func:`release_cells`
_G_Q_SITES = _tel.gauge("serving.quantize.sites",
                        "weights quantized to int8 in the serving params")
_G_Q_WBYTES = _tel.gauge("serving.quantize.weight_bytes",
                         "serving params bytes after quantization")
_G_Q_SAVED = _tel.gauge("serving.quantize.bytes_saved",
                        "params bytes saved by int8 quantization")
_M_Q_REQUANT = _tel.counter(
    "serving.quantize.requantizations",
    "weight requantizations after a params update (no recompile: the "
    "quantized avals are identical)")
_M_Q_FALLBACK = _tel.counter(
    "serving.quantize.fallbacks",
    "quantize requests served f32 instead (env pin or quantization "
    "failure — the engine degrades, it does not die)")

#: leaf names of the attention projection family and the axis the model
#: axis lands on when the layer's heads divide it: column-sharded
#: in-projections (each device computes its own heads' q/k/v — no
#: collective), row-sharded out-projection (one psum closes the layer)
_ATTN_COL = ("Wq", "Wk", "Wv")
_ATTN_COL_B = ("bq", "bk", "bv")
_ATTN_ROW = ("Wo",)


def path_names(path) -> Tuple[str, ...]:
    """Stringified pytree path (DictKey/SequenceKey/FlattenedIndexKey all
    carry ``.key``) — the name tuple every spec function matches on."""
    return tuple(str(getattr(k, "key", k)) for k in path)


def dense_tp_keys(model) -> Set[str]:
    """Top-level param keys (layer index / vertex name) whose layer is in
    the dense family — extracted from ``ParallelWrapper._dense_keys``.
    Matching on the leaf name 'W' alone would also catch embedding tables
    and LSTM/GRU input kernels, whose per-step collectives hurt TP."""
    from ..nn.layers.core import DenseLayer, LossLayer, OutputLayer
    dense = (DenseLayer, OutputLayer, LossLayer)
    keys: Set[str] = set()
    for key, lyr in _iter_layers(model):
        if isinstance(lyr, dense):
            keys.add(key)
    return keys


def attention_tp_heads(model) -> Dict[str, int]:
    """Top-level param key -> ``n_heads`` for every attention layer — the
    serving-side extension of the dense family. Per-layer head counts
    decide per-layer shardability (``n_heads % k == 0``), and the KV
    cache for a layer shards its head axis exactly when the layer's
    projections do, so activations and cache stay aligned."""
    heads: Dict[str, int] = {}
    for key, lyr in _iter_layers(model):
        n = getattr(lyr, "n_heads", None)
        if isinstance(n, int) and n >= 1 and hasattr(lyr, "decode_cache_spec"):
            heads[key] = n
    return heads


def _iter_layers(model):
    """(top-level param key, layer) pairs for MLN and graph models."""
    from ..nn.vertices import LayerVertex
    if getattr(model, "_is_graph", None) or hasattr(model.conf, "vertices"):
        verts = getattr(model.conf, "vertices", None)
        if verts is not None:
            for name, v, _ in verts:
                if isinstance(v, LayerVertex):
                    yield str(name), v.layer
            return
    for i, lyr in enumerate(model.layers):
        yield str(i), lyr


def tp_param_spec(names: Tuple[str, ...], leaf, model_axis: Optional[str],
                  tp: int, dense_keys: Set[str],
                  attn_heads: Optional[Dict[str, int]] = None) -> P:
    """PartitionSpec for one parameter leaf under tensor parallelism.

    ``attn_heads=None`` reproduces ``ParallelWrapper._param_spec``
    exactly (dense family only — the training contract); a head map adds
    the serving-side attention rules. A ``QuantizedTensor`` leaf is
    specced by its int8 payload's geometry (see
    :func:`quantized_shardings` for the scale rule)."""
    if model_axis is None or tp <= 1:
        return P()
    if isinstance(leaf, _q.QuantizedTensor):
        leaf = leaf.q
    if not names:
        return P()
    top, name = str(names[0]), str(names[-1])
    ndim = getattr(leaf, "ndim", 0)
    if top in dense_keys:
        if name == "W" and ndim == 2:
            return P(None, model_axis)      # dense kernel: shard out-dim
        if name == "b" and ndim == 1:
            return P(model_axis)
        return P()
    if attn_heads and top in attn_heads and attn_heads[top] % tp == 0:
        if name in _ATTN_COL and ndim == 2:
            return P(None, model_axis)      # each device owns whole heads
        if name in _ATTN_COL_B and ndim == 1:
            return P(model_axis)
        if name in _ATTN_ROW and ndim == 2:
            return P(model_axis, None)      # out-proj row shard: one psum
    return P()


def quantized_shardings(qt, wspec: P, mesh, model_axis: Optional[str]):
    """(q, scale) NamedShardings for one ``QuantizedTensor`` leaf. The
    scale vector ``[channels]`` runs along the quantized axis (always the
    OUT channel axis, ``ndim - 1``); it shards over the model axis iff
    the weight spec put the model axis there, else replicates (e.g. a
    row-sharded ``Wo`` is quantized along its replicated out-dim)."""
    ndim = getattr(qt.q, "ndim", 0)
    wtuple = tuple(wspec) + (None,) * (ndim - len(tuple(wspec)))
    on_q_axis = ndim and qt.axis == ndim - 1 and \
        wtuple[qt.axis] == model_axis and model_axis is not None
    sspec = P(model_axis) if on_q_axis else P()
    return (NamedSharding(mesh, wspec), NamedSharding(mesh, sspec))


def sharding_tree(mesh, tree, spec_fn: Callable[[Tuple[str, ...], object], P]):
    """NamedSharding tree matching ``tree`` (QuantizedTensor leaves place
    as one unit: a QT of shardings, same pytree structure)."""
    def leaf(path, a):
        names = path_names(path)
        spec = spec_fn(names, a)
        if isinstance(a, _q.QuantizedTensor):
            qsh, ssh = quantized_shardings(
                a, spec, mesh, _spec_axis(spec))
            return _q.QuantizedTensor(qsh, ssh, a.axis)
        return NamedSharding(mesh, spec)
    return tree_map_with_path(
        leaf, tree, is_leaf=lambda x: isinstance(x, _q.QuantizedTensor))


def _spec_axis(spec: P) -> Optional[str]:
    for ax in tuple(spec):
        if ax is not None:
            return ax if isinstance(ax, str) else ax[0]
    return None


def cache_sharding_tree(mesh, tree, model_axis: str, tp: int,
                        head_axis: int = 1):
    """NamedSharding tree for a KV-cache aval/spec tree: the head axis
    (axis 1 for both contiguous ``[S, H, C, d]`` buckets and paged
    ``[n_pages*P, H, d]`` pool payloads, scales included) splits ``H/k``
    per device when divisible, else that leaf replicates. Axis 0 (slot
    row / page row) stays unsharded: the host page table indexes
    arbitrary page rows, so a data-axis split would orphan rows."""
    def leaf(a):
        shp = getattr(a, "shape", ())
        if len(shp) > head_axis and tp > 1 and shp[head_axis] % tp == 0:
            spec = [None] * len(shp)
            spec[head_axis] = model_axis
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())
    return jax.tree.map(leaf, tree)


def put_full(value, sharding):
    """Place one host FULL VALUE (or an already-global array) under
    ``sharding``. Multi-host, a host value must go through
    ``make_array_from_callback`` — every process holds the full value and
    contributes the shards it owns (the full-value contract from
    ``ParallelWrapper._build``; the host-shard variant
    ``make_array_from_process_local_data`` is for batches, and confusing
    the two once turned a (6,16) Adam slot into (6,32)). Arrays already
    carrying the target sharding pass through untouched."""
    if isinstance(value, jax.Array):
        if value.sharding == sharding:
            return value
        if not value.is_fully_addressable and not value.is_fully_replicated:
            # cross-placement reshard of a distributed array: let the
            # runtime route it (jax>=0.4.35 device_put reshards)
            return jax.device_put(value, sharding)
        value = np.asarray(value)
    if jax.process_count() > 1:
        arr = np.asarray(value)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])
    return jax.device_put(value, sharding)


def put_tree(tree, shardings, keep_on_mesh: bool = False, mesh=None):
    """Per-leaf :func:`put_full` over matching pytrees. With
    ``keep_on_mesh``, leaves already carrying a NamedSharding on ``mesh``
    keep their placement (the pre-TP serving semantic: a tensor-parallel
    leaf left behind by training must not be gathered — that can OOM the
    exact models TP exists for)."""
    def leaf(t, sh):
        if keep_on_mesh and isinstance(t, jax.Array) and \
                isinstance(getattr(t, "sharding", None), NamedSharding) and \
                t.sharding.mesh == mesh:
            return t
        return put_full(t, sh)
    return jax.tree.map(leaf, tree, shardings)


def placement_fingerprint(*trees) -> str:
    """Order-insensitive digest of every leaf's sharding — the engines'
    compiled-key component that keys AOT executables to the placement
    they were lowered for. ``"host"`` when any leaf is undevice'd."""
    shs = []
    for t in trees:
        shs += [getattr(x, "sharding", None) for x in jax.tree.leaves(t)]
    if any(s is None for s in shs):
        return "host"
    return "|".join(sorted(set(str(s) for s in shs)))


def mesh_key(mesh) -> str:
    """The r18 schedule-key mesh component: device-grid shape as
    ``"2x4"`` — a report measured on one topology never seeds another."""
    return "x".join(str(s) for s in mesh.devices.shape)


def mesh_suffix(mesh, model_axis: Optional[str] = None) -> str:
    """Attribution-cache key suffix for a mesh-placed serving program:
    mesh shape + model-axis (TP) size, so a TP decode step's cached cost
    fractions never blend with single-device ones (r18 rule)."""
    tp = int(mesh.shape[model_axis]) \
        if model_axis and model_axis in mesh.axis_names else 1
    return f"mesh={mesh_key(mesh)}:tp{tp}"


def release_cells(engine_id: str) -> int:
    """Drop every telemetry cell bound to one engine id (engines register
    this through ``weakref.finalize`` so per-engine cells die with the
    engine)."""
    return _tel.registry.discard_cells(engine=engine_id)


def tree_bytes_per_device(tree, shardings) -> int:
    """PER-DEVICE bytes of a placed (or to-be-placed) tree: each leaf's
    bytes divided by the product of the mesh-axis sizes its spec shards
    over. This is the number ``memory_report`` / ``max_batch`` must
    account under TP — the full-tree bytes over-report a sharded model's
    per-device footprint by the TP factor (the satellite bugfix)."""
    total = 0
    for leaf, sh in zip(jax.tree.leaves(tree), jax.tree.leaves(shardings)):
        nbytes = int(np.prod(getattr(leaf, "shape", ()) or (1,))) * \
            np.dtype(leaf.dtype).itemsize
        denom = 1
        if isinstance(sh, NamedSharding):
            for ax in tuple(sh.spec):
                if ax is None:
                    continue
                for a in (ax if isinstance(ax, tuple) else (ax,)):
                    denom *= int(sh.mesh.shape[a])
        total += -(-nbytes // denom)
    return total


def load_checkpoint(model, directory: str):
    """Restore ``model`` in place from a pod ``TrainingCheckpointer``
    directory (gather-on-save makes the layout topology-independent, so
    a serving host restores host-side full values regardless of the
    training topology). The engines' ``warmup(checkpoint=...)`` rides
    this: restore, then the placement walk loads each host's addressable
    shards onto the serving mesh. Returns the restored step (None on an
    empty directory — the model keeps its initialized params)."""
    from .checkpoint import TrainingCheckpointer
    ck = TrainingCheckpointer(directory)
    try:
        return ck.restore(model)
    finally:
        ck.close()


class QuantizedParamsMixin:
    """Quantize-on-warmup params source shared by the serving engines
    (ISSUE 9; extracted here with the placement machinery — ISSUE 17).
    ``quantize="int8"`` makes :meth:`_serving_params` hand the
    executables a per-channel int8 params tree instead of the model's
    f32 one — quantized ONCE per params identity (warmup pays it; a
    ``fit()`` rebinding the params requantizes host-side with identical
    avals, so zero post-warmup compiles survive the transform). The
    ``DL4J_TPU_QUANT=off`` env pin and any quantization failure (fault
    site ``serving.quantize``) degrade to f32 serving, sticky + counted
    — a quantizer bug must not flap executable shapes or kill serving."""

    def _init_quantize(self, quantize: Optional[str]):
        if quantize not in (None, "int8"):
            raise ValueError(f"unknown quantize mode {quantize!r} "
                             "(expected None or 'int8')")
        self.quantize = quantize
        self._qparams = None
        self._qparams_src = None
        self._q_report = None
        self._q_disabled: Optional[str] = None   # sticky fallback reason

    def _quantize_active(self) -> bool:
        return self.quantize is not None and self._q_disabled is None

    def _serving_params(self):
        """The params tree the executables are compiled over and fed:
        the model's own tree, or its quantized twin (identity-cached on
        ``model.params`` — ``fit()`` rebinds the dict, so the cache
        tracks updates exactly like ``_place_params``)."""
        if self.quantize is None or self._q_disabled is not None:
            return self.model.params
        src = self.model.params
        if self._qparams_src is src:
            return self._qparams
        if _q.mode() == "off" and self._qparams is None:
            # CI kill switch, evaluated BEFORE anything compiled: serve
            # f32, counted, sticky (a pin is a process constant — no
            # shape flapping). Once an engine HAS warmed quantized, the
            # executables' avals are int8+scale, so a later mode flip
            # does not stop requantization — handing them f32 params
            # would be a signature mismatch, and serving stale weights
            # after a fit() would be silently wrong; use
            # set_quantize(None) + re-warm to actually leave int8.
            self._q_disabled = "env_off"
            self._m_q_fallback.inc()
            log.warning("DL4J_TPU_QUANT=off: engine quantize=%r request "
                        "serves f32", self.quantize)
            return self.model.params
        try:
            if _faults.enabled():
                _faults.trip("serving.quantize")
            qparams, report = _q.quantize_model_params(self.model)
        except Exception as e:
            self._m_q_fallback.inc()
            if self._qparams is not None:
                # a REquantization failed after warmup: keep serving the
                # previous quantized tree (stale scales beat feeding f32
                # avals to executables compiled for int8). The failed
                # source is cached so a persistent failure does not
                # re-walk + re-warn on EVERY request — the next params
                # rebind (a new identity) retries
                log.warning("weight requantization failed (%s: %s); "
                            "serving the previous quantized params",
                            type(e).__name__, e)
                self._qparams_src = src
                return self._qparams
            # degrade, don't die: f32 serving with the failure counted;
            # sticky so the executable avals never flap mid-traffic
            self._q_disabled = "error"
            log.warning("weight quantization failed (%s: %s); serving "
                        "f32", type(e).__name__, e)
            return self.model.params
        if self._qparams_src is not None:
            self._m_q_requant.inc()   # params updated -> fresh scales
        self._qparams = qparams
        self._qparams_src = src
        self._q_report = report
        self._g_q_sites.set(report.sites)
        total, _qb = _q.quantized_bytes(qparams)
        self._g_q_wbytes.set(total)
        self._g_q_saved.set(report.bytes_saved)
        return qparams

    def _bind_quantize_cells(self):
        # pool= beside engine= (ISSUE 18): engines set _pool_label before
        # binding; non-serving hosts of the mixin fall back to "default"
        eid = self._id
        pool = getattr(self, "_pool_label", "default")
        self._m_q_requant = _M_Q_REQUANT.labeled(engine=eid, pool=pool)
        self._m_q_fallback = _M_Q_FALLBACK.labeled(engine=eid, pool=pool)
        self._g_q_sites = _G_Q_SITES.labeled(engine=eid, pool=pool)
        self._g_q_wbytes = _G_Q_WBYTES.labeled(engine=eid, pool=pool)
        self._g_q_saved = _G_Q_SAVED.labeled(engine=eid, pool=pool)

    def set_quantize(self, quantize: Optional[str]):
        """Flip the engine's quantization mode. Every warmed executable
        compiled the other params dtype, so the bucket cache is
        invalidated with cause ``quantize`` — the retrace tracker
        attributes the rebuilds instead of showing mystery
        ``new_bucket`` events. Re-warm before traffic."""
        if quantize not in (None, "int8"):
            raise ValueError(f"unknown quantize mode {quantize!r} "
                             "(expected None or 'int8')")
        self.quantize = quantize
        self._qparams = None
        self._qparams_src = None
        self._q_report = None
        self._q_disabled = None
        self.invalidate(cause="quantize")
        return self

    def _quantize_stats(self) -> dict:
        out = {"quantize": self.quantize or "off"}
        if self._q_disabled is not None:
            out["quantize_fallback"] = self._q_disabled
        if self._q_report is not None:
            out["quantized_sites"] = self._q_report.sites
            out["quantized_bytes_saved"] = self._q_report.bytes_saved
        return out


class ParamsPlacement:
    """One engine's (or wrapper's) placement policy over one mesh:
    derives the TP spec trees, places identity-cached params/state, and
    fingerprints placements for the compiled-key cache.

    ``model_axis`` activates tensor parallelism only when the mesh
    actually carries that axis with size > 1 — a data-only mesh degrades
    to the replicated placement the pre-TP engines used, bit-for-bit.
    """

    def __init__(self, mesh, model=None, model_axis: Optional[str] = "model",
                 data_axis: str = "data"):
        self.mesh = mesh
        self.data_axis = data_axis
        active = (mesh is not None and model_axis is not None
                  and model_axis in mesh.axis_names
                  and int(mesh.shape[model_axis]) > 1)
        self.model_axis = model_axis if active else None
        self.tp = int(mesh.shape[model_axis]) if active else 1
        self._dense = dense_tp_keys(model) if (active and model is not None) \
            else set()
        self._attn = attention_tp_heads(model) \
            if (active and model is not None) else {}
        self._placed_src: Optional[tuple] = None
        self._placed: Optional[tuple] = None

    # ------------------------------------------------------------- specs
    def param_spec(self, names: Tuple[str, ...], leaf) -> P:
        return tp_param_spec(names, leaf, self.model_axis, self.tp,
                             self._dense, self._attn)

    def param_shardings(self, params):
        return sharding_tree(self.mesh, params, self.param_spec)

    def state_shardings(self, state):
        repl = self.replicated()
        return jax.tree.map(lambda _: repl, state)

    def cache_shardings(self, cache_tree):
        """Head-sharded NamedSharding tree for a decode-cache or paged
        pool aval/spec tree (replicated when TP is inactive)."""
        if self.model_axis is None:
            repl = self.replicated()
            return jax.tree.map(lambda _: repl, cache_tree)
        return cache_sharding_tree(self.mesh, cache_tree,
                                   self.model_axis, self.tp)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    # --------------------------------------------------------- placement
    def place(self, params, state, src: Optional[tuple] = None,
              keep_on_mesh: bool = False):
        """(placed params, placed state), identity-cached on ``src``
        (default: the trees themselves). TP active forces the derived
        spec (AOT executables pin these exact in_shardings);
        ``keep_on_mesh`` preserves the pre-TP keep-what's-on-the-mesh
        semantic for replicated placements."""
        key = src if src is not None else (params, state)
        if self._placed_src is not None \
                and self._placed_src[0] is key[0] \
                and self._placed_src[1] is key[1]:
            return self._placed
        keep = keep_on_mesh and self.model_axis is None
        placed = (
            put_tree(params, self.param_shardings(params),
                     keep_on_mesh=keep, mesh=self.mesh),
            put_tree(state, self.state_shardings(state),
                     keep_on_mesh=keep, mesh=self.mesh),
        )
        self._placed_src, self._placed = key, placed
        return placed

    def invalidate(self):
        """Forget the cached placement (quantize toggles, new params)."""
        self._placed_src = self._placed = None

    def fingerprint(self, *trees) -> str:
        return placement_fingerprint(*trees)

    def suffix(self) -> str:
        return mesh_suffix(self.mesh, self.model_axis)
