"""Parallelism: data-parallel training, multi-host launcher, sharded
checkpoints (SURVEY.md §2.6/§2.8)."""

from .data_parallel import ParallelWrapper, make_mesh  # noqa: F401
from .launcher import (HostShardedIterator, global_mesh, initialize,  # noqa: F401
                       is_multi_host, make_global_array, process_count,
                       process_index, shutdown)
from .checkpoint import TrainingCheckpointer  # noqa: F401
from .resilience import ResiliencePolicy, run_resilient_fit  # noqa: F401
