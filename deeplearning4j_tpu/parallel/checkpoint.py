"""Preemption-safe sharded training checkpoints (orbax-backed).

TPU-native equivalent of the reference's checkpoint/resume story
(reference: ``ModelSerializer`` + ``CheckpointListener`` rotation per
SURVEY.md §5 "Checkpoint / resume"; reference mount was empty, citations
upstream-relative, unverified) — upgraded where SURVEY.md §5 flags the gap:
the reference never captures data-iterator position, so resume replays or
skips data. Here a checkpoint is {params, updater state, layer state, RNG
key, counters, **iterator cursor**}: restore continues the exact example
sequence (tested bit-exact in tests/test_checkpoint.py).

Storage is `orbax.checkpoint` — on a pod each host writes only the shards
it owns (OCDBT), which is the multi-host analog of the reference's
single-file ZIP; the single-host interchange ZIP (``utils/serializer.py``)
remains the portable format. Rotation (`max_to_keep`) mirrors
CheckpointListener's keepLast semantics.
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _config_equivalent(stored_json, live_json) -> bool:
    """Architecture equality modulo the init seed: every checkpointed array
    overwrites the fresh init, so a restarted job may build with a different
    seed, but any structural difference means the weights don't belong to
    this model."""
    import json

    if stored_json is None:
        return True  # pre-config-check checkpoint (format v1 early saves)
    a, b = json.loads(stored_json), json.loads(live_json)
    a.pop("seed", None)
    b.pop("seed", None)
    return a == b


class TrainingCheckpointer:
    """Rotating, resumable training checkpoints for both engines.

    Usage::

        ckpt = TrainingCheckpointer(dir, max_to_keep=3)
        ...
        ckpt.save(net, iterator=it)               # inside the train loop
        ...
        step = ckpt.restore(net, iterator=it)     # after restart; None if none
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))

    # -- save ---------------------------------------------------------------
    def save(self, model, iterator=None, step: Optional[int] = None,
             wait: bool = False) -> int:
        """Checkpoint the full training state at ``step`` (default: the
        model's iteration counter). Saves are async on the orbax side;
        ``wait=True`` blocks until durable (use before deliberate exit)."""
        ocp = self._ocp
        step = int(model.iteration if step is None else step)
        tree = {"params": model.params,
                "rng_key": jax.random.key_data(model._key)
                if jnp.issubdtype(model._key.dtype, jax.dtypes.prng_key)
                else model._key}
        # orbax rejects empty pytree nodes; BN-less models have state == {}
        # and un-stepped models have updater_state == {} — save only what is
        if model.state:
            tree["state"] = model.state
        if model.updater_state:
            tree["updater"] = model.updater_state
        # GATHER-ON-SAVE: leaves sharded across devices (TP params, ZeRO-1
        # updater state under ParallelWrapper(shard_update=True)) are pulled
        # to host numpy when fully addressable, so the stored checkpoint is
        # topology-independent — it restores bit-exactly onto any device
        # count and either shard_update setting (re-sharding happens lazily
        # on the wrapper's next step). Multi-host leaves are NOT fully
        # addressable and stay as global arrays for orbax's OCDBT
        # shard-per-host writes; the restore-side reshard covers them.
        def _gather(x):
            if (isinstance(x, jax.Array)
                    and not x.sharding.is_fully_replicated
                    and x.is_fully_addressable):
                return np.asarray(x)
            return x
        tree = jax.tree.map(_gather, tree)
        if jax.process_count() > 1:
            # multi-host: globally-sharded leaves (params trained through
            # ParallelWrapper) serialize as-is, but host-local single-device
            # arrays (the RNG key, any state never touched by the sharded
            # step) cannot — orbax refuses them. They are replicated by
            # construction (same value computed on every host), so hand
            # them over as numpy, which orbax writes from the primary host.
            def _localize(x):
                if isinstance(x, jax.Array) and len(x.sharding.device_set) == 1:
                    return np.asarray(x)
                return x
            tree = jax.tree.map(_localize, tree)
        meta = {"iteration": int(model.iteration), "epoch": int(model.epoch),
                "model_class": type(model).__name__,
                "configuration": model.conf.to_json(),
                "iterator": dict(iterator.state()) if iterator is not None
                else None,
                "format": "deeplearning4j_tpu.parallel.checkpoint",
                "version": 1}
        self._mngr.save(step, args=ocp.args.Composite(
            tree=ocp.args.PyTreeSave(tree),
            meta=ocp.args.JsonSave(meta)))
        if wait:
            self._mngr.wait_until_finished()
        return step

    # -- restore ------------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, model, iterator=None,
                step: Optional[int] = None) -> Optional[int]:
        """Restore model (+ iterator cursor) in place from ``step`` (default
        latest). Returns the restored step, or None when no checkpoint
        exists (first launch) — callers can use that as the cold-start
        signal. The model must be built from the same configuration; this is
        asserted against the stored config JSON."""
        ocp = self._ocp
        if step is None:
            step = self._mngr.latest_step()
        if step is None:
            return None
        try:
            restored = self._mngr.restore(step, args=ocp.args.Composite(
                tree=ocp.args.PyTreeRestore(),
                meta=ocp.args.JsonRestore()))
        except Exception as e:
            # topology change (e.g. a host died and the survivors restore
            # on fewer devices — the §5 failure-recovery path): the saved
            # shardings name devices that no longer exist. The exception
            # TYPE and wording vary across orbax versions (ValueError,
            # KeyError, orbax-internal types — ADVICE r5), so catch broadly
            # with no message sniffing:
            # instead, attempt the numpy fallback and re-raise the ORIGINAL
            # error if it also fails — a corrupt checkpoint fails both ways
            # and surfaces its real cause, while a genuine topology change
            # recovers. Re-reading every leaf as host numpy is safe:
            # jnp.asarray below re-places on the current topology's default
            # device and ParallelWrapper re-shards on the next step.
            try:
                tree_meta = self._mngr.item_metadata(step)["tree"]
                restore_args = jax.tree.map(
                    lambda _: ocp.RestoreArgs(restore_type=np.ndarray),
                    tree_meta)
                restored = self._mngr.restore(step, args=ocp.args.Composite(
                    tree=ocp.args.PyTreeRestore(restore_args=restore_args),
                    meta=ocp.args.JsonRestore()))
            except Exception:
                raise e  # surface the ORIGINAL failure, not the fallback's
        tree, meta = restored["tree"], restored["meta"]
        if meta["model_class"] != type(model).__name__:
            raise ValueError(
                f"checkpoint holds a {meta['model_class']}, restoring into "
                f"a {type(model).__name__}")
        if not _config_equivalent(meta.get("configuration"),
                                  model.conf.to_json()):
            raise ValueError(
                "checkpoint configuration does not match the model being "
                "restored into — rebuild the model from the same config "
                "(the stored JSON is in meta['configuration'])")
        if not model.params:
            model.init()
        model.params = jax.tree.map(jnp.asarray, tree["params"])
        if "state" in tree:
            model.state = jax.tree.map(jnp.asarray, tree["state"])
        if "updater" in tree:
            model.updater_state = jax.tree.map(jnp.asarray, tree["updater"])
        key = np.asarray(tree["rng_key"])
        model._key = jax.random.wrap_key_data(key) \
            if jnp.issubdtype(model._key.dtype, jax.dtypes.prng_key) \
            else jnp.asarray(key)
        model.iteration = meta["iteration"]
        model.epoch = meta["epoch"]
        if iterator is not None and meta.get("iterator") is not None:
            iterator.set_state(meta["iterator"])
        return step

    def wait_until_finished(self):
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
