"""Preemption-safe sharded training checkpoints (orbax-backed).

TPU-native equivalent of the reference's checkpoint/resume story
(reference: ``ModelSerializer`` + ``CheckpointListener`` rotation per
SURVEY.md §5 "Checkpoint / resume"; reference mount was empty, citations
upstream-relative, unverified) — upgraded where SURVEY.md §5 flags the gap:
the reference never captures data-iterator position, so resume replays or
skips data. Here a checkpoint is {params, updater state, layer state, RNG
key, counters, **iterator cursor**}: restore continues the exact example
sequence (tested bit-exact in tests/test_checkpoint.py).

Storage is `orbax.checkpoint` — on a pod each host writes only the shards
it owns (OCDBT), which is the multi-host analog of the reference's
single-file ZIP; the single-host interchange ZIP (``utils/serializer.py``)
remains the portable format. Rotation (`max_to_keep`) mirrors
CheckpointListener's keepLast semantics.

Crash safety (ISSUE 5): every save is certified by an atomically-written
sha256 manifest (tmp + fsync + rename after the orbax commit); restore
checksum-verifies newest-first and falls back past torn writes to the
newest VERIFIED checkpoint, raising ``CorruptCheckpoint`` only when
nothing verifies. ``async_save=True`` snapshots device leaves with an
enqueued copy and commits on a background thread, so the step loop never
blocks on a save. Save latency / restore / fallback counts feed
``runtime.faults`` telemetry (PerformanceListener, ui.StatsListener).
"""

from __future__ import annotations

import hashlib
import itertools
import json
import logging
import os
import queue
import threading
import time
import weakref
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import faults as _faults
from ..runtime import telemetry as _tel
from ..runtime.faults import CorruptCheckpoint

log = logging.getLogger("deeplearning4j_tpu")

# durable-save / restore latency distributions (ISSUE 6): registry
# histograms so bench artifacts and `GET /metrics` see checkpoint cost;
# the per-instance `last_save_latency_s` / `save_latencies` attributes
# stay as the historical accessors
_H_SAVE = _tel.histogram("checkpoint.save_latency_s",
                         "save()->durable (manifest fsync'd) latency")
_H_RESTORE = _tel.histogram("checkpoint.restore_s",
                            "restore() wall time (verified walk included)")
#: cells are labeled ckpt=<id> per TrainingCheckpointer (two models
#: checkpointing in one process must not blend their latency p99s; a
#: weakref finalizer reclaims a churned instance's cells, same rule as
#: engine=/pi=/model= elsewhere)
_ckpt_ids = itertools.count()

#: Per-checkpoint checksum manifest (crash-safety layer, ISSUE 5): written
#: tmp + fsync + rename AFTER the checkpoint commit, so its presence+match
#: certifies the whole step directory. A checkpoint with no manifest is
#: "unverified" (pre-ISSUE-5 save or one whose writer died before the
#: manifest — restore accepts it only as a last resort); a MISMATCH is a
#: torn write and the checkpoint is skipped.
MANIFEST = "manifest.sha256.json"


def _primary_host() -> bool:
    """Process 0 is the single manifest writer on a pod (and the only
    process in a single-host run). A seam, so tests can simulate a
    non-primary host without confusing orbax's own process_index view."""
    import jax
    return jax.process_index() == 0


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _config_equivalent(stored_json, live_json) -> bool:
    """Architecture equality modulo the init seed: every checkpointed array
    overwrites the fresh init, so a restarted job may build with a different
    seed, but any structural difference means the weights don't belong to
    this model."""
    import json

    if stored_json is None:
        return True  # pre-config-check checkpoint (format v1 early saves)
    a, b = json.loads(stored_json), json.loads(live_json)
    for d in (a, b):
        d.pop("seed", None)
        # the resilience policy's LR backoff legitimately mutates the live
        # updater's learning rate between checkpoint and rollback-restore;
        # a changed LR is a hyperparameter, not a different architecture
        if isinstance(d.get("updater"), dict):
            d["updater"] = dict(d["updater"])
            d["updater"].pop("learning_rate", None)
    return a == b


class TrainingCheckpointer:
    """Rotating, resumable training checkpoints for both engines.

    Usage::

        ckpt = TrainingCheckpointer(dir, max_to_keep=3)
        ...
        ckpt.save(net, iterator=it)               # inside the train loop
        ...
        step = ckpt.restore(net, iterator=it)     # after restart; None if none
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 async_save: bool = False):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self._max_to_keep = max_to_keep
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True))
        # crash-safety state (ISSUE 5): ONE long-lived background worker
        # drains a finalize queue (wait-for-orbax-commit + checksum
        # manifest; for async_save, the whole host-gather + commit), so
        # saves never block the step loop and thread count stays bounded.
        # Concurrent _mngr.save (foreground) vs the worker's
        # wait_until_finished is safe: orbax's async manager serializes
        # commits internally (save() itself waits for the previous
        # commit), and restore() drains the queue before touching _mngr.
        self.async_save = bool(async_save)
        self._finalize_q: "queue.Queue" = queue.Queue()
        self._worker: Optional[threading.Thread] = None
        self._bg_errors: List[BaseException] = []
        self.restore_count = 0
        self.restore_fallbacks = 0
        self.last_save_latency_s: Optional[float] = None
        # rolling window (multi-week cadenced runs must not grow a list)
        from collections import deque
        self.save_latencies = deque(maxlen=512)
        self._id = str(next(_ckpt_ids))
        weakref.finalize(self, _tel.registry.discard_cells, ckpt=self._id)
        # host=<process_index> rides along on pods so a pod-level scrape/
        # merge can't blend per-host save latencies (ISSUE 10 satellite)
        self._h_save = _H_SAVE.labeled(ckpt=self._id, **_tel.host_labels())
        self._h_restore = _H_RESTORE.labeled(ckpt=self._id,
                                             **_tel.host_labels())

    # -- save ---------------------------------------------------------------
    def save(self, model, iterator=None, step: Optional[int] = None,
             wait: bool = False) -> int:
        """Checkpoint the full training state at ``step`` (default: the
        model's iteration counter). Saves are async on the orbax side;
        ``wait=True`` blocks until durable (use before deliberate exit)."""
        ocp = self._ocp
        step = int(model.iteration if step is None else step)
        tree = {"params": model.params,
                "rng_key": jax.random.key_data(model._key)
                if jnp.issubdtype(model._key.dtype, jax.dtypes.prng_key)
                else model._key}
        # orbax rejects empty pytree nodes; BN-less models have state == {}
        # and un-stepped models have updater_state == {} — save only what is
        if model.state:
            tree["state"] = model.state
        if model.updater_state:
            tree["updater"] = model.updater_state
        # GATHER-ON-SAVE: leaves sharded across devices (TP params, ZeRO-1
        # updater state under ParallelWrapper(shard_update=True)) are pulled
        # to host numpy when fully addressable, so the stored checkpoint is
        # topology-independent — it restores bit-exactly onto any device
        # count and either shard_update setting (re-sharding happens lazily
        # on the wrapper's next step). Multi-host leaves are NOT fully
        # addressable and stay as global arrays for orbax's OCDBT
        # shard-per-host writes; the restore-side reshard covers them.
        def _gather(x):
            if (isinstance(x, jax.Array)
                    and not x.sharding.is_fully_replicated
                    and x.is_fully_addressable):
                return np.asarray(x)
            return x
        tree = jax.tree.map(_gather, tree)
        if jax.process_count() > 1:
            # multi-host: globally-sharded leaves (params trained through
            # ParallelWrapper) serialize as-is, but host-local single-device
            # arrays (the RNG key, any state never touched by the sharded
            # step) cannot — orbax refuses them. They are replicated by
            # construction (same value computed on every host), so hand
            # them over as numpy, which orbax writes from the primary host.
            def _localize(x):
                if isinstance(x, jax.Array) and len(x.sharding.device_set) == 1:
                    return np.asarray(x)
                return x
            tree = jax.tree.map(_localize, tree)
        meta = {"iteration": int(model.iteration), "epoch": int(model.epoch),
                "model_class": type(model).__name__,
                "configuration": model.conf.to_json(),
                "iterator": dict(iterator.state()) if iterator is not None
                else None,
                # divergence-sentinel counters ride along so a resumed run
                # continues the exact telemetry series (and the bench's
                # recovery metric can diff them); filled below — the async
                # path must NOT sync them here (host int() would drain the
                # in-flight steps), so the device counters go into the
                # copied payload and convert on the background thread
                "resilience": None,
                "format": "deeplearning4j_tpu.parallel.checkpoint",
                "version": 2}
        sent = getattr(model, "_sentinel", None)
        has_counters = hasattr(model, "resilience_counters")
        t0 = time.perf_counter()
        if self.async_save and not wait:
            # ASYNC-SAVE MODE: never blocks the step loop AT ALL. The
            # device-side jnp.copy snapshots every leaf WITHOUT a host sync
            # (the copy is enqueued behind the in-flight step), so the fit
            # loop's buffer donation cannot invalidate what the background
            # writer reads; the host gather, orbax commit, and manifest all
            # happen on the finalize worker.
            tree = jax.tree.map(
                lambda a: jnp.copy(a) if isinstance(a, jax.Array) else a,
                tree)
            sent_copy = jax.tree.map(jnp.copy, sent) if sent else None

            def job():
                if has_counters:
                    from ..runtime import sentinel as _sent
                    meta["resilience"] = _sent.to_host(sent_copy)
                host = jax.tree.map(
                    lambda a: np.asarray(a)
                    if isinstance(a, jax.Array) else a, tree)
                committed = self._mngr.save(step, args=ocp.args.Composite(
                    tree=ocp.args.PyTreeSave(host),
                    meta=ocp.args.JsonSave(meta)))
                self._mngr.wait_until_finished()
                self._after_commit(step, t0, committed)

            self._enqueue_finalize(job)
            return step
        if has_counters:
            meta["resilience"] = model.resilience_counters()
        # orbax's save is async on its side (it snapshots to host before
        # returning), keeping the historical non-blocking wait=False
        # contract for in-train-loop callers
        committed = self._mngr.save(step, args=ocp.args.Composite(
            tree=ocp.args.PyTreeSave(tree),
            meta=ocp.args.JsonSave(meta)))
        if wait:
            self._mngr.wait_until_finished()
            self._after_commit(step, t0, committed)
            return step

        # the checksum manifest certifies a COMPLETE commit, so it must
        # wait for orbax — on the finalize worker, never in the step loop;
        # a following restore()/wait_until_finished() joins the queue
        def job():
            self._mngr.wait_until_finished()
            self._after_commit(step, t0, committed)

        self._enqueue_finalize(job)
        return step

    def _after_commit(self, step: int, t0: float, committed):
        """Post-commit gate: a save that orbax SKIPPED (``save()`` returns
        False when the step already exists — e.g. re-reaching the same
        iteration after a rollback) must NOT finalize, or the manifest
        would be rewritten from whatever bytes are on disk, re-certifying
        a possibly torn/stale checkpoint as verified."""
        if committed is False:
            log.warning(
                "checkpoint step %d already exists; orbax kept the existing "
                "bytes — manifest left untouched", step)
            return
        self._finalize_save(step, t0)

    def _enqueue_finalize(self, job):
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._worker_loop, daemon=True,
                name="TrainingCheckpointer-finalizer")
            self._worker.start()
        self._finalize_q.put(job)

    def _worker_loop(self):
        while True:
            job = self._finalize_q.get()
            try:
                job()
            except BaseException as e:  # surfaced by wait_until_finished
                self._bg_errors.append(e)
            finally:
                self._finalize_q.task_done()

    def _finalize_save(self, step: int, t0: float):
        """Post-commit finalize: write the checksum manifest (atomically:
        tmp file + fsync + rename + directory fsync), then record the
        durable-save latency. The ``checkpoint.write`` fault site sits
        AFTER the manifest so an injected torn write produces exactly what
        a real one does — on-disk bytes that no longer match the manifest
        — which ``restore()`` must detect and fall back from.

        Multi-host (ISSUE 10): every host commits its own addressable
        shards through orbax (whose finalize barrier has already passed by
        the time ``wait_until_finished`` returned here), but the manifest
        has exactly ONE writer — process 0 — hashing the complete step
        directory on the shared filesystem. N racing writers could
        interleave tmp-renames or certify a directory another host was
        still materializing; a single writer after the collective commit
        certifies the whole checkpoint or nothing."""
        primary = _primary_host()
        if primary:
            self._write_manifest(step)
        inj = (_faults.trip("checkpoint.write")
               if primary and _faults.enabled() else None)
        if inj is not None:
            self._tear(step)
        latency = time.perf_counter() - t0
        self.last_save_latency_s = latency
        self.save_latencies.append(latency)
        self._h_save.observe(latency)
        _faults.telemetry_bump("checkpoint_saves")
        _faults.telemetry_set("checkpoint_last_save_latency_s", latency)

    # -- manifest / verification --------------------------------------------
    def _step_dir(self, step: int) -> Optional[str]:
        """The on-disk directory of ``step`` (orbax names it ``<step>`` or
        ``<prefix>_<step>`` depending on options)."""
        if not os.path.isdir(self.directory):
            return None
        for name in os.listdir(self.directory):
            p = os.path.join(self.directory, name)
            if os.path.isdir(p) and (
                    name == str(step) or name.rsplit("_", 1)[-1] == str(step)):
                return p
        return None

    def _write_manifest(self, step: int):
        d = self._step_dir(step)
        if d is None:
            return
        try:
            files = {}
            for root, _, fs in os.walk(d):
                for f in fs:
                    if f == MANIFEST or f.endswith(".tmp"):
                        continue
                    p = os.path.join(root, f)
                    files[os.path.relpath(p, d)] = {
                        "sha256": _sha256(p), "bytes": os.path.getsize(p)}
            payload = json.dumps({"step": int(step), "files": files},
                                 sort_keys=True).encode()
            tmp = os.path.join(d, MANIFEST + ".tmp")
            with open(tmp, "wb") as fh:
                fh.write(payload)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, os.path.join(d, MANIFEST))
            dfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except FileNotFoundError:
            # the checkpoint was rotated away (max_to_keep) by a NEWER save
            # while this background finalize was still hashing it — a
            # deleted checkpoint needs no manifest
            log.info("checkpoint %d rotated away during manifest finalize",
                     step)

    def _tear(self, step: int):
        """Injected torn write: truncate the largest manifest-listed file
        to half its committed size (what an interrupted writer leaves)."""
        d = self._step_dir(step)
        if d is None:
            return  # rotated away before the injection could tear it
        mpath = os.path.join(d, MANIFEST)
        with open(mpath) as fh:
            files = json.load(fh)["files"]
        rel = max(files, key=lambda r: files[r]["bytes"])
        p = os.path.join(d, rel)
        with open(p, "r+b") as fh:
            fh.truncate(max(1, files[rel]["bytes"] // 2))
        log.warning("injected torn write: truncated %s in checkpoint %d",
                    rel, step)

    def verify(self, step: int) -> Optional[bool]:
        """Checksum-verify one checkpoint against its manifest. True =
        verified, False = CORRUPT (missing/short/mismatched file — a torn
        write), None = no manifest (pre-manifest checkpoint; unknown)."""
        d = self._step_dir(step)
        if d is None:
            return False
        mpath = os.path.join(d, MANIFEST)
        if not os.path.exists(mpath):
            return None
        try:
            with open(mpath) as fh:
                files = json.load(fh)["files"]
        except (ValueError, KeyError, OSError):
            return False  # torn manifest
        for rel, want in files.items():
            p = os.path.join(d, rel)
            if not os.path.exists(p) or \
                    os.path.getsize(p) != want["bytes"] or \
                    _sha256(p) != want["sha256"]:
                return False
        return True

    def verified_steps(self) -> List[int]:
        """Steps whose manifest verifies, newest first (None-manifest
        steps excluded)."""
        return [s for s in sorted(self._mngr.all_steps(), reverse=True)
                if self.verify(s) is True]

    def scan_steps(self) -> dict:
        """One watch-loop scan (the fleet's hot-swap seam, ISSUE 20):
        classify every on-disk step as ``verified`` (manifest checks
        out), ``torn`` (manifest mismatch — an interrupted writer; the
        fleet watch loop skips these loudly) or ``unverified`` (no
        manifest — pre-manifest checkpoint). Each list is newest first.
        Forces a directory re-read where orbax supports it, so a watcher
        polling a directory another PROCESS writes sees new steps."""
        try:
            steps = self._mngr.reload() or self._mngr.all_steps()
        except (AttributeError, TypeError):  # older orbax: no reload()
            try:
                steps = self._mngr.all_steps(read=True)
            except TypeError:
                steps = self._mngr.all_steps()
        out = {"verified": [], "torn": [], "unverified": []}
        for s in sorted(steps, reverse=True):
            v = self.verify(s)
            key = "verified" if v is True else (
                "torn" if v is False else "unverified")
            out[key].append(s)
        return out

    # -- restore ------------------------------------------------------------
    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, model, iterator=None,
                step: Optional[int] = None) -> Optional[int]:
        """Restore model (+ iterator cursor) in place from ``step`` (default
        latest). Returns the restored step, or None when no checkpoint
        exists (first launch) — callers can use that as the cold-start
        signal. The model must be built from the same configuration; this is
        asserted against the stored config JSON.

        Corruption handling (ISSUE 5): with ``step=None`` the candidate
        list is walked newest-first and every candidate is checksum-
        verified against its manifest; a torn write (mismatch) is skipped
        — counted in ``restore_fallbacks`` — and the newest VERIFIED
        checkpoint restores instead. Only if every checkpoint fails
        verification does this raise :class:`CorruptCheckpoint`. An
        explicitly requested ``step`` raises immediately when corrupt."""
        ocp = self._ocp
        t_restore0 = time.perf_counter()
        self.wait_until_finished()  # async saves must commit before we pick
        if step is None:
            steps = sorted(self._mngr.all_steps(), reverse=True)
            if not steps:
                return None
            # newest VERIFIED first; a manifest-less checkpoint (verify()
            # None — e.g. the writer died between the orbax commit and the
            # manifest, or a pre-manifest save) is accepted only when NO
            # verified checkpoint exists at all (last resort); a mismatch
            # (False) is a torn write and never restores. Lazy walk: the
            # common case (newest checkpoint intact) hashes exactly one
            # checkpoint, not all max_to_keep of them.
            step = first_unverified = None
            chosen_verdict = True
            for s in steps:
                v = self.verify(s)
                if v is True:
                    step = s
                    break
                if v is None and first_unverified is None:
                    first_unverified = s
            if step is None and first_unverified is not None:
                step, chosen_verdict = first_unverified, None
            if step is None:
                raise CorruptCheckpoint(
                    f"all {len(steps)} checkpoints in {self.directory} "
                    "failed manifest verification")
            skipped = steps.index(step)
            if skipped:
                log.warning(
                    "checkpoint(s) %s skipped (torn write or missing "
                    "manifest); falling back to step %d (verify=%s)",
                    steps[:skipped], step, chosen_verdict)
                self.restore_fallbacks += skipped
                _faults.telemetry_bump("restore_fallbacks", skipped)
        elif self._step_dir(step) is None:
            # plain not-found (never saved, or rotated away by max_to_keep)
            # — NOT a corruption signal; callers must not take disk-repair
            # recovery actions for a typo'd/rotated step
            raise ValueError(
                f"checkpoint step {step} not found in {self.directory} "
                f"(available: {sorted(self._mngr.all_steps())})")
        elif self.verify(step) is False:
            raise CorruptCheckpoint(
                f"checkpoint {step} in {self.directory} failed manifest "
                "verification (torn write)")
        try:
            restored = self._mngr.restore(step, args=ocp.args.Composite(
                tree=ocp.args.PyTreeRestore(),
                meta=ocp.args.JsonRestore()))
        except Exception as e:
            # topology change (e.g. a host died and the survivors restore
            # on fewer devices — the §5 failure-recovery path): the saved
            # shardings name devices that no longer exist. The exception
            # TYPE and wording vary across orbax versions (ValueError,
            # KeyError, orbax-internal types — ADVICE r5), so catch broadly
            # with no message sniffing:
            # instead, attempt the numpy fallback and re-raise the ORIGINAL
            # error if it also fails — a corrupt checkpoint fails both ways
            # and surfaces its real cause, while a genuine topology change
            # recovers. Re-reading every leaf as host numpy is safe:
            # jnp.asarray below re-places on the current topology's default
            # device and ParallelWrapper re-shards on the next step.
            try:
                tree_meta = self._mngr.item_metadata(step)["tree"]
                restore_args = jax.tree.map(
                    lambda _: ocp.RestoreArgs(restore_type=np.ndarray),
                    tree_meta)
                restored = self._mngr.restore(step, args=ocp.args.Composite(
                    tree=ocp.args.PyTreeRestore(restore_args=restore_args),
                    meta=ocp.args.JsonRestore()))
            except Exception:
                raise e  # surface the ORIGINAL failure, not the fallback's
        tree, meta = restored["tree"], restored["meta"]
        if meta["model_class"] != type(model).__name__:
            raise ValueError(
                f"checkpoint holds a {meta['model_class']}, restoring into "
                f"a {type(model).__name__}")
        if not _config_equivalent(meta.get("configuration"),
                                  model.conf.to_json()):
            raise ValueError(
                "checkpoint configuration does not match the model being "
                "restored into — rebuild the model from the same config "
                "(the stored JSON is in meta['configuration'])")
        if not model.params:
            model.init()
        model.params = jax.tree.map(jnp.asarray, tree["params"])
        if "state" in tree:
            model.state = jax.tree.map(jnp.asarray, tree["state"])
        if "updater" in tree:
            model.updater_state = jax.tree.map(jnp.asarray, tree["updater"])
        key = np.asarray(tree["rng_key"])
        model._key = jax.random.wrap_key_data(key) \
            if jnp.issubdtype(model._key.dtype, jax.dtypes.prng_key) \
            else jnp.asarray(key)
        model.iteration = meta["iteration"]
        model.epoch = meta["epoch"]
        if iterator is not None and meta.get("iterator") is not None:
            iterator.set_state(meta["iterator"])
        rc = meta.get("resilience")
        if rc is not None and hasattr(model, "resilience_counters"):
            # resume the sentinel counter series exactly (bit-equivalent
            # resume includes the telemetry)
            model._sentinel = {k: jnp.asarray(int(v), jnp.int32)
                               for k, v in rc.items()}
        self.restore_count += 1
        self._h_restore.observe(time.perf_counter() - t_restore0)
        _faults.telemetry_bump("restore_count")
        return step

    def wait_until_finished(self):
        """Block until every in-flight save (orbax commit AND background
        manifest finalize) is durable; re-raises the first background
        failure."""
        self._mngr.wait_until_finished()
        self._finalize_q.join()
        if self._bg_errors:
            raise self._bg_errors.pop(0)

    def quiesce(self) -> List[BaseException]:
        """Best-effort drain for RECOVERY paths (whole-host loss): wait
        for in-flight saves but SWALLOW background failures instead of
        raising — a lost host cancels orbax's cross-host commit barrier
        mid-save, which is expected collateral, and the recovery restore
        walks manifest-VERIFIED checkpoints regardless (a save whose
        barrier died never got a manifest, so it can't restore). Returns
        the swallowed exceptions for logging."""
        swallowed: List[BaseException] = []
        try:
            self._mngr.wait_until_finished()
        except Exception as e:
            swallowed.append(e)
        self._finalize_q.join()  # worker catches into _bg_errors
        swallowed.extend(self._bg_errors)
        self._bg_errors.clear()
        for e in swallowed:
            log.warning("checkpoint quiesce swallowed %s: %s",
                        type(e).__name__, e)
        return swallowed

    def reopen(self) -> None:
        """Rebuild the orbax manager in place — REQUIRED after
        ``launcher.reinitialize()``: orbax's async checkpointer captures
        the distributed coordination client's barrier function at
        construction, so a manager that outlives the client would sync
        every later save against a dead service (observed: CANCELLED
        WaitAtBarrierAsync). Pending saves are quiesced first; on-disk
        state is untouched."""
        ocp = self._ocp
        self.quiesce()
        try:
            self._mngr.close()
        except Exception as e:  # dead-client close is best-effort
            log.warning("checkpoint reopen: old manager close failed "
                        "(%s: %s)", type(e).__name__, e)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self._max_to_keep, create=True))

    def close(self):
        self.wait_until_finished()
        self._mngr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
