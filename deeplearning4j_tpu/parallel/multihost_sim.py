"""2-process CPU pod simulation: the measured half of ISSUE 10.

Real subprocesses joined by ``jax.distributed`` over loopback — each with
4 virtual CPU devices — stand in for TPU hosts (the pattern
``tests/test_multihost.py`` established; SURVEY.md §4's thread+loopback
fake, upgraded to real process isolation). One orchestrator
(:func:`run_simulation`) drives five worker phases and writes a
MULTICHIP-style artifact proving the acceptance criteria *by
measurement*:

- ``timing1`` / ``timing2``: ZeRO-1 + hierarchical-overlap training on
  the 1-host and 2-host pod mesh, warm per-step times + a zero
  post-warmup compile-event assertion → ``scaling_efficiency``.
- ``train``: the uninterrupted 2-host reference run under the resilient
  driver — produces the checkpoint directory (every host writes its
  addressable shards, process 0 the single sha256 manifest) and the
  truth params.
- ``hostloss``: the same run with ``parallel.host_loss`` injected
  mid-training on every process (SPMD: the pod loses a host, everyone
  sees it); ``run_resilient_fit`` cycles ``launcher.reinitialize()``,
  restores, resumes — final params must be BIT-equal to ``train``'s.
- ``restore1``: a single process (the 2→1 changed topology) restores
  ``train``'s multi-host checkpoint through the verified-manifest path
  and must match the truth bit-exactly, then trains on.

Workers re-enter this module via ``python -m`` (no textwrap scripts), so
the phase logic is importable and unit-testable. The tier-1 smoke
(:func:`run_smoke`) spawns the 2-process pod for 2 steps and a clean
shutdown; the full matrix is bench/`make multihost-sim` territory.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

#: virtual devices per simulated host. 2 keeps the thread count near the
#: CI container's core budget (2 procs x 2 XLA device threads + gloo);
#: the correctness tests in tests/test_multihost*.py use 4 — this knob is
#: about timing fidelity, not semantics.
DEVICES_PER_HOST = int(os.environ.get("DL4J_TPU_SIM_DEVICES_PER_HOST", "2"))
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# --------------------------------------------------------------- worker
def _build_net(in_dim: int, seed: int = 0):
    from ..nn.config import InputType, NeuralNetConfiguration
    from ..nn.layers.core import DenseLayer, OutputLayer
    from ..nn.model import MultiLayerNetwork
    from ..nn.updaters import Adam

    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Adam(learning_rate=1e-3))
            .input_type(InputType.feed_forward(in_dim))
            .list(DenseLayer(n_out=128, activation="tanh"),
                  DenseLayer(n_out=128, activation="relu"),
                  OutputLayer(n_out=8))
            .build())
    return MultiLayerNetwork(conf).init()


def _build_attn_net(vocab: int, seed: int = 5):
    """Decode-capable attention LM for the ``serving`` phase: 4 heads so
    the head axis divides the 2-way model axis, one-hot token features."""
    from ..nn.config import InputType, NeuralNetConfiguration
    from ..nn.layers.attention import SelfAttentionLayer
    from ..nn.layers.core import DenseLayer, OutputLayer
    from ..nn.model import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(seed)
            .input_type(InputType.recurrent(vocab, 8))
            .list(SelfAttentionLayer(n_out=32, n_heads=4),
                  DenseLayer(n_out=32, activation="relu"),
                  OutputLayer(n_out=vocab, activation="softmax"))
            .build())
    return MultiLayerNetwork(conf).init()


def _addressable_bytes(tree) -> int:
    """Bytes of ``tree`` THIS process can address — per-host footprint of
    a placed params tree (QuantizedTensor leaves flatten to q + scale)."""
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        if isinstance(leaf, jax.Array):
            total += sum(
                int(np.prod(s.data.shape)) * np.dtype(s.data.dtype).itemsize
                for s in leaf.addressable_shards)
        else:
            a = np.asarray(leaf)
            total += a.size * a.itemsize
    return total


def _serving_phase(args, result) -> None:
    """ISSUE 17 acceptance phase: serve an attention LM through the paged
    TP engine over the pod mesh (nprocs=2, one device per simulated host,
    model axis spanning the pod) or the single-device oracle (nprocs=1 —
    which also writes the checkpoint the pod workers restore from).
    Greedy tokens, byte accounting, compile events, and dispatch counters
    land in ``result`` for the orchestrator's assertions."""
    import jax
    import numpy as np

    from ..ops import flash_attention as _fa
    from ..serving.engine import PagedGenerativeEngine
    from . import launcher
    from .checkpoint import TrainingCheckpointer

    V, PAGE = 16, 8
    net = _build_attn_net(V)
    ckdir = os.path.join(args.outdir, "ckpt_serving")
    if args.nprocs == 1:
        ck = TrainingCheckpointer(ckdir)
        try:
            ck.save(net, step=0)
        finally:
            ck.close()
        mesh = None
    else:
        # the whole point of pod serving: the model axis SPANS hosts, so
        # each host holds 1/k of the params — the model need not fit one
        mesh = launcher.pod_mesh(model=jax.device_count(),
                                 model_span="pod")
    full_bytes = sum(
        int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
        for a in jax.tree.leaves(net.params))
    result["params_bytes_full"] = full_bytes
    result["variants"] = {}
    eye = np.eye(V, dtype=np.float32)
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, V, 6), rng.integers(0, V, 9)]

    for variant, kvc in (("f32", None), ("int8", "int8")):
        _fa.reset_counters()
        eng = PagedGenerativeEngine(net, slots=4, pages=32, page_size=PAGE,
                                    max_cache_len=64, kv_cache=kvc,
                                    mesh=mesh)
        eng.warmup([64], [16], checkpoint=ckdir)
        c0 = _compile_total()
        state = eng.new_state(64)
        cur = {}
        for slot, toks in enumerate(prompts):
            plen = len(toks)
            pages = eng.pool.alloc(-(-plen // PAGE))
            eng.map_pages(state, slot, pages)
            state, logits = eng.prefill(state, eye[toks], plen, slot)
            cur[slot] = int(np.argmax(logits))
        streams = {s: [cur[s]] for s in cur}
        active = np.zeros((eng.slots,), np.int32)
        active[list(cur)] = 1
        for _ in range(12):
            snap = eng.pool.ref_snapshot()
            pairs = []
            for s in cur:
                pairs += eng.prepare_write(state, s, 1, ref_snapshot=snap)
            if pairs:
                state = eng.fork(state, pairs)
            x_t = np.zeros((eng.slots, 1, V), np.float32)
            for s in cur:
                x_t[s, 0] = eye[cur[s]]
            state, logits = eng.decode(state, x_t, active)
            for s in cur:
                cur[s] = int(np.argmax(logits[s]))
                streams[s].append(cur[s])
        placed, _ = eng._place_params()
        result["variants"][variant] = {
            "tokens": {str(s): streams[s] for s in streams},
            "post_warmup_compile_events": _compile_total() - c0,
            "params_bytes_per_host": _addressable_bytes(placed),
            "pool_bytes": eng.pool_bytes(),
            "pool_bytes_per_device": eng.pool_bytes(per_device=True),
            "tp_shards": getattr(eng._placement_layer, "tp", 1)
            if eng._placement_layer is not None else 1,
            "dispatch": {k: v for k, v in _fa.counters().items() if v},
        }


def _disagg_worker(args) -> None:
    """ISSUE 18 acceptance phase: TWO processes NOT joined by
    ``jax.distributed`` — pid 0 is a PREFILL-pool server (a
    :class:`~..serving.disagg.PrefillReplica` per KV variant behind a
    loopback TCP shipment channel), pid 1 is the DECODE-pool driver (a
    paged ``ContinuousBatcher`` per variant that adopts the shipped
    pages, plus a colocated single-pool oracle). The driver asserts, for
    f32 AND int8 KV:

    - migrated-stream greedy tokens BIT-equal to the un-migrated
      single-pool oracle;
    - the second identical prompt hits the DECODE pool's prefix registry
      (fleet-wide: migrated pages re-served with no second migration);
    - zero post-warmup compile events in both processes;
    - the stitched cross-process timeline (both pools' ``type="trace"``
      records under ONE trace id) has phases summing to the measured
      request latency within 10% across the handoff.
    """
    import numpy as np

    from ..runtime import telemetry as _tel
    from ..serving.batcher import ContinuousBatcher
    from ..serving.disagg import (KVShipment, PrefillReplica, read_msg,
                                  write_msg)
    from ..serving.kv_pool import prompt_key

    V, PAGE, CACHE, MAX_NEW = 16, 8, 32, 8
    pid = args.pid
    evpath = os.path.join(args.outdir, f"events_disagg_{pid}.jsonl")
    _tel.event_log(evpath)
    net = _build_attn_net(V)
    eye = np.eye(V, dtype=np.float32)
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, V, 6), rng.integers(0, V, 9)]
    variants = (("f32", None), ("int8", "int8"))
    result = {"phase": "disagg", "pid": pid, "variants": {}}

    if pid == 0:
        # ---------------------------------------------- prefill server
        replicas = {
            name: PrefillReplica(net, pages=32, page_size=PAGE,
                                 max_cache_len=CACHE, prompt_buckets=[16],
                                 kv_cache=kvc, pool_label="prefill")
            for name, kvc in variants}
        c0 = _compile_total()
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", args.port))
        srv.listen(1)
        conn, _addr = srv.accept()
        try:
            while True:
                msg = json.loads(read_msg(conn).decode("utf-8"))
                if msg.get("cmd") == "quit":
                    break
                pre = replicas[msg["variant"]]
                ship = pre.prefill(eye[np.asarray(msg["tokens"], int)])
                write_msg(conn, ship.to_bytes())
        finally:
            conn.close()
            srv.close()
        result["post_warmup_compile_events"] = _compile_total() - c0
        assert result["post_warmup_compile_events"] == 0, \
            (f"{result['post_warmup_compile_events']} post-warmup "
             "compiles in the prefill pool")
        for name, pre in replicas.items():
            result["variants"][name] = {"prefill_pool": pre.stats()}
    else:
        # ----------------------------------------------- decode driver
        fronts = {}
        for name, kvc in variants:
            fronts[name] = {
                "decode": ContinuousBatcher(
                    net, slots=2, max_cache_len=CACHE, paged=True,
                    pages=32, page_size=PAGE, max_new_tokens=MAX_NEW,
                    kv_cache=kvc, pool_label="decode",
                    migrate_buckets=[2]),
                "oracle": ContinuousBatcher(
                    net, slots=2, max_cache_len=CACHE, paged=True,
                    pages=32, page_size=PAGE, max_new_tokens=MAX_NEW,
                    kv_cache=kvc, pool_label="colocated"),
            }
        c0 = _compile_total()
        conn = socket.socket()
        deadline = time.time() + 60
        while True:
            try:
                conn.connect(("127.0.0.1", args.port))
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.2)

        def migrate(variant: str, toks):
            # measured request latency = ORIGIN (prefill-pool arrival) ->
            # resolution, the span the stitched phases tile: the
            # shipment's origin-side elapsed plus the decode-side
            # submit->result wall. The request-leg RPC transport happens
            # BEFORE the request exists origin-side; it rides t_wall
            # (reported, sub-ms on loopback), not the timeline.
            t0 = time.perf_counter()
            write_msg(conn, json.dumps(
                {"variant": variant, "tokens": [int(t) for t in toks]}
            ).encode("utf-8"))
            ship = KVShipment.from_bytes(read_msg(conn))
            t_sub = time.perf_counter()
            h = fronts[variant]["decode"].submit_prefilled(
                ship, max_new_tokens=MAX_NEW)
            out = h.result(timeout=120)
            now = time.perf_counter()
            return ship, out, ship.elapsed_s + (now - t_sub), now - t0

        try:
            for name, _kvc in variants:
                cb = fronts[name]["decode"]
                oracle = fronts[name]["oracle"]
                vres = {}
                ship0, out0, lat0, wall0 = migrate(name, prompts[0])
                _ship1, out1, _l1, _w1 = migrate(name, prompts[1])
                for toks, out in ((prompts[0], out0), (prompts[1], out1)):
                    ref = oracle.submit(
                        eye[toks], max_new_tokens=MAX_NEW).result(
                            timeout=120)
                    assert out["tokens"] == ref["tokens"], \
                        (f"{name}: migrated tokens {out['tokens']} != "
                         f"single-pool oracle {ref['tokens']}")
                # fleet-wide prefix reuse: the repeat prompt is resident
                # in the DECODE pool (adopted pages) — served locally,
                # no second migration
                key = prompt_key(eye[prompts[0]], len(prompts[0]))
                assert cb.engine.pool.peek_prefix(key), \
                    f"{name}: migrated prefix not registered decode-side"
                adoptions_before = cb.engine.pool.stats()["adoptions"]
                rep = cb.submit(eye[prompts[0]],
                                max_new_tokens=MAX_NEW).result(timeout=120)
                assert rep["tokens"] == out0["tokens"], \
                    f"{name}: prefix-hit tokens diverge from migrated run"
                pstats = cb.engine.pool.stats()
                assert pstats["prefix_hits"] >= 1, \
                    f"{name}: repeat prompt missed the migrated prefix"
                assert pstats["adoptions"] == adoptions_before, \
                    f"{name}: repeat prompt migrated again"
                # ONE stitched timeline across the process boundary:
                # phases must tile the measured latency (±10%)
                rec0 = [json.loads(ln) for ln in open(
                    os.path.join(args.outdir, "events_disagg_0.jsonl"))
                    if ln.strip()]
                rec1 = [json.loads(ln) for ln in open(evpath)
                        if ln.strip()]
                recs = [r for r in rec0 + rec1
                        if r.get("type") == "trace"
                        and r.get("trace") == ship0.trace_id]
                assert len(recs) == 2, \
                    (f"{name}: expected prefill+decode trace records for "
                     f"{ship0.trace_id}, got {len(recs)}")
                merged = _tel.merge_trace_records(recs)
                assert merged["pools"] == ["prefill", "decode"], merged
                phase_sum = sum(p.get("duration_s", 0.0)
                                for p in merged["phases"])
                assert abs(phase_sum - lat0) <= 0.10 * lat0, \
                    (f"{name}: stitched phases sum {phase_sum * 1e3:.2f}ms"
                     f" vs measured {lat0 * 1e3:.2f}ms (>10% apart)")
                names = [p.get("phase") for p in merged["phases"]]
                assert "handoff" in names and "adopt" in names, names
                vres.update({
                    "tokens": [int(t) for t in out0["tokens"]],
                    "latency_ms": round(lat0 * 1e3, 3),
                    "wall_with_transport_ms": round(wall0 * 1e3, 3),
                    "stitched_phase_sum_ms": round(phase_sum * 1e3, 3),
                    "phases": names,
                    "decode_pool": pstats,
                })
                result["variants"][name] = vres
            result["post_warmup_compile_events"] = _compile_total() - c0
            assert result["post_warmup_compile_events"] == 0, \
                (f"{result['post_warmup_compile_events']} post-warmup "
                 "compiles in the decode pool")
        finally:
            write_msg(conn, json.dumps({"cmd": "quit"}).encode("utf-8"))
            conn.close()
            for name in fronts:
                fronts[name]["decode"].shutdown()
                fronts[name]["oracle"].shutdown()

    _tel.close_event_log()
    with open(os.path.join(args.outdir,
                           f"result_disagg_{pid}.json"), "w") as f:
        json.dump(result, f)
    print(f"phase disagg pid {pid}: ok", flush=True)


def _make_stream(global_batch: int, steps: int, in_dim: int):
    """The SAME deterministic global batch stream on every host — the
    HostShardedIterator takes each host's slice (TensorFlow's contract:
    same program, each worker reads only its shard)."""
    import numpy as np

    from ..data.dataset import NumpyDataSetIterator
    rng = np.random.default_rng(7)
    n = global_batch * steps
    x = rng.normal(size=(n, in_dim)).astype(np.float32)
    y = np.eye(8, dtype=np.float32)[rng.integers(0, 8, n)]
    return NumpyDataSetIterator(x, y, batch_size=global_batch, shuffle=False)


def _flat_params(net):
    import jax
    import numpy as np
    leaves = sorted(jax.tree_util.tree_leaves_with_path(net.params),
                    key=lambda kv: str(kv[0]))
    return np.concatenate([np.asarray(a).ravel() for _, a in leaves])


def _compile_total() -> int:
    from ..runtime import telemetry as _tel
    m = _tel.registry.get("compile.events")
    return int(m.total()) if m is not None else 0


def _worker(args) -> None:
    """One phase, inside a subprocess (see module doc). Writes
    ``result_<phase>_<pid>.json`` (+ ``params_<phase>_<pid>.npy``) into
    ``--outdir`` and exits 0 on success — assertions ARE the contract."""
    import numpy as np

    in_dim = 64
    phase, pid, nprocs = args.phase, args.pid, args.nprocs
    if phase == "disagg":
        # ISSUE 18: the disaggregated pair is NOT a jax.distributed pod —
        # two independent single-process runtimes joined only by the
        # KV-shipment channel (the --port the pod phases would have used
        # for the coordinator is the prefill server's listen port here)
        _disagg_worker(args)
        return
    from . import launcher
    if nprocs > 1:
        launcher.initialize(
            coordinator_address=f"127.0.0.1:{args.port}",
            num_processes=nprocs, process_id=pid)
    import jax
    assert jax.process_count() == nprocs, \
        f"pod did not form: {jax.process_count()} != {nprocs}"

    if phase == "serving":
        result = {"phase": phase, "pid": pid, "nprocs": nprocs,
                  "devices": int(jax.device_count())}
        _serving_phase(args, result)
        with open(os.path.join(args.outdir,
                               f"result_{phase}_{pid}.json"), "w") as f:
            json.dump(result, f)
        if nprocs > 1:
            launcher.shutdown()
        print(f"phase {phase} pid {pid}: ok", flush=True)
        return

    from .data_parallel import ParallelWrapper
    from .resilience import ResiliencePolicy

    net = _build_net(in_dim)
    base = _make_stream(args.global_batch, args.steps, in_dim)
    it = launcher.HostShardedIterator(base)
    mesh = launcher.pod_mesh()
    pw = ParallelWrapper(net, mesh, shard_update=True, overlap_grads=True)

    result: Dict = {"phase": phase, "pid": pid, "nprocs": nprocs,
                    "devices": int(mesh.devices.size),
                    "mesh_shape": dict(mesh.shape),
                    "global_batch": args.global_batch}

    if phase == "smoke":
        # tier-1 contract: spawn + 2 steps + clean shutdown
        pw.fit(it, epochs=1)
        assert np.isfinite(float(net.score()))
        result["loss"] = float(net.score())
    elif phase in ("timing1", "timing2"):
        pw.fit(it, epochs=1)                      # warmup (compiles)
        float(net.score())
        c0 = _compile_total()
        per_step: List[float] = []
        for _ in range(args.epochs):
            for ds in it:
                t0 = time.perf_counter()
                pw.fit(ds, epochs=1)
                float(net.score())                # force the dispatch
                per_step.append(time.perf_counter() - t0)
        result["per_step_s"] = per_step
        result["warm_step_s"] = float(np.median(per_step))
        result["post_warmup_compile_events"] = _compile_total() - c0
        result["overlap_buckets"] = _overlap_buckets(net)
    elif phase in ("train", "hostloss"):
        # identical configuration; "hostloss" additionally carries the
        # DL4J_TPU_FAULTS injection in its environment. Bit-equality of
        # the two final params IS acceptance criterion (c).
        policy = ResiliencePolicy(
            checkpointer=os.path.join(args.outdir, f"ckpt_{phase}"),
            checkpoint_every_iterations=2, max_restarts=3)
        pw.fit(it, epochs=args.epochs, resilience=policy)
        assert np.isfinite(float(net.score()))
        from ..runtime import faults as _faults
        snap = _faults.telemetry_snapshot()
        result["loss"] = float(net.score())
        result["iteration"] = int(net.iteration)
        result["host_loss_recoveries"] = int(snap["host_loss_recoveries"])
        result["auto_resumes"] = int(snap["auto_resumes"])
        if phase == "hostloss":
            assert result["host_loss_recoveries"] >= 1, \
                "injection never fired — the phase proved nothing"
        np.save(os.path.join(args.outdir, f"params_{phase}_{pid}.npy"),
                _flat_params(net))
    elif phase == "restore1":
        # changed topology: ONE process, 4 devices, restoring the 2-host
        # sharded checkpoint through the verified-manifest walk
        from .checkpoint import TrainingCheckpointer
        ck = TrainingCheckpointer(os.path.join(args.outdir, "ckpt_train"))
        verified = ck.verified_steps()
        assert verified, "no manifest-verified steps in the 2-host dir"
        step = ck.restore(net, iterator=base)
        assert step == max(verified), (step, verified)
        result["restored_step"] = int(step)
        result["verified_steps"] = verified
        np.save(os.path.join(args.outdir, f"params_{phase}_{pid}.npy"),
                _flat_params(net))
        # the survivor must be able to keep training on its own topology;
        # the restored cursor sits at train's end-of-data — reset for the
        # continuation epoch (this phase proves trainability, not resume)
        base.reset()
        pw1 = ParallelWrapper(net, launcher.pod_mesh(),
                              shard_update=True, overlap_grads=True)
        pw1.fit(it, epochs=1)
        assert np.isfinite(float(net.score()))
        result["continued_loss"] = float(net.score())
    else:
        raise SystemExit(f"unknown phase {phase!r}")

    with open(os.path.join(args.outdir,
                           f"result_{phase}_{pid}.json"), "w") as f:
        json.dump(result, f)
    if nprocs > 1:
        launcher.shutdown()
    print(f"phase {phase} pid {pid}: ok", flush=True)


def _overlap_buckets(net) -> int:
    from ..runtime import telemetry as _tel
    g = _tel.registry.get("parallel.overlap.buckets")
    if g is None:
        return 0
    vals = [int(v) for v in g.series().values()]
    return max(vals) if vals else 0


# ---------------------------------------------------------- orchestrator
def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(phase: str, nprocs: int, outdir: str, steps: int, epochs: int,
           global_batch: int, timeout: float, extra_env: Optional[dict] = None
           ) -> List[dict]:
    """Run one phase (nprocs subprocesses), assert success, return the
    per-pid result dicts."""
    port = _free_port()
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count="
                         f"{DEVICES_PER_HOST}",
               PYTHONPATH=_REPO_ROOT)
    env.update(extra_env or {})   # may override XLA_FLAGS (serving phase)
    # a parent arming faults for ITSELF must not leak them into phases
    # that do not ask for an injection
    if "DL4J_TPU_FAULTS" not in (extra_env or {}):
        env.pop("DL4J_TPU_FAULTS", None)
    cmd = [sys.executable, "-m",
           "deeplearning4j_tpu.parallel.multihost_sim", "--worker",
           "--phase", phase, "--port", str(port), "--nprocs", str(nprocs),
           "--outdir", outdir, "--steps", str(steps),
           "--epochs", str(epochs), "--global-batch", str(global_batch)]
    procs = [subprocess.Popen(cmd + ["--pid", str(i)], env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for i in range(nprocs)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise RuntimeError(f"phase {phase}: worker timed out "
                               f"after {timeout}s")
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(
                f"phase {phase} pid {i} rc={p.returncode}:\n{out[-4000:]}")
    results = []
    for i in range(nprocs):
        with open(os.path.join(outdir, f"result_{phase}_{i}.json")) as f:
            results.append(json.load(f))
    return results


def run_smoke(outdir: str, timeout: float = 300.0) -> dict:
    """Tier-1 smoke: the 2-process pod forms, trains 2 steps through the
    ZeRO-1 + hierarchical-overlap path, and shuts down cleanly."""
    os.makedirs(outdir, exist_ok=True)
    res = _spawn("smoke", nprocs=2, outdir=outdir, steps=2, epochs=1,
                 global_batch=16, timeout=timeout)
    return {"ok": True, "losses": [r["loss"] for r in res],
            "mesh_shape": res[0]["mesh_shape"]}


def run_serving(outdir: str, timeout: float = 420.0,
                artifact_path: Optional[str] = None) -> dict:
    """ISSUE 17 acceptance: a 2-process pod (ONE device per simulated
    host, model axis spanning the pod) serves an attention LM whose full
    params exceed one host's simulated bytes_limit; greedy tokens must be
    BIT-equal to the single-device oracle for f32 AND int8 KV, with zero
    post-warmup compile events and the per-device page pool ≈ 1/k of the
    unsharded pool. The oracle runs first and writes the pod
    ``TrainingCheckpointer`` directory both topologies restore through
    (``warmup(checkpoint=)`` — per-host addressable-shard loading)."""
    os.makedirs(outdir, exist_ok=True)
    one_dev = {"XLA_FLAGS": "--xla_force_host_platform_device_count=1",
               "DL4J_TPU_SIM_DEVICES_PER_HOST": "1"}
    oracle = _spawn("serving", 1, outdir, 1, 1, 1, timeout,
                    extra_env=one_dev)[0]
    pod = _spawn("serving", 2, outdir, 1, 1, 1, timeout,
                 extra_env=one_dev)

    full = int(oracle["params_bytes_full"])
    # the simulated per-host HBM budget: the full model does NOT fit one
    # host, its 1/k shard does — the workload class pod serving exists for
    bytes_limit = int(0.75 * full)
    checks = {}
    for variant in ("f32", "int8"):
        ov = oracle["variants"][variant]
        pv = [r["variants"][variant] for r in pod]
        assert pv[0]["tokens"] == pv[1]["tokens"], \
            f"{variant}: pod hosts disagree on greedy tokens"
        assert ov["tokens"] == pv[0]["tokens"], \
            f"{variant}: TP tokens diverge from the single-device oracle"
        compiles = max(int(r["post_warmup_compile_events"]) for r in pv)
        assert compiles == 0, \
            f"{variant}: {compiles} post-warmup compiles on the pod"
        per_host = max(int(r["params_bytes_per_host"]) for r in pv)
        assert per_host < bytes_limit < full, \
            (f"{variant}: per-host {per_host} vs limit {bytes_limit} "
             f"vs full {full} — the pod is not actually sharding")
        k = int(pv[0]["tp_shards"])
        assert k == 2, f"{variant}: expected 2 model shards, got {k}"
        pool_ratio = pv[0]["pool_bytes_per_device"] / pv[0]["pool_bytes"]
        assert abs(pool_ratio - 1.0 / k) < 0.05, \
            f"{variant}: per-device pool ratio {pool_ratio} != 1/{k}"
        assert any(key.endswith("tp_shard_map") or key.endswith("tp_gspmd")
                   for key in pv[0]["dispatch"]), \
            f"{variant}: no TP dispatch decision counted (silent route?)"
        checks[variant] = {
            "tokens_bit_equal": True,
            "post_warmup_compile_events": compiles,
            "params_bytes_per_host": per_host,
            "pool_bytes_per_device_ratio": round(pool_ratio, 4),
            "dispatch": pv[0]["dispatch"],
        }
    artifact = {
        "metric": "pod_serving_sim",
        "value": 1.0,
        "unit": "bool_all_assertions",
        "hosts": 2,
        "devices_per_host": 1,
        "model_span": "pod",
        "params_bytes_full": full,
        "simulated_host_bytes_limit": bytes_limit,
        "variants": checks,
        "note": "CPU loopback pod: bit-parity/byte/compile proofs are the "
                "artifact; real-pod throughput comes from hardware runs",
    }
    if artifact_path:
        with open(artifact_path, "w") as f:
            json.dump(artifact, f, indent=1)
    return artifact


def run_disagg(outdir: str, timeout: float = 300.0,
               artifact_path: Optional[str] = None) -> dict:
    """ISSUE 18 acceptance: a PREFILL-pool process ships KV pages over a
    loopback channel to a DECODE-pool process that adopts and serves
    them. The workers assert the contract (bit-equal migrated streams
    for f32 and int8 KV, fleet-wide prefix reuse with no re-migration,
    zero post-warmup compiles in BOTH pools, stitched cross-process
    timelines whose phases sum to the measured latency ±10%); the
    orchestrator folds their result files into the artifact. Fast enough
    for tier-1 (small model, one prompt pair per variant)."""
    os.makedirs(outdir, exist_ok=True)
    one_dev = {"XLA_FLAGS": "--xla_force_host_platform_device_count=1",
               "DL4J_TPU_SIM_DEVICES_PER_HOST": "1"}
    res = _spawn("disagg", 2, outdir, 1, 1, 1, timeout,
                 extra_env=one_dev)
    server, driver = res[0], res[1]
    for r in res:
        assert int(r["post_warmup_compile_events"]) == 0, r
    artifact = {
        "metric": "disagg_serving_sim",
        "value": 1.0,
        "unit": "bool_all_assertions",
        "pools": {"prefill": 1, "decode": 1},
        "variants": driver["variants"],
        "prefill_pool": {name: v["prefill_pool"]
                         for name, v in server["variants"].items()},
        "post_warmup_compile_events": 0,
        "note": "CPU loopback pools: bit-parity/prefix-reuse/compile/"
                "timeline proofs are the artifact; split-vs-colocated "
                "latency comes from bench.py disaggregated_serving",
    }
    if artifact_path:
        with open(artifact_path, "w") as f:
            json.dump(artifact, f, indent=1)
    return artifact


def run_simulation(outdir: str, steps: int = 4, epochs: int = 2,
                   global_batch_per_host: int = 16,
                   artifact_path: Optional[str] = None,
                   timeout: float = 420.0) -> dict:
    """The full acceptance matrix (module doc). Weak scaling: the
    per-host batch is constant, so the 2-host run processes 2x the global
    examples per step — ideal scaling keeps the step time flat and
    ``scaling_efficiency = t_1host / t_2host = 1.0``. On the CPU
    simulation the DCN hop is loopback gloo; the number is the harness
    proof, the real-pod value comes from running the same phases on
    hardware."""
    import numpy as np

    os.makedirs(outdir, exist_ok=True)
    t_begin = time.time()

    t1 = _spawn("timing1", 1, outdir, steps, epochs,
                global_batch_per_host, timeout)[0]
    t2 = _spawn("timing2", 2, outdir, steps, epochs,
                2 * global_batch_per_host, timeout)
    train = _spawn("train", 2, outdir, steps, max(2, epochs),
                   2 * global_batch_per_host, timeout)
    # whole-host loss: fires on every process at the same step (after=
    # counts per-process trips — SPMD keeps them in lockstep), inside the
    # LAST epoch so the recovery actually has steps left to redo
    fire_after = steps * (max(2, epochs) - 1) + 1
    hostloss = _spawn(
        "hostloss", 2, outdir, steps, max(2, epochs),
        2 * global_batch_per_host, timeout,
        extra_env={"DL4J_TPU_FAULTS":
                   f"parallel.host_loss:error=host_loss:after={fire_after}"})
    restore1 = _spawn("restore1", 1, outdir, steps, 1,
                      global_batch_per_host, timeout)[0]

    p_train = [np.load(os.path.join(outdir, f"params_train_{i}.npy"))
               for i in range(2)]
    p_loss = [np.load(os.path.join(outdir, f"params_hostloss_{i}.npy"))
              for i in range(2)]
    p_restore = np.load(os.path.join(outdir, "params_restore1_0.npy"))

    cross_host_equal = bool((p_train[0] == p_train[1]).all()
                            and (p_loss[0] == p_loss[1]).all())
    resume_bit_equal = bool((p_train[0] == p_loss[0]).all())
    # restore1 restored train's LAST checkpoint == train's final state
    # (the resilient driver's epoch-end save), so the comparison is exact
    topo_restore_ok = bool((p_restore == p_train[0]).all())

    step1 = float(t1["warm_step_s"])
    step2 = float(np.median([r["warm_step_s"] for r in t2]))
    compiles2 = max(int(r["post_warmup_compile_events"]) for r in t2)
    artifact = {
        "metric": "multihost_scaling",
        "value": round(step1 / step2, 3),
        "unit": "x_scaling_efficiency_1to2_hosts_weak",
        "hosts": 2,
        "devices_per_host": DEVICES_PER_HOST,
        "mesh": t2[0]["mesh_shape"],
        "parallelism": "ZeRO-1 shard_update + overlap_grads "
                       "(hierarchical dcn/ici collectives)",
        "overlap_buckets": t2[0].get("overlap_buckets", 0),
        "global_batch_per_host": global_batch_per_host,
        "step_time_ms_1host": round(step1 * 1e3, 2),
        "step_time_ms_2host": round(step2 * 1e3, 2),
        "scaling_efficiency": round(step1 / step2, 3),
        "post_warmup_compile_events": compiles2,
        "zero_post_warmup_compiles": compiles2 == 0,
        "host_loss_recoveries": max(r["host_loss_recoveries"]
                                    for r in hostloss),
        "host_loss_resume_bit_equal": resume_bit_equal,
        "cross_host_params_bit_equal": cross_host_equal,
        "topology_restore_2to1_bit_equal": topo_restore_ok,
        "restore1_verified_steps": restore1["verified_steps"],
        "train_final_loss": round(train[0]["loss"], 6),
        "hostloss_final_loss": round(hostloss[0]["loss"], 6),
        "elapsed_s": round(time.time() - t_begin, 1),
        "note": "CPU loopback simulation (gloo DCN): step times are "
                "CPU-relative; the harness + bit-equality proofs are the "
                "artifact, real-pod efficiency comes from hardware runs",
    }
    if artifact_path:
        with open(artifact_path, "w") as f:
            json.dump(artifact, f, indent=1)
    return artifact


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--phase", default="smoke")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--pid", type=int, default=0)
    ap.add_argument("--nprocs", type=int, default=1)
    ap.add_argument("--outdir", default="multihost_sim_out")
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--global-batch", type=int, default=32)
    ap.add_argument("--artifact", default=None,
                    help="orchestrator mode: write the MULTICHIP-style "
                         "artifact json here")
    ap.add_argument("--serving", action="store_true",
                    help="orchestrator mode: run the ISSUE 17 pod-serving "
                         "acceptance phase instead of the training matrix")
    ap.add_argument("--disagg", action="store_true",
                    help="orchestrator mode: run the ISSUE 18 "
                         "disaggregated prefill/decode acceptance phase "
                         "(two processes joined by the KV-shipment "
                         "channel, not jax.distributed)")
    args = ap.parse_args(argv)
    if args.worker:
        _worker(args)
        return
    if args.serving:
        art = run_serving(args.outdir, artifact_path=args.artifact)
        print(json.dumps(art, indent=1))
        return
    if args.disagg:
        art = run_disagg(args.outdir, artifact_path=args.artifact)
        print(json.dumps(art, indent=1))
        return
    art = run_simulation(args.outdir, steps=args.steps, epochs=args.epochs,
                         artifact_path=args.artifact)
    print(json.dumps(art, indent=1))


if __name__ == "__main__":
    main()
