"""Runtime environment knobs.

TPU-native equivalent of libnd4j's ``Environment`` singleton + nd4j's
``ND4JSystemProperties``/``Nd4jEnvironmentVars`` (reference:
``libnd4j/include/system/Environment.h``†, ``nd4j-common``† per SURVEY.md §5
"Config / flag system"; reference mount was empty, citations
upstream-relative, unverified).

Env-var overrides use the ``DL4J_TPU_`` prefix (mirror of the reference's
``ND4J_``/``org.nd4j.*`` convention).

The load-bearing knob is **matmul precision policy**: DL4J is strict-fp32;
XLA's *default* matmul/conv precision on TPU (and this CPU stack) decomposes
f32 into bf16 passes (~1e-2 error). Policy: float32 inputs compute at
``Precision.HIGHEST`` (DL4J numeric parity, grad-checkable); bfloat16 inputs
use native MXU passes (the perf path — mixed-precision models opt in by
dtype, per SURVEY.md §7.3 item 8).
"""

from __future__ import annotations

import os

import jax
from jax import lax


class Environment:
    _instance = None

    def __init__(self):
        self.debug = os.environ.get("DL4J_TPU_DEBUG", "0") == "1"
        self.verbose = os.environ.get("DL4J_TPU_VERBOSE", "0") == "1"
        # "highest" => f32 math is true f32 (DL4J parity); "default" => let
        # XLA use fast bf16 passes even for f32 inputs.
        self.f32_matmul_precision = os.environ.get(
            "DL4J_TPU_F32_MATMUL_PRECISION", "highest")
        # NaN/Inf panic mode (ProfilerConfig.checkForNAN/INF equivalent):
        # routes to jax debug_nans/debug_infs.
        if os.environ.get("DL4J_TPU_CHECK_NAN", "0") == "1":
            jax.config.update("jax_debug_nans", True)
        if os.environ.get("DL4J_TPU_CHECK_INF", "0") == "1":
            jax.config.update("jax_debug_infs", True)
        # Default CNN data format for layers ("NCHW" = DL4J default; "NHWC"
        # is the TPU-preferred layout zoo/bench configs use).
        self.default_data_format = os.environ.get("DL4J_TPU_DATA_FORMAT", "NCHW")

    @classmethod
    def instance(cls) -> "Environment":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def set_check_nan(self, enabled: bool) -> None:
        jax.config.update("jax_debug_nans", enabled)

    def set_check_inf(self, enabled: bool) -> None:
        jax.config.update("jax_debug_infs", enabled)


def precision_for(*arrays):
    """lax.Precision for a matmul/conv over these operands.

    float32 anywhere -> HIGHEST (unless policy overridden); pure
    bf16/f16/int -> None (XLA default, native MXU passes).
    """
    env = Environment.instance()
    if env.f32_matmul_precision != "highest":
        return None
    import jax.numpy as jnp
    for a in arrays:
        dt = getattr(a, "dtype", None)
        if dt == jnp.float32 or dt == jnp.float64:
            return lax.Precision.HIGHEST
    return None
