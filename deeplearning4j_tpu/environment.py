"""Runtime environment knobs.

TPU-native equivalent of libnd4j's ``Environment`` singleton + nd4j's
``ND4JSystemProperties``/``Nd4jEnvironmentVars`` (reference:
``libnd4j/include/system/Environment.h``†, ``nd4j-common``† per SURVEY.md §5
"Config / flag system"; reference mount was empty, citations
upstream-relative, unverified).

Env-var overrides use the ``DL4J_TPU_`` prefix (mirror of the reference's
``ND4J_``/``org.nd4j.*`` convention).

The load-bearing knob is **matmul precision policy**: DL4J is strict-fp32;
XLA's *default* matmul/conv precision decomposes f32 into bf16 passes
(~2.5e-3 rel err). The "auto" policy resolves per platform: CPU computes f32
at ``Precision.HIGHEST`` (exact oracle/grad-check parity, where CI runs);
TPU uses ``Precision.DEFAULT`` (measured on this backend: LeNet train step
compiles 25s vs 283s at HIGH with identical runtime — and bf16-pass f32 is
standard JAX training practice). Numeric-parity workloads on TPU opt in to
"high" (~2e-5 rel err) or "highest" via the env var or the instance
attribute. bfloat16 inputs always use native MXU passes (the perf path —
mixed-precision models opt in by dtype, per SURVEY.md §7.3 item 8).
"""

from __future__ import annotations

import os

import jax
from jax import lax


class Environment:
    _instance = None

    def __init__(self):
        self.debug = os.environ.get("DL4J_TPU_DEBUG", "0") == "1"
        self.verbose = os.environ.get("DL4J_TPU_VERBOSE", "0") == "1"
        # f32 matmul/conv precision policy:
        #   "auto"    => HIGHEST on CPU (exact oracle/grad-check parity),
        #                DEFAULT on TPU (single bf16 pass — measured on this
        #                backend: full LeNet step compiles 25s vs 283s at
        #                HIGH, runs identically; ~2.5e-3 conv rel err is
        #                standard JAX training practice)
        #   "highest" | "high" | "default" => force that lax.Precision
        #   (numeric-parity workloads on TPU set "high": ~2e-5 rel err)
        self.f32_matmul_precision = os.environ.get(
            "DL4J_TPU_F32_MATMUL_PRECISION", "auto")
        if self.f32_matmul_precision not in ("auto", "highest", "high", "default"):
            raise ValueError(
                f"DL4J_TPU_F32_MATMUL_PRECISION={self.f32_matmul_precision!r} "
                "— expected one of: auto, highest, high, default")
        # Persistent XLA compile cache: a given (program, shape) compiles
        # once per machine, not once per process. "" or "0" disables; any
        # failure to create the dir just disables caching (never blocks
        # package import).
        cache_dir = os.environ.get(
            "DL4J_TPU_COMPILE_CACHE",
            os.path.expanduser("~/.cache/deeplearning4j_tpu/xla"))
        if cache_dir not in ("", "0"):
            try:
                os.makedirs(cache_dir, exist_ok=True)
                jax.config.update("jax_compilation_cache_dir", cache_dir)
                jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
            except OSError:
                pass
        # NaN/Inf panic mode (ProfilerConfig.checkForNAN/INF equivalent):
        # routes to jax debug_nans/debug_infs.
        if os.environ.get("DL4J_TPU_CHECK_NAN", "0") == "1":
            jax.config.update("jax_debug_nans", True)
        if os.environ.get("DL4J_TPU_CHECK_INF", "0") == "1":
            jax.config.update("jax_debug_infs", True)
        # Default CNN data format for layers ("NCHW" = DL4J default; "NHWC"
        # is the TPU-preferred layout zoo/bench configs use).
        self.default_data_format = os.environ.get("DL4J_TPU_DATA_FORMAT", "NCHW")
        # XLA latency-hiding scheduler for the engines' TPU programs:
        # overlaps the async HBM copies (weight/activation layout
        # conversions) with compute. Measured ~3% faster ResNet-50 bf16
        # train step on v5e; harmless single-chip, designed for multi-chip
        # collective overlap. DL4J_TPU_LHS=0 disables.
        self.latency_hiding_scheduler = os.environ.get(
            "DL4J_TPU_LHS", "1") == "1"

    @classmethod
    def instance(cls) -> "Environment":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def set_check_nan(self, enabled: bool) -> None:
        jax.config.update("jax_debug_nans", enabled)

    def set_check_inf(self, enabled: bool) -> None:
        jax.config.update("jax_debug_infs", enabled)


_DEFAULT_BACKEND = None  # cached: backend probing is the only expensive part


def engine_compiler_options():
    """``compiler_options`` for the engines' jitted train/epoch programs.

    TPU-only (CPU/GPU backends reject unknown TPU flags): enables the XLA
    latency-hiding scheduler unless Environment disables it. Returns None
    when there is nothing to apply (jax.jit treats None as default)."""
    global _DEFAULT_BACKEND
    if _DEFAULT_BACKEND is None:
        _DEFAULT_BACKEND = jax.default_backend()
    if _DEFAULT_BACKEND != "tpu":
        return None
    if not Environment.instance().latency_hiding_scheduler:
        return None
    return {"xla_tpu_enable_latency_hiding_scheduler": "true"}


def _resolved_f32_precision():
    """Resolve the policy — re-read per call so tests/users can flip
    ``Environment.instance().f32_matmul_precision`` at runtime."""
    global _DEFAULT_BACKEND
    mode = Environment.instance().f32_matmul_precision
    if mode == "auto":
        if _DEFAULT_BACKEND is None:
            _DEFAULT_BACKEND = jax.default_backend()
        mode = "highest" if _DEFAULT_BACKEND == "cpu" else "default"
    try:
        return {
            "highest": lax.Precision.HIGHEST,
            "high": lax.Precision.HIGH,
            "default": lax.Precision.DEFAULT,
        }[mode]
    except KeyError:
        raise ValueError(
            f"f32_matmul_precision={mode!r} — expected one of: "
            "auto, highest, high, default") from None


def precision_for(*arrays):
    """lax.Precision for a matmul/conv over these operands.

    float32/float64 anywhere -> the policy precision (see Environment); pure
    bf16/f16/int -> None (XLA default, native MXU passes).
    """
    import jax.numpy as jnp
    for a in arrays:
        dt = getattr(a, "dtype", None)
        if dt == jnp.float32 or dt == jnp.float64:
            return _resolved_f32_precision()
    return None
