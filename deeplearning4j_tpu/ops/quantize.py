"""Int8 post-training quantization primitives (ISSUE 9 tentpole, layer 1).

Production serving is memory-bound: the hot path streams weights (and,
for generative decode, the KV cache) out of HBM every request. Symmetric
int8 weights halve that traffic and roughly double the serveable batch
per the r9 HBM accounting, and on TPU an int8 x int8 -> int32 contraction
is a NATIVE MXU pass (``jax.lax.dot_general`` with int8 operands and
``preferred_element_type=jnp.int32`` lowers to it — the same contract the
conv path uses). This module is the primitive set the rest of the stack
rides:

- :func:`quantize_per_channel` / :class:`QuantizedTensor` — per-channel
  symmetric int8 weight quantization with f32 scales (one scale per
  OUTPUT channel; zero-point-free, range ±127 so negation is closed).
  ``QuantizedTensor`` is a registered pytree, so a quantized params tree
  flows through ``jax.eval_shape`` / ``device_put`` / the serving
  engines' placement walks like any other params tree.
- :func:`quantize_dynamic` / :func:`quantize_per_example` — dynamic
  activation scales computed inside the compiled graph per call (no
  calibration dataset; the TF-Serving posture of quantization as a
  deploy-time engine transform, not a training-time concern). The fused
  kernels use the PER-EXAMPLE variant: under coalesced serving a
  per-tensor scale would couple co-batched requests (one request's
  outlier crushes its neighbours' resolution); per-example scales keep
  each row's answer independent of its batch neighbours
  (batch-invariance, regression-tested).
- :func:`int8_matmul` / :func:`int8_conv` — the fused kernels: quantize
  the activation, contract in int8 with an int32 accumulator, and
  dequantize INTO the accumulator epilogue (one multiply by
  ``x_scale * w_scale[channel]``). Integer arithmetic is exact, so the
  ``dot_general`` path and the einsum reference path are BIT-identical
  — that is the CPU-deterministic parity contract tier-1 asserts
  without an MXU (``impl`` knob / ``DL4J_TPU_QUANT_IMPL``).
- :func:`quantize_rows` / :func:`dequantize_rows` — per-row (per slot,
  head, position) int8 KV-cache quantization for the generative decode
  path: scales stored beside the ``(k, v, length)`` buckets, shaped
  ``[B, H, C, 1]`` so ``flash_attention.cache_insert`` appends them with
  the same machinery as the values.

Env pins: ``DL4J_TPU_QUANT`` (``int8`` | ``off`` — ``off`` makes every
engine-level ``quantize="int8"`` request serve f32, counted as a
fallback, the CI kill switch) and ``DL4J_TPU_QUANT_IMPL``
(``dot`` | ``einsum``). Every routing decision bumps
``quantize.dispatch{decision=}`` — zero silent fallbacks, same registry
posture as ``flash_attention.dispatch``.

Divergence (recorded in PARITY.md): DL4J/nd4j quantization
(``INDArray`` half/quarter-precision compression) was a training-side
storage codec; there is no DL4J int8 *serving* path, and dynamic
activation scales have no reference equivalent at all.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import register
from ..runtime import telemetry as _tel

#: symmetric range: ±127 (not -128) so q == -q is representable and the
#: scale math stays zero-point-free
QMAX = 127.0
#: scale floor for all-zero channels/tensors: dequantizes to exact zeros
#: without a divide-by-zero in the quantize direction
_EPS = 1e-12

_DISPATCH = _tel.counter(
    "quantize.dispatch",
    "int8 kernel dispatch decisions at trace time (dot vs einsum)")
_REWRITE = _tel.counter(
    "quantize.rewrite",
    "SameDiff weight-quantization rewrite decisions per site "
    "(matched vs skipped_<reason>)")

_state = {
    "mode": os.environ.get("DL4J_TPU_QUANT", "int8"),
    "impl": os.environ.get("DL4J_TPU_QUANT_IMPL", "dot"),
}


def mode() -> str:
    """``int8`` (quantization honored when an engine asks for it) or
    ``off`` (the ``DL4J_TPU_QUANT=off`` CI pin: every engine-level
    quantize request serves f32 instead, counted as a fallback)."""
    return _state["mode"]


def set_mode(m: str) -> str:
    if m not in ("int8", "off"):
        raise ValueError(f"quantize mode {m!r} not in ('int8', 'off')")
    old = _state["mode"]
    _state["mode"] = m
    return old


def impl() -> str:
    return _state["impl"]


def set_impl(i: str) -> str:
    """``dot`` (``lax.dot_general`` — the native int8 MXU lowering) or
    ``einsum`` (the reference spelling). Integer arithmetic is exact, so
    the two are bit-identical — the parity test's lever. Consulted at
    TRACE time (same caveat as ``flash_attention.set_mode``)."""
    if i not in ("dot", "einsum"):
        raise ValueError(f"quantize impl {i!r} not in ('dot', 'einsum')")
    old = _state["impl"]
    _state["impl"] = i
    return old


# --------------------------------------------------------------------------
# quantized weight container
# --------------------------------------------------------------------------

@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """Per-channel symmetric int8 weight: ``q`` int8 with the original
    shape, ``scale`` f32 ``[channels]`` along ``axis`` (the OUTPUT
    channel axis). A pytree node, so quantized params trees flow through
    ``eval_shape``/``device_put``/placement walks unchanged; ``axis`` is
    static aux data (part of the tree structure, never traced)."""

    __slots__ = ("q", "scale", "axis")

    #: duck-type marker for dtype-policy tree walks: ``cast_floating``
    #: must leave a quantized leaf alone (the int8 values are not
    #: floating, and casting the f32 scales to a 16-bit compute dtype
    #: would permanently degrade dequantization accuracy)
    __quantized_tensor__ = True

    def __init__(self, q, scale, axis: int):
        self.q = q
        self.scale = scale
        self.axis = int(axis)

    def tree_flatten(self):
        return (self.q, self.scale), self.axis

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def nbytes(self) -> int:
        return _nbytes(self.q) + _nbytes(self.scale)

    def _bcast_scale(self):
        shape = [1] * len(self.q.shape)
        shape[self.axis] = self.q.shape[self.axis]
        # f32 regardless of what a dtype-policy tree cast did to the
        # stored copy: the epilogue multiply is the accuracy-critical op
        return jnp.asarray(self.scale, jnp.float32).reshape(shape)

    def dequantize(self, dtype=jnp.float32):
        return (self.q.astype(jnp.float32) * self._bcast_scale()).astype(
            dtype)

    def __repr__(self):
        return (f"QuantizedTensor(int8 {tuple(self.q.shape)}, "
                f"axis={self.axis})")


def _nbytes(a) -> int:
    return int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize


def quantize_per_channel(w, axis: int) -> QuantizedTensor:
    """Symmetric per-channel int8 quantization of a weight: one f32
    scale per slice along ``axis`` (``absmax / 127``), values rounded
    half-to-even and clipped to ±127. All-zero channels get a unit scale
    (dequantize to exact zeros)."""
    w = jnp.asarray(w)
    axis = axis % w.ndim
    reduce_axes = tuple(i for i in range(w.ndim) if i != axis)
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=reduce_axes)            # [channels]
    scale = jnp.where(amax <= _EPS, 1.0, amax / QMAX)
    shape = [1] * w.ndim
    shape[axis] = w.shape[axis]
    q = jnp.clip(jnp.round(w32 / scale.reshape(shape)), -QMAX, QMAX)
    return QuantizedTensor(q.astype(jnp.int8), scale, axis)


def quantize_dynamic(x) -> Tuple[jax.Array, jax.Array]:
    """Dynamic per-tensor symmetric activation quantization: returns
    ``(q int8, scale f32 scalar)`` computed from this call's absmax —
    inside the compiled graph, so serving needs no calibration pass and
    out-of-distribution requests cannot fall outside a frozen range."""
    x32 = jnp.asarray(x).astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32))
    scale = jnp.where(amax <= _EPS, 1.0, amax / QMAX)
    q = jnp.clip(jnp.round(x32 / scale), -QMAX, QMAX).astype(jnp.int8)
    return q, scale


def quantize_per_example(x) -> Tuple[jax.Array, jax.Array]:
    """Dynamic PER-EXAMPLE activation quantization: one scale per
    leading-axis row (``scale`` f32 shaped ``[B, 1, ..., 1]``). This is
    what the fused kernels use — under coalesced serving, a per-tensor
    scale would couple co-batched requests (one request's outlier
    activation crushes its neighbours' int8 resolution, so the same
    request could answer differently depending on who it was batched
    with); per-example scales keep every row's quantization a function
    of that row alone (batch-invariance, regression-tested)."""
    x32 = jnp.asarray(x).astype(jnp.float32)
    axes = tuple(range(1, x32.ndim))
    amax = jnp.max(jnp.abs(x32), axis=axes, keepdims=True)
    scale = jnp.where(amax <= _EPS, 1.0, amax / QMAX)
    q = jnp.clip(jnp.round(x32 / scale), -QMAX, QMAX).astype(jnp.int8)
    return q, scale


# --------------------------------------------------------------------------
# fused int8 kernels (dequantize in the accumulator epilogue)
# --------------------------------------------------------------------------

def _int8_contract(xq, wq):
    """int8 x int8 -> int32 over (x's last dim, w's first dim). The
    ``dot`` impl is the native-MXU lowering; ``einsum`` is the reference
    spelling — integer arithmetic, so bit-identical (parity-tested)."""
    if _state["impl"] == "einsum":
        _DISPATCH.inc(decision="einsum")
        return jnp.einsum("...k,ko->...o", xq, wq,
                          preferred_element_type=jnp.int32)
    _DISPATCH.inc(decision="dot")
    return jax.lax.dot_general(
        xq, wq, (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)


def int8_matmul(x, wq, w_scale, bias=None, out_dtype=None):
    """Fused quantized matmul: dynamic-quantize ``x`` with PER-EXAMPLE
    scales (batch-invariant under request coalescing — see
    :func:`quantize_per_example`), contract in int8 with an int32
    accumulator, dequantize in the epilogue by
    ``x_scale[row] * w_scale[out_channel]``. ``wq`` int8 ``[in, out]``,
    ``w_scale`` f32 ``[out]``; output in ``x``'s (floating) dtype."""
    x = jnp.asarray(x)
    out_dtype = out_dtype or (x.dtype if jnp.issubdtype(x.dtype,
                                                        jnp.floating)
                              else jnp.float32)
    if x.ndim >= 2:
        # scale [B, 1, ..., 1]: constant over the contracted last axis,
        # broadcasts over the accumulator's [..., out] unchanged
        xq, xs = quantize_per_example(x)
    else:  # 1-D x: the leading axis IS the contraction — per-tensor
        xq, xs = quantize_dynamic(x)
    acc = _int8_contract(xq, jnp.asarray(wq))
    y = acc.astype(jnp.float32) * xs * jnp.asarray(w_scale, jnp.float32)
    if bias is not None:
        y = y + jnp.asarray(bias, jnp.float32)
    return y.astype(out_dtype)


def qdot(x, w, bias=None):
    """``jnp.dot(x, w) + bias`` that routes :class:`QuantizedTensor`
    weights through :func:`int8_matmul` — the one call site the dense /
    output / attention-projection layers use, so a quantized params tree
    changes the kernel without touching layer code paths."""
    if isinstance(w, QuantizedTensor):
        if w.axis != w.q.ndim - 1:
            raise ValueError(
                f"qdot needs output-channel-last quantization (axis="
                f"{w.q.ndim - 1}); got axis={w.axis}")
        return int8_matmul(x, w.q, jnp.asarray(w.scale, jnp.float32), bias)
    from ..environment import precision_for
    y = jnp.dot(x, w, precision=precision_for(x, w))
    return y if bias is None else y + bias


def int8_conv(x, w: QuantizedTensor, b=None, stride=(1, 1), padding=0,
              dilation=(1, 1), mode="truncate", data_format="NCHW",
              groups: int = 1):
    """Fused quantized 2D convolution (OIHW weights quantized per OUTPUT
    channel, ``axis=0``): dynamic-quantize ``x`` per example (batch-
    invariant — see :func:`quantize_per_example`), integer conv with an
    int32 accumulator (``preferred_element_type`` — the native int8 MXU
    conv pass on TPU), dequantize per output channel in the epilogue."""
    from .nnops import _conv_dnums, _conv_padding, _pair
    if w.axis != 0:
        raise ValueError(f"int8_conv wants per-output-channel (axis=0) "
                         f"quantization; got axis={w.axis}")
    x = jnp.asarray(x)
    out_dtype = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.float32
    stride, dilation = _pair(stride), _pair(dilation)
    kh, kw = w.q.shape[2], w.q.shape[3]
    io_layout, _, out_layout = _conv_dnums(data_format)
    dn = jax.lax.conv_dimension_numbers(x.shape, w.q.shape,
                                        (io_layout, "OIHW", out_layout))
    pad = _conv_padding(mode, padding, (kh, kw), stride, dilation)
    xq, xs = quantize_per_example(x)  # [N,1,1,1]: per-row decoupling
    _DISPATCH.inc(decision="conv")
    acc = jax.lax.conv_general_dilated(
        xq, w.q, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups, preferred_element_type=jnp.int32)
    ws = jnp.asarray(w.scale, jnp.float32)
    chan = ws.reshape((1, -1, 1, 1) if data_format == "NCHW"
                      else (1, 1, 1, -1))
    y = acc.astype(jnp.float32) * (xs * chan)
    if b is not None:
        y = y + (jnp.asarray(b, jnp.float32).reshape(1, -1, 1, 1)
                 if data_format == "NCHW"
                 else jnp.asarray(b, jnp.float32).reshape(1, 1, 1, -1))
    return y.astype(out_dtype)


# --------------------------------------------------------------------------
# int8 KV cache (generative decode): per-row scales beside the buckets
# --------------------------------------------------------------------------

def quantize_rows(x) -> Tuple[jax.Array, jax.Array]:
    """Per-row KV quantization: ``x`` ``[B, H, T, d]`` -> ``(q int8,
    scale f32 [B, H, T, 1])`` — one scale per (slot, head, position), so
    every appended token quantizes against its OWN range (a loud outlier
    token cannot crush the whole cache's resolution) and the scale
    tensor appends through ``flash_attention.cache_insert`` exactly like
    a ``d=1`` value cache."""
    x32 = jnp.asarray(x).astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1, keepdims=True)
    scale = jnp.where(amax <= _EPS, 1.0, amax / QMAX)
    q = jnp.clip(jnp.round(x32 / scale), -QMAX, QMAX).astype(jnp.int8)
    return q, scale


def dequantize_rows(q, scale, dtype):
    """Inverse of :func:`quantize_rows`: int8 cache + ``[B, H, C, 1]``
    scales -> the compute-dtype cache the decode kernel streams."""
    return (q.astype(jnp.float32) * jnp.asarray(scale, jnp.float32)) \
        .astype(dtype)


# --------------------------------------------------------------------------
# params-tree walk (shared by MLN / CG quantize_params and the engines)
# --------------------------------------------------------------------------

class QuantizeReport:
    """What a params-tree (or graph) quantization pass did: ``sites`` =
    weights quantized, ``skipped`` = candidate records left f32 (with
    reasons), plus the byte accounting behind the serveable-batch
    claim."""

    def __init__(self):
        self.sites = 0
        self.skipped = 0
        self.reasons = []
        self.bytes_f32 = 0
        self.bytes_q = 0

    @property
    def bytes_saved(self) -> int:
        return max(0, self.bytes_f32 - self.bytes_q)

    def __str__(self):
        return (f"quantize: {self.sites} weights -> int8 "
                f"({self.bytes_f32} -> {self.bytes_q} bytes), "
                f"{self.skipped} skipped")


def quantize_layer_params(layer, params, report: Optional[QuantizeReport]
                          = None) -> dict:
    """Quantize one layer's weights per its ``quantize_spec`` (the
    ``decode_pointwise``-style opt-in mark on ``nn/layers/base.py``):
    the named leaves become :class:`QuantizedTensor`; everything else
    (biases, norms, embeddings, learned queries) stays f32. The
    ``quantizable`` class flag gates the spec, so a subclass can opt
    back OUT (``quantizable = False``) without overriding the method."""
    if not params or not getattr(layer, "quantizable", False):
        return params
    spec = layer.quantize_spec(params)
    if not spec:
        return params
    new = dict(params)
    for name, axis in spec.items():
        w = new.get(name)
        if w is None or isinstance(w, QuantizedTensor):
            continue
        qt = quantize_per_channel(w, axis)
        if report is not None:
            report.sites += 1
            report.bytes_f32 += _nbytes(w)
            report.bytes_q += qt.nbytes
        new[name] = qt
    return new


def quantize_model_params(model) -> Tuple[dict, QuantizeReport]:
    """Layer-walk post-training quantization for MultiLayerNetwork and
    ComputationGraph (the decode/remat walk pattern): returns a NEW
    params tree with every opted-in weight quantized — the model's own
    f32 params are untouched, so training and f32 serving continue to
    work on the same instance."""
    report = QuantizeReport()
    out = {}
    if hasattr(model.conf, "inputs"):              # ComputationGraph
        from ..nn.vertices import LayerVertex
        for name, (v, _ins) in model._vertex_map.items():
            p = model.params.get(name)
            if p is None:
                continue
            lyr = v.layer if isinstance(v, LayerVertex) else None
            out[name] = quantize_layer_params(lyr, p, report) \
                if lyr is not None else p
    else:                                          # MultiLayerNetwork
        for i, layer in enumerate(model.layers):
            si = str(i)
            p = model.params.get(si)
            if p is None:
                continue
            out[si] = quantize_layer_params(layer, p, report)
    return out, report


def quantized_bytes(tree) -> Tuple[int, int]:
    """(total_bytes, quantized_bytes) of a params tree — the HBM
    accounting ``memory_report``/``max_batch`` and the serving stats
    surface report."""
    total = q = 0
    for leaf in jax.tree.leaves(tree):
        n = _nbytes(leaf)
        total += n
        if np.dtype(leaf.dtype) == np.dtype(np.int8):
            q += n
    return total, q


# --------------------------------------------------------------------------
# graph op (the SameDiff rewrite target — autodiff/quantize.py)
# --------------------------------------------------------------------------

@register("quantize.int8_mmul", category="quantize", differentiable=False)
def int8_mmul(x, wq, w_scale):
    """Quantized-weight matmul graph op: the rewrite target of the
    SameDiff weight-quantization pass (``autodiff/quantize.py``),
    replacing a ``linalg.mmul`` whose right operand was a stored 2-D
    weight. ``wq`` int8 ``[in, out]`` constant, ``w_scale`` f32
    ``[out]``; the activation quantizes dynamically per call.
    Inference-only (rounding has no useful gradient — deploy-time
    transform, recorded in PARITY.md)."""
    return int8_matmul(x, wq, w_scale)


def counters() -> dict:
    """Dispatch-decision counts (trace-time, like
    ``flash_attention.counters``)."""
    return {k[0][1]: int(v) for k, v in _DISPATCH.series().items()}


def rewrite_counters() -> dict:
    return {k[0][1]: int(v) for k, v in _REWRITE.series().items()}


def reset_counters() -> None:
    _DISPATCH.zero()
    _REWRITE.zero()
