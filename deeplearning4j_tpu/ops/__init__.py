"""Op catalog and coverage ledger.

TPU-native equivalent of the libnd4j declarable-op registry + nd4j
``OpValidation`` coverage accounting (reference:
``libnd4j/include/ops/declarable/OpRegistrator.h``†,
``nd4j-api .../autodiff/validation/OpValidation.java``† per SURVEY.md
§2.1/§2.2; reference mount was empty, citations upstream-relative,
unverified).

Every public op in this package is a pure function over ``jax.Array``s,
registered here with a name and flags for whether a forward test and a
gradient test exist. ``coverage_report()`` mirrors OpValidation's accounting:
CI asserts that coverage never regresses (see ``tests/test_op_coverage.py``).

There is no dispatch machinery — XLA is the executor; the registry exists for
(a) test-coverage accounting, (b) the graph layer's name->callable lookup used
by serialization and import frontends.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional


@dataclasses.dataclass
class OpDef:
    name: str
    fn: Callable
    category: str = "misc"
    differentiable: bool = True


_REGISTRY: Dict[str, OpDef] = {}
_FWD_TESTED: set = set()
_GRAD_TESTED: set = set()


def register(name: str, category: str = "misc", differentiable: bool = True):
    """Decorator: register an op in the catalog."""

    def deco(fn):
        _REGISTRY[name] = OpDef(name=name, fn=fn, category=category,
                                differentiable=differentiable)
        return fn

    return deco


def get(name: str) -> OpDef:
    return _REGISTRY[name]


def lookup(name: str) -> Optional[Callable]:
    od = _REGISTRY.get(name)
    return od.fn if od else None


def all_ops() -> Dict[str, OpDef]:
    return dict(_REGISTRY)


def mark_fwd_tested(name: str) -> None:
    _FWD_TESTED.add(name)


def mark_grad_tested(name: str) -> None:
    _GRAD_TESTED.add(name)


def coverage_report() -> dict:
    """OpValidation-style accounting of which ops have fwd/grad tests."""
    total = len(_REGISTRY)
    diff = [n for n, d in _REGISTRY.items() if d.differentiable]
    return {
        "total_ops": total,
        "fwd_tested": sorted(_FWD_TESTED & set(_REGISTRY)),
        "grad_tested": sorted(_GRAD_TESTED & set(diff)),
        "fwd_untested": sorted(set(_REGISTRY) - _FWD_TESTED),
        "grad_untested": sorted(set(diff) - _GRAD_TESTED),
        "fwd_coverage": (len(_FWD_TESTED & set(_REGISTRY)) / total) if total else 1.0,
        "grad_coverage": (len(_GRAD_TESTED & set(diff)) / len(diff)) if diff else 1.0,
    }


# Import op modules so registration runs at package import.
from . import activations  # noqa: E402,F401
from . import losses  # noqa: E402,F401
from . import math  # noqa: E402,F401
from . import nnops  # noqa: E402,F401
from . import random  # noqa: E402,F401
from . import reduce  # noqa: E402,F401
from . import flash_attention  # noqa: E402,F401  (attention.fused_sdpa)
from . import fused_epilogues  # noqa: E402,F401  (epilogue.* fused kernels)
from . import quantize  # noqa: E402,F401  (quantize.int8_mmul)
from . import sampling  # noqa: E402,F401  (sampling.* decode-loop primitives)
