"""Loss functions.

TPU-native equivalent of nd4j's ``ILossFunction`` implementations (reference:
``nd4j-api .../linalg/lossfunctions/impl/``† per SURVEY.md §2.2 — LossMCXENT,
LossSparseMCXENT, LossBinaryXENT, MSE/L1/L2/MAE, Hinge, SquaredHinge, KLD,
Poisson, CosineProximity, MultiLabel, Wasserstein; reference mount was empty,
citations upstream-relative, unverified).

Contract (mirrors ILossFunction.computeScore semantics):
``fn(labels, predictions, mask=None, weights=None)`` -> scalar mean-per-example
score. ``predictions`` are post-activation outputs (DL4J passes
preOutput+activationFn; under autodiff the distinction is unnecessary — the
softmax+CE fusion DL4J hand-codes is done by XLA on the logits path in the
Output layer, which calls :func:`softmax_cross_entropy_with_logits` directly).
``mask``: per-example or per-timestep 0/1 mask broadcastable to labels' leading
dims. Gradient comes from ``jax.grad`` — DL4J's computeGradient methods have
no equivalent here by design.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import register

LOSSES = {}
EPS = 1e-7


def _loss(name):
    def deco(fn):
        LOSSES[name] = fn
        register(f"loss.{name}", category="loss")(fn)
        return fn
    return deco


def _per_example(value, mask):
    """Reduce per-example loss to a scalar: mean over (unmasked) examples.

    value: [batch] or [batch, time] per-example/per-timestep loss, already
    summed over the output dim. mask: 0/1, broadcastable to value's shape.
    DL4J averages over the count of unmasked examples/timesteps, not batch
    size — preserved here.
    """
    if mask is not None:
        mask = jnp.broadcast_to(jnp.asarray(mask, dtype=value.dtype), value.shape)
        # where, not multiply: a non-finite loss on a fully-masked example
        # (e.g. a zero-padded DP tail row overflowing an activation) must not
        # leak NaN into the sum (NaN * 0 = NaN) or the gradient
        masked = jnp.where(mask > 0, value * mask, 0.0)
        return jnp.sum(masked) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(value)


def combine_masks(a, b):
    """Intersect two 0/1 loss masks of possibly different ranks (e.g. a
    per-example [B] pad mask with a per-timestep [B,T] sequence mask):
    leading dims are aligned, trailing dims broadcast. None is identity."""
    if a is None:
        return b
    if b is None:
        return a
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    nd = max(a.ndim, b.ndim)
    a = a.reshape(a.shape + (1,) * (nd - a.ndim))
    b = b.reshape(b.shape + (1,) * (nd - b.ndim))
    return a * b


def _sum_outputs(elem, weights):
    """Sum per-element loss over the trailing (output) axis with optional weights."""
    if weights is not None:
        elem = elem * jnp.asarray(weights, dtype=elem.dtype)
    return jnp.sum(elem, axis=-1)


@_loss("mcxent")
def mcxent(labels, predictions, mask=None, weights=None):
    """Multi-class cross entropy on probabilities (LossMCXENT)."""
    p = jnp.clip(predictions, EPS, 1.0 - EPS)
    elem = -labels * jnp.log(p)
    return _per_example(_sum_outputs(elem, weights), mask)


@_loss("sparse_mcxent")
def sparse_mcxent(labels, predictions, mask=None, weights=None):
    """Sparse (integer-label) multi-class cross entropy (LossSparseMCXENT).

    ``weights``: per-class weights [num_classes]; each example's loss is
    scaled by its class weight (matches the dense-label weighting)."""
    p = jnp.clip(predictions, EPS, 1.0 - EPS)
    logp = jnp.log(p)
    lab = jnp.asarray(labels, dtype=jnp.int32)
    if lab.ndim == logp.ndim:
        lab = lab[..., 0]
    elem = -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]
    if weights is not None:
        elem = elem * jnp.take(jnp.asarray(weights, dtype=elem.dtype), lab)
    return _per_example(elem, mask)


def softmax_cross_entropy_with_logits(labels, logits, mask=None, weights=None):
    """Fused softmax+CE on logits — the numerically-stable Output-layer path.

    DL4J reaches the same fusion via LossMCXENT's special-cased softmax
    gradient (labels - softmax); here XLA derives it from log_softmax.
    """
    logp = jax.nn.log_softmax(logits, axis=-1)
    elem = -labels * logp
    return _per_example(_sum_outputs(elem, weights), mask)


register("loss.softmax_ce_logits", category="loss")(softmax_cross_entropy_with_logits)
LOSSES["softmax_ce_logits"] = softmax_cross_entropy_with_logits


@_loss("binary_xent")
def binary_xent(labels, predictions, mask=None, weights=None):
    """Binary cross entropy on probabilities (LossBinaryXENT)."""
    p = jnp.clip(predictions, EPS, 1.0 - EPS)
    elem = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p))
    return _per_example(_sum_outputs(elem, weights), mask)


def sigmoid_binary_xent_with_logits(labels, logits, mask=None, weights=None):
    """Fused sigmoid+BCE on logits."""
    elem = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return _per_example(_sum_outputs(elem, weights), mask)


register("loss.sigmoid_bce_logits", category="loss")(sigmoid_binary_xent_with_logits)
LOSSES["sigmoid_bce_logits"] = sigmoid_binary_xent_with_logits


@_loss("mse")
def mse(labels, predictions, mask=None, weights=None):
    """DL4J LossMSE = LossL2 / nOut (mean, not sum, over the output dim)."""
    elem = jnp.square(predictions - labels)
    return _per_example(_sum_outputs(elem, weights) / elem.shape[-1], mask)


@_loss("l2")
def l2(labels, predictions, mask=None, weights=None):
    # DL4J LossL2 = SUM of squared errors over the output dim (no 1/n).
    elem = jnp.square(predictions - labels)
    return _per_example(_sum_outputs(elem, weights), mask)


@_loss("mae")
def mae(labels, predictions, mask=None, weights=None):
    """DL4J LossMAE = LossL1 / nOut (mean, not sum, over the output dim)."""
    elem = jnp.abs(predictions - labels)
    return _per_example(_sum_outputs(elem, weights) / elem.shape[-1], mask)


@_loss("l1")
def l1(labels, predictions, mask=None, weights=None):
    # DL4J LossL1 = SUM of absolute errors over the output dim (no 1/n).
    elem = jnp.abs(predictions - labels)
    return _per_example(_sum_outputs(elem, weights), mask)


@_loss("hinge")
def hinge(labels, predictions, mask=None, weights=None):
    # labels in {-1, +1}
    elem = jnp.maximum(0.0, 1.0 - labels * predictions)
    return _per_example(_sum_outputs(elem, weights), mask)


@_loss("squared_hinge")
def squared_hinge(labels, predictions, mask=None, weights=None):
    elem = jnp.square(jnp.maximum(0.0, 1.0 - labels * predictions))
    return _per_example(_sum_outputs(elem, weights), mask)


@_loss("kld")
def kld(labels, predictions, mask=None, weights=None):
    p = jnp.clip(predictions, EPS, 1.0)
    q = jnp.clip(labels, EPS, 1.0)
    elem = labels * (jnp.log(q) - jnp.log(p))
    return _per_example(_sum_outputs(elem, weights), mask)


@_loss("poisson")
def poisson(labels, predictions, mask=None, weights=None):
    p = jnp.clip(predictions, EPS, None)
    elem = p - labels * jnp.log(p)
    return _per_example(_sum_outputs(elem, weights), mask)


@_loss("cosine_proximity")
def cosine_proximity(labels, predictions, mask=None, weights=None):
    if weights is not None:
        raise ValueError("cosine_proximity has no per-output weights "
                         "(loss is a whole-vector similarity)")
    ln = labels / jnp.maximum(jnp.linalg.norm(labels, axis=-1, keepdims=True), EPS)
    pn = predictions / jnp.maximum(jnp.linalg.norm(predictions, axis=-1, keepdims=True), EPS)
    elem = -jnp.sum(ln * pn, axis=-1)
    return _per_example(elem, mask)


@_loss("multi_label")
def multi_label(labels, predictions, mask=None, weights=None):
    """LossMultiLabel: pairwise ranking loss between positive & negative labels."""
    if weights is not None:
        raise ValueError("multi_label is a pairwise ranking loss; per-output "
                         "weights are not supported")
    pos = labels > 0.5
    neg = ~pos
    # score diff matrix per example: exp(neg_score - pos_score), normalized
    def per_example(y, p):
        diffs = jnp.exp(p[None, :] - p[:, None])  # [out, out]; diffs[i,j]=exp(p_j - p_i)
        m = (y[:, None] > 0.5) & (y[None, :] <= 0.5)  # pos i, neg j
        npos = jnp.maximum(jnp.sum(y > 0.5), 1)
        nneg = jnp.maximum(jnp.sum(y <= 0.5), 1)
        return jnp.sum(jnp.where(m, diffs, 0.0)) / (npos * nneg)

    elem = jax.vmap(per_example)(labels.reshape(-1, labels.shape[-1]),
                                 predictions.reshape(-1, predictions.shape[-1]))
    elem = elem.reshape(labels.shape[:-1])
    return _per_example(elem, mask)


@_loss("wasserstein")
def wasserstein(labels, predictions, mask=None, weights=None):
    # LossWasserstein: mean(labels * predictions) (critic loss form)
    elem = labels * predictions
    return _per_example(_sum_outputs(elem, weights), mask)


@_loss("fmeasure")
def fmeasure(labels, predictions, mask=None, weights=None, beta=1.0):
    """LossFMeasure: 1 - soft-F_beta on binary predictions. Batch-level
    (non-decomposable) like the reference — counts are summed over the
    whole (unmasked) batch, then one F score is formed; masks weight the
    counts rather than averaging per example."""
    if weights is not None:
        raise ValueError("fmeasure is a single-column batch-level loss; "
                         "per-output weights do not apply")
    y = labels[..., -1] if labels.shape[-1] > 1 else labels[..., 0]
    p = predictions[..., -1] if predictions.shape[-1] > 1 \
        else predictions[..., 0]
    if mask is not None:
        m = jnp.broadcast_to(jnp.asarray(mask, y.dtype), y.shape)
        y, p = y * m, p * m
    tp = jnp.sum(y * p)
    fp = jnp.sum((1.0 - y) * p)
    fn = jnp.sum(y * (1.0 - p))
    b2 = beta * beta
    f = (1.0 + b2) * tp / jnp.maximum((1.0 + b2) * tp + b2 * fn + fp, EPS)
    return 1.0 - f


@_loss("mixture_density")
def mixture_density(labels, predictions, mask=None, weights=None,
                    num_mixtures=None):
    """LossMixtureDensity: negative log-likelihood of an isotropic Gaussian
    mixture. Network output layout matches the reference:
    ``[alpha (K) | sigma (K) | mu (K*L)]`` with labels [.., L]; K inferred
    from the widths when not given (width = K*(2+L)).

    The sigma block is passed through ``exp`` — DL4J's LossMixtureDensity
    treats the network output as log-sigma (reference†
    nd4j …/lossfunctions/impl/LossMixtureDensity.java applies exp to the
    sigma slice; mount empty, unverified). An additive EPS floor keeps
    sigma**2 away from f32 underflow (exp alone hits 0 below logit ~-104,
    turning the nll into inf/NaN) while leaving gradients nonzero."""
    L = labels.shape[-1]
    width = predictions.shape[-1]
    K = num_mixtures or width // (2 + L)
    if K * (2 + L) != width:
        raise ValueError(f"output width {width} != K*(2+L) for labels "
                         f"width {L}")
    alpha = predictions[..., :K]
    sigma = jnp.exp(predictions[..., K:2 * K]) + EPS
    mu = predictions[..., 2 * K:].reshape(predictions.shape[:-1] + (K, L))
    log_pi = jax.nn.log_softmax(alpha, axis=-1)
    d2 = jnp.sum((labels[..., None, :] - mu) ** 2, axis=-1)     # [.., K]
    log_n = (-0.5 * d2 / (sigma ** 2)
             - L * jnp.log(sigma)
             - 0.5 * L * jnp.log(2.0 * jnp.pi))
    nll = -jax.nn.logsumexp(log_pi + log_n, axis=-1)
    return _per_example(nll, mask)


@_loss("ctc")
def ctc(labels, predictions, mask=None, weights=None, blank=0):
    """Connectionist Temporal Classification negative log-likelihood
    (libnd4j ``ctc_loss`` declarable op / cuDNN ctcLoss helper path† per
    SURVEY.md §2.1; mount empty, unverified).

    ``predictions``: [B, T, C] LOGITS (use activation="identity" on the
    loss layer; log_softmax is applied here, matching torch/cudnn).
    ``labels``: [B, S] integer class ids, padded with any NEGATIVE value;
    label lengths are the per-row count of non-negative entries. ``blank``
    is class 0 (torch/cudnn convention). ``mask``: optional [B, T] input
    mask; input lengths are its per-row sums (None = full length).

    Forward algorithm in log space as ONE ``lax.scan`` over time (the XLA
    shape: the [B, 2S+1] alpha lattice updates are fused elementwise +
    gathers). Gradients come from jax.grad through the scan — no
    hand-written beta recursion needed. Returns the batch MEAN of the
    per-sequence NLL (torch reduction='sum over lattice, mean over batch
    without length scaling' == reduction='none'.mean()).
    """
    lp = jax.nn.log_softmax(predictions, axis=-1)          # [B,T,C]
    B, T, C = lp.shape
    S = labels.shape[1]
    lab = jnp.maximum(labels, 0)
    label_len = jnp.sum(labels >= 0, axis=1)               # [B]
    if mask is None:
        input_len = jnp.full((B,), T, jnp.int32)
    else:
        input_len = jnp.sum(jnp.asarray(mask) > 0, axis=1).astype(jnp.int32)
    NEG = jnp.asarray(jnp.finfo(lp.dtype).min / 2, lp.dtype)

    # extended label sequence: blank, l1, blank, l2, ..., blank  [B, 2S+1]
    ext = jnp.full((B, 2 * S + 1), blank, lab.dtype)
    ext = ext.at[:, 1::2].set(lab)
    # skip transition k-2 -> k allowed when ext[k] is a label differing
    # from ext[k-2]
    ext_m2 = jnp.concatenate([jnp.full((B, 2), blank, lab.dtype),
                              ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_m2)
    can_skip = can_skip.at[:, :2].set(False)
    # positions beyond this row's 2*label_len are invalid lattice states
    pos = jnp.arange(2 * S + 1)[None, :]
    valid_state = pos <= 2 * label_len[:, None]

    emit0 = jnp.take_along_axis(lp[:, 0, :], ext, axis=1)  # [B, 2S+1]
    alpha0 = jnp.where(pos <= 1, emit0, NEG)
    alpha0 = jnp.where(valid_state, alpha0, NEG)

    def step(alpha, inp):
        lp_t, t = inp                                       # [B,C], scalar
        a1 = jnp.concatenate([jnp.full((B, 1), NEG, lp.dtype),
                              alpha[:, :-1]], axis=1)
        a2 = jnp.concatenate([jnp.full((B, 2), NEG, lp.dtype),
                              alpha[:, :-2]], axis=1)
        a2 = jnp.where(can_skip, a2, NEG)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2)
        emit = jnp.take_along_axis(lp_t, ext, axis=1)
        new = jnp.where(valid_state, merged + emit, NEG)
        active = (t < input_len)[:, None]                   # padded steps hold
        return jnp.where(active, new, alpha), None

    lps = jnp.moveaxis(lp[:, 1:, :], 1, 0)                  # [T-1,B,C]
    ts = jnp.arange(1, T, dtype=jnp.int32)
    alpha, _ = jax.lax.scan(step, alpha0, (lps, ts))

    idx_last = (2 * label_len)[:, None]                     # final blank
    idx_prev = jnp.maximum(2 * label_len - 1, 0)[:, None]   # final label
    a_last = jnp.take_along_axis(alpha, idx_last, axis=1)[:, 0]
    a_prev = jnp.where(label_len > 0,
                       jnp.take_along_axis(alpha, idx_prev, axis=1)[:, 0],
                       NEG)
    nll = -jnp.logaddexp(a_last, a_prev)                    # [B]
    if weights is not None:
        nll = nll * jnp.asarray(weights)
    # average over examples with at least one valid timestep: a fully
    # masked row (ParallelWrapper pad) must not leak its garbage NLL into
    # the batch mean — same contract as _per_example for the other losses
    return _per_example(nll, (input_len > 0).astype(nll.dtype)
                        if mask is not None else None)


def get(name_or_fn):
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower()
    if key not in LOSSES:
        raise ValueError(f"Unknown loss {name_or_fn!r}; known: {sorted(LOSSES)}")
    return LOSSES[key]


def name_of(fn) -> str:
    for k, v in LOSSES.items():
        if v is fn:
            return k
    raise ValueError(f"Unregistered loss {fn}")
