"""Pallas block-shape autotuner for the flash-attention kernel (ISSUE 7).

The kernel shipped with ``block_q = block_k = 128`` hardcoded — the right
tile for bert-shaped f32 at seq 1024, and a guess everywhere else. The TVM
line of work (PAPERS.md, 1802.04799) says the honest way out is the boring
one: enumerate the feasible schedule space, MEASURE each candidate on the
device, and cache the winner per shape key so the sweep runs once. This
module is that loop for the one schedule knob the flash kernel exposes,
its (block_q, block_k) tiling:

- **Key**: ``(Tq, Tk, head_dim, dtype, has_bias)`` — the quantities that
  change the kernel's grid, VMEM footprint, and MXU utilization. Batch and
  head count only scale the embarrassingly-parallel grid dimension and are
  normalized out of the sweep (relative block ranking transfers).
- **Candidates**: the largest few multiple-of-8 divisor blocks per axis
  (``axis_blocks``), cross-producted and filtered through the kernel's own
  ``fits_vmem_attention`` guard — every candidate is a shape the dispatcher
  itself would accept.
- **Measurement**: each candidate compiles the REAL train-shaped work
  (forward + custom-VJP backward through ``_flash``) and is timed with a
  forced host readback (``block_until_ready`` is unreliable on this PJRT
  plugin — same posture as bench.py); min over repeats. Sweeps only run on
  TPU — a CPU "timing" of the Pallas interpreter would tune for the
  interpreter — except when a test explicitly passes ``interpret=True`` to
  exercise the sweep machinery itself (marked slow in the suite).
- **Cache**: process-lifetime dict, persistable to disk as JSON the same
  way the serving engine's AOT bucket cache makes warmup a once-per-deploy
  cost (``DL4J_TPU_AUTOTUNE_CACHE=<path>`` auto-loads before the first
  lookup and auto-saves after every sweep). A key with no sweep yet is
  seeded with the dispatcher's classic target-128 defaults and marked
  ``source="default"`` — CPU/tier-1 runs therefore NEVER sweep (guarded by
  a regression test) and behave exactly as before this module existed.

Observability (ISSUE 7 satellite): every sweep compile goes through the
retrace tracker as ``record_compile("flash_attention.autotune",
cause="autotune")`` so warm-cache steady state keeps its zero-compile
assertion, and every lookup outcome bumps the
``flash_attention.autotune{event=}`` registry counter
(hit / default / sweep / sweep_candidate).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..runtime import telemetry as _tel

#: largest block the candidate enumeration will consider per axis
MAX_BLOCK = 256
#: candidates per axis (the largest N feasible divisor blocks)
AXIS_CANDIDATES = 4

_EVENTS = _tel.counter(
    "flash_attention.autotune",
    "block-shape autotuner events (hit / default / sweep / sweep_candidate)")
_EP_EVENTS = _tel.counter(
    "fused_epilogues.autotune",
    "epilogue row-block autotuner events (hit / default / sweep / "
    "sweep_candidate)")

_lock = threading.RLock()
_cache: Dict[tuple, dict] = {}
_env_cache_loaded = False
_state = {"mode": os.environ.get("DL4J_TPU_AUTOTUNE", "auto")}


def mode() -> str:
    return _state["mode"]


def set_mode(m: str) -> str:
    """"auto" (cache miss on TPU with concrete operands sweeps inline),
    "off" (never sweep — cache hits and target-128 defaults only; explicit
    :func:`sweep` calls still work). Returns the previous mode."""
    if m not in ("auto", "off"):
        raise ValueError(f"autotune mode {m!r} not in ('auto', 'off')")
    old = _state["mode"]
    _state["mode"] = m
    return old


def counters() -> dict:
    """Lookup/sweep outcome counts — a view over the registry's
    ``flash_attention.autotune{event=}`` counter."""
    return {k: int(_EVENTS.value(event=k))
            for k in ("hit", "default", "sweep", "sweep_candidate")}


def reset_counters() -> None:
    _EVENTS.zero()


def epilogue_counters() -> dict:
    """Epilogue-tuner outcome counts — a view over the registry's
    ``fused_epilogues.autotune{event=}`` counter (ISSUE 16)."""
    return {k: int(_EP_EVENTS.value(event=k))
            for k in ("hit", "default", "sweep", "sweep_candidate")}


def reset_epilogue_counters() -> None:
    _EP_EVENTS.zero()


# ----------------------------------------------------------------- keys
def cache_key(tq: int, tk: int, d: int, dtype, has_bias: bool,
              decode: bool = False, page: int = 0) -> tuple:
    """``decode=True`` keys the decode kernel's tiling (block_q pinned to
    Tq — 1 for single-query decode, k for the speculative multi-query
    verify; only the cache-axis block is tuned) separately from the
    one-shot kernel — the same (Tq, Tk) shape prefers very different
    schedules when the query side is a handful of rows. ``page`` (paged
    KV serving, ISSUE 12): the cache is a page-table gather at this page
    granularity, so the winning cache-axis block differs from a
    contiguous cache of the same length — page size is part of the key
    (``page0`` = contiguous)."""
    base = (int(tq), int(tk), int(d), str(np.dtype(dtype)), bool(has_bias))
    if decode:
        base = base + ("decode",)
    if page:
        base = base + (f"page{int(page)}",)
    return base


def axis_blocks(t: int, cap: int = MAX_BLOCK,
                limit: int = AXIS_CANDIDATES) -> List[int]:
    """The largest ``limit`` multiple-of-8 blocks <= ``cap`` that divide
    ``t`` — the per-axis candidate set (descending)."""
    out: List[int] = []
    b = min(int(cap), int(t))
    b -= b % 8
    while b >= 8 and len(out) < limit:
        if t % b == 0:
            out.append(b)
        b -= 8
    return out


def candidates(tq: int, tk: int, d: int, itemsize: int = 4,
               decode: bool = False) -> List[Tuple[int, int]]:
    """VMEM-feasible (block_q, block_k) candidates for one key — the cross
    product of the per-axis divisor blocks filtered through the kernel's
    ``fits_vmem_attention`` budget (every candidate is dispatchable).
    Decode keys pin ``block_q = 1`` (the kernel runs one query row) and
    enumerate only the cache-axis blocks."""
    from . import flash_attention as _fa
    out = []
    # decode keys pin the query block to the whole (small) query window:
    # 1 for single-query decode, k for the speculative Tq=k verify
    q_blocks = [int(tq)] if decode else axis_blocks(tq)
    for bq in q_blocks:
        for bk in axis_blocks(tk):
            if _fa.fits_vmem_attention(bq, bk, d, itemsize):
                out.append((bq, bk))
    return out


def _default_blocks(tq: int, tk: int,
                    decode: bool = False) -> Optional[Tuple[int, int]]:
    from . import flash_attention as _fa
    bq = int(tq) if decode else _fa.pick_block(tq)
    bk = _fa.pick_block(tk)
    if bq is None or bk is None:
        return None
    return bq, bk


# ---------------------------------------------------------------- cache
def atomic_json_save(path: str, snap: dict) -> str:
    """Persist a JSON-able cache snapshot via tmp+rename — a torn write
    must never corrupt the next process's load. Shared persistence
    discipline for the sweep-and-cache tuners (this module's flash-block
    cache and ``runtime/schedule.py``'s joint schedule cache)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(snap, f, indent=1)
    os.replace(tmp, path)
    return path


def _cache_path() -> Optional[str]:
    p = os.environ.get("DL4J_TPU_AUTOTUNE_CACHE", "")
    return p or None


def _ensure_loaded() -> None:
    global _env_cache_loaded
    if _env_cache_loaded:
        return
    _env_cache_loaded = True
    p = _cache_path()
    if p and os.path.exists(p):
        try:
            load(p)
        except (OSError, ValueError, KeyError):
            pass  # a corrupt cache file must never block dispatch


def lookup(tq, tk, d, dtype, has_bias,
           decode: bool = False, page: int = 0) -> Optional[dict]:
    """The cache entry for a key, or None (no counter bump)."""
    with _lock:
        _ensure_loaded()
        e = _cache.get(cache_key(tq, tk, d, dtype, has_bias, decode, page))
        return dict(e) if e else None


def _valid_blocks(blocks, tq, tk, d, dtype, decode: bool = False) -> bool:
    """A cache entry's blocks must be usable for ITS key: multiple-of-8
    divisors within the VMEM budget (decode keys: ``block_q`` exactly the
    query-window size — the whole small-Tq grid row). Guards against
    stale/hand-edited disk caches — an invalid pair would silently
    truncate the kernel grid (``Tq // bq``) and produce wrong attention
    output."""
    from . import flash_attention as _fa
    try:
        bq, bk = int(blocks[0]), int(blocks[1])
    except (TypeError, ValueError, IndexError):
        return False
    q_ok = bq == int(tq) if decode \
        else (bq >= 8 and bq % 8 == 0 and tq % bq == 0)
    return (q_ok and bk >= 8 and bk % 8 == 0 and tk % bk == 0
            and _fa.fits_vmem_attention(bq, bk, d,
                                        np.dtype(dtype).itemsize))


def get_blocks(tq, tk, d, dtype, has_bias, *, concrete: bool = False,
               decode: bool = False, page: int = 0
               ) -> Optional[Tuple[int, int]]:
    """(block_q, block_k) for one attention shape key.

    A SWEPT cache hit returns the stored blocks. A miss (or a
    default-seeded entry) seeds and returns the classic target-128
    defaults — UNLESS ``concrete=True`` (the operands are real arrays,
    not tracers), the mode is "auto" and the backend is TPU, in which
    case it sweeps inline and returns the winner (a default seed left by
    an earlier traced dispatch is UPGRADED, not pinned forever). Dispatch
    under ``jit`` always passes ``concrete=False``: a sweep cannot run
    mid-trace, so warm the cache first (``warmup``/``sweep``/disk cache)
    to tune traced programs. Returns None when nothing tiles (caller
    falls back). Invalid entries (corrupt/stale disk cache) are dropped,
    never served. ``decode=True`` keys the decode kernels (``tq`` = the
    query window: 1 or the speculative k); ``page`` keys the paged-KV
    gather granularity separately from a contiguous cache."""
    key = cache_key(tq, tk, d, dtype, has_bias, decode, page)
    can_sweep = (concrete and _state["mode"] == "auto"
                 and jax.default_backend() == "tpu")
    with _lock:
        _ensure_loaded()
        e = _cache.get(key)
        if e is not None and not _valid_blocks(e.get("blocks"),
                                               tq, tk, d, dtype, decode):
            del _cache[key]
            e = None
        # only a REAL timing sweep is authoritative on TPU: default seeds
        # AND interpreter-"swept" entries (whose timings tune nothing) are
        # upgraded when a real sweep is possible
        if e is not None and not (can_sweep
                                  and e.get("source") != "sweep"):
            _EVENTS.inc(event="hit")
            return tuple(e["blocks"])
    if can_sweep:
        e = sweep(tq, tk, d, dtype, has_bias, decode=decode, page=page)
        return tuple(e["blocks"]) if e else None
    default = _default_blocks(tq, tk, decode)
    if default is None:
        return None
    with _lock:
        # pre-seed so repeated lookups are hits and CPU runs never sweep
        _cache.setdefault(key, {"blocks": list(default), "source": "default"})
    _EVENTS.inc(event="default")
    return default


# ------------------------------------------------- fused-epilogue keys
# The epilogue kernels (ops/fused_epilogues.py) expose one schedule knob:
# the row-block size of the (rows // block,) grid. Same sweep-and-cache
# discipline as the attention keys, same disk file, distinct key prefix
# ("epilogue", kind, rows, cols, dtype) and a distinct registry counter so
# the two kernel families' tuner health is separable on /metrics.

def epilogue_cache_key(kind: str, rows: int, cols: int, dtype) -> tuple:
    return ("epilogue", str(kind), int(rows), int(cols),
            str(np.dtype(dtype)))


def epilogue_candidates(kind: str, rows: int, cols: int,
                        dtype) -> List[int]:
    """Feasible row blocks for one epilogue key (descending): the largest
    few sublane-multiple divisors of ``rows`` that fit the kernel's VMEM
    budget — every candidate is a shape the dispatcher would accept."""
    from . import fused_epilogues as _fe
    mult = _fe._row_mult(dtype)
    itemsize = np.dtype(dtype).itemsize
    out: List[int] = []
    b = min(MAX_BLOCK, int(rows))
    b -= b % mult
    while b >= mult and len(out) < AXIS_CANDIDATES:
        if rows % b == 0 and _fe.fits_vmem_epilogue(b, cols, itemsize, kind):
            out.append(b)
        b -= mult
    return out


def _valid_epilogue_blocks(blocks, kind, rows, cols, dtype) -> bool:
    from . import fused_epilogues as _fe
    try:
        br = int(blocks[0])
    except (TypeError, ValueError, IndexError):
        return False
    mult = _fe._row_mult(dtype)
    return (br >= mult and br % mult == 0 and rows % br == 0
            and _fe.fits_vmem_epilogue(br, cols,
                                       np.dtype(dtype).itemsize, kind))


def epilogue_blocks(kind: str, rows: int, cols: int, dtype, *,
                    concrete: bool = False) -> Optional[int]:
    """Row block for one epilogue key — the :func:`get_blocks` contract
    (swept hit > inline sweep when concrete on TPU > seeded default),
    scalar-valued since the epilogue grid has one axis. Returns None when
    nothing tiles (the dispatcher already guarded, so only for degenerate
    keys)."""
    from . import fused_epilogues as _fe
    key = epilogue_cache_key(kind, rows, cols, dtype)
    can_sweep = (concrete and _state["mode"] == "auto"
                 and jax.default_backend() == "tpu")
    with _lock:
        _ensure_loaded()
        e = _cache.get(key)
        if e is not None and not _valid_epilogue_blocks(
                e.get("blocks"), kind, rows, cols, dtype):
            del _cache[key]
            e = None
        if e is not None and not (can_sweep and e.get("source") != "sweep"):
            _EP_EVENTS.inc(event="hit")
            return int(e["blocks"][0])
    if can_sweep:
        e = epilogue_sweep(kind, rows, cols, dtype)
        return int(e["blocks"][0]) if e else None
    default = _fe.row_block(rows, _fe._row_mult(dtype))
    if default is None:
        return None
    with _lock:
        _cache.setdefault(key, {"blocks": [int(default)],
                                "source": "default"})
    _EP_EVENTS.inc(event="default")
    return default


def _time_epilogue_candidate(kind, rows, cols, dtype, br, interpret,
                             repeats: int) -> float:
    """Seconds (min over repeats) for one fwd+bwd through the epilogue
    kernel at row block ``br`` on synthetic operands."""
    from . import fused_epilogues as _fe
    rng = np.random.default_rng(0)
    x2 = jnp.asarray(rng.normal(size=(rows, cols)) * 0.5, dtype)
    v1 = jnp.asarray(rng.normal(size=(1, cols)) * 0.5,
                     jnp.float32 if kind == "affine" else dtype)
    v2 = jnp.asarray(rng.normal(size=(1, cols)) * 0.5, v1.dtype)

    if kind == "ln":
        def loss(x_, g_, b_):
            y = _fe._ln_act(x_, g_, b_, 1e-6, "gelu", br, interpret)
            return jnp.sum(y.astype(jnp.float32))
    else:
        def loss(x_, g_, b_):
            y = _fe._affine_act(x_, g_, b_, "relu", br, interpret)
            return jnp.sum(y.astype(jnp.float32))

    fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    _tel.record_compile("fused_epilogues.autotune", "autotune",
                        blocks=[int(br)], kind=str(kind),
                        rows=int(rows), cols=int(cols))
    _EP_EVENTS.inc(event="sweep_candidate")

    def run():
        gs = fn(x2, v1, v2)
        return float(jnp.sum(gs[0].astype(jnp.float32)))  # force readback

    run()  # compile + settle
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best


def epilogue_sweep(kind: str, rows: int, cols: int, dtype, *,
                   interpret: bool = False,
                   repeats: int = 3) -> Optional[dict]:
    """Measure every candidate row block for one epilogue key and cache
    the winner — the :func:`sweep` contract (TPU-only unless
    ``interpret=True``; interpreter entries tagged for re-sweep)."""
    if not interpret and jax.default_backend() != "tpu":
        raise RuntimeError(
            "autotune.epilogue_sweep() timings are only meaningful on TPU; "
            "CPU runs use pre-seeded defaults (pass interpret=True to "
            "exercise the sweep machinery in tests)")
    cands = epilogue_candidates(kind, rows, cols, dtype)
    if not cands:
        return None
    timings = []
    for br in cands:
        dt = _time_epilogue_candidate(kind, rows, cols, dtype, br,
                                      interpret, repeats)
        timings.append({"blocks": [int(br)], "us": round(dt * 1e6, 2)})
    best = min(timings, key=lambda t: t["us"])
    entry = {
        "blocks": best["blocks"],
        "source": "sweep_interpret" if interpret else "sweep",
        "us": best["us"],
        "candidates": timings,
        "backend": jax.default_backend(),
    }
    key = epilogue_cache_key(kind, rows, cols, dtype)
    with _lock:
        _cache[key] = entry
    _EP_EVENTS.inc(event="sweep")
    if _cache_path():
        try:
            save()
        except OSError:
            pass  # persistence is best-effort; the process cache holds
    return dict(entry)


def _norm_shape(shape) -> tuple:
    """Normalize a warmup/seed shape spec: 5-tuples are one-shot keys,
    6-tuples carry a trailing decode flag."""
    if len(shape) == 5:
        return tuple(shape) + (False,)
    tq, tk, d, dtype, has_bias, decode = shape
    return (tq, tk, d, dtype, has_bias, bool(decode))


def seed_defaults(shapes) -> None:
    """Pre-seed target-128 defaults for an iterable of
    ``(Tq, Tk, head_dim, dtype, has_bias[, decode])`` keys (no sweeps —
    the CPU/CI posture; on TPU use :func:`warmup`)."""
    for shape in shapes:
        tq, tk, d, dtype, has_bias, decode = _norm_shape(shape)
        get_blocks(tq, tk, d, dtype, has_bias, concrete=False,
                   decode=decode)


def warmup(shapes, *, interpret: bool = False) -> dict:
    """Sweep every unswept key in ``shapes`` (same tuples as
    :func:`seed_defaults`) — the serving-warmup analogue: pay every sweep
    before traffic/timing so steady state stays zero-compile. Keys whose
    cache entry is only a default SEED (e.g. left by an earlier traced
    dispatch) are swept too, not skipped. Off-TPU (unless
    ``interpret=True``), or under mode "off", missing keys seed defaults
    instead of sweeping. Returns {key: entry} for the keys swept."""
    out = {}
    can_sweep = interpret or (jax.default_backend() == "tpu"
                              and _state["mode"] == "auto")
    # what counts as already-tuned: a real sweep always; an interpreter
    # "sweep" only for another interpret warmup (its timings tune nothing
    # on a real chip — a TPU warmup re-sweeps it, per sweep()'s contract)
    done_sources = ("sweep", "sweep_interpret") if interpret else ("sweep",)
    for shape in shapes:
        tq, tk, d, dtype, has_bias, decode = _norm_shape(shape)
        e = lookup(tq, tk, d, dtype, has_bias, decode)
        if can_sweep and (e is None or
                          e.get("source") not in done_sources):
            out[cache_key(tq, tk, d, dtype, has_bias, decode)] = \
                sweep(tq, tk, d, dtype, has_bias, interpret=interpret,
                      decode=decode)
        else:
            get_blocks(tq, tk, d, dtype, has_bias, concrete=False,
                       decode=decode)
    return out


def reset() -> None:
    """Drop the in-process cache (disk files untouched)."""
    global _env_cache_loaded
    with _lock:
        _cache.clear()
        _env_cache_loaded = True  # a reset cache stays reset (tests)


def save(path: Optional[str] = None) -> Optional[str]:
    """Persist the cache as JSON (tmp+rename — a torn write must not
    corrupt the next process's load). Returns the path written, or None
    when no path is configured."""
    path = path or _cache_path()
    if not path:
        return None
    return atomic_json_save(path, cache_snapshot())


def load(path: Optional[str] = None, merge: bool = True) -> int:
    """Load a JSON cache file; ``merge=False`` replaces the in-process
    cache. Swept disk entries win over in-process default seeds; in-process
    sweeps win over disk defaults. Returns the entry count loaded."""
    path = path or _cache_path()
    if not path:
        return 0
    with open(path) as f:
        snap = json.load(f)
    n = 0
    with _lock:
        if not merge:
            _cache.clear()
        for ent in snap.get("entries", []):
            raw = ent["key"]
            if str(raw[0]) == "epilogue":
                kind, rows, cols = str(raw[1]), int(raw[2]), int(raw[3])
                dt = str(raw[4])
                key = epilogue_cache_key(kind, rows, cols, dt)
                if not _valid_epilogue_blocks(ent.get("blocks"), kind,
                                              rows, cols, dt):
                    continue  # stale/hand-edited entry: never serve it
                cur = _cache.get(key)
                if cur is not None and cur.get("source") != "default" \
                        and ent.get("source") == "default":
                    continue
                _cache[key] = {k: v for k, v in ent.items() if k != "key"}
                n += 1
                continue
            tail = [str(x) for x in raw[5:]]
            decode = "decode" in tail
            page = next((int(t[4:]) for t in tail
                         if t.startswith("page") and t[4:].isdigit()), 0)
            key = cache_key(int(raw[0]), int(raw[1]), int(raw[2]),
                            str(raw[3]), bool(raw[4]), decode, page)
            if not _valid_blocks(ent.get("blocks"), key[0], key[1],
                                 key[2], key[3], decode):
                continue  # stale/hand-edited entry: never serve it
            cur = _cache.get(key)
            if cur is not None and cur.get("source") != "default" \
                    and ent.get("source") == "default":
                continue
            _cache[key] = {k: v for k, v in ent.items() if k != "key"}
            n += 1
    return n


def cache_snapshot() -> dict:
    """JSON-able view of the cache — embedded in bench artifacts so the
    blocks behind a kernel metric are part of the record."""
    with _lock:
        entries = [{"key": list(k), **v} for k, v in sorted(_cache.items())]
    return {"version": 1, "backend": jax.default_backend(),
            "entries": entries}


# ---------------------------------------------------------------- sweep
_SWEEP_GRID_ROWS = 16  # synthetic B*H: enough grid rows to fill the chip's
#                        cores; relative block ranking transfers to real B*H


def _time_candidate(tq, tk, d, dtype, has_bias, bq, bk, interpret,
                    repeats: int, decode: bool = False) -> float:
    """Seconds (min over repeats) for one fwd+bwd at (bq, bk) on synthetic
    operands — forward-only for ``decode`` keys (decode never trains).
    The compile is reported to the retrace tracker BEFORE the first call
    so a hung compile is still visible in compile_events()."""
    from . import flash_attention as _fa
    rng = np.random.default_rng(0)
    heads = 4
    g = _SWEEP_GRID_ROWS
    batch = g // heads
    scale = 1.0 / float(np.sqrt(d))
    q3 = jnp.asarray(rng.normal(size=(g, tq, d)) * 0.5, dtype)
    k3 = jnp.asarray(rng.normal(size=(g, tk, d)) * 0.5, dtype)
    v3 = jnp.asarray(rng.normal(size=(g, tk, d)) * 0.5, dtype)
    kb = None
    if has_bias:
        mask = np.ones((batch, tk), np.float32)
        mask[:, tk - tk // 8:] = 0.0
        kb = jnp.where(jnp.asarray(mask) > 0, 0.0,
                       np.float32(np.finfo(np.float32).min))

    if decode:
        # the serving decode hot path: single/multi-query forward, ragged
        # cache occupancy as the mask (the same program decode_attention /
        # decode_multiquery_attention runs; tq > 1 = speculative verify)
        lo = max(1, min(tk // 2, max(1, tk - tq)))
        hi = max(lo + 1, tk - tq + 2)
        lengths = jnp.asarray(rng.integers(lo, hi, size=(batch,)), jnp.int32)

        if tq > 1:
            lens2 = jnp.broadcast_to(lengths[:, None], (batch, _fa._LANES)
                                     ).astype(jnp.int32)

            def fwd(q_, k_, v_):
                o = _fa._mq_impl(q_, k_, v_, lens2, scale, heads,
                                 bk, interpret)
                return (o,)
        else:
            kbd = _fa.length_bias(lengths, tk)

            def fwd(q_, k_, v_):
                o, _, _ = _fa._fwd_impl(q_, k_, v_, kbd, scale, heads,
                                        bq, bk, interpret)
                return (o,)  # tuple like grad's output: run() reads gs[0]

        fn = jax.jit(fwd)
    else:
        def loss(q_, k_, v_):
            o = _fa._flash(q_, k_, v_, kb, scale, heads, bq, bk, interpret)
            return jnp.sum(o.astype(jnp.float32))

        fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    _tel.record_compile("flash_attention.autotune", "autotune",
                        blocks=[int(bq), int(bk)], tq=int(tq), tk=int(tk),
                        decode=bool(decode))
    _EVENTS.inc(event="sweep_candidate")

    def run():
        gs = fn(q3, k3, v3)
        return float(jnp.sum(gs[0].astype(jnp.float32)))  # force readback

    run()  # compile + settle
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        run()
        best = min(best, time.perf_counter() - t0)
    return best


def sweep(tq, tk, d, dtype, has_bias, *, interpret: bool = False,
          repeats: int = 3, decode: bool = False,
          page: int = 0) -> Optional[dict]:
    """Measure every candidate block shape for one key and cache the
    winner. TPU-only unless ``interpret=True`` (the slow-marked test path:
    exercises the sweep machinery through the Pallas interpreter, whose
    "timings" tune nothing — the entry is tagged so a real chip re-sweeps).
    ``decode=True`` sweeps the single-query decode kernel (forward only,
    block_q pinned to 1). Returns the cache entry, or None when nothing
    tiles."""
    if not interpret and jax.default_backend() != "tpu":
        raise RuntimeError(
            "autotune.sweep() timings are only meaningful on TPU; CPU runs "
            "use pre-seeded defaults (pass interpret=True to exercise the "
            "sweep machinery through the Pallas interpreter in tests)")
    itemsize = np.dtype(dtype).itemsize
    cands = candidates(tq, tk, d, itemsize, decode=decode)
    if not cands:
        return None
    timings = []
    for bq, bk in cands:
        dt = _time_candidate(tq, tk, d, dtype, has_bias, bq, bk,
                             interpret, repeats, decode=decode)
        timings.append({"blocks": [int(bq), int(bk)],
                        "us": round(dt * 1e6, 2)})
    best = min(timings, key=lambda t: t["us"])
    entry = {
        "blocks": best["blocks"],
        "source": "sweep_interpret" if interpret else "sweep",
        "us": best["us"],
        "candidates": timings,
        "backend": jax.default_backend(),
    }
    key = cache_key(tq, tk, d, dtype, has_bias, decode, page)
    with _lock:
        _cache[key] = entry
    _EVENTS.inc(event="sweep")
    if _cache_path():
        try:
            save()
        except OSError:
            pass  # persistence is best-effort; the process cache holds
    return dict(entry)
