"""Activation functions.

TPU-native equivalent of nd4j's ``IActivation`` implementations (reference:
``nd4j-api .../linalg/activations/impl/``† — ~25 classes, per SURVEY.md §2.2;
reference mount was empty, citation upstream-relative, unverified).

Each is a pure elementwise function; XLA fuses them into the surrounding
matmul/conv epilogue, so there is no per-activation kernel (the whole reason
DL4J needed IActivation.backprop methods disappears under autodiff).
Names mirror the DL4J activation enum (``Activation.RELU`` etc.) and are the
strings used in config JSON round-trips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import register

# name -> callable; populated by _act
ACTIVATIONS = {}


def _act(name):
    def deco(fn):
        ACTIVATIONS[name] = fn
        register(f"act.{name}", category="activation")(fn)
        return fn
    return deco


@_act("identity")
def identity(x):
    return x


@jax.custom_jvp
def _relu_outgrad(x):
    return jnp.maximum(x, 0)


@_relu_outgrad.defjvp
def _relu_outgrad_jvp(primals, tangents):
    # Gradient mask from the OUTPUT (y > 0), not the input: the output is
    # materialized anyway (it feeds the next layer), so reverse-mode saves no
    # residual and the pre-activation can die inside its producing fusion.
    # Cuts one full activation write+read per conv/BN/relu block on TPU
    # (measured: ~7% step time on ResNet-50). Same subgradient as
    # jax.nn.relu: zero at x == 0.
    (x,), (t,) = primals, tangents
    y = jnp.maximum(x, 0)
    return y, jnp.where(y > 0, t, jnp.zeros_like(t))


@_act("relu")
def relu(x):
    return _relu_outgrad(x)


@_act("relu6")
def relu6(x):
    return jnp.minimum(jax.nn.relu(x), 6.0)


@_act("leakyrelu")
def leakyrelu(x, alpha=0.01):
    # DL4J LeakyReLU default alpha = 0.01
    return jnp.where(x >= 0, x, alpha * x)


@_act("thresholdedrelu")
def thresholdedrelu(x, theta=1.0):
    return jnp.where(x > theta, x, 0.0)


@_act("elu")
def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


@_act("selu")
def selu(x):
    return jax.nn.selu(x)


@_act("gelu")
def gelu(x, approximate=True):
    # DL4J GELU is the tanh approximation (matches original paper impl);
    # ONNX opset-20 Gelu defaults to the exact erf form (approximate=False).
    return jax.nn.gelu(x, approximate=approximate)


@_act("swish")
def swish(x):
    return jax.nn.silu(x)


@_act("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))


@_act("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@_act("hardsigmoid")
def hardsigmoid(x):
    # DL4J HardSigmoid: clamp(0.2*x + 0.5, 0, 1)
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


@_act("tanh")
def tanh(x):
    return jnp.tanh(x)


@_act("hardtanh")
def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


@_act("rationaltanh")
def rationaltanh(x):
    # DL4J RationalTanh: 1.7159 * tanh_approx(2x/3) with rational approx
    # f(x) = 1.7159 * sgn(x) * (1 - 1/(1 + |c*x| + (c*x)^2 + 1.41645*(c*x)^4))
    cx = jnp.abs(2.0 * x / 3.0)
    a = 1.0 + cx + cx * cx + 1.41645 * cx ** 4
    return 1.7159 * jnp.sign(x) * (1.0 - 1.0 / a)


@_act("recttanh")
def recttanh(x):
    # Rectified tanh: max(0, tanh(x))
    return jnp.maximum(0.0, jnp.tanh(x))


@_act("softmax")
def softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


@_act("logsoftmax")
def logsoftmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


@_act("softmax_onnx_legacy")
def softmax_onnx_legacy(x, axis=1, log=False):
    """ONNX opset<13 Softmax semantics: flatten to 2D at ``axis``
    (coerce [d0..dn] -> [prod(:axis), prod(axis:)]), softmax over the
    second dim, reshape back. Shapes resolve at trace time, so importers
    can emit this without knowing intermediate ranks."""
    shape = x.shape
    ax = axis if axis >= 0 else len(shape) + axis
    lead = 1
    for s in shape[:ax]:
        lead *= int(s)
    flat = x.reshape(lead, -1)
    y = jax.nn.log_softmax(flat, axis=-1) if log else \
        jax.nn.softmax(flat, axis=-1)
    return y.reshape(shape)


@_act("softplus")
def softplus(x):
    return jax.nn.softplus(x)


@_act("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@_act("cube")
def cube(x):
    return x ** 3


@_act("rrelu")
def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, key=None):
    """Randomized leaky ReLU (DL4J ``ActivationRReLU``): negative slope
    drawn U(lower, upper) per element when a PRNG ``key`` is given (the
    training mode), fixed at the mean slope otherwise (inference — also
    what the plain activation-string path uses, since activation fns are
    pure; pass a key explicitly for the stochastic mode, the same rng
    plumbing dropout uses)."""
    alpha = ((lower + upper) / 2.0 if key is None
             else jax.random.uniform(key, x.shape, dtype=x.dtype,
                                     minval=lower, maxval=upper))
    return jnp.where(x >= 0, x, alpha * x)


def get(name_or_fn):
    """Resolve an activation by DL4J-style name (case-insensitive) or passthrough."""
    if callable(name_or_fn):
        return name_or_fn
    key = str(name_or_fn).lower().replace("_", "")
    if key not in ACTIVATIONS:
        raise ValueError(f"Unknown activation {name_or_fn!r}; known: {sorted(ACTIVATIONS)}")
    return ACTIVATIONS[key]


def name_of(fn) -> str:
    for k, v in ACTIVATIONS.items():
        if v is fn:
            return k
    raise ValueError(f"Unregistered activation {fn}")
