"""Random-distribution ops + image ops + CTC loss.

TPU-native equivalents of libnd4j's ``declarable/generic/random``,
``declarable/generic/images`` and the cuDNN CTC helper path (reference:
``libnd4j/include/ops/declarable/generic/{random,images}/``† per SURVEY.md
§2.1; reference mount was empty, citations upstream-relative, unverified).

Random ops take an explicit threefry key (functional RNG — the TPU-native
contract; DL4J's stateful Nd4jRandom maps to rng.py's seeded key streams).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from . import register

# -- random distributions ----------------------------------------------------
register("random.normal", category="random", differentiable=False)(
    lambda key, shape, dtype=jnp.float32: jax.random.normal(key, shape, dtype))
register("random.uniform", category="random", differentiable=False)(
    lambda key, shape, minval=0.0, maxval=1.0, dtype=jnp.float32:
    jax.random.uniform(key, shape, dtype, minval, maxval))
register("random.bernoulli", category="random", differentiable=False)(
    lambda key, p, shape: jax.random.bernoulli(key, p, shape))
register("random.gamma", category="random", differentiable=False)(
    lambda key, alpha, shape=None: jax.random.gamma(key, alpha, shape))
register("random.poisson", category="random", differentiable=False)(
    lambda key, lam, shape=None: jax.random.poisson(key, lam, shape))
register("random.exponential", category="random", differentiable=False)(
    lambda key, shape, dtype=jnp.float32: jax.random.exponential(key, shape, dtype))
register("random.truncated_normal", category="random", differentiable=False)(
    lambda key, shape, lower=-2.0, upper=2.0, dtype=jnp.float32:
    jax.random.truncated_normal(key, lower, upper, shape, dtype))
register("random.shuffle", category="random", differentiable=False)(
    lambda key, x, axis=0: jax.random.permutation(key, x, axis=axis))
register("random.randint", category="random", differentiable=False)(
    lambda key, shape, minval, maxval: jax.random.randint(key, shape, minval, maxval))


@register("random.dropout_inverted", category="random")
def dropout_inverted(key, x, rate):
    """Inverted dropout as a catalog op (layer-level dropout lives in
    nnops.dropout; registered separately for graph/import use)."""
    from .nnops import dropout
    return dropout(x, rate, key)


# -- image ops ---------------------------------------------------------------
@register("image.resize_bilinear", category="image")
def resize_bilinear(x, size, data_format="NHWC", expect_leading=None):
    """Resize spatial dims of [B,H,W,C] (or [B,C,H,W]) to `size` (h, w)."""
    h, w = size
    if data_format == "NHWC":
        shape = (x.shape[0], h, w, x.shape[3])
        leading = (x.shape[0], x.shape[3])
    else:
        shape = (x.shape[0], x.shape[1], h, w)
        leading = (x.shape[0], x.shape[1])
    if expect_leading is not None and tuple(expect_leading) != leading:
        raise ValueError(
            f"resize: node requested leading dims {tuple(expect_leading)} "
            f"but input has {leading} (batch/channel resize unsupported)")
    return jax.image.resize(x, shape, method="bilinear")


@register("image.resize_nearest", category="image")
def resize_nearest(x, size, data_format="NHWC", require_integer_upscale=False,
                   expect_leading=None):
    h, w = size
    if data_format == "NHWC":
        xh, xw = x.shape[1], x.shape[2]
        shape = (x.shape[0], h, w, x.shape[3])
        leading = (x.shape[0], x.shape[3])
    else:
        xh, xw = x.shape[2], x.shape[3]
        shape = (x.shape[0], x.shape[1], h, w)
        leading = (x.shape[0], x.shape[1])
    # trace-time guards for graph importers whose node metadata can't be
    # validated at import (shapes unknown there, static here)
    if expect_leading is not None and tuple(expect_leading) != leading:
        raise ValueError(
            f"resize: node requested leading dims {tuple(expect_leading)} "
            f"but input has {leading} (batch/channel resize unsupported)")
    if require_integer_upscale and (h % xh or w % xw):
        raise ValueError(
            f"nearest resize {xh}x{xw} -> {h}x{w}: asymmetric-floor grid "
            "only matches half-pixel sampling for integer upscales")
    return jax.image.resize(x, shape, method="nearest")


@register("image.resize_scale", category="image")
def resize_scale(x, scale, method="nearest", data_format="NHWC"):
    """Resize spatial dims by a (sh, sw) scale factor. Output size is
    computed from the traced input shape, so graph importers can emit this
    without knowing intermediate shapes (ONNX Resize scales form)."""
    sh, sw = scale
    # ONNX Resize output size is floor(input_size * scale) — round() would
    # diverge by one pixel on fractional downscales (e.g. 0.5 on an odd dim)
    if data_format == "NHWC":
        shape = (x.shape[0], int(math.floor(x.shape[1] * sh)),
                 int(math.floor(x.shape[2] * sw)), x.shape[3])
    else:
        shape = (x.shape[0], x.shape[1], int(math.floor(x.shape[2] * sh)),
                 int(math.floor(x.shape[3] * sw)))
    return jax.image.resize(x, shape, method=method)


@register("image.crop_to_box", category="image", differentiable=False)
def crop_to_box(x, top, left, height, width, data_format="NHWC"):
    if data_format == "NHWC":
        return x[:, top:top + height, left:left + width, :]
    return x[:, :, top:top + height, left:left + width]


@register("image.flip_lr", category="image")
def flip_lr(x, data_format="NHWC"):
    return jnp.flip(x, axis=2 if data_format == "NHWC" else 3)


@register("image.flip_ud", category="image")
def flip_ud(x, data_format="NHWC"):
    return jnp.flip(x, axis=1 if data_format == "NHWC" else 2)


@register("image.adjust_brightness", category="image")
def adjust_brightness(x, delta):
    return x + delta


@register("image.adjust_contrast", category="image")
def adjust_contrast(x, factor):
    mean = jnp.mean(x, axis=(-3, -2), keepdims=True)
    return (x - mean) * factor + mean


# -- CTC loss (cuDNN CTC helper / LossCTC equivalent) ------------------------
@register("loss.ctc", category="loss")
def ctc_loss(log_probs, labels, logit_paddings=None, label_paddings=None,
             blank_id=0):
    """Connectionist temporal classification loss (mean over batch).

    log_probs: [B, T, C] logits; labels: [B, S] int labels;
    paddings: 1.0 where padded (optax convention).
    """
    import optax
    if logit_paddings is None:
        logit_paddings = jnp.zeros(log_probs.shape[:2], log_probs.dtype)
    if label_paddings is None:
        label_paddings = jnp.zeros(labels.shape, log_probs.dtype)
    per_seq = optax.ctc_loss(log_probs, logit_paddings,
                             jnp.asarray(labels, jnp.int32), label_paddings,
                             blank_id=blank_id)
    return jnp.mean(per_seq)
