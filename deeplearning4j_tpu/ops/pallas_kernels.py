"""Pallas TPU kernels for hot ops.

The SURVEY.md §7.2 M5 note ("+Pallas fused cell if needed for perf") and
§7.3 item 3 flag the LSTM cell as the op worth hand-fusing: per scan step
the lax path emits two matmuls plus a chain of elementwise gate ops, and
although XLA fuses most of the chain, the fused kernel keeps gates, state
update, and both matmuls in VMEM with one HBM round-trip per step.

Kernel strategy: single-block (whole operands in VMEM) — LSTM step
operands are [B,F]/[F,4U] sized, far under the ~16 MB VMEM budget for any
practical cell; ``fits_vmem`` guards the dispatch and callers fall back to
``nnops.lstm_cell`` above the budget or off-TPU. Forward-only: the scan
layers call this under ``jax.checkpoint``-free inference/streaming paths;
training keeps the lax cell (custom VJP for the kernel is not worth the
maintenance while XLA's fused backward is this close).

NEGATIVE RESULT (round 3, recorded so it is not retried): a fused
1x1-conv backward kernel (dX + dW from one pass over dY, f32 VMEM
accumulator across a row-tiled grid) was numerically correct but ~50%
SLOWER than XLA's derived backward on the real v5e chip (ResNet-50 step
54 -> 80 ms), and even rerouting the 1x1 forward from lax.conv to a dot
(no Pallas) cost ~20% — XLA's conv fusions carry layout/epilogue
decisions a naive contraction loses. Don't fight the conv pipeline with
hand kernels here; the remaining bwd HBM traffic is structural.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_VMEM_BUDGET = 8 * 1024 * 1024  # conservative half of ~16MB VMEM


def available() -> bool:
    """Pallas TPU lowering available on the default backend?"""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def fits_vmem(batch: int, n_in: int, units: int, bytes_per: int = 4) -> bool:
    total = (batch * n_in + batch * units * 2      # x, h, c
             + n_in * 4 * units + units * 4 * units  # W, RW
             + 4 * units                            # b
             + batch * 4 * units                    # z scratch
             + batch * units * 2) * bytes_per       # outputs
    return total < _VMEM_BUDGET


def _lstm_kernel(forget_bias, x_ref, h_ref, c_ref, w_ref, rw_ref, b_ref,
                 h_out, c_out):
    z = (jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)
         + jnp.dot(h_ref[:], rw_ref[:], preferred_element_type=jnp.float32)
         + b_ref[:])
    u = z.shape[-1] // 4
    i = jax.nn.sigmoid(z[:, :u])
    f = jax.nn.sigmoid(z[:, u:2 * u] + forget_bias)
    o = jax.nn.sigmoid(z[:, 2 * u:3 * u])
    g = jnp.tanh(z[:, 3 * u:])
    c_new = f * c_ref[:].astype(jnp.float32) + i * g
    h_new = o * jnp.tanh(c_new)
    h_out[:] = h_new.astype(h_out.dtype)
    c_out[:] = c_new.astype(c_out.dtype)


def lstm_cell_fused(x, h, c, w_ih, w_hh, b, forget_bias: float = 0.0,
                    interpret: bool = False):
    """Fused LSTM step (gate order [i,f,o,g], matching nnops.lstm_cell).

    All operands land in VMEM; both matmuls accumulate f32 on the MXU and
    the whole gate chain runs before anything returns to HBM. Raises
    ValueError when the operands exceed the VMEM budget — callers guard
    with :func:`fits_vmem` and fall back to the lax cell.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, F = x.shape
    U = w_hh.shape[0]
    if not fits_vmem(B, F, U, np.dtype(x.dtype).itemsize):
        raise ValueError(
            f"lstm_cell_fused operands exceed the VMEM budget "
            f"(B={B}, F={F}, U={U}); use nnops.lstm_cell")
    kernel = functools.partial(_lstm_kernel, float(forget_bias))
    spec = pl.BlockSpec(memory_space=pl.ANY if interpret else pltpu.VMEM)
    h_new, c_new = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((B, U), x.dtype),
                   jax.ShapeDtypeStruct((B, U), x.dtype)),
        in_specs=[spec] * 6,
        out_specs=(spec, spec),
        interpret=interpret,
    )(x, h, c, w_ih, w_hh, b)
    return h_new, c_new
