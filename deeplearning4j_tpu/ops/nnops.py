"""Neural-network ops: convolution, pooling, normalization, recurrence, attention.

TPU-native replacement for the libnd4j declarable-op nn families and their
cuDNN/oneDNN platform helpers (reference:
``libnd4j/include/ops/declarable/generic/nn/``†,
``libnd4j/include/ops/declarable/platform/{cudnn,mkldnn}/``† per SURVEY.md
§2.1; reference mount was empty, citations upstream-relative, unverified).

Everything lowers to ``lax`` primitives that XLA maps onto the MXU
(conv/matmul) or fuses into epilogues (bias, activation, bn). The cuDNN
"helper seam" from SURVEY.md §3.1 does not exist here — XLA owns kernel
choice.

Layout policy (SURVEY.md §7.3 item 1): ops take ``data_format`` ("NCHW" |
"NHWC"). DL4J's default is NCHW; TPU prefers NHWC. Layers default to NCHW for
config/import parity and XLA:TPU transposes internally; perf-critical zoo
configs set NHWC end-to-end.

Padding parity: DL4J ConvolutionMode.Truncate == explicit pad (default 0) with
floor division; Same == TF-style SAME; Causal == left-pad for conv1d.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import register
from ..environment import precision_for


def _safe_root(s, p):
    """s ** (1/p) with a finite gradient at s == 0 (the derivative is inf
    there; 0-cotangent * inf = NaN would poison shared grads — double-where)."""
    pos = s > 0
    return jnp.where(pos, jnp.where(pos, s, 1.0) ** (1.0 / p), 0.0)


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _conv_dnums(data_format: str):
    if data_format == "NCHW":
        return ("NCHW", "OIHW", "NCHW")
    if data_format == "NHWC":
        return ("NHWC", "HWIO", "NHWC")
    raise ValueError(f"Unknown data_format {data_format}")


def _conv_padding(mode: str, padding, kernel, stride, dilation):
    """Resolve DL4J ConvolutionMode + explicit padding to lax padding config."""
    if mode == "same":
        return "SAME"
    if mode == "causal":
        # left-pad only (1d conv): (k-1)*d on the left
        return [((k - 1) * d, 0) for k, d in zip(kernel, dilation)]
    # truncate/strict: explicit symmetric padding
    pad = padding if isinstance(padding, (tuple, list)) else (padding,) * len(kernel)
    return [(int(p), int(p)) for p in pad]


@register("conv2d", category="cnn")
def conv2d(x, w, b=None, stride=(1, 1), padding=0, dilation=(1, 1),
           mode="truncate", data_format="NCHW", groups=1):
    """2D convolution (libnd4j ``conv2d`` declarable op; cuDNN helper path).

    x: [N,C,H,W] or [N,H,W,C]; w: [O,I/g,kH,kW] (OIHW, DL4J weight layout)
    regardless of data_format — importers hand us OIHW and we let XLA
    transpose. b: [O] or None.
    """
    stride, dilation = _pair(stride), _pair(dilation)
    kh, kw = w.shape[2], w.shape[3]
    io_layout, _, out_layout = _conv_dnums(data_format)
    dn = lax.conv_dimension_numbers(x.shape, w.shape, (io_layout, "OIHW", out_layout))
    pad = _conv_padding(mode, padding, (kh, kw), stride, dilation)
    # no preferred_element_type=f32 for bf16: the MXU accumulates bf16 convs
    # in f32 natively, and forcing the OUTPUT dtype breaks the conv VJP
    # (transposed conv gets mixed bf16/f32 operands — found benching bf16)
    y = lax.conv_general_dilated(
        x, w, window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups, precision=precision_for(x, w))
    if b is not None:
        y = y + (b.reshape(1, -1, 1, 1) if data_format == "NCHW" else b.reshape(1, 1, 1, -1))
    return y


@register("deconv2d", category="cnn")
def deconv2d(x, w, b=None, stride=(1, 1), padding=0, dilation=(1, 1),
             mode="truncate", data_format="NCHW"):
    """Transposed 2D convolution (libnd4j ``deconv2d``). w: [O,I,kH,kW] with
    O = output channels (DL4J deconv weight layout)."""
    stride, dilation = _pair(stride), _pair(dilation)
    kh, kw = w.shape[2], w.shape[3]
    dn = lax.conv_dimension_numbers(x.shape, (w.shape[1], w.shape[0], kh, kw),
                                    (_conv_dnums(data_format)[0], "OIHW", _conv_dnums(data_format)[2]))
    if mode == "same":
        pad = "SAME"
    else:
        # DL4J/torch transposed-conv semantics: out = (in-1)*s + k_eff - 2p.
        # lax.conv_transpose's explicit (lo, hi) padding is ADDITIVE to the
        # bare transpose (whose pad-free output is (in-1)*s + k_eff - 2*(k_eff-1)),
        # so forward-padding p maps to lo = hi = (k_eff - 1) - p.
        p = padding if isinstance(padding, (tuple, list)) else (padding, padding)
        k_eff = ((kh - 1) * dilation[0] + 1, (kw - 1) * dilation[1] + 1)
        pad = [(k_eff[i] - 1 - int(pi), k_eff[i] - 1 - int(pi))
               for i, pi in enumerate(p)]
    # lax.conv_transpose wants rhs as [spatial..., I, O] per dn; use OIHW with
    # transpose_kernel semantics: swap I/O of the stored weight.
    y = lax.conv_transpose(
        x, jnp.swapaxes(w, 0, 1), strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn, transpose_kernel=True,
        precision=precision_for(x, w))
    if b is not None:
        y = y + (b.reshape(1, -1, 1, 1) if data_format == "NCHW" else b.reshape(1, 1, 1, -1))
    return y


@register("depthwise_conv2d", category="cnn")
def depthwise_conv2d(x, w, b=None, stride=(1, 1), padding=0, dilation=(1, 1),
                     mode="truncate", data_format="NCHW"):
    """Depthwise conv (libnd4j ``depthwise_conv2d``). w: [C*mult, 1, kH, kW]."""
    c = x.shape[1] if data_format == "NCHW" else x.shape[3]
    return conv2d(x, w, b, stride, padding, dilation, mode, data_format, groups=c)


@register("separable_conv2d", category="cnn")
def separable_conv2d(x, w_depth, w_point, b=None, stride=(1, 1), padding=0,
                     dilation=(1, 1), mode="truncate", data_format="NCHW"):
    """Separable conv = depthwise then 1x1 pointwise (libnd4j ``sconv2d``)."""
    y = depthwise_conv2d(x, w_depth, None, stride, padding, dilation, mode, data_format)
    return conv2d(y, w_point, b, (1, 1), 0, (1, 1), "truncate", data_format)


def _pool(x, kind, kernel, stride, padding, mode, data_format, pnorm_p=2.0,
          count_include_pad=True):
    kh, kw = _pair(kernel)
    sh, sw = _pair(stride)
    if data_format == "NCHW":
        window = (1, 1, kh, kw)
        strides = (1, 1, sh, sw)
    else:
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
    if mode == "same":
        pad = "SAME"
    else:
        ph, pw = _pair(padding)
        pad = [(0, 0), (0, 0), (ph, ph), (pw, pw)] if data_format == "NCHW" else \
              [(0, 0), (ph, ph), (pw, pw), (0, 0)]
    if kind == "max":
        init = -jnp.inf
        y = lax.reduce_window(x, init, lax.max, window, strides, pad)
    elif kind == "avg":
        s = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
        # DL4J avg pool divides by the full kernel size (incl. padding cells)
        # in Truncate mode; with SAME it divides by the actual window count.
        # count_include_pad=False forces the window-count divisor for
        # explicit padding too (ONNX AveragePool default semantics); with no
        # padding every window is full, so skip the count pass.
        explicit_pad = mode != "same" and any(p != (0, 0) for p in pad)
        if mode == "same" or (not count_include_pad and explicit_pad):
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pad)
            y = s / cnt
        else:
            y = s / (kh * kw)
    elif kind == "pnorm":
        s = lax.reduce_window(jnp.abs(x) ** pnorm_p, 0.0, lax.add, window, strides, pad)
        y = _safe_root(s, pnorm_p)
    else:
        raise ValueError(kind)
    return y


@register("maxpool2d", category="cnn")
def max_pool2d(x, kernel, stride=None, padding=0, mode="truncate", data_format="NCHW"):
    """Max pooling (SubsamplingLayer PoolingType.MAX; libnd4j ``maxpool2d``)."""
    return _pool(x, "max", kernel, stride or kernel, padding, mode, data_format)


@register("avgpool2d", category="cnn")
def avg_pool2d(x, kernel, stride=None, padding=0, mode="truncate",
               data_format="NCHW", count_include_pad=True):
    return _pool(x, "avg", kernel, stride or kernel, padding, mode,
                 data_format, count_include_pad=count_include_pad)


@register("pnormpool2d", category="cnn")
def pnorm_pool2d(x, kernel, stride=None, padding=0, mode="truncate",
                 data_format="NCHW", p=2.0):
    return _pool(x, "pnorm", kernel, stride or kernel, padding, mode, data_format, p)


@register("global_pool", category="cnn")
def global_pool(x, pool_type="max", data_format="NCHW", keepdims=False, p=2.0):
    """GlobalPoolingLayer: pool over all spatial (or time) dims.
    ``p`` is the pnorm exponent (DL4J GlobalPoolingLayer.pnorm)."""
    if x.ndim == 5:  # CNN3D [N,C,D,H,W] or [N,D,H,W,C]
        axes = (2, 3, 4) if data_format in ("NCHW", "NCDHW") else (1, 2, 3)
    elif x.ndim == 4:
        axes = (2, 3) if data_format == "NCHW" else (1, 2)
    else:
        axes = (2,) if data_format == "NCHW" else (1,)
    if pool_type == "max":
        return jnp.max(x, axis=axes, keepdims=keepdims)
    if pool_type == "avg":
        return jnp.mean(x, axis=axes, keepdims=keepdims)
    if pool_type == "sum":
        return jnp.sum(x, axis=axes, keepdims=keepdims)
    if pool_type == "pnorm":
        return _safe_root(jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=keepdims), p)
    raise ValueError(pool_type)


@register("batch_norm", category="normalization")
def batch_norm(x, gamma, beta, mean, var, eps=1e-5, axis=1):
    """Batch norm inference/normalize step (libnd4j ``batchnorm``; cuDNN
    helper path). ``axis`` = channel axis (1 for NCHW, -1 for NHWC).

    Training-mode statistics are computed by the BatchNormalization layer
    (which passes batch statistics here and maintains running averages); XLA
    fuses the whole thing.
    """
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    mean = mean.reshape(shape)
    var = var.reshape(shape)
    g = gamma.reshape(shape) if gamma is not None else 1.0
    b = beta.reshape(shape) if beta is not None else 0.0
    inv = lax.rsqrt(var + eps)
    return (x - mean) * inv * g + b


@register("layer_norm", category="normalization")
def layer_norm(x, gamma, beta, eps=1e-5, axis=-1):
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    return y * gamma + beta


@register("instance_norm", category="normalization")
def instance_norm(x, gamma, beta, eps=1e-5):
    """Per-instance per-channel normalization over spatial dims, NCHW-style
    [N,C,D1..Dn] (ONNX InstanceNormalization; torch InstanceNormNd)."""
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    y = (x - mean) * lax.rsqrt(var + eps)
    cshape = (1, x.shape[1]) + (1,) * len(axes)
    return y * gamma.reshape(cshape) + beta.reshape(cshape)


@register("lrn", category="normalization")
def local_response_normalization(x, k=2.0, n=5, alpha=1e-4, beta=0.75,
                                 data_format="NCHW"):
    """LocalResponseNormalization (libnd4j ``lrn``), cross-channel."""
    caxis = 1 if data_format == "NCHW" else 3
    sq = jnp.square(x)
    half = n // 2
    window = [1] * x.ndim
    window[caxis] = n
    pad = [(0, 0)] * x.ndim
    pad[caxis] = (half, half)
    s = lax.reduce_window(sq, 0.0, lax.add, tuple(window), (1,) * x.ndim, pad)
    return x / jnp.power(k + alpha * s, beta)


@register("dropout", category="regularization")
def dropout(x, rate, key, deterministic=False):
    """Inverted dropout (DL4J Dropout with p = *retain* probability is the
    config-level concern; this op takes the DROP rate)."""
    if deterministic or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


@register("embedding_lookup", category="embedding")
def embedding_lookup(table, ids):
    """EmbeddingLayer/EmbeddingSequenceLayer lookup (gather rides HBM)."""
    return jnp.take(table, jnp.asarray(ids, dtype=jnp.int32), axis=0)


# -- recurrence -------------------------------------------------------------

@register("lstm_cell", category="rnn")
def lstm_cell(x, h, c, w_ih, w_hh, b, forget_bias=0.0):
    """Standard LSTM cell, gate order [i, f, o, g] (DL4J LSTMBlockCell order).

    One fused [in+hidden, 4*units] matmul per step — the shape the MXU wants.
    Peephole (GravesLSTM) variant is :func:`graves_lstm_cell`.
    """
    prec = precision_for(x, w_ih)
    z = jnp.dot(x, w_ih, precision=prec) + jnp.dot(h, w_hh, precision=prec) + b
    i, f, o, g = jnp.split(z, 4, axis=-1)
    f = jax.nn.sigmoid(f + forget_bias)
    i = jax.nn.sigmoid(i)
    o = jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


@register("graves_lstm_cell", category="rnn")
def graves_lstm_cell(x, h, c, w_ih, w_hh, b, w_peep):
    """Graves (peephole) LSTM cell — DL4J GravesLSTM parity
    (peepholes on i, f from c_{t-1}; on o from c_t). w_peep: [3, units]."""
    prec = precision_for(x, w_ih)
    z = jnp.dot(x, w_ih, precision=prec) + jnp.dot(h, w_hh, precision=prec) + b
    i, f, o, g = jnp.split(z, 4, axis=-1)
    i = jax.nn.sigmoid(i + w_peep[0] * c)
    f = jax.nn.sigmoid(f + w_peep[1] * c)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    o = jax.nn.sigmoid(o + w_peep[2] * c_new)
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


@register("gru_cell", category="rnn")
def gru_cell(x, h, w_ih, w_hh, b, rb=None):
    """GRU cell, gate order [z, r, h~] (Keras/CuDNN order — DL4J has no GRU;
    this exists for importer parity and as a first-class cell).

    ``rb`` (recurrent bias [3u]) selects the Keras ``reset_after=True`` /
    CuDNN formulation (candidate uses r * (h.RWh + rb_h)); ``rb=None`` is
    the classic reset-before form (candidate uses (r*h).RWh).
    One fused [B, in]x[in,3u] + [B,u]x[u,3u] matmul pair per step.
    """
    prec = precision_for(x, w_ih)
    xi = jnp.dot(x, w_ih, precision=prec) + b
    xz, xr, xh = jnp.split(xi, 3, axis=-1)
    if rb is not None:
        hi = jnp.dot(h, w_hh, precision=prec) + rb
        hz, hr, hh = jnp.split(hi, 3, axis=-1)
        z = jax.nn.sigmoid(xz + hz)
        r = jax.nn.sigmoid(xr + hr)
        n = jnp.tanh(xh + r * hh)
    else:
        u = w_hh.shape[0]
        hz = jnp.dot(h, w_hh[:, :u], precision=prec)
        hr = jnp.dot(h, w_hh[:, u:2 * u], precision=prec)
        z = jax.nn.sigmoid(xz + hz)
        r = jax.nn.sigmoid(xr + hr)
        n = jnp.tanh(xh + jnp.dot(r * h, w_hh[:, 2 * u:], precision=prec))
    return z * h + (1.0 - z) * n


@register("simple_rnn_cell", category="rnn")
def simple_rnn_cell(x, h, w_ih, w_hh, b, activation=jnp.tanh):
    prec = precision_for(x, w_ih)
    return activation(jnp.dot(x, w_ih, precision=prec) + jnp.dot(h, w_hh, precision=prec) + b)


def _onnx_dirs(direction, n_dirs):
    if direction == "forward":
        want = 1
    elif direction == "reverse":
        want = 1
    elif direction == "bidirectional":
        want = 2
    else:
        raise ValueError(f"ONNX RNN direction {direction!r} not supported")
    if n_dirs != want:
        raise ValueError(
            f"direction={direction!r} expects {want} weight slice(s), "
            f"got {n_dirs}")


@register("onnx_lstm", category="rnn")
def onnx_lstm(x, w, r, b, direction="forward", hidden_size=0):
    """ONNX ``LSTM`` node semantics (default activations, layout=0).

    x: [T, B, I]; w: [D, 4H, I] gate rows in ONNX order [i, o, f, c];
    r: [D, 4H, H]; b: [D, 8H] (Wb || Rb). Returns the ONNX output triple
    (Y [T, D, B, H], Y_h [D, B, H], Y_c [D, B, H]) — a multi-output op,
    recorded via SameDiff.call_multi. Runs as lax.scan over our fused
    lstm_cell (gate order [i, f, o, g]) after an in-graph reorder, so
    gradients flow to the ONNX-layout weights (imported graphs fine-tune).
    """
    H = int(hidden_size)

    def reorder(m):  # [4H, K] rows iofc -> columns [K, 4H] ifog
        i, o, f, c = (m[0:H], m[H:2 * H], m[2 * H:3 * H], m[3 * H:4 * H])
        return jnp.concatenate([i, f, o, c], axis=0).T

    def run_dir(xs, wd, rd, bd, rev):
        w2, r2 = reorder(wd), reorder(rd)
        bb = bd[:4 * H] + bd[4 * H:]
        b2 = jnp.concatenate([bb[0:H], bb[2 * H:3 * H], bb[H:2 * H],
                              bb[3 * H:4 * H]])
        if rev:
            xs = jnp.flip(xs, axis=0)
        B = xs.shape[1]
        h0 = jnp.zeros((B, H), xs.dtype)
        c0 = jnp.zeros((B, H), xs.dtype)

        def step(carry, x_t):
            h, c = carry
            h, c = lstm_cell(x_t, h, c, w2, r2, b2)
            return (h, c), h

        (h_T, c_T), ys = jax.lax.scan(step, (h0, c0), xs)
        if rev:
            ys = jnp.flip(ys, axis=0)
        return ys, h_T, c_T

    n_dirs = w.shape[0]
    _onnx_dirs(direction, n_dirs)
    outs = []
    for d in range(n_dirs):
        rev = (direction == "reverse") or (d == 1)
        outs.append(run_dir(x, w[d], r[d], b[d], rev))
    Y = jnp.stack([o[0] for o in outs], axis=1)        # [T, D, B, H]
    Y_h = jnp.stack([o[1] for o in outs], axis=0)      # [D, B, H]
    Y_c = jnp.stack([o[2] for o in outs], axis=0)
    return Y, Y_h, Y_c


@register("onnx_gru", category="rnn")
def onnx_gru(x, w, r, b, direction="forward", hidden_size=0,
             linear_before_reset=0):
    """ONNX ``GRU`` node semantics (default activations, layout=0).

    x: [T, B, I]; w: [D, 3H, I] gate rows [z, r, h]; r: [D, 3H, H];
    b: [D, 6H] (Wb || Rb). ``linear_before_reset=1`` is the CuDNN/Keras
    ``reset_after`` form (our gru_cell with a separate recurrent bias).
    Returns (Y [T, D, B, H], Y_h [D, B, H]).
    """
    H = int(hidden_size)

    def run_dir(xs, wd, rd, bd, rev):
        w2, r2 = wd.T, rd.T              # [I,3H] / [H,3H], order z,r,h = ours
        wb, rb = bd[:3 * H], bd[3 * H:]
        if rev:
            xs = jnp.flip(xs, axis=0)
        B = xs.shape[1]
        h0 = jnp.zeros((B, H), xs.dtype)
        if linear_before_reset:
            cell = lambda x_t, h: gru_cell(x_t, h, w2, r2, wb, rb)
        else:
            cell = lambda x_t, h: gru_cell(x_t, h, w2, r2, wb + rb, None)

        def step(h, x_t):
            h = cell(x_t, h)
            return h, h

        h_T, ys = jax.lax.scan(step, h0, xs)
        if rev:
            ys = jnp.flip(ys, axis=0)
        return ys, h_T

    n_dirs = w.shape[0]
    _onnx_dirs(direction, n_dirs)
    outs = []
    for d in range(n_dirs):
        rev = (direction == "reverse") or (d == 1)
        outs.append(run_dir(x, w[d], r[d], b[d], rev))
    Y = jnp.stack([o[0] for o in outs], axis=1)
    Y_h = jnp.stack([o[1] for o in outs], axis=0)
    return Y, Y_h


@register("dot_product_attention", category="attention")
def dot_product_attention(q, k, v, mask=None, scaled=True):
    """Scaled dot-product attention (DL4J ``dot_product_attention`` op /
    attention vertices). q,k,v: [..., T, d]. mask: broadcastable to
    [..., Tq, Tk], 1 = attend."""
    d = q.shape[-1]
    scores = jnp.einsum("...qd,...kd->...qk", q, k, precision=precision_for(q, k))
    if scaled:
        scores = scores / jnp.sqrt(jnp.asarray(d, dtype=scores.dtype))
    if mask is not None:
        scores = jnp.where(mask.astype(bool), scores, jnp.finfo(scores.dtype).min)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", w, v, precision=precision_for(w, v))


# -- resampling / structural -----------------------------------------------

@register("upsampling2d", category="cnn")
def upsampling2d(x, size, data_format="NCHW"):
    sh, sw = _pair(size)
    if data_format == "NCHW":
        return jnp.repeat(jnp.repeat(x, sh, axis=2), sw, axis=3)
    return jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)


@register("zero_padding2d", category="cnn")
def zero_padding2d(x, padding, data_format="NCHW"):
    """padding: (pad_h, pad_w) symmetric, or ((top, bottom), (left, right))."""
    if isinstance(padding[0], (tuple, list)):
        (pt, pb), (pl, pr) = padding
    else:
        pt = pb = int(padding[0])
        pl = pr = int(padding[1])
    cfg = [(0, 0), (0, 0), (pt, pb), (pl, pr)] if data_format == "NCHW" else \
          [(0, 0), (pt, pb), (pl, pr), (0, 0)]
    return jnp.pad(x, cfg)


@register("cropping2d", category="cnn")
def cropping2d(x, cropping, data_format="NCHW"):
    if not isinstance(cropping[0], (tuple, list)):
        (ct, cb), (cl, cr) = (cropping[0], cropping[0]), (cropping[1], cropping[1])
    else:
        (ct, cb), (cl, cr) = cropping
    if data_format == "NCHW":
        h, w = x.shape[2], x.shape[3]
        return x[:, :, ct:h - cb, cl:w - cr]
    h, w = x.shape[1], x.shape[2]
    return x[:, ct:h - cb, cl:w - cr, :]


@register("space_to_depth", category="cnn")
def space_to_depth(x, block_size, data_format="NCHW"):
    b = block_size
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, c, h // b, b, w // b, b)
        x = x.transpose(0, 3, 5, 1, 2, 4)
        return x.reshape(n, c * b * b, h // b, w // b)
    n, h, w, c = x.shape
    x = x.reshape(n, h // b, b, w // b, b, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // b, w // b, c * b * b)


@register("depth_to_space", category="cnn")
def depth_to_space(x, block_size, data_format="NCHW"):
    b = block_size
    if data_format == "NCHW":
        n, c, h, w = x.shape
        x = x.reshape(n, b, b, c // (b * b), h, w)
        x = x.transpose(0, 3, 4, 1, 5, 2)
        return x.reshape(n, c // (b * b), h * b, w * b)
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, b, b, c // (b * b))
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h * b, w * b, c // (b * b))


# ---- 3D convolution family --------------------------------------------------

def _triple(v):
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]), int(v[2]))
    return (int(v),) * 3


@register("conv3d", category="cnn")
def conv3d(x, w, b=None, stride=(1, 1, 1), padding=0, dilation=(1, 1, 1),
           mode="truncate", data_format="NCDHW"):
    """3D convolution (libnd4j ``conv3dnew``). x: [N,C,D,H,W] or
    [N,D,H,W,C]; w: [O,I,kD,kH,kW] (OIDHW, the DL4J layout) regardless of
    data_format."""
    stride, dilation = _triple(stride), _triple(dilation)
    io = "NCDHW" if data_format == "NCDHW" else "NDHWC"
    dn = lax.conv_dimension_numbers(x.shape, w.shape, (io, "OIDHW", io))
    if mode == "same":
        pad = "SAME"
    else:
        p = _triple(padding)
        pad = [(pi, pi) for pi in p]
    y = lax.conv_general_dilated(
        x, w, window_strides=stride, padding=pad, rhs_dilation=dilation,
        dimension_numbers=dn, precision=precision_for(x, w))
    if b is not None:
        shape = (1, -1, 1, 1, 1) if data_format == "NCDHW" else (1, 1, 1, 1, -1)
        y = y + b.reshape(shape)
    return y


def _pool3d(x, kind, kernel, stride, padding, mode, data_format):
    kd, kh, kw = _triple(kernel)
    sd_, sh, sw = _triple(stride)
    if data_format == "NCDHW":
        window = (1, 1, kd, kh, kw)
        strides = (1, 1, sd_, sh, sw)
    else:
        window = (1, kd, kh, kw, 1)
        strides = (1, sd_, sh, sw, 1)
    if mode == "same":
        pad = "SAME"
    else:
        pd, ph, pw = _triple(padding)
        spatial = [(pd, pd), (ph, ph), (pw, pw)]
        pad = ([(0, 0), (0, 0)] + spatial) if data_format == "NCDHW" else \
            ([(0, 0)] + spatial + [(0, 0)])
    if kind == "max":
        return lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pad)
    s = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
    if mode == "same":
        cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, window,
                                strides, pad)
        return s / cnt
    return s / (kd * kh * kw)


@register("maxpool3d", category="cnn")
def max_pool3d(x, kernel, stride=None, padding=0, mode="truncate",
               data_format="NCDHW"):
    return _pool3d(x, "max", kernel, stride or kernel, padding, mode,
                   data_format)


@register("avgpool3d", category="cnn")
def avg_pool3d(x, kernel, stride=None, padding=0, mode="truncate",
               data_format="NCDHW"):
    return _pool3d(x, "avg", kernel, stride or kernel, padding, mode,
                   data_format)


@register("upsampling3d", category="cnn")
def upsampling3d(x, size, data_format="NCDHW"):
    sd_, sh, sw = _triple(size)
    axes = (2, 3, 4) if data_format == "NCDHW" else (1, 2, 3)
    y = jnp.repeat(x, sd_, axis=axes[0])
    y = jnp.repeat(y, sh, axis=axes[1])
    return jnp.repeat(y, sw, axis=axes[2])


@register("deconv3d", category="cnn")
def deconv3d(x, w, b=None, stride=(1, 1, 1), padding=0,
             dilation=(1, 1, 1), mode="truncate", data_format="NCDHW"):
    """Transposed 3D convolution (libnd4j ``deconv3d``). w: [O,I,kD,kH,kW];
    out = (in-1)*s + k_eff - 2p per spatial dim (same padding mapping as
    deconv2d — lax.conv_transpose explicit padding is additive)."""
    stride, dilation = _triple(stride), _triple(dilation)
    kd, kh, kw = w.shape[2], w.shape[3], w.shape[4]
    io = "NCDHW" if data_format == "NCDHW" else "NDHWC"
    dn = lax.conv_dimension_numbers(
        x.shape, (w.shape[1], w.shape[0], kd, kh, kw), (io, "OIDHW", io))
    if mode == "same":
        pad = "SAME"
    else:
        p = _triple(padding)
        k_eff = tuple((k - 1) * d + 1 for k, d in zip((kd, kh, kw), dilation))
        pad = [(k_eff[i] - 1 - p[i], k_eff[i] - 1 - p[i]) for i in range(3)]
    y = lax.conv_transpose(
        x, jnp.swapaxes(w, 0, 1), strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn, transpose_kernel=True,
        precision=precision_for(x, w))
    if b is not None:
        shape = (1, -1, 1, 1, 1) if data_format == "NCDHW" else (1, 1, 1, 1, -1)
        y = y + b.reshape(shape)
    return y


@register("space_to_batch", category="cnn")
def space_to_batch(x, block_size, paddings=((0, 0), (0, 0)),
                   data_format="NCHW"):
    """TF-style space_to_batch for 2D inputs (libnd4j ``space_to_batch``)."""
    bs = block_size if isinstance(block_size, (tuple, list)) else (block_size,) * 2
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
    B, H, W, C = x.shape
    x = jnp.pad(x, [(0, 0), tuple(paddings[0]), tuple(paddings[1]), (0, 0)])
    Hp, Wp = x.shape[1], x.shape[2]
    x = x.reshape(B, Hp // bs[0], bs[0], Wp // bs[1], bs[1], C)
    x = jnp.transpose(x, (2, 4, 0, 1, 3, 5))
    x = x.reshape(B * bs[0] * bs[1], Hp // bs[0], Wp // bs[1], C)
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 3, 1, 2))
    return x


@register("batch_to_space", category="cnn")
def batch_to_space(x, block_size, crops=((0, 0), (0, 0)),
                   data_format="NCHW"):
    """Inverse of space_to_batch."""
    bs = block_size if isinstance(block_size, (tuple, list)) else (block_size,) * 2
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 2, 3, 1))
    Bb, H, W, C = x.shape
    B = Bb // (bs[0] * bs[1])
    x = x.reshape(bs[0], bs[1], B, H, W, C)
    x = jnp.transpose(x, (2, 3, 0, 4, 1, 5))
    x = x.reshape(B, H * bs[0], W * bs[1], C)
    (ct, cb), (cl, cr) = crops
    x = x[:, ct:x.shape[1] - cb, cl:x.shape[2] - cr, :]
    if data_format == "NCHW":
        x = jnp.transpose(x, (0, 3, 1, 2))
    return x
