"""Pallas TPU fused-epilogue kernel library + the fused master-cast updater.

The r17 ``mfu_gap`` attribution and the r18 ``master_cast_ms`` audit name
three memory-bound chains that XLA leaves as separate HBM round-trips and
that schedule tuning (r18) cannot recover — they are kernels that do not
exist yet. This module is those kernels (the TVM framing from PAPERS.md:
hand-fused operator *epilogues* with a sweep-and-cache tuner, never the
matmul/conv itself — the recorded negative result in ``pallas_kernels.py``
shows naive conv kernels lose to XLA's conv pipeline):

- :func:`bn_act` — batch-norm normalize + activation as one row-tiled
  affine kernel ``y = act(x*scale + shift)`` with scale/shift folded from
  the BN statistics outside the kernel ([C]-sized math, XLA's job). The
  ResNet hot-block tail (conv -> BN -> relu) stops round-tripping the conv
  output through HBM twice.
- :func:`bias_act` — conv/matmul bias + activation epilogue on the same
  affine kernel (scale absent).
- :func:`layer_norm_act` — LayerNorm + affine + activation for the
  transformer blocks; spliced into TF-imported SameDiff graphs by
  ``autodiff/fusion.py``'s ``fuse_epilogues`` rewrite (the r8
  ``fuse_attention`` splice pattern).
- :func:`dispatch_updater` / ``nn/updaters.py`` ``apply_leaf_cast`` — the
  fused master-cast+updater step: the per-step f32->bf16 master cast is
  folded into the updater's parameter write (one fused sweep emits the f32
  master AND its bf16 compute copy), eliminating the standalone cast sweep
  ``master_cast_ms`` attributes. Pure XLA (no Pallas) — the win is program
  structure, so it applies on every backend.

All kernels carry custom VJPs. The affine backward recomputes the
pre-activation from x/scale/shift (no extra residuals — the activation
input never hits HBM); per-channel grads accumulate in f32 VMEM scratch
across the sequential row-block grid and flush on the last step (the
flash-attention dkv pattern). LayerNorm saves only the per-row mean/rstd,
lane-replicated like flash's softmax stats.

Dispatch follows the flash-attention house style: mode env pin
``DL4J_TPU_FUSED_EPILOGUES`` (auto/force/off), every decision bumps
``fused_epilogues.dispatch{decision=}`` (zero silent fallbacks), fallbacks
reproduce the EXACT pre-fusion formula (``nnops.batch_norm`` + the
activation catalog fn) so auto-mode on CPU is bit-identical to the
unfused layer stack. Row-block sizes ride ``ops/autotune.py``
sweep-and-cache entries keyed ``("epilogue", kind, rows, cols, dtype)``.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import register
from . import nnops
from . import activations as _activations
from .pallas_kernels import _VMEM_BUDGET, available as _tpu_available

_LANES = 128

# lazily bound so importing this module never requires pallas to load;
# kernel bodies reference this module-global (the flash_attention pattern)
pl = None


def _load_pallas():
    global pl
    from . import flash_attention as _fa
    _pl, pltpu = _fa._load_pallas()
    pl = _pl
    return _pl, pltpu


# --------------------------------------------------------------------------
# activation table: forward + derivative-from-preactivation, kernel-safe
# --------------------------------------------------------------------------

_SQRT_2_OVER_PI = 0.7978845608028654
_GELU_A = 0.044715
_INV_SQRT2 = 0.7071067811865476
_INV_SQRT_2PI = 0.3989422804014327

# canonical (lowercase, underscore-stripped) names the kernels implement.
# Only activations with a cheap closed-form derivative from the
# pre-activation qualify — the backward recomputes act'(z) instead of
# saving residuals. Parameterized activations (leakyrelu alpha, elu) fall
# back: their alpha plumbing is not worth a kernel variant.
_FOLDABLE = ("identity", "relu", "relu6", "tanh", "sigmoid", "gelu",
             "geluexact")


def _canon(act) -> str:
    return str(act).lower().replace("_", "")


def foldable_act(act, alpha=None) -> bool:
    """Can this activation ride a fused epilogue kernel?"""
    return alpha is None and _canon(act) in _FOLDABLE


def _act_fwd(act, z):
    """act(z), f32 in/out, inside the kernel."""
    if act == "identity":
        return z
    if act == "relu":
        return jnp.maximum(z, 0.0)
    if act == "relu6":
        return jnp.clip(z, 0.0, 6.0)
    if act == "tanh":
        return jnp.tanh(z)
    if act == "sigmoid":
        return jax.nn.sigmoid(z)
    if act == "gelu":  # tanh approximation (DL4J GELU)
        u = _SQRT_2_OVER_PI * (z + _GELU_A * z * z * z)
        return 0.5 * z * (1.0 + jnp.tanh(u))
    if act == "geluexact":  # ONNX erf form
        return 0.5 * z * (1.0 + jax.lax.erf(z * _INV_SQRT2))
    raise ValueError(f"unfoldable activation {act!r}")


def _act_grad(act, z):
    """d act/d z recomputed from the pre-activation (no residuals)."""
    if act == "identity":
        return jnp.ones_like(z)
    if act == "relu":
        # same subgradient as the reference _relu_outgrad: zero at z == 0
        return (z > 0.0).astype(z.dtype)
    if act == "relu6":
        return ((z > 0.0) & (z < 6.0)).astype(z.dtype)
    if act == "tanh":
        t = jnp.tanh(z)
        return 1.0 - t * t
    if act == "sigmoid":
        s = jax.nn.sigmoid(z)
        return s * (1.0 - s)
    if act == "gelu":
        u = _SQRT_2_OVER_PI * (z + _GELU_A * z * z * z)
        t = jnp.tanh(u)
        du = _SQRT_2_OVER_PI * (1.0 + 3.0 * _GELU_A * z * z)
        return 0.5 * (1.0 + t) + 0.5 * z * (1.0 - t * t) * du
    if act == "geluexact":
        cdf = 0.5 * (1.0 + jax.lax.erf(z * _INV_SQRT2))
        pdf = _INV_SQRT_2PI * jnp.exp(-0.5 * z * z)
        return cdf + z * pdf
    raise ValueError(f"unfoldable activation {act!r}")


def reference_act(act, alpha=None):
    """The exact catalog activation the fallback path applies — identical
    callable to what the unfused layer stack uses, so an auto-mode
    fallback is bit-for-bit the pre-fusion program."""
    act = _canon(act)
    if act == "geluexact":
        return lambda x: _activations.gelu(x, approximate=False)
    fn = _activations.get(act)
    if alpha is not None:
        return lambda x: fn(x, alpha)
    return fn


# --------------------------------------------------------------------------
# kernel bodies (grid = (row-blocks,), sequential — "arbitrary" semantics
# so the per-channel grad scratch accumulates safely across steps)
# --------------------------------------------------------------------------

def _fused_epilogue_affine_fwd(*refs, act, has_scale):
    if has_scale:
        x_ref, s_ref, b_ref, y_ref = refs
    else:
        x_ref, b_ref, y_ref = refs
        s_ref = None
    x = x_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)  # [1, C] broadcasts over rows
    z = x * s_ref[...].astype(jnp.float32) + b if has_scale else x + b
    y_ref[...] = _act_fwd(act, z).astype(y_ref.dtype)


def _fused_epilogue_affine_bwd(*refs, act, has_scale, nblocks):
    if has_scale:
        (x_ref, s_ref, b_ref, dy_ref,
         dx_ref, ds_ref, db_ref, ds_scr, db_scr) = refs
    else:
        x_ref, b_ref, dy_ref, dx_ref, db_ref, db_scr = refs
        s_ref = ds_ref = ds_scr = None
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        db_scr[...] = jnp.zeros_like(db_scr)
        if has_scale:
            ds_scr[...] = jnp.zeros_like(ds_scr)

    x = x_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    if has_scale:
        s = s_ref[...].astype(jnp.float32)
        z = x * s + b
    else:
        z = x + b
    dz = dy * _act_grad(act, z)
    dx_ref[...] = ((dz * s) if has_scale else dz).astype(dx_ref.dtype)
    if has_scale:
        ds_scr[...] += jnp.sum(dz * x, axis=0, keepdims=True)
    db_scr[...] += jnp.sum(dz, axis=0, keepdims=True)

    @pl.when(i == nblocks - 1)
    def _flush():
        db_ref[...] = db_scr[...]
        if has_scale:
            ds_ref[...] = ds_scr[...]


def _fused_epilogue_ln_fwd(x_ref, g_ref, b_ref, y_ref, mu_ref, rs_ref, *,
                           act, eps):
    x = x_ref[...].astype(jnp.float32)
    mu = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mu
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    z = (xhat * g_ref[...].astype(jnp.float32)
         + b_ref[...].astype(jnp.float32))
    y_ref[...] = _act_fwd(act, z).astype(y_ref.dtype)
    rows = x.shape[0]
    mu_ref[...] = jnp.broadcast_to(mu, (rows, _LANES))
    rs_ref[...] = jnp.broadcast_to(rstd, (rows, _LANES))


def _fused_epilogue_ln_bwd(x_ref, g_ref, b_ref, mu_ref, rs_ref, dy_ref,
                           dx_ref, dg_ref, db_ref, dg_scr, db_scr, *,
                           act, nblocks):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dg_scr[...] = jnp.zeros_like(dg_scr)
        db_scr[...] = jnp.zeros_like(db_scr)

    x = x_ref[...].astype(jnp.float32)
    mu = mu_ref[...][:, :1]
    rstd = rs_ref[...][:, :1]
    xhat = (x - mu) * rstd
    g = g_ref[...].astype(jnp.float32)
    z = xhat * g + b_ref[...].astype(jnp.float32)
    dz = dy_ref[...].astype(jnp.float32) * _act_grad(act, z)
    dg_scr[...] += jnp.sum(dz * xhat, axis=0, keepdims=True)
    db_scr[...] += jnp.sum(dz, axis=0, keepdims=True)
    dxh = dz * g
    m1 = jnp.mean(dxh, axis=1, keepdims=True)
    m2 = jnp.mean(dxh * xhat, axis=1, keepdims=True)
    dx_ref[...] = ((dxh - m1 - xhat * m2) * rstd).astype(dx_ref.dtype)

    @pl.when(i == nblocks - 1)
    def _flush():
        dg_ref[...] = dg_scr[...]
        db_ref[...] = db_scr[...]


def _compiler_params_rows(pltpu):
    try:
        return pltpu.TPUCompilerParams(dimension_semantics=("arbitrary",))
    except Exception:  # older/newer spelling: let the compiler default
        return None


# --------------------------------------------------------------------------
# pallas_call wrappers (grid = (rows // block_rows,))
# --------------------------------------------------------------------------

def _affine_fwd_impl(x2, s2, b2, act, br, interpret):
    _pl, pltpu = _load_pallas()
    R, C = x2.shape
    n = R // br
    has_scale = s2 is not None
    vec = _pl.BlockSpec((1, C), lambda i: (0, 0))
    in_specs = [_pl.BlockSpec((br, C), lambda i: (i, 0))]
    args = [x2]
    if has_scale:
        in_specs.append(vec)
        args.append(s2)
    in_specs.append(vec)
    args.append(b2)
    kernel = functools.partial(_fused_epilogue_affine_fwd, act=act,
                               has_scale=has_scale)
    return _pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=in_specs,
        out_shape=jax.ShapeDtypeStruct((R, C), x2.dtype),
        out_specs=_pl.BlockSpec((br, C), lambda i: (i, 0)),
        compiler_params=_compiler_params_rows(pltpu),
        interpret=interpret,
    )(*args)


def _affine_bwd_impl(x2, s2, b2, dy, act, br, interpret):
    _pl, pltpu = _load_pallas()
    R, C = x2.shape
    n = R // br
    has_scale = s2 is not None
    vec = _pl.BlockSpec((1, C), lambda i: (0, 0))
    row = _pl.BlockSpec((br, C), lambda i: (i, 0))
    in_specs = [row] + ([vec, vec] if has_scale else [vec]) + [row]
    args = ([x2, s2, b2, dy] if has_scale else [x2, b2, dy])
    out_shape = [jax.ShapeDtypeStruct((R, C), x2.dtype)]
    out_specs = [row]
    scratch = []
    if has_scale:
        out_shape.append(jax.ShapeDtypeStruct((1, C), jnp.float32))
        out_specs.append(vec)
        scratch.append(pltpu.VMEM((1, C), jnp.float32))
    out_shape.append(jax.ShapeDtypeStruct((1, C), jnp.float32))
    out_specs.append(vec)
    scratch.append(pltpu.VMEM((1, C), jnp.float32))
    kernel = functools.partial(_fused_epilogue_affine_bwd, act=act,
                               has_scale=has_scale, nblocks=n)
    outs = _pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=in_specs,
        out_shape=tuple(out_shape),
        out_specs=tuple(out_specs),
        scratch_shapes=scratch,
        compiler_params=_compiler_params_rows(pltpu),
        interpret=interpret,
    )(*args)
    if has_scale:
        dx, ds, db = outs
        return dx, ds, db
    dx, db = outs
    return dx, None, db


def _ln_fwd_impl(x2, g2, b2, eps, act, br, interpret):
    _pl, pltpu = _load_pallas()
    R, C = x2.shape
    n = R // br
    vec = _pl.BlockSpec((1, C), lambda i: (0, 0))
    row = _pl.BlockSpec((br, C), lambda i: (i, 0))
    stat = _pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    kernel = functools.partial(_fused_epilogue_ln_fwd, act=act, eps=eps)
    return _pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[row, vec, vec],
        out_shape=(jax.ShapeDtypeStruct((R, C), x2.dtype),
                   jax.ShapeDtypeStruct((R, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((R, _LANES), jnp.float32)),
        out_specs=(row, stat, stat),
        compiler_params=_compiler_params_rows(pltpu),
        interpret=interpret,
    )(x2, g2, b2)


def _ln_bwd_impl(x2, g2, b2, mu, rstd, dy, eps, act, br, interpret):
    _pl, pltpu = _load_pallas()
    R, C = x2.shape
    n = R // br
    vec = _pl.BlockSpec((1, C), lambda i: (0, 0))
    row = _pl.BlockSpec((br, C), lambda i: (i, 0))
    stat = _pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    kernel = functools.partial(_fused_epilogue_ln_bwd, act=act, nblocks=n)
    return _pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[row, vec, vec, stat, stat, row],
        out_shape=(jax.ShapeDtypeStruct((R, C), x2.dtype),
                   jax.ShapeDtypeStruct((1, C), jnp.float32),
                   jax.ShapeDtypeStruct((1, C), jnp.float32)),
        out_specs=(row, vec, vec),
        scratch_shapes=[pltpu.VMEM((1, C), jnp.float32),
                        pltpu.VMEM((1, C), jnp.float32)],
        compiler_params=_compiler_params_rows(pltpu),
        interpret=interpret,
    )(x2, g2, b2, mu, rstd, dy)


# --------------------------------------------------------------------------
# custom VJPs
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _affine_act(x2, s2, b2, act, br, interpret):
    return _affine_fwd_impl(x2, s2, b2, act, br, interpret)


def _affine_act_fwd_rule(x2, s2, b2, act, br, interpret):
    # backward recomputes z from x/scale/shift: no residual beyond inputs
    return _affine_fwd_impl(x2, s2, b2, act, br, interpret), (x2, s2, b2)


def _affine_act_bwd_rule(act, br, interpret, res, dy):
    x2, s2, b2 = res
    dx, ds, db = _affine_bwd_impl(x2, s2, b2, dy, act, br, interpret)
    ds_out = None if s2 is None else ds.astype(s2.dtype)
    return dx, ds_out, db.astype(b2.dtype)


_affine_act.defvjp(_affine_act_fwd_rule, _affine_act_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ln_act(x2, g2, b2, eps, act, br, interpret):
    y, _, _ = _ln_fwd_impl(x2, g2, b2, eps, act, br, interpret)
    return y


def _ln_act_fwd_rule(x2, g2, b2, eps, act, br, interpret):
    y, mu, rstd = _ln_fwd_impl(x2, g2, b2, eps, act, br, interpret)
    return y, (x2, g2, b2, mu, rstd)


def _ln_act_bwd_rule(eps, act, br, interpret, res, dy):
    x2, g2, b2, mu, rstd = res
    dx, dg, db = _ln_bwd_impl(x2, g2, b2, mu, rstd, dy, eps, act, br,
                              interpret)
    return dx, dg.astype(g2.dtype), db.astype(b2.dtype)


_ln_act.defvjp(_ln_act_fwd_rule, _ln_act_bwd_rule)


# --------------------------------------------------------------------------
# shape/VMEM guards
# --------------------------------------------------------------------------

def row_block(rows: int, mult: int, target: int = 256) -> Optional[int]:
    """Largest row block <= target dividing ``rows``, multiple of ``mult``
    (8 sublanes for 4-byte dtypes, 16 for 2-byte); None when nothing
    tiles. The dispatch guard AND the autotune candidate generator both
    derive from this so a cached block can never stop tiling."""
    b = min(int(target), int(rows))
    b -= b % mult
    while b >= mult:
        if rows % b == 0:
            return b
        b -= mult
    return None


def _row_mult(dtype) -> int:
    return 16 if np.dtype(dtype).itemsize == 2 else 8


def fits_vmem_epilogue(br: int, cols: int, itemsize: int = 4,
                       kind: str = "affine") -> bool:
    """Worst-of-fwd/bwd per-grid-step VMEM estimate (dispatching commits
    the backward too); x2 for pipelining double-buffers."""
    core = (3 * br * cols * itemsize  # x, dy in + dx out blocks (bwd)
            + 4 * cols * 4            # scale/shift in + dscale/dshift out
            + 2 * cols * 4)           # f32 accumulation scratch
    if kind == "ln":
        core += 4 * br * _LANES * 4   # mu/rstd: fwd writes 2, bwd reads 2
    return 2 * core < _VMEM_BUDGET


# --------------------------------------------------------------------------
# dispatch: mode + counters (zero-silent-fallback observability)
# --------------------------------------------------------------------------

_COUNTER_KEYS = ("fused", "fallback_mode", "fallback_platform",
                 "fallback_act", "fallback_dtype", "fallback_shape",
                 "fallback_vmem",
                 # master-cast+updater decisions ride the same registry
                 # counter so the whole library's mix is one metric family
                 "fused_updater", "fallback_updater_mode",
                 "fallback_updater_dtype", "fallback_updater_penalty")
from ..runtime import telemetry as _tel  # noqa: E402

_DISPATCH = _tel.counter(
    "fused_epilogues.dispatch",
    "fused-epilogue dispatch decisions at trace time (fused vs fallback_*)")
_state = {"mode": os.environ.get("DL4J_TPU_FUSED_EPILOGUES", "auto")}
_FUSABLE_DTYPES = (jnp.float32, jnp.bfloat16, jnp.float16)


def mode() -> str:
    return _state["mode"]


def set_mode(m: str) -> str:
    """"auto" (TPU -> kernels, elsewhere -> exact unfused reference),
    "force" (kernels everywhere — Pallas interpret off-TPU; how the CPU
    tier-1 suite exercises the kernel code), "off" (reference everywhere,
    fused updater disabled). Returns the previous mode.

    Consulted at TRACE time, exactly like flash attention's mode: flip it
    BEFORE building/tracing, or invalidate compiled caches after."""
    if m not in ("auto", "force", "off"):
        raise ValueError(f"fused epilogues mode {m!r} not in "
                         "('auto', 'force', 'off')")
    old = _state["mode"]
    _state["mode"] = m
    return old


def counters() -> dict:
    """Dispatch-decision counts (trace-time units, like flash attention:
    one count per compiled call-site, not per execution)."""
    return {k: int(_DISPATCH.value(decision=k)) for k in _COUNTER_KEYS}


def reset_counters() -> None:
    _DISPATCH.zero()


def route_elementwise(shape, dtype, axis=-1, act="identity", alpha=None,
                      kind="affine") -> Optional[str]:
    """None = fuse; otherwise the fallback counter key. Pure function of
    static facts (shape/dtype/act/mode/backend) so the staticcheck fusion
    probe and the layer fold planners share the dispatcher's exact
    decision."""
    if _state["mode"] == "off":
        return "fallback_mode"
    if not foldable_act(act, alpha):
        return "fallback_act"
    if _state["mode"] != "force" and not _tpu_available():
        return "fallback_platform"
    if jnp.dtype(dtype) not in [jnp.dtype(d) for d in _FUSABLE_DTYPES]:
        return "fallback_dtype"
    ndim = len(shape)
    if ndim < 2 or axis not in (-1, ndim - 1):
        return "fallback_shape"  # kernels are channel-last row-tiled
    cols = int(shape[-1])
    rows = 1
    for s in shape[:-1]:
        rows *= int(s)
    if cols < 1 or _tpu_available() and cols % _LANES:
        return "fallback_shape"  # lane alignment on real hardware
    br = row_block(rows, _row_mult(dtype))
    if br is None:
        return "fallback_shape"
    if not fits_vmem_epilogue(br, cols, np.dtype(dtype).itemsize, kind):
        return "fallback_vmem"
    return None


def _collapse(x):
    cols = x.shape[-1]
    rows = 1
    for s in x.shape[:-1]:
        rows *= int(s)
    return x.reshape(rows, cols), rows, cols


def _tuned_row_block(kind, rows, cols, x):
    from . import autotune as _autotune
    br = _autotune.epilogue_blocks(
        kind, rows, cols, x.dtype,
        concrete=not isinstance(x, jax.core.Tracer))
    if br is not None and rows % br == 0 and br % _row_mult(x.dtype) == 0:
        return br
    return row_block(rows, _row_mult(x.dtype))


# --------------------------------------------------------------------------
# public fused ops
# --------------------------------------------------------------------------

def bn_act(x, gamma, beta, mean, var, eps=1e-5, axis=-1, act="identity",
           alpha=None):
    """Batch-norm normalize + activation epilogue. Fused route folds the
    statistics into per-channel scale/shift ([C]-sized prologue math left
    to XLA — gradients to gamma/beta/mean/var flow through it) and runs
    one row-tiled affine+act kernel over the conv output. Fallback is the
    EXACT legacy formula: ``nnops.batch_norm`` then the catalog
    activation — bit-identical to the unfused layer pair."""
    act_c = _canon(act)
    reason = route_elementwise(x.shape, x.dtype, axis, act, alpha)
    if reason is None:
        _DISPATCH.inc(decision="fused")
        x2, rows, cols = _collapse(x)
        inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
        scale = inv if gamma is None else inv * gamma.astype(jnp.float32)
        shift = -mean.astype(jnp.float32) * scale
        if beta is not None:
            shift = beta.astype(jnp.float32) + shift
        br = _tuned_row_block("affine", rows, cols, x2)
        y = _affine_act(x2, scale.reshape(1, cols), shift.reshape(1, cols),
                        act_c, br, not _tpu_available())
        return y.reshape(x.shape)
    _DISPATCH.inc(decision=reason)
    y = nnops.batch_norm(x, gamma, beta, mean, var, eps, axis)
    if act_c == "identity" and alpha is None:
        return y
    return reference_act(act, alpha)(y)


def bias_act(x, b=None, act="identity", axis=-1, alpha=None):
    """Bias + activation epilogue (the post-conv/post-matmul tail).
    ``b`` is a [C] vector over ``axis`` or None. Fallback reproduces the
    conv layers' legacy tail exactly: broadcast-add then the catalog
    activation."""
    act_c = _canon(act)
    if b is None and act_c == "identity" and alpha is None:
        return x  # nothing to fuse; keep the dispatch mix meaningful
    reason = route_elementwise(x.shape, x.dtype, axis, act, alpha)
    if reason is None:
        _DISPATCH.inc(decision="fused")
        x2, rows, cols = _collapse(x)
        bb = jnp.zeros((cols,), x.dtype) if b is None else b
        br = _tuned_row_block("affine", rows, cols, x2)
        y = _affine_act(x2, None, bb.reshape(1, cols), act_c, br,
                        not _tpu_available())
        return y.reshape(x.shape)
    _DISPATCH.inc(decision=reason)
    if b is not None:
        shape = [1] * x.ndim
        shape[axis] = b.shape[0]
        x = x + b.reshape(shape)
    if act_c == "identity" and alpha is None:
        return x
    return reference_act(act, alpha)(x)


def layer_norm_act(x, gamma, beta, eps=1e-5, act="identity"):
    """LayerNorm (last axis) + affine + activation epilogue for the
    transformer blocks; ``fuse_epilogues(sd)`` splices TF-imported
    decompositions into this op. Fallback is ``nnops.layer_norm`` + the
    catalog activation."""
    act_c = _canon(act)
    reason = route_elementwise(x.shape, x.dtype, -1, act, None, kind="ln")
    if reason is None:
        _DISPATCH.inc(decision="fused")
        x2, rows, cols = _collapse(x)
        br = _tuned_row_block("ln", rows, cols, x2)
        y = _ln_act(x2, gamma.reshape(1, cols), beta.reshape(1, cols),
                    float(eps), act_c, br, not _tpu_available())
        return y.reshape(x.shape)
    _DISPATCH.inc(decision=reason)
    y = nnops.layer_norm(x, gamma, beta, eps, axis=-1)
    if act_c == "identity":
        return y
    return reference_act(act)(y)


# catalog ops the SameDiff rewrite pass splices in (serde round-trips the
# names + attrs; execution resolves through the registry like every op)

@register("epilogue.layer_norm_act", category="normalization")
def layer_norm_act_op(x, gamma, beta, eps=1e-5, act="identity"):
    return layer_norm_act(x, gamma, beta, eps=eps, act=act)


@register("epilogue.bias_act", category="activation")
def bias_act_op(x, b=None, act="identity"):
    return bias_act(x, b, act=act)


# --------------------------------------------------------------------------
# fused master-cast + updater routing
# --------------------------------------------------------------------------

def route_updater(policy, *, has_penalty: bool = False) -> Optional[str]:
    """None = fold the f32->16-bit master cast into the updater's write
    (``nn/updaters.py`` ``apply_leaf_cast``); otherwise the fallback
    counter key. No platform gate: the fusion is pure XLA program
    structure (the cast rides the parameter-update sweep instead of its
    own HBM sweep at the top of the forward), a win on every backend.

    ``has_penalty``: engine train steps whose loss reads the f32 masters
    for l1/l2 terms keep the unfused split (the SameDiff path handles
    penalties by differentiating masters and compute copies separately,
    so it always passes False)."""
    if _state["mode"] == "off":
        return "fallback_updater_mode"
    from .. import dtypes as _dt
    if not _dt.is_mixed(policy):
        return "fallback_updater_dtype"
    if has_penalty:
        return "fallback_updater_penalty"
    return None


def dispatch_updater(policy, *, has_penalty: bool = False) -> Optional[str]:
    """Counted :func:`route_updater` — call once per train-step build."""
    reason = route_updater(policy, has_penalty=has_penalty)
    _DISPATCH.inc(decision=reason or "fused_updater")
    return reason
