"""Tiled Pallas TPU flash attention + the framework's attention dispatch.

Attention was the one hot path the kernel layer had not touched:
``nn/layers/attention.py`` materialized the full [B,H,Tq,Tk] score matrix
through einsum+softmax, and the TF-imported BERT path runs the same
``batch_matmul -> scale -> mask-add -> softmax -> batch_matmul`` chain
through ``autodiff/samediff.py``. XLA fuses the softmax *chain* but still
round-trips the quadratic scores tensor through HBM in both forward and
backward — the exact fusion the TVM line of work (PAPERS.md) says must be
done by hand. This module is that hand fusion:

- :func:`flash_attention` — the raw fused op. Online-softmax forward over a
  (batch*heads, q-blocks, kv-blocks) grid with f32 running max/sum
  accumulators in VMEM scratch; kv is the innermost ("arbitrary") grid
  dimension so the scores tile never leaves VMEM. A custom VJP recomputes
  p = exp(s - m)/l per tile in the backward (two kernels: dq, and dk/dv),
  saving only the per-row logsumexp — carried as its two pieces (running
  max m, running sum l) so a finfo.min mask bias can't absorb log(l) —
  plus the output, for di = sum(o*do). Training steps benefit, not just
  serving.
- :func:`reference_attention` — the quadratic einsum path, scores upcast to
  f32 before softmax (matching the kernel's f32 accumulators; this is also
  the numerics fix for the layers' bf16 dtype policy).
- :func:`attention` — the dispatcher the layers and the SameDiff fused op
  ride: routes to the kernel on TPU (or in Pallas interpret mode when
  forced, so the CPU tier-1 suite exercises the real kernel code) when the
  shapes tile and the bias is key-reducible, else falls back to the
  reference path. Every routing decision bumps a counter
  (:func:`counters`) so a silent fallback is visible in tests and bench.

Numerics contract (kernel == reference at f32 atol ~1e-5): s = (q . k^T) *
scale + bias computed in f32; softmax in f32; p cast to the value dtype for
the p@v matmul with f32 accumulation; output cast back to the input dtype.
A fully-masked row (all keys at finfo.min bias) degrades to UNIFORM
attention in both paths — softmax of equal scores — preserving the layer
contract where masked *steps* are zeroed by the caller, not here.

Divergence (recorded in PARITY.md): the fused path treats ``bias`` as
non-differentiable (zero cotangent) — bias here is always a mask-derived
constant (layers' key masks, BERT's extended attention mask). A *learned*
additive bias must use the reference path (mode "off" or a non-key-
reducible bias, which falls back automatically).

LSTM-cell precedent and the 1x1-conv negative result live in
``pallas_kernels.py``; this kernel follows the same dispatch house style
(``fits_vmem``-like budget guard, loud fallbacks, lax path for training
parity tests).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from . import register
from ..environment import precision_for
from .pallas_kernels import _VMEM_BUDGET, available as _tpu_available

_LANES = 128          # TPU lane count: running max/sum ride replicated lanes
_NEG = float(np.finfo(np.float32).min)


# --------------------------------------------------------------------------
# reference (quadratic) path — f32 softmax, shared by layers and fallbacks
# --------------------------------------------------------------------------

def reference_attention(q, k, v, bias=None, scale: Optional[float] = None):
    """Quadratic einsum attention with the kernel's numerics: scores in f32,
    softmax in f32, p@v accumulated in f32, output in the input dtype.

    q: [..., Tq, d]; k, v: [..., Tk, d]; bias broadcastable to
    [..., Tq, Tk] (additive, finite large-negative for masking)."""
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    s = jnp.einsum("...qd,...kd->...qk", q, k,
                   precision=precision_for(q, k),
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + jnp.maximum(bias.astype(jnp.float32), _NEG)
    p = jax.nn.softmax(s, axis=-1)
    y = jnp.einsum("...qk,...kd->...qd", p.astype(v.dtype), v,
                   precision=precision_for(v, v),
                   preferred_element_type=jnp.float32)
    return y.astype(q.dtype)


# --------------------------------------------------------------------------
# kernel bodies
# --------------------------------------------------------------------------

def _lanes(x, n):
    """[rows, _LANES] lane-replicated stat -> [rows, n] broadcast."""
    if x.shape[1] == n:
        return x
    return jnp.broadcast_to(x[:, :1], (x.shape[0], n))


def _scores(q_ref, k_ref, bias_ref, scale):
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if bias_ref is not None:
        s = s + bias_ref[...].astype(jnp.float32)  # [1, bk] broadcasts rows
    return s


def _fwd_kernel(*refs, scale, nk, has_bias):
    if has_bias:
        (q_ref, k_ref, v_ref, bias_ref, o_ref, m_ref, l_ref,
         m_scr, l_scr, acc_scr) = refs
    else:
        q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr = refs
        bias_ref = None
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    s = _scores(q_ref, k_ref, bias_ref, scale)             # [bq, bk] f32
    m_prev, l_prev = m_scr[...], l_scr[...]                # [bq, LANES]
    m_curr = jnp.max(s, axis=1, keepdims=True)             # [bq, 1]
    m_next = jnp.maximum(m_prev, m_curr)                   # [bq, LANES]
    alpha = jnp.exp(m_prev - m_next)
    p = jnp.exp(s - _lanes(m_next, s.shape[1]))            # [bq, bk]
    m_scr[...] = m_next
    l_scr[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    d = acc_scr.shape[1]
    acc_scr[...] = acc_scr[...] * _lanes(alpha, d) + jax.lax.dot(
        p.astype(v_ref.dtype), v_ref[0], preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finish():
        l_fin = l_scr[...]
        safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0] = (acc_scr[...] / _lanes(safe, d)).astype(o_ref.dtype)
        # the softmax stats are saved as SEPARATE max + sum (the logsumexp
        # in two pieces): m + log(l) would absorb log(l) entirely when m is
        # a finfo.min mask bias (ulp(3e38) >> log l), and the backward's
        # recomputed p = exp(s - lse) would come out 1 instead of 1/Tk on
        # fully-masked rows (found by the masked-row gradient parity test)
        m_ref[0] = m_scr[...]
        l_ref[0] = safe


def _bwd_dq_kernel(*refs, scale, nk, has_bias):
    if has_bias:
        (q_ref, k_ref, v_ref, bias_ref, m_ref, l_ref, di_ref, do_ref,
         dq_ref, dq_scr) = refs
    else:
        (q_ref, k_ref, v_ref, m_ref, l_ref, di_ref, do_ref,
         dq_ref, dq_scr) = refs
        bias_ref = None
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    s = _scores(q_ref, k_ref, bias_ref, scale)
    bk = s.shape[1]
    p = jnp.exp(s - _lanes(m_ref[0], bk)) * _lanes(1.0 / l_ref[0], bk)
    dp = jax.lax.dot_general(                               # do @ v^T
        do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - _lanes(di_ref[0], bk)) * scale           # [bq, bk] f32
    dq_scr[...] += jax.lax.dot(ds.astype(k_ref.dtype), k_ref[0],
                               preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, nq, has_bias):
    if has_bias:
        (q_ref, k_ref, v_ref, bias_ref, m_ref, l_ref, di_ref, do_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, m_ref, l_ref, di_ref, do_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        bias_ref = None
    jq = pl.program_id(2)

    @pl.when(jq == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    s = _scores(q_ref, k_ref, bias_ref, scale)              # [bq, bk]
    bk = s.shape[1]
    p = jnp.exp(s - _lanes(m_ref[0], bk)) * _lanes(1.0 / l_ref[0], bk)
    do = do_ref[0]
    dv_scr[...] += jax.lax.dot_general(                     # p^T @ do
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(                               # do @ v^T
        do, v_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - _lanes(di_ref[0], bk)) * scale
    dk_scr[...] += jax.lax.dot_general(                     # ds^T @ q
        ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(jq == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _mq_decode_kernel(q_ref, k_ref, v_ref, len_ref, o_ref,
                      m_scr, l_scr, acc_scr, *, scale, nk, bk):
    """Multi-query decode forward (speculative verify, ISSUE 12): the
    whole Tq=k query window rides one grid row, streaming the cache in
    ``bk`` tiles. The mask is computed INSIDE the kernel from the per-row
    valid length: query i (global position ``l + i``) may attend cache
    columns ``< l + 1 + i`` — a per-(query, key) causal window that is
    not key-reducible, so it cannot ride the fwd kernel's [B, Tk] bias."""
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale          # [bq, bk] f32
    ln = len_ref[0, 0]                                       # int32 scalar
    col = j * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    s = jnp.where(col < ln + 1 + row, s, _NEG)
    m_prev, l_prev = m_scr[...], l_scr[...]
    m_curr = jnp.max(s, axis=1, keepdims=True)
    m_next = jnp.maximum(m_prev, m_curr)
    alpha = jnp.exp(m_prev - m_next)
    p = jnp.exp(s - _lanes(m_next, s.shape[1]))
    m_scr[...] = m_next
    l_scr[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
    d = acc_scr.shape[1]
    acc_scr[...] = acc_scr[...] * _lanes(alpha, d) + jax.lax.dot(
        p.astype(v_ref.dtype), v_ref[0], preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finish():
        l_fin = l_scr[...]
        safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0] = (acc_scr[...] / _lanes(safe, d)).astype(o_ref.dtype)


# lazily bound so importing this module never requires pallas to load
pl = None


def _load_pallas():
    global pl
    if pl is None:
        from jax.experimental import pallas as _pl
        pl = _pl
    from jax.experimental.pallas import tpu as pltpu
    return pl, pltpu


def _compiler_params(pltpu):
    try:
        return pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    except Exception:  # older/newer spelling: let the compiler default
        return None


# --------------------------------------------------------------------------
# pallas_call wrappers (grid = (B*H, q-blocks, kv-blocks))
# --------------------------------------------------------------------------

def _fwd_impl(q3, k3, v3, kb, scale, heads, bq, bk, interpret):
    pl, pltpu = _load_pallas()
    G, Tq, d = q3.shape
    Tk = k3.shape[1]
    nq, nk = Tq // bq, Tk // bk
    has_bias = kb is not None
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
    ]
    args = [q3, k3, v3]
    if has_bias:
        in_specs.append(
            pl.BlockSpec((1, bk), lambda b, i, j: (b // heads, j)))
        args.append(kb)
    kernel = functools.partial(_fwd_kernel, scale=scale, nk=nk,
                               has_bias=has_bias)
    row = pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0))
    o, m, l = pl.pallas_call(
        kernel,
        grid=(G, nq, nk),
        in_specs=in_specs,
        out_shape=(jax.ShapeDtypeStruct((G, Tq, d), q3.dtype),
                   jax.ShapeDtypeStruct((G, Tq, _LANES), jnp.float32),
                   jax.ShapeDtypeStruct((G, Tq, _LANES), jnp.float32)),
        out_specs=(pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
                   row, row),
        scratch_shapes=[pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, _LANES), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_compiler_params(pltpu),
        interpret=interpret,
    )(*args)
    return o, m, l


def _bwd_impl(q3, k3, v3, kb, m, l, di, do, scale, heads, bq, bk, interpret):
    pl, pltpu = _load_pallas()
    G, Tq, d = q3.shape
    Tk = k3.shape[1]
    nq, nk = Tq // bq, Tk // bk
    has_bias = kb is not None

    qkv_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),   # q by i
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),   # k by j
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),   # v by j
    ]
    bias_spec = [pl.BlockSpec((1, bk), lambda b, i, j: (b // heads, j))] \
        if has_bias else []
    row_specs = [
        pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),  # m
        pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),  # l
        pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, 0)),  # di
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),       # do
    ]
    args = [q3, k3, v3] + ([kb] if has_bias else []) + [m, l, di, do]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, nk=nk,
                          has_bias=has_bias),
        grid=(G, nq, nk),
        in_specs=qkv_specs + bias_spec + row_specs,
        out_shape=jax.ShapeDtypeStruct((G, Tq, d), q3.dtype),
        out_specs=pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_compiler_params(pltpu),
        interpret=interpret,
    )(*args)

    # dk/dv grid: kv-blocks outer, q-blocks inner (the reduction axis)
    dkv_qkv_specs = [
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, j, 0)),   # q by inner j
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, i, 0)),   # k by outer i
        pl.BlockSpec((1, bk, d), lambda b, i, j: (b, i, 0)),   # v by outer i
    ]
    dkv_bias_spec = [pl.BlockSpec((1, bk), lambda b, i, j: (b // heads, i))] \
        if has_bias else []
    dkv_row_specs = [
        pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, j, 0)),  # m
        pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, j, 0)),  # l
        pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, j, 0)),  # di
        pl.BlockSpec((1, bq, d), lambda b, i, j: (b, j, 0)),       # do
    ]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, nq=nq,
                          has_bias=has_bias),
        grid=(G, nk, nq),
        in_specs=dkv_qkv_specs + dkv_bias_spec + dkv_row_specs,
        out_shape=(jax.ShapeDtypeStruct((G, Tk, d), k3.dtype),
                   jax.ShapeDtypeStruct((G, Tk, d), v3.dtype)),
        out_specs=(pl.BlockSpec((1, bk, d), lambda b, i, j: (b, i, 0)),
                   pl.BlockSpec((1, bk, d), lambda b, i, j: (b, i, 0))),
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_compiler_params(pltpu),
        interpret=interpret,
    )(*args)
    return dq, dk, dv


def _mq_impl(q3, k3, v3, lens2, scale, heads, bk, interpret):
    """pallas_call wrapper for the Tq=k multi-query decode kernel: the
    whole query window is one block (bq = Tq), the cache streams in
    ``bk`` tiles, ``lens2`` is the lane-replicated [B, LANES] int32
    valid-length array (forward only — verify never trains)."""
    pl, pltpu = _load_pallas()
    G, Tq, d = q3.shape
    Tk = k3.shape[1]
    nk = Tk // bk
    kernel = functools.partial(_mq_decode_kernel, scale=scale, nk=nk, bk=bk)
    return pl.pallas_call(
        kernel,
        grid=(G, 1, nk),
        in_specs=[
            pl.BlockSpec((1, Tq, d), lambda b, i, j: (b, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, _LANES), lambda b, i, j: (b // heads, 0)),
        ],
        out_shape=jax.ShapeDtypeStruct((G, Tq, d), q3.dtype),
        out_specs=pl.BlockSpec((1, Tq, d), lambda b, i, j: (b, 0, 0)),
        scratch_shapes=[pltpu.VMEM((Tq, _LANES), jnp.float32),
                        pltpu.VMEM((Tq, _LANES), jnp.float32),
                        pltpu.VMEM((Tq, d), jnp.float32)],
        compiler_params=_compiler_params(pltpu),
        interpret=interpret,
    )(q3, k3, v3, lens2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q3, k3, v3, kb, scale, heads, bq, bk, interpret):
    o, _, _ = _fwd_impl(q3, k3, v3, kb, scale, heads, bq, bk, interpret)
    return o


def _flash_fwd(q3, k3, v3, kb, scale, heads, bq, bk, interpret):
    o, m, l = _fwd_impl(q3, k3, v3, kb, scale, heads, bq, bk, interpret)
    return o, (q3, k3, v3, kb, o, m, l)


def _flash_bwd(scale, heads, bq, bk, interpret, res, do):
    q3, k3, v3, kb, o, m, l = res
    di = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32),
                 axis=-1, keepdims=True)
    di = jnp.broadcast_to(di, m.shape)  # lane-replicated like m/l
    dq, dk, dv = _bwd_impl(q3, k3, v3, kb, m, l, di, do,
                           scale, heads, bq, bk, interpret)
    # bias is mask-derived here: zero cotangent (recorded divergence)
    dkb = None if kb is None else jnp.zeros_like(kb)
    return dq, dk, dv, dkb


_flash.defvjp(_flash_fwd, _flash_bwd)


# --------------------------------------------------------------------------
# public fused op
# --------------------------------------------------------------------------

def pick_block(t: int, target: int = 128) -> Optional[int]:
    """Largest block <= target that divides ``t`` and is a multiple of 8
    (layout-friendly sublanes); None when nothing tiles.

    r12: any multiple-of-8 divisor qualifies, not only power-of-two tiles —
    odd sequence lengths like 24, 120 or 384 now tile (fewer dispatcher
    ``fallback_shape`` exits) instead of demanding a power-of-two factor."""
    b = min(int(target), int(t))
    b -= b % 8
    while b >= 8:
        if t % b == 0:
            return b
        b -= 8
    return None


def fits_vmem_attention(bq: int, bk: int, d: int, itemsize: int = 4) -> bool:
    """Per-grid-cell VMEM estimate over the WORST of the three kernels —
    dispatching commits the backward too, and the dkv kernel holds the
    largest set (q/k/v/do blocks, four f32 score-sized tiles, dk/dv
    scratch AND outputs). x2 for pipelining double-buffers."""
    fwd = ((bq * d + 2 * bk * d) * itemsize           # q, k, v blocks
           + 2 * bq * bk * 4                          # scores + p (f32)
           + (2 * bq * _LANES + bq * d) * 4           # m/l/acc scratch
           + (bq * d + 2 * bq * _LANES) * 4)          # o + m/l out blocks
    dkv = ((2 * bq * d + 2 * bk * d) * itemsize       # q, do, k, v blocks
           + 4 * bq * bk * 4                          # s/p/dp/ds (f32)
           + 3 * bq * _LANES * 4                      # m/l/di row blocks
           + 2 * bk * d * 4                           # dk/dv scratch
           + 2 * bk * d * itemsize)                   # dk/dv out blocks
    return 2 * max(fwd, dkv) < _VMEM_BUDGET


def _key_bias(bias, batch, tk):
    """Reduce an additive bias broadcastable to [B,H,Tq,Tk] down to the
    per-(batch, key) form [B, Tk] the kernel streams, or None if the bias
    genuinely varies over heads/queries."""
    if bias is None:
        return None
    if bias.ndim != 4 or bias.shape[1] != 1 or bias.shape[2] != 1:
        return None
    if bias.shape[0] not in (1, batch) or bias.shape[3] != tk:
        return None
    kb = jnp.broadcast_to(bias[:, 0, 0, :], (batch, tk))
    return jnp.maximum(kb.astype(jnp.float32), _NEG)


def flash_attention(q, k, v, bias=None, scale: Optional[float] = None, *,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: bool = False):
    """Fused flash attention: softmax((q.k^T)*scale + bias) @ v.

    q: [B, H, Tq, d]; k, v: [B, H, Tk, d]; bias broadcastable to
    [B, H, Tq, Tk] with singleton head/query dims (key-mask form — a
    full per-query bias falls outside this kernel; use the dispatcher,
    which falls back). Raises ValueError on non-tiling shapes — callers
    go through :func:`attention` for guarded dispatch.

    ``block_q``/``block_k``: explicit TARGET tile sizes (the largest
    divisor block <= target is used, the pre-r12 contract). The default
    ``None`` consults the block-shape autotuner (``ops/autotune.py``):
    swept blocks when the cache is warm for this (Tq, Tk, d, dtype, bias)
    key, else the classic 128-target defaults (seeded, never swept, when
    the operands are tracers or the backend is not TPU).
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError(f"flash_attention wants [B,H,T,d]; got {q.shape}")
    B, H, Tq, d = q.shape
    Tk = k.shape[2]
    if k.shape != (B, H, Tk, d) or v.shape != (B, H, Tk, d):
        raise ValueError(f"q/k/v shapes disagree: {q.shape} {k.shape} "
                         f"{v.shape}")
    if block_q is None and block_k is None:
        from . import autotune as _autotune
        tuned = _autotune.get_blocks(
            Tq, Tk, d, q.dtype, bias is not None,
            concrete=not isinstance(q, jax.core.Tracer))
        bq, bk = tuned if tuned is not None else (None, None)
        # belt over the autotuner's own validation: blocks that do not
        # tile would silently truncate the grid (Tq // bq); a poisoned
        # entry falls back to the target-128 defaults, never garbage
        if bq is not None and (Tq % bq or Tk % bk):
            bq, bk = pick_block(Tq), pick_block(Tk)
    else:
        bq = pick_block(Tq, block_q or 128)
        bk = pick_block(Tk, block_k or 128)
    if bq is None or bk is None:
        raise ValueError(f"sequence lengths ({Tq}, {Tk}) do not tile into "
                         f"({block_q or 128}, {block_k or 128}) blocks")
    if not fits_vmem_attention(bq, bk, d, np.dtype(q.dtype).itemsize):
        raise ValueError(f"attention tiles exceed the VMEM budget "
                         f"(bq={bq}, bk={bk}, d={d})")
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    kb = _key_bias(bias, B, Tk)
    if bias is not None and kb is None:
        raise ValueError(f"bias shape {bias.shape} is not key-reducible "
                         "([B,1,1,Tk]); use attention() for fallback")
    o = _flash(q.reshape(B * H, Tq, d), k.reshape(B * H, Tk, d),
               v.reshape(B * H, Tk, d), kb, float(scale), H, bq, bk,
               bool(interpret))
    return o.reshape(B, H, Tq, d)


# --------------------------------------------------------------------------
# autoregressive decode: one new-token query over a bucketed KV cache
# --------------------------------------------------------------------------

def length_bias(lengths, cache_len: int):
    """Per-row valid-length mask in the kernel's key-bias form: ``[B, C]``
    f32, zero where ``position < length`` and finfo.min elsewhere — exactly
    the ``kb`` the forward kernel streams, so ragged cache occupancy stays
    exact without materializing a [B,H,1,C] mask."""
    lengths = jnp.asarray(lengths)
    pos = jax.lax.broadcasted_iota(jnp.int32, (1, cache_len), 1)
    return jnp.where(pos < lengths[:, None].astype(jnp.int32),
                     jnp.float32(0.0), jnp.float32(_NEG))


def reference_decode_attention(q, k, v, lengths, scale=None):
    """Quadratic reference for single-step decode: ``q`` [B, H, 1, d]
    attends over the cache [B, H, C, d], positions >= ``lengths[b]``
    masked out. Shares :func:`reference_attention`'s f32 numerics."""
    C = k.shape[2]
    bias = length_bias(lengths, C)[:, None, None, :]
    return reference_attention(q, k, v, bias=bias, scale=scale)


def decode_attention(q, k, v, lengths, scale=None, *,
                     block_k: Optional[int] = None,
                     interpret: bool = False, page: int = 0):
    """Fused single-query decode: the flash forward kernel at ``bq=1``
    (forward only — decode is inference; no VJP needed) streaming the
    cache in ``block_k`` tiles with the per-row length mask as the key
    bias. ``q`` [B, H, 1, d]; ``k``/``v`` [B, H, C, d] (the HBM cache at
    its power-of-two bucket length); ``lengths`` [B] — the number of
    valid cache entries per row, the just-appended token included.

    ``block_k=None`` consults the autotuner under its ``decode=True``
    cache key (``ops/autotune.py``); explicit ints keep the target-block
    semantics. Raises ValueError on non-tiling shapes — serving goes
    through :func:`decode_dispatch` for guarded dispatch."""
    if q.ndim != 4 or q.shape[2] != 1:
        raise ValueError(f"decode_attention wants q [B,H,1,d]; got {q.shape}")
    B, H, _, d = q.shape
    C = k.shape[2]
    if k.shape != (B, H, C, d) or v.shape != (B, H, C, d):
        raise ValueError(f"q/cache shapes disagree: {q.shape} {k.shape} "
                         f"{v.shape}")
    if block_k is None:
        from . import autotune as _autotune
        tuned = _autotune.get_blocks(
            1, C, d, q.dtype, True, decode=True, page=page,
            concrete=not isinstance(q, jax.core.Tracer))
        bk = tuned[1] if tuned is not None else None
        if bk is not None and C % bk:
            bk = pick_block(C)  # belt: a poisoned entry must not truncate
    else:
        bk = pick_block(C, block_k)
    if bk is None:
        raise ValueError(f"cache length {C} does not tile into decode "
                         "blocks; bucket the cache to a power of two")
    if not fits_vmem_attention(1, bk, d, np.dtype(q.dtype).itemsize):
        raise ValueError(f"decode tiles exceed the VMEM budget "
                         f"(bk={bk}, d={d})")
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    kb = length_bias(lengths, C)
    o, _, _ = _fwd_impl(q.reshape(B * H, 1, d), k.reshape(B * H, C, d),
                        v.reshape(B * H, C, d), kb, float(scale), H, 1, bk,
                        bool(interpret))
    return o.reshape(B, H, 1, d)


def cache_insert(cache, new, lengths, write=None):
    """Append one token window's K or V rows into a bucketed cache:
    ``cache`` [B, H, C, d], ``new`` [B, H, k, d] (k = 1 for plain decode,
    k > 1 for a speculative verify window), written at positions
    ``lengths[b] .. lengths[b]+k-1`` per row via a vmapped
    ``dynamic_update_slice`` — O(B*H*k*d) bytes touched instead of a
    one-hot select over the whole cache, and with donated buffers (the
    serving decode executables) XLA updates the HBM cache in place.

    ``write`` [B] (optional 0/1): rows with ``write == 0`` keep their
    cache bit-identical — the window's values at the target positions are
    replaced by a gather of what is already there, so a full-cache
    select is never needed (the continuous batcher's inactive slots).
    Out-of-range ``lengths`` clamp (XLA slice semantics) and the gathered
    old values make the clamped write a no-op, so a freed slot's stale
    length can never corrupt a neighbour."""
    lengths = jnp.asarray(lengths).astype(jnp.int32)
    new = new.astype(cache.dtype)
    if write is not None:
        kw = new.shape[2]
        old = jax.vmap(
            lambda c, l: jax.lax.dynamic_slice(
                c, (0, l, 0), (c.shape[0], kw, c.shape[2])))(cache, lengths)
        keep = jnp.asarray(write).astype(bool)[:, None, None, None]
        new = jnp.where(keep, new, old)
    return jax.vmap(
        lambda c, n, l: jax.lax.dynamic_update_slice(c, n, (0, l, 0)))(
        cache, new, lengths)


# --------------------------------------------------------------------------
# paged KV cache: page-table gather/scatter over a token-row pool (ISSUE 12)
# --------------------------------------------------------------------------
# The pool stores one layer's K or V cache as [n_pages * page_size, H, d]
# token rows; a host-side page table [S, MP] maps each slot's logical page
# j to a physical page id. Shapes stay static (the serving zero-compile
# contract): the gathered per-slot cache is always [S, H, MP*page_size, d]
# and the usual length bias masks the unoccupied tail, so ragged occupancy
# and partially-filled pages stay exact. Page id 0 is reserved as the
# zero page: unallocated table entries point there, and write-gated rows
# scatter back the value they gathered, so a freed/inactive slot can never
# corrupt a page another slot (or the prefix registry) still references.

def paged_positions(page_table, positions, page_size: int):
    """Physical token rows for logical positions: ``page_table`` [S, MP]
    int32, ``positions`` [S, k] -> [S, k] int32. Out-of-table positions
    clamp to the last page entry (XLA gather semantics) — callers gate
    those writes, mirroring ``cache_insert``'s stale-length contract."""
    P = int(page_size)
    positions = jnp.asarray(positions).astype(jnp.int32)
    pi = jnp.clip(positions // P, 0, page_table.shape[1] - 1)
    page = jnp.take_along_axis(page_table, pi, axis=1)
    return page * P + positions % P


def paged_gather(pool, page_table, page_size: int):
    """Materialize per-slot caches from the pool: ``pool``
    [NP, H, d] token rows, ``page_table`` [S, MP] -> [S, H, MP*P, d] —
    the gather-indices form the ISSUE 12 tentpole threads through
    ``decode_attention``/``cached_sdpa``. The gather is a temp (the
    attention kernel reads every valid row anyway); only the POOL is
    persistent HBM, which is what paging shrinks."""
    P = int(page_size)
    S, MP = page_table.shape
    idx = (page_table[:, :, None].astype(jnp.int32) * P
           + jnp.arange(P, dtype=jnp.int32)[None, None, :]).reshape(S, MP * P)
    return jnp.transpose(pool[idx], (0, 2, 1, 3))


def paged_insert(pool, new, lengths, page_table, page_size: int, write=None):
    """Append k tokens' K or V rows into the paged pool: ``new``
    [S, H, k, d] written at logical positions ``lengths[s] + i`` through
    the page table. ``write`` [S] gates rows exactly like
    :func:`cache_insert` (gated rows scatter back the old value — a
    no-op even on the clamped/zero page). The scatter touches O(S*k*H*d)
    bytes; with donated pool buffers XLA updates the pool in place."""
    lengths = jnp.asarray(lengths).astype(jnp.int32)
    new = jnp.asarray(new)
    S, H, k, d = new.shape
    pos = lengths[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
    rows = paged_positions(page_table, pos, page_size).reshape(S * k)
    upd = jnp.transpose(new, (0, 2, 1, 3)).reshape(S * k, H, d) \
        .astype(pool.dtype)
    if write is not None:
        keep = jnp.repeat(jnp.asarray(write).astype(bool), k)[:, None, None]
        upd = jnp.where(keep, upd, pool[rows])
    return pool.at[rows].set(upd)


def page_rows(pages, page_size: int):
    """Token rows covering WHOLE pages: ``pages`` [n] page ids ->
    [n * page_size] int32 rows — the index form shared by
    :func:`page_export` / :func:`page_import` (ISSUE 18). Page id 0
    (padding in a fixed-size migration bucket) resolves to the reserved
    zero page; importers gate those rows off."""
    P = int(page_size)
    pages = jnp.asarray(pages).astype(jnp.int32)
    return (pages[:, None] * P
            + jnp.arange(P, dtype=jnp.int32)[None, :]).reshape(-1)


def page_export(pool, rows):
    """Gather whole pages out of one layer's pool in ONE device call:
    ``pool`` [NP*P, H, d], ``rows`` [n*P] -> [n*P, H, d] payload block
    (ISSUE 18 KV-page migration — never a device round-trip per page)."""
    return pool[rows]


def page_import(pool, rows, payload, gate):
    """Scatter whole pages into one layer's pool in ONE device call.
    ``gate`` [n*P] bool follows the write-gate contract of
    :func:`paged_insert`: gated-off rows (bucket padding pointing at the
    zero page) scatter back the value they gathered — a no-op — so an
    import can never corrupt the zero page or a page another stream
    holds."""
    upd = jnp.asarray(payload).astype(pool.dtype)
    upd = jnp.where(jnp.asarray(gate).astype(bool)[:, None, None],
                    upd, pool[rows])
    return pool.at[rows].set(upd)


# --------------------------------------------------------------------------
# multi-query decode: verify k speculated tokens in ONE step (ISSUE 12)
# --------------------------------------------------------------------------

def reference_decode_multiquery(q, k, v, lengths, scale=None):
    """Quadratic reference for the speculative Tq=k verify window: query
    i sits at global position ``lengths[b] + i`` and attends cache
    columns ``< lengths[b] + 1 + i`` (its own just-appended token
    included) — causal WITHIN the window, full visibility of the prefix.
    Shares :func:`reference_attention`'s f32 numerics."""
    C = k.shape[2]
    Tq = q.shape[2]
    lengths = jnp.asarray(lengths).astype(jnp.int32)
    col = jnp.arange(C, dtype=jnp.int32)[None, None, :]
    row = jnp.arange(Tq, dtype=jnp.int32)[None, :, None]
    valid = col < lengths[:, None, None] + 1 + row
    bias = jnp.where(valid, jnp.float32(0.0), jnp.float32(_NEG))[:, None]
    return reference_attention(q, k, v, bias=bias, scale=scale)


def decode_multiquery_attention(q, k, v, lengths, scale=None, *,
                                block_k: Optional[int] = None,
                                interpret: bool = False, page: int = 0):
    """Fused multi-query decode: the window-causal kernel at ``bq = Tq=k``
    (forward only — verification is inference) streaming the cache in
    ``block_k`` tiles with the per-row base length driving the in-kernel
    causal mask. ``q`` [B, H, k, d]; ``k``/``v`` [B, H, C, d];
    ``lengths`` [B] = valid cache entries BEFORE the k-token window (the
    window's own rows already appended at ``lengths .. lengths+k-1``)."""
    if q.ndim != 4 or q.shape[2] < 1:
        raise ValueError(f"decode_multiquery wants q [B,H,k,d]; got "
                         f"{q.shape}")
    B, H, Tq, d = q.shape
    C = k.shape[2]
    if k.shape != (B, H, C, d) or v.shape != (B, H, C, d):
        raise ValueError(f"q/cache shapes disagree: {q.shape} {k.shape} "
                         f"{v.shape}")
    if block_k is None:
        from . import autotune as _autotune
        tuned = _autotune.get_blocks(
            Tq, C, d, q.dtype, True, decode=True, page=page,
            concrete=not isinstance(q, jax.core.Tracer))
        bk = tuned[1] if tuned is not None else None
        if bk is not None and C % bk:
            bk = pick_block(C)
    else:
        bk = pick_block(C, block_k)
    if bk is None:
        raise ValueError(f"cache length {C} does not tile into decode "
                         "blocks; bucket the cache to a power of two")
    if not fits_vmem_attention(Tq, bk, d, np.dtype(q.dtype).itemsize):
        raise ValueError(f"multi-query decode tiles exceed the VMEM "
                         f"budget (Tq={Tq}, bk={bk}, d={d})")
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    lens2 = jnp.broadcast_to(
        jnp.asarray(lengths).astype(jnp.int32)[:, None], (B, _LANES))
    o = _mq_impl(q.reshape(B * H, Tq, d), k.reshape(B * H, C, d),
                 v.reshape(B * H, C, d), lens2, float(scale), H, bk,
                 bool(interpret))
    return o.reshape(B, H, Tq, d)


# --------------------------------------------------------------------------
# dispatch: mode + counters (zero-silent-fallback observability)
# --------------------------------------------------------------------------

_COUNTER_KEYS = ("fused", "fallback_mode", "fallback_platform",
                 "fallback_shape", "fallback_bias", "fallback_dtype",
                 "fallback_vmem",
                 # decode decisions ride the same registry counter so the
                 # serving dispatch mix shows up on the same /metrics family
                 "decode_fused", "decode_fallback_mode",
                 "decode_fallback_platform", "decode_fallback_shape",
                 "decode_fallback_dtype", "decode_fallback_vmem",
                 # ISSUE 12: Tq>1 decisions split out of the one
                 # decode_fallback_shape slug — a query-bank reference
                 # route (by design) is distinguishable from the
                 # speculative verify either taking its fused Tq=k path
                 # (decode_multiquery) or silently losing it
                 # (decode_multiquery_fallback)
                 "decode_fallback_multiquery", "decode_multiquery",
                 "decode_multiquery_fallback",
                 # ISSUE 17: tensor-parallel serving decisions. Armed by
                 # tp_shard_context during engine lowering: heads divide
                 # the model axis -> per-shard dispatch under shard_map;
                 # otherwise the GSPMD-partitioned einsum path. Both
                 # counted — zero silent fallbacks extends to TP.
                 "decode_tp_shard_map", "decode_fallback_tp_gspmd",
                 "decode_multiquery_tp_shard_map",
                 "decode_multiquery_fallback_tp_gspmd",
                 "fallback_tp_gspmd")
# dispatch decisions live in the process-wide MetricsRegistry (ISSUE 6):
# one counter, labeled by decision, so `GET /metrics` exposes the
# fused-vs-fallback mix; counters()/reset_counters() below are the
# pre-registry views tier-1 asserts against.
from ..runtime import telemetry as _tel  # noqa: E402  (stdlib-only import)

_DISPATCH = _tel.counter(
    "flash_attention.dispatch",
    "attention dispatch decisions at trace time (fused vs fallback_*)")
_state = {"mode": os.environ.get("DL4J_TPU_FLASH_ATTENTION", "auto"),
          "tp_mesh": None, "tp_axis": None}
_FUSABLE_DTYPES = (jnp.float32, jnp.bfloat16, jnp.float16)


class tp_shard_context:
    """Arm tensor-parallel dispatch for the duration of a trace (ISSUE
    17). The serving engines enter this around ``jit(...).lower(...)``
    when params/KV are model-axis sharded; while armed,
    :func:`decode_dispatch` / :func:`decode_multiquery_dispatch` route
    per-shard under ``shard_map`` when the head axis divides the model
    axis, and :func:`attention` + indivisible decode shapes take the
    GSPMD-partitioned einsum path — every decision counted. Consulted at
    TRACE time only (same contract as :func:`set_mode`): warmed
    executables keep whichever path was traced into them. Re-entrant;
    not thread-safe (lowering happens under the engine lock)."""

    def __init__(self, mesh, axis: str):
        self.mesh = mesh
        self.axis = axis
        self._prev = (None, None)

    def __enter__(self):
        self._prev = (_state["tp_mesh"], _state["tp_axis"])
        _state["tp_mesh"] = self.mesh
        _state["tp_axis"] = self.axis
        return self

    def __exit__(self, *exc):
        _state["tp_mesh"], _state["tp_axis"] = self._prev
        return False


def _tp_armed():
    """(mesh, axis, k) while a tp_shard_context is live, else None."""
    mesh, axis = _state["tp_mesh"], _state["tp_axis"]
    if mesh is None or axis is None or axis not in mesh.shape:
        return None
    k = int(mesh.shape[axis])
    return (mesh, axis, k) if k > 1 else None


def mode() -> str:
    return _state["mode"]


def set_mode(m: str) -> str:
    """"auto" (TPU -> kernel, elsewhere -> reference), "force" (kernel
    everywhere — Pallas interpret off-TPU; how the CPU tier-1 suite
    exercises the kernel), "off" (reference everywhere). Returns the
    previous mode so tests can restore it.

    The mode is consulted at TRACE time: functions already jit-compiled
    (an engine's cached train step / output fn, a warmed serving
    executable) keep whichever path was traced into them, with no counter
    bump on later executions. Flip the mode BEFORE building/tracing, or
    invalidate the model's compiled cache (``net._invalidate_compiled()``)
    after flipping."""
    if m not in ("auto", "force", "off"):
        raise ValueError(f"flash attention mode {m!r} not in "
                         "('auto', 'force', 'off')")
    old = _state["mode"]
    _state["mode"] = m
    return old


def counters() -> dict:
    """Dispatch-decision counts. Decisions happen at TRACE time (shapes are
    static), so under jit each compiled call-site counts once, not once per
    execution — the right unit for "did the kernel path get taken". A view
    over the registry's ``flash_attention.dispatch{decision=}`` counter."""
    return {k: int(_DISPATCH.value(decision=k)) for k in _COUNTER_KEYS}


def reset_counters() -> None:
    _DISPATCH.zero()


def _route(q, k, v, bias) -> Optional[str]:
    """None = fuse; otherwise the fallback counter key."""
    if _state["mode"] == "off":
        return "fallback_mode"
    if _state["mode"] != "force" and not _tpu_available():
        return "fallback_platform"
    if q.ndim != 4 or k.shape != v.shape or q.shape[:2] != k.shape[:2] \
            or q.shape[-1] != k.shape[-1]:
        return "fallback_shape"
    if q.dtype not in _FUSABLE_DTYPES:
        return "fallback_dtype"
    bq = pick_block(q.shape[2])
    bk = pick_block(k.shape[2])
    if bq is None or bk is None:
        return "fallback_shape"
    if bias is not None and _key_bias(bias, q.shape[0], k.shape[2]) is None:
        return "fallback_bias"
    if not fits_vmem_attention(bq, bk, q.shape[-1],
                               np.dtype(q.dtype).itemsize):
        return "fallback_vmem"
    return None


def attention(q, k, v, bias=None, scale: Optional[float] = None):
    """Guarded attention dispatch: the flash kernel when the route is clear,
    the f32-softmax reference path otherwise. Layers and the SameDiff
    ``attention.fused_sdpa`` op both enter here.

    Under an armed :class:`tp_shard_context` (TP prefill lowering) the
    reference einsum path is taken unconditionally: GSPMD partitions the
    head-sharded contractions itself and the decision is counted under
    ``fallback_tp_gspmd`` (not silent)."""
    if _tp_armed() is not None:
        _DISPATCH.inc(decision="fallback_tp_gspmd")
        return reference_attention(q, k, v, bias, scale)
    reason = _route(q, k, v, bias)
    if reason is None:
        _DISPATCH.inc(decision="fused")
        return flash_attention(q, k, v, bias, scale,
                               interpret=not _tpu_available())
    _DISPATCH.inc(decision=reason)
    return reference_attention(q, k, v, bias, scale)


def _route_decode(q, k, v) -> Optional[str]:
    """None = fuse the decode kernel; otherwise the fallback counter key
    (every key prefixed ``decode_`` so the serving mix is separable from
    the one-shot dispatch on the same registry counter)."""
    if _state["mode"] == "off":
        return "decode_fallback_mode"
    if _state["mode"] != "force" and not _tpu_available():
        return "decode_fallback_platform"
    if q.ndim != 4 or q.shape[2] != 1 or k.shape != v.shape or \
            q.shape[:2] != k.shape[:2] or q.shape[-1] != k.shape[-1]:
        return "decode_fallback_shape"
    if q.dtype not in _FUSABLE_DTYPES:
        return "decode_fallback_dtype"
    bk = pick_block(k.shape[2])
    if bk is None:
        return "decode_fallback_shape"
    if not fits_vmem_attention(1, bk, q.shape[-1],
                               np.dtype(q.dtype).itemsize):
        return "decode_fallback_vmem"
    return None


def _decode_dispatch_local(q, k, v, lengths, scale=None, page: int = 0):
    """The per-device decode dispatch body: single-query flash kernel
    when the route is clear, f32-softmax reference otherwise. Called
    directly (bypassing TP routing) from inside the shard_map inner —
    the TP context is still armed during that trace and re-entering
    :func:`decode_dispatch` would recurse."""
    if q.ndim == 4 and q.shape[2] == 1:
        reason = _route_decode(q, k, v)
    elif q.ndim == 4 and q.shape[2] > 1:
        reason = "decode_fallback_multiquery"
    else:
        reason = "decode_fallback_shape"
    if reason is None:
        _DISPATCH.inc(decision="decode_fused")
        return decode_attention(q, k, v, lengths, scale, page=page,
                                interpret=not _tpu_available())
    _DISPATCH.inc(decision=reason)
    C = k.shape[2]
    bias = length_bias(lengths, C)[:, None, None, :]
    return reference_attention(q, k, v, bias=bias, scale=scale)


def _tp_head_shard(local_fn, armed, q, k, v, lengths, scale, page):
    """Run a per-device dispatch body under shard_map with heads (axis 1
    of the [B, H, *, d] operands) split over the model axis. ``lengths``
    stays replicated; softmax is per-head so no cross-shard collective
    is needed (check_rep=False: the head axis is genuinely sharded)."""
    from jax.experimental.shard_map import shard_map
    mesh, axis, _ = armed
    spec4 = P(None, axis, None, None)

    def inner(q_, k_, v_, lengths_):
        return local_fn(q_, k_, v_, lengths_, scale=scale, page=page)

    return shard_map(inner, mesh=mesh,
                     in_specs=(spec4, spec4, spec4, P()),
                     out_specs=spec4, check_rep=False)(q, k, v, lengths)


def decode_dispatch(q, k, v, lengths, scale=None, page: int = 0):
    """Guarded decode dispatch: the single-query flash kernel when the
    route is clear, the f32-softmax reference otherwise. The KV-cache
    layers and the SameDiff ``attention.cached_sdpa`` op both enter here.
    ``q`` with Tq > 1 (e.g. LearnedSelfAttention's query bank — uniform
    visibility over the valid cache, NOT the speculative verify's causal
    window) takes the reference path, counted under its own
    ``decode_fallback_multiquery`` slug (ISSUE 12 satellite) so it never
    blends with genuine shape failures or the verify path's decisions.

    Under an armed :class:`tp_shard_context` (ISSUE 17): heads divisible
    by the model-axis size run the per-shard body under ``shard_map``
    (``decode_tp_shard_map``); otherwise the GSPMD-partitioned reference
    einsum (``decode_fallback_tp_gspmd``). Both counted."""
    armed = _tp_armed()
    if armed is not None and q.ndim == 4:
        if q.shape[1] % armed[2] == 0:
            _DISPATCH.inc(decision="decode_tp_shard_map")
            return _tp_head_shard(_decode_dispatch_local, armed,
                                  q, k, v, lengths, scale, page)
        _DISPATCH.inc(decision="decode_fallback_tp_gspmd")
        C = k.shape[2]
        bias = length_bias(lengths, C)[:, None, None, :]
        return reference_attention(q, k, v, bias=bias, scale=scale)
    return _decode_dispatch_local(q, k, v, lengths, scale=scale, page=page)


def _route_multiquery(q, k, v) -> Optional[str]:
    """None = fuse the Tq=k window-causal verify kernel; otherwise the
    single ``decode_multiquery_fallback`` slug — the signal the ISSUE 12
    satellite asks for: speculative verify silently losing its fused
    path is one visible number on ``/metrics``."""
    if _state["mode"] == "off":
        return "decode_multiquery_fallback"
    if _state["mode"] != "force" and not _tpu_available():
        return "decode_multiquery_fallback"
    if q.ndim != 4 or q.shape[2] < 1 or k.shape != v.shape or \
            q.shape[:2] != k.shape[:2] or q.shape[-1] != k.shape[-1]:
        return "decode_multiquery_fallback"
    if q.dtype not in _FUSABLE_DTYPES:
        return "decode_multiquery_fallback"
    bk = pick_block(k.shape[2])
    if bk is None:
        return "decode_multiquery_fallback"
    if not fits_vmem_attention(q.shape[2], bk, q.shape[-1],
                               np.dtype(q.dtype).itemsize):
        return "decode_multiquery_fallback"
    return None


def _decode_multiquery_local(q, k, v, lengths, scale=None, page: int = 0):
    """Per-device multi-query verify dispatch body (see
    :func:`_decode_dispatch_local` for why the TP wrapper calls this
    directly)."""
    reason = _route_multiquery(q, k, v)
    if reason is None:
        _DISPATCH.inc(decision="decode_multiquery")
        return decode_multiquery_attention(q, k, v, lengths, scale,
                                           page=page,
                                           interpret=not _tpu_available())
    _DISPATCH.inc(decision=reason)
    return reference_decode_multiquery(q, k, v, lengths, scale=scale)


def decode_multiquery_dispatch(q, k, v, lengths, scale=None, page: int = 0):
    """Guarded multi-query decode dispatch (speculative verify, ISSUE
    12): the window-causal Tq=k kernel when the route is clear, the
    reference path with an explicit per-query bias otherwise. ``lengths``
    [B] counts valid cache entries BEFORE the k-token window. Every
    decision is counted (``decode_multiquery`` vs
    ``decode_multiquery_fallback``) — the tier-1 dispatch asserts and
    ``/metrics`` both see a verify that lost its fused path.

    TP routing under an armed :class:`tp_shard_context` mirrors
    :func:`decode_dispatch` (``decode_multiquery_tp_shard_map`` /
    ``decode_multiquery_fallback_tp_gspmd``)."""
    armed = _tp_armed()
    if armed is not None and q.ndim == 4:
        if q.shape[1] % armed[2] == 0:
            _DISPATCH.inc(decision="decode_multiquery_tp_shard_map")
            return _tp_head_shard(_decode_multiquery_local, armed,
                                  q, k, v, lengths, scale, page)
        _DISPATCH.inc(decision="decode_multiquery_fallback_tp_gspmd")
        return reference_decode_multiquery(q, k, v, lengths, scale=scale)
    return _decode_multiquery_local(q, k, v, lengths, scale=scale,
                                    page=page)


@register("attention.fused_sdpa", category="attention")
def fused_sdpa(q, k, v, bias=None, scale: float = 1.0):
    """Fused scaled-dot-product attention graph op: the rewrite target of
    the SameDiff attention-pattern fusion pass (``autodiff/fusion.py``).
    Semantics: softmax((q @ k^T) * scale + bias, axis=-1) @ v — exactly the
    imported ``batch_matmul -> scale -> (mask add) -> softmax ->
    batch_matmul`` chain it replaces, with the softmax in f32. Dispatches
    to the flash kernel for [B,H,T,d] operands on TPU."""
    return attention(q, k, v, bias=bias, scale=float(scale))


@register("attention.cached_sdpa", category="attention",
          differentiable=False)
def cached_sdpa(q, k_new, v_new, k_cache, v_cache, lengths,
                scale: float = 1.0):
    """KV-cached decode-step attention graph op: the rewrite target of the
    SameDiff decode pass (``autodiff/decode.py``), replacing an
    ``attention.fused_sdpa`` site in the one-token decode replay.

    ``q``/``k_new``/``v_new``: this step's projections, [B, H, 1, d];
    ``k_cache``/``v_cache``: [B, H, C, d] HBM cache at its bucket length;
    ``lengths``: [B] valid entries per row BEFORE this token. Appends
    (k_new, v_new) at position ``lengths``, attends the query over the
    ``lengths + 1`` valid entries, and returns
    ``(y, k_cache', v_cache')`` so the cache state threads through the
    graph replay. Inference-only (no VJP — decode never trains).

    The CALLER must keep ``lengths < C``: an out-of-range position
    clamps (XLA slice semantics) and would overwrite the last cache row
    — ``autodiff.decode.DecodeGraph.decode_step`` raises host-side when
    the cache is full, and the serving batcher grows the bucket first."""
    lengths = jnp.asarray(lengths)
    kc = cache_insert(k_cache, k_new, lengths)
    vc = cache_insert(v_cache, v_new, lengths)
    y = decode_dispatch(q, kc, vc, lengths + 1, scale=float(scale))
    return y, kc, vc


@register("attention.paged_sdpa", category="attention",
          differentiable=False)
def paged_sdpa(q, k_new, v_new, k_pool, v_pool, page_table, lengths,
               scale: float = 1.0, page_size: int = 16):
    """Paged-KV decode-step attention graph op (ISSUE 12): the paged twin
    of ``attention.cached_sdpa``, the rewrite target of
    ``autodiff.decode.rewrite_for_decode(..., paged=True)``.

    ``q``/``k_new``/``v_new``: this step's projections, [B, H, Tq, d]
    (Tq = 1 for plain decode, k for a speculative verify window);
    ``k_pool``/``v_pool``: [n_pages*page_size, H, d] token-row pools;
    ``page_table``: [B, MP] int32 physical page ids; ``lengths``: [B]
    valid entries per row BEFORE this window. Appends the window's rows
    through the page table, attends (single-query length-masked, or
    window-causal for Tq > 1), and returns ``(y, k_pool', v_pool')``.
    The CALLER keeps ``lengths + Tq <= MP*page_size`` and forks shared
    pages first (copy-on-write lives host-side in the pool allocator)."""
    lengths = jnp.asarray(lengths)
    kp = paged_insert(k_pool, k_new, lengths, page_table, page_size)
    vp = paged_insert(v_pool, v_new, lengths, page_table, page_size)
    kf = paged_gather(kp, page_table, page_size)
    vf = paged_gather(vp, page_table, page_size)
    if q.shape[2] == 1:
        y = decode_dispatch(q, kf, vf, lengths + 1, scale=float(scale),
                            page=int(page_size))
    else:
        y = decode_multiquery_dispatch(q, kf, vf, lengths,
                                       scale=float(scale),
                                       page=int(page_size))
    return y, kp, vp
