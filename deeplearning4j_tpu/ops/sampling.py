"""On-device sampling primitives for the generative decode loop.

The host decode loop samples with ``int(np.argmax(logits))`` — one
device->host readback per generated token.  These primitives run the
same reductions *inside* the compiled step so a multi-token horizon
(`lax.scan` over k decode iterations) never touches the host:

* :func:`greedy` — argmax over the vocab axis (bit-exact with the host
  oracle, and lowers to a single ``argmax`` primitive the staticcheck
  decode probe counts).
* :func:`categorical` — temperature softmax sampling via the Gumbel
  trick with a threaded PRNG key.
* :func:`top_k` — top-k filtered temperature sampling.
* :func:`eos_hit` — on-device EOS detection feeding the existing
  write-gating masks so finished slots freeze bit-exactly.

A :class:`SamplingSpec` pins the *static* part of the configuration
(method, k) into the engine compile key while temperature stays a
runtime scalar — changing temperature never recompiles.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from . import register

__all__ = [
    "greedy", "categorical", "top_k", "eos_hit",
    "SamplingSpec", "GREEDY",
]

_TEMP_FLOOR = 1e-6


@register("sampling.greedy", "sampling", differentiable=False)
def greedy(logits):
    """Greedy token selection: argmax over the last axis -> int32."""
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


@register("sampling.categorical", "sampling", differentiable=False)
def categorical(logits, key, temperature=1.0):
    """Temperature softmax sampling via the Gumbel-max trick.

    ``argmax(logits/T + Gumbel)`` draws exactly from
    ``softmax(logits/T)`` without normalising on device.
    """
    t = jnp.maximum(jnp.asarray(temperature, logits.dtype), _TEMP_FLOOR)
    g = jax.random.gumbel(key, logits.shape, logits.dtype)
    return jnp.argmax(logits / t + g, axis=-1).astype(jnp.int32)


@register("sampling.top_k", "sampling", differentiable=False)
def top_k(logits, key, k, temperature=1.0):
    """Top-k filtered temperature sampling (k is static)."""
    k = int(k)
    if k <= 0:
        raise ValueError("top_k requires k >= 1")
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    neg = jnp.asarray(jnp.finfo(logits.dtype).min, logits.dtype)
    masked = jnp.where(logits < kth, neg, logits)
    return categorical(masked, key, temperature)


def eos_hit(tokens, eos_ids):
    """Per-slot EOS detection mask.

    ``eos_ids`` holds one int32 id per slot with ``-1`` meaning "no EOS
    for this slot"; returns int32 1 where the freshly sampled token
    terminates the stream.  Feed the complement into the write gate so
    finished slots freeze bit-exactly.
    """
    return ((eos_ids >= 0) & (tokens == eos_ids)).astype(jnp.int32)


@dataclasses.dataclass(frozen=True)
class SamplingSpec:
    """Static sampling configuration threaded into engine compile keys.

    ``method`` is one of ``greedy`` / ``categorical`` / ``top_k``; only
    ``method`` and ``k`` participate in the compile key — temperature is
    a runtime scalar argument of the compiled step.
    """

    method: str = "greedy"
    temperature: float = 1.0
    k: int = 0

    def __post_init__(self):
        if self.method not in ("greedy", "categorical", "top_k"):
            raise ValueError(f"unknown sampling method {self.method!r}")
        if self.method == "top_k" and self.k <= 0:
            raise ValueError("top_k sampling requires k >= 1")

    @property
    def stochastic(self):
        return self.method != "greedy"

    def static_key(self):
        return (self.method, int(self.k))

    def build(self):
        """Return ``fn(logits, key, temperature) -> int32 tokens``."""
        if self.method == "greedy":
            return lambda logits, key, temperature: greedy(logits)
        if self.method == "categorical":
            return categorical
        k = int(self.k)
        return lambda logits, key, temperature: top_k(
            logits, key, k, temperature)


GREEDY = SamplingSpec()
