"""Elementwise / pairwise / shape / linalg / scatter / sort op families.

TPU-native equivalents of libnd4j's legacy transform/pairwise/broadcast/
scalar loop families and the declarable ``parity_ops``/``transforms``
generics (reference: ``libnd4j/include/loops/``,
``libnd4j/include/ops/declarable/generic/{parity_ops,transforms,blas}``† per
SURVEY.md §2.1; reference mount was empty, citations upstream-relative,
unverified).

These are thin named registrations over jnp/lax: XLA is the executor; the
catalog entry is the contract used by the SameDiff-equivalent graph layer's
serialization (name -> callable) and by import frontends. DL4J-specific
semantics (rsub/rdiv argument order, OldSoftMax-style shifted softmax, etc.)
are preserved where they differ from numpy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from . import register
from ..environment import precision_for

# -- pairwise arithmetic (broadcasting; DL4J pairwise + broadcast families) --
register("math.add", category="pairwise")(jnp.add)
register("math.sub", category="pairwise")(jnp.subtract)
register("math.mul", category="pairwise")(jnp.multiply)
register("math.div", category="pairwise")(jnp.divide)
register("math.floordiv", category="pairwise")(jnp.floor_divide)
register("math.mod", category="pairwise")(jnp.mod)
register("math.pow", category="pairwise")(jnp.power)
register("math.maximum", category="pairwise")(jnp.maximum)
register("math.minimum", category="pairwise")(jnp.minimum)
register("math.atan2", category="pairwise")(jnp.arctan2)


@register("math.rsub", category="pairwise")
def rsub(a, b):
    """DL4J rsub: b - a (reversed operand order)."""
    return b - a


@register("math.rdiv", category="pairwise")
def rdiv(a, b):
    """DL4J rdiv: b / a (reversed operand order)."""
    return b / a


@register("math.squared_difference", category="pairwise")
def squared_difference(a, b):
    return jnp.square(a - b)


# -- scalar/elementwise transforms (DL4J transform family) -------------------
register("math.neg", category="transform")(jnp.negative)
register("math.abs", category="transform")(jnp.abs)
register("math.sqrt", category="transform")(jnp.sqrt)
register("math.square", category="transform")(jnp.square)
register("math.exp", category="transform")(jnp.exp)
register("math.expm1", category="transform")(jnp.expm1)
register("math.log", category="transform")(jnp.log)
register("math.log1p", category="transform")(jnp.log1p)
register("math.log2", category="transform")(jnp.log2)
register("math.sin", category="transform")(jnp.sin)
register("math.cos", category="transform")(jnp.cos)
register("math.tan", category="transform")(jnp.tan)
register("math.asin", category="transform")(jnp.arcsin)
register("math.acos", category="transform")(jnp.arccos)
register("math.atan", category="transform")(jnp.arctan)
register("math.sinh", category="transform")(jnp.sinh)
register("math.cosh", category="transform")(jnp.cosh)
register("math.floor", category="transform", differentiable=False)(jnp.floor)
register("math.ceil", category="transform", differentiable=False)(jnp.ceil)
register("math.round", category="transform", differentiable=False)(jnp.round)
register("math.sign", category="transform", differentiable=False)(jnp.sign)
register("math.reciprocal", category="transform")(jnp.reciprocal)
register("math.rsqrt", category="transform")(lax.rsqrt)
register("math.erf", category="transform")(jax.scipy.special.erf)
register("math.erfc", category="transform")(jax.scipy.special.erfc)


@register("math.clip", category="transform")
def clip(a, min_value, max_value):
    """DL4J clipbyvalue."""
    return jnp.clip(a, min_value, max_value)


@register("math.clip_by_norm", category="transform")
def clip_by_norm(a, clip_norm, axis=None):
    norm = jnp.sqrt(jnp.sum(jnp.square(a), axis=axis, keepdims=axis is not None))
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(norm, 1e-12))
    return a * scale


# -- comparisons / logic (DL4J conditions; non-differentiable) ---------------
register("math.equal", category="compare", differentiable=False)(jnp.equal)
register("math.not_equal", category="compare", differentiable=False)(jnp.not_equal)
register("math.greater", category="compare", differentiable=False)(jnp.greater)
register("math.greater_equal", category="compare", differentiable=False)(jnp.greater_equal)
register("math.less", category="compare", differentiable=False)(jnp.less)
register("math.less_equal", category="compare", differentiable=False)(jnp.less_equal)
register("math.logical_and", category="compare", differentiable=False)(jnp.logical_and)
register("math.logical_or", category="compare", differentiable=False)(jnp.logical_or)
register("math.logical_not", category="compare", differentiable=False)(jnp.logical_not)
register("math.logical_xor", category="compare", differentiable=False)(jnp.logical_xor)
register("math.isnan", category="compare", differentiable=False)(jnp.isnan)
register("math.isinf", category="compare", differentiable=False)(jnp.isinf)
register("math.where", category="compare")(jnp.where)


# -- blas / linalg -----------------------------------------------------------
@register("linalg.mmul", category="blas")
def mmul(a, b, transpose_a=False, transpose_b=False):
    """DL4J mmul (gemm). Rides the MXU; f32 precision policy applies."""
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b, precision=precision_for(a, b))


@register("linalg.tensordot", category="blas")
def tensordot(a, b, axes=2):
    return jnp.tensordot(a, b, axes=axes, precision=precision_for(a, b))


@register("linalg.einsum", category="blas")
def einsum(*operands, equation):
    """Einstein summation (TF-import Einsum nodes land here; contractions
    ride the MXU with the f32 precision policy)."""
    return jnp.einsum(equation, *operands,
                      precision=precision_for(*operands))


register("linalg.outer", category="blas")(jnp.outer)
register("linalg.diag", category="linalg")(jnp.diag)
register("linalg.diag_part", category="linalg")(jnp.diagonal)
register("linalg.trace", category="linalg")(jnp.trace)
register("linalg.inverse", category="linalg")(jnp.linalg.inv)
register("linalg.cholesky", category="linalg")(jnp.linalg.cholesky)
register("linalg.solve", category="linalg")(jnp.linalg.solve)
register("linalg.lstsq", category="linalg", differentiable=False)(jnp.linalg.lstsq)
register("linalg.matrix_rank", category="linalg", differentiable=False)(jnp.linalg.matrix_rank)
register("linalg.svd", category="linalg")(jnp.linalg.svd)
register("linalg.eigh", category="linalg")(jnp.linalg.eigh)
register("linalg.qr", category="linalg")(jnp.linalg.qr)
register("linalg.det", category="linalg")(jnp.linalg.det)
register("linalg.norm", category="linalg")(jnp.linalg.norm)


# -- shape / structural ------------------------------------------------------
register("shape.reshape", category="shape")(jnp.reshape)


@register("shape.reshape_onnx", category="shape")
def _reshape_onnx(x, shape, allowzero=0):
    """ONNX Reshape semantics: a 0 entry copies the input dim at that
    position (unless ``allowzero``), -1 infers as usual. Resolved at trace
    time from the static input shape — torch RNN exports reshape
    bidirectional outputs with 0-entries."""
    shape = list(shape)
    if not allowzero:
        shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return jnp.reshape(x, shape)
register("shape.transpose", category="shape")(jnp.transpose)
register("shape.permute", category="shape")(jnp.transpose)  # DL4J name
register("shape.squeeze", category="shape")(jnp.squeeze)
register("shape.expand_dims", category="shape")(jnp.expand_dims)
register("shape.concat", category="shape")(jnp.concatenate)
register("shape.stack", category="shape")(jnp.stack)


@register("shape.concat_v", category="shape")
def _concat_v(*arrays, axis=0):
    """Variadic concat: inputs as separate positional args, the calling
    convention graph layers (SameDiff/import frontends) use — jnp's
    sequence-arg concatenate can't be applied per recorded input."""
    return jnp.concatenate(arrays, axis=axis)


@register("shape.stack_v", category="shape")
def _stack_v(*arrays, axis=0):
    """Variadic stack (see shape.concat_v)."""
    return jnp.stack(arrays, axis=axis)


@register("shape.flatten2d", category="shape")
def _flatten2d(x):
    """[B, ...] -> [B, prod(...)]: ONNX Flatten(axis=1) / keras Flatten —
    'keep the batch dim' is not expressible as a static reshape attr."""
    return jnp.reshape(x, (x.shape[0], -1))
register("shape.split", category="shape")(jnp.split)


@register("shape.unstack", category="shape")
def _unstack(x, axis=0):
    """TF Unpack / nd4j unstack: split along ``axis`` into rank-1-lower
    pieces (multi-output; pairs with shape.stack)."""
    n = x.shape[axis]
    return tuple(jnp.squeeze(s, axis=axis)
                 for s in jnp.split(x, n, axis=axis))
register("shape.tile", category="shape")(jnp.tile)
register("shape.repeat", category="shape")(jnp.repeat)
register("shape.flip", category="shape")(jnp.flip)
register("shape.roll", category="shape")(jnp.roll)
register("shape.pad", category="shape")(jnp.pad)
register("shape.broadcast_to", category="shape")(jnp.broadcast_to)
register("shape.gather", category="shape")(jnp.take)
register("shape.take_along_axis", category="shape")(jnp.take_along_axis)
register("shape.tril", category="shape")(jnp.tril)
register("shape.triu", category="shape")(jnp.triu)


@register("shape.strided_slice", category="shape", differentiable=False)
def strided_slice(a, begin, end, strides=None):
    idx = tuple(slice(b, e, s) for b, e, s in
                zip(begin, end, strides or [1] * len(begin)))
    return a[idx]


@register("shape.strided_slice_v2", category="shape")
def strided_slice_v2(a, spec):
    """General numpy-style indexing from a serializable per-dim spec (the
    lowering target for TF StridedSlice with begin/end/ellipsis/new-axis/
    shrink-axis masks). Each spec entry is one of::

        ["slice", begin|None, end|None, stride]   # a[b:e:s]
        ["index", i]                              # a[i] (shrink axis)
        ["newaxis"]                               # a[None]
        ["ellipsis"]                              # a[...]
    """
    idx = []
    for ent in spec:
        kind = ent[0]
        if kind == "slice":
            idx.append(slice(ent[1], ent[2], ent[3]))
        elif kind == "index":
            idx.append(int(ent[1]))
        elif kind == "newaxis":
            idx.append(None)
        elif kind == "ellipsis":
            idx.append(Ellipsis)
        else:
            raise ValueError(f"bad strided-slice spec entry {ent!r}")
    return a[tuple(idx)]


@register("math.cast", category="math")
def cast(a, dtype="float32"):
    """Explicit dtype conversion (TF Cast / nd4j CastOp). ``dtype`` is a
    string for graph-serializability; bfloat16 supported via jnp."""
    return jnp.asarray(a).astype(jnp.dtype(dtype))


@register("shape.shape_of", category="shape", differentiable=False)
def shape_of(a):
    """TF Shape: the (static under jit) shape as an int32 vector."""
    return jnp.asarray(a.shape, jnp.int32)


@register("shape.one_hot", category="shape", differentiable=False)
def one_hot(indices, depth, dtype=jnp.float32):
    return jax.nn.one_hot(jnp.asarray(indices, jnp.int32), depth, dtype=dtype)


# -- sort / search / scatter (libnd4j helpers: sort, topk, scatter) ----------
register("sort.sort", category="sort")(jnp.sort)
register("sort.argsort", category="sort", differentiable=False)(jnp.argsort)


@register("sort.top_k", category="sort", differentiable=False)
def top_k(a, k):
    """values, indices of the k largest along the last axis (DL4J top_k)."""
    return lax.top_k(a, k)


@register("sort.in_top_k", category="sort", differentiable=False)
def in_top_k(predictions, targets, k):
    _, idx = lax.top_k(predictions, k)
    return jnp.any(idx == jnp.asarray(targets)[:, None], axis=-1)


@register("scatter.update", category="scatter")
def scatter_update(a, indices, updates):
    return a.at[jnp.asarray(indices, jnp.int32)].set(updates)


@register("scatter.add", category="scatter")
def scatter_add(a, indices, updates):
    return a.at[jnp.asarray(indices, jnp.int32)].add(updates)


@register("scatter.mul", category="scatter")
def scatter_mul(a, indices, updates):
    return a.at[jnp.asarray(indices, jnp.int32)].multiply(updates)


@register("scatter.max", category="scatter")
def scatter_max(a, indices, updates):
    return a.at[jnp.asarray(indices, jnp.int32)].max(updates)


@register("scatter.segment_sum", category="scatter")
def segment_sum(data, segment_ids, num_segments):
    return jax.ops.segment_sum(data, jnp.asarray(segment_ids, jnp.int32),
                               num_segments=num_segments)


@register("scatter.segment_mean", category="scatter")
def segment_mean(data, segment_ids, num_segments):
    """libnd4j ``segment_mean`` / ``unsorted_segment_mean`` (our segment ops
    are all unsorted-tolerant — jax.ops handles unsorted ids)."""
    ids = jnp.asarray(segment_ids, jnp.int32)
    s = jax.ops.segment_sum(data, ids, num_segments=num_segments)
    n = jax.ops.segment_sum(jnp.ones(data.shape[:1], data.dtype), ids,
                            num_segments=num_segments)
    shape = (num_segments,) + (1,) * (data.ndim - 1)
    return s / jnp.maximum(n.reshape(shape), 1)


@register("scatter.segment_max", category="scatter")
def segment_max(data, segment_ids, num_segments):
    return jax.ops.segment_max(data, jnp.asarray(segment_ids, jnp.int32),
                               num_segments=num_segments)


@register("scatter.segment_min", category="scatter")
def segment_min(data, segment_ids, num_segments):
    return jax.ops.segment_min(data, jnp.asarray(segment_ids, jnp.int32),
                               num_segments=num_segments)


@register("scatter.segment_prod", category="scatter")
def segment_prod(data, segment_ids, num_segments):
    return jax.ops.segment_prod(data, jnp.asarray(segment_ids, jnp.int32),
                                num_segments=num_segments)


# -- accumulation / misc -----------------------------------------------------
register("math.cumprod", category="reduce")(jnp.cumprod)


@register("math.fmod", category="pairwise")
def fmod(a, b):
    return jnp.fmod(a, b)
