"""Reduction / index / statistics ops registered in the catalog.

TPU-native equivalent of libnd4j's legacy reduce / indexreduce /
summarystats loop families (reference: ``libnd4j/include/loops/``† per
SURVEY.md §2.1; reference mount was empty, citation upstream-relative,
unverified). These exist as named catalog entries for the graph layer and
coverage ledger; the Tensor facade calls jnp directly.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import register

register("reduce.sum", category="reduce")(jnp.sum)
register("reduce.mean", category="reduce")(jnp.mean)
register("reduce.max", category="reduce")(jnp.max)
register("reduce.min", category="reduce")(jnp.min)
register("reduce.prod", category="reduce")(jnp.prod)
register("reduce.std", category="reduce")(jnp.std)
register("reduce.var", category="reduce")(jnp.var)
register("reduce.argmax", category="indexreduce", differentiable=False)(jnp.argmax)
register("reduce.argmin", category="indexreduce", differentiable=False)(jnp.argmin)
register("reduce.cumsum", category="reduce")(jnp.cumsum)


@register("reduce.norm1", category="reduce")
def norm1(a, axis=None, keepdims=False):
    return jnp.sum(jnp.abs(a), axis=axis, keepdims=keepdims)


@register("reduce.norm2", category="reduce")
def norm2(a, axis=None, keepdims=False):
    return jnp.sqrt(jnp.sum(jnp.square(a), axis=axis, keepdims=keepdims))


@register("reduce.normmax", category="reduce")
def normmax(a, axis=None, keepdims=False):
    return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdims)


@register("reduce.logsumexp", category="reduce")
def logsumexp(a, axis=None, keepdims=False):
    m = jnp.max(a, axis=axis, keepdims=True)
    out = jnp.log(jnp.sum(jnp.exp(a - m), axis=axis, keepdims=True)) + m
    return out if keepdims else jnp.squeeze(out, axis=axis)
