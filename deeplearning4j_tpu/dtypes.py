"""Data type system.

TPU-native equivalent of the nd4j/libnd4j dtype system
(reference: ``libnd4j/include/array/ArrayOptions.h``†,
``nd4j-api .../linalg/api/buffer/DataType.java``† — paths per SURVEY.md §2.1/2.2;
reference mount was empty, citations are upstream-relative, unverified).

Divergences (deliberate, TPU-first):
- ``bfloat16`` is a first-class citizen (native on the MXU); DL4J treats it as
  exotic.
- ``float64`` is supported but discouraged on TPU (software emulation); it is
  kept for grad-check oracles on CPU.
- UTF8/compressed buffer types are out of scope (no string tensors in XLA).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# DL4J-style names -> jnp dtypes. Keys mirror org.nd4j.linalg.api.buffer.DataType.
_NAME_TO_DTYPE = {
    "BOOL": jnp.bool_,
    "INT8": jnp.int8,
    "INT16": jnp.int16,
    "INT32": jnp.int32,
    "INT64": jnp.int64,
    "UINT8": jnp.uint8,
    "UINT16": jnp.uint16,
    "UINT32": jnp.uint32,
    "UINT64": jnp.uint64,
    "FLOAT16": jnp.float16,
    "BFLOAT16": jnp.bfloat16,
    "FLOAT": jnp.float32,
    "DOUBLE": jnp.float64,
    # Aliases (numpy-style, accepted everywhere a dtype name is accepted)
    "bool": jnp.bool_,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "uint8": jnp.uint8,
    "uint16": jnp.uint16,
    "uint32": jnp.uint32,
    "uint64": jnp.uint64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
}

bool_ = jnp.bool_
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
uint64 = jnp.uint64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64

FLOATING = (jnp.float16, jnp.bfloat16, jnp.float32, jnp.float64)


def resolve(dtype) -> np.dtype:
    """Resolve a dtype-ish value (DL4J name, numpy name, np/jnp dtype) to numpy dtype."""
    if isinstance(dtype, str):
        try:
            return np.dtype(_NAME_TO_DTYPE[dtype])
        except KeyError:
            raise ValueError(f"Unknown dtype name: {dtype!r}") from None
    return np.dtype(dtype)


def name_of(dtype) -> str:
    """DL4J-style canonical name for a dtype (used in config JSON round-trips)."""
    d = np.dtype(dtype)
    for name, cand in _NAME_TO_DTYPE.items():
        if name.isupper() and np.dtype(cand) == d:
            return name
    raise ValueError(f"No DL4J name for dtype {d}")


def is_floating(dtype) -> bool:
    return np.dtype(dtype) in {np.dtype(d) for d in FLOATING}


# ---- mixed precision (SURVEY.md §7.3 item 8) --------------------------------
# A 16-bit network dtype selects the COMPUTE dtype only: the engines keep
# fp32 master params + fp32 updater state and cast params/activations to the
# compute dtype inside the jitted step, so matmuls/convs hit the MXU in
# bf16 while weight updates retain full mantissa. (bf16 shares fp32's
# exponent range, so no loss scaling is needed; fp16 nets get the same
# master-weight treatment but remain exotic on TPU.)

_SIXTEEN_BIT = {np.dtype(np.float16), np.dtype(bfloat16)}


def is_mixed(dtype) -> bool:
    """True when `dtype` names a 16-bit compute policy with fp32 masters."""
    return resolve(dtype) in _SIXTEEN_BIT


def param_dtype(dtype) -> np.dtype:
    """Storage dtype for params/updater state under the network dtype."""
    d = resolve(dtype)
    return np.dtype(np.float32) if d in _SIXTEEN_BIT else d


def cast_floating(tree, dtype):
    """Cast every floating-point leaf of a pytree to `dtype` (ints/bools
    untouched). Identity for leaves already in `dtype`.

    Quantized weights (``ops.quantize.QuantizedTensor``, duck-typed via
    the ``__quantized_tensor__`` marker so this module needs no ops
    import) pass through WHOLE: their int8 values are not floating, and
    casting their f32 scales to a 16-bit compute dtype would permanently
    degrade dequantization accuracy — the int8 kernels upcast the scale
    themselves."""
    import jax

    d = np.dtype(dtype)

    def _is_quantized(n):
        return getattr(n, "__quantized_tensor__", False)

    def _cast(a):
        if _is_quantized(a):
            return a
        if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) \
                and a.dtype != d:
            return a.astype(d)
        return a

    return jax.tree.map(_cast, tree, is_leaf=_is_quantized)


def upcast_16(a):
    """Promote a 16-bit floating array to fp32 (loss/eval heads compute in
    fp32 under the mixed-precision policy); other dtypes pass through."""
    if hasattr(a, "dtype") and np.dtype(a.dtype) in _SIXTEEN_BIT:
        return a.astype(np.float32)
    return a
