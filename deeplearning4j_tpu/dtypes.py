"""Data type system.

TPU-native equivalent of the nd4j/libnd4j dtype system
(reference: ``libnd4j/include/array/ArrayOptions.h``†,
``nd4j-api .../linalg/api/buffer/DataType.java``† — paths per SURVEY.md §2.1/2.2;
reference mount was empty, citations are upstream-relative, unverified).

Divergences (deliberate, TPU-first):
- ``bfloat16`` is a first-class citizen (native on the MXU); DL4J treats it as
  exotic.
- ``float64`` is supported but discouraged on TPU (software emulation); it is
  kept for grad-check oracles on CPU.
- UTF8/compressed buffer types are out of scope (no string tensors in XLA).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# DL4J-style names -> jnp dtypes. Keys mirror org.nd4j.linalg.api.buffer.DataType.
_NAME_TO_DTYPE = {
    "BOOL": jnp.bool_,
    "INT8": jnp.int8,
    "INT16": jnp.int16,
    "INT32": jnp.int32,
    "INT64": jnp.int64,
    "UINT8": jnp.uint8,
    "UINT16": jnp.uint16,
    "UINT32": jnp.uint32,
    "UINT64": jnp.uint64,
    "FLOAT16": jnp.float16,
    "BFLOAT16": jnp.bfloat16,
    "FLOAT": jnp.float32,
    "DOUBLE": jnp.float64,
    # Aliases (numpy-style, accepted everywhere a dtype name is accepted)
    "bool": jnp.bool_,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "uint8": jnp.uint8,
    "uint16": jnp.uint16,
    "uint32": jnp.uint32,
    "uint64": jnp.uint64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
}

bool_ = jnp.bool_
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
uint64 = jnp.uint64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64

FLOATING = (jnp.float16, jnp.bfloat16, jnp.float32, jnp.float64)


def resolve(dtype) -> np.dtype:
    """Resolve a dtype-ish value (DL4J name, numpy name, np/jnp dtype) to numpy dtype."""
    if isinstance(dtype, str):
        try:
            return np.dtype(_NAME_TO_DTYPE[dtype])
        except KeyError:
            raise ValueError(f"Unknown dtype name: {dtype!r}") from None
    return np.dtype(dtype)


def name_of(dtype) -> str:
    """DL4J-style canonical name for a dtype (used in config JSON round-trips)."""
    d = np.dtype(dtype)
    for name, cand in _NAME_TO_DTYPE.items():
        if name.isupper() and np.dtype(cand) == d:
            return name
    raise ValueError(f"No DL4J name for dtype {d}")


def is_floating(dtype) -> bool:
    return np.dtype(dtype) in {np.dtype(d) for d in FLOATING}
