"""Keras HDF5 model import.

TPU-native equivalent of deeplearning4j-modelimport's Keras frontend
(reference: ``deeplearning4j-modelimport .../modelimport/keras/
KerasModelImport.java``, the ~80-mapper ``KerasLayer`` registry under
``.../keras/layers/**``, ``Hdf5Archive.java``† per SURVEY.md §2.5/§3.5;
reference mount was empty, citations upstream-relative, unverified).

Same two-stage contract as the reference: (1) the ``model_config`` JSON
attribute maps through a per-class layer registry into OUR config objects —
Sequential → MultiLayerNetwork, Functional → ComputationGraph; (2) weights
are copied name-by-name from the ``model_weights`` group with layout
transposes. Divergence from the reference, recorded: DL4J imports into NCHW
and inserts NHWC→NCHW preprocessors; we keep the model in Keras's native
NHWC (channels_last) because that is also the TPU-preferred layout — no
transpose at the data boundary at all. Handles the Keras 2 ("keras_version"
2.x h5) and Keras 3 ("legacy h5" writer) flavors of the format.

Formats: legacy .h5 (Keras 2 and Keras 3 legacy writer), the modern
.keras v3 zip archive, and config-only import
(importKerasModelConfiguration parity). ~45 layer classes: the 2D conv
family (Conv2D/Transpose/Separable/Depthwise, poolings, BN,
zero-pad/crop/upsample), Conv1D + 1D poolings, Conv3D, Dense/Embedding/
Flatten/Dropout family/activation layers incl. LayerNormalization and
PReLU/ELU/ReLU variants, LSTM/GRU (both reset_after)/SimpleRNN/
Bidirectional (all merge modes + return_sequences=False semantics),
merge layers (Add/Subtract/Multiply/Maximum/Average/Concatenate), and
Lambda + custom-layer registration hooks. Unsupported classes raise with
the class name so coverage gaps are loud, mirroring the reference's
UnsupportedKerasConfigurationException.
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..nn.config import InputType, NeuralNetConfiguration
from ..nn.layers.conv import (BatchNormalization, ConvolutionLayer,
                              GlobalPoolingLayer, SubsamplingLayer,
                              Upsampling2D, ZeroPadding2D)
from ..nn.layers.core import (ActivationLayer, DenseLayer, DropoutLayer,
                              EmbeddingLayer, FlattenLayer)
from ..nn.layers.recurrent import GRU, LSTM, Bidirectional, SimpleRnn

_ACT = {"linear": "identity", "relu": "relu", "relu6": "relu6",
        "tanh": "tanh", "sigmoid": "sigmoid", "hard_sigmoid": "hardsigmoid",
        "softmax": "softmax", "softplus": "softplus", "softsign": "softsign",
        "elu": "elu", "selu": "selu", "gelu": "gelu", "swish": "swish",
        "silu": "swish", "mish": "mish", "exponential": None, "None": "identity"}


def _act(name: Optional[str]) -> str:
    if name is None:
        return "identity"
    mapped = _ACT.get(str(name))
    if mapped is None:
        raise ValueError(f"unsupported Keras activation {name!r}")
    return mapped


def _pair(v) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def _check_channels_last(cfg: dict, cls: str):
    df = cfg.get("data_format", "channels_last")
    if df not in (None, "channels_last"):
        raise ValueError(
            f"{cls} with data_format={df!r}: only channels_last imports "
            "(TPU-native NHWC); convert the model or permute inputs")


class _Mapped:
    """One imported layer: our layer object (or None for structural nodes),
    plus how to map its Keras weight list into our param dict."""

    def __init__(self, layer=None, weights: Optional[Callable] = None,
                 vertex: Optional[tuple] = None):
        self.layer = layer
        self.weights = weights          # fn(list[np.ndarray]) -> params dict
        self.vertex = vertex            # ("kind", kwargs) for non-layer nodes


def _map_dense(cfg) -> _Mapped:
    lyr = DenseLayer(n_out=int(cfg["units"]),
                     activation=_act(cfg.get("activation")))
    def w(ws):
        if cfg.get("use_bias", True):
            k, b = ws
        else:
            (k,), b = ws, np.zeros(cfg["units"], np.float32)
        return {"W": k, "b": b}  # Keras kernel [in,out] == ours
    return _Mapped(lyr, w)


def _map_conv2d(cfg) -> _Mapped:
    _check_channels_last(cfg, "Conv2D")
    same = cfg.get("padding", "valid") == "same"
    lyr = ConvolutionLayer(
        n_out=int(cfg["filters"]), kernel=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        dilation=_pair(cfg.get("dilation_rate", 1)),
        mode="same" if same else "truncate",
        activation=_act(cfg.get("activation")),
        has_bias=cfg.get("use_bias", True), data_format="NHWC")
    def w(ws):
        k = ws[0].transpose(3, 2, 0, 1)  # HWIO -> OIHW (our storage)
        out = {"W": k}
        if cfg.get("use_bias", True):
            out["b"] = ws[1]
        return out
    return _Mapped(lyr, w)


def _map_pool(cfg, pool_type: str) -> _Mapped:
    _check_channels_last(cfg, "Pooling2D")
    same = cfg.get("padding", "valid") == "same"
    k = _pair(cfg.get("pool_size", 2))
    s = _pair(cfg["strides"]) if cfg.get("strides") else k
    return _Mapped(SubsamplingLayer(kernel=k, stride=s, pool_type=pool_type,
                                    mode="same" if same else "truncate",
                                    data_format="NHWC"))


def _map_bn(cfg) -> _Mapped:
    axis = cfg.get("axis", -1)
    if isinstance(axis, list):
        axis = axis[0]
    lyr = BatchNormalization(eps=float(cfg.get("epsilon", 1e-3)),
                             decay=float(cfg.get("momentum", 0.99)),
                             data_format="NHWC")
    scale = cfg.get("scale", True)
    center = cfg.get("center", True)
    def w(ws):
        ws = list(ws)
        params = {}
        params["gamma"] = ws.pop(0) if scale else np.ones_like(ws[-1])
        params["beta"] = ws.pop(0) if center else np.zeros_like(ws[-1])
        state = {"mean": ws[0], "var": ws[1]}
        return {"__params__": params, "__state__": state}
    return _Mapped(lyr, w)


def _check_go_backwards(cfg, cls):
    # go_backwards reverses the scan direction; importing it as a forward
    # RNN would be silently wrong (a standalone reversed layer has no
    # forward twin to pair with, unlike inside Bidirectional where Keras
    # sets it on the backward copy and the wrapper handles the flip).
    if cfg.get("go_backwards"):
        raise ValueError(
            f"standalone {cls} with go_backwards=True not supported "
            "(wrap in Bidirectional or reverse the time axis upstream)")


def _map_lstm(cfg) -> _Mapped:
    if cfg.get("return_state"):
        raise ValueError("LSTM return_state not supported in import")
    _check_go_backwards(cfg, "LSTM")
    if _act(cfg.get("activation", "tanh")) != "tanh" or \
            cfg.get("recurrent_activation", "sigmoid") != "sigmoid":
        # hard_sigmoid gates would silently change the cell math — our
        # lstm_cell computes exact sigmoid
        raise ValueError("only tanh/sigmoid LSTM variants import")
    if not cfg.get("return_sequences", False):
        # our LSTM layer always returns sequences; the Sequential importer
        # appends a LastTimeStep wrapper for return_sequences=False
        pass
    # Keras bias already encodes unit_forget_bias; our cell's runtime
    # forget-bias addition must be disabled
    lyr = LSTM(n_out=int(cfg["units"]), forget_bias=0.0)
    u = int(cfg["units"])
    def w(ws):
        k, rk = ws[0], ws[1]
        b = ws[2] if len(ws) > 2 else np.zeros(4 * u, np.float32)
        # Keras gate order [i, f, c(g), o] -> ours [i, f, o, g]
        def reorder(m):
            blocks = np.split(m, 4, axis=-1)
            return np.concatenate([blocks[0], blocks[1], blocks[3],
                                   blocks[2]], axis=-1)
        return {"W": reorder(k), "RW": reorder(rk), "b": reorder(b)}
    return _Mapped(lyr, w, vertex=("lstm", {
        "return_sequences": bool(cfg.get("return_sequences", False))}))


def _map_gru(cfg) -> _Mapped:
    if cfg.get("return_state"):
        raise ValueError("GRU return_state not supported in import")
    _check_go_backwards(cfg, "GRU")
    if _act(cfg.get("activation", "tanh")) != "tanh" or \
            cfg.get("recurrent_activation", "sigmoid") != "sigmoid":
        raise ValueError("only tanh/sigmoid GRU variants import")
    reset_after = bool(cfg.get("reset_after", True))
    u = int(cfg["units"])
    lyr = GRU(n_out=u, reset_after=reset_after)

    def w(ws):
        k, rk = ws[0], ws[1]
        # Keras gate order [z, r, h] matches ours — no reorder
        if reset_after:
            b = ws[2] if len(ws) > 2 else np.zeros((2, 3 * u), np.float32)
            b = np.asarray(b).reshape(2, 3 * u)
            return {"W": k, "RW": rk, "b": b[0], "rb": b[1]}
        b = ws[2] if len(ws) > 2 else np.zeros(3 * u, np.float32)
        return {"W": k, "RW": rk, "b": b}

    return _Mapped(lyr, w, vertex=("rnn", {
        "return_sequences": bool(cfg.get("return_sequences", False))}))


def _map_bidirectional(cfg) -> _Mapped:
    inner_cfg = cfg["layer"]
    inner_cls = inner_cfg["class_name"]
    if inner_cls not in ("LSTM", "GRU", "SimpleRNN"):
        raise ValueError(
            f"Bidirectional around {inner_cls!r} not supported")
    fwd_cfg = dict(inner_cfg["config"])
    if fwd_cfg.get("go_backwards"):
        # cfg["layer"] is the FORWARD layer; go_backwards=True here means
        # the user swapped the scan directions — importing as the mirrored
        # default would silently swap the output streams
        raise ValueError(
            "Bidirectional with go_backwards=True on the forward layer "
            "not supported")
    bwd = cfg.get("backward_layer")
    if bwd is not None:
        # Keras 3 always serializes the backward copy; accept only the
        # mirrored default (identical config up to name + flipped
        # go_backwards) and raise loudly on a genuinely custom one
        def norm(c):
            c = dict(c)
            c.pop("name", None)
            c.pop("go_backwards", None)
            return c
        bwd_cfg = dict(bwd.get("config", {}))
        if (bwd.get("class_name") != inner_cls
                or not bwd_cfg.get("go_backwards", False)
                or norm(bwd_cfg) != norm(fwd_cfg)):
            raise ValueError(
                "Bidirectional with a non-mirrored backward_layer config "
                "is not supported (only the default mirrored form)")
    inner_imp = dict(fwd_cfg)
    inner_imp.pop("go_backwards", None)  # mirrored default: wrapper owns it
    inner = _MAPPERS[inner_cls](inner_imp)
    merge = {"concat": "concat", "sum": "add", "mul": "mul",
             "ave": "average"}.get(cfg.get("merge_mode", "concat"))
    if merge is None:
        raise ValueError(
            f"Bidirectional merge_mode={cfg.get('merge_mode')!r} "
            "not supported (concat/sum/mul/ave)")
    rs = bool(inner_cfg["config"].get("return_sequences", False))
    # return_sequences=False lives on the Bidirectional layer itself (the
    # keras semantics merge each direction's OWN last step — a LastTimeStep
    # over the merged sequence would take the backward stream's first step)
    lyr = Bidirectional(layer=inner.layer, mode=merge, return_sequences=rs)

    def w(ws):
        ws = list(ws)
        if len(ws) % 2:
            raise ValueError(
                f"Bidirectional expects paired fw/bw weights, got {len(ws)}")
        half = len(ws) // 2
        return {"fw": inner.weights(ws[:half]),
                "bw": inner.weights(ws[half:])}

    return _Mapped(lyr, w, vertex=("rnn", {"return_sequences": True}))


def _map_conv1d(cfg) -> _Mapped:
    from ..nn.layers.conv_extra import Convolution1D
    if cfg.get("data_format", "channels_last") != "channels_last":
        raise ValueError("Conv1D channels_first not supported")
    pad = cfg.get("padding", "valid")
    if pad not in ("valid", "same"):
        raise ValueError(f"Conv1D padding={pad!r} not supported")
    lyr = Convolution1D(
        n_out=int(cfg["filters"]), kernel=int(_one(cfg["kernel_size"])),
        stride=int(_one(cfg.get("strides", 1))),
        dilation=int(_one(cfg.get("dilation_rate", 1))),
        mode="same" if pad == "same" else "truncate",
        activation=_act(cfg.get("activation")),
        has_bias=bool(cfg.get("use_bias", True)))

    def w(ws):
        # Keras kernel [k, in, out] -> ours [out, in, 1, k]
        kern = np.transpose(np.asarray(ws[0]), (2, 1, 0))[:, :, None, :]
        out = {"W": kern}
        if len(ws) > 1:
            out["b"] = ws[1]
        return out

    return _Mapped(lyr, w)


def _map_conv2d_transpose(cfg) -> _Mapped:
    from ..nn.layers.conv_extra import Deconvolution2D
    _check_channels_last(cfg, "Conv2DTranspose")
    pad = cfg.get("padding", "valid")
    if pad not in ("valid", "same"):
        raise ValueError(f"Conv2DTranspose padding={pad!r} not supported")
    if tuple(_pair(cfg.get("dilation_rate", 1))) != (1, 1):
        raise ValueError("Conv2DTranspose dilation != 1 not supported")
    if cfg.get("output_padding") not in (None,):
        raise ValueError("Conv2DTranspose explicit output_padding "
                         "not supported")
    lyr = Deconvolution2D(
        n_out=int(cfg["filters"]), kernel=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        mode="same" if pad == "same" else "truncate",
        activation=_act(cfg.get("activation")),
        has_bias=bool(cfg.get("use_bias", True)), data_format="NHWC")

    def w(ws):
        # Keras kernel [kH, kW, out, in] -> ours [out, in, kH, kW]
        kern = np.transpose(np.asarray(ws[0]), (2, 3, 0, 1))
        out = {"W": kern}
        if len(ws) > 1:
            out["b"] = ws[1]
        return out

    return _Mapped(lyr, w)


def _map_conv3d_transpose(cfg) -> _Mapped:
    from ..nn.layers.conv3d import Deconvolution3D
    if cfg.get("data_format", "channels_last") != "channels_last":
        raise ValueError("Conv3DTranspose channels_first not supported")
    pad = cfg.get("padding", "valid")
    if pad not in ("valid", "same"):
        raise ValueError(f"Conv3DTranspose padding={pad!r} not supported")
    if tuple(_triple3(cfg.get("dilation_rate", 1))) != (1, 1, 1):
        raise ValueError("Conv3DTranspose dilation != 1 not supported")
    if cfg.get("output_padding") not in (None,):
        raise ValueError("Conv3DTranspose explicit output_padding "
                         "not supported")
    lyr = Deconvolution3D(
        n_out=int(cfg["filters"]), kernel=_triple3(cfg["kernel_size"]),
        stride=_triple3(cfg.get("strides", 1)),
        mode="same" if pad == "same" else "truncate",
        activation=_act(cfg.get("activation")),
        has_bias=bool(cfg.get("use_bias", True)), data_format="NDHWC")

    def w(ws):
        # Keras kernel [kD, kH, kW, out, in] -> ours [out, in, kD, kH, kW]
        kern = np.transpose(np.asarray(ws[0]), (3, 4, 0, 1, 2))
        out = {"W": kern}
        if len(ws) > 1:
            out["b"] = ws[1]
        return out

    return _Mapped(lyr, w)


def _map_cudnn_lstm(cfg) -> _Mapped:
    """tf.compat.v1 CuDNNLSTM: fixed tanh/sigmoid math (== our cell); the
    only difference from LSTM is the DOUBLE bias (input + recurrent halves,
    [2, 4u] or flat [8u]) which sums into one effective bias."""
    cfg = dict(cfg)
    cfg.setdefault("activation", "tanh")
    cfg.setdefault("recurrent_activation", "sigmoid")
    base = _map_lstm(cfg)
    u = int(cfg["units"])

    def w(ws):
        ws = list(ws)
        if len(ws) > 2 and np.asarray(ws[2]).size == 8 * u:
            b2 = np.asarray(ws[2]).reshape(2, 4 * u)
            ws[2] = b2[0] + b2[1]
        return base.weights(ws)

    return _Mapped(base.layer, w, vertex=base.vertex)


def _map_cudnn_gru(cfg) -> _Mapped:
    """tf.compat.v1 CuDNNGRU == GRU(reset_after=True) with the double
    bias already in the [2, 3u] layout our reset_after mapper consumes."""
    cfg = dict(cfg)
    cfg.setdefault("activation", "tanh")
    cfg.setdefault("recurrent_activation", "sigmoid")
    cfg["reset_after"] = True
    return _map_gru(cfg)


def _map_multi_head_attention(cfg) -> _Mapped:
    """Keras MultiHeadAttention in the self-attention arrangement
    (query == value == key — the only form expressible in a single-input
    layer stack; cross-attention needs graph-level wiring). Maps onto
    SelfAttentionLayer with per-projection biases."""
    from ..nn.layers.attention import SelfAttentionLayer
    heads = int(cfg["num_heads"])
    key_dim = int(cfg["key_dim"])
    if cfg.get("value_dim") not in (None, key_dim):
        raise ValueError("MultiHeadAttention value_dim != key_dim "
                         "not supported")
    if cfg.get("attention_axes") not in (None, [1], (1,)):
        raise ValueError("MultiHeadAttention attention_axes beyond the "
                         "time axis not supported")
    use_bias = bool(cfg.get("use_bias", True))
    oshape = cfg.get("output_shape")
    if isinstance(oshape, (list, tuple)):
        oshape = oshape[-1] if oshape else None
    # n_out=0 resolves to the input feature dim at init (the keras default
    # when output_shape is unset)
    lyr = SelfAttentionLayer(n_out=int(oshape) if oshape else 0,
                             n_heads=heads, head_size=key_dim,
                             has_bias=use_bias)

    def w(ws):
        ws = [np.asarray(a) for a in ws]
        if use_bias:
            kq, bq, kk, bk, kv, bv, ko, bo = ws
        else:
            kq, kk, kv, ko = ws
        f = kq.shape[0]
        proj = heads * key_dim
        out = {"Wq": kq.reshape(f, proj), "Wk": kk.reshape(f, proj),
               "Wv": kv.reshape(f, proj),
               "Wo": ko.reshape(proj, ko.shape[-1])}
        if use_bias:
            out.update({"bq": bq.reshape(proj), "bk": bk.reshape(proj),
                        "bv": bv.reshape(proj), "bo": bo.reshape(-1)})
        return out

    return _Mapped(lyr, w)


def _map_conv3d(cfg) -> _Mapped:
    from ..nn.layers.conv3d import Convolution3D
    if cfg.get("data_format", "channels_last") != "channels_last":
        raise ValueError("Conv3D channels_first not supported")
    pad = cfg.get("padding", "valid")
    if pad not in ("valid", "same"):
        raise ValueError(f"Conv3D padding={pad!r} not supported")
    lyr = Convolution3D(
        n_out=int(cfg["filters"]), kernel=_triple3(cfg["kernel_size"]),
        stride=_triple3(cfg.get("strides", 1)),
        dilation=_triple3(cfg.get("dilation_rate", 1)),
        mode="same" if pad == "same" else "truncate",
        activation=_act(cfg.get("activation")),
        has_bias=bool(cfg.get("use_bias", True)), data_format="NDHWC")

    def w(ws):
        # Keras kernel [kd, kh, kw, in, out] -> ours [out, in, kd, kh, kw]
        kern = np.transpose(np.asarray(ws[0]), (4, 3, 0, 1, 2))
        out = {"W": kern}
        if len(ws) > 1:
            out["b"] = ws[1]
        return out

    return _Mapped(lyr, w)


def _map_pool1d(cfg, pool_type: str) -> _Mapped:
    from ..nn.layers.conv_extra import Subsampling1DLayer
    pad = cfg.get("padding", "valid")
    if pad not in ("valid", "same"):
        raise ValueError(f"Pooling1D padding={pad!r} not supported")
    return _Mapped(Subsampling1DLayer(
        kernel=int(_one(cfg.get("pool_size", 2))),
        stride=int(_one(cfg.get("strides") or cfg.get("pool_size", 2))),
        pool_type=pool_type, mode="same" if pad == "same" else "truncate"))


def _one(v):
    return v[0] if isinstance(v, (list, tuple)) else v


def _triple3(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (int(v),) * 3


#: name -> Layer instance; Keras Lambda layers serialize arbitrary Python,
#: so the import cannot reconstruct them — users register the equivalent
#: layer under the LAMBDA LAYER'S NAME (the reference's
#: KerasLayer.registerLambdaLayer contract).
_LAMBDA_LAYERS: Dict[str, Any] = {}


def register_lambda_layer(name: str, layer) -> None:
    _LAMBDA_LAYERS[name] = layer


def register_custom_layer(class_name: str, mapper: Callable) -> None:
    """Register an import mapper for a custom Keras layer class
    (``KerasLayer.registerCustomLayer``†): ``mapper(config_dict) -> _Mapped``
    (or anything exposing .layer/.weights/.vertex)."""
    _MAPPERS[class_name] = mapper


def _map_layer_norm(cfg) -> _Mapped:
    from ..nn.layers.special import LayerNormalization
    axis = cfg.get("axis", -1)
    if isinstance(axis, (list, tuple)):
        axis = axis[0] if len(axis) == 1 else axis
    if axis not in (-1,):
        raise ValueError(f"LayerNormalization axis={axis} not supported "
                         "(last-axis only)")
    lyr = LayerNormalization(eps=float(cfg.get("epsilon", 1e-3)),
                             scale=bool(cfg.get("scale", True)),
                             center=bool(cfg.get("center", True)))

    def w(ws):
        ws = list(ws)
        out = {}
        if lyr.scale:
            out["gamma"] = ws.pop(0)
        if lyr.center:
            out["beta"] = ws.pop(0)
        return out
    return _Mapped(lyr, w)


def _map_lambda(cfg) -> _Mapped:
    name = cfg.get("name")
    if name in _LAMBDA_LAYERS:
        return _Mapped(_LAMBDA_LAYERS[name])
    raise ValueError(
        f"Lambda layer {name!r}: Keras Lambdas serialize arbitrary Python "
        "and cannot be imported mechanically — call "
        "modelimport.keras.register_lambda_layer({name!r}, <equivalent "
        "Layer>) before importing (reference: KerasLayer."
        "registerLambdaLayer)")


def _map_simple_rnn(cfg) -> _Mapped:
    _check_go_backwards(cfg, "SimpleRNN")
    lyr = SimpleRnn(n_out=int(cfg["units"]),
                    activation=_act(cfg.get("activation", "tanh")))
    u = int(cfg["units"])
    def w(ws):
        b = ws[2] if len(ws) > 2 else np.zeros(u, np.float32)
        return {"W": ws[0], "RW": ws[1], "b": b}
    return _Mapped(lyr, w, vertex=("rnn", {
        "return_sequences": bool(cfg.get("return_sequences", False))}))


def _map_relu(cfg) -> _Mapped:
    mv = cfg.get("max_value")
    if cfg.get("negative_slope") or cfg.get("threshold"):
        raise ValueError("ReLU with negative_slope/threshold not supported — "
                         "import as LeakyReLU/ThresholdedReLU instead")
    if mv in (None, 0):
        return _Mapped(ActivationLayer(activation="relu"))
    if float(mv) == 6.0:
        return _Mapped(ActivationLayer(activation="relu6"))
    raise ValueError(f"ReLU max_value={mv} not supported (only None/6.0)")


def _upsample_interp(cfg) -> str:
    interp = cfg.get("interpolation", "nearest")
    if interp not in ("nearest", "bilinear"):
        raise ValueError(
            f"UpSampling2D interpolation={interp!r} not supported")
    return interp


def _map_zeropad(cfg) -> _Mapped:
    p = cfg["padding"]
    if isinstance(p, int):
        pad = (p, p)
    else:
        ph, pw = p
        if isinstance(ph, (list, tuple)):
            # ((top,bottom),(left,right)) — legacy ResNet/Inception exports
            # routinely pad (0,1); the layer takes the nested form verbatim
            pad = ((int(ph[0]), int(ph[1])), (int(pw[0]), int(pw[1])))
        else:
            pad = (int(ph), int(pw))
    return _Mapped(ZeroPadding2D(padding=pad, data_format="NHWC"))


def _map_embedding(cfg) -> _Mapped:
    lyr = EmbeddingLayer(n_in=int(cfg["input_dim"]),
                         n_out=int(cfg["output_dim"]))
    return _Mapped(lyr, lambda ws: {"W": ws[0]})


_MAPPERS: Dict[str, Callable[[dict], _Mapped]] = {
    "Dense": _map_dense,
    "Conv2D": _map_conv2d,
    "MaxPooling2D": lambda c: _map_pool(c, "max"),
    "AveragePooling2D": lambda c: _map_pool(c, "avg"),
    "GlobalAveragePooling2D": lambda c: _Mapped(
        GlobalPoolingLayer(pool_type="avg", data_format="NHWC")),
    "GlobalMaxPooling2D": lambda c: _Mapped(
        GlobalPoolingLayer(pool_type="max", data_format="NHWC")),
    "BatchNormalization": _map_bn,
    "Dropout": lambda c: _Mapped(DropoutLayer(rate=float(c["rate"]))),
    "Flatten": lambda c: _Mapped(FlattenLayer()),
    "Activation": lambda c: _Mapped(
        ActivationLayer(activation=_act(c["activation"]))),
    "ReLU": lambda c: _map_relu(c),
    "LeakyReLU": lambda c: _Mapped(ActivationLayer(
        activation="leakyrelu",
        alpha=float(c.get("negative_slope", c.get("alpha", 0.3))))),
    "Softmax": lambda c: _Mapped(ActivationLayer(activation="softmax")),
    "ZeroPadding2D": lambda c: _map_zeropad(c),
    "UpSampling2D": lambda c: _Mapped(Upsampling2D(
        size=_pair(c.get("size", 2)), data_format="NHWC",
        interpolation=_upsample_interp(c))),
    "Embedding": _map_embedding,
    "LSTM": _map_lstm,
    "GRU": _map_gru,
    "SimpleRNN": _map_simple_rnn,
    "Bidirectional": _map_bidirectional,
    "Conv1D": _map_conv1d,
    "Conv2DTranspose": lambda c: _map_conv2d_transpose(c),
    "Conv3DTranspose": lambda c: _map_conv3d_transpose(c),
    "Conv3D": _map_conv3d,
    # legacy tf.compat.v1 cuDNN-pinned RNNs: same math as our cells with
    # double (input+recurrent) biases
    "CuDNNLSTM": _map_cudnn_lstm,
    "CuDNNGRU": _map_cudnn_gru,
    "MultiHeadAttention": _map_multi_head_attention,
    "MaxPooling1D": lambda c: _map_pool1d(c, "max"),
    "AveragePooling1D": lambda c: _map_pool1d(c, "avg"),
    "GlobalAveragePooling1D": lambda c: _Mapped(
        GlobalPoolingLayer(pool_type="avg")),
    "GlobalMaxPooling1D": lambda c: _Mapped(
        GlobalPoolingLayer(pool_type="max")),
    "Lambda": _map_lambda,
    "LayerNormalization": lambda c: _map_layer_norm(c),
    "ELU": lambda c: _Mapped(ActivationLayer(
        activation="elu", alpha=float(c.get("alpha", 1.0)))),
    "SeparableConv2D": lambda c: _map_separable(c),
    "DepthwiseConv2D": lambda c: _map_depthwise(c),
    "PReLU": lambda c: _map_prelu(c),
    "SpatialDropout2D": lambda c: _map_special(
        "SpatialDropout", rate=float(c["rate"]), data_format="NHWC"),
    "GaussianNoise": lambda c: _map_special(
        "GaussianNoise", stddev=float(c["stddev"])),
    "GaussianDropout": lambda c: _map_special(
        "GaussianDropout", rate=float(c["rate"])),
    "Cropping2D": lambda c: _map_cropping(c),
    # ---- round-4 tail: seq2seq staples, 1D/3D variants, wrappers --------
    "Permute": lambda c: _map_structural("PermuteLayer",
                                         dims=tuple(int(d) for d in c["dims"])),
    "Reshape": lambda c: _map_structural(
        "ReshapeLayer", target_shape=tuple(int(t) for t in c["target_shape"])),
    "Masking": lambda c: _map_structural(
        "MaskingLayer", mask_value=float(c.get("mask_value", 0.0))),
    "RepeatVector": lambda c: _map_wrapper("RepeatVector", n=int(c["n"])),
    "TimeDistributed": lambda c: _map_time_distributed(c),
    "ConvLSTM2D": lambda c: _map_convlstm2d(c),
    "SeparableConv1D": lambda c: _map_separable1d(c),
    "AlphaDropout": lambda c: _map_special(
        "AlphaDropout", rate=float(c["rate"])),
    "ThresholdedReLU": lambda c: _Mapped(ActivationLayer(
        activation="thresholdedrelu", alpha=float(c.get("theta", 1.0)))),
    "SpatialDropout1D": lambda c: _map_special(
        "SpatialDropout", rate=float(c["rate"]), data_format="NWC"),
    "SpatialDropout3D": lambda c: _map_special(
        "SpatialDropout", rate=float(c["rate"]), data_format="NDHWC"),
    "Cropping1D": lambda c: _map_crop1d(c),
    "ZeroPadding1D": lambda c: _map_pad1d(c),
    "UpSampling1D": lambda c: _map_upsampling1d(c),
    "Cropping3D": lambda c: _map_3d_symmetric("Cropping3D", "cropping", c),
    "ZeroPadding3D": lambda c: _map_3d_symmetric(
        "ZeroPadding3DLayer", "padding", c),
    "UpSampling3D": lambda c: _map_upsampling3d(c),
    "MaxPooling3D": lambda c: _map_pool3d(c, "max"),
    "AveragePooling3D": lambda c: _map_pool3d(c, "avg"),
    "GlobalAveragePooling3D": lambda c: _Mapped(
        GlobalPoolingLayer(pool_type="avg", data_format="NDHWC")),
    "GlobalMaxPooling3D": lambda c: _Mapped(
        GlobalPoolingLayer(pool_type="max", data_format="NDHWC")),
    "LocallyConnected1D": lambda c: _map_locally_connected1d(c),
    "LocallyConnected2D": lambda c: _map_locally_connected2d(c),
}


def _map_structural(cls_name: str, **kw) -> _Mapped:
    from ..nn.layers import core as _core_layers
    return _Mapped(getattr(_core_layers, cls_name)(**kw))


def _map_wrapper(cls_name: str, **kw) -> _Mapped:
    from ..nn.layers import wrappers as _wrap
    return _Mapped(getattr(_wrap, cls_name)(**kw))


def _map_time_distributed(cfg) -> _Mapped:
    from ..nn.layers.wrappers import TimeDistributed
    inner_cfg = cfg["layer"]
    inner_cls = inner_cfg["class_name"]
    if inner_cls not in _MAPPERS:
        raise ValueError(
            f"TimeDistributed around unmapped layer {inner_cls!r}")
    inner = _MAPPERS[inner_cls](inner_cfg["config"])
    if inner.vertex is not None:
        raise ValueError(
            f"TimeDistributed around recurrent layer {inner_cls!r} "
            "not supported")
    return _Mapped(TimeDistributed(layer=inner.layer), inner.weights)


def _map_convlstm2d(cfg) -> _Mapped:
    from ..nn.layers.recurrent import ConvLSTM2D
    _check_go_backwards(cfg, "ConvLSTM2D")
    if cfg.get("data_format", "channels_last") != "channels_last":
        raise ValueError("ConvLSTM2D channels_first not supported")
    if cfg.get("stateful"):
        raise ValueError("stateful ConvLSTM2D not supported in import")
    if tuple(_pair(cfg.get("dilation_rate", 1))) != (1, 1):
        raise ValueError("dilated ConvLSTM2D not supported")
    if cfg.get("return_state"):
        raise ValueError("ConvLSTM2D return_state not supported in import")
    act = _act(cfg.get("activation", "tanh"))
    gate = {"sigmoid": "sigmoid", "hard_sigmoid": "hardsigmoid"}.get(
        cfg.get("recurrent_activation", "hard_sigmoid"))
    if act != "tanh" or gate is None:
        raise ValueError("only tanh/(hard_)sigmoid ConvLSTM2D variants "
                         "import")
    pad = cfg.get("padding", "valid")
    if pad not in ("valid", "same"):
        raise ValueError(f"ConvLSTM2D padding={pad!r} not supported")
    f = int(cfg["filters"])
    lyr = ConvLSTM2D(
        n_out=f, kernel=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        mode="same" if pad == "same" else "truncate",
        return_sequences=bool(cfg.get("return_sequences", False)),
        activation="tanh", gate_activation=gate)

    def w(ws):
        def reorder(m):  # Keras gates [i,f,c,o] -> ours [i,f,o,g]
            blocks = np.split(np.asarray(m), 4, axis=-1)
            return np.concatenate([blocks[0], blocks[1], blocks[3],
                                   blocks[2]], axis=-1)
        # [kh,kw,cin,4f] -> OIHW [4f,cin,kh,kw]
        k = np.transpose(reorder(ws[0]), (3, 2, 0, 1))
        rk = np.transpose(reorder(ws[1]), (3, 2, 0, 1))
        b = reorder(ws[2]) if len(ws) > 2 else np.zeros(4 * f, np.float32)
        return {"W": k, "RW": rk, "b": b}
    return _Mapped(lyr, w)


def _map_separable1d(cfg) -> _Mapped:
    from ..nn.layers.conv_extra import SeparableConvolution1D
    if cfg.get("data_format", "channels_last") != "channels_last":
        raise ValueError("SeparableConv1D channels_first not supported")
    pad = cfg.get("padding", "valid")
    if pad not in ("valid", "same"):
        raise ValueError(f"SeparableConv1D padding={pad!r} not supported")
    lyr = SeparableConvolution1D(
        n_out=int(cfg["filters"]), kernel=int(_one(cfg["kernel_size"])),
        stride=int(_one(cfg.get("strides", 1))),
        dilation=int(_one(cfg.get("dilation_rate", 1))),
        depth_multiplier=int(cfg.get("depth_multiplier", 1)),
        mode="same" if pad == "same" else "truncate",
        activation=_act(cfg.get("activation")),
        has_bias=bool(cfg.get("use_bias", True)))

    def w(ws):
        dk = np.asarray(ws[0])             # [k, cin, mult]
        k, cin, mult = dk.shape
        dw = dk.transpose(1, 2, 0).reshape(cin * mult, 1, 1, k)
        pw = np.asarray(ws[1])             # [1, cin*mult, out]
        pw = pw.transpose(2, 1, 0)[:, :, :, None]  # [out, cin*mult, 1, 1]
        out = {"dW": dw, "pW": pw}
        if cfg.get("use_bias", True):
            out["b"] = ws[2]
        return out
    return _Mapped(lyr, w)


def _map_crop1d(cfg) -> _Mapped:
    from ..nn.layers.conv_extra import Cropping1D
    cr = cfg["cropping"]
    lo, hi = (cr, cr) if isinstance(cr, int) else (int(cr[0]), int(cr[1]))
    return _Mapped(Cropping1D(cropping=(lo, hi)))


def _map_pad1d(cfg) -> _Mapped:
    from ..nn.layers.conv_extra import ZeroPadding1DLayer
    p = cfg["padding"]
    lo, hi = (p, p) if isinstance(p, int) else (int(p[0]), int(p[1]))
    return _Mapped(ZeroPadding1DLayer(padding=(lo, hi)))


def _map_upsampling1d(cfg) -> _Mapped:
    from ..nn.layers.conv_extra import Upsampling1D
    return _Mapped(Upsampling1D(size=int(cfg.get("size", 2))))


def _map_3d_symmetric(cls_name: str, field: str, cfg) -> _Mapped:
    from ..nn.layers import conv3d as _c3d
    v = cfg["cropping" if field == "cropping" else "padding"]
    if isinstance(v, int):
        triple = (v, v, v)
    else:
        triple = []
        for pair in v:
            if isinstance(pair, (list, tuple)):
                if pair[0] != pair[1]:
                    raise ValueError(
                        f"asymmetric {cls_name} {field} {v} not supported")
                triple.append(int(pair[0]))
            else:
                triple.append(int(pair))
        triple = tuple(triple)
    return _Mapped(getattr(_c3d, cls_name)(
        **{field: triple}, data_format="NDHWC"))


def _map_upsampling3d(cfg) -> _Mapped:
    from ..nn.layers.conv3d import Upsampling3D
    s = cfg.get("size", 2)
    size = (s, s, s) if isinstance(s, int) else tuple(int(v) for v in s)
    return _Mapped(Upsampling3D(size=size, data_format="NDHWC"))


def _map_pool3d(cfg, pool_type: str) -> _Mapped:
    from ..nn.layers.conv3d import Subsampling3DLayer
    if cfg.get("data_format", "channels_last") != "channels_last":
        raise ValueError("Pooling3D channels_first not supported")
    pad = cfg.get("padding", "valid")
    if pad not in ("valid", "same"):
        raise ValueError(f"Pooling3D padding={pad!r} not supported")
    k = cfg.get("pool_size", 2)
    kernel = (k, k, k) if isinstance(k, int) else tuple(int(v) for v in k)
    s = cfg.get("strides") or kernel
    stride = (s, s, s) if isinstance(s, int) else tuple(int(v) for v in s)
    return _Mapped(Subsampling3DLayer(
        kernel=kernel, stride=stride, pool_type=pool_type,
        mode="same" if pad == "same" else "truncate", data_format="NDHWC"))


def _map_locally_connected2d(cfg) -> _Mapped:
    from ..nn.layers.conv_extra import LocallyConnected2D
    if cfg.get("data_format", "channels_last") != "channels_last":
        raise ValueError("LocallyConnected2D channels_first not supported")
    if cfg.get("padding", "valid") != "valid":
        raise ValueError("LocallyConnected2D padding='same' not supported "
                         "(Keras only supports 'valid' either)")
    lyr = LocallyConnected2D(
        n_out=int(cfg["filters"]), kernel=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        activation=_act(cfg.get("activation")),
        has_bias=bool(cfg.get("use_bias", True)))

    def w(ws):
        out = {"W": np.asarray(ws[0])}   # [P, khkwC, F] matches ours
        if cfg.get("use_bias", True):
            out["b"] = np.asarray(ws[1]).reshape(-1, out["W"].shape[-1])
        return out
    return _Mapped(lyr, w)


def _map_locally_connected1d(cfg) -> _Mapped:
    from ..nn.layers.conv_extra import LocallyConnected1D
    if cfg.get("padding", "valid") != "valid":
        raise ValueError("LocallyConnected1D padding='same' not supported")
    lyr = LocallyConnected1D(
        n_out=int(cfg["filters"]), kernel=int(_one(cfg["kernel_size"])),
        stride=int(_one(cfg.get("strides", 1))),
        activation=_act(cfg.get("activation")),
        has_bias=bool(cfg.get("use_bias", True)))

    def w(ws):
        out = {"W": np.asarray(ws[0])}   # [To, k*F, F_out] matches ours
        if cfg.get("use_bias", True):
            out["b"] = np.asarray(ws[1])
        return out
    return _Mapped(lyr, w)


def _map_special(cls_name: str, **kw) -> _Mapped:
    from ..nn.layers import special
    return _Mapped(getattr(special, cls_name)(**kw))


def _map_separable(cfg) -> _Mapped:
    from ..nn.layers.conv_extra import SeparableConvolution2D
    _check_channels_last(cfg, "SeparableConv2D")
    same = cfg.get("padding", "valid") == "same"
    lyr = SeparableConvolution2D(
        n_out=int(cfg["filters"]), kernel=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)),
        dilation=_pair(cfg.get("dilation_rate", 1)),
        depth_multiplier=int(cfg.get("depth_multiplier", 1)),
        mode="same" if same else "truncate",
        activation=_act(cfg.get("activation")),
        has_bias=cfg.get("use_bias", True), data_format="NHWC")

    def w(ws):
        # keras depthwise kernel [kh,kw,cin,mult] -> ours [cin*mult,1,kh,kw];
        # pointwise [1,1,cin*mult,out] -> [out,cin*mult,1,1]
        dk = ws[0]
        kh, kw, cin, mult = dk.shape
        dw = dk.transpose(2, 3, 0, 1).reshape(cin * mult, 1, kh, kw)
        pw = ws[1].transpose(3, 2, 0, 1)
        out = {"dW": dw, "pW": pw}
        if cfg.get("use_bias", True):
            out["b"] = ws[2]
        return out
    return _Mapped(lyr, w)


def _map_depthwise(cfg) -> _Mapped:
    from ..nn.layers.conv_extra import DepthwiseConvolution2D
    _check_channels_last(cfg, "DepthwiseConv2D")
    same = cfg.get("padding", "valid") == "same"
    lyr = DepthwiseConvolution2D(
        kernel=_pair(cfg["kernel_size"]), stride=_pair(cfg.get("strides", 1)),
        dilation=_pair(cfg.get("dilation_rate", 1)),
        depth_multiplier=int(cfg.get("depth_multiplier", 1)),
        mode="same" if same else "truncate",
        activation=_act(cfg.get("activation")),
        has_bias=cfg.get("use_bias", True), data_format="NHWC")

    def w(ws):
        dk = ws[0]
        kh, kw, cin, mult = dk.shape
        out = {"W": dk.transpose(2, 3, 0, 1).reshape(cin * mult, 1, kh, kw)}
        if cfg.get("use_bias", True):
            out["b"] = ws[1]
        return out
    return _Mapped(lyr, w)


def _map_prelu(cfg) -> _Mapped:
    from ..nn.layers.special import PReLULayer
    return _Mapped(PReLULayer(), lambda ws: {"alpha": ws[0]})


def _map_cropping(cfg) -> _Mapped:
    from ..nn.layers.conv_extra import Cropping2D
    cr = cfg["cropping"]
    if isinstance(cr, int):
        t = b = l = r = cr
    else:
        (t, b), (l, r) = cr
    return _Mapped(Cropping2D(cropping=(int(t), int(b), int(l), int(r)),
                              data_format="NHWC"))


def _input_type_from_batch_shape(shape) -> tuple:
    dims = [d for d in shape[1:]]
    if len(dims) == 1:
        return InputType.feed_forward(int(dims[0]))
    if len(dims) == 2:
        t, f = dims
        return InputType.recurrent(int(f), None if t is None else int(t))
    if len(dims) == 3:
        h, w, c = dims
        return InputType.convolutional(int(c), int(h), int(w),
                                       data_format="NHWC")
    if len(dims) == 4:
        d, h, w, c = dims
        return InputType.convolutional3d(int(c), int(d), int(h), int(w),
                                         data_format="NDHWC")
    raise ValueError(f"unsupported input shape {shape}")


def _h5_weights(f, layer_name: str) -> List[np.ndarray]:
    if isinstance(f, dict):        # .keras v3 path: weights precomputed
        return f.get(layer_name, [])
    mw = f["model_weights"]
    if layer_name not in mw:
        return []
    g = mw[layer_name]
    names = [n.decode() if isinstance(n, bytes) else n
             for n in g.attrs.get("weight_names", [])]
    if not names:  # Keras 2 nests one more level without weight_names attr
        # visititems yields in HDF5 (alphabetical) order — beta < gamma
        # would silently swap same-shaped BN params; reorder by the
        # canonical per-layer weight rank instead
        rank = {"kernel": 0, "embeddings": 0, "gamma": 0,
                "depthwise_kernel": 0, "recurrent_kernel": 1,
                "pointwise_kernel": 1, "beta": 1,
                "bias": 2, "moving_mean": 2, "moving_variance": 3}

        def key_of(path):
            leaf = path.split("/")[-1].split(":")[0]
            return rank.get(leaf, 99)

        out = []
        def visit(path, obj):
            import h5py
            if isinstance(obj, h5py.Dataset):
                out.append((key_of(path), np.array(obj)))
        g.visititems(visit)
        return [a for _, a in sorted(out, key=lambda kv: kv[0])]
    return [np.array(g[n]) for n in names]


def _snake(name: str) -> str:
    """keras.src.utils.naming.to_snake_case — note the second pattern is
    [a-z] WITHOUT digits (Conv2D -> conv2d, not conv2_d)."""
    import re as _re
    s = _re.sub(r"\W+", "", name)
    s = _re.sub(r"(.)([A-Z][a-z]+)", r"\1_\2", s)
    return _re.sub(r"([a-z])([A-Z])", r"\1_\2", s).lower()


def _import_keras_v3(path: str):
    """Keras 3 ``.keras`` archive: zip{config.json, model.weights.h5}.

    The weight store keys layers by SNAKE-CASED CLASS NAME with an
    occurrence counter ("dense", "dense_1", ...) in declaration order —
    user layer names do not appear — so the mapping walks the config's
    layer list rebuilding those keys. Flat ``vars`` groups only (nested
    wrapper stores raise with the layer key)."""
    import io as _io
    import zipfile as _zip

    import h5py

    with _zip.ZipFile(path) as z:
        cfg = json.loads(z.read("config.json"))
        weights_data = z.read("model.weights.h5")

    # rebuild the store keys from the config layer order
    layers_cfg = cfg["config"]["layers"] if isinstance(cfg["config"], dict) \
        else cfg["config"]
    counters: Dict[str, int] = {}
    by_config_name: Dict[str, str] = {}
    for lcfg in layers_cfg:
        cls = lcfg["class_name"]
        if cls == "InputLayer":
            continue
        # structural layers (Add/Concatenate/...) still occupy store keys
        key = _snake(cls)
        n = counters.get(key, 0)
        counters[key] = n + 1
        by_config_name[lcfg["config"]["name"]] = key if n == 0 \
            else f"{key}_{n}"

    weights: Dict[str, List[np.ndarray]] = {}
    with h5py.File(_io.BytesIO(weights_data), "r") as f:
        store = f["layers"] if "layers" in f else f

        # wrapper stores we know how to flatten, in legacy-h5 weight order;
        # state-only groups carry no trainable weights and must NOT be
        # swept into the weight list (an LSTM(dropout=...) stores RNG state
        # under seed_generator next to cell/vars)
        _WRAPPER_CHILDREN = ("cell", "forward_layer", "backward_layer",
                             "layer")
        _STATE_CHILDREN = ("seed_generator",)

        def _collect_vars(g, key="?") -> List[np.ndarray]:
            """Flatten a layer store depth-first: a layer's own ``vars``
            first, then KNOWN nested wrapper stores (RNN layers keep
            weights under ``cell/vars``; Bidirectional under
            ``forward_layer``/``backward_layer`` — visited in that order to
            match the legacy h5 weight ordering the mappers consume).
            Unknown child groups that contain weights raise loudly rather
            than misassigning them."""
            out: List[np.ndarray] = []
            if "vars" in g and len(g["vars"]) > 0:
                vs = g["vars"]
                out += [np.array(vs[k]) for k in sorted(vs.keys(), key=int)]
            for k in _WRAPPER_CHILDREN:
                if k in g and hasattr(g[k], "keys"):
                    out += _collect_vars(g[k], key=f"{key}/{k}")
            for k in g.keys():
                if k == "vars" or k in _WRAPPER_CHILDREN \
                        or k in _STATE_CHILDREN:
                    continue
                child = g[k]
                if hasattr(child, "keys") and _collect_vars(child,
                                                            key=f"{key}/{k}"):
                    raise ValueError(
                        f".keras layer store {key!r} has weights under an "
                        f"unrecognized child group {k!r} — store layout out "
                        "of sync with this keras version; save as legacy "
                        ".h5 instead")
            return out

        unconsumed = {k for k in store.keys()
                      if k not in set(by_config_name.values())
                      and _collect_vars(store[k])}
        if unconsumed:
            # a key-derivation mismatch would otherwise leave layers on
            # their random init SILENTLY (found the hard way: Conv2D vs a
            # wrong snake-casing); empty groups of structural layers are
            # fine to ignore
            raise ValueError(
                f".keras weight store entries {sorted(unconsumed)} match "
                "no config layer — store-key derivation out of sync with "
                "this keras version")
        for cfg_name, store_key in by_config_name.items():
            if store_key not in store:
                continue
            ws = _collect_vars(store[store_key])
            if ws:
                weights[cfg_name] = ws

    cls = cfg["class_name"]
    if cls == "Sequential":
        return _import_sequential(cfg, weights)
    if cls in ("Functional", "Model"):
        return _import_functional(cfg, weights)
    raise ValueError(f"unsupported Keras model class {cls!r}")


def _inbound_parents(node_spec) -> List[str]:
    """Parent layer names from inbound_nodes, Keras 2 and 3 formats."""
    out: List[str] = []

    def walk(o):
        if isinstance(o, dict):
            if o.get("class_name") == "__keras_tensor__":
                out.append(o["config"]["keras_history"][0])
            else:
                for v in o.values():
                    walk(v)
        elif isinstance(o, (list, tuple)):
            if (len(o) >= 3 and isinstance(o[0], str)
                    and isinstance(o[1], int) and isinstance(o[2], int)):
                out.append(o[0])  # Keras 2 ["name", node_idx, tensor_idx, {}]
            else:
                for v in o:
                    walk(v)

    walk(node_spec)
    return out


class KerasModelImport:
    """Entry points mirroring the reference's static methods."""

    @staticmethod
    def import_keras_sequential_model_and_weights(path: str,
                                                  enforce_training_config: bool = False):
        model = KerasModelImport.import_keras_model_and_weights(path)
        from ..nn.model import MultiLayerNetwork
        if not isinstance(model, MultiLayerNetwork):
            raise TypeError("model in file is not Sequential")
        return model

    @staticmethod
    def import_keras_model_and_weights(path: str):
        """.h5 → MultiLayerNetwork (Sequential) or ComputationGraph
        (Functional), weights copied and ready for inference/fine-tuning."""
        import h5py

        if path.lower().endswith(".keras"):
            return _import_keras_v3(path)
        with h5py.File(path, "r") as f:
            cfg = json.loads(f.attrs["model_config"])
            cls = cfg["class_name"]
            if cls == "Sequential":
                return _import_sequential(cfg, f)
            if cls in ("Functional", "Model"):
                return _import_functional(cfg, f)
            raise ValueError(f"unsupported Keras model class {cls!r}")

    @staticmethod
    def import_keras_model_configuration(source: str):
        """Config-ONLY import (DL4J ``importKerasModelConfiguration``):
        ``source`` is a model-config JSON string, a path to a .json file,
        or a .h5 whose config attribute is read without touching weights.
        Returns an initialized network with fresh (random) parameters."""
        import os
        if source.lstrip().startswith("{"):
            cfg = json.loads(source)
        elif os.path.splitext(source)[1].lower() in (".h5", ".hdf5"):
            import h5py
            with h5py.File(source, "r") as f:
                cfg = json.loads(f.attrs["model_config"])
        else:
            with open(source) as f:
                cfg = json.load(f)
        cls = cfg["class_name"]
        if cls == "Sequential":
            return _import_sequential(cfg, None)
        if cls in ("Functional", "Model"):
            return _import_functional(cfg, None)
        raise ValueError(f"unsupported Keras model class {cls!r}")

    @staticmethod
    def import_keras_sequential_configuration(source: str):
        model = KerasModelImport.import_keras_model_configuration(source)
        from ..nn.model import MultiLayerNetwork
        if not isinstance(model, MultiLayerNetwork):
            raise TypeError("configuration is not Sequential")
        return model


# Keras-1 legacy spellings (DL4J's KerasLayerConfiguration carries both
# generations of field names; same contract here). Class renames plus
# per-config key translations — applied before mapper dispatch.
_KERAS1_CLASS = {"Convolution2D": "Conv2D", "Convolution1D": "Conv1D",
                 "Convolution3D": "Conv3D", "Deconvolution2D":
                 "Conv2DTranspose", "Highway": None, "MaxoutDense": None}
_KERAS1_KEYS = {"output_dim": "units", "nb_filter": "filters",
                "subsample": "strides", "subsample_length": "strides",
                "border_mode": "padding", "inner_activation":
                "recurrent_activation", "p": "rate", "bias": "use_bias",
                "nb_units": "units"}
_KERAS1_DROPOUTS = ("Dropout", "SpatialDropout1D", "SpatialDropout2D",
                    "SpatialDropout3D", "AlphaDropout", "GaussianDropout")


def _normalize_keras1(lcfg: dict) -> dict:
    """Translate a Keras-1 layer config to the Keras-2 spellings the
    mappers consume. No-op for modern configs (key sets are disjoint —
    EXCEPT Embedding, whose modern spelling is input_dim/output_dim in
    every keras generation and must not be rewritten)."""
    cls = lcfg["class_name"]
    if cls == "Embedding":
        return lcfg
    c = lcfg.get("config", {})
    legacy = (cls in _KERAS1_CLASS
              or any(k in c for k in ("nb_filter", "output_dim",
                                      "border_mode", "nb_row"))
              # 'bias' alone is ambiguous: gate on the absence of the
              # modern 'use_bias' marker (mirroring the dropout 'p' check)
              # so a modern layer legitimately carrying a 'bias' config key
              # is not rewritten (ADVICE r5)
              or ("bias" in c and "use_bias" not in c)
              # Keras-1 dropouts spell rate as "p" with no other marker
              or (cls in _KERAS1_DROPOUTS and "p" in c
                  and "rate" not in c))
    if not legacy:
        return lcfg
    if cls in _KERAS1_CLASS and _KERAS1_CLASS[cls] is None:
        raise ValueError(f"Keras-1 layer {cls!r} has no modern equivalent "
                         "to map onto")
    c = dict(c)
    for old, new in _KERAS1_KEYS.items():
        if old in c and new not in c:
            c[new] = c.pop(old)
    if "nb_row" in c:  # Convolution2D kernel spelling
        c.setdefault("kernel_size", (int(c.pop("nb_row")),
                                     int(c.pop("nb_col"))))
    if "filter_length" in c:  # Convolution1D
        c.setdefault("kernel_size", int(c.pop("filter_length")))
    if c.get("padding") == "full":
        raise ValueError("Keras-1 border_mode='full' is not supported")
    if c.get("dim_ordering") == "th":
        raise ValueError("Keras-1 dim_ordering='th' (channels_first) is "
                         "not supported — NHWC imports only")
    c.pop("dim_ordering", None)
    c.pop("init", None)  # weights come from the h5, init is irrelevant
    return {**lcfg, "class_name": _KERAS1_CLASS.get(cls, cls), "config": c}


def _map_layer(lcfg: dict) -> _Mapped:
    lcfg = _normalize_keras1(lcfg)
    cls = lcfg["class_name"]
    if cls not in _MAPPERS:
        raise ValueError(
            f"unsupported Keras layer class {cls!r} (layer "
            f"{lcfg.get('config', {}).get('name')!r}) — extend "
            "modelimport/keras.py:_MAPPERS")
    return _MAPPERS[cls](lcfg["config"])


def _set_params(model_params, model_state, key: str, mapped: _Mapped,
                kws: List[np.ndarray]):
    if mapped.weights is None or not kws:
        return
    import jax.numpy as jnp
    out = mapped.weights(kws)
    params = out.get("__params__", out if "__state__" not in out else {})
    state = out.get("__state__")
    def merge(tgt, src, path):
        for name, val in src.items():
            if isinstance(val, dict):  # nested (Bidirectional fw/bw)
                tgt[name] = merge(dict(tgt.get(name, {})), val,
                                  f"{path}/{name}")
                continue
            if name in tgt and tuple(tgt[name].shape) != tuple(
                    np.asarray(val).shape):
                raise ValueError(
                    f"shape mismatch importing {path}/{name}: "
                    f"ours {tuple(tgt[name].shape)} vs h5 "
                    f"{tuple(np.asarray(val).shape)}")
            tgt[name] = jnp.asarray(val)
        return tgt

    model_params[key] = merge(dict(model_params.get(key, {})), params, key)
    if state:
        st = model_state.get(key, {})
        for name, val in state.items():
            st[name] = jnp.asarray(val)
        model_state[key] = st


def _import_sequential(cfg: dict, f):
    from ..nn.layers.recurrent import LastTimeStep
    from ..nn.model import MultiLayerNetwork

    lcfgs = cfg["config"]["layers"] if isinstance(cfg["config"], dict) \
        else cfg["config"]  # Keras 1 stored a bare list
    input_type = None
    ours: List[Tuple[str, _Mapped]] = []
    for lcfg in lcfgs:
        cls = lcfg["class_name"]
        c = lcfg["config"]
        if cls == "InputLayer":
            shape = c.get("batch_shape") or c.get("batch_input_shape")
            input_type = _input_type_from_batch_shape(shape)
            continue
        if input_type is None and ("batch_input_shape" in c or
                                   "batch_shape" in c):
            shape = c.get("batch_shape") or c.get("batch_input_shape")
            input_type = _input_type_from_batch_shape(shape)
        mapped = _map_layer(lcfg)
        ours.append((c["name"], mapped))
        if mapped.vertex and mapped.vertex[0] in ("lstm", "rnn") and \
                not mapped.vertex[1]["return_sequences"]:
            ours.append((c["name"] + "_last", _Mapped(LastTimeStep())))
    if input_type is None:
        raise ValueError("model has no input shape; cannot import")

    # final Dense -> OutputLayer so the imported net is trainable (the
    # reference maps the Keras compile loss; absent that, infer the
    # canonical loss from the head activation — same forward math)
    if ours and isinstance(ours[-1][1].layer, DenseLayer):
        from ..nn.layers.core import OutputLayer
        d = ours[-1][1].layer
        loss = {"softmax": "mcxent", "sigmoid": "xent"}.get(
            d.activation, "mse")
        ours[-1][1].layer = OutputLayer(n_out=d.n_out,
                                        activation=d.activation, loss=loss)

    b = (NeuralNetConfiguration.builder().input_type(input_type)
         .list(*[m.layer for _, m in ours]))
    net = MultiLayerNetwork(b.build()).init()
    if f is not None:  # config-only import keeps the random init
        for i, (kname, mapped) in enumerate(ours):
            _set_params(net.params, net.state, str(i), mapped,
                        _h5_weights(f, kname))
    return net


def _io_names(spec) -> List[str]:
    """input_layers/output_layers spellings across Keras versions: a flat
    ["name", 0, 0] triple (single io), a list of such triples, a list of
    names, or keras-tensor dicts."""
    if (isinstance(spec, list) and len(spec) == 3 and isinstance(spec[0], str)
            and isinstance(spec[1], int)):
        return [spec[0]]
    out: List[str] = []
    for x in spec:
        if isinstance(x, str):
            out.append(x)
        elif isinstance(x, list) and x and isinstance(x[0], str):
            out.append(x[0])
        elif isinstance(x, dict) and x.get("class_name") == "__keras_tensor__":
            out.append(x["config"]["keras_history"][0])
        else:
            raise ValueError(f"unrecognized io spec entry {x!r}")
    return out


def _import_functional(cfg: dict, f):
    from ..nn.graph import ComputationGraph
    from ..nn.layers.recurrent import LastTimeStep
    from ..nn.vertices import ElementWiseVertex, LayerVertex, MergeVertex

    c = cfg["config"]
    lcfgs = c["layers"]
    input_names = _io_names(c["input_layers"])
    output_names = _io_names(c["output_layers"])

    gb = NeuralNetConfiguration.builder().graph_builder()
    input_types = []
    mapped_by_name: Dict[str, _Mapped] = {}
    for lcfg in lcfgs:
        cls = lcfg["class_name"]
        lc = lcfg["config"]
        name = lc["name"]
        if cls == "InputLayer":
            shape = lc.get("batch_shape") or lc.get("batch_input_shape")
            input_types.append(_input_type_from_batch_shape(shape))
            continue
        parents = _inbound_parents(lcfg.get("inbound_nodes", []))
        if cls == "MultiHeadAttention":
            # keras MHA is called (query, value[, key]); the self-attention
            # arrangement passes the same tensor — our SelfAttentionLayer
            # takes it once. Distinct parents = cross-attention: unsupported
            uniq = sorted(set(parents))
            if len(uniq) > 1:
                raise ValueError(
                    "MultiHeadAttention with distinct query/value/key "
                    "parents (cross-attention) is not supported in import")
            parents = uniq
            # call-time kwargs live in the inbound node spec; importing a
            # causal model as full attention would be silently wrong
            def _has_truthy(o, key):
                if isinstance(o, dict):
                    return bool(o.get(key)) or any(
                        _has_truthy(v, key) for v in o.values())
                if isinstance(o, (list, tuple)):
                    return any(_has_truthy(v, key) for v in o)
                return False
            for bad in ("use_causal_mask", "attention_mask"):
                if _has_truthy(lcfg.get("inbound_nodes", []), bad):
                    raise ValueError(
                        f"MultiHeadAttention called with {bad} is not "
                        "supported in import (would silently import as "
                        "full bidirectional attention)")
        if cls == "Add":
            gb.add_vertex(name, ElementWiseVertex(op="add"), *parents)
            continue
        if cls == "Subtract":
            gb.add_vertex(name, ElementWiseVertex(op="subtract"), *parents)
            continue
        if cls == "Multiply":
            gb.add_vertex(name, ElementWiseVertex(op="product"), *parents)
            continue
        if cls == "Maximum":
            gb.add_vertex(name, ElementWiseVertex(op="max"), *parents)
            continue
        if cls == "Average":
            gb.add_vertex(name, ElementWiseVertex(op="average"), *parents)
            continue
        if cls == "Minimum":
            gb.add_vertex(name, ElementWiseVertex(op="min"), *parents)
            continue
        if cls == "Concatenate":
            gb.add_vertex(name, MergeVertex(data_format="NHWC"), *parents)
            continue
        if cls == "Dot":
            axes = lc.get("axes", -1)
            if lc.get("normalize"):
                raise ValueError("Dot(normalize=True) not supported")
            if isinstance(axes, (list, tuple)):
                if len(axes) != 2 or axes[0] != axes[1]:
                    raise ValueError(
                        f"Dot with differing axes {axes} not supported "
                        "(contracts different dims of each input)")
                axes = axes[0]
            from ..nn.vertices import DotProductVertex
            gb.add_vertex(name, DotProductVertex(axis=int(axes)), *parents)
            continue
        mapped = _map_layer(lcfg)
        mapped_by_name[name] = mapped
        gb.add_layer(name, mapped.layer, *parents)
        if mapped.vertex and mapped.vertex[0] in ("lstm", "rnn") and \
                not mapped.vertex[1]["return_sequences"]:
            # consumers reference the keras name; re-point by inserting the
            # wrapper under the keras name and renaming the cell layer —
            # simpler: wrapper gets suffix, later consumers resolved below
            raise ValueError(
                "Functional LSTM with return_sequences=False: wrap with "
                "LastTimeStep manually (Sequential import handles it)")

    gb.add_inputs(*input_names)
    gb.set_input_types(*input_types)
    gb.set_outputs(*output_names)
    net = ComputationGraph(gb.build()).init()
    if f is not None:  # config-only import keeps the random init
        for name, mapped in mapped_by_name.items():
            _set_params(net.params, net.state, name, mapped,
                        _h5_weights(f, name))
    return net
