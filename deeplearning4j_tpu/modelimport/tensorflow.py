"""TF-GraphDef import → SameDiff graph.

TPU-native equivalent of samediff-import-tensorflow (reference:
``nd4j/samediff-import/samediff-import-tensorflow`` — Kotlin
``OpMappingRegistry`` + per-op mapping rules + ``ImportGraph`` walk, and the
older ``TFGraphMapper``† per SURVEY.md §2.2/§3.5; reference mount was empty,
citations upstream-relative, unverified).

Same architecture as the reference: walk the frozen GraphDef in node order,
map each TF op through a per-op-type registry into catalog ops recorded on a
:class:`~deeplearning4j_tpu.autodiff.samediff.SameDiff` instance — which then
jit-compiles the whole program to XLA (§3.3 "TPU translation"). Frozen
inference graphs only (variables already folded to Const, the standard
``convert_variables_to_constants`` output the reference's test corpus uses).

Static-argument convention: TF passes reduction axes / target shapes /
permutations as Const *tensor inputs*; XLA needs them static, so the mapper
resolves Const inputs to python values at import time and bakes them into op
attrs. Unsupported op types raise with the op name (loud coverage gaps, as
the reference's ImportGraph does).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ..autodiff.samediff import SameDiff, SDVariable


class _Ctx:
    def __init__(self, sd: SameDiff, library: Optional[Dict] = None,
                 prefix: str = ""):
        self.sd = sd
        self.vars: Dict[str, SDVariable] = {}     # tf tensor name -> SDVar
        self.consts: Dict[str, np.ndarray] = {}   # tf node name -> value
        self.library = library or {}              # FunctionDef name -> def
        self.prefix = prefix                      # graph-name prefix (fn bodies)

    def get(self, ref: str) -> SDVariable:
        parts = ref.split(":")
        if len(parts) == 3:
            # FunctionDef-body ref 'node:out_arg_name:k' — k indexes WITHIN
            # the named output arg, so the flat slot needs the producing
            # op's output-arg table (bind_outputs registers these keys)
            named = f"{parts[0]}:{parts[1]}:{parts[2]}"
            if named in self.vars:
                return self.vars[named]
        name, idx = _split_ref(ref)
        if idx and f"{name}:{idx}" in self.vars:
            return self.vars[f"{name}:{idx}"]
        if name not in self.vars:
            raise ValueError(f"reference to unknown tensor {ref!r}")
        if idx:
            raise ValueError(
                f"reference {ref!r} wants output slot {idx} of a "
                "single-output mapping")
        return self.vars[name]

    def const_value(self, ref: str) -> np.ndarray:
        name = _strip(ref)
        if name not in self.consts:
            raise ValueError(
                f"op needs a static value but input {ref!r} is not Const")
        return self.consts[name]

    def local_key(self, node_name: str) -> str:
        """Graph names inside function bodies are prefixed (``fn/node``);
        tensor refs within the body use the unprefixed name."""
        if self.prefix and node_name.startswith(self.prefix):
            return node_name[len(self.prefix):]
        return node_name

    def set_const(self, node_name: str, value) -> None:
        self.consts[self.local_key(node_name)] = value

    def bind_outputs(self, node_name: str, vs,
                     op_type: Optional[str] = None) -> SDVariable:
        """Register the extra output slots of a multi-output node. With
        ``op_type``, also register FunctionDef-style named-arg keys
        (``node:out_arg:k``) from the TF op registry — refs inside If/While
        bodies use that spelling, and resolving only the trailing integer
        would alias every arg's slot 0."""
        key = self.local_key(node_name)
        for k, v in enumerate(vs):
            if k:
                self.vars[f"{key}:{k}"] = v
        if op_type is not None:
            try:
                from tensorflow.python.framework import (  # type: ignore
                    op_def_registry)
                op_def = op_def_registry.get(op_type)
            except Exception:
                op_def = None
            if op_def is not None and len(op_def.output_arg) == len(vs):
                # one tensor per output arg (true for TopKV2/Split-style
                # ops we map; number_attr/list outputs would need widths)
                for arg, v in zip(op_def.output_arg, vs):
                    self.vars[f"{key}:{arg.name}:0"] = v
        return vs[0]


def _strip(ref: str) -> str:
    """'node:0' -> 'node'; control deps '^node' are filtered earlier."""
    return ref.split(":")[0]


def _split_ref(ref: str):
    """GraphDef 'node:1' / FunctionDef 'node:out_name:1' -> (node, 1)."""
    parts = ref.split(":")
    idx = int(parts[-1]) if len(parts) > 1 and parts[-1].isdigit() else 0
    return parts[0], idx


def _attr(node, key, default=None):
    if key not in node.attr:
        return default
    a = node.attr[key]
    field = a.WhichOneof("value")
    v = getattr(a, field)
    if field == "list":
        for f in ("i", "f", "b", "s"):
            items = list(getattr(v, f))
            if items:
                return items
        return []
    if field == "s":
        return v.decode()
    return v


def _pair_from(v, layout="NHWC"):
    """ksize/strides attr [1,h,w,1] (NHWC) -> (h, w)."""
    v = list(v)
    if len(v) == 4:
        return (int(v[1]), int(v[2])) if layout == "NHWC" else (int(v[2]), int(v[3]))
    if len(v) == 2:
        return (int(v[0]), int(v[1]))
    return (int(v[0]),) * 2


_MAPPERS: Dict[str, Callable] = {}


def tf_op(*types):
    def deco(fn):
        for t in types:
            _MAPPERS[t] = fn
        return fn
    return deco


# ---- elementwise / unary ----------------------------------------------------
_UNARY = {"Relu": "act.relu", "Relu6": "act.relu6", "Elu": "act.elu",
          "Selu": "act.selu", "Sigmoid": "act.sigmoid", "Tanh": "act.tanh",
          "Softmax": "act.softmax", "LogSoftmax": "act.logsoftmax",
          "Softplus": "act.softplus", "Softsign": "act.softsign",
          "Exp": "math.exp", "Log": "math.log", "Log1p": "math.log1p",
          "Sqrt": "math.sqrt", "Rsqrt": "math.rsqrt", "Square": "math.square",
          "Abs": "math.abs", "Neg": "math.neg", "Sign": "math.sign",
          "Floor": "math.floor", "Ceil": "math.ceil", "Round": "math.round",
          "Erf": "math.erf", "Sin": "math.sin", "Cos": "math.cos",
          "Tan": "math.tan", "Sinh": "math.sinh", "Cosh": "math.cosh",
          "Asin": "math.asin", "Acos": "math.acos", "Atan": "math.atan",
          "Reciprocal": "math.reciprocal", "Expm1": "math.expm1",
          "IsNan": "math.isnan", "IsInf": "math.isinf",
          "Erfc": "math.erfc",
          "LogicalNot": "math.logical_not"}

_BINARY = {"Add": "math.add", "AddV2": "math.add",
           "Sub": "math.sub", "Mul": "math.mul", "RealDiv": "math.div",
           "Div": "math.div", "FloorDiv": "math.floordiv",
           "Maximum": "math.maximum", "Minimum": "math.minimum",
           "Pow": "math.pow", "SquaredDifference": "math.squared_difference",
           "FloorMod": "math.mod", "Atan2": "math.atan2",
           "Greater": "math.greater", "GreaterEqual": "math.greater_equal",
           "Less": "math.less", "LessEqual": "math.less_equal",
           "Equal": "math.equal", "NotEqual": "math.not_equal",
           "LogicalAnd": "math.logical_and", "LogicalOr": "math.logical_or"}


def _map_unary(node, ctx, ins):
    return ctx.sd.call(_UNARY[node.op], ctx.get(ins[0]), name=node.name)


# numpy equivalents for import-time const-folding: TF shape arithmetic
# (Shape -> StridedSlice -> Mul/Pack -> Reshape) must stay statically
# resolvable for const-consuming mappers like Reshape/Tile/Fill
_NP_BINARY = {"Add": np.add, "AddV2": np.add, "Sub": np.subtract,
              "Mul": np.multiply, "RealDiv": np.divide, "Div": np.divide,
              "FloorDiv": np.floor_divide, "Maximum": np.maximum,
              "Minimum": np.minimum, "FloorMod": np.mod}


def _map_binary(node, ctx, ins):
    if node.op in _NP_BINARY and all(_strip(i) in ctx.consts for i in ins):
        ctx.set_const(node.name, _NP_BINARY[node.op](
            np.asarray(ctx.consts[_strip(ins[0])]),
            np.asarray(ctx.consts[_strip(ins[1])])))
    return ctx.sd.call(_BINARY[node.op], ctx.get(ins[0]), ctx.get(ins[1]),
                       name=node.name)


@tf_op("AddN")
def _add_n(node, ctx, ins):
    out = ctx.get(ins[0])
    for i in ins[1:-1]:
        out = ctx.sd.call("math.add", out, ctx.get(i))
    if len(ins) > 1:
        out = ctx.sd.call("math.add", out, ctx.get(ins[-1]),
                          name=node.name)
        return out
    return ctx.sd.call("act.identity", out, name=node.name)


@tf_op("MatMul")
def _matmul(node, ctx, ins):
    return ctx.sd.call("linalg.mmul", ctx.get(ins[0]), ctx.get(ins[1]),
                       name=node.name,
                       attrs={"transpose_a": bool(_attr(node, "transpose_a", False)),
                              "transpose_b": bool(_attr(node, "transpose_b", False))})


@tf_op("Einsum")
def _einsum(node, ctx, ins):
    return ctx.sd.call("linalg.einsum", *[ctx.get(i) for i in ins],
                       name=node.name,
                       attrs={"equation": _attr(node, "equation")})


@tf_op("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3")
def _batch_matmul(node, ctx, ins):
    # adjoint == transpose for real tensors (our import surface is real)
    return ctx.sd.call("linalg.mmul", ctx.get(ins[0]), ctx.get(ins[1]),
                       name=node.name,
                       attrs={"transpose_a": bool(_attr(node, "adj_x", False)),
                              "transpose_b": bool(_attr(node, "adj_y", False))})


@tf_op("BiasAdd")
def _bias_add(node, ctx, ins):
    # NCHW BiasAdd would need the [C] bias broadcast over axis 1, not the
    # trailing axis plain add gives — reject it like the Conv2D/pool guards.
    if _attr(node, "data_format", "NHWC") != "NHWC":
        raise ValueError("BiasAdd NCHW graphs not supported (convert to NHWC)")
    return ctx.sd.call("math.add", ctx.get(ins[0]), ctx.get(ins[1]),
                       name=node.name)


@tf_op("Conv2D")
def _conv2d(node, ctx, ins):
    if _attr(node, "data_format", "NHWC") != "NHWC":
        raise ValueError("Conv2D NCHW graphs not supported (convert to NHWC)")
    pad = _attr(node, "padding", "VALID")
    if pad == "EXPLICIT":
        raise ValueError("Conv2D padding=EXPLICIT not supported "
                         "(explicit_paddings would be silently dropped)")
    # TF kernel layout HWIO; our conv2d stores OIHW
    w = ctx.sd.call("shape.transpose", ctx.get(ins[1]),
                    attrs={"axes": [3, 2, 0, 1]})
    return ctx.sd.call(
        "conv2d", ctx.get(ins[0]), w, name=node.name,
        attrs={"stride": _pair_from(_attr(node, "strides", [1, 1, 1, 1])),
               "dilation": _pair_from(_attr(node, "dilations", [1, 1, 1, 1])),
               "mode": "same" if pad == "SAME" else "truncate",
               "data_format": "NHWC"})


@tf_op("MaxPool", "AvgPool")
def _pool(node, ctx, ins):
    op = "maxpool2d" if node.op == "MaxPool" else "avgpool2d"
    if _attr(node, "data_format", "NHWC") != "NHWC":
        raise ValueError(f"{node.op} NCHW graphs not supported "
                         "(convert to NHWC)")
    pad = _attr(node, "padding", "VALID")
    if pad == "EXPLICIT":
        raise ValueError(f"{node.op} padding=EXPLICIT not supported")
    return ctx.sd.call(
        op, ctx.get(ins[0]), name=node.name,
        attrs={"kernel": _pair_from(_attr(node, "ksize", [1, 2, 2, 1])),
               "stride": _pair_from(_attr(node, "strides", [1, 2, 2, 1])),
               "mode": "same" if pad == "SAME" else "truncate",
               "data_format": "NHWC"})


@tf_op("DepthwiseConv2dNative")
def _depthwise_conv(node, ctx, ins):
    if _attr(node, "data_format", "NHWC") != "NHWC":
        raise ValueError("DepthwiseConv2dNative NCHW not supported")
    pad = _attr(node, "padding", "VALID")
    if pad == "EXPLICIT":
        raise ValueError("DepthwiseConv2dNative padding=EXPLICIT "
                         "not supported")
    # TF kernel [kH, kW, C, mult] -> our depthwise storage [C*mult, 1, kH, kW]
    w = ctx.sd.call("shape.transpose", ctx.get(ins[1]),
                    attrs={"axes": [2, 3, 0, 1]})      # [C, mult, kH, kW]
    w = ctx.sd.call("shape.reshape", w,
                    attrs={"shape": list(_depthwise_out_shape(ctx, ins[1]))})
    return ctx.sd.call(
        "depthwise_conv2d", ctx.get(ins[0]), w, name=node.name,
        attrs={"stride": _pair_from(_attr(node, "strides", [1, 1, 1, 1])),
               "dilation": _pair_from(_attr(node, "dilations", [1, 1, 1, 1])),
               "mode": "same" if pad == "SAME" else "truncate",
               "data_format": "NHWC"})


def _depthwise_out_shape(ctx, wref):
    """[C*mult, 1, kH, kW] target shape from the (const or shaped) kernel."""
    name = _strip(wref)
    if name in ctx.consts:
        kh, kw, c, mult = np.asarray(ctx.consts[name]).shape
    else:
        var = ctx.get(wref)
        if var.shape is None or any(s is None for s in var.shape):
            raise ValueError("DepthwiseConv2dNative needs a static kernel "
                             "shape")
        kh, kw, c, mult = var.shape
    return [c * mult, 1, kh, kw]


@tf_op("ResizeBilinear", "ResizeNearestNeighbor")
def _resize(node, ctx, ins):
    # jax.image.resize samples half-pixel centers — the TF2 convention
    # (tf.image.resize sets half_pixel_centers=True). The TF1 legacy grid
    # (half_pixel_centers=False / align_corners) is a different sampling
    # lattice; mapping it silently would be numerically wrong everywhere.
    if _attr(node, "align_corners", False):
        raise ValueError(f"{node.op} align_corners=True not supported")
    if not _attr(node, "half_pixel_centers", False):
        raise ValueError(
            f"{node.op} with the TF1 legacy grid (half_pixel_centers=False) "
            "not supported — re-export with tf.image.resize (TF2)")
    size = [int(s) for s in
            np.asarray(ctx.const_value(ins[1])).reshape(-1).tolist()]
    op = ("image.resize_bilinear" if node.op == "ResizeBilinear"
          else "image.resize_nearest")
    return ctx.sd.call(op, ctx.get(ins[0]), name=node.name,
                       attrs={"size": size, "data_format": "NHWC"})


@tf_op("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _fused_bn(node, ctx, ins):
    if _attr(node, "is_training", False):
        raise ValueError("FusedBatchNorm training mode not supported "
                         "(freeze the graph for inference import)")
    return ctx.sd.call("batch_norm", ctx.get(ins[0]), ctx.get(ins[1]),
                       ctx.get(ins[2]), ctx.get(ins[3]), ctx.get(ins[4]),
                       name=node.name,
                       attrs={"eps": float(_attr(node, "epsilon", 1e-3)),
                              "axis": -1})


@tf_op("Mean", "Sum", "Max", "Min", "Prod")
def _reduce(node, ctx, ins):
    op = {"Mean": "reduce.mean", "Sum": "reduce.sum", "Max": "reduce.max",
          "Min": "reduce.min", "Prod": "reduce.prod"}[node.op]
    axes = ctx.const_value(ins[1]).tolist()
    axes = axes if isinstance(axes, list) else [axes]
    keep = bool(_attr(node, "keep_dims", False))
    if _strip(ins[0]) in ctx.consts:  # tf.reduce_prod(shape) etc.
        np_red = {"Mean": np.mean, "Sum": np.sum, "Max": np.max,
                  "Min": np.min, "Prod": np.prod}[node.op]
        ctx.set_const(node.name, np_red(
            np.asarray(ctx.consts[_strip(ins[0])]),
            axis=tuple(int(a) for a in axes) or None, keepdims=keep))
    return ctx.sd.call(op, ctx.get(ins[0]), name=node.name,
                       attrs={"axis": tuple(int(a) for a in axes),
                              "keepdims": keep})


@tf_op("ArgMax", "ArgMin")
def _argreduce(node, ctx, ins):
    op = "reduce.argmax" if node.op == "ArgMax" else "reduce.argmin"
    axis = int(np.asarray(ctx.const_value(ins[1])))
    return ctx.sd.call(op, ctx.get(ins[0]), name=node.name,
                       attrs={"axis": axis})


@tf_op("Reshape")
def _reshape(node, ctx, ins):
    shape = [int(s) for s in ctx.const_value(ins[1]).tolist()]
    return ctx.sd.call("shape.reshape", ctx.get(ins[0]), name=node.name,
                       attrs={"shape": shape})


@tf_op("Transpose")
def _transpose(node, ctx, ins):
    perm = [int(p) for p in ctx.const_value(ins[1]).tolist()]
    return ctx.sd.call("shape.transpose", ctx.get(ins[0]), name=node.name,
                       attrs={"axes": perm})


@tf_op("ExpandDims")
def _expand(node, ctx, ins):
    axis = int(np.asarray(ctx.const_value(ins[1])))
    return ctx.sd.call("shape.expand_dims", ctx.get(ins[0]), name=node.name,
                       attrs={"axis": axis})


@tf_op("Squeeze")
def _squeeze(node, ctx, ins):
    dims = _attr(node, "squeeze_dims", []) or None
    attrs = {"axis": tuple(int(d) for d in dims)} if dims else {}
    return ctx.sd.call("shape.squeeze", ctx.get(ins[0]), name=node.name,
                       attrs=attrs)


@tf_op("ConcatV2")
def _concat(node, ctx, ins):
    axis = int(np.asarray(ctx.const_value(ins[-1])))
    return ctx.sd.call("shape.concat_v",
                       *[ctx.get(i) for i in ins[:-1]], name=node.name,
                       attrs={"axis": axis})


@tf_op("Pack")
def _pack(node, ctx, ins):
    axis = int(_attr(node, "axis", 0))
    if all(_strip(i) in ctx.consts for i in ins):
        ctx.set_const(node.name, np.stack(
            [np.asarray(ctx.consts[_strip(i)]) for i in ins], axis=axis))
    return ctx.sd.call("shape.stack_v", *[ctx.get(i) for i in ins],
                       name=node.name, attrs={"axis": axis})


@tf_op("GatherV2", "Gather")
def _gather(node, ctx, ins):
    axis = 0
    if len(ins) > 2:
        axis = int(np.asarray(ctx.const_value(ins[2])))
    return ctx.sd.call("shape.gather", ctx.get(ins[0]), ctx.get(ins[1]),
                       name=node.name, attrs={"axis": axis})


@tf_op("Pad", "PadV2")
def _pad(node, ctx, ins):
    widths = [(int(a), int(b)) for a, b in ctx.const_value(ins[1]).tolist()]
    return ctx.sd.call("shape.pad", ctx.get(ins[0]), name=node.name,
                       attrs={"pad_width": widths})


@tf_op("Tile")
def _tile(node, ctx, ins):
    reps = [int(r) for r in ctx.const_value(ins[1]).tolist()]
    return ctx.sd.call("shape.tile", ctx.get(ins[0]), name=node.name,
                       attrs={"reps": reps})


@tf_op("Cast")
def _cast(node, ctx, ins):
    """Faithful Cast: maps DstT to a math.cast with the target dtype (the
    reference maps DstT the same way; the former identity mapping silently
    relied on jnp promotion — a landmine for int->float graphs)."""
    from tensorflow.python.framework import dtypes as _tfd  # type: ignore
    np_dt = np.dtype(_tfd.as_dtype(int(_attr(node, "DstT"))).as_numpy_dtype)
    if _strip(ins[0]) in ctx.consts:
        # const-fold so shape-arithmetic chains stay statically resolvable
        ctx.set_const(node.name, np.asarray(
            ctx.consts[_strip(ins[0])]).astype(np_dt))
    return ctx.sd.call("math.cast", ctx.get(ins[0]), name=node.name,
                       attrs={"dtype": np_dt.name})


@tf_op("Shape")
def _shape_op(node, ctx, ins):
    """Static fold when the producer's shape is fully known (placeholders
    and constants record shapes); otherwise a shape_of op — any consumer
    that needs it as a STATIC value will raise the usual const error."""
    var = ctx.get(ins[0])
    if var.shape is not None and all(s is not None for s in var.shape):
        val = np.asarray(var.shape, np.int32)
        ctx.set_const(node.name, val)
        return ctx.sd.constant(node.name, val)
    return ctx.sd.call("shape.shape_of", var, name=node.name)


@tf_op("StridedSlice")
def _strided_slice(node, ctx, ins):
    """Full StridedSlice: begin/end/ellipsis/new-axis/shrink-axis masks are
    lowered to a numpy-style per-dim spec (shape.strided_slice_v2)."""
    begin = np.asarray(ctx.const_value(ins[1])).reshape(-1).tolist()
    end = np.asarray(ctx.const_value(ins[2])).reshape(-1).tolist()
    strides = np.asarray(ctx.const_value(ins[3])).reshape(-1).tolist() \
        if len(ins) > 3 else [1] * len(begin)
    bm = int(_attr(node, "begin_mask", 0))
    em = int(_attr(node, "end_mask", 0))
    el = int(_attr(node, "ellipsis_mask", 0))
    na = int(_attr(node, "new_axis_mask", 0))
    sh = int(_attr(node, "shrink_axis_mask", 0))
    spec = []
    for i in range(len(begin)):
        if (el >> i) & 1:
            spec.append(["ellipsis"])
        elif (na >> i) & 1:
            spec.append(["newaxis"])
        elif (sh >> i) & 1:
            spec.append(["index", int(begin[i])])
        else:
            spec.append(["slice",
                         None if (bm >> i) & 1 else int(begin[i]),
                         None if (em >> i) & 1 else int(end[i]),
                         int(strides[i])])
    if _strip(ins[0]) in ctx.consts:
        idx = tuple(slice(e[1], e[2], e[3]) if e[0] == "slice"
                    else int(e[1]) if e[0] == "index"
                    else None if e[0] == "newaxis" else Ellipsis
                    for e in spec)
        ctx.set_const(node.name, np.asarray(
            ctx.consts[_strip(ins[0])])[idx])
    return ctx.sd.call("shape.strided_slice_v2", ctx.get(ins[0]),
                       name=node.name, attrs={"spec": spec})


@tf_op("Split")
def _split(node, ctx, ins):
    axis = int(np.asarray(ctx.const_value(ins[0])))
    num = int(_attr(node, "num_split"))
    vs = ctx.sd.call_multi("shape.split", ctx.get(ins[1]), n_outputs=num,
                           name=node.name,
                           attrs={"indices_or_sections": num, "axis": axis})
    return ctx.bind_outputs(node.name, vs, op_type=node.op)


@tf_op("SplitV")
def _split_v(node, ctx, ins):
    sizes = np.asarray(ctx.const_value(ins[1])).reshape(-1).tolist()
    axis = int(np.asarray(ctx.const_value(ins[2])))
    if any(s < 0 for s in sizes):
        raise ValueError("SplitV with -1 (inferred) size not supported")
    cuts = np.cumsum(sizes)[:-1].tolist()
    vs = ctx.sd.call_multi("shape.split", ctx.get(ins[0]),
                           n_outputs=len(sizes), name=node.name,
                           attrs={"indices_or_sections": [int(c) for c in cuts],
                                  "axis": axis})
    return ctx.bind_outputs(node.name, vs, op_type=node.op)


@tf_op("Unpack")
def _unpack(node, ctx, ins):
    num = int(_attr(node, "num"))
    axis = int(_attr(node, "axis", 0))
    vs = ctx.sd.call_multi("shape.unstack", ctx.get(ins[0]), n_outputs=num,
                           name=node.name, attrs={"axis": axis})
    return ctx.bind_outputs(node.name, vs, op_type=node.op)


@tf_op("TopKV2")
def _topk(node, ctx, ins):
    k = int(np.asarray(ctx.const_value(ins[1])))
    vs = ctx.sd.call_multi("sort.top_k", ctx.get(ins[0]), n_outputs=2,
                           name=node.name, attrs={"k": k})
    return ctx.bind_outputs(node.name, vs, op_type=node.op)


def _import_function(ctx, fn_name: str, formals, sd):
    """Trace a GraphDef library FunctionDef as a SameDiff subgraph body.
    ``formals`` are the subgraph's formal SDVariables (one per signature
    input); returns the function's result SDVariables."""
    if fn_name not in ctx.library:
        raise ValueError(f"function {fn_name!r} not in graph library")
    fdef = ctx.library[fn_name]
    sub = _Ctx(sd, library=ctx.library, prefix=f"{ctx.prefix}{fn_name}/")
    args = list(fdef.signature.input_arg)
    if len(args) != len(formals):
        raise ValueError(f"function {fn_name!r} takes {len(args)} args, "
                         f"got {len(formals)}")
    for arg, var in zip(args, formals):
        sub.vars[arg.name] = var
    _map_nodes(fdef.node_def, sub, trainable=False)
    return [sub.get(fdef.ret[o.name]) for o in fdef.signature.output_arg]


@tf_op("StatelessIf", "If")
def _if(node, ctx, ins):
    """tf.cond: branch FunctionDefs become SameDiff cond subgraphs."""
    then_fn = _attr(node, "then_branch").name
    else_fn = _attr(node, "else_branch").name
    operands = [ctx.get(i) for i in ins[1:]]

    def mk(fname):
        def body(sd, *formals):
            return tuple(_import_function(ctx, fname, formals, sd))
        return body

    vs = ctx.sd.cond(ctx.get(ins[0]), mk(then_fn), mk(else_fn), *operands,
                     name=node.name)
    return ctx.bind_outputs(node.name, vs, op_type=node.op)


@tf_op("StatelessWhile", "While")
def _while(node, ctx, ins):
    """tf.while_loop: cond/body FunctionDefs become while subgraphs."""
    cond_fn = _attr(node, "cond").name
    body_fn = _attr(node, "body").name
    loop_vars = [ctx.get(i) for i in ins]

    def mk(fname):
        def body(sd, *formals):
            return tuple(_import_function(ctx, fname, formals, sd))
        return body

    vs = ctx.sd.while_loop(mk(cond_fn), mk(body_fn), *loop_vars,
                           name=node.name)
    return ctx.bind_outputs(node.name, vs, op_type=node.op)


@tf_op("StopGradient", "Identity", "PreventGradient", "CheckNumerics")
def _identity(node, ctx, ins):
    return ctx.sd.call("act.identity", ctx.get(ins[0]), name=node.name)


@tf_op("Select", "SelectV2")
def _select(node, ctx, ins):
    return ctx.sd.call("math.where", ctx.get(ins[0]), ctx.get(ins[1]),
                       ctx.get(ins[2]), name=node.name)


@tf_op("ClipByValue")
def _clip(node, ctx, ins):
    lo = float(np.asarray(ctx.const_value(ins[1])))
    hi = float(np.asarray(ctx.const_value(ins[2])))
    return ctx.sd.call("math.clip", ctx.get(ins[0]), name=node.name,
                       attrs={"min_value": lo, "max_value": hi})


@tf_op("LeakyRelu")
def _leaky(node, ctx, ins):
    alpha = float(_attr(node, "alpha", 0.2))
    return ctx.sd.call("act.leakyrelu", ctx.get(ins[0]), name=node.name,
                       attrs={"alpha": alpha})


@tf_op("Fill")
def _fill(node, ctx, ins):
    dims = [int(d) for d in np.asarray(ctx.const_value(ins[0])).tolist()]
    return ctx.sd.call("shape.broadcast_to", ctx.get(ins[1]),
                       name=node.name, attrs={"shape": dims})


@tf_op("Range")
def _range(node, ctx, ins):
    start = np.asarray(ctx.const_value(ins[0]))
    limit = np.asarray(ctx.const_value(ins[1]))
    delta = np.asarray(ctx.const_value(ins[2]))
    value = np.arange(start, limit, delta)
    ctx.set_const(node.name, value)
    return ctx.sd.constant(node.name, value)


@tf_op("All", "Any")
def _reduce_bool(node, ctx, ins):
    # feeds Asserts in frozen graphs; map faithfully anyway. Lowered via
    # reduce.min/max on the bool array (catalog has no reduce.all);
    # min==True iff all True, max==True iff any True
    axes = np.asarray(ctx.const_value(ins[1])).reshape(-1).tolist()
    red = "reduce.min" if node.op == "All" else "reduce.max"
    return ctx.sd.call(red, ctx.get(ins[0]), name=node.name,
                       attrs={"axis": tuple(int(a) for a in axes),
                              "keepdims": bool(_attr(node, "keep_dims",
                                                     False))})


@tf_op("Slice")
def _slice(node, ctx, ins):
    begin = [int(v) for v in np.asarray(ctx.const_value(ins[1])).tolist()]
    size = [int(v) for v in np.asarray(ctx.const_value(ins[2])).tolist()]
    end = [b + s if s != -1 else None for b, s in zip(begin, size)]
    # lower to strided_slice with unit strides
    return ctx.sd.call("shape.strided_slice", ctx.get(ins[0]),
                       name=node.name,
                       attrs={"begin": begin,
                              "end": [e if e is not None else 2**31 - 1
                                      for e in end]})


@tf_op("OneHot")
def _one_hot(node, ctx, ins):
    depth = int(np.asarray(ctx.const_value(ins[1])))
    return ctx.sd.call("shape.one_hot", ctx.get(ins[0]), name=node.name,
                       attrs={"depth": depth})


class _Renamed:
    """Node shim that presents a prefixed graph name (function-body nodes
    must not collide with main-graph names) while passing everything else
    through to the proto node."""

    def __init__(self, node, name):
        self._node = node
        self.name = name

    def __getattr__(self, attr):
        return getattr(self._node, attr)


def _map_nodes(nodes, ctx: _Ctx, trainable: bool):
    """Map a node list (GraphDef.node or FunctionDef.node_def) into
    ``ctx.sd``. ``ctx.vars``/``ctx.consts`` are keyed by the LOCAL (tf)
    names; SameDiff graph names carry ``ctx.prefix``."""
    sd = ctx.sd
    for node in nodes:
        key = node.name
        if ctx.prefix:
            node = _Renamed(node, ctx.prefix + node.name)
        ins = [i for i in node.input if not i.startswith("^")]
        if node.op == "Const":
            value = _tensor_value(node)
            ctx.consts[key] = value
            if value.dtype == np.object_ or value.dtype.kind == "U":
                continue  # string consts (Assert messages): attr-only
            if trainable and value.dtype.kind == "f" and value.ndim >= 1:
                ctx.vars[key] = sd.var(node.name, value)
            else:
                ctx.vars[key] = sd.constant(node.name, value)
        elif node.op in ("Placeholder", "PlaceholderV2"):
            ctx.vars[key] = sd.placeholder(node.name, _attr_shape(node))
        elif node.op in ("NoOp", "Assert"):
            continue  # control-flow only; referenced via ^control deps
        elif node.op in _UNARY:
            ctx.vars[key] = _map_unary(node, ctx, ins)
        elif node.op in _BINARY:
            ctx.vars[key] = _map_binary(node, ctx, ins)
        elif node.op in _MAPPERS:
            ctx.vars[key] = _MAPPERS[node.op](node, ctx, ins)
        elif node.op in ("Switch", "Merge", "Enter", "Exit",
                         "NextIteration", "LoopCond"):
            raise ValueError(
                f"v1-style dataflow control flow ({node.op!r}, node "
                f"{node.name!r}) is not supported — re-freeze with "
                "convert_variables_to_constants_v2(..., "
                "lower_control_flow=False) to keep functional "
                "StatelessIf/StatelessWhile nodes, which import as "
                "SameDiff cond/while subgraphs")
        else:
            raise ValueError(
                f"unsupported TF op type {node.op!r} (node "
                f"{node.name!r}) — extend modelimport/tensorflow.py")


class TensorflowFrameworkImporter:
    """Reference-parity entry point (``TensorflowFrameworkImporter`` /
    ``TFGraphMapper.importGraph``†)."""

    @staticmethod
    def import_graph_def(graph_def, trainable: bool = False) -> SameDiff:
        """Frozen GraphDef (proto object or serialized bytes) → SameDiff.
        Placeholders become SameDiff placeholders; run with
        ``sd.output({placeholder: value}, [output_names])``.

        ``trainable=True`` imports non-scalar FLOAT constants (the frozen
        model's weights) as trainable VARIABLEs, so the imported graph
        fine-tunes via ``sd.fit`` — the BERT-via-TF-import baseline path.
        Scalar/int consts (shapes, axes, epsilons) stay constant.

        Control flow: StatelessIf/If and StatelessWhile/While nodes import
        their branch/cond/body FunctionDefs (``graph_def.library``) as
        SameDiff cond/while subgraphs → ``lax.cond``/``lax.while_loop``."""
        if isinstance(graph_def, (bytes, bytearray)):
            from tensorflow.core.framework import graph_pb2  # type: ignore
            gd = graph_pb2.GraphDef()
            gd.ParseFromString(bytes(graph_def))
            graph_def = gd

        sd = SameDiff()
        library = {f.signature.name: f
                   for f in graph_def.library.function} \
            if graph_def.HasField("library") else {}
        ctx = _Ctx(sd, library=library)
        _map_nodes(graph_def.node, ctx, trainable)
        return sd

    @staticmethod
    def import_file(path: str) -> SameDiff:
        with open(path, "rb") as f:
            return TensorflowFrameworkImporter.import_graph_def(f.read())


def _tensor_value(node) -> np.ndarray:
    from tensorflow.python.framework import tensor_util  # type: ignore
    return np.asarray(tensor_util.MakeNdarray(node.attr["value"].tensor))


def _attr_shape(node):
    if "shape" not in node.attr:
        return None
    dims = [d.size for d in node.attr["shape"].shape.dim]
    return tuple(None if d == -1 else int(d) for d in dims) or None
