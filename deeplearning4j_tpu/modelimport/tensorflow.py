"""TF-GraphDef import → SameDiff graph.

TPU-native equivalent of samediff-import-tensorflow (reference:
``nd4j/samediff-import/samediff-import-tensorflow`` — Kotlin
``OpMappingRegistry`` + per-op mapping rules + ``ImportGraph`` walk, and the
older ``TFGraphMapper``† per SURVEY.md §2.2/§3.5; reference mount was empty,
citations upstream-relative, unverified).

Same architecture as the reference: walk the frozen GraphDef in node order,
map each TF op through a per-op-type registry into catalog ops recorded on a
:class:`~deeplearning4j_tpu.autodiff.samediff.SameDiff` instance — which then
jit-compiles the whole program to XLA (§3.3 "TPU translation"). Frozen
inference graphs only (variables already folded to Const, the standard
``convert_variables_to_constants`` output the reference's test corpus uses).

Static-argument convention: TF passes reduction axes / target shapes /
permutations as Const *tensor inputs*; XLA needs them static, so the mapper
resolves Const inputs to python values at import time and bakes them into op
attrs. Unsupported op types raise with the op name (loud coverage gaps, as
the reference's ImportGraph does).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ..autodiff.samediff import SameDiff, SDVariable


class _Ctx:
    def __init__(self, sd: SameDiff):
        self.sd = sd
        self.vars: Dict[str, SDVariable] = {}     # tf tensor name -> SDVar
        self.consts: Dict[str, np.ndarray] = {}   # tf node name -> value

    def get(self, ref: str) -> SDVariable:
        name = _strip(ref)
        if name not in self.vars:
            raise ValueError(f"reference to unknown tensor {ref!r}")
        return self.vars[name]

    def const_value(self, ref: str) -> np.ndarray:
        name = _strip(ref)
        if name not in self.consts:
            raise ValueError(
                f"op needs a static value but input {ref!r} is not Const")
        return self.consts[name]


def _strip(ref: str) -> str:
    """'node:0' -> 'node'; control deps '^node' are filtered earlier."""
    return ref.split(":")[0]


def _attr(node, key, default=None):
    if key not in node.attr:
        return default
    a = node.attr[key]
    field = a.WhichOneof("value")
    v = getattr(a, field)
    if field == "list":
        for f in ("i", "f", "b", "s"):
            items = list(getattr(v, f))
            if items:
                return items
        return []
    if field == "s":
        return v.decode()
    return v


def _pair_from(v, layout="NHWC"):
    """ksize/strides attr [1,h,w,1] (NHWC) -> (h, w)."""
    v = list(v)
    if len(v) == 4:
        return (int(v[1]), int(v[2])) if layout == "NHWC" else (int(v[2]), int(v[3]))
    if len(v) == 2:
        return (int(v[0]), int(v[1]))
    return (int(v[0]),) * 2


_MAPPERS: Dict[str, Callable] = {}


def tf_op(*types):
    def deco(fn):
        for t in types:
            _MAPPERS[t] = fn
        return fn
    return deco


# ---- elementwise / unary ----------------------------------------------------
_UNARY = {"Relu": "act.relu", "Relu6": "act.relu6", "Elu": "act.elu",
          "Selu": "act.selu", "Sigmoid": "act.sigmoid", "Tanh": "act.tanh",
          "Softmax": "act.softmax", "LogSoftmax": "act.logsoftmax",
          "Softplus": "act.softplus", "Softsign": "act.softsign",
          "Exp": "math.exp", "Log": "math.log", "Log1p": "math.log1p",
          "Sqrt": "math.sqrt", "Rsqrt": "math.rsqrt", "Square": "math.square",
          "Abs": "math.abs", "Neg": "math.neg", "Sign": "math.sign",
          "Floor": "math.floor", "Ceil": "math.ceil", "Round": "math.round",
          "Erf": "math.erf", "Sin": "math.sin", "Cos": "math.cos",
          "Tan": "math.tan", "Sinh": "math.sinh", "Cosh": "math.cosh",
          "Asin": "math.asin", "Acos": "math.acos", "Atan": "math.atan",
          "Reciprocal": "math.reciprocal", "Expm1": "math.expm1",
          "IsNan": "math.isnan", "IsInf": "math.isinf",
          "Erfc": "math.erfc",
          "LogicalNot": "math.logical_not"}

_BINARY = {"Add": "math.add", "AddV2": "math.add",
           "Sub": "math.sub", "Mul": "math.mul", "RealDiv": "math.div",
           "Div": "math.div", "FloorDiv": "math.floordiv",
           "Maximum": "math.maximum", "Minimum": "math.minimum",
           "Pow": "math.pow", "SquaredDifference": "math.squared_difference",
           "FloorMod": "math.fmod", "Atan2": "math.atan2",
           "Greater": "math.greater", "GreaterEqual": "math.greater_equal",
           "Less": "math.less", "LessEqual": "math.less_equal",
           "Equal": "math.equal", "NotEqual": "math.not_equal",
           "LogicalAnd": "math.logical_and", "LogicalOr": "math.logical_or"}


def _map_unary(node, ctx, ins):
    return ctx.sd.call(_UNARY[node.op], ctx.get(ins[0]), name=node.name)


def _map_binary(node, ctx, ins):
    return ctx.sd.call(_BINARY[node.op], ctx.get(ins[0]), ctx.get(ins[1]),
                       name=node.name)


@tf_op("MatMul")
def _matmul(node, ctx, ins):
    return ctx.sd.call("linalg.mmul", ctx.get(ins[0]), ctx.get(ins[1]),
                       name=node.name,
                       attrs={"transpose_a": bool(_attr(node, "transpose_a", False)),
                              "transpose_b": bool(_attr(node, "transpose_b", False))})


@tf_op("Einsum")
def _einsum(node, ctx, ins):
    return ctx.sd.call("linalg.einsum", *[ctx.get(i) for i in ins],
                       name=node.name,
                       attrs={"equation": _attr(node, "equation")})


@tf_op("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3")
def _batch_matmul(node, ctx, ins):
    # adjoint == transpose for real tensors (our import surface is real)
    return ctx.sd.call("linalg.mmul", ctx.get(ins[0]), ctx.get(ins[1]),
                       name=node.name,
                       attrs={"transpose_a": bool(_attr(node, "adj_x", False)),
                              "transpose_b": bool(_attr(node, "adj_y", False))})


@tf_op("BiasAdd")
def _bias_add(node, ctx, ins):
    # NCHW BiasAdd would need the [C] bias broadcast over axis 1, not the
    # trailing axis plain add gives — reject it like the Conv2D/pool guards.
    if _attr(node, "data_format", "NHWC") != "NHWC":
        raise ValueError("BiasAdd NCHW graphs not supported (convert to NHWC)")
    return ctx.sd.call("math.add", ctx.get(ins[0]), ctx.get(ins[1]),
                       name=node.name)


@tf_op("Conv2D")
def _conv2d(node, ctx, ins):
    if _attr(node, "data_format", "NHWC") != "NHWC":
        raise ValueError("Conv2D NCHW graphs not supported (convert to NHWC)")
    pad = _attr(node, "padding", "VALID")
    if pad == "EXPLICIT":
        raise ValueError("Conv2D padding=EXPLICIT not supported "
                         "(explicit_paddings would be silently dropped)")
    # TF kernel layout HWIO; our conv2d stores OIHW
    w = ctx.sd.call("shape.transpose", ctx.get(ins[1]),
                    attrs={"axes": [3, 2, 0, 1]})
    return ctx.sd.call(
        "conv2d", ctx.get(ins[0]), w, name=node.name,
        attrs={"stride": _pair_from(_attr(node, "strides", [1, 1, 1, 1])),
               "dilation": _pair_from(_attr(node, "dilations", [1, 1, 1, 1])),
               "mode": "same" if pad == "SAME" else "truncate",
               "data_format": "NHWC"})


@tf_op("MaxPool", "AvgPool")
def _pool(node, ctx, ins):
    op = "maxpool2d" if node.op == "MaxPool" else "avgpool2d"
    if _attr(node, "data_format", "NHWC") != "NHWC":
        raise ValueError(f"{node.op} NCHW graphs not supported "
                         "(convert to NHWC)")
    pad = _attr(node, "padding", "VALID")
    if pad == "EXPLICIT":
        raise ValueError(f"{node.op} padding=EXPLICIT not supported")
    return ctx.sd.call(
        op, ctx.get(ins[0]), name=node.name,
        attrs={"kernel": _pair_from(_attr(node, "ksize", [1, 2, 2, 1])),
               "stride": _pair_from(_attr(node, "strides", [1, 2, 2, 1])),
               "mode": "same" if pad == "SAME" else "truncate",
               "data_format": "NHWC"})


@tf_op("FusedBatchNorm", "FusedBatchNormV2", "FusedBatchNormV3")
def _fused_bn(node, ctx, ins):
    if _attr(node, "is_training", False):
        raise ValueError("FusedBatchNorm training mode not supported "
                         "(freeze the graph for inference import)")
    return ctx.sd.call("batch_norm", ctx.get(ins[0]), ctx.get(ins[1]),
                       ctx.get(ins[2]), ctx.get(ins[3]), ctx.get(ins[4]),
                       name=node.name,
                       attrs={"eps": float(_attr(node, "epsilon", 1e-3)),
                              "axis": -1})


@tf_op("Mean", "Sum", "Max", "Min", "Prod")
def _reduce(node, ctx, ins):
    op = {"Mean": "reduce.mean", "Sum": "reduce.sum", "Max": "reduce.max",
          "Min": "reduce.min", "Prod": "reduce.prod"}[node.op]
    axes = ctx.const_value(ins[1]).tolist()
    axes = axes if isinstance(axes, list) else [axes]
    return ctx.sd.call(op, ctx.get(ins[0]), name=node.name,
                       attrs={"axis": tuple(int(a) for a in axes),
                              "keepdims": bool(_attr(node, "keep_dims", False))})


@tf_op("ArgMax", "ArgMin")
def _argreduce(node, ctx, ins):
    op = "reduce.argmax" if node.op == "ArgMax" else "reduce.argmin"
    axis = int(np.asarray(ctx.const_value(ins[1])))
    return ctx.sd.call(op, ctx.get(ins[0]), name=node.name,
                       attrs={"axis": axis})


@tf_op("Reshape")
def _reshape(node, ctx, ins):
    shape = [int(s) for s in ctx.const_value(ins[1]).tolist()]
    return ctx.sd.call("shape.reshape", ctx.get(ins[0]), name=node.name,
                       attrs={"shape": shape})


@tf_op("Transpose")
def _transpose(node, ctx, ins):
    perm = [int(p) for p in ctx.const_value(ins[1]).tolist()]
    return ctx.sd.call("shape.transpose", ctx.get(ins[0]), name=node.name,
                       attrs={"axes": perm})


@tf_op("ExpandDims")
def _expand(node, ctx, ins):
    axis = int(np.asarray(ctx.const_value(ins[1])))
    return ctx.sd.call("shape.expand_dims", ctx.get(ins[0]), name=node.name,
                       attrs={"axis": axis})


@tf_op("Squeeze")
def _squeeze(node, ctx, ins):
    dims = _attr(node, "squeeze_dims", []) or None
    attrs = {"axis": tuple(int(d) for d in dims)} if dims else {}
    return ctx.sd.call("shape.squeeze", ctx.get(ins[0]), name=node.name,
                       attrs=attrs)


@tf_op("ConcatV2")
def _concat(node, ctx, ins):
    axis = int(np.asarray(ctx.const_value(ins[-1])))
    return ctx.sd.call("shape.concat_v",
                       *[ctx.get(i) for i in ins[:-1]], name=node.name,
                       attrs={"axis": axis})


@tf_op("Pack")
def _pack(node, ctx, ins):
    return ctx.sd.call("shape.stack_v", *[ctx.get(i) for i in ins],
                       name=node.name,
                       attrs={"axis": int(_attr(node, "axis", 0))})


@tf_op("GatherV2", "Gather")
def _gather(node, ctx, ins):
    axis = 0
    if len(ins) > 2:
        axis = int(np.asarray(ctx.const_value(ins[2])))
    return ctx.sd.call("shape.gather", ctx.get(ins[0]), ctx.get(ins[1]),
                       name=node.name, attrs={"axis": axis})


@tf_op("Pad", "PadV2")
def _pad(node, ctx, ins):
    widths = [(int(a), int(b)) for a, b in ctx.const_value(ins[1]).tolist()]
    return ctx.sd.call("shape.pad", ctx.get(ins[0]), name=node.name,
                       attrs={"pad_width": widths})


@tf_op("Tile")
def _tile(node, ctx, ins):
    reps = [int(r) for r in ctx.const_value(ins[1]).tolist()]
    return ctx.sd.call("shape.tile", ctx.get(ins[0]), name=node.name,
                       attrs={"reps": reps})


@tf_op("Cast")
def _cast(node, ctx, ins):
    # dtype tracking is owned by XLA here; pass-through (recorded divergence:
    # the reference maps DstT; our catalog ops promote per jnp rules)
    return ctx.sd.call("act.identity", ctx.get(ins[0]), name=node.name)


@tf_op("StopGradient", "Identity", "PreventGradient", "CheckNumerics")
def _identity(node, ctx, ins):
    return ctx.sd.call("act.identity", ctx.get(ins[0]), name=node.name)


@tf_op("Select", "SelectV2")
def _select(node, ctx, ins):
    return ctx.sd.call("math.where", ctx.get(ins[0]), ctx.get(ins[1]),
                       ctx.get(ins[2]), name=node.name)


@tf_op("ClipByValue")
def _clip(node, ctx, ins):
    lo = float(np.asarray(ctx.const_value(ins[1])))
    hi = float(np.asarray(ctx.const_value(ins[2])))
    return ctx.sd.call("math.clip", ctx.get(ins[0]), name=node.name,
                       attrs={"min_value": lo, "max_value": hi})


@tf_op("LeakyRelu")
def _leaky(node, ctx, ins):
    alpha = float(_attr(node, "alpha", 0.2))
    return ctx.sd.call("act.leakyrelu", ctx.get(ins[0]), name=node.name,
                       attrs={"alpha": alpha})


@tf_op("Fill")
def _fill(node, ctx, ins):
    dims = [int(d) for d in np.asarray(ctx.const_value(ins[0])).tolist()]
    return ctx.sd.call("shape.broadcast_to", ctx.get(ins[1]),
                       name=node.name, attrs={"shape": dims})


@tf_op("Range")
def _range(node, ctx, ins):
    start = np.asarray(ctx.const_value(ins[0]))
    limit = np.asarray(ctx.const_value(ins[1]))
    delta = np.asarray(ctx.const_value(ins[2]))
    value = np.arange(start, limit, delta)
    ctx.consts[node.name] = value
    return ctx.sd.constant(node.name, value)


@tf_op("All", "Any")
def _reduce_bool(node, ctx, ins):
    # feeds Asserts in frozen graphs; map faithfully anyway. Lowered via
    # reduce.min/max on the bool array (catalog has no reduce.all);
    # min==True iff all True, max==True iff any True
    axes = np.asarray(ctx.const_value(ins[1])).reshape(-1).tolist()
    red = "reduce.min" if node.op == "All" else "reduce.max"
    return ctx.sd.call(red, ctx.get(ins[0]), name=node.name,
                       attrs={"axis": tuple(int(a) for a in axes),
                              "keepdims": bool(_attr(node, "keep_dims",
                                                     False))})


@tf_op("Slice")
def _slice(node, ctx, ins):
    begin = [int(v) for v in np.asarray(ctx.const_value(ins[1])).tolist()]
    size = [int(v) for v in np.asarray(ctx.const_value(ins[2])).tolist()]
    end = [b + s if s != -1 else None for b, s in zip(begin, size)]
    # lower to strided_slice with unit strides
    return ctx.sd.call("shape.strided_slice", ctx.get(ins[0]),
                       name=node.name,
                       attrs={"begin": begin,
                              "end": [e if e is not None else 2**31 - 1
                                      for e in end]})


@tf_op("OneHot")
def _one_hot(node, ctx, ins):
    depth = int(np.asarray(ctx.const_value(ins[1])))
    return ctx.sd.call("shape.one_hot", ctx.get(ins[0]), name=node.name,
                       attrs={"depth": depth})


class TensorflowFrameworkImporter:
    """Reference-parity entry point (``TensorflowFrameworkImporter`` /
    ``TFGraphMapper.importGraph``†)."""

    @staticmethod
    def import_graph_def(graph_def, trainable: bool = False) -> SameDiff:
        """Frozen GraphDef (proto object or serialized bytes) → SameDiff.
        Placeholders become SameDiff placeholders; run with
        ``sd.output({placeholder: value}, [output_names])``.

        ``trainable=True`` imports non-scalar FLOAT constants (the frozen
        model's weights) as trainable VARIABLEs, so the imported graph
        fine-tunes via ``sd.fit`` — the BERT-via-TF-import baseline path.
        Scalar/int consts (shapes, axes, epsilons) stay constant."""
        if isinstance(graph_def, (bytes, bytearray)):
            from tensorflow.core.framework import graph_pb2  # type: ignore
            gd = graph_pb2.GraphDef()
            gd.ParseFromString(bytes(graph_def))
            graph_def = gd

        sd = SameDiff()
        ctx = _Ctx(sd)
        for node in graph_def.node:
            ins = [i for i in node.input if not i.startswith("^")]
            if node.op == "Const":
                value = _tensor_value(node)
                ctx.consts[node.name] = value
                if value.dtype == np.object_ or value.dtype.kind == "U":
                    continue  # string consts (Assert messages): attr-only
                if trainable and value.dtype.kind == "f" and value.ndim >= 1:
                    ctx.vars[node.name] = sd.var(node.name, value)
                else:
                    ctx.vars[node.name] = sd.constant(node.name, value)
            elif node.op in ("Placeholder", "PlaceholderV2"):
                shape = _attr_shape(node)
                ctx.vars[node.name] = sd.placeholder(node.name, shape)
            elif node.op in ("NoOp", "Assert"):
                continue  # control-flow only; referenced via ^control deps
            elif node.op in _UNARY:
                ctx.vars[node.name] = _map_unary(node, ctx, ins)
            elif node.op in _BINARY:
                ctx.vars[node.name] = _map_binary(node, ctx, ins)
            elif node.op in _MAPPERS:
                ctx.vars[node.name] = _MAPPERS[node.op](node, ctx, ins)
            else:
                raise ValueError(
                    f"unsupported TF op type {node.op!r} (node "
                    f"{node.name!r}) — extend modelimport/tensorflow.py")
        return sd

    @staticmethod
    def import_file(path: str) -> SameDiff:
        with open(path, "rb") as f:
            return TensorflowFrameworkImporter.import_graph_def(f.read())


def _tensor_value(node) -> np.ndarray:
    from tensorflow.python.framework import tensor_util  # type: ignore
    return np.asarray(tensor_util.MakeNdarray(node.attr["value"].tensor))


def _attr_shape(node):
    if "shape" not in node.attr:
        return None
    dims = [d.size for d in node.attr["shape"].shape.dim]
    return tuple(None if d == -1 else int(d) for d in dims) or None
