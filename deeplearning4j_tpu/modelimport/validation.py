"""Import-validation runners: execute source-framework models and diff
against our import — one-liner triage for importer work.

TPU-native equivalent of the reference's validation backends (reference:
``nd4j/nd4j-tensorflow`` ``GraphRunner`` over libtensorflow and
``nd4j/nd4j-onnxruntime`` over onnxruntime† per SURVEY.md §2.2; reference
mount was empty, citations upstream-relative, unverified). The reference
runs the SOURCE framework in-process as the oracle for import regression
tests; here the oracles are the in-environment tensorflow (GraphDef) and
torch (ONNX is validated against a caller-supplied torch module — the
onnxruntime package is absent, and torch is this environment's ONNX
producer anyway).

Usage::

    from deeplearning4j_tpu.modelimport.validation import (
        TensorflowGraphRunner, validate_tf_import, validate_onnx_import)

    # run a frozen GraphDef under live TF (oracle side only)
    runner = TensorflowGraphRunner(graph_def, ["x"], ["out"])
    outs = runner.run({"x": x})

    # full triage: oracle run + our import + numeric diff
    report = validate_tf_import(graph_def, {"x": x}, ["out"])
    assert report.ok, report.summary()
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclass
class ValidationReport:
    """Per-output numeric diff between the source framework and our import."""
    ok: bool
    max_abs_diff: Dict[str, float] = field(default_factory=dict)
    max_rel_diff: Dict[str, float] = field(default_factory=dict)
    shapes: Dict[str, tuple] = field(default_factory=dict)
    atol: float = 1e-4
    rtol: float = 1e-4
    error: Optional[str] = None

    def summary(self) -> str:
        if self.error:
            return f"FAILED: {self.error}"
        lines = [f"{'OK' if self.ok else 'MISMATCH'} "
                 f"(atol={self.atol}, rtol={self.rtol})"]
        for name in self.max_abs_diff:
            lines.append(
                f"  {name}: shape={self.shapes.get(name)} "
                f"max_abs={self.max_abs_diff[name]:.3e} "
                f"max_rel={self.max_rel_diff[name]:.3e}")
        return "\n".join(lines)


class TensorflowGraphRunner:
    """Run a frozen TF GraphDef via live tensorflow (nd4j-tensorflow
    ``GraphRunner`` parity)."""

    def __init__(self, graph_def, input_names: Sequence[str],
                 output_names: Sequence[str]):
        import tensorflow as tf
        if isinstance(graph_def, (bytes, bytearray)):
            from tensorflow.core.framework import graph_pb2
            gd = graph_pb2.GraphDef()
            gd.ParseFromString(bytes(graph_def))
            graph_def = gd
        self._tf = tf
        self.graph_def = graph_def
        self.input_names = list(input_names)
        self.output_names = list(output_names)

        def _import():
            self._tf.graph_util.import_graph_def(self.graph_def, name="")

        def tensor_name(n: str) -> str:
            # bare op names address output 0; "op:1"-style names pass
            # through so non-default outputs stay reachable
            return n if ":" in n else f"{n}:0"

        wrapped = tf.compat.v1.wrap_function(_import, [])
        self._fn = wrapped.prune(
            [tensor_name(n) for n in self.input_names],
            [tensor_name(n) for n in self.output_names])

    def run(self, feeds: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        args = [self._tf.constant(feeds[n]) for n in self.input_names]
        outs = self._fn(*args)
        return {n: np.asarray(o)
                for n, o in zip(self.output_names, outs)}


def _diff(ref: Dict[str, np.ndarray], got: Dict[str, np.ndarray],
          atol: float, rtol: float) -> ValidationReport:
    rep = ValidationReport(ok=True, atol=atol, rtol=rtol)
    for name, r in ref.items():
        g = np.asarray(got[name])
        r = np.asarray(r)
        rep.shapes[name] = tuple(g.shape)
        if g.shape != r.shape:
            rep.ok = False
            rep.error = (f"{name}: shape mismatch ours {g.shape} "
                         f"vs source {r.shape}")
            return rep
        ad = np.abs(g.astype(np.float64) - r.astype(np.float64))
        rep.max_abs_diff[name] = float(ad.max()) if ad.size else 0.0
        denom = np.maximum(np.abs(r.astype(np.float64)), 1e-12)
        rep.max_rel_diff[name] = float((ad / denom).max()) if ad.size else 0.0
        if not np.allclose(g, r, atol=atol, rtol=rtol):
            rep.ok = False
    return rep


def validate_tf_import(graph_def, feeds: Dict[str, np.ndarray],
                       output_names: Sequence[str], atol: float = 1e-4,
                       rtol: float = 1e-4) -> ValidationReport:
    """Import a GraphDef with our TF frontend AND run it under live TF;
    diff every requested output."""
    from .tensorflow import TensorflowFrameworkImporter
    try:
        runner = TensorflowGraphRunner(graph_def, list(feeds), output_names)
        ref = runner.run(feeds)
        sd = TensorflowFrameworkImporter.import_graph_def(runner.graph_def)
        got = sd.output(feeds, list(output_names))
        return _diff(ref, got, atol, rtol)
    except Exception as e:
        return ValidationReport(ok=False, atol=atol, rtol=rtol,
                                error=f"{type(e).__name__}: {e}")


def validate_onnx_import(onnx_bytes, torch_module, feeds: Dict[str, np.ndarray],
                         atol: float = 1e-4, rtol: float = 1e-4
                         ) -> ValidationReport:
    """Import ONNX bytes with our frontend and diff against the producing
    torch module's forward (the environment has no onnxruntime — torch IS
    the oracle here; recorded divergence from nd4j-onnxruntime)."""
    import torch
    from .onnx import OnnxFrameworkImporter
    try:
        sd = OnnxFrameworkImporter.import_model_proto(onnx_bytes)
        out_names = list(sd.onnx_outputs)
        got = sd.output(feeds, out_names)
        # feed the torch oracle in the ONNX graph's declared input order,
        # not the feeds dict's insertion order
        args = [torch.from_numpy(np.asarray(feeds[n]))
                for n in sd.onnx_inputs]
        with torch.no_grad():
            ref_t = torch_module(*args)
        if isinstance(ref_t, (tuple, list)):
            ref_vals = [np.asarray(r) for r in ref_t]
        else:
            ref_vals = [ref_t.numpy()]
        if len(ref_vals) != len(out_names):
            return ValidationReport(
                ok=False, atol=atol, rtol=rtol,
                error=f"oracle returned {len(ref_vals)} outputs, ONNX "
                      f"graph declares {len(out_names)} ({out_names})")
        ref = dict(zip(out_names, ref_vals))
        return _diff(ref, got, atol, rtol)
    except Exception as e:
        return ValidationReport(ok=False, atol=atol, rtol=rtol,
                                error=f"{type(e).__name__}: {e}")
