"""Let ``torch.onnx.export`` run without the ``onnx`` pip package.

The legacy TorchScript exporter imports ``onnx`` only for its
onnxscript-function scan (``load_model_from_string`` + a no-op walk for
plain models — it returns the original bytes when nothing custom is
found). This environment has no ``onnx`` package (our importer parses
files via the vendored minimal schema, see ``proto/onnx_min_pb2``);
installing this stub makes torch's exporter work end-to-end so users can
produce .onnx artifacts to feed ``OnnxFrameworkImporter``.

The stub carries a real ``ModuleSpec`` — a bare ModuleType has
``__spec__=None``, which makes ``importlib.util.find_spec("onnx")``
RAISE, crashing unrelated code that probes for onnx (torch._dynamo's
trace_rules does exactly that).
"""

from __future__ import annotations

import importlib.machinery
import sys
import types


def install_onnx_export_stub() -> None:
    """Idempotent: no-op when a real (or stub) ``onnx`` module exists."""
    if "onnx" in sys.modules:
        return
    from .proto import onnx_min_pb2 as _P

    def load_model_from_string(data):
        m = _P.ModelProto()
        m.ParseFromString(data)
        return m

    stub = types.ModuleType("onnx")
    stub.load_model_from_string = load_model_from_string
    stub.__spec__ = importlib.machinery.ModuleSpec("onnx", loader=None)
    sys.modules["onnx"] = stub
