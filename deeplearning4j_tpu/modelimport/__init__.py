"""Model import frontends (SURVEY.md §3.5): Keras-H5 → layer configs,
TF-GraphDef / ONNX → SameDiff graphs."""

from .keras import KerasModelImport  # noqa: F401
from .onnx import OnnxFrameworkImporter  # noqa: F401
from .tensorflow import TensorflowFrameworkImporter  # noqa: F401
