"""ONNX model import → SameDiff graph.

TPU-native equivalent of samediff-import-onnx (reference:
``nd4j/samediff-import/samediff-import-onnx``† per SURVEY.md §2.2/§3.5;
reference mount was empty, citation upstream-relative, unverified).

The ``onnx`` pip package is not in this environment, so parsing uses a
vendored minimal transcription of the public ONNX schema
(``proto/onnx_min.proto``, field numbers are the stable ONNX wire contract)
compiled with protoc — the import path therefore reads real ``.onnx`` files
with zero extra dependencies. Mapping mirrors the TF frontend: per-op-type
registry → catalog ops recorded on a SameDiff; initializers become
VARIABLEs (fine-tunable), graph inputs become placeholders; unsupported op
types raise with the name.
"""

from __future__ import annotations

from typing import Callable, Dict, List

import numpy as np

from ..autodiff.samediff import SameDiff, SDVariable

_DTYPES = {1: np.float32, 2: np.uint8, 3: np.int8, 5: np.int16, 6: np.int32,
           7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64}


def _tensor_to_np(t) -> np.ndarray:
    dims = tuple(t.dims)
    dt = _DTYPES.get(t.data_type)
    if dt is None:
        raise ValueError(f"unsupported ONNX tensor dtype {t.data_type}")
    if t.raw_data:
        a = np.frombuffer(t.raw_data, dtype=dt)
    elif t.float_data:
        a = np.asarray(list(t.float_data), dtype=dt)
    elif t.int64_data:
        a = np.asarray(list(t.int64_data), dtype=dt)
    elif t.int32_data:
        if t.data_type == 10:  # fp16 payloads are uint16 BIT PATTERNS in
            # int32_data (ONNX spec) — reinterpret, don't value-cast
            a = np.asarray(list(t.int32_data),
                           dtype=np.uint16).view(np.float16)
        else:
            a = np.asarray(list(t.int32_data), dtype=dt)
    elif t.double_data:
        a = np.asarray(list(t.double_data), dtype=dt)
    else:
        a = np.zeros(dims, dtype=dt)
    return a.reshape(dims)


def _attrs(node) -> Dict[str, object]:
    out = {}
    for a in node.attribute:
        if a.type == 1:      # FLOAT
            out[a.name] = float(a.f)
        elif a.type == 2:    # INT
            out[a.name] = int(a.i)
        elif a.type == 3:    # STRING
            out[a.name] = a.s.decode()
        elif a.type == 4:    # TENSOR
            out[a.name] = _tensor_to_np(a.t)
        elif a.type == 6:    # FLOATS
            out[a.name] = [float(v) for v in a.floats]
        elif a.type == 7:    # INTS
            out[a.name] = [int(v) for v in a.ints]
        else:
            out[a.name] = None
    return out


class _Ctx:
    def __init__(self, sd: SameDiff):
        self.sd = sd
        self.vars: Dict[str, SDVariable] = {}
        self.consts: Dict[str, np.ndarray] = {}
        self.opset = 1

    def get(self, name: str) -> SDVariable:
        if name not in self.vars:
            raise ValueError(f"reference to unknown tensor {name!r}")
        return self.vars[name]


#: op_type -> [(since_version, handler)] sorted newest-first. Mirrors the
#: reference import-registry's per-opset rule selection (samediff-import-api
#: ``OpMappingRegistry``†): the handler with the largest since_version <=
#: the model's declared ai.onnx opset wins.
_M: Dict[str, list] = {}


def onnx_op(*types, since: int = 1):
    def deco(fn):
        for t in types:
            _M.setdefault(t, []).append((since, fn))
            _M[t].sort(key=lambda p: -p[0])
        return fn
    return deco


def _select_handler(op_type: str, opset: int):
    for since, fn in _M[op_type]:
        if since <= opset:
            return fn
    raise ValueError(
        f"ONNX op {op_type!r}: no handler for opset {opset} (handlers start "
        f"at opset {_M[op_type][-1][0]})")


_UNARY = {"Relu": "act.relu", "Sigmoid": "act.sigmoid", "Tanh": "act.tanh",
          "Softplus": "act.softplus", "Softsign": "act.softsign",
          "Elu": "act.elu", "Selu": "act.selu", "Exp": "math.exp",
          "Log": "math.log", "Sqrt": "math.sqrt", "Abs": "math.abs",
          "Neg": "math.neg", "Floor": "math.floor", "Ceil": "math.ceil",
          "Round": "math.round", "Erf": "math.erf", "Sin": "math.sin",
          "Cos": "math.cos", "Identity": "act.identity",
          "Reciprocal": "math.reciprocal", "Sign": "math.sign"}
_BINARY = {"Add": "math.add", "Sub": "math.sub", "Mul": "math.mul",
           "Div": "math.div", "Pow": "math.pow", "Max": "math.maximum",
           "Min": "math.minimum", "Greater": "math.greater",
           "Less": "math.less", "Equal": "math.equal"}


@onnx_op("Gemm")
def _gemm(node, ctx, at):
    a, b = ctx.get(node.input[0]), ctx.get(node.input[1])
    alpha, beta = at.get("alpha", 1.0), at.get("beta", 1.0)
    y = ctx.sd.call("linalg.mmul", a, b,
                    attrs={"transpose_a": bool(at.get("transA", 0)),
                           "transpose_b": bool(at.get("transB", 0))})
    if alpha != 1.0:
        y = ctx.sd.call("math.mul", y, ctx.sd._lift(np.float32(alpha)))
    if len(node.input) > 2:
        c = ctx.get(node.input[2])
        if beta != 1.0:
            c = ctx.sd.call("math.mul", c, ctx.sd._lift(np.float32(beta)))
        y = ctx.sd.call("math.add", y, c, name=node.output[0])
    else:
        y = ctx.sd.call("act.identity", y, name=node.output[0])
    return y


@onnx_op("MatMul")
def _matmul(node, ctx, at):
    return ctx.sd.call("linalg.mmul", ctx.get(node.input[0]),
                       ctx.get(node.input[1]), name=node.output[0])


@onnx_op("Conv")
def _conv(node, ctx, at):
    # ONNX is NCHW with kernel OIHW == our storage layout directly
    kernel_shape = at.get("kernel_shape")
    strides = at.get("strides", [1, 1])
    dil = at.get("dilations", [1, 1])
    pads = at.get("pads", [0, 0, 0, 0])
    auto = at.get("auto_pad", "NOTSET")
    groups = int(at.get("group", 1))
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        mode, pad = "same", (0, 0)
    else:
        if len(pads) == 4 and (pads[0] != pads[2] or pads[1] != pads[3]):
            raise ValueError("asymmetric Conv pads not supported")
        mode, pad = "truncate", (int(pads[0]), int(pads[1]))
    args = [ctx.get(node.input[0]), ctx.get(node.input[1])]
    if len(node.input) > 2:
        args.append(ctx.get(node.input[2]))
    # ONNX grouped weight layout [M, C/g, kH, kW] == our conv2d contract
    # (depthwise/MobileNet and ResNeXt exports)
    return ctx.sd.call("conv2d", *args, name=node.output[0],
                       attrs={"stride": tuple(int(s) for s in strides),
                              "padding": pad, "mode": mode,
                              "dilation": tuple(int(d) for d in dil),
                              "data_format": "NCHW", "groups": groups})


@onnx_op("MaxPool", "AveragePool")
def _pool(node, ctx, at):
    op = "maxpool2d" if node.op_type == "MaxPool" else "avgpool2d"
    pads = at.get("pads", [0, 0, 0, 0])
    auto = at.get("auto_pad", "NOTSET")
    if auto in ("SAME_UPPER", "SAME_LOWER"):
        mode, pad = "same", (0, 0)
    else:
        if len(pads) == 4 and (pads[0] != pads[2] or pads[1] != pads[3]):
            raise ValueError(f"asymmetric {node.op_type} pads {pads} not "
                             "supported (end-side padding would be dropped)")
        mode, pad = "truncate", (int(pads[0]), int(pads[1]))
    attrs = {"kernel": tuple(int(k) for k in at["kernel_shape"]),
             "stride": tuple(int(s) for s in at.get("strides", at["kernel_shape"])),
             "padding": pad, "mode": mode,
             "data_format": "NCHW"}
    if op == "avgpool2d":
        # ONNX default count_include_pad=0: padded cells are EXCLUDED from
        # the divisor, unlike DL4J truncate-mode avg pool.
        cip = bool(at.get("count_include_pad", 0))
        if cip and mode == "same":
            raise ValueError(
                "AveragePool auto_pad=SAME with count_include_pad=1 not "
                "supported (our same-mode divisor always excludes padding)")
        attrs["count_include_pad"] = cip
    return ctx.sd.call(op, ctx.get(node.input[0]), name=node.output[0],
                       attrs=attrs)


@onnx_op("LeakyRelu")
def _leaky_onnx(node, ctx, at):
    return ctx.sd.call("act.leakyrelu", ctx.get(node.input[0]),
                       name=node.output[0],
                       attrs={"alpha": float(at.get("alpha", 0.01))})


@onnx_op("PRelu")
def _prelu_onnx(node, ctx, at):
    # slope broadcasts per ONNX; a scalar/1-elem slope == leakyrelu,
    # a [C] slope multiplies the negative part elementwise
    x = ctx.get(node.input[0])
    slope = ctx.get(node.input[1])
    neg = ctx.sd.call("math.minimum", x, ctx.sd._lift(np.float32(0.0)))
    pos = ctx.sd.call("math.maximum", x, ctx.sd._lift(np.float32(0.0)))
    scaled = ctx.sd.call("math.mul", neg, slope)
    return ctx.sd.call("math.add", pos, scaled, name=node.output[0])


def _emit_clip(node, ctx, lo, hi):
    # Absent bounds mean "no bound" (not ±3.4e38, which would clip
    # legitimate float64 values).
    if lo is None and hi is None:
        return ctx.sd.call("act.identity", ctx.get(node.input[0]),
                           name=node.output[0])
    lo = -np.inf if lo is None else lo
    hi = np.inf if hi is None else hi
    return ctx.sd.call("math.clip", ctx.get(node.input[0]),
                       name=node.output[0],
                       attrs={"min_value": lo, "max_value": hi})


@onnx_op("Clip")  # opset 1-10: min/max as attributes
def _clip_onnx_attrs(node, ctx, at):
    if len(node.input) > 1:
        # converter bumped opset_import without rewriting the node (or
        # vice versa) — the bounds live in inputs; honor them
        return _clip_onnx_inputs(node, ctx, at)
    lo = float(at["min"]) if "min" in at else None
    hi = float(at["max"]) if "max" in at else None
    return _emit_clip(node, ctx, lo, hi)


@onnx_op("Clip", since=11)  # opset 11+: min/max as optional inputs
def _clip_onnx_inputs(node, ctx, at):
    # runtime (non-initializer) bounds are unsupported and must raise the
    # named error, not a bare KeyError
    def bound(idx, attr):
        if len(node.input) > idx and node.input[idx]:
            name = node.input[idx]
            if name not in ctx.consts:
                raise ValueError(
                    f"Clip with runtime (non-initializer) {attr} input "
                    f"{name!r} not supported")
            return float(np.asarray(ctx.consts[name]).reshape(()))
        # attribute-form bounds on an opset-11+ node: converter artifact,
        # the intent is unambiguous — honor rather than silently drop
        return float(at[attr]) if attr in at else None
    return _emit_clip(node, ctx, bound(1, "min"), bound(2, "max"))


@onnx_op("ConvTranspose")
def _conv_transpose(node, ctx, at):
    """torchvision FCN/DeepLab-style deconv. ONNX weight layout is IOHW
    ([Cin, Cout/g, kH, kW]) vs our deconv2d's OIHW — permuted in-graph so
    the weight stays a trainable VARIABLE."""
    if at.get("group", 1) != 1:
        raise ValueError("grouped ConvTranspose not supported")
    if any(int(v) for v in at.get("output_padding", [])):
        raise ValueError("ConvTranspose output_padding not supported")
    if at.get("output_shape"):
        # spec derives effective pads from output_shape; defaulting pads
        # to 0 would silently mis-size the deconv
        raise ValueError("ConvTranspose output_shape not supported "
                         "(re-export with explicit pads)")
    if at.get("auto_pad", "NOTSET") not in ("NOTSET", "VALID"):
        raise ValueError("ConvTranspose auto_pad SAME not supported")
    pads = at.get("pads", [0, 0, 0, 0])
    if len(pads) == 4 and (pads[0] != pads[2] or pads[1] != pads[3]):
        raise ValueError("asymmetric ConvTranspose pads not supported")
    w = ctx.sd.call("shape.permute", ctx.get(node.input[1]),
                    attrs={"axes": (1, 0, 2, 3)})
    args = [ctx.get(node.input[0]), w]
    if len(node.input) > 2:
        args.append(ctx.get(node.input[2]))
    return ctx.sd.call(
        "deconv2d", *args, name=node.output[0],
        attrs={"stride": tuple(int(s) for s in at.get("strides", [1, 1])),
               "padding": (int(pads[0]), int(pads[1])),
               "dilation": tuple(int(d) for d in at.get("dilations", [1, 1])),
               "mode": "truncate", "data_format": "NCHW"})


@onnx_op("Resize", since=11)
def _resize_onnx(node, ctx, at):
    """Resize for the cases real exports hit: torch Upsample(nearest) =
    asymmetric+floor with integer scales, and bilinear half_pixel
    (align_corners=False). jax.image.resize samples half-pixel centers;
    nearest with integer upscale is identical under both grids."""
    mode = at.get("mode", "nearest")
    ctm = at.get("coordinate_transformation_mode", "half_pixel")
    x = ctx.get(node.input[0])
    if mode == "nearest":
        if ctm not in ("asymmetric", "half_pixel"):
            raise ValueError(f"Resize nearest with {ctm!r} not supported")
        method, sized_op = "nearest", "image.resize_nearest"
    elif mode == "linear":
        if ctm not in ("half_pixel", "pytorch_half_pixel"):
            raise ValueError(f"Resize linear with {ctm!r} not supported "
                             "(align_corners differs from half-pixel)")
        method, sized_op = "bilinear", "image.resize_bilinear"
    else:
        raise ValueError(f"Resize mode {mode!r} not supported")
    # opset 11/13 layout: X, roi, scales, sizes (trailing inputs optional)
    if len(node.input) > 3 and node.input[3]:
        nm = node.input[3]
        if nm not in ctx.consts:
            raise ValueError(f"Resize with runtime sizes {nm!r} not supported")
        sz = [int(v) for v in np.asarray(ctx.consts[nm]).ravel()]
        attrs = {"size": (sz[2], sz[3]), "data_format": "NCHW",
                 # batch/channel sizes can't be checked at import (input
                 # shape unknown) — the op asserts them at trace time
                 "expect_leading": (sz[0], sz[1])}
        if mode == "nearest" and ctm == "asymmetric":
            # floor-grid == half-pixel-grid only for integer upscales;
            # shapes are unknown at import, so the op checks at trace time
            attrs["require_integer_upscale"] = True
        return ctx.sd.call(sized_op, x, name=node.output[0], attrs=attrs)
    if len(node.input) > 2 and node.input[2]:
        nm = node.input[2]
        if nm not in ctx.consts:
            raise ValueError(
                f"Resize with runtime scales {nm!r} not supported")
        sc = [float(v) for v in np.asarray(ctx.consts[nm]).ravel()]
        if len(sc) == 4:
            if sc[0] != 1.0 or sc[1] != 1.0:
                raise ValueError("Resize scaling batch/channel dims "
                                 "not supported")
            if mode == "nearest" and ctm == "asymmetric" and (
                    sc[2] != int(sc[2]) or sc[3] != int(sc[3])):
                raise ValueError(
                    "Resize nearest asymmetric supports integer upscales "
                    "only (fractional grids differ from half-pixel "
                    "sampling)")
            return ctx.sd.call("image.resize_scale", x, name=node.output[0],
                               attrs={"scale": (sc[2], sc[3]),
                                      "method": method,
                                      "data_format": "NCHW"})
    raise ValueError("Resize needs constant scales or sizes")


@onnx_op("LayerNormalization", since=17)
def _layer_norm_onnx(node, ctx, at):
    """Opset-17 transformer exports. Single-output form (the training
    mean/invstd outputs are not produced)."""
    axis = int(at.get("axis", -1))
    if axis not in (-1,):
        raise ValueError("LayerNormalization axis != -1 not supported")
    if len(node.output) > 1 and any(node.output[1:]):
        raise ValueError(
            "LayerNormalization mean/invstd outputs not supported")
    x = ctx.get(node.input[0])
    scale = ctx.get(node.input[1])
    if len(node.input) > 2 and node.input[2]:
        bias = ctx.get(node.input[2])
    else:
        bias = ctx.sd._lift(np.float32(0.0))
    return ctx.sd.call("layer_norm", x, scale, bias, name=node.output[0],
                       attrs={"eps": float(at.get("epsilon", 1e-5)),
                              "axis": -1})


@onnx_op("InstanceNormalization")
def _instance_norm_onnx(node, ctx, at):
    """Per-instance per-channel normalization (NCHW); scale/bias stay
    trainable VARIABLEs for fine-tuning."""
    return ctx.sd.call("instance_norm", ctx.get(node.input[0]),
                       ctx.get(node.input[1]), ctx.get(node.input[2]),
                       name=node.output[0],
                       attrs={"eps": float(at.get("epsilon", 1e-5))})


@onnx_op("Gelu", since=20)
def _gelu_onnx(node, ctx, at):
    approx = at.get("approximate", "none")
    return ctx.sd.call("act.gelu", ctx.get(node.input[0]),
                       name=node.output[0],
                       attrs={"approximate": approx == "tanh"})


@onnx_op("GlobalMaxPool")
def _gmp(node, ctx, at):
    return ctx.sd.call("reduce.max", ctx.get(node.input[0]),
                       name=node.output[0],
                       attrs={"axis": (2, 3), "keepdims": True})


@onnx_op("GlobalAveragePool")
def _gap(node, ctx, at):
    return ctx.sd.call("reduce.mean", ctx.get(node.input[0]),
                       name=node.output[0],
                       attrs={"axis": (2, 3), "keepdims": True})


@onnx_op("BatchNormalization")
def _bn(node, ctx, at):
    return ctx.sd.call("batch_norm", ctx.get(node.input[0]),
                       ctx.get(node.input[1]), ctx.get(node.input[2]),
                       ctx.get(node.input[3]), ctx.get(node.input[4]),
                       name=node.output[0],
                       attrs={"eps": float(at.get("epsilon", 1e-5)),
                              "axis": 1})


@onnx_op("Reshape")
def _reshape(node, ctx, at):
    shape = ctx.consts.get(node.input[1]) if len(node.input) > 1 else \
        np.asarray(at.get("shape", []))
    if shape is None:
        raise ValueError("Reshape with dynamic shape input not supported")
    # ONNX semantics: 0 copies the input dim (allowzero=0 default) —
    # resolved at trace time by the catalog's reshape_onnx lowering
    return ctx.sd.call("shape.reshape_onnx", ctx.get(node.input[0]),
                       name=node.output[0],
                       attrs={"shape": [int(s) for s in
                                        np.asarray(shape).tolist()],
                              "allowzero": int(at.get("allowzero", 0))})


@onnx_op("Flatten")
def _flatten(node, ctx, at):
    axis = at.get("axis", 1)
    if axis != 1:
        raise ValueError("Flatten axis != 1 not supported")
    return ctx.sd.call("shape.flatten2d", ctx.get(node.input[0]),
                       name=node.output[0])


@onnx_op("Softmax", "LogSoftmax")
def _softmax_legacy(node, ctx, at):
    """Opset 1-12 semantics: flatten to 2D at ``axis`` (default 1), softmax
    over the SECOND dim, reshape back — implemented by a trace-time op
    (intermediate shapes are unknown at import, so an import-time rank
    guard cannot work)."""
    return ctx.sd.call("act.softmax_onnx_legacy", ctx.get(node.input[0]),
                       name=node.output[0],
                       attrs={"axis": int(at.get("axis", 1)),
                              "log": node.op_type == "LogSoftmax"})


@onnx_op("Softmax", "LogSoftmax", since=13)
def _softmax13(node, ctx, at):
    op = "act.softmax" if node.op_type == "Softmax" else "act.logsoftmax"
    return ctx.sd.call(op, ctx.get(node.input[0]), name=node.output[0],
                       attrs={"axis": int(at.get("axis", -1))})


@onnx_op("Concat")
def _concat(node, ctx, at):
    if all(i in ctx.consts for i in node.input):
        # shape-arithmetic fold: torch RNN exports build initial-state
        # shapes via Shape->Gather->Unsqueeze->Concat->Expand; Expand
        # needs the concatenated shape as a known const
        ctx.consts[node.output[0]] = np.concatenate(
            [np.asarray(ctx.consts[i]) for i in node.input],
            axis=int(at["axis"]))
    return ctx.sd.call("shape.concat_v", *[ctx.get(i) for i in node.input],
                       name=node.output[0], attrs={"axis": int(at["axis"])})


@onnx_op("Transpose")
def _transpose(node, ctx, at):
    perm = [int(p) for p in at.get("perm", [])]
    v = ctx.sd.call("shape.transpose", ctx.get(node.input[0]),
                    name=node.output[0], attrs={"axes": perm})
    # propagate the static shape: torch RNN exports take Shape() of a
    # transposed input to build initial states — without this the
    # downstream Shape->...->Expand chain cannot const-fold
    src = ctx.get(node.input[0])
    if src.shape is not None and all(s is not None for s in src.shape):
        order = perm or list(range(len(src.shape)))[::-1]
        v.shape = tuple(src.shape[p] for p in order)
    return v


@onnx_op("Unsqueeze")
def _unsqueeze(node, ctx, at):
    axes = at.get("axes")
    if axes is None and len(node.input) > 1:
        axes = ctx.consts[node.input[1]].tolist()
    if node.input[0] in ctx.consts:  # shape-arithmetic fold (see Concat)
        v = np.asarray(ctx.consts[node.input[0]])
        # ONNX Unsqueeze axes refer to the OUTPUT rank; normalize negatives
        # against it before sorting — raw mixed axes like [-3, 1] would
        # sort as [-3, 1] and misplace dims or raise AxisError (ADVICE r5)
        out_rank = v.ndim + len(axes)
        for a in sorted(int(a) % out_rank for a in axes):
            v = np.expand_dims(v, a)
        ctx.consts[node.output[0]] = v
    return ctx.sd.call("shape.expand_dims", ctx.get(node.input[0]),
                       name=node.output[0],
                       attrs={"axis": tuple(int(a) for a in axes)})


@onnx_op("Squeeze")
def _squeeze(node, ctx, at):
    axes = at.get("axes")
    if axes is None and len(node.input) > 1:
        axes = ctx.consts[node.input[1]].tolist()
    attrs = {"axis": tuple(int(a) for a in axes)} if axes else {}
    return ctx.sd.call("shape.squeeze", ctx.get(node.input[0]),
                       name=node.output[0], attrs=attrs)


@onnx_op("Shape")
def _shape(node, ctx, at):
    """Static fold when the producer's shape is known (placeholders with
    full shapes, initializers); else a runtime shape_of (const-consuming
    downstream nodes will raise the usual named error)."""
    name = node.input[0]
    var = ctx.get(name)
    if name in ctx.consts:
        val = np.asarray(np.asarray(ctx.consts[name]).shape, np.int64)
        ctx.consts[node.output[0]] = val
        ctx.vars[node.output[0]] = ctx.sd.constant(node.output[0], val)
        return ctx.vars[node.output[0]]
    if var.shape is not None and all(s is not None for s in var.shape):
        val = np.asarray(var.shape, np.int64)
        ctx.consts[node.output[0]] = val
        ctx.vars[node.output[0]] = ctx.sd.constant(node.output[0], val)
        return ctx.vars[node.output[0]]
    return ctx.sd.call("shape.shape_of", var, name=node.output[0])


@onnx_op("Gather")
def _gather(node, ctx, at):
    axis = int(at.get("axis", 0))
    if node.input[0] in ctx.consts and node.input[1] in ctx.consts:
        ctx.consts[node.output[0]] = np.take(
            np.asarray(ctx.consts[node.input[0]]),
            np.asarray(ctx.consts[node.input[1]]).astype(np.int64),
            axis=axis)
    return ctx.sd.call("shape.gather", ctx.get(node.input[0]),
                       ctx.get(node.input[1]), name=node.output[0],
                       attrs={"axis": axis})


@onnx_op("Cast")
def _cast(node, ctx, at):
    np_dt = _DTYPES.get(int(at.get("to", 1)))
    if np_dt is None:
        raise ValueError(f"Cast to unsupported ONNX dtype {at.get('to')}")
    if node.input[0] in ctx.consts:
        ctx.consts[node.output[0]] = np.asarray(
            ctx.consts[node.input[0]]).astype(np_dt)
    return ctx.sd.call("math.cast", ctx.get(node.input[0]),
                       name=node.output[0],
                       attrs={"dtype": np.dtype(np_dt).name})


@onnx_op("Slice")
def _slice(node, ctx, at):
    """Opset-10+ form (starts/ends/axes/steps as const inputs) and the
    opset-1 attribute form."""
    if len(node.input) > 1:
        starts = np.asarray(ctx.consts[node.input[1]]).tolist()
        ends = np.asarray(ctx.consts[node.input[2]]).tolist()
        axes = np.asarray(ctx.consts[node.input[3]]).tolist() \
            if len(node.input) > 3 and node.input[3] else \
            list(range(len(starts)))
        steps = np.asarray(ctx.consts[node.input[4]]).tolist() \
            if len(node.input) > 4 and node.input[4] else [1] * len(starts)
    else:
        starts = at["starts"]
        ends = at["ends"]
        axes = at.get("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    if any(int(a) < 0 for a in axes):
        # negative axes are spec-legal; normalize against the input rank
        var = ctx.get(node.input[0])
        if node.input[0] in ctx.consts:
            rank = np.asarray(ctx.consts[node.input[0]]).ndim
        elif var.shape is not None:
            rank = len(var.shape)
        else:
            raise ValueError(
                "Slice with negative axes needs a known input rank")
        axes = [int(a) % rank for a in axes]
    by_axis = {int(a): (int(s), int(e), int(st))
               for a, s, e, st in zip(axes, starts, ends, steps)}
    max_axis = max(by_axis) if by_axis else -1
    INT_MAX = 2 ** 31 - 1
    spec = []
    for ax in range(max_axis + 1):
        if ax in by_axis:
            s, e, st = by_axis[ax]
            # ONNX clamps out-of-range ends; huge sentinels -> None
            spec.append(["slice", None if abs(s) >= INT_MAX else s,
                         None if abs(e) >= INT_MAX else e, st])
        else:
            spec.append(["slice", None, None, 1])
    if node.input[0] in ctx.consts:
        idx = tuple(slice(e[1], e[2], e[3]) for e in spec)
        ctx.consts[node.output[0]] = np.asarray(
            ctx.consts[node.input[0]])[idx]
    return ctx.sd.call("shape.strided_slice_v2", ctx.get(node.input[0]),
                       name=node.output[0], attrs={"spec": spec})


@onnx_op("Expand")
def _expand(node, ctx, at):
    shape = [int(s) for s in
             np.asarray(ctx.consts[node.input[1]]).tolist()]
    if node.input[0] in ctx.consts:
        # fold: torch RNN exports Expand a zero scalar into the initial
        # state; the LSTM/GRU handler's zero-state check reads consts
        ctx.consts[node.output[0]] = np.broadcast_to(
            np.asarray(ctx.consts[node.input[0]]), shape)
    return ctx.sd.call("shape.broadcast_to", ctx.get(node.input[0]),
                       name=node.output[0], attrs={"shape": shape})


@onnx_op("Where")
def _where(node, ctx, at):
    return ctx.sd.call("math.where", ctx.get(node.input[0]),
                       ctx.get(node.input[1]), ctx.get(node.input[2]),
                       name=node.output[0])


@onnx_op("ConstantOfShape")
def _const_of_shape(node, ctx, at):
    shape = [int(s) for s in
             np.asarray(ctx.consts[node.input[0]]).tolist()]
    value = at.get("value")
    fill = np.asarray(value).reshape(-1)[0] if value is not None else \
        np.float32(0.0)
    arr = np.full(shape, fill)
    ctx.consts[node.output[0]] = arr
    ctx.vars[node.output[0]] = ctx.sd.constant(node.output[0], arr)
    return ctx.vars[node.output[0]]


@onnx_op("Split")
def _split_onnx(node, ctx, at):
    axis = int(at.get("axis", 0))
    sizes = at.get("split")
    if sizes is None and len(node.input) > 1 and node.input[1]:
        sizes = np.asarray(ctx.consts[node.input[1]]).tolist()
    x = ctx.get(node.input[0])
    n_out = len(node.output)
    if sizes:
        cuts = np.cumsum([int(s) for s in sizes])[:-1].tolist()
        attrs = {"indices_or_sections": [int(c) for c in cuts],
                 "axis": axis}
    else:
        attrs = {"indices_or_sections": n_out, "axis": axis}
    vs = ctx.sd.call_multi("shape.split", x, n_outputs=n_out,
                           name=list(node.output), attrs=attrs)
    for out_name, v in zip(node.output, vs):
        ctx.vars[out_name] = v
    return vs[0]


@onnx_op("Tile")
def _tile_onnx(node, ctx, at):
    reps = [int(r) for r in np.asarray(ctx.consts[node.input[1]]).tolist()]
    return ctx.sd.call("shape.tile", ctx.get(node.input[0]),
                       name=node.output[0], attrs={"reps": reps})


@onnx_op("Pad")
def _pad_onnx(node, ctx, at):
    mode = at.get("mode", "constant")
    if mode not in ("constant", b"constant"):
        raise ValueError(f"Pad mode {mode!r} not supported")
    if len(node.input) > 1:
        pads = np.asarray(ctx.consts[node.input[1]]).tolist()
        value = float(np.asarray(
            ctx.consts[node.input[2]]).reshape(-1)[0]) \
            if len(node.input) > 2 and node.input[2] else 0.0
    else:
        pads = at["pads"]
        value = float(at.get("value", 0.0))
    n = len(pads) // 2
    widths = [(int(pads[i]), int(pads[i + n])) for i in range(n)]
    return ctx.sd.call("shape.pad", ctx.get(node.input[0]),
                       name=node.output[0],
                       attrs={"pad_width": widths,
                              "constant_values": value})


def _rnn_optional(ctx, node, idx):
    """Optional ONNX input: returns the tensor name or None for ''/absent."""
    if len(node.input) > idx and node.input[idx]:
        return node.input[idx]
    return None


def _rnn_check_initial(ctx, name, what):
    if name is None:
        return
    if name in ctx.consts and not np.any(ctx.consts[name]):
        return  # zero initial state == our default
    raise ValueError(f"{what} with non-zero initial state not supported")


@onnx_op("LSTM", "GRU")
def _rnn(node, ctx, at):
    """ONNX LSTM/GRU -> onnx_lstm/onnx_gru catalog ops (multi-output:
    Y/Y_h[/Y_c]). Default activations, layout=0, zero initial state,
    no sequence_lens (matches torch.onnx.export of nn.LSTM/nn.GRU)."""
    kind = node.op_type
    if at.get("layout"):
        raise ValueError(f"{kind} layout=1 not supported (re-export with "
                         "the default seq-major layout)")
    if at.get("clip"):
        raise ValueError(f"{kind} clip not supported")
    if at.get("activations"):
        raise ValueError(f"{kind} custom activations not supported")
    hidden = int(at["hidden_size"])
    direction = at.get("direction", "forward")
    n_dirs = 2 if direction == "bidirectional" else 1
    x = ctx.get(node.input[0])
    w = ctx.get(node.input[1])
    r = ctx.get(node.input[2])
    b_name = _rnn_optional(ctx, node, 3)
    if b_name is None:
        width = 8 * hidden if kind == "LSTM" else 6 * hidden
        b = ctx.sd._lift(np.zeros((n_dirs, width), np.float32))
    else:
        b = ctx.get(b_name)
    seq_lens = _rnn_optional(ctx, node, 4)
    if seq_lens is not None:
        raise ValueError(f"{kind} sequence_lens not supported "
                         "(pad to a fixed length)")
    _rnn_check_initial(ctx, _rnn_optional(ctx, node, 5), f"{kind} initial_h")
    if kind == "LSTM":
        _rnn_check_initial(ctx, _rnn_optional(ctx, node, 6),
                           "LSTM initial_c")
        names = [node.output[k] if len(node.output) > k and node.output[k]
                 else None for k in range(3)]
        vs = ctx.sd.call_multi(
            "onnx_lstm", x, w, r, b, n_outputs=3, name=names,
            attrs={"direction": direction, "hidden_size": hidden})
    else:
        names = [node.output[k] if len(node.output) > k and node.output[k]
                 else None for k in range(2)]
        vs = ctx.sd.call_multi(
            "onnx_gru", x, w, r, b, n_outputs=2, name=names,
            attrs={"direction": direction, "hidden_size": hidden,
                   "linear_before_reset": int(
                       at.get("linear_before_reset", 0))})
    for out_name, v in zip(node.output, vs):
        if out_name:
            ctx.vars[out_name] = v
    return vs[0]


@onnx_op("ReduceMean", "ReduceSum", "ReduceMax", "ReduceMin")
def _reduce(node, ctx, at):
    op = {"ReduceMean": "reduce.mean", "ReduceSum": "reduce.sum",
          "ReduceMax": "reduce.max", "ReduceMin": "reduce.min"}[node.op_type]
    axes = at.get("axes")
    if axes is None and len(node.input) > 1:
        axes = ctx.consts[node.input[1]].tolist()
    return ctx.sd.call(op, ctx.get(node.input[0]), name=node.output[0],
                       attrs={"axis": tuple(int(a) for a in axes) if axes else None,
                              "keepdims": bool(at.get("keepdims", 1))})


class OnnxFrameworkImporter:
    """Reference-parity entry point (samediff-import-onnx†)."""

    @staticmethod
    def import_file(path: str) -> SameDiff:
        with open(path, "rb") as f:
            return OnnxFrameworkImporter.import_model_proto(f.read())

    @staticmethod
    def import_model_proto(data) -> SameDiff:
        from .proto import onnx_min_pb2 as P

        if isinstance(data, (bytes, bytearray)):
            model = P.ModelProto()
            model.ParseFromString(bytes(data))
        else:
            model = data
        g = model.graph
        sd = SameDiff()
        ctx = _Ctx(sd)
        # declared ai.onnx opset drives per-handler since_version selection;
        # a model with NO declaration (hand-built fixtures — real exporters
        # always declare) is treated as a modern opset-13 graph
        opset = 0
        for oi in model.opset_import:
            if oi.domain in ("", "ai.onnx"):
                opset = max(opset, int(oi.version))
        ctx.opset = opset = opset or 13
        for init in g.initializer:
            value = _tensor_to_np(init)
            ctx.consts[init.name] = value
            ctx.vars[init.name] = sd.var(init.name, value)
        for vi in g.input:
            if vi.name in ctx.vars:
                continue  # initializer doubling as input (pre-IR4 style)
            shape = None
            tt = vi.type.tensor_type
            if tt.shape.dim:
                shape = tuple(d.dim_value if d.dim_value else None
                              for d in tt.shape.dim)
            ctx.vars[vi.name] = sd.placeholder(vi.name, shape)
        for node in g.node:
            at = _attrs(node)
            if node.op_type == "Constant":
                value = at.get("value")
                ctx.consts[node.output[0]] = np.asarray(value)
                ctx.vars[node.output[0]] = sd.constant(node.output[0], value)
            elif node.op_type in _UNARY:
                ctx.vars[node.output[0]] = sd.call(
                    _UNARY[node.op_type], ctx.get(node.input[0]),
                    name=node.output[0])
            elif node.op_type in _BINARY:
                ctx.vars[node.output[0]] = sd.call(
                    _BINARY[node.op_type], ctx.get(node.input[0]),
                    ctx.get(node.input[1]), name=node.output[0])
            elif node.op_type in _M:
                fn = _select_handler(node.op_type, opset)
                ctx.vars[node.output[0]] = fn(node, ctx, at)
            else:
                raise ValueError(
                    f"unsupported ONNX op {node.op_type!r} (node "
                    f"{node.name!r}) — extend modelimport/onnx.py")
        sd.onnx_outputs = [vi.name for vi in g.output]  # type: ignore
        # declared graph-input order (initializers excluded): validation
        # runners feed positional oracles (torch forward) in this order
        sd.onnx_inputs = [vi.name for vi in g.input  # type: ignore
                          if vi.name not in ctx.consts]
        return sd
