"""SameDiff-equivalent define-then-run graph autodiff layer.

TPU-native equivalent of nd4j's SameDiff (reference:
``nd4j-api .../autodiff/samediff/SameDiff.java``,
``.../autodiff/samediff/internal/{InferenceSession,TrainingSession}.java``,
``.../autodiff/samediff/serde/FlatBuffersMapper.java``† per SURVEY.md
§2.2/§3.3; reference mount was empty, citations upstream-relative,
unverified).

Architecture (the §3.3 "TPU translation"): the reference's dependency-tracked
op-at-a-time interpreter (ExecStep queue, ArrayCacheMemoryMgr) is replaced by
trace-once/compile-once: the recorded op list IS the program; executing it
under ``jax.jit`` hands XLA the whole graph for fusion, and the reference's
per-op ``doDiff`` gradient graph construction is ``jax.grad`` of the traced
function — no hand-written backward per op.

Variable kinds mirror SDVariable.VariableType: VARIABLE (trainable),
PLACEHOLDER (fed per call), CONSTANT (baked), ARRAY (op output).

Serialization: JSON graph-def (ops reference catalog names from
``deeplearning4j_tpu.ops``) + npz of VARIABLE/CONSTANT values, zipped — the
moral equivalent of the FlatBuffers ``.fb`` (format is ours; the contract —
graph+weights reload in a fresh process with identical outputs — is the
reference's). This layer is the compile target for the import frontends
(SURVEY.md §3.5).
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops as _catalog

VARIABLE = "VARIABLE"
PLACEHOLDER = "PLACEHOLDER"
CONSTANT = "CONSTANT"
ARRAY = "ARRAY"


class SDVariable:
    """Symbolic handle into a SameDiff graph (nd4j ``SDVariable``†)."""

    def __init__(self, sd: "SameDiff", name: str, kind: str,
                 shape: Optional[Tuple[int, ...]] = None):
        self.sd = sd
        self.name = name
        self.kind = kind
        self.shape = tuple(shape) if shape is not None else None

    # ---- operator sugar (each records a graph op) --------------------------
    def _bin(self, op, other, swap=False):
        other = self.sd._lift(other)
        a, b = (other, self) if swap else (self, other)
        return self.sd.call(op, a, b)

    def __add__(self, o):
        return self._bin("math.add", o)

    def __radd__(self, o):
        return self._bin("math.add", o, swap=True)

    def __sub__(self, o):
        return self._bin("math.sub", o)

    def __rsub__(self, o):
        return self._bin("math.sub", o, swap=True)

    def __mul__(self, o):
        return self._bin("math.mul", o)

    def __rmul__(self, o):
        return self._bin("math.mul", o, swap=True)

    def __truediv__(self, o):
        return self._bin("math.div", o)

    def __rtruediv__(self, o):
        return self._bin("math.div", o, swap=True)

    def __pow__(self, o):
        return self._bin("math.pow", o)

    def __neg__(self):
        return self.sd.call("math.neg", self)

    def __matmul__(self, o):
        return self._bin("linalg.mmul", o)

    # ---- common graph methods (SDVariable sugar) ---------------------------
    def mmul(self, other, **kw):
        return self.sd.call("linalg.mmul", self, self.sd._lift(other), **kw)

    def add(self, other):
        return self.__add__(other)

    def sub(self, other):
        return self.__sub__(other)

    def mul(self, other):
        return self.__mul__(other)

    def div(self, other):
        return self.__truediv__(other)

    def reshape(self, *shape):
        return self.sd.call("shape.reshape", self, attrs={"shape": list(shape)})

    def transpose(self, *axes):
        return self.sd.call("shape.transpose", self,
                            attrs={"axes": list(axes)} if axes else {})

    def sum(self, axis=None, keepdims=False):
        return self.sd.call("reduce.sum", self,
                            attrs={"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return self.sd.call("reduce.mean", self,
                            attrs={"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return self.sd.call("reduce.max", self,
                            attrs={"axis": axis, "keepdims": keepdims})

    def std(self, axis=None, keepdims=False):
        return self.sd.call("reduce.std", self,
                            attrs={"axis": axis, "keepdims": keepdims})

    def eval(self, feeds: Optional[Dict[str, Any]] = None):
        """Evaluate just this variable (session compile + execute)."""
        return self.sd.output(feeds or {}, [self.name])[self.name]


class _OpRecord:
    __slots__ = ("op", "inputs", "output", "attrs")

    def __init__(self, op: str, inputs: List[str], output: str,
                 attrs: Dict[str, Any]):
        self.op = op
        self.inputs = inputs
        self.output = output
        self.attrs = attrs


class SameDiff:
    """The graph container + session (nd4j ``SameDiff`` / sessions†)."""

    def __init__(self):
        self._vars: Dict[str, SDVariable] = {}
        self._values: Dict[str, jnp.ndarray] = {}   # VARIABLE + CONSTANT
        self._ops: List[_OpRecord] = []             # creation order == topo
        self._counter = 0
        self._fn_cache: Dict[Tuple, Callable] = {}
        self.updater = None
        self.loss_name: Optional[str] = None

    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    # ------------------------------------------------------------ variables
    def _fresh(self, base: str) -> str:
        self._counter += 1
        name = f"{base}_{self._counter}"
        while name in self._vars:
            self._counter += 1
            name = f"{base}_{self._counter}"
        return name

    def _register(self, name, kind, shape=None) -> SDVariable:
        if name in self._vars:
            raise ValueError(f"variable {name!r} already exists")
        v = SDVariable(self, name, kind, shape)
        self._vars[name] = v
        return v

    def placeholder(self, name: str, shape=None, dtype=jnp.float32) -> SDVariable:
        return self._register(name, PLACEHOLDER, shape)

    def var(self, name: str, value) -> SDVariable:
        """Trainable VARIABLE with an initial value."""
        arr = jnp.asarray(value)
        v = self._register(name, VARIABLE, arr.shape)
        self._values[name] = arr
        return v

    def constant(self, name: str, value) -> SDVariable:
        arr = jnp.asarray(value)
        v = self._register(name, CONSTANT, arr.shape)
        self._values[name] = arr
        return v

    def _lift(self, value) -> SDVariable:
        """Lift a python/numpy scalar or array into a CONSTANT."""
        if isinstance(value, SDVariable):
            return value
        return self.constant(self._fresh("const"), value)

    # ----------------------------------------------------------------- ops
    def call(self, op_name: str, *inputs: SDVariable, name: Optional[str] = None,
             attrs: Optional[Dict[str, Any]] = None, **kw_attrs) -> SDVariable:
        """Record a catalog op application; returns the output SDVariable."""
        if _catalog.lookup(op_name) is None:
            raise ValueError(f"unknown op {op_name!r} (not in the catalog)")
        attrs = dict(attrs or {})
        attrs.update(kw_attrs)
        for v in inputs:
            if v.name not in self._vars:
                raise ValueError(f"input {v.name!r} is not in this graph")
        out = name or self._fresh(op_name.split(".")[-1])
        v = self._register(out, ARRAY)
        self._ops.append(_OpRecord(op_name, [i.name for i in inputs], out, attrs))
        self._fn_cache.clear()
        return v

    # nd4j namespace sugar (sd.nn()/sd.math() style collapsed to methods)
    def relu(self, x, name=None):
        return self.call("act.relu", x, name=name)

    def sigmoid(self, x, name=None):
        return self.call("act.sigmoid", x, name=name)

    def tanh(self, x, name=None):
        return self.call("act.tanh", x, name=name)

    def softmax(self, x, name=None):
        return self.call("act.softmax", x, name=name)

    def mmul(self, a, b, name=None):
        return self.call("linalg.mmul", a, b, name=name)

    # ------------------------------------------------------------ execution
    def _compute(self, values: Dict[str, jnp.ndarray],
                 feeds: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        """Pure topo-order evaluation of the recorded program."""
        env: Dict[str, jnp.ndarray] = {}
        env.update(values)
        env.update(feeds)
        for rec in self._ops:
            fn = _catalog.get(rec.op).fn
            args = [env[i] for i in rec.inputs]
            attrs = {k: _attr_in(v) for k, v in rec.attrs.items()}
            env[rec.output] = fn(*args, **attrs)
        return env

    def _session(self, targets: Tuple[str, ...]) -> Callable:
        """Compile-once-execute-many (InferenceSession equivalent): one jit
        program per requested target set."""
        key = targets
        if key not in self._fn_cache:
            def fn(values, feeds):
                env = self._compute(values, feeds)
                return {t: env[t] for t in targets}
            self._fn_cache[key] = jax.jit(fn)
        return self._fn_cache[key]

    def output(self, feeds: Dict[str, Any], targets: Sequence[str]) -> Dict[str, np.ndarray]:
        """Evaluate target variables under the given placeholder feeds."""
        missing = [n for n, v in self._vars.items()
                   if v.kind == PLACEHOLDER and n not in feeds]
        needed = self._needed_placeholders(targets)
        missing = [m for m in missing if m in needed]
        if missing:
            raise ValueError(f"missing placeholder feeds: {missing}")
        fn = self._session(tuple(targets))
        out = fn(self._values, {k: jnp.asarray(v) for k, v in feeds.items()
                                if k in needed})
        return {k: np.asarray(v) for k, v in out.items()}

    def _needed_placeholders(self, targets) -> set:
        """Backward reachability: which placeholders feed the targets."""
        producers = {r.output: r for r in self._ops}
        need, stack = set(), list(targets)
        seen = set()
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            v = self._vars.get(n)
            if v is not None and v.kind == PLACEHOLDER:
                need.add(n)
            rec = producers.get(n)
            if rec:
                stack.extend(rec.inputs)
        return need

    # ------------------------------------------------------------- training
    def set_loss(self, loss: SDVariable) -> "SameDiff":
        self.loss_name = loss.name
        return self

    def set_updater(self, updater) -> "SameDiff":
        self.updater = updater
        return self

    def grad(self, feeds: Dict[str, Any],
             wrt: Optional[Sequence[str]] = None) -> Dict[str, np.ndarray]:
        """Gradients of the loss w.r.t. VARIABLEs (createGradFunction +
        execBackwards equivalent — here just jax.grad of the traced program)."""
        if self.loss_name is None:
            raise ValueError("set_loss(...) first")
        wrt = list(wrt or [n for n, v in self._vars.items()
                           if v.kind == VARIABLE])
        loss_name = self.loss_name

        def loss_fn(train_vals, other_vals, feeds):
            env = self._compute({**other_vals, **train_vals}, feeds)
            return env[loss_name]

        train = {n: self._values[n] for n in wrt}
        other = {n: v for n, v in self._values.items() if n not in train}
        g = jax.jit(jax.grad(loss_fn))(
            train, other, {k: jnp.asarray(v) for k, v in feeds.items()})
        return {k: np.asarray(v) for k, v in g.items()}

    def fit(self, feeds_iter, epochs: int = 1) -> List[float]:
        """Minibatch training. feeds_iter: iterable of feed dicts (or a single
        dict). Returns per-step losses (History equivalent)."""
        if self.loss_name is None or self.updater is None:
            raise ValueError("set_loss(...) and set_updater(...) first")
        feeds_list = [feeds_iter] if isinstance(feeds_iter, dict) else list(feeds_iter)
        loss_name = self.loss_name
        train_names = [n for n, v in self._vars.items() if v.kind == VARIABLE]
        updater = self.updater

        def step(train_vals, opt_state, other_vals, step_i, feeds):
            def loss_fn(tv):
                env = self._compute({**other_vals, **tv}, feeds)
                return env[loss_name]
            loss, grads = jax.value_and_grad(loss_fn)(train_vals)
            delta, new_opt = updater.apply(grads, opt_state, train_vals, step_i)
            new_vals = jax.tree.map(lambda p, d: p - d, train_vals, delta)
            return new_vals, new_opt, loss

        # cache ONE compiled step across fit() calls — re-jitting a large
        # imported graph per call costs seconds (found fine-tuning
        # BERT-base). Keyed on the updater's CONFIG (hyperparameters are
        # baked into the trace, so mutating them must retrace), and only the
        # latest step is kept (old compiled executables for big graphs are
        # device memory worth releasing).
        import json as _json
        spec = ("fit", loss_name,
                _json.dumps(updater.to_dict(), sort_keys=True, default=str),
                tuple(train_names))
        cached = self._fn_cache.get("__fit_step__")
        if cached is not None and cached[0] == spec:
            step = cached[1]
        else:
            step = jax.jit(step, donate_argnums=(0, 1))
            self._fn_cache["__fit_step__"] = (spec, step)
        train_vals = {n: self._values[n] for n in train_names}
        other_vals = {n: v for n, v in self._values.items()
                      if n not in train_names}
        opt_state = updater.init_state(train_vals)
        losses = []
        i = 0
        for _ in range(epochs):
            for feeds in feeds_list:
                feeds = {k: jnp.asarray(v) for k, v in feeds.items()}
                train_vals, opt_state, loss = step(
                    train_vals, opt_state, other_vals,
                    jnp.asarray(i, jnp.int32), feeds)
                losses.append(float(loss))
                i += 1
        self._values.update(train_vals)
        # no cache clear: sessions/steps take values as ARGUMENTS, so the
        # updated weights flow through; only graph mutation (call()) clears
        return losses

    # ------------------------------------------------------------ accessors
    def get_value(self, name: str) -> np.ndarray:
        return np.asarray(self._values[name])

    def set_value(self, name: str, value) -> None:
        if self._vars[name].kind not in (VARIABLE, CONSTANT):
            raise ValueError(f"{name} has no stored value")
        self._values[name] = jnp.asarray(value)
        self._fn_cache.clear()

    def variables(self) -> List[str]:
        return [n for n, v in self._vars.items() if v.kind == VARIABLE]

    # ------------------------------------------------------------ serde
    def to_json(self) -> str:
        return json.dumps({
            "format_version": 1,
            "model_class": "SameDiff",
            "variables": [{"name": v.name, "kind": v.kind,
                           "shape": list(v.shape) if v.shape else None}
                          for v in self._vars.values()],
            "ops": [{"op": r.op, "inputs": r.inputs, "output": r.output,
                     "attrs": {k: _attr_out(v) for k, v in r.attrs.items()}}
                    for r in self._ops],
            "loss": self.loss_name,
            "updater": self.updater.to_dict() if self.updater else None,
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "SameDiff":
        from ..nn import updaters as _upd
        d = json.loads(s)
        sd = SameDiff()
        for vd in d["variables"]:
            if vd["name"] in sd._vars:
                continue
            sd._register(vd["name"], vd["kind"],
                         tuple(vd["shape"]) if vd.get("shape") else None)
        for od in d["ops"]:
            sd._ops.append(_OpRecord(od["op"], list(od["inputs"]),
                                     od["output"], dict(od.get("attrs", {}))))
        sd.loss_name = d.get("loss")
        if d.get("updater"):
            sd.updater = _upd.Updater.from_dict(d["updater"])
        return sd

    def save(self, path: str) -> None:
        """graph.json + values.npz in a zip (the .fb-equivalent artifact)."""
        from ..utils.serializer import _tree_to_npz_bytes
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("graph.json", self.to_json())
            zf.writestr("values.npz", _tree_to_npz_bytes(
                {k: v for k, v in self._values.items()}))

    @staticmethod
    def load(path: str) -> "SameDiff":
        from ..utils.serializer import _npz_bytes_to_tree
        with zipfile.ZipFile(path, "r") as zf:
            sd = SameDiff.from_json(zf.read("graph.json").decode())
            sd._values = dict(_npz_bytes_to_tree(zf.read("values.npz")))
        return sd


def _attr_out(v):
    if isinstance(v, tuple):
        return list(v)
    return v


def _attr_in(v):
    if isinstance(v, list):
        return tuple(v)
    return v
