"""SameDiff-equivalent define-then-run graph autodiff layer.

TPU-native equivalent of nd4j's SameDiff (reference:
``nd4j-api .../autodiff/samediff/SameDiff.java``,
``.../autodiff/samediff/internal/{InferenceSession,TrainingSession}.java``,
``.../autodiff/samediff/serde/FlatBuffersMapper.java``† per SURVEY.md
§2.2/§3.3; reference mount was empty, citations upstream-relative,
unverified).

Architecture (the §3.3 "TPU translation"): the reference's dependency-tracked
op-at-a-time interpreter (ExecStep queue, ArrayCacheMemoryMgr) is replaced by
trace-once/compile-once: the recorded op list IS the program; executing it
under ``jax.jit`` hands XLA the whole graph for fusion, and the reference's
per-op ``doDiff`` gradient graph construction is ``jax.grad`` of the traced
function — no hand-written backward per op.

Variable kinds mirror SDVariable.VariableType: VARIABLE (trainable),
PLACEHOLDER (fed per call), CONSTANT (baked), ARRAY (op output).

Serialization: JSON graph-def (ops reference catalog names from
``deeplearning4j_tpu.ops``) + npz of VARIABLE/CONSTANT values, zipped — the
moral equivalent of the FlatBuffers ``.fb`` (format is ours; the contract —
graph+weights reload in a fresh process with identical outputs — is the
reference's). This layer is the compile target for the import frontends
(SURVEY.md §3.5).
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import ops as _catalog

VARIABLE = "VARIABLE"
PLACEHOLDER = "PLACEHOLDER"
CONSTANT = "CONSTANT"
ARRAY = "ARRAY"


class SDVariable:
    """Symbolic handle into a SameDiff graph (nd4j ``SDVariable``†)."""

    def __init__(self, sd: "SameDiff", name: str, kind: str,
                 shape: Optional[Tuple[int, ...]] = None):
        self.sd = sd
        self.name = name
        self.kind = kind
        self.shape = tuple(shape) if shape is not None else None

    # ---- operator sugar (each records a graph op) --------------------------
    def _bin(self, op, other, swap=False):
        other = self.sd._lift(other)
        a, b = (other, self) if swap else (self, other)
        return self.sd.call(op, a, b)

    def __add__(self, o):
        return self._bin("math.add", o)

    def __radd__(self, o):
        return self._bin("math.add", o, swap=True)

    def __sub__(self, o):
        return self._bin("math.sub", o)

    def __rsub__(self, o):
        return self._bin("math.sub", o, swap=True)

    def __mul__(self, o):
        return self._bin("math.mul", o)

    def __rmul__(self, o):
        return self._bin("math.mul", o, swap=True)

    def __truediv__(self, o):
        return self._bin("math.div", o)

    def __rtruediv__(self, o):
        return self._bin("math.div", o, swap=True)

    def __pow__(self, o):
        return self._bin("math.pow", o)

    def __neg__(self):
        return self.sd.call("math.neg", self)

    def __matmul__(self, o):
        return self._bin("linalg.mmul", o)

    # ---- common graph methods (SDVariable sugar) ---------------------------
    def mmul(self, other, **kw):
        return self.sd.call("linalg.mmul", self, self.sd._lift(other), **kw)

    def add(self, other):
        return self.__add__(other)

    def sub(self, other):
        return self.__sub__(other)

    def mul(self, other):
        return self.__mul__(other)

    def div(self, other):
        return self.__truediv__(other)

    def reshape(self, *shape):
        return self.sd.call("shape.reshape", self, attrs={"shape": list(shape)})

    def transpose(self, *axes):
        return self.sd.call("shape.transpose", self,
                            attrs={"axes": list(axes)} if axes else {})

    def sum(self, axis=None, keepdims=False):
        return self.sd.call("reduce.sum", self,
                            attrs={"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False):
        return self.sd.call("reduce.mean", self,
                            attrs={"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False):
        return self.sd.call("reduce.max", self,
                            attrs={"axis": axis, "keepdims": keepdims})

    def std(self, axis=None, keepdims=False):
        return self.sd.call("reduce.std", self,
                            attrs={"axis": axis, "keepdims": keepdims})

    def eval(self, feeds: Optional[Dict[str, Any]] = None):
        """Evaluate just this variable (session compile + execute)."""
        return self.sd.output(feeds or {}, [self.name])[self.name]


class _OpRecord:
    """One recorded op application. ``outputs`` is a list — multi-output ops
    (split/unstack/top_k, nd4j multi-output DynamicCustomOps) bind every
    element of the returned tuple to its own graph name. Control-flow
    records (op ``__cond__``/``__while__``/``__scan__``) carry their traced
    subgraphs in ``attrs`` (see SameDiff.cond)."""
    __slots__ = ("op", "inputs", "outputs", "attrs")

    def __init__(self, op: str, inputs: List[str], outputs, attrs: Dict[str, Any]):
        self.op = op
        self.inputs = inputs
        self.outputs = [outputs] if isinstance(outputs, str) else list(outputs)
        self.attrs = attrs

    @property
    def output(self) -> str:
        return self.outputs[0]

    def referenced(self) -> List[str]:
        """All graph names this record reads — its direct inputs plus, for
        control flow, everything its subgraphs read (captured parent
        references included; formals excluded is unnecessary for
        reachability since formals map back to inputs anyway)."""
        names = list(self.inputs)
        for key in ("true", "false", "cond", "body"):
            sub = self.attrs.get(key)
            if isinstance(sub, _Subgraph):
                for rec in sub.ops:
                    names.extend(rec.referenced())
        return names


class _Subgraph:
    """A traced sub-program for control flow: formal parameter names, result
    names, and the op list. Ops may reference names from the ENCLOSING graph
    (captured constants/variables) — at execution the subgraph environment
    is seeded with the parent environment."""
    __slots__ = ("params", "results", "ops")

    def __init__(self, params: List[str], results: List[str],
                 ops: List[_OpRecord]):
        self.params = list(params)
        self.results = list(results)
        self.ops = list(ops)

    def to_dict(self):
        return {"params": self.params, "results": self.results,
                "ops": [_op_to_dict(r) for r in self.ops]}

    @staticmethod
    def from_dict(d):
        return _Subgraph(d["params"], d["results"],
                         [_op_from_dict(od) for od in d["ops"]])


from ..runtime.sentinel import SentinelCounterMixin as _SentinelCounterMixin


class SameDiff(_SentinelCounterMixin):
    """The graph container + session (nd4j ``SameDiff`` / sessions†).
    Inherits the divergence-sentinel counter surface
    (``resilience_counters`` et al.) from the shared mixin."""

    def __init__(self):
        self._vars: Dict[str, SDVariable] = {}
        self._values: Dict[str, jnp.ndarray] = {}   # VARIABLE + CONSTANT
        self._ops: List[_OpRecord] = []             # creation order == topo
        self._counter = 0
        self._fn_cache: Dict[Tuple, Callable] = {}
        self.updater = None
        self.loss_name: Optional[str] = None
        self._listeners: List[Any] = []
        self.iteration = 0
        self.epoch = 0
        self._score = float("nan")
        self.train_config: Dict[str, Any] = {}
        self.dtype = "FLOAT"  # "BFLOAT16" = bf16 compute / fp32 masters
        # activation-checkpoint policy for the compiled fit step
        # (none | full | dots_saveable | every_<k> — autodiff/remat.py
        # segments the op list at attention anchors)
        self.workspace_mode = "none"
        # divergence-sentinel counter tree (runtime/sentinel.py), threaded
        # through the compiled fit step like the optimizer state
        self._sentinel = None

    # listener-facing Model protocol (Score/Collect/Checkpoint listeners)
    def score(self) -> float:
        return self._score

    def set_listeners(self, *listeners) -> "SameDiff":
        self._listeners = list(listeners)
        return self

    def add_listener(self, l) -> "SameDiff":
        self._listeners.append(l)
        return self

    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    # ------------------------------------------------------------ variables
    def _fresh(self, base: str) -> str:
        self._counter += 1
        name = f"{base}_{self._counter}"
        while name in self._vars:
            self._counter += 1
            name = f"{base}_{self._counter}"
        return name

    def _register(self, name, kind, shape=None) -> SDVariable:
        if name in self._vars:
            raise ValueError(f"variable {name!r} already exists")
        v = SDVariable(self, name, kind, shape)
        self._vars[name] = v
        return v

    def placeholder(self, name: str, shape=None, dtype=jnp.float32) -> SDVariable:
        return self._register(name, PLACEHOLDER, shape)

    def var(self, name: str, value) -> SDVariable:
        """Trainable VARIABLE with an initial value."""
        arr = jnp.asarray(value)
        v = self._register(name, VARIABLE, arr.shape)
        self._values[name] = arr
        return v

    def constant(self, name: str, value) -> SDVariable:
        arr = jnp.asarray(value)
        v = self._register(name, CONSTANT, arr.shape)
        self._values[name] = arr
        return v

    def _lift(self, value) -> SDVariable:
        """Lift a python/numpy scalar or array into a CONSTANT."""
        if isinstance(value, SDVariable):
            return value
        return self.constant(self._fresh("const"), value)

    # ----------------------------------------------------------------- ops
    def call(self, op_name: str, *inputs: SDVariable, name: Optional[str] = None,
             attrs: Optional[Dict[str, Any]] = None, **kw_attrs) -> SDVariable:
        """Record a catalog op application; returns the output SDVariable."""
        if _catalog.lookup(op_name) is None:
            raise ValueError(f"unknown op {op_name!r} (not in the catalog)")
        attrs = dict(attrs or {})
        attrs.update(kw_attrs)
        for v in inputs:
            if v.name not in self._vars:
                raise ValueError(f"input {v.name!r} is not in this graph")
        out = name or self._fresh(op_name.split(".")[-1])
        v = self._register(out, ARRAY)
        self._ops.append(_OpRecord(op_name, [i.name for i in inputs], out, attrs))
        self._fn_cache.clear()
        return v

    def call_multi(self, op_name: str, *inputs: SDVariable, n_outputs: int,
                   name: Optional[str] = None,
                   attrs: Optional[Dict[str, Any]] = None,
                   **kw_attrs) -> Tuple[SDVariable, ...]:
        """Record a MULTI-OUTPUT catalog op (split/unstack/top_k/...; nd4j
        multi-output DynamicCustomOp equivalent). The op must return a
        tuple/list of ``n_outputs`` arrays. ``name`` may be a base string
        (outputs named ``<base>``, ``<base>__k``) or a sequence of
        ``n_outputs`` explicit names (importers bind source-graph tensor
        names this way); None entries get generated names."""
        if _catalog.lookup(op_name) is None:
            raise ValueError(f"unknown op {op_name!r} (not in the catalog)")
        if n_outputs < 1:
            raise ValueError("n_outputs must be >= 1")
        attrs = dict(attrs or {})
        attrs.update(kw_attrs)
        for v in inputs:
            if v.name not in self._vars:
                raise ValueError(f"input {v.name!r} is not in this graph")
        if isinstance(name, (list, tuple)):
            if len(name) != n_outputs:
                raise ValueError(
                    f"{len(name)} output names for n_outputs={n_outputs}")
            outs = [n or self._fresh(op_name.split(".")[-1]) for n in name]
        else:
            base = name or self._fresh(op_name.split(".")[-1])
            outs = [base if k == 0 else f"{base}__{k}"
                    for k in range(n_outputs)]
        vs = tuple(self._register(o, ARRAY) for o in outs)
        self._ops.append(_OpRecord(op_name, [i.name for i in inputs], outs, attrs))
        self._fn_cache.clear()
        return vs

    # ------------------------------------------------------------ control flow
    def _trace_subgraph(self, fn: Callable, formals: Sequence[SDVariable]):
        """Run a Python builder function while recording into a fresh op
        list. The builder receives this SameDiff (so constants/captured
        variables land in the shared registry) plus the formal SDVariables;
        it returns one SDVariable or a tuple."""
        outer_ops = self._ops
        self._ops = []
        try:
            res = fn(self, *formals)
        finally:
            sub_ops, self._ops = self._ops, outer_ops
        res_vars = list(res) if isinstance(res, (tuple, list)) else [res]
        return _Subgraph([f.name for f in formals],
                         [r.name for r in res_vars], sub_ops), res_vars

    def cond(self, pred: SDVariable, true_fn: Callable, false_fn: Callable,
             *operands: SDVariable, name: Optional[str] = None
             ) -> Tuple[SDVariable, ...]:
        """``lax.cond`` record (nd4j If/Switch-Merge equivalent). Both
        branch builders get ``(sd, *formal_operands)`` and must return
        structurally matching outputs. Returns the output SDVariables
        (tuple even for a single output)."""
        formals = [self._register(self._fresh("cond_arg"), ARRAY)
                   for _ in operands]
        sub_t, res_t = self._trace_subgraph(true_fn, formals)
        formals_f = [self._register(self._fresh("cond_arg"), ARRAY)
                     for _ in operands]
        sub_f, res_f = self._trace_subgraph(false_fn, formals_f)
        if len(res_t) != len(res_f):
            raise ValueError(
                f"cond branches return {len(res_t)} vs {len(res_f)} outputs")
        base = name or self._fresh("cond")
        outs = [base if k == 0 else f"{base}__{k}" for k in range(len(res_t))]
        vs = tuple(self._register(o, ARRAY) for o in outs)
        self._ops.append(_OpRecord(
            "__cond__", [pred.name] + [o.name for o in operands], outs,
            {"true": sub_t, "false": sub_f}))
        self._fn_cache.clear()
        return vs

    def while_loop(self, cond_fn: Callable, body_fn: Callable,
                   *loop_vars: SDVariable, name: Optional[str] = None
                   ) -> Tuple[SDVariable, ...]:
        """``lax.while_loop`` record (nd4j While equivalent). ``cond_fn``
        returns a scalar-bool SDVariable; ``body_fn`` returns new loop vars
        (same structure). Reverse-mode gradients through a while loop are
        not defined (same as JAX); use scan for differentiable loops."""
        formals_c = [self._register(self._fresh("while_arg"), ARRAY)
                     for _ in loop_vars]
        sub_c, res_c = self._trace_subgraph(cond_fn, formals_c)
        if len(res_c) != 1:
            raise ValueError("while_loop cond_fn must return one scalar bool")
        formals_b = [self._register(self._fresh("while_arg"), ARRAY)
                     for _ in loop_vars]
        sub_b, res_b = self._trace_subgraph(body_fn, formals_b)
        if len(res_b) != len(loop_vars):
            raise ValueError(
                f"while_loop body returns {len(res_b)} values for "
                f"{len(loop_vars)} loop vars")
        base = name or self._fresh("while")
        outs = [base if k == 0 else f"{base}__{k}"
                for k in range(len(loop_vars))]
        vs = tuple(self._register(o, ARRAY) for o in outs)
        self._ops.append(_OpRecord("__while__", [o.name for o in loop_vars],
                                   outs, {"cond": sub_c, "body": sub_b}))
        self._fn_cache.clear()
        return vs

    def scan(self, body_fn: Callable, carry: Sequence[SDVariable],
             xs: Sequence[SDVariable], name: Optional[str] = None
             ) -> Tuple[Tuple[SDVariable, ...], Tuple[SDVariable, ...]]:
        """``lax.scan`` record: ``body_fn(sd, *carry, *x_slices)`` returns
        ``(*new_carry, *y_slices)``. ``xs`` are scanned over their leading
        axis. Returns ``(final_carry_vars, stacked_y_vars)``. Differentiable
        (the TPU-native way to express sequential loops)."""
        carry = list(carry)
        xs = list(xs)
        formals = [self._register(self._fresh("scan_arg"), ARRAY)
                   for _ in range(len(carry) + len(xs))]
        sub, res = self._trace_subgraph(body_fn, formals)
        n_carry = len(carry)
        n_ys = len(res) - n_carry
        if n_ys < 0:
            raise ValueError("scan body must return at least the new carry")
        base = name or self._fresh("scan")
        outs = [base if k == 0 else f"{base}__{k}"
                for k in range(n_carry + n_ys)]
        vs = tuple(self._register(o, ARRAY) for o in outs)
        self._ops.append(_OpRecord(
            "__scan__", [c.name for c in carry] + [x.name for x in xs], outs,
            {"body": sub, "n_carry": n_carry}))
        self._fn_cache.clear()
        return vs[:n_carry], vs[n_carry:]

    # nd4j namespace sugar (sd.nn()/sd.math() style collapsed to methods)
    def relu(self, x, name=None):
        return self.call("act.relu", x, name=name)

    def sigmoid(self, x, name=None):
        return self.call("act.sigmoid", x, name=name)

    def tanh(self, x, name=None):
        return self.call("act.tanh", x, name=name)

    def softmax(self, x, name=None):
        return self.call("act.softmax", x, name=name)

    def mmul(self, a, b, name=None):
        return self.call("linalg.mmul", a, b, name=name)

    # ------------------------------------------------------------ execution
    def _compute(self, values: Dict[str, jnp.ndarray],
                 feeds: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        """Pure topo-order evaluation of the recorded program."""
        env: Dict[str, jnp.ndarray] = {}
        env.update(values)
        env.update(feeds)
        self._exec_ops(self._ops, env)
        return env

    def _exec_ops(self, ops: List[_OpRecord], env: Dict[str, jnp.ndarray]):
        """Execute a recorded op list into ``env`` (shared by subgraphs —
        the recursion point for control flow)."""
        for rec in ops:
            if rec.op == "__cond__":
                pred = jnp.asarray(env[rec.inputs[0]], bool).reshape(())
                operands = tuple(env[i] for i in rec.inputs[1:])
                t, f = rec.attrs["true"], rec.attrs["false"]
                res = jax.lax.cond(pred,
                                   self._subgraph_fn(t, env),
                                   self._subgraph_fn(f, env), operands)
            elif rec.op == "__while__":
                operands = tuple(env[i] for i in rec.inputs)
                c, b = rec.attrs["cond"], rec.attrs["body"]
                cf = self._subgraph_fn(c, env)
                bf = self._subgraph_fn(b, env)
                res = jax.lax.while_loop(
                    lambda vs: jnp.asarray(cf(vs)[0], bool).reshape(()),
                    bf, operands)
            elif rec.op == "__scan__":
                n_carry = int(rec.attrs["n_carry"])
                carry0 = tuple(env[i] for i in rec.inputs[:n_carry])
                xs = tuple(env[i] for i in rec.inputs[n_carry:])
                bf = self._subgraph_fn(rec.attrs["body"], env)

                def scan_body(carry, x_slices, _bf=bf, _n=n_carry):
                    out = _bf(tuple(carry) + tuple(x_slices))
                    return out[:_n], out[_n:]
                final, ys = jax.lax.scan(scan_body, carry0, xs)
                res = tuple(final) + tuple(ys)
            else:
                fn = _catalog.get(rec.op).fn
                args = [env[i] for i in rec.inputs]
                attrs = {k: _attr_in(v) for k, v in rec.attrs.items()}
                res = fn(*args, **attrs)
            if len(rec.outputs) == 1:
                env[rec.outputs[0]] = res if not isinstance(res, (tuple, list)) \
                    else res[0]
            else:
                if not isinstance(res, (tuple, list)) or \
                        len(res) != len(rec.outputs):
                    got = (len(res) if isinstance(res, (tuple, list))
                           else type(res).__name__)
                    raise ValueError(
                        f"op {rec.op!r} bound to {len(rec.outputs)} outputs "
                        f"but returned {got}")
                for o, r in zip(rec.outputs, res):
                    env[o] = r

    def _subgraph_fn(self, sub: _Subgraph, parent_env: Dict[str, jnp.ndarray]):
        """Callable over a tuple of operand values; the subgraph environment
        is seeded with a SNAPSHOT of the parent env so captured names
        (constants, variables, earlier results) resolve — they become
        closure constants of the traced branch, exactly lax semantics."""
        captured = dict(parent_env)

        def run(operand_vals):
            env = dict(captured)
            env.update(zip(sub.params, operand_vals))
            self._exec_ops(sub.ops, env)
            return tuple(env[r] for r in sub.results)
        return run

    def _session(self, targets: Tuple[str, ...]) -> Callable:
        """Compile-once-execute-many (InferenceSession equivalent): one jit
        program per requested target set."""
        key = targets
        if key not in self._fn_cache:
            def fn(values, feeds):
                env = self._compute(values, feeds)
                return {t: env[t] for t in targets}
            self._fn_cache[key] = jax.jit(fn)
        return self._fn_cache[key]

    def output(self, feeds: Dict[str, Any], targets: Sequence[str]) -> Dict[str, np.ndarray]:
        """Evaluate target variables under the given placeholder feeds."""
        missing = [n for n, v in self._vars.items()
                   if v.kind == PLACEHOLDER and n not in feeds]
        needed = self._needed_placeholders(targets)
        missing = [m for m in missing if m in needed]
        if missing:
            raise ValueError(f"missing placeholder feeds: {missing}")
        fn = self._session(tuple(targets))
        out = fn(self._values, {k: jnp.asarray(v) for k, v in feeds.items()
                                if k in needed})
        return {k: np.asarray(v) for k, v in out.items()}

    def _needed_placeholders(self, targets) -> set:
        """Backward reachability: which placeholders feed the targets
        (traverses control-flow subgraphs via _OpRecord.referenced)."""
        producers = {o: r for r in self._ops for o in r.outputs}
        need, stack = set(), list(targets)
        seen = set()
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            v = self._vars.get(n)
            if v is not None and v.kind == PLACEHOLDER:
                need.add(n)
            rec = producers.get(n)
            if rec:
                stack.extend(rec.referenced())
        return need

    # ------------------------------------------------------------- training
    def set_loss(self, loss: SDVariable) -> "SameDiff":
        self.loss_name = loss.name
        return self

    def set_updater(self, updater) -> "SameDiff":
        self.updater = updater
        return self

    def set_dtype(self, dtype) -> "SameDiff":
        """Training dtype policy — mirrors the nn engines' ``dtype=
        "BFLOAT16"`` (SameDiff TrainingConfig dtype†, SURVEY.md §7.3.8):
        under a 16-bit policy the compiled fit step keeps fp32 MASTER
        weights/updater state and runs the graph (matmuls included) in the
        compute dtype; gradients flow back through the cast and land in
        fp32. Affects ``fit`` only — ``exec``/``output``/``grad`` stay in
        the recorded dtypes (imported-graph inference parity)."""
        from .. import dtypes as _dt
        _dt.resolve(dtype)  # validate early
        self.dtype = dtype
        self._fn_cache.pop("__fit_step__", None)
        return self

    def set_workspace_mode(self, mode) -> "SameDiff":
        """Activation-checkpoint policy for the compiled fit step
        (engine-parity knob — ``nn/memory.py`` policies): the recorded op
        list is segmented into transformer-block chunks at attention
        anchors (``autodiff/remat.py``) and each segment replays inside
        ``jax.checkpoint``, so the backward pass rematerializes block
        interiors instead of keeping them in HBM. The policy is part of
        the fit-step cache spec — mutating it retraces. Affects ``fit``
        only; ``exec``/``output``/``grad`` never remat (no backward pass
        to trade against)."""
        from ..nn import memory as _memory
        self.workspace_mode = _memory.resolve_policy(mode).name
        self._fn_cache.pop("__fit_step__", None)
        return self

    def set_training_config(self, updater=None, l1: float = 0.0,
                            l2: float = 0.0,
                            gradient_clip_value: Optional[float] = None,
                            gradient_clip_l2: Optional[float] = None,
                            gradient_normalization: Optional[str] = None,
                            gradient_normalization_threshold: float = 1.0
                            ) -> "SameDiff":
        """nd4j ``TrainingConfig`` parity: updater + l1/l2 regularization
        over VARIABLEs + gradient clipping/normalization, all applied inside
        the compiled fit step. GradientNormalization 'per layer' means per
        VARIABLE here (SameDiff has no layer grouping — recorded)."""
        from ..nn import gradnorm as _gn
        _gn.validate(gradient_normalization)
        if updater is not None:
            self.updater = updater
        self.train_config = {
            "l1": float(l1), "l2": float(l2),
            "clip_value": gradient_clip_value,
            "clip_l2": gradient_clip_l2,
            "grad_norm": gradient_normalization,
            "grad_norm_threshold": float(gradient_normalization_threshold),
        }
        self._fn_cache.pop("__fit_step__", None)
        return self

    def grad(self, feeds: Dict[str, Any],
             wrt: Optional[Sequence[str]] = None) -> Dict[str, np.ndarray]:
        """Gradients of the loss w.r.t. VARIABLEs (createGradFunction +
        execBackwards equivalent — here just jax.grad of the traced program)."""
        if self.loss_name is None:
            raise ValueError("set_loss(...) first")
        wrt = list(wrt or [n for n, v in self._vars.items()
                           if v.kind == VARIABLE])
        loss_name = self.loss_name

        def loss_fn(train_vals, other_vals, feeds):
            env = self._compute({**other_vals, **train_vals}, feeds)
            return env[loss_name]

        train = {n: self._values[n] for n in wrt}
        other = {n: v for n, v in self._values.items() if n not in train}
        g = jax.jit(jax.grad(loss_fn))(
            train, other, {k: jnp.asarray(v) for k, v in feeds.items()})
        return {k: np.asarray(v) for k, v in g.items()}

    def _cast_other_vals(self, other_vals):
        """bf16 audit fix (ISSUE 14 satellite, the r12 cast hoist's
        sibling): under a 16-bit dtype policy, cast the NON-trainable
        values (imported CONSTs, frozen weights) to the compute dtype
        ONCE, host-call-side, instead of re-casting them inside every
        compiled fit step — they never change between steps, so the
        per-step ``cast_floating`` over them was pure wasted bandwidth
        (for a frozen-encoder fine-tune, the entire encoder re-cast
        every step). The step's in-graph ``cast_floating`` stays as a
        safety net and is an IDENTITY (zero jaxpr eqns) for pre-cast
        leaves, so a caller handing raw f32 values still computes
        correctly — just without the hoist. Bit-equal to the un-hoisted
        program: the same cast, done once (tested, jaxpr-regressed).
        Identity under a non-mixed policy."""
        from .. import dtypes as _dt
        if not _dt.is_mixed(self.dtype):
            return other_vals
        return _dt.cast_floating(other_vals, _dt.resolve(self.dtype))

    def _fit_loss_fn(self, split_penalty: bool = False):
        """The pure training loss ``(train_vals, other_vals, feeds) ->
        scalar`` the fit step differentiates — factored out so
        :meth:`memory_report` can account its forward→backward residuals.
        Applies the ``workspace_mode`` remat policy: the op-list replay is
        segmented at attention anchors and each segment rematerializes in
        the backward pass (``autodiff/remat.py``).

        ``split_penalty=True`` returns the four-arg form ``(tv_penalty,
        tv_forward, other_vals, feeds)`` the fused master-cast updater
        step (ISSUE 16) differentiates: the forward reads the
        compute-dtype copies carried across steps (``cast_floating`` on
        them is an identity, so the traced forward is bit-equal to the
        unfused one) while l1/l2 penalties keep reading the f32 MASTERS
        — exactly the split the unfused program has. The default form is
        the split one applied to the same tree twice."""
        loss_name = self.loss_name
        tc = dict(self.train_config)
        from .. import dtypes as _dt
        from ..nn import memory as _memory
        mixed = _dt.is_mixed(self.dtype)
        cdt = _dt.resolve(self.dtype)
        policy = _memory.resolve_policy(getattr(self, "workspace_mode", None))

        def loss_split(tv_pen, tv, other_vals, feeds):
            vals, fd = {**other_vals, **tv}, feeds
            if mixed:
                # fp32 masters -> compute-dtype working copies; grads
                # flow back through the cast into fp32 (engine parity).
                # Identity (zero eqns) for pre-cast fused-carry leaves.
                vals = _dt.cast_floating(vals, cdt)
                fd = _dt.cast_floating(fd, cdt)
            if policy.remat:
                from . import remat as _remat
                env = _remat.compute_with_remat(self, vals, fd,
                                                (loss_name,), policy)
            else:
                env = self._compute(vals, fd)
            total = env[loss_name]
            if mixed:  # regularization/score accumulate in fp32
                total = jnp.asarray(total, jnp.float32)
            if tc.get("l1"):
                total = total + tc["l1"] * sum(
                    jnp.sum(jnp.abs(v)) for v in tv_pen.values())
            if tc.get("l2"):
                total = total + 0.5 * tc["l2"] * sum(
                    jnp.sum(jnp.square(v)) for v in tv_pen.values())
            return total

        if split_penalty:
            return loss_split

        def loss_fn(tv, other_vals, feeds):
            return loss_split(tv, tv, other_vals, feeds)

        return loss_fn

    def _make_fit_step(self):
        """(spec, jitted step fn) for the compiled fit step. The spec keys
        everything the trace bakes in: loss/updater/train-config, the
        dtype policy, the workspace_mode remat policy, the Environment's
        f32 matmul-precision mode, and the VARIABLE set — mutating any of
        them must retrace instead of silently reusing the old executable
        (the cache in :meth:`fit` compares specs)."""
        loss_name = self.loss_name
        train_names = [n for n, v in self._vars.items() if v.kind == VARIABLE]
        updater = self.updater
        tc = dict(self.train_config)
        fused_cast = self.fused_updater_active()
        loss_fn = self._fit_loss_fn(split_penalty=fused_cast)
        penalty = bool(tc.get("l1")) or bool(tc.get("l2"))
        from .. import dtypes as _dt
        cdt = _dt.resolve(self.dtype)

        from ..runtime import sentinel as _sent
        from ..nn import updaters as _updaters

        def _clip_and_ok(loss, grads):
            from ..nn import gradnorm as _gn
            # the shared engine clip pipeline; per-VARIABLE grouping means
            # each leaf is wrapped as its own "layer" for the mode step
            # (value/L2 clip are tree-shape agnostic, so the wrap is safe)
            wrapped = {k: {"g": g} for k, g in grads.items()}
            wrapped, clip_events = _gn.clip_with_events(
                tc.get("grad_norm"), tc.get("grad_norm_threshold", 1.0),
                tc.get("clip_value"), tc.get("clip_l2"), wrapped)
            grads = {k: v["g"] for k, v in wrapped.items()}
            # DIVERGENCE SENTINEL — engine-parity contract (see
            # MultiLayerNetwork._build_train_step): non-finite loss or
            # global grad norm skips the weight update inside lax.cond and
            # bumps the on-device counters; zero host syncs/retraces.
            ok = _sent.finite_ok(loss, grads)
            return grads, ok, clip_events

        if fused_cast:
            # FUSED MASTER-CAST UPDATER STEP (ISSUE 16): the first arg is
            # the ``(masters, compute_copies)`` carry from _fit_carry().
            # The forward reads the pre-cast compute copies (cast_floating
            # on them is identity -> bit-equal forward); cotangents come
            # back 16-bit and are upcast EXACTLY like the unfused cast's
            # transpose (f32<-16-bit convert is value-exact); the updater
            # emits the fresh compute copy in the same fusion that writes
            # the f32 master (apply_leafwise_cast), so the standalone
            # per-step master-cast sweep disappears from the program.
            def step(carry, opt_state, other_vals, step_i, feeds,
                     sentinel=None):
                tv, tv_c = carry
                if penalty:
                    # penalties read the f32 masters (argnum 0), the
                    # forward reads the compute copies (argnum 1) — the
                    # exact split the unfused program differentiates; the
                    # two cotangent paths sum commutatively (bit-equal)
                    loss, (g_m, g_c) = jax.value_and_grad(
                        lambda a, b: loss_fn(a, b, other_vals, feeds),
                        argnums=(0, 1))(tv, tv_c)
                    grads = jax.tree.map(
                        lambda p, gm, gc: gm + gc.astype(p.dtype),
                        tv, g_m, g_c)
                else:
                    loss, g_c = jax.value_and_grad(
                        lambda b: loss_fn(tv, b, other_vals, feeds))(tv_c)
                    grads = jax.tree.map(lambda p, gc: gc.astype(p.dtype),
                                         tv, g_c)
                grads, ok, clip_events = _clip_and_ok(loss, grads)

                def _apply(pair, opt_state):
                    p, _ = pair
                    new_p, new_pc, new_opt = _updaters.apply_leafwise_cast(
                        updater, grads, opt_state, p, step_i, cdt)
                    return (new_p, new_pc), new_opt

                new_carry, new_opt = _sent.guarded_apply(
                    ok, _apply, (tv, tv_c), opt_state)
                if sentinel is None:  # pre-sentinel call signature
                    return new_carry, new_opt, loss
                return (new_carry, new_opt,
                        _sent.update_counters(sentinel, ok, clip_events),
                        loss)
        else:
            def step(train_vals, opt_state, other_vals, step_i, feeds,
                     sentinel=None):
                loss, grads = jax.value_and_grad(
                    lambda tv: loss_fn(tv, other_vals, feeds))(train_vals)
                grads, ok, clip_events = _clip_and_ok(loss, grads)

                def _apply(train_vals, opt_state):
                    delta, new_opt = updater.apply(grads, opt_state,
                                                   train_vals, step_i)
                    return (jax.tree.map(lambda p, d: p - d, train_vals,
                                         delta),
                            new_opt)

                new_vals, new_opt = _sent.guarded_apply(
                    ok, _apply, train_vals, opt_state)
                if sentinel is None:  # pre-sentinel call signature
                    return new_vals, new_opt, loss
                return (new_vals, new_opt,
                        _sent.update_counters(sentinel, ok, clip_events),
                        loss)

        import json as _json
        from .. import environment as _envmod
        spec = ("fit", loss_name,
                _json.dumps(updater.to_dict(), sort_keys=True, default=str),
                _json.dumps(self.train_config, sort_keys=True, default=str),
                str(self.dtype),
                str(getattr(self, "workspace_mode", "none")),
                str(_envmod.Environment.instance().f32_matmul_precision),
                tuple(train_names),
                "fused_cast" if fused_cast else "plain")
        return spec, jax.jit(step, donate_argnums=(0, 1))

    # ------------------------------------------- fused master-cast carry
    def fused_updater_active(self) -> bool:
        """Does the compiled fit step use the fused master-cast updater
        (ISSUE 16)? True under a 16-bit dtype policy with the fused-
        epilogue library enabled (``DL4J_TPU_FUSED_EPILOGUES`` != off).
        When True the step's first argument is the ``(masters,
        compute_copies)`` tuple from :meth:`_fit_carry`, not the bare
        master dict — external drivers (bench) go through the carry
        helpers instead of assuming the plain signature."""
        from ..ops import fused_epilogues as _fe
        # the SameDiff step differentiates penalties against the masters
        # explicitly (split_penalty), so l1/l2 never forces a fallback
        return _fe.route_updater(self.dtype) is None

    def _fit_carry(self, train_vals):
        """The compiled step's first argument for ``train_vals``: the
        ``(masters, compute_copies)`` pair when the fused updater is
        active (the ONE remaining host-side cast — every subsequent step
        re-emits the copies from inside the updater), else the bare
        master dict."""
        if not self.fused_updater_active():
            return train_vals
        from .. import dtypes as _dt
        return (train_vals,
                _dt.cast_floating(train_vals, _dt.resolve(self.dtype)))

    @staticmethod
    def _carry_masters(carry):
        """The f32 masters view of a step carry (either signature)."""
        return carry[0] if isinstance(carry, tuple) else carry

    #: spec tuple positions -> retrace-tracker cause (see _make_fit_step
    #: for the tuple layout); anything else is a generic config change
    _SPEC_CAUSES = {4: "dtype_policy", 5: "workspace_mode", 6: "precision",
                    8: "fused_updater"}

    def _fit_step_cached(self):
        """The cached compiled fit step (built if absent/stale). ONE step
        is kept across fit() calls — re-jitting a large imported graph per
        call costs seconds (found fine-tuning BERT-base); old compiled
        executables for big graphs are device memory worth releasing.
        Every rebuild reports to the retrace tracker with the spec field
        that changed as its cause — a silent retrace of a BERT-sized
        import is exactly what ISSUE 6 makes visible."""
        spec, step = self._make_fit_step()
        cached = self._fn_cache.get("__fit_step__")
        if cached is not None and cached[0] == spec:
            return cached[1]
        from ..runtime import telemetry as _tel
        # the mutators (set_dtype/set_workspace_mode/...) pop the cache to
        # release the old executable's device memory, so the cause diff
        # runs against the last-built spec kept separately
        prev_spec = getattr(self, "_last_fit_spec", None)
        if prev_spec is None:
            cause = "first_build"
        else:
            changed = [i for i, (a, b) in enumerate(zip(prev_spec, spec))
                       if a != b]
            cause = next((self._SPEC_CAUSES[i] for i in changed
                          if i in self._SPEC_CAUSES), "config_change")
        _tel.record_compile("samediff.fit_step", cause,
                            loss=str(spec[1]))
        # dispatch accounting rides the cache miss: ONE decision count per
        # compiled step, not one per fit() call (mirrors the kernel-side
        # fused_epilogues.dispatch discipline: zero silent fallbacks)
        from ..ops import fused_epilogues as _fe
        _fe.dispatch_updater(self.dtype)
        self._fn_cache["__fit_step__"] = (spec, step)
        self._last_fit_spec = spec
        return step

    def fit(self, feeds_iter, epochs: int = 1, listeners: Optional[List] = None
            ) -> "History":
        """Minibatch training. feeds_iter: iterable of feed dicts (or a single
        dict). Returns a History (loss curve + per-epoch averages — nd4j
        ``History``†). ``listeners`` (or ones attached via set_listeners)
        receive the same iteration_done/on_epoch_end callbacks as the nn
        engines; ``self`` quacks enough like a Model for Score/Collect/
        Checkpoint listeners (score(), iteration, epoch, save())."""
        if self.loss_name is None or self.updater is None:
            raise ValueError("set_loss(...) and set_updater(...) first")
        feeds_list = [feeds_iter] if isinstance(feeds_iter, dict) else list(feeds_iter)
        train_names = [n for n, v in self._vars.items() if v.kind == VARIABLE]
        updater = self.updater
        step = self._fit_step_cached()
        # fused master-cast carry (ISSUE 16): under a 16-bit policy the
        # step carries (masters, compute_copies) — built ONCE here, then
        # the fused updater re-emits the copies every step on-device
        carry = self._fit_carry({n: self._values[n] for n in train_names})
        train_vals = self._carry_masters(carry)
        # cast hoist (ISSUE 14 satellite): constants/frozen values go to
        # the compute dtype ONCE here, not once per compiled step —
        # self._values keeps the f32 originals (masters discipline)
        other_vals = self._cast_other_vals(
            {n: v for n, v in self._values.items()
             if n not in train_names})
        opt_state = updater.init_state(train_vals)
        cbs = list(self._listeners) + list(listeners or [])
        history = History()
        i = self.iteration
        from ..runtime import faults as _faults
        for _ in range(epochs):
            epoch_losses = []
            for feeds in feeds_list:
                feeds = {k: jnp.asarray(v) for k, v in feeds.items()}
                if _faults.enabled():
                    _faults.trip("train.step")  # crash/preemption site
                    # float check FIRST: all-int feeds must not consume
                    # the injection's fire budget without poisoning anything
                    if any(jnp.issubdtype(v.dtype, jnp.floating)
                           for v in feeds.values()) and \
                            _faults.trip("train.nonfinite") is not None:
                        feeds = {k: jnp.full_like(v, jnp.nan)
                                 if jnp.issubdtype(v.dtype, jnp.floating)
                                 else v for k, v in feeds.items()}
                carry, opt_state, self._sentinel, loss = step(
                    carry, opt_state, other_vals,
                    jnp.asarray(i, jnp.int32), feeds,
                    self._ensure_sentinel())
                train_vals = self._carry_masters(carry)
                loss = float(loss)
                history.losses.append(loss)
                epoch_losses.append(loss)
                self._score = loss
                i += 1
                self.iteration = i
                if cbs:
                    # listeners may save/inspect: publish updated weights
                    self._values.update(train_vals)
                for cb in cbs:
                    cb.iteration_done(self, i, self.epoch)
            self.epoch += 1
            history.epoch_losses.append(
                sum(epoch_losses) / max(1, len(epoch_losses)))
            if cbs:
                self._values.update(train_vals)
            for cb in cbs:
                cb.on_epoch_end(self)
        self._values.update(train_vals)
        # no cache clear: sessions/steps take values as ARGUMENTS, so the
        # updated weights flow through; only graph mutation (call()) clears
        return history

    def evaluate(self, data_iter, output_name: str,
                 evaluation=None):
        """nd4j ``SameDiff.evaluate`` equivalent: run ``output_name`` over
        an iterable of ``(feeds_dict, labels_array)`` pairs and accumulate
        a classification Evaluation (one-hot or index labels)."""
        from ..eval.evaluation import Evaluation
        ev = evaluation or Evaluation()
        for feeds, labels in data_iter:
            out = self.output(feeds, [output_name])[output_name]
            labels = np.asarray(labels)
            if labels.ndim == out.ndim - 1:  # index labels -> one-hot
                labels = np.eye(out.shape[-1],
                                dtype=np.float32)[labels.astype(int)]
            ev.eval(labels, out)
        return ev

    # ---------------------------------------------------- memory accounting
    def memory_report(self, feeds: Dict[str, Any]) -> dict:
        """Compiled-HBM accounting of the fit step for one example feed
        dict (arrays OR ``jax.ShapeDtypeStruct``s — only shapes/dtypes are
        read): AOT lower+compile of the REAL compiled step (nothing
        executes, nothing allocates) exposing XLA ``memory_analysis()``
        temp/argument/output bytes, the forward→backward
        ``activation_bytes`` the workspace_mode remat shrinks, and live
        device ``memory_stats()``. Engine-parity twin of
        ``MultiLayerNetwork.memory_report`` (``nn/memory.py``); fields
        degrade to None on PJRT builds without the API."""
        if self.loss_name is None or self.updater is None:
            raise ValueError("set_loss(...) and set_updater(...) first")
        from ..nn import memory as _memory
        step = self._fit_step_cached()
        train_names = [n for n, v in self._vars.items() if v.kind == VARIABLE]
        tv = {n: self._values[n] for n in train_names}
        # mirror fit()'s cast hoist so the lowered program IS the one the
        # fit loop runs (pre-cast other_vals avals)
        ov = self._cast_other_vals(
            {n: v for n, v in self._values.items() if n not in tv})
        tv_avals = jax.eval_shape(lambda: tv)
        # the step's first arg is the fused (masters, copies) carry when
        # the fused updater is active — lower the REAL signature
        carry_avals = jax.eval_shape(lambda: self._fit_carry(tv))
        ov_avals = jax.eval_shape(lambda: ov)
        opt_avals = jax.eval_shape(lambda: self.updater.init_state(tv))
        feeds_avals = {
            k: (v if isinstance(v, jax.ShapeDtypeStruct) else
                jax.ShapeDtypeStruct(np.asarray(v).shape,
                                     np.asarray(v).dtype))
            for k, v in feeds.items()}
        batch = next((int(a.shape[0]) for a in feeds_avals.values()
                      if len(a.shape)), None)
        report = {
            "workspace_mode": str(getattr(self, "workspace_mode", "none")),
            "batch_size": batch,
            "temp_bytes": None, "argument_bytes": None, "output_bytes": None,
            "alias_bytes": None, "generated_code_bytes": None,
            "peak_bytes": None,
            "residual_bytes": None, "activation_bytes": None,
            "residual_count": None,
            "device": _memory.device_memory_stats(),
        }
        from ..runtime import sentinel as _sent
        from ..runtime import telemetry as _tel
        # sentinel counters included: accounts the REAL step fit() runs;
        # the accounting compile is attributed like every other probe
        _tel.record_compile("samediff.fit_step", "probe", batch=batch)
        compiled = step.lower(carry_avals, opt_avals, ov_avals,
                              jax.ShapeDtypeStruct((), jnp.int32),
                              feeds_avals, _sent.counter_avals()).compile()
        cm = _memory.compiled_memory(compiled)
        if cm:
            report.update(cm)
        rb = _memory.residual_bytes(self._fit_loss_fn(), tv_avals,
                                    ov_avals, feeds_avals)
        if rb:
            report.update(rb)
        return report

    # ------------------------------------------------------------ accessors
    def get_value(self, name: str) -> np.ndarray:
        return np.asarray(self._values[name])

    def set_value(self, name: str, value) -> None:
        if self._vars[name].kind not in (VARIABLE, CONSTANT):
            raise ValueError(f"{name} has no stored value")
        self._values[name] = jnp.asarray(value)
        self._fn_cache.clear()

    def variables(self) -> List[str]:
        return [n for n, v in self._vars.items() if v.kind == VARIABLE]

    # ------------------------------------------------------------ serde
    def to_json(self) -> str:
        return json.dumps({
            "format_version": 2,
            "model_class": "SameDiff",
            "variables": [{"name": v.name, "kind": v.kind,
                           "shape": list(v.shape) if v.shape else None}
                          for v in self._vars.values()],
            "ops": [_op_to_dict(r) for r in self._ops],
            "loss": self.loss_name,
            "updater": self.updater.to_dict() if self.updater else None,
            "training_config": self.train_config or None,
            "workspace_mode": self.workspace_mode,
        }, indent=2)

    @staticmethod
    def from_json(s: str) -> "SameDiff":
        from ..nn import updaters as _upd
        d = json.loads(s)
        sd = SameDiff()
        for vd in d["variables"]:
            if vd["name"] in sd._vars:
                continue
            sd._register(vd["name"], vd["kind"],
                         tuple(vd["shape"]) if vd.get("shape") else None)
        for od in d["ops"]:
            sd._ops.append(_op_from_dict(od))
        sd.loss_name = d.get("loss")
        if d.get("updater"):
            sd.updater = _upd.Updater.from_dict(d["updater"])
        sd.train_config = d.get("training_config") or {}
        sd.workspace_mode = d.get("workspace_mode", "none")
        return sd

    def save(self, path: str) -> None:
        """graph.json + values.npz in a zip (the .fb-equivalent artifact).

        Values are stored under positional npz keys with a JSON name table:
        the shared tree serializer treats ``/`` as a nesting separator, but
        SameDiff names are FLAT and TF-imported graphs are full of slashes
        (``bert/encoder/...``)."""
        from ..utils.serializer import _tree_to_npz_bytes
        names = list(self._values.keys())
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("graph.json", self.to_json())
            zf.writestr("value_names.json", json.dumps(names))
            zf.writestr("values.npz", _tree_to_npz_bytes(
                {f"v{i}": self._values[n] for i, n in enumerate(names)}))

    @staticmethod
    def load(path: str) -> "SameDiff":
        from ..utils.serializer import _npz_bytes_to_tree
        with zipfile.ZipFile(path, "r") as zf:
            sd = SameDiff.from_json(zf.read("graph.json").decode())
            tree = _npz_bytes_to_tree(zf.read("values.npz"))
            if "value_names.json" in zf.namelist():
                names = json.loads(zf.read("value_names.json").decode())
                sd._values = {n: tree[f"v{i}"] for i, n in enumerate(names)}
            else:  # round-2 artifact: flat keys, no slashes in names
                sd._values = dict(tree)
        return sd


class History:
    """Training history (nd4j ``History``† — loss curve plus per-epoch
    aggregates; evaluations attach via listeners). Iterable/indexable as the
    per-iteration loss list for round-2 call-site compatibility."""

    def __init__(self):
        self.losses: List[float] = []        # one per iteration
        self.epoch_losses: List[float] = []  # mean loss per epoch

    def loss_curve(self) -> List[float]:
        return list(self.losses)

    def __len__(self):
        return len(self.losses)

    def __iter__(self):
        return iter(self.losses)

    def __getitem__(self, i):
        return self.losses[i]


def _attr_out(v):
    if isinstance(v, tuple):
        return list(v)
    if isinstance(v, _Subgraph):
        return {"__subgraph__": v.to_dict()}
    return v


def _attr_in(v):
    if isinstance(v, list):
        return tuple(v)
    return v


def _op_to_dict(r: _OpRecord) -> Dict[str, Any]:
    return {"op": r.op, "inputs": r.inputs, "outputs": list(r.outputs),
            "attrs": {k: _attr_out(v) for k, v in r.attrs.items()}}


def _op_from_dict(od: Dict[str, Any]) -> _OpRecord:
    attrs = {}
    for k, v in dict(od.get("attrs", {})).items():
        if isinstance(v, dict) and "__subgraph__" in v:
            v = _Subgraph.from_dict(v["__subgraph__"])
        attrs[k] = v
    outs = od["outputs"] if "outputs" in od else od["output"]  # v1 compat
    return _OpRecord(od["op"], list(od["inputs"]), outs, attrs)
