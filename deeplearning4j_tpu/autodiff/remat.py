"""Workspace-mode rematerialization for imported SameDiff graphs.

The nn engines apply the ``workspace_mode`` activation-checkpoint policy at
layer/vertex granularity (``nn/memory.py``); an imported ``SameDiff`` graph
has no layers — just the recorded op list. This module recovers the block
structure: the topo-sorted op list is segmented into **transformer-block
chunks** anchored at attention sites — ``attention.fused_sdpa`` ops (the
post-``fusion.fuse_attention`` spelling) or raw softmax-anchored attention
chains, recognized by REUSING ``fusion._match_site``'s chain matcher — and
each segment's replay runs inside ``jax.checkpoint``. A BERT-class import
then keeps one set of boundary activations per encoder block and
rematerializes the block interior (QKV projections, scores, FFN
intermediates) during the backward pass.

Graphs with no attention anchors (plain MLPs, convnets) fall back to
sqrt-sized uniform chunks — the classic O(sqrt(n)) checkpoint spacing.

Liveness is exact: each segment receives precisely the names it reads that
were produced earlier (weights included — checkpoint inputs are saved, not
recomputed, which is correct for parameters) and returns precisely the
names later segments or the targets read. Everything else is
rematerialized.
"""

from __future__ import annotations

import math
from typing import List, Sequence, Tuple

from ..nn import memory as _memory

#: op names that anchor a transformer block (one anchor ≈ one block)
ANCHOR_OPS = ("attention.fused_sdpa",)


def attention_anchors(sd) -> List[int]:
    """Indices of ops that anchor a transformer block: fused attention
    ops, plus raw attention chains recognized by ``fusion._match_site``
    (the same matcher the fusion pass trusts for rewriting; a stray
    standalone softmax does NOT cut a block). For a raw chain the anchor
    is the UPSTREAM scores mmul, not the softmax — cutting at the softmax
    would park the O(B·H·T²) scores tensor on a checkpoint boundary (saved
    instead of rematerialized); cutting before the scores mmul keeps the
    whole quadratic interior inside one segment, so only q/k/v-sized
    boundaries survive (the same shape the fused anchor saves)."""
    from .fusion import _match_site
    from collections import Counter

    anchors = [i for i, r in enumerate(sd._ops) if r.op in ANCHOR_OPS]
    soft = [i for i, r in enumerate(sd._ops) if r.op == "act.softmax"]
    if soft:
        consumers: Counter = Counter()
        for rec in sd._ops:
            consumers.update(rec.referenced())
        producers = {o: r for r in sd._ops for o in r.outputs}
        idx_of = {id(r): i for i, r in enumerate(sd._ops)}
        for idx in soft:
            site, _reason = _match_site(sd, producers, consumers, idx)
            if site is not None:
                # earliest chain record == the scores mmul
                anchors.append(min(idx_of[id(r)] for r in site["remove"]))
    return sorted(anchors)


def segment_bounds(sd, policy) -> List[Tuple[int, int]]:
    """[(start, end), ...] op-index ranges covering the whole op list.
    With attention anchors: one segment per ``policy.every`` consecutive
    anchors, cut at the anchor op (head ops before the first anchor join
    the first segment; tail ops after the last join the last). Without:
    uniform sqrt-sized chunks."""
    n = len(sd._ops)
    if n == 0:
        return []
    anchors = attention_anchors(sd)
    if anchors:
        cuts = anchors[policy.every::policy.every]
        bounds = []
        prev = 0
        for c in cuts:
            bounds.append((prev, c))
            prev = c
        bounds.append((prev, n))
        return bounds
    size = max(1, math.isqrt(n))
    return _memory.segment_ranges(n, size)


def plan_segments(sd, targets: Sequence[str], policy):
    """[(ops_slice, in_names, out_names), ...] for a rematerialized replay
    toward ``targets``: ``in_names`` is what the segment reads from earlier
    (initial values/feeds or previous segments' outputs), ``out_names``
    what later segments or the targets read of its products."""
    ops = sd._ops
    bounds = segment_bounds(sd, policy)
    # names available before any op runs: everything with a stored/fed value
    available = {n for n, v in sd._vars.items() if v.kind != "ARRAY"}
    # referenced-by-suffix sets, computed right-to-left once
    needed_after = [set(targets)]  # needed_after[j] = reads of ops[e_j:]
    for s, e in reversed(bounds):
        nxt = set(needed_after[0])
        for rec in ops[s:e]:
            nxt.update(rec.referenced())
        needed_after.insert(0, nxt)
    plan = []
    for j, (s, e) in enumerate(bounds):
        seg_ops = ops[s:e]
        produced = {o for rec in seg_ops for o in rec.outputs}
        reads = set()
        for rec in seg_ops:
            reads.update(rec.referenced())
        in_names = tuple(sorted((reads - produced) & available))
        out_names = tuple(sorted(produced & needed_after[j + 1]))
        plan.append((tuple(seg_ops), in_names, out_names))
        available |= produced
    return plan


def compute_with_remat(sd, values, feeds, targets: Sequence[str], policy):
    """Drop-in for ``SameDiff._compute`` on the training path: the same
    topo-order replay, but each planned segment runs inside
    ``jax.checkpoint`` under the policy's saveable rule. Returns an env
    guaranteed to hold ``targets`` (plus every segment-boundary value)."""
    env = {}
    env.update(values)
    env.update(feeds)
    for seg_ops, in_names, out_names in plan_segments(sd, targets, policy):

        def seg_fn(env_in, _ops=seg_ops, _outs=out_names):
            e = dict(env_in)
            sd._exec_ops(list(_ops), e)
            return {n: e[n] for n in _outs}

        env.update(_memory.checkpoint(seg_fn, policy)(
            {n: env[n] for n in in_names}))
    return env
