"""SameDiff KV-cache decode rewrite (ISSUE 8 tentpole, layer 2b).

``fusion.fuse_attention`` turns an imported transformer's raw attention
chains into ``attention.fused_sdpa`` sites. This pass is the NEXT rewrite
in the same style: it clones the graph and swaps every fused site for
``attention.cached_sdpa`` (``ops/flash_attention.py``) — the one-token
decode op that appends this step's (k, v) projection into per-site HBM
cache placeholders and attends the single query over the valid prefix —
so a SameDiff-imported transformer accepts/returns per-layer
``(k, v, length)`` cache state without touching importer code:

- **prefill**: runs the ORIGINAL graph once over the (padded) prompt and
  harvests each fused site's ``k``/``v`` intermediates as extra output
  targets — the prompt's cache rows come out of the same one-shot flash
  kernel executable that computes the prompt logits (no separate
  prefill program to maintain).
- **decode_step**: runs the REWRITTEN graph on sequence-length-1 feeds;
  each cached site consumes ``<site>__k_cache`` / ``<site>__v_cache``
  placeholders plus the shared ``__cache_lengths__`` and emits the
  updated caches as additional outputs, threading the state functionally
  through the replay.

Constraints (checked/raised loudly, recorded in PARITY.md):

- the graph must already be fused (run ``fusion.fuse_attention`` first);
- every non-attention op between input and output must be
  shape-polymorphic over the sequence axis (dense/layernorm/gelu chains
  are; hardcoded-T reshapes and positional-embedding adds are not — the
  importer-shaped head-split reshapes that carry a static T constant
  need ``-1`` in that position);
- the fused site's mask bias (if any) is DROPPED in the decode replay:
  cache validity is governed by ``__cache_lengths__``, which subsumes
  the prompt key mask.

Semantics match the engine path: prefix-LM (prompt bidirectional over
itself, generated tokens causal), so N-step decode equals the full-prefix
recompute within dtype tolerance (parity-tested).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from .samediff import ARRAY, PLACEHOLDER, SameDiff, _OpRecord

#: the shared per-row valid-length placeholder of the decode replay
LENGTHS = "__cache_lengths__"
#: the shared page-table placeholder of the PAGED decode replay (ISSUE 12)
PAGE_TABLE = "__page_table__"


@dataclasses.dataclass
class _Site:
    """One rewritten attention site."""
    name: str          # the fused op's output name (kept by the rewrite)
    q: str
    k: str
    v: str
    scale: float
    k_cache: str       # decode-graph placeholder names
    v_cache: str
    k_out: str         # decode-graph cache output names
    v_out: str


class DecodeGraph:
    """A SameDiff graph pair ready for KV-cached generation.

    ``prefill(feeds, lengths, cache_len)`` -> ``(out, caches)`` and
    ``decode_step(feeds, caches, lengths)`` -> ``(out, caches')`` —
    caches are ``{site: {"k": [B,H,C,d], "v": [B,H,C,d]}}`` numpy
    arrays, the same (k, v, length) threading contract as the layer
    stack's decode walk."""

    def __init__(self, base: SameDiff, decode: SameDiff,
                 sites: List[_Site], output: str,
                 paged: bool = False, page_size: int = 16):
        self.base = base
        self.decode = decode
        self.sites = sites
        self.output = output
        self.paged = bool(paged)
        self.page_size = int(page_size)

    def site_names(self) -> List[str]:
        return [s.name for s in self.sites]

    def prefill(self, feeds: Dict, lengths, cache_len: int):
        """One pass of the ORIGINAL (fused) graph over the prompt feeds;
        returns ``(out, caches)`` with each site's prompt k/v bucketed
        into zero-padded ``cache_len`` rows. ``lengths`` [B] true prompt
        lengths (rows past a row's length carry garbage the decode-side
        length bias masks).

        Paged graphs (ISSUE 12): each site's cache is instead a
        ``[n_pages*page_size, H, d]`` token-row pool; rows are mapped
        through a linear per-row page table (page 0 reserved) stored in
        ``caches["__page_table__"]`` — the demonstration allocator; a
        serving deployment owns the real refcounted one
        (``serving.kv_pool.PagedKVPool``)."""
        targets = [self.output]
        for s in self.sites:
            targets += [s.k, s.v]
        res = self.base.output(feeds, targets)
        lengths = np.asarray(lengths)
        caches = {}
        page_table = None
        for s in self.sites:
            k, v = res[s.k], res[s.v]
            if k.ndim != 4:
                raise ValueError(
                    f"site {s.name!r}: cached decode needs [B,H,T,d] "
                    f"k/v projections, got {k.shape}")
            t = k.shape[2]
            if t > cache_len:
                raise ValueError(f"prompt length {t} exceeds cache_len "
                                 f"{cache_len}")
            if self.paged:
                P = self.page_size
                if cache_len % P:
                    raise ValueError(f"cache_len {cache_len} is not a "
                                     f"multiple of page_size {P}")
                B, H, _, d = k.shape
                mp = cache_len // P
                if page_table is None:
                    page_table = (1 + np.arange(B * mp, dtype=np.int32)
                                  ).reshape(B, mp)
                rows_total = (1 + B * mp) * P
                pool_k = np.zeros((rows_total, H, d), np.asarray(k).dtype)
                pool_v = np.zeros_like(pool_k)
                pos = np.arange(t)
                for b in range(B):
                    rows = page_table[b, pos // P] * P + pos % P
                    pool_k[rows] = np.asarray(k)[b].transpose(1, 0, 2)
                    pool_v[rows] = np.asarray(v)[b].transpose(1, 0, 2)
                caches[s.name] = {"k": pool_k, "v": pool_v}
            else:
                pad = [(0, 0), (0, 0), (0, cache_len - t), (0, 0)]
                caches[s.name] = {"k": np.pad(np.asarray(k), pad),
                                  "v": np.pad(np.asarray(v), pad)}
        if self.paged:
            caches[PAGE_TABLE] = page_table
        return res[self.output], caches

    def _cache_len(self, caches: Dict) -> int:
        if self.paged:
            return caches[PAGE_TABLE].shape[1] * self.page_size
        return next(iter(
            c["k"].shape[2] for n, c in caches.items() if n != PAGE_TABLE))

    def decode_step(self, feeds: Dict, caches: Dict, lengths):
        """One token through the REWRITTEN graph: ``feeds`` are the
        sequence-length-1 placeholder feeds; returns
        ``(out, new_caches)``. The caller advances ``lengths`` by one
        afterwards (same contract as the layer walk)."""
        full = dict(feeds)
        full[LENGTHS] = np.asarray(lengths, np.int32)
        # overflow guard: cached_sdpa's insert CLAMPS an out-of-range
        # position (XLA slice semantics) — without this host-side check a
        # full cache would silently overwrite its last row every step
        c = self._cache_len(caches)
        if int(np.max(full[LENGTHS])) >= c:
            raise ValueError(
                f"cache full (lengths {int(np.max(full[LENGTHS]))} >= "
                f"cache_len {c}): re-bucket (contiguous: zero-pad axis 2; "
                "paged: widen the page table) before the next decode_step")
        if self.paged:
            full[PAGE_TABLE] = np.asarray(caches[PAGE_TABLE], np.int32)
        for s in self.sites:
            full[s.k_cache] = caches[s.name]["k"]
            full[s.v_cache] = caches[s.name]["v"]
        targets = [self.output]
        for s in self.sites:
            targets += [s.k_out, s.v_out]
        res = self.decode.output(full, targets)
        new_caches = {s.name: {"k": res[s.k_out], "v": res[s.v_out]}
                      for s in self.sites}
        if self.paged:
            new_caches[PAGE_TABLE] = caches[PAGE_TABLE]
        return res[self.output], new_caches

    def generate(self, prompt_feeds: Dict, lengths, cache_len: int,
                 steps: int, next_feeds):
        """Greedy convenience driver: prefill then ``steps`` decode
        iterations. ``next_feeds(out, step)`` maps the last step's output
        to the next one-token feeds dict. Yields each step's output."""
        out, caches = self.prefill(prompt_feeds, lengths, cache_len)
        lengths = np.asarray(lengths).copy()
        for i in range(steps):
            feeds = next_feeds(out, i)
            out, caches = self.decode_step(feeds, caches, lengths)
            lengths = lengths + 1
            yield out


def rewrite_for_decode(sd: SameDiff, output: Optional[str] = None,
                       paged: bool = False,
                       page_size: int = 16) -> DecodeGraph:
    """Build the decode twin of a fused SameDiff graph.

    The original graph is untouched (it stays the prefill program); the
    clone gets every top-level ``attention.fused_sdpa`` record replaced
    by ``attention.cached_sdpa`` — or, with ``paged=True`` (ISSUE 12),
    by ``attention.paged_sdpa`` consuming per-site token-row POOLS plus
    the shared ``__page_table__`` — with per-site cache placeholders and
    the shared ``__cache_lengths__``. Raises when the graph has no fused
    sites (run ``fusion.fuse_attention(sd)`` first — this pass rides on
    its safety checks) or when a site sits inside a control-flow
    subgraph (not rewritable record-by-record)."""
    fused_idx = [i for i, r in enumerate(sd._ops)
                 if r.op == "attention.fused_sdpa"]
    if not fused_idx:
        raise ValueError(
            "graph has no attention.fused_sdpa sites; run "
            "autodiff.fusion.fuse_attention(sd) before rewrite_for_decode")
    if output is None:
        if sd.loss_name:
            output = sd.loss_name
        else:
            raise ValueError("pass output=<variable name> (graph has no "
                             "loss to default to)")
    dec = SameDiff.from_json(sd.to_json())
    dec._values = dict(sd._values)
    dec._register(LENGTHS, PLACEHOLDER)
    if paged:
        dec._register(PAGE_TABLE, PLACEHOLDER)
    sites: List[_Site] = []
    for idx in fused_idx:
        rec = dec._ops[idx]
        q, k, v = rec.inputs[:3]   # optional 4th input (mask bias) is
        #                            dropped: lengths subsume the key mask
        o = rec.output
        suffix = "pool" if paged else "cache"
        kc, vc = f"{o}__k_{suffix}", f"{o}__v_{suffix}"
        ko, vo = f"{o}__k_{suffix}_out", f"{o}__v_{suffix}_out"
        dec._register(kc, PLACEHOLDER)
        dec._register(vc, PLACEHOLDER)
        dec._register(ko, ARRAY)
        dec._register(vo, ARRAY)
        scale = float(rec.attrs.get("scale", 1.0))
        if paged:
            dec._ops[idx] = _OpRecord(
                "attention.paged_sdpa",
                [q, k, v, kc, vc, PAGE_TABLE, LENGTHS],
                [o, ko, vo],
                {"scale": scale, "page_size": int(page_size)})
        else:
            dec._ops[idx] = _OpRecord(
                "attention.cached_sdpa", [q, k, v, kc, vc, LENGTHS],
                [o, ko, vo], {"scale": scale})
        sites.append(_Site(name=o, q=q, k=k, v=v, scale=scale,
                           k_cache=kc, v_cache=vc, k_out=ko, v_out=vo))
    dec._fn_cache.clear()
    return DecodeGraph(sd, dec, sites, output, paged=paged,
                       page_size=page_size)
