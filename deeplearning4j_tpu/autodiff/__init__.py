"""Graph autodiff layer (SameDiff equivalent) — see samediff.py."""

from .samediff import (ARRAY, CONSTANT, PLACEHOLDER, VARIABLE, SameDiff,
                       SDVariable)

__all__ = ["SameDiff", "SDVariable", "VARIABLE", "PLACEHOLDER", "CONSTANT",
           "ARRAY"]
