"""Graph autodiff layer (SameDiff equivalent) — see samediff.py; graph
rewrite passes (attention fusion) in fusion.py."""

from .samediff import (ARRAY, CONSTANT, PLACEHOLDER, VARIABLE, SameDiff,
                       SDVariable)
from .fusion import FusionReport, fuse_attention

__all__ = ["SameDiff", "SDVariable", "VARIABLE", "PLACEHOLDER", "CONSTANT",
           "ARRAY", "fuse_attention", "FusionReport"]
