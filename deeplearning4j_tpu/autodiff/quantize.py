"""SameDiff graph-rewrite pass: post-training int8 weight quantization
(ISSUE 9 tentpole, imported-graph layer).

``fusion.fuse_attention`` rewrites imported attention chains;
``decode.rewrite_for_decode`` swaps fused sites for cached ones. This
pass is the third rewrite in the same splice-by-record-identity style:
every ``linalg.mmul`` record whose RIGHT operand is a stored 2-D weight
(VARIABLE or CONSTANT — the dense projections of an imported
transformer) is swapped for one ``quantize.int8_mmul`` record
(``ops/quantize.py``): the weight becomes an int8 CONSTANT with a f32
per-output-channel scale constant beside it, and the activation
quantizes dynamically inside the compiled graph. The record's OUTPUT
name is kept, so every downstream consumer — fused attention sites
included — is untouched.

Safety rules (a candidate site is skipped, and counted, unless ALL
hold; same posture as the fusion pass):

- the weight has a stored value, is 2-D, and is NOT fed per call
  (placeholders quantize dynamically already — nothing to pre-bake);
- the mmul carries no transpose flags (imported dense layers are plain
  ``x @ W``; a transposed weight would need its own channel-axis
  bookkeeping — recorded as a skip reason, not guessed at);
- the weight is consumed ONLY by mmul records that this pass rewrites
  (a weight also read elsewhere — e.g. a tied embedding — keeps its
  f32 value; quantizing one consumer would fork the two views).

The original f32 value is dropped from the value store when the last
consumer is rewritten — that is the HBM win (the int8 + scale pair is
~4x smaller). The rewrite is a DEPLOY-time transform: ``fit()`` through
a quantized site raises (``quantize.int8_mmul`` is registered
non-differentiable — rounding has no useful gradient), mirroring
TF-Serving's engine-level quantized-deploy posture (PAPERS.md,
1605.08695). Every decision bumps
``quantize.rewrite{decision=matched|skipped_<reason>}`` so ``GET
/stats``/``/metrics`` expose the per-site rewrite mix.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import List

import numpy as np

from ..ops import quantize as _q
from .samediff import ARRAY, CONSTANT, SameDiff, VARIABLE, _OpRecord


@dataclasses.dataclass
class QuantizeGraphReport:
    """matched = mmul sites swapped for ``quantize.int8_mmul``;
    skipped = candidate sites left f32, with reasons; ``bytes_f32`` /
    ``bytes_q`` = value-store weight bytes before/after (the serveable-
    batch accounting)."""
    matched: int = 0
    skipped: int = 0
    sites: List[str] = dataclasses.field(default_factory=list)
    reasons: List[str] = dataclasses.field(default_factory=list)
    bytes_f32: int = 0
    bytes_q: int = 0

    @property
    def bytes_saved(self) -> int:
        return max(0, self.bytes_f32 - self.bytes_q)

    def __str__(self):
        return (f"weight quantization: {self.matched} mmul sites -> int8 "
                f"({self.bytes_f32} -> {self.bytes_q} weight bytes), "
                f"{self.skipped} skipped")


def _skip(report: QuantizeGraphReport, rec: _OpRecord, slug: str,
          reason: str):
    """``slug`` is the short counter label
    (``quantize.rewrite{decision=skipped_<slug>}`` — distinct per skip
    class so the /metrics mix separates a tied embedding from a rank-3
    tensor); ``reason`` is the human-readable report line."""
    report.skipped += 1
    report.reasons.append(f"{rec.output}: {reason}")
    _q._REWRITE.inc(decision="skipped_" + slug)


def quantize_weights(sd: SameDiff, min_elements: int = 1
                     ) -> QuantizeGraphReport:
    """Rewrite every safe stored-weight ``linalg.mmul`` in ``sd`` to one
    ``quantize.int8_mmul`` op, in place. ``min_elements`` skips tiny
    weights where the int8 + scale pair saves nothing. Returns a
    :class:`QuantizeGraphReport`. Run AFTER ``fuse_attention`` (the
    fused sites' q/k/v projections are exactly the mmuls this pass
    wants; order is not load-bearing, but fusing first keeps the
    attention chain intact for its own rewrite)."""
    report = QuantizeGraphReport()
    consumers: Counter = Counter()
    for rec in sd._ops:
        consumers.update(rec.referenced())

    # one pass to decide; weights shared by several plain mmuls are
    # quantized once and every consumer site swaps
    sites = []          # (record, weight_name)
    per_weight = {}     # weight_name -> [records]
    for rec in sd._ops:
        if rec.op != "linalg.mmul":
            continue
        if len(rec.inputs) != 2:
            continue
        w_name = rec.inputs[1]
        var = sd._vars.get(w_name)
        if var is None or var.kind not in (VARIABLE, CONSTANT):
            # activation @ activation (attention scores/context) or a
            # per-call placeholder feed: not a stored-weight site
            continue
        val = sd._values.get(w_name)
        if val is None:
            _skip(report, rec, "no_value", "weight has no stored value")
            continue
        val = np.asarray(val)
        if val.ndim != 2:
            _skip(report, rec, "rank", f"weight rank {val.ndim} != 2")
            continue
        if val.size < int(min_elements):
            _skip(report, rec, "min_elements",
                  f"weight below min_elements ({val.size})")
            continue
        if not np.issubdtype(val.dtype, np.floating):
            _skip(report, rec, "dtype",
                  f"weight dtype {val.dtype} not floating")
            continue
        if rec.attrs.get("transpose_a") or rec.attrs.get("transpose_b"):
            _skip(report, rec, "transpose", "transpose flags set")
            continue
        sites.append((rec, w_name))
        per_weight.setdefault(w_name, []).append(rec)

    # a weight read by anything OTHER than its rewritten mmuls keeps its
    # f32 value (tied embeddings, norm-sharing exports)
    blocked = set()
    for w_name, recs in per_weight.items():
        if consumers[w_name] != len(recs):
            blocked.add(w_name)
            for rec in recs:
                _skip(report, rec, "shared_weight",
                      f"weight {w_name!r} has "
                      f"{consumers[w_name] - len(recs)} non-mmul consumers")
    sites = [(rec, w) for rec, w in sites if w not in blocked]
    if not sites:
        return report

    quantized = {}  # weight_name -> (q_name, scale_name)
    replace = {}    # id(old record) -> new record
    for rec, w_name in sites:
        if w_name not in quantized:
            val = np.asarray(sd._values[w_name])
            report.bytes_f32 += val.nbytes
            qt = _q.quantize_per_channel(val, axis=1)
            q_name, s_name = f"{w_name}__q", f"{w_name}__scale"
            sd._register(q_name, CONSTANT, tuple(qt.q.shape))
            sd._register(s_name, CONSTANT, tuple(qt.scale.shape))
            sd._values[q_name] = qt.q
            sd._values[s_name] = qt.scale
            report.bytes_q += qt.nbytes
            quantized[w_name] = (q_name, s_name)
        q_name, s_name = quantized[w_name]
        # splice by record identity, keeping the mmul's output name so
        # downstream consumers (and output()/serving callers) see no
        # graph-surface change; all replacements are known up front, so
        # the op list rebuilds ONCE (not once per site)
        replace[id(rec)] = _OpRecord(
            "quantize.int8_mmul", [rec.inputs[0], q_name, s_name],
            rec.output, {})
        report.matched += 1
        report.sites.append(rec.output)
        _q._REWRITE.inc(decision="matched")
    sd._ops = [replace.get(id(r), r) for r in sd._ops]

    # the f32 originals are dead now: drop the VALUES (the HBM win) but
    # keep the variable entries as value-less markers — ``get_value``
    # raising KeyError tells a caller the weight was quantized away
    for w_name in quantized:
        sd._values.pop(w_name, None)
        sd._vars[w_name].kind = ARRAY
    sd._fn_cache.clear()
    return report
