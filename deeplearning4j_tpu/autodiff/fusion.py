"""SameDiff graph-rewrite passes: attention-pattern fusion.

TF-imported transformer graphs (the BERT-base bench path) spell attention
as the raw five-op chain

    linalg.mmul(q, k, transpose_b=True)      # BatchMatMul(adj_y=True)
      -> math.div / math.mul (scalar const)  # 1/sqrt(head) scale
      -> math.add (mask bias)                # extended attention mask
      -> act.softmax
      -> linalg.mmul(probs, v)               # BatchMatMul

which XLA executes with the quadratic scores tensor round-tripping HBM.
:func:`fuse_attention` pattern-matches that chain on the recorded op list
and rewrites it to ONE ``attention.fused_sdpa`` op (``ops/
flash_attention.py``) — the tiled Pallas flash kernel on TPU, the
f32-softmax einsum reference elsewhere — so the imported model gets the
kernel without touching importer code. The scale and the optional mask-add
may appear in either order (HF TFBert divides then adds; other exports
flip it); both are optional. The scale may also live UPSTREAM of the
scores mmul as a scalar div/mul of q (the PyTorch->ONNX export shape,
``q/sqrt(d) @ k^T`` — r12): it is absorbed into the fused op's scale, so
the q-sized elementwise op leaves the graph too.

Safety rules (a site is skipped, and counted unmatched, unless ALL hold):
- every intermediate (scores / scaled / masked / probs) has exactly ONE
  consumer in the whole graph (control-flow subgraph reads included) —
  rewriting a fan-out would change other consumers' inputs;
- no intermediate is the graph's loss; the scale operand is a scalar
  CONSTANT; the mmuls carry the exact transpose flags above.

The rewrite keeps the chain's OUTPUT name (the context mmul's), so every
downstream consumer — and the training step — is untouched; intermediate
names are dropped from the variable registry (requesting one via
``output()`` after fusing is an error by design). Gradients flow through
the fused op's custom VJP. Numerics: the fused op runs its softmax in f32;
for f32 graphs this is the same computation reassociated (parity tested at
1e-5), for bf16-policy fit steps it is strictly more accurate — recorded
in PARITY.md.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import List, Optional

import numpy as np

from .samediff import ARRAY, CONSTANT, SameDiff, _OpRecord


@dataclasses.dataclass
class FusionReport:
    """matched = sites rewritten; unmatched = softmax ops that anchored a
    candidate chain (a batched-mmul ancestry) but failed a safety check,
    with the reasons; sites = fused output names."""
    matched: int = 0
    unmatched: int = 0
    sites: List[str] = dataclasses.field(default_factory=list)
    reasons: List[str] = dataclasses.field(default_factory=list)

    def __str__(self):
        return (f"attention fusion: {self.matched} matched, "
                f"{self.unmatched} unmatched")


def _scalar_const(sd: SameDiff, name: str) -> Optional[float]:
    var = sd._vars.get(name)
    if var is None or var.kind != CONSTANT:
        return None
    val = np.asarray(sd._values[name])
    if val.size != 1:
        return None
    return float(val.reshape(()))


def _match_site(sd, producers, consumers, soft_idx):
    """Try to anchor a fusable chain at the act.softmax record at
    ``soft_idx``. Returns (site dict, None) or (None, skip-reason);
    reason None means 'not even a candidate'."""
    ops = sd._ops
    soft = ops[soft_idx]
    axis = soft.attrs.get("axis", -1)
    if axis not in (-1,):
        return None, "softmax axis not -1"

    # downstream: softmax output reaches exactly one plain batched mmul as
    # the LEFT operand (probs @ v), possibly through act.identity links —
    # frozen-graph dropout / StopGradient import as identities
    chain = [soft]
    cur_out = soft.output
    ctx_idx = None
    for _ in range(4):
        nxt_idx = None
        for idx in range(soft_idx + 1, len(ops)):
            if cur_out in ops[idx].referenced():
                nxt_idx = idx
                break
        if nxt_idx is None:
            return None, None
        rec = ops[nxt_idx]
        if rec.op == "act.identity" and consumers[cur_out] == 1:
            chain.append(rec)
            cur_out = rec.output
            continue
        if (rec.op == "linalg.mmul" and rec.inputs[0] == cur_out
                and not rec.attrs.get("transpose_a")
                and not rec.attrs.get("transpose_b")):
            ctx_idx = nxt_idx
        break
    if ctx_idx is None:
        return None, None

    # upstream: [mask add], [scalar scale], and any scalar-const adds
    # (softmax is shift-invariant — HF stable_softmax's +eps absorbs away),
    # in any order, then the transposed-key scores mmul
    cur = soft.inputs[0]
    bias_name = None
    scale = 1.0
    for _ in range(4):
        rec = producers.get(cur)
        if rec is None or rec.op not in ("math.add", "math.mul", "math.div"):
            break
        if rec.op == "math.add":
            a, b = rec.inputs
            if _scalar_const(sd, b) is not None:
                pass                      # epsilon add: softmax(x+c)==softmax(x)
            elif _scalar_const(sd, a) is not None:
                a = b                     # epsilon add, operands flipped
            elif bias_name is None:
                nxt = a if _chain_like(producers.get(a)) else b
                if nxt is b and not _chain_like(producers.get(b)):
                    return None, "mask-add has no upstream mmul/scale operand"
                bias_name = b if nxt is a else a
                a = nxt
            else:
                return None, "more than one non-scalar mask add"
            chain.append(rec)
            cur = a
        elif scale == 1.0:
            a, b = rec.inputs
            c = _scalar_const(sd, b)
            if c is None and rec.op == "math.mul":
                c = _scalar_const(sd, a)
                if c is not None:
                    a = b
            if c is None:
                return None, "scale operand is not a scalar constant"
            scale = c if rec.op == "math.mul" else 1.0 / c
            chain.append(rec)
            cur = a
        else:
            return None, "more than one scale op"
    scores = producers.get(cur)
    if scores is None or scores.op != "linalg.mmul":
        return None, None
    if scores.attrs.get("transpose_a") or not scores.attrs.get("transpose_b"):
        return None, "scores mmul transpose flags are not (False, True)"
    chain.append(scores)

    # pre-scaled query (r12 coverage gap): PyTorch->ONNX and some TF
    # exports scale q BEFORE the scores mmul (q/sqrt(d) @ k^T) instead of
    # scaling the scores. Absorb a single-consumer scalar div/mul feeding
    # the mmul's LEFT input into the fused op's scale — without this the
    # site still fused but left the q-sized elementwise op (a full
    # [B,H,T,d] HBM round-trip) in the graph.
    q_name = scores.inputs[0]
    if scale == 1.0:
        qrec = producers.get(q_name)
        if qrec is not None and qrec.op in ("math.mul", "math.div") \
                and len(qrec.outputs) == 1 and consumers[q_name] == 1:
            a, b = qrec.inputs
            c = _scalar_const(sd, b)
            if c is None and qrec.op == "math.mul":
                c = _scalar_const(sd, a)
                if c is not None:
                    a = b
            if c is not None:
                scale = c if qrec.op == "math.mul" else 1.0 / c
                q_name = a
                chain.append(qrec)

    # single-consumer + not-the-loss safety net over every intermediate
    for rec in chain:
        out = rec.output
        if consumers[out] != 1:
            return None, f"intermediate {out!r} has {consumers[out]} consumers"
        if out == sd.loss_name:
            return None, f"intermediate {out!r} is the loss"
        if len(rec.outputs) != 1 or sd._vars[out].kind != ARRAY:
            return None, f"intermediate {out!r} is not a plain ARRAY output"

    ctx = ops[ctx_idx]
    if len(ctx.outputs) != 1:
        return None, "context mmul is not single-output"
    return {
        "remove": chain,       # softmax, [add], [scale], scores mmul,
        "ctx": ctx,            # [pre-scale of q]
        "q": q_name, "k": scores.inputs[1], "v": ctx.inputs[1],
        "bias": bias_name, "scale": float(scale), "out": ctx.output,
    }, None


def _chain_like(rec) -> bool:
    return rec is not None and rec.op in ("linalg.mmul", "math.mul",
                                          "math.div", "math.add")


def fuse_attention(sd: SameDiff, verbose: bool = False) -> FusionReport:
    """Rewrite every safe ``mmul -> [scale] -> [mask add] -> softmax ->
    mmul`` chain in ``sd`` to one ``attention.fused_sdpa`` op, in place.
    Returns a :class:`FusionReport` with matched/unmatched site counts."""
    report = FusionReport()
    consumers: Counter = Counter()
    for rec in sd._ops:
        consumers.update(rec.referenced())
    producers = {out: rec for rec in sd._ops for out in rec.outputs}

    sites = []
    for idx, rec in enumerate(sd._ops):
        if rec.op != "act.softmax":
            continue
        site, reason = _match_site(sd, producers, consumers, idx)
        if site is not None:
            sites.append(site)
        elif reason is not None:
            report.unmatched += 1
            report.reasons.append(f"{rec.output}: {reason}")

    for site in sites:
        inputs = [site["q"], site["k"], site["v"]]
        if site["bias"] is not None:
            inputs.append(site["bias"])
        fused = _OpRecord("attention.fused_sdpa", inputs, site["out"],
                          {"scale": site["scale"]})
        # splice by record identity — indices go stale after the first site
        removed = set(id(r) for r in site["remove"])
        sd._ops = [fused if r is site["ctx"] else r
                   for r in sd._ops if id(r) not in removed]
        for rec in site["remove"]:
            del sd._vars[rec.output]
        report.matched += 1
        report.sites.append(site["out"])

    if sites:
        sd._fn_cache.clear()
    if verbose:
        print(report)
        for r in report.reasons:
            print(" unmatched:", r)
    return report
