"""SameDiff graph-rewrite passes: attention-pattern fusion.

TF-imported transformer graphs (the BERT-base bench path) spell attention
as the raw five-op chain

    linalg.mmul(q, k, transpose_b=True)      # BatchMatMul(adj_y=True)
      -> math.div / math.mul (scalar const)  # 1/sqrt(head) scale
      -> math.add (mask bias)                # extended attention mask
      -> act.softmax
      -> linalg.mmul(probs, v)               # BatchMatMul

which XLA executes with the quadratic scores tensor round-tripping HBM.
:func:`fuse_attention` pattern-matches that chain on the recorded op list
and rewrites it to ONE ``attention.fused_sdpa`` op (``ops/
flash_attention.py``) — the tiled Pallas flash kernel on TPU, the
f32-softmax einsum reference elsewhere — so the imported model gets the
kernel without touching importer code. The scale and the optional mask-add
may appear in either order (HF TFBert divides then adds; other exports
flip it); both are optional. The scale may also live UPSTREAM of the
scores mmul as a scalar div/mul of q (the PyTorch->ONNX export shape,
``q/sqrt(d) @ k^T`` — r12): it is absorbed into the fused op's scale, so
the q-sized elementwise op leaves the graph too.

Safety rules (a site is skipped, and counted unmatched, unless ALL hold):
- every intermediate (scores / scaled / masked / probs) has exactly ONE
  consumer in the whole graph (control-flow subgraph reads included) —
  rewriting a fan-out would change other consumers' inputs;
- no intermediate is the graph's loss; the scale operand is a scalar
  CONSTANT; the mmuls carry the exact transpose flags above.

The rewrite keeps the chain's OUTPUT name (the context mmul's), so every
downstream consumer — and the training step — is untouched; intermediate
names are dropped from the variable registry (requesting one via
``output()`` after fusing is an error by design). Gradients flow through
the fused op's custom VJP. Numerics: the fused op runs its softmax in f32;
for f32 graphs this is the same computation reassociated (parity tested at
1e-5), for bf16-policy fit steps it is strictly more accurate — recorded
in PARITY.md.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import List, Optional

import numpy as np

from .samediff import ARRAY, CONSTANT, VARIABLE, SameDiff, _OpRecord


@dataclasses.dataclass
class FusionReport:
    """matched = sites rewritten; unmatched = anchor ops that started a
    candidate chain but failed a safety check, with the reasons; sites =
    fused output names; kinds = what each site fused to (parallel to
    ``sites`` — ``fuse_epilogues`` mixes layer-norm and gelu sites in one
    report, ``fuse_attention`` leaves it all-attention)."""
    matched: int = 0
    unmatched: int = 0
    sites: List[str] = dataclasses.field(default_factory=list)
    reasons: List[str] = dataclasses.field(default_factory=list)
    kinds: List[str] = dataclasses.field(default_factory=list)

    def __str__(self):
        return (f"fusion: {self.matched} matched, "
                f"{self.unmatched} unmatched")


def _scalar_const(sd: SameDiff, name: str) -> Optional[float]:
    var = sd._vars.get(name)
    if var is None or var.kind != CONSTANT:
        return None
    val = np.asarray(sd._values[name])
    if val.size != 1:
        return None
    return float(val.reshape(()))


def _match_site(sd, producers, consumers, soft_idx):
    """Try to anchor a fusable chain at the act.softmax record at
    ``soft_idx``. Returns (site dict, None) or (None, skip-reason);
    reason None means 'not even a candidate'."""
    ops = sd._ops
    soft = ops[soft_idx]
    axis = soft.attrs.get("axis", -1)
    if axis not in (-1,):
        return None, "softmax axis not -1"

    # downstream: softmax output reaches exactly one plain batched mmul as
    # the LEFT operand (probs @ v), possibly through act.identity links —
    # frozen-graph dropout / StopGradient import as identities
    chain = [soft]
    cur_out = soft.output
    ctx_idx = None
    for _ in range(4):
        nxt_idx = None
        for idx in range(soft_idx + 1, len(ops)):
            if cur_out in ops[idx].referenced():
                nxt_idx = idx
                break
        if nxt_idx is None:
            return None, None
        rec = ops[nxt_idx]
        if rec.op == "act.identity" and consumers[cur_out] == 1:
            chain.append(rec)
            cur_out = rec.output
            continue
        if (rec.op == "linalg.mmul" and rec.inputs[0] == cur_out
                and not rec.attrs.get("transpose_a")
                and not rec.attrs.get("transpose_b")):
            ctx_idx = nxt_idx
        break
    if ctx_idx is None:
        return None, None

    # upstream: [mask add], [scalar scale], and any scalar-const adds
    # (softmax is shift-invariant — HF stable_softmax's +eps absorbs away),
    # in any order, then the transposed-key scores mmul
    cur = soft.inputs[0]
    bias_name = None
    scale = 1.0
    for _ in range(4):
        rec = producers.get(cur)
        if rec is None or rec.op not in ("math.add", "math.mul", "math.div"):
            break
        if rec.op == "math.add":
            a, b = rec.inputs
            if _scalar_const(sd, b) is not None:
                pass                      # epsilon add: softmax(x+c)==softmax(x)
            elif _scalar_const(sd, a) is not None:
                a = b                     # epsilon add, operands flipped
            elif bias_name is None:
                nxt = a if _chain_like(producers.get(a)) else b
                if nxt is b and not _chain_like(producers.get(b)):
                    return None, "mask-add has no upstream mmul/scale operand"
                bias_name = b if nxt is a else a
                a = nxt
            else:
                return None, "more than one non-scalar mask add"
            chain.append(rec)
            cur = a
        elif scale == 1.0:
            a, b = rec.inputs
            c = _scalar_const(sd, b)
            if c is None and rec.op == "math.mul":
                c = _scalar_const(sd, a)
                if c is not None:
                    a = b
            if c is None:
                return None, "scale operand is not a scalar constant"
            scale = c if rec.op == "math.mul" else 1.0 / c
            chain.append(rec)
            cur = a
        else:
            return None, "more than one scale op"
    scores = producers.get(cur)
    if scores is None or scores.op != "linalg.mmul":
        return None, None
    if scores.attrs.get("transpose_a") or not scores.attrs.get("transpose_b"):
        return None, "scores mmul transpose flags are not (False, True)"
    chain.append(scores)

    # pre-scaled query (r12 coverage gap): PyTorch->ONNX and some TF
    # exports scale q BEFORE the scores mmul (q/sqrt(d) @ k^T) instead of
    # scaling the scores. Absorb a single-consumer scalar div/mul feeding
    # the mmul's LEFT input into the fused op's scale — without this the
    # site still fused but left the q-sized elementwise op (a full
    # [B,H,T,d] HBM round-trip) in the graph.
    q_name = scores.inputs[0]
    if scale == 1.0:
        qrec = producers.get(q_name)
        if qrec is not None and qrec.op in ("math.mul", "math.div") \
                and len(qrec.outputs) == 1 and consumers[q_name] == 1:
            a, b = qrec.inputs
            c = _scalar_const(sd, b)
            if c is None and qrec.op == "math.mul":
                c = _scalar_const(sd, a)
                if c is not None:
                    a = b
            if c is not None:
                scale = c if qrec.op == "math.mul" else 1.0 / c
                q_name = a
                chain.append(qrec)

    # single-consumer + not-the-loss safety net over every intermediate
    for rec in chain:
        out = rec.output
        if consumers[out] != 1:
            return None, f"intermediate {out!r} has {consumers[out]} consumers"
        if out == sd.loss_name:
            return None, f"intermediate {out!r} is the loss"
        if len(rec.outputs) != 1 or sd._vars[out].kind != ARRAY:
            return None, f"intermediate {out!r} is not a plain ARRAY output"

    ctx = ops[ctx_idx]
    if len(ctx.outputs) != 1:
        return None, "context mmul is not single-output"
    return {
        "remove": chain,       # softmax, [add], [scale], scores mmul,
        "ctx": ctx,            # [pre-scale of q]
        "q": q_name, "k": scores.inputs[1], "v": ctx.inputs[1],
        "bias": bias_name, "scale": float(scale), "out": ctx.output,
    }, None


def _chain_like(rec) -> bool:
    return rec is not None and rec.op in ("linalg.mmul", "math.mul",
                                          "math.div", "math.add")


def fuse_attention(sd: SameDiff, verbose: bool = False) -> FusionReport:
    """Rewrite every safe ``mmul -> [scale] -> [mask add] -> softmax ->
    mmul`` chain in ``sd`` to one ``attention.fused_sdpa`` op, in place.
    Returns a :class:`FusionReport` with matched/unmatched site counts."""
    report = FusionReport()
    consumers: Counter = Counter()
    for rec in sd._ops:
        consumers.update(rec.referenced())
    producers = {out: rec for rec in sd._ops for out in rec.outputs}

    sites = []
    for idx, rec in enumerate(sd._ops):
        if rec.op != "act.softmax":
            continue
        site, reason = _match_site(sd, producers, consumers, idx)
        if site is not None:
            sites.append(site)
        elif reason is not None:
            report.unmatched += 1
            report.reasons.append(f"{rec.output}: {reason}")

    for site in sites:
        inputs = [site["q"], site["k"], site["v"]]
        if site["bias"] is not None:
            inputs.append(site["bias"])
        fused = _OpRecord("attention.fused_sdpa", inputs, site["out"],
                          {"scale": site["scale"]})
        # splice by record identity — indices go stale after the first site
        removed = set(id(r) for r in site["remove"])
        sd._ops = [fused if r is site["ctx"] else r
                   for r in sd._ops if id(r) not in removed]
        for rec in site["remove"]:
            del sd._vars[rec.output]
        report.matched += 1
        report.sites.append(site["out"])

    if sites:
        sd._fn_cache.clear()
    if verbose:
        print(report)
        for r in report.reasons:
            print(" unmatched:", r)
    return report


# ---------------------------------------------------------------------------
# normalization / activation epilogue fusion (ISSUE 16)
# ---------------------------------------------------------------------------
#
# TF/keras-imported transformer blocks spell LayerNormalization and exact
# GELU as raw op chains:
#
#   mean = reduce.mean(x, axis=-1, keepdims)
#   var  = reduce.mean(squared_difference(x, mean), axis=-1, keepdims)
#   inv  = math.rsqrt(var + eps)
#   # keras folded form:            # plain form:
#   inv2 = inv * gamma              # y = ((x - mean) * inv) * gamma + beta
#   y    = x*inv2 + (beta - mean*inv2)
#
#   u = x * 0.7071067811  (or x / 1.4142135623)
#   g = 0.5 * x * (1 + math.erf(u))      # operand groupings vary by export
#
# Each chain re-reads the activation multiple times; on the BERT bench the
# row-stat reductions and the erf tail show up as distinct HBM sweeps.
# ``fuse_epilogues`` pattern-matches both shapes and splices in ONE catalog
# op each — ``epilogue.layer_norm_act`` / ``epilogue.bias_act`` (``ops/
# fused_epilogues.py``), the row-tiled Pallas kernels on TPU and the exact
# nnops/activations reference elsewhere. A rank-1 bias add directly under a
# gelu chain is absorbed into the ``epilogue.bias_act`` record. Same safety
# and splice discipline as ``fuse_attention``: every removed intermediate
# must be consumed only inside the matched chain, must not be the loss, and
# must be a plain single-output ARRAY; the chain's OUTPUT name survives so
# downstream consumers and serde are untouched.

_SQRT_2 = 1.4142135623730951
_INV_SQRT_2 = 0.7071067811865476


def _approx(val, target, rtol=1e-4):
    return val is not None and abs(val - target) <= rtol * abs(target)


def _single_axis(attrs):
    ax = attrs.get("axis")
    if isinstance(ax, (tuple, list)):
        if len(ax) != 1:
            return None
        ax = ax[0]
    return int(ax) if ax is not None else None


def _last_axis_ok(sd, x_name, ax):
    """Is ``ax`` the LAST axis of ``x``? -1 always is; a non-negative
    axis (TF imports record concrete indices) verifies against the
    variable's recorded rank when known, else the site is rejected —
    fusing a non-last-axis normalization would be wrong."""
    if ax == -1:
        return True
    if ax is None or ax < 0:
        return False
    shape = sd._vars.get(x_name).shape if x_name in sd._vars else None
    return shape is not None and ax == len(shape) - 1


def _vector_var(sd, name):
    """gamma/beta/bias operand: a rank-1 VARIABLE/CONSTANT with a known
    shape (the fused kernel reshapes it to [1, C])."""
    var = sd._vars.get(name)
    return (var is not None and var.kind in (VARIABLE, CONSTANT)
            and var.shape is not None and len(var.shape) == 1)


def _chain_safe(sd, consumers, remove, keep_out):
    """The fuse_attention safety net generalized to chains with internal
    fan-out (the keras folded LN reads inv*gamma twice): every removed
    record's outputs may only be consumed by OTHER REMOVED records, must
    not be the loss, and must be plain single-output ARRAYs. ``keep_out``
    (the final record's output) is exempt — it survives the splice."""
    remove = list({id(r): r for r in remove}.values())  # plain-form LN lists
    internal = Counter()                                # sub(x, mean) twice
    for rec in remove:
        internal.update(rec.referenced())
    for rec in remove:
        if len(rec.outputs) != 1:
            return f"intermediate {rec.output!r} is not single-output"
        out = rec.output
        if out == keep_out:
            continue
        if out == sd.loss_name:
            return f"intermediate {out!r} is the loss"
        if sd._vars[out].kind != ARRAY:
            return f"intermediate {out!r} is not a plain ARRAY output"
        if consumers[out] != internal[out]:
            return (f"intermediate {out!r} has "
                    f"{consumers[out] - internal[out]} outside consumers")
    return None


def _binop(producers, name, op):
    rec = producers.get(name)
    return rec if rec is not None and rec.op == op else None


def _split_scalar(sd, rec):
    """(other_operand, scalar_value) for a binary record with one scalar-
    const operand, else (None, None)."""
    a, b = rec.inputs
    c = _scalar_const(sd, b)
    if c is not None:
        return a, c
    c = _scalar_const(sd, a)
    if c is not None:
        return b, c
    return None, None


def _match_ln_site(sd, producers, consumers, rsqrt_idx):
    """Anchor a layer-norm chain at the math.rsqrt record. Returns
    (site, None), (None, reason), or (None, None) = not a candidate."""
    ops = sd._ops
    inv_rec = ops[rsqrt_idx]

    # upstream: rsqrt(var + eps), var/mean last-axis keepdims reductions
    add_rec = _binop(producers, inv_rec.inputs[0], "math.add")
    if add_rec is None:
        return None, None
    var_name, eps = _split_scalar(sd, add_rec)
    if var_name is None:
        return None, None
    var_rec = _binop(producers, var_name, "reduce.mean")
    if var_rec is None:
        return None, None
    if not var_rec.attrs.get("keepdims"):
        return None, "variance reduction lacks keepdims"
    ax = _single_axis(var_rec.attrs)

    sq_rec = producers.get(var_rec.inputs[0])
    if sq_rec is None:
        return None, None
    chain = [inv_rec, add_rec, var_rec, sq_rec]
    if sq_rec.op == "math.squared_difference":
        cand = list(sq_rec.inputs)
    elif sq_rec.op == "math.square":
        sub_rec = _binop(producers, sq_rec.inputs[0], "math.sub")
        if sub_rec is None:
            return None, None
        chain.append(sub_rec)
        cand = list(sub_rec.inputs)
    elif sq_rec.op == "math.mul" and sq_rec.inputs[0] == sq_rec.inputs[1]:
        sub_rec = _binop(producers, sq_rec.inputs[0], "math.sub")
        if sub_rec is None:
            return None, None
        chain.append(sub_rec)
        cand = list(sub_rec.inputs)
    else:
        return None, None
    mean_rec = None
    for i, nm in enumerate(cand):
        r = _binop(producers, nm, "reduce.mean")
        if r is not None and r.attrs.get("keepdims") \
                and _single_axis(r.attrs) == ax:
            mean_rec, x_name = r, cand[1 - i]
            break
    if mean_rec is None or mean_rec.inputs[0] != x_name:
        return None, None
    chain.append(mean_rec)
    if not _last_axis_ok(sd, x_name, ax):
        return None, f"cannot verify axis {ax} is the last axis of x"
    mean_name = mean_rec.output

    # downstream of inv: keras folded or plain affine
    inv_name = inv_rec.output
    inv_users = [r for r in ops if inv_name in r.referenced()]

    def _mul_with(rec, name):
        """other operand if rec is a mul touching ``name``, else None."""
        if rec is None or rec.op != "math.mul" or name not in rec.inputs:
            return None
        a, b = rec.inputs
        return b if a == name else a

    site = None
    if len(inv_users) == 1 and inv_users[0].op == "math.mul":
        g_name = _mul_with(inv_users[0], inv_name)
        inv2 = inv_users[0]
        if g_name is not None and _vector_var(sd, g_name):
            # keras folded: x*(inv*g) + (beta - mean*(inv*g))
            inv2_users = [r for r in ops if inv2.output in r.referenced()]
            t_x = t_mu = None
            for r in inv2_users:
                other = _mul_with(r, inv2.output)
                if other == x_name:
                    t_x = r
                elif other == mean_name:
                    t_mu = r
            if t_x is not None and t_mu is not None and len(inv2_users) == 2:
                sub_rec = None
                for r in ops:
                    if r.op == "math.sub" and r.inputs[1] == t_mu.output:
                        sub_rec = r
                        break
                if sub_rec is not None and _vector_var(sd, sub_rec.inputs[0]):
                    b_name = sub_rec.inputs[0]
                    out_rec = None
                    for r in ops:
                        if r.op == "math.add" and set(r.inputs) == {
                                t_x.output, sub_rec.output}:
                            out_rec = r
                            break
                    if out_rec is not None:
                        site = {"remove": chain + [inv2, t_x, t_mu, sub_rec,
                                                   out_rec],
                                "final": out_rec, "x": x_name,
                                "gamma": g_name, "beta": b_name,
                                "eps": float(eps), "out": out_rec.output}
        if site is None and g_name is not None and not _vector_var(sd, g_name):
            # plain: ((x - mean) * inv) * gamma + beta
            d_rec = _binop(producers, g_name, "math.sub")
            if d_rec is not None and d_rec.inputs[0] == x_name \
                    and d_rec.inputs[1] == mean_name:
                n_rec = inv_users[0]
                g_rec = None
                for r in ops:
                    other = _mul_with(r, n_rec.output)
                    if other is not None and _vector_var(sd, other):
                        g_rec, gamma = r, other
                        break
                if g_rec is not None:
                    out_rec = None
                    for r in ops:
                        if r.op == "math.add" and g_rec.output in r.inputs:
                            other = (r.inputs[1] if r.inputs[0] == g_rec.output
                                     else r.inputs[0])
                            if _vector_var(sd, other):
                                out_rec, beta = r, other
                                break
                    if out_rec is not None:
                        site = {"remove": chain + [d_rec, n_rec, g_rec,
                                                   out_rec],
                                "final": out_rec, "x": x_name,
                                "gamma": gamma, "beta": beta,
                                "eps": float(eps), "out": out_rec.output}
    if site is None:
        return None, "normalization tail shape not recognized"
    reason = _chain_safe(sd, consumers, site["remove"], site["out"])
    if reason is not None:
        return None, reason
    return site, None


def _match_gelu_site(sd, producers, consumers, erf_idx):
    """Anchor an exact-GELU chain at the math.erf record."""
    ops = sd._ops
    erf_rec = ops[erf_idx]

    # upstream: u = x * (1/sqrt 2)  or  x / sqrt 2
    u_rec = producers.get(erf_rec.inputs[0])
    if u_rec is None or u_rec.op not in ("math.mul", "math.div"):
        return None, None
    x_name, c = _split_scalar(sd, u_rec)
    if x_name is None:
        return None, None
    if u_rec.op == "math.mul" and not _approx(c, _INV_SQRT_2):
        return None, f"erf prescale {c} is not 1/sqrt(2)"
    if u_rec.op == "math.div":
        if u_rec.inputs[0] != x_name or not _approx(c, _SQRT_2):
            return None, f"erf prescale divisor {c} is not sqrt(2)"

    # downstream: (1 + erf), then 0.5 and x multiplied in, any grouping
    f_rec = None
    for r in ops:
        if r.op == "math.add" and erf_rec.output in r.inputs:
            other = (r.inputs[1] if r.inputs[0] == erf_rec.output
                     else r.inputs[0])
            if _approx(_scalar_const(sd, other), 1.0, rtol=1e-9):
                f_rec = r
                break
    if f_rec is None:
        return None, "no (1 + erf) add"
    chain = [u_rec, erf_rec, f_rec]

    def _users(name):
        return [r for r in ops if name in r.referenced()]

    # multiply f by x and 0.5 in either grouping (three export shapes)
    fu = _users(f_rec.output)
    site = None
    if len(fu) == 1 and fu[0].op == "math.mul":
        m1 = fu[0]
        other = m1.inputs[1] if m1.inputs[0] == f_rec.output else m1.inputs[0]
        if other == x_name:                      # (x*f) * 0.5
            m2 = next((r for r in _users(m1.output)
                       if r.op == "math.mul"), None)
            if m2 is not None:
                o2 = (m2.inputs[1] if m2.inputs[0] == m1.output
                      else m2.inputs[0])
                if _approx(_scalar_const(sd, o2), 0.5, rtol=1e-9):
                    site = {"remove": chain + [m1, m2], "final": m2,
                            "x": x_name, "out": m2.output}
        elif _approx(_scalar_const(sd, other), 0.5, rtol=1e-9):  # (0.5*f)*x
            m2 = next((r for r in _users(m1.output)
                       if r.op == "math.mul" and x_name in r.inputs), None)
            if m2 is not None:
                site = {"remove": chain + [m1, m2], "final": m2,
                        "x": x_name, "out": m2.output}
        else:                                    # f * (0.5*x)
            hx = producers.get(other)
            if hx is not None and hx.op == "math.mul":
                hx_x, hc = _split_scalar(sd, hx)
                if hx_x == x_name and _approx(hc, 0.5, rtol=1e-9):
                    site = {"remove": chain + [hx, m1], "final": m1,
                            "x": x_name, "out": m1.output}
    if site is None:
        return None, "gelu multiply tail shape not recognized"

    # absorb a rank-1 bias add feeding x (matmul -> bias -> gelu tail)
    site["bias"] = None
    b_rec = producers.get(x_name)
    if b_rec is not None and b_rec.op == "math.add":
        pre, bias = b_rec.inputs
        if not _vector_var(sd, bias) and _vector_var(sd, pre):
            pre, bias = bias, pre
        if _vector_var(sd, bias):
            trial = site["remove"] + [b_rec]
            if _chain_safe(sd, consumers, trial, site["out"]) is None:
                site = {**site, "remove": trial, "x": pre, "bias": bias}
    reason = _chain_safe(sd, consumers, site["remove"], site["out"])
    if reason is not None:
        return None, reason
    return site, None


def fuse_epilogues(sd: SameDiff, verbose: bool = False) -> FusionReport:
    """Rewrite every safe decomposed LayerNorm chain to one
    ``epilogue.layer_norm_act`` op and every safe exact-GELU chain to one
    ``epilogue.bias_act(act='gelu_exact')`` op, in place (ISSUE 16).
    Returns a :class:`FusionReport`; ``kinds[i]`` says what ``sites[i]``
    fused to (``layer_norm`` / ``gelu``)."""
    report = FusionReport()
    consumers: Counter = Counter()
    for rec in sd._ops:
        consumers.update(rec.referenced())
    producers = {out: rec for rec in sd._ops for out in rec.outputs}

    sites = []
    for idx, rec in enumerate(sd._ops):
        if rec.op == "math.rsqrt":
            site, reason = _match_ln_site(sd, producers, consumers, idx)
            kind = "layer_norm"
        elif rec.op == "math.erf":
            site, reason = _match_gelu_site(sd, producers, consumers, idx)
            kind = "gelu"
        else:
            continue
        if site is not None:
            site["kind"] = kind
            sites.append(site)
        elif reason is not None:
            report.unmatched += 1
            report.reasons.append(f"{rec.output}: {reason}")

    claimed = set()
    for site in sites:
        site["remove"] = list({id(r): r for r in site["remove"]}.values())
        ids = set(id(r) for r in site["remove"])
        if ids & claimed:  # overlapping matches: first anchor wins
            continue
        claimed |= ids
        if site["kind"] == "layer_norm":
            fused = _OpRecord(
                "epilogue.layer_norm_act",
                [site["x"], site["gamma"], site["beta"]], site["out"],
                {"eps": site["eps"], "act": "identity"})
        else:
            inputs = [site["x"]]
            if site["bias"] is not None:
                inputs.append(site["bias"])
            fused = _OpRecord("epilogue.bias_act", inputs, site["out"],
                              {"act": "gelu_exact"})
        removed = set(id(r) for r in site["remove"])
        sd._ops = [fused if r is site["final"] else r
                   for r in sd._ops if id(r) not in removed or r is site["final"]]
        for rec in site["remove"]:
            if rec is not site["final"]:
                del sd._vars[rec.output]
        report.matched += 1
        report.sites.append(site["out"])
        report.kinds.append(site["kind"])

    if report.matched:
        sd._fn_cache.clear()
    if verbose:
        print(report)
        for r in report.reasons:
            print(" unmatched:", r)
    return report
