"""Early stopping.

TPU-native equivalent of DL4J's early-stopping package (reference:
``deeplearning4j-nn .../earlystopping/{EarlyStoppingConfiguration,
trainer/EarlyStoppingTrainer,EarlyStoppingResult}.java``† per SURVEY.md
§2.5; reference mount was empty, citations upstream-relative, unverified).

Same contract as the reference: fit epoch-by-epoch, score on a held-out set
with a ScoreCalculator every ``evaluate_every_n_epochs``, keep the best model
via a ModelSaver, and stop on the first satisfied termination condition
(epoch-level checked after each epoch's score; iteration-level checked
inside the fit loop through a listener). The result always carries the
best model restored from the saver.

Works with both engines (MultiLayerNetwork and ComputationGraph) — both
expose ``fit/score/save`` and the in-memory snapshot round-trips through the
same ZIP serializer bytes.
"""

from __future__ import annotations

import io
import time
import zipfile
from typing import Any, Callable, List, Optional

import numpy as np


# ---------------------------------------------------------------- snapshots
def _model_to_bytes(model) -> bytes:
    from ..utils.serializer import save_model
    buf = io.BytesIO()
    save_model(model, buf)
    return buf.getvalue()


def _model_from_bytes(data: bytes):
    from ..utils.serializer import load_model
    return load_model(io.BytesIO(data))


class InMemoryModelSaver:
    """Keeps the best/latest model as serialized bytes (DL4J
    ``InMemoryModelSaver`` keeps a clone; bytes give the same isolation
    without aliasing device buffers)."""

    def __init__(self):
        self._best: Optional[bytes] = None
        self._latest: Optional[bytes] = None

    def save_best_model(self, model, score: float):
        self._best = _model_to_bytes(model)

    def save_latest_model(self, model, score: float):
        self._latest = _model_to_bytes(model)

    def get_best_model(self):
        return None if self._best is None else _model_from_bytes(self._best)

    def get_latest_model(self):
        return None if self._latest is None else _model_from_bytes(self._latest)


class LocalFileModelSaver:
    """Saves best/latest model zips under a directory (DL4J
    ``LocalFileModelSaver``)."""

    def __init__(self, directory: str):
        import os
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name: str) -> str:
        import os
        return os.path.join(self.directory, name)

    def save_best_model(self, model, score: float):
        model.save(self._path("bestModel.zip"))

    def save_latest_model(self, model, score: float):
        model.save(self._path("latestModel.zip"))

    def get_best_model(self):
        import os
        from ..utils.serializer import load_model
        p = self._path("bestModel.zip")
        return load_model(p) if os.path.exists(p) else None

    def get_latest_model(self):
        import os
        from ..utils.serializer import load_model
        p = self._path("latestModel.zip")
        return load_model(p) if os.path.exists(p) else None


# ---------------------------------------------------------- score calculators
class DataSetLossCalculator:
    """Average loss over a DataSetIterator (DL4J ``DataSetLossCalculator``).
    ``minimize_score()`` is True: lower is better."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def minimize_score(self) -> bool:
        return True

    def calculate_score(self, model) -> float:
        total, n = 0.0, 0
        for ds in self.iterator:
            b = ds.num_examples()
            total += model.score(ds) * (b if self.average else 1.0)
            n += b
        return total / max(n, 1) if self.average else total


class ClassificationScoreCalculator:
    """Accuracy/F1 on a held-out iterator; higher is better (DL4J
    ``ClassificationScoreCalculator``)."""

    def __init__(self, iterator, metric: str = "accuracy"):
        self.iterator = iterator
        self.metric = metric

    def minimize_score(self) -> bool:
        return False

    def calculate_score(self, model) -> float:
        ev = model.evaluate(self.iterator)
        return getattr(ev, self.metric)()


# ------------------------------------------------------ termination conditions
class MaxEpochsTerminationCondition:
    def __init__(self, max_epochs: int):
        self.max_epochs = int(max_epochs)

    def terminate(self, epoch: int, score: float, best_score: float) -> bool:
        return epoch + 1 >= self.max_epochs

    def __str__(self):
        return f"MaxEpochs({self.max_epochs})"


class ScoreImprovementEpochTerminationCondition:
    """Stop after N epochs with no (sufficient) improvement. Tracks its own
    best (scores arrive in minimize orientation), so it is independent of
    when the trainer updates its best-model snapshot."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = int(max_epochs_without_improvement)
        self.min_improvement = float(min_improvement)
        self._best: Optional[float] = None
        self._since_best = 0

    def terminate(self, epoch: int, score: float, best_score: float) -> bool:
        if np.isnan(score):
            self._since_best += 1
        elif self._best is None or \
                (self._best - score) > self.min_improvement:
            self._best = score
            self._since_best = 0
        else:
            self._since_best += 1
        return self._since_best >= self.patience

    def __str__(self):
        return (f"ScoreImprovement(patience={self.patience}, "
                f"min={self.min_improvement})")


class BestScoreEpochTerminationCondition:
    """Stop as soon as the score is at least this good. ``value`` is in the
    calculator's RAW orientation (a loss bound for minimizing calculators,
    an accuracy bound for maximizing ones); the trainer tells us the sign it
    normalizes scores with."""

    def __init__(self, value: float):
        self.value = float(value)
        self._sign = 1.0  # set by EarlyStoppingTrainer.fit

    def terminate(self, epoch: int, score: float, best_score: float) -> bool:
        # trainer passes score = sign * raw (minimize orientation); compare
        # against the threshold in the same space
        return score <= self._sign * self.value

    def __str__(self):
        return f"BestScore({self.value})"


class MaxTimeIterationTerminationCondition:
    def __init__(self, max_minutes: float):
        self.max_seconds = float(max_minutes) * 60.0
        self._start: Optional[float] = None

    def initialize(self):
        self._start = time.monotonic()

    def terminate(self, last_score: float) -> bool:
        return (time.monotonic() - self._start) > self.max_seconds

    def __str__(self):
        return f"MaxTime({self.max_seconds / 60:.1f}min)"


class MaxScoreIterationTerminationCondition:
    """Stop if the training score exceeds a bound (diverging)."""

    def __init__(self, max_score: float):
        self.max_score = float(max_score)

    def initialize(self):
        pass

    def terminate(self, last_score: float) -> bool:
        return last_score > self.max_score

    def __str__(self):
        return f"MaxScore({self.max_score})"


class InvalidScoreIterationTerminationCondition:
    """Stop on a NaN/inf training score (DL4J invalid-score termination).

    ``max_bad_steps`` (ISSUE 5 satellite) additionally wires this to the
    divergence sentinel's bad-step counter: because the sentinel SKIPS
    non-finite steps instead of letting them poison the params, a
    diverging run's *score* recovers as soon as one good batch lands —
    the skipped-step counter is the signal that persists. With
    ``max_bad_steps=N`` the condition trips once the model's lifetime
    ``bad_total`` reaches N, even if the current score is finite."""

    wants_model = True  # _IterationConditionListener injects `_model`

    def __init__(self, max_bad_steps: Optional[int] = None):
        self.max_bad_steps = max_bad_steps
        self._model = None

    def initialize(self):
        self._model = None

    def terminate(self, last_score: float) -> bool:
        if bool(np.isnan(last_score) or np.isinf(last_score)):
            return True
        if self.max_bad_steps is not None and self._model is not None and \
                hasattr(self._model, "resilience_counters"):
            return self._model.resilience_counters()["bad_total"] \
                >= self.max_bad_steps
        return False

    def __str__(self):
        if self.max_bad_steps is not None:
            return f"InvalidScore(max_bad_steps={self.max_bad_steps})"
        return "InvalidScore"


# ----------------------------------------------------------------- trainer
class EarlyStoppingConfiguration:
    """Builder-style config (DL4J ``EarlyStoppingConfiguration.Builder``)."""

    def __init__(self, *,
                 epoch_termination_conditions: Optional[List[Any]] = None,
                 iteration_termination_conditions: Optional[List[Any]] = None,
                 score_calculator: Any = None,
                 model_saver: Any = None,
                 evaluate_every_n_epochs: int = 1,
                 save_last_model: bool = False):
        self.epoch_conditions = epoch_termination_conditions or []
        self.iteration_conditions = iteration_termination_conditions or []
        self.score_calculator = score_calculator
        self.saver = model_saver or InMemoryModelSaver()
        self.every_n = int(evaluate_every_n_epochs)
        self.save_last = save_last_model


class EarlyStoppingResult:
    def __init__(self, termination_reason: str, termination_details: str,
                 best_model_epoch: int, best_model_score: float,
                 total_epochs: int, best_model):
        self.termination_reason = termination_reason
        self.termination_details = termination_details
        self.best_model_epoch = best_model_epoch
        self.best_model_score = best_model_score
        self.total_epochs = total_epochs
        self.best_model = best_model

    def __repr__(self):
        return (f"EarlyStoppingResult(reason={self.termination_reason}, "
                f"details={self.termination_details}, "
                f"best_epoch={self.best_model_epoch}, "
                f"best_score={self.best_model_score:.6f}, "
                f"epochs={self.total_epochs})")


class _IterationStop(Exception):
    def __init__(self, condition):
        self.condition = condition


class _IterationConditionListener:
    """Fit-loop listener that checks iteration termination conditions on the
    live training score and aborts the epoch via exception (the functional
    equivalent of DL4J's in-loop check)."""

    def __init__(self, conditions):
        self.conditions = conditions

    def iteration_done(self, model, iteration, epoch):
        score = model.score()
        for c in self.conditions:
            if getattr(c, "wants_model", False):
                c._model = model  # sentinel-wired conditions read counters
            if c.terminate(score):
                raise _IterationStop(c)

    def on_epoch_end(self, model):
        pass


class EarlyStoppingTrainer:
    """DL4J ``EarlyStoppingTrainer`` / ``EarlyStoppingGraphTrainer`` (one
    class — both engines share the fit/score surface here)."""

    def __init__(self, config: EarlyStoppingConfiguration, model, train_data):
        self.config = config
        self.model = model
        self.train_data = train_data

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        calc = cfg.score_calculator
        if calc is None:
            raise ValueError("EarlyStoppingConfiguration needs a "
                             "score_calculator")
        sign = 1.0 if calc.minimize_score() else -1.0
        for c in cfg.epoch_conditions:
            if hasattr(c, "_sign"):
                c._sign = sign  # conditions holding raw-orientation bounds
        best_score = float("nan")
        best_epoch = -1
        epoch = 0
        reason, details = "Unknown", ""
        for c in cfg.iteration_conditions:
            c.initialize()
        listener = _IterationConditionListener(cfg.iteration_conditions)
        self.model.add_listener(listener)
        try:
            while True:
                try:
                    self.model.fit(self.train_data, epochs=1)
                except _IterationStop as stop:
                    reason = "IterationTerminationCondition"
                    details = str(stop.condition)
                    break
                terminated = False
                if (epoch + 1) % cfg.every_n == 0:
                    score = sign * calc.calculate_score(self.model)
                    if np.isnan(best_score) or score < best_score:
                        best_score = score
                        best_epoch = epoch
                        cfg.saver.save_best_model(self.model, sign * score)
                    if cfg.save_last:
                        cfg.saver.save_latest_model(self.model, sign * score)
                    for c in cfg.epoch_conditions:
                        if c.terminate(epoch, score, best_score):
                            reason = "EpochTerminationCondition"
                            details = str(c)
                            terminated = True
                            break
                else:
                    # still enforce MaxEpochs-style conditions on off-epochs
                    for c in cfg.epoch_conditions:
                        if isinstance(c, MaxEpochsTerminationCondition) and \
                                c.terminate(epoch, float("nan"), best_score):
                            reason = "EpochTerminationCondition"
                            details = str(c)
                            terminated = True
                            break
                epoch += 1
                if terminated:
                    break
        finally:
            if listener in self.model._listeners:
                self.model._listeners.remove(listener)
        best = cfg.saver.get_best_model() or self.model
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            best_model_epoch=best_epoch,
            best_model_score=sign * best_score if not np.isnan(best_score)
            else float("nan"),
            total_epochs=epoch, best_model=best)
