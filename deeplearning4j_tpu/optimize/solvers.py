"""Second-order-ish solvers: BackTrackLineSearch, LBFGS, ConjugateGradient.

TPU-native equivalent of DL4J's legacy solver stack (reference:
``deeplearning4j-nn .../optimize/solvers/{BackTrackLineSearch,LBFGS,
ConjugateGradient,StochasticGradientDescent}.java``† per SURVEY.md §2.4
optimizers row; reference mount was empty, citations upstream-relative,
unverified).

Design: the solvers are HOST-side control loops over ONE jitted
value-and-grad of the network loss on a flat parameter vector (the
flat-param contract, SURVEY.md §7.3.5) — each inner iteration is a single
device call; the line search reuses the same compiled function. DL4J runs
its solver per minibatch inside ``Solver.optimize`` (§3.1 call stack);
``MultiLayerNetwork.fit`` routes here when the config says
``optimization_algo("LBFGS" | "CONJUGATE_GRADIENT" |
"LINE_GRADIENT_DESCENT")``.

Recorded divergences: BatchNorm running averages are not refreshed by
solver iterations (DL4J's computeGradientAndScore does refresh them);
CenterLossOutputLayer is unsupported under solvers; dropout draws ONE mask
per optimize() call (per minibatch) rather than per forward — the line
search needs a deterministic objective, so every trial within a batch sees
the same mask; gradient clipping/normalization configs RAISE (clipped
gradients would poison LBFGS curvature pairs) while weight constraints are
projected after each optimize() like the SGD path.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class BackTrackLineSearch:
    """Armijo backtracking (reference BackTrackLineSearch†: sufficient
    decrease with geometric step contraction; DL4J defaults
    maxIterations=5)."""

    def __init__(self, max_iterations: int = 5, c1: float = 1e-4,
                 contraction: float = 0.5, initial_step: float = 1.0,
                 min_step: float = 1e-12):
        self.max_iterations = int(max_iterations)
        self.c1 = float(c1)
        self.contraction = float(contraction)
        self.initial_step = float(initial_step)
        self.min_step = float(min_step)

    def search(self, f: Callable[[jnp.ndarray], Tuple],
               x: jnp.ndarray, fx: float, g: jnp.ndarray,
               direction: jnp.ndarray, initial: Optional[float] = None):
        """-> (step, new_x, new_f, new_g); step 0.0 means no acceptable
        point was found (caller should fall back to plain gradient).
        ``initial`` overrides the first trial step (solvers pass the
        previous accepted step so the search adapts to the local scale).
        When the first trial already satisfies Armijo, the step is
        greedily doubled while it keeps satisfying it (cheap bracketing —
        pure backtracking stalls nonlinear CG on anisotropic bowls)."""
        slope = float(jnp.vdot(g, direction))
        if slope >= 0:  # not a descent direction
            return 0.0, x, fx, g
        step = float(initial) if initial else self.initial_step

        def trial(s):
            x_n = x + s * direction
            f_n, g_n = f(x_n)
            return float(f_n), x_n, g_n

        f_new, x_new, g_new = trial(step)
        if np.isfinite(f_new) and f_new <= fx + self.c1 * step * slope:
            # expansion: accept the largest doubling that still satisfies
            for _ in range(self.max_iterations):
                f2, x2, g2 = trial(step * 2.0)
                if not (np.isfinite(f2)
                        and f2 <= fx + self.c1 * step * 2.0 * slope
                        and f2 < f_new):
                    break
                step *= 2.0
                f_new, x_new, g_new = f2, x2, g2
            return step, x_new, f_new, g_new
        # backtracking runs to min_step regardless of max_iterations: a
        # stiff direction may need a tiny step, and giving up early turns
        # the whole solver iteration into a no-op (~40 trials worst case)
        while True:
            step *= self.contraction
            if step < self.min_step:
                break
            f_new, x_new, g_new = trial(step)
            if np.isfinite(f_new) and f_new <= fx + self.c1 * step * slope:
                return step, x_new, f_new, g_new
        return 0.0, x, fx, g


class LBFGS:
    """Limited-memory BFGS, two-loop recursion (reference LBFGS†; memory
    default mirrors DL4J's m=10)."""

    def __init__(self, iterations: int = 5, memory: int = 10,
                 line_search: Optional[BackTrackLineSearch] = None,
                 tolerance: float = 1e-8):
        self.iterations = int(iterations)
        self.memory = int(memory)
        self.line_search = line_search or BackTrackLineSearch()
        self.tolerance = float(tolerance)

    def minimize(self, f, x0) -> Tuple[jnp.ndarray, float]:
        x = jnp.asarray(x0)
        fx, g = f(x)
        fx = float(fx)
        s_hist, y_hist, rho = [], [], []
        for _ in range(self.iterations):
            # two-loop recursion
            q = g
            alphas = []
            for s, y, r in zip(reversed(s_hist), reversed(y_hist),
                               reversed(rho)):
                a = r * float(jnp.vdot(s, q))
                alphas.append(a)
                q = q - a * y
            if y_hist:
                ys = float(jnp.vdot(s_hist[-1], y_hist[-1]))
                yy = float(jnp.vdot(y_hist[-1], y_hist[-1]))
                q = q * (ys / max(yy, 1e-20))
            for (s, y, r), a in zip(zip(s_hist, y_hist, rho),
                                    reversed(alphas)):
                b = r * float(jnp.vdot(y, q))
                q = q + (a - b) * s
            direction = -q
            step, x_new, f_new, g_new = self.line_search.search(
                f, x, fx, g, direction)
            if step == 0.0:
                # fall back to steepest descent once; stop if that fails too
                step, x_new, f_new, g_new = self.line_search.search(
                    f, x, fx, g, -g)
                if step == 0.0:
                    break
            s = x_new - x
            y = g_new - g
            sy = float(jnp.vdot(s, y))
            if sy > 1e-10:  # curvature condition: keep the pair
                s_hist.append(s)
                y_hist.append(y)
                rho.append(1.0 / sy)
                if len(s_hist) > self.memory:
                    s_hist.pop(0); y_hist.pop(0); rho.pop(0)
            if abs(fx - f_new) < self.tolerance:
                x, fx, g = x_new, f_new, g_new
                break
            x, fx, g = x_new, f_new, g_new
        return x, fx


class ConjugateGradient:
    """Nonlinear CG, Polak-Ribière+ with automatic restart (reference
    ConjugateGradient†)."""

    def __init__(self, iterations: int = 5,
                 line_search: Optional[BackTrackLineSearch] = None,
                 tolerance: float = 1e-8):
        self.iterations = int(iterations)
        self.line_search = line_search or BackTrackLineSearch()
        self.tolerance = float(tolerance)

    def minimize(self, f, x0) -> Tuple[jnp.ndarray, float]:
        x = jnp.asarray(x0)
        fx, g = f(x)
        fx = float(fx)
        d = -g
        prev_step = None
        for _ in range(self.iterations):
            step, x_new, f_new, g_new = self.line_search.search(
                f, x, fx, g, d, initial=prev_step)
            if step == 0.0:
                break
            prev_step = step
            gg = float(jnp.vdot(g, g))
            beta = max(0.0, float(jnp.vdot(g_new, g_new - g)) /
                       max(gg, 1e-20))  # PR+ (restart on negative)
            d = -g_new + beta * d
            if abs(fx - f_new) < self.tolerance:
                x, fx, g = x_new, f_new, g_new
                break
            x, fx, g = x_new, f_new, g_new
        return x, fx


class LineGradientDescent:
    """Steepest descent with line search (reference
    LineGradientDescent†)."""

    def __init__(self, iterations: int = 5,
                 line_search: Optional[BackTrackLineSearch] = None):
        self.iterations = int(iterations)
        self.line_search = line_search or BackTrackLineSearch()

    def minimize(self, f, x0) -> Tuple[jnp.ndarray, float]:
        x = jnp.asarray(x0)
        fx, g = f(x)
        fx = float(fx)
        for _ in range(self.iterations):
            step, x, fx, g = self.line_search.search(f, x, fx, g, -g)
            if step == 0.0:
                break
        return x, fx


_SOLVERS = {
    "LBFGS": LBFGS,
    "CONJUGATE_GRADIENT": ConjugateGradient,
    "LINE_GRADIENT_DESCENT": LineGradientDescent,
}


def get_solver(name: str, iterations: int = 5,
               max_line_search_iterations: int = 5):
    key = str(name).upper()
    if key not in _SOLVERS:
        raise ValueError(f"unknown optimization_algo {name!r}; known: "
                         f"SGD (default fit path) + {sorted(_SOLVERS)}")
    return _SOLVERS[key](
        iterations=iterations,
        line_search=BackTrackLineSearch(
            max_iterations=max_line_search_iterations))


class Solver:
    """DL4J ``Solver`` equivalent: owns the jitted flat value-and-grad of a
    model's loss and runs the configured algorithm per minibatch."""

    def __init__(self, model, algo: str, iterations: int = 5,
                 max_line_search_iterations: int = 5):
        conf = model.conf
        if (conf.gradient_normalization or conf.gradient_clip_value
                or conf.gradient_clip_l2):
            # the SGD step applies these per update; the solver's curvature
            # estimates (LBFGS s/y pairs) would be poisoned by clipped
            # gradients — refuse rather than silently ignore the config
            raise ValueError(
                "gradient clipping/normalization is not supported with "
                f"optimization_algo({algo!r}); remove it or use SGD")
        self.model = model
        self.opt = get_solver(algo, iterations, max_line_search_iterations)
        self._vg = None
        self._unravel = None

    def _build(self):
        from jax.flatten_util import ravel_pytree
        model = self.model
        _, unravel = ravel_pytree(model.params)
        self._unravel = unravel

        out_layer = model._out_layer
        if hasattr(out_layer, "update_centers"):
            # CenterLoss needs its features/centers plumbing (SGD step only);
            # silently training with bare CE would be a different loss
            raise ValueError(
                "CenterLossOutputLayer is not supported with solver "
                "optimization_algo; use SGD")
        from ..ops import losses as _loss

        def loss_fn(vec, x, y, fm, lm, key):
            params = unravel(vec)
            out, _, out_mask = model._forward(
                params, x, model.state, train=True, rng=key, mask=fm)
            m = _loss.combine_masks(lm, out_mask)
            data_loss = out_layer.loss_value(
                out, y, mask=m,
                weights=getattr(out_layer, "loss_weights", None))
            return data_loss + model._regularization(params)

        vg = jax.jit(jax.value_and_grad(loss_fn))
        self._vg = vg

    def optimize(self, x, y, fm=None, lm=None, key=None) -> float:
        """One DL4J Solver.optimize call: run the algorithm's iterations on
        this batch, write the result back into the model. Returns the final
        loss. ``key`` seeds dropout/noise for the WHOLE call (held fixed so
        the line-search objective is deterministic)."""
        from jax.flatten_util import ravel_pytree
        from ..nn import constraints as _constraints
        model = self.model
        if self._vg is None:
            self._build()
        vec0, _ = ravel_pytree(model.params)

        def f(vec):
            return self._vg(vec, x, y, fm, lm, key)

        vec, fx = self.opt.minimize(f, vec0)
        new_params = self._unravel(vec)
        # weight constraints project after the solver step, same as the
        # SGD path applies them after each update — and with the same
        # frozen-layer exemption ("no updates of any kind")
        from ..nn.layers.wrappers import FrozenLayer
        frozen_keys = frozenset(
            str(i) for i, l in enumerate(model.layers)
            if isinstance(l, FrozenLayer))
        new_params = _constraints.apply_constraints(
            model.conf.constraints, new_params, skip=frozen_keys)
        model.params = new_params
        return fx
