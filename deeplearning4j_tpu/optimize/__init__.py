from .earlystopping import (  # noqa: F401
    BestScoreEpochTerminationCondition, ClassificationScoreCalculator,
    DataSetLossCalculator, EarlyStoppingConfiguration, EarlyStoppingResult,
    EarlyStoppingTrainer, InMemoryModelSaver,
    InvalidScoreIterationTerminationCondition, LocalFileModelSaver,
    MaxEpochsTerminationCondition, MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition)
from .listeners import (  # noqa: F401
    CheckpointListener, CollectScoresListener, EvaluativeListener,
    PerformanceListener, ScoreIterationListener, TrainingListener)
