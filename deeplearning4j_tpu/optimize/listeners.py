"""Training listeners.

TPU-native equivalent of DL4J's listener pipeline (reference:
``deeplearning4j-nn .../optimize/listeners/{ScoreIterationListener,
PerformanceListener,EvaluativeListener,CheckpointListener}.java``† per
SURVEY.md §2.4/§5; reference mount was empty, citations upstream-relative,
unverified).

Hook contract: ``iteration_done(model, iteration, epoch)`` after every
optimizer step; ``on_epoch_end(model)`` after each epoch. Matches DL4J's
TrainingListener events that matter; forward/backward sub-events don't exist
here (the step is one fused XLA program — by design).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Callable, List, Optional

log = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    def iteration_done(self, model, iteration: int, epoch: int):
        pass

    def on_epoch_end(self, model):
        pass


class ScoreIterationListener(TrainingListener):
    """Log the score every N iterations (DL4J ScoreIterationListener)."""

    def __init__(self, print_iterations: int = 10, printer: Callable = None):
        self.n = max(1, print_iterations)
        self._print = printer or (lambda s: log.info(s))

    def iteration_done(self, model, iteration, epoch):
        if iteration % self.n == 0:
            self._print(f"Score at iteration {iteration} is {model.score()}")


class CollectScoresListener(TrainingListener):
    """Record (iteration, score) pairs (DL4J CollectScoresIterationListener)."""

    def __init__(self):
        self.scores: List[tuple] = []

    def iteration_done(self, model, iteration, epoch):
        self.scores.append((iteration, model.score()))


class PerformanceListener(TrainingListener):
    """Throughput tracking: examples/sec, iterations/sec (DL4J
    PerformanceListener), plus optional MFU given a per-example FLOP count —
    the TPU-era metric the reference lacked (SURVEY.md §5 tracing row) —
    and per-interval device HBM telemetry (PJRT ``memory_stats()``:
    peak_bytes_in_use/bytes_limit; ``last_memory`` stays None on backends
    like CPU that don't report them)."""

    def __init__(self, frequency: int = 10, batch_size: Optional[int] = None,
                 flops_per_example: Optional[float] = None,
                 peak_flops: Optional[float] = None, printer: Callable = None,
                 collect_memory: bool = True, collect_resilience: bool = True,
                 collect_phases: bool = True):
        self.frequency = max(1, frequency)
        self.batch_size = batch_size
        self.flops_per_example = flops_per_example
        self.peak_flops = peak_flops or _detect_peak_flops()
        self.collect_memory = collect_memory
        self.collect_resilience = collect_resilience
        self.collect_phases = collect_phases
        self._print = printer or (lambda s: log.info(s))
        self._t0 = None
        self._it0 = 0
        self.last_examples_per_sec = float("nan")
        self.last_mfu = float("nan")
        self.last_memory: Optional[dict] = None
        self.last_resilience: Optional[dict] = None
        self.last_phases: Optional[dict] = None

    def iteration_done(self, model, iteration, epoch):
        now = time.perf_counter()
        if self._t0 is None:
            self._t0 = now
            self._it0 = iteration
            return
        if (iteration - self._it0) % self.frequency:
            return
        dt = now - self._t0
        iters = iteration - self._it0
        if dt <= 0 or iters <= 0:
            return
        its_per_sec = iters / dt
        msg = f"iteration {iteration}: {its_per_sec:.2f} it/s"
        if self.batch_size:
            eps = its_per_sec * self.batch_size
            self.last_examples_per_sec = eps
            msg += f", {eps:.1f} examples/s"
            if self.flops_per_example and self.peak_flops:
                # 3x fwd flops approximates fwd+bwd
                self.last_mfu = 3 * self.flops_per_example * eps / self.peak_flops
                msg += f", MFU {self.last_mfu * 100:.1f}%"
        if self.collect_memory:
            from ..nn.memory import device_memory_stats
            self.last_memory = device_memory_stats()
            if self.last_memory:
                msg += (f", hbm peak "
                        f"{self.last_memory['peak_bytes_in_use'] / 2**30:.2f}"
                        f"/{self.last_memory['bytes_limit'] / 2**30:.2f} GiB")
        if self.collect_phases:
            # step-phase split over THIS interval (ISSUE 6): the fit loops
            # record data-wait and step-dispatch durations into the
            # registry; windowing by the interval keeps the numbers
            # current instead of lifetime
            from ..runtime import telemetry as _tel
            lbl = getattr(model, "telemetry_label", None)
            mlabels = {} if lbl is None else {"model": lbl}
            wait = _tel.histogram("train.phase.data_wait_s") \
                .hist_snapshot(window=dt, **mlabels)
            disp = _tel.histogram("train.phase.step_s") \
                .hist_snapshot(window=dt, **mlabels)
            self.last_phases = {
                "data_wait_ms_p50": None if wait["p50"] is None
                else wait["p50"] * 1e3,
                "step_dispatch_ms_p50": None if disp["p50"] is None
                else disp["p50"] * 1e3,
                "data_wait_count": wait["count"],
            }
            if wait["p50"] is not None and disp["p50"] is not None:
                msg += (f", wait/dispatch p50 {wait['p50'] * 1e3:.1f}/"
                        f"{disp['p50'] * 1e3:.1f}ms")
        if self.collect_resilience and hasattr(model, "resilience_counters"):
            # divergence-sentinel counters (the interval's ONE deliberate
            # device sync — frequency-gated) + checkpoint/restore telemetry
            from ..runtime import faults as _faults
            rc = dict(model.resilience_counters())
            rc.update(_faults.telemetry_snapshot())
            self.last_resilience = rc
            if rc["bad_total"]:
                msg += f", skipped {rc['bad_total']} non-finite steps"
            if rc["clip_events"]:
                msg += f", {rc['clip_events']} clip events"
            if rc.get("checkpoint_last_save_latency_s") is not None:
                msg += (f", ckpt save "
                        f"{rc['checkpoint_last_save_latency_s'] * 1e3:.0f}ms")
            if rc.get("restore_count"):
                msg += f", {rc['restore_count']} restores"
        self._print(msg)
        self._t0 = now
        self._it0 = iteration


def _detect_peak_flops() -> Optional[float]:
    """Peak BF16 FLOPs of device 0, for MFU. (v5e's widely-quoted 394
    TOPS figure is INT8; bf16 peak is 197 TFLOPs — using 394 halves every
    reported MFU.)

    ``DL4J_TPU_PEAK_FLOPS`` (ISSUE 6 satellite) overrides the detection —
    unknown devices (CI CPUs, new TPU generations before the table grows
    a row) used to silently yield ``MFU=None``; with the override set,
    MFU telemetry keeps flowing everywhere PerformanceListener runs."""
    env = os.environ.get("DL4J_TPU_PEAK_FLOPS")
    if env:
        try:
            v = float(env)
            if v > 0:
                return v
            log.warning("DL4J_TPU_PEAK_FLOPS=%r is not positive; ignored",
                        env)
        except ValueError:
            log.warning("DL4J_TPU_PEAK_FLOPS=%r is not a number; ignored",
                        env)
    try:
        import jax
        d = jax.devices()[0]
        kind = getattr(d, "device_kind", "").lower()
        if "v5 lite" in kind or "v5e" in kind:
            return 197e12
        if "v4" in kind:
            return 275e12
        if "v5p" in kind or "v5" in kind:
            return 459e12
        if "v6" in kind:
            return 918e12
    except Exception:
        pass
    return None


class EvaluativeListener(TrainingListener):
    """Periodic evaluation against a held-out iterator (DL4J EvaluativeListener)."""

    def __init__(self, iterator, frequency_epochs: int = 1, printer: Callable = None):
        self.iterator = iterator
        self.frequency = max(1, frequency_epochs)
        self._print = printer or (lambda s: log.info(s))
        self.last_evaluation = None

    def on_epoch_end(self, model):
        if model.epoch % self.frequency:
            return
        ev = model.evaluate(self.iterator)
        self.last_evaluation = ev
        self._print(f"epoch {model.epoch}: accuracy={ev.accuracy():.4f} f1={ev.f1():.4f}")


class CheckpointListener(TrainingListener):
    """Periodic rotating checkpoints (DL4J CheckpointListener semantics:
    save every N epochs/iterations, keep last K)."""

    def __init__(self, directory: str, save_every_epochs: Optional[int] = 1,
                 save_every_iterations: Optional[int] = None, keep_last: int = 3):
        self.dir = directory
        self.every_epochs = save_every_epochs
        self.every_iters = save_every_iterations
        self.keep_last = keep_last
        self._saved: List[str] = []
        os.makedirs(directory, exist_ok=True)

    def _save(self, model, tag: str):
        path = os.path.join(self.dir, f"checkpoint_{tag}.zip")
        model.save(path)
        self._saved.append(path)
        while len(self._saved) > self.keep_last:
            old = self._saved.pop(0)
            try:
                os.remove(old)
            except OSError:
                pass

    def iteration_done(self, model, iteration, epoch):
        if self.every_iters and iteration and iteration % self.every_iters == 0:
            self._save(model, f"iter_{iteration}")

    def on_epoch_end(self, model):
        if self.every_epochs and model.epoch % self.every_epochs == 0:
            self._save(model, f"epoch_{model.epoch}")
